"""Strength of connection.

Analog of src/classical/strength/ (strength_base.cu AHAT, ALL,
affinity.cu). AHAT marks a_ij strong when it is a sufficiently large
negative coupling relative to the row's largest one:

    -a_ij >= theta * max_k(-a_ik),   k != i

with the reference's `max_row_sum` weakening: rows whose |row sum| /
|diagonal| exceeds max_row_sum get ALL their connections weakened to
nothing (they are essentially Dirichlet rows). Returns a boolean mask
over the CSR entries — pure segment ops, fully deterministic.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import registry
from ...matrix import CsrMatrix


class Strength:
    def __init__(self, cfg, scope):
        self.theta = float(cfg.get("strength_threshold", scope))
        self.max_row_sum = float(cfg.get("max_row_sum", scope))

    def strong_mask(self, A: CsrMatrix):
        raise NotImplementedError


@registry.strength.register("AHAT")
class AhatStrength(Strength):
    def strong_mask(self, A: CsrMatrix):
        from ...matrix import host_resident
        if not A.is_block and host_resident(
                A.row_offsets, A.col_indices, A.values, A.diag):
            return self._strong_mask_host(A)
        rows, cols, vals = A.coo()
        n = A.num_rows
        offdiag = rows != cols
        # sign convention: couplings opposite in sign to the diagonal are
        # "negative" couplings
        diag = A.diagonal()
        sgn = jnp.sign(jnp.where(diag == 0, 1.0, diag))
        coupling = -vals * sgn[rows]          # >0 for strong-type entries
        coupling = jnp.where(offdiag, coupling, 0.0)
        row_max = jax.ops.segment_max(coupling, rows, num_segments=n,
                                      indices_are_sorted=True)
        row_max = jnp.maximum(row_max, 0.0)
        strong = offdiag & (coupling >= self.theta * row_max[rows]) \
            & (coupling > 0)
        if self.max_row_sum < 1.0:
            rowsum = jax.ops.segment_sum(vals, rows, num_segments=n,
                                         indices_are_sorted=True)
            if A.has_external_diag:
                rowsum = rowsum + A.diag
            weak_row = jnp.abs(rowsum) > self.max_row_sum * jnp.abs(diag)
            strong = strong & ~weak_row[rows]
        return strong

    def _strong_mask_host(self, A: CsrMatrix):
        """Numpy form of the same mask for host-resident matrices (the
        host-setup path; avoids ~20 eager XLA:CPU dispatches/level).
        The in-line-diagonal case runs as ONE native C++ sweep
        (amgx_strength_ahat) — this is a per-level O(nnz) hot path."""
        import numpy as np
        from ...matrix import _np_row_reduce
        n = A.num_rows
        if not A.has_external_diag and \
                np.asarray(A.values).dtype.kind == "f":
            from ... import native
            strong = native.strength_ahat_native(
                n, np.asarray(A.row_offsets), np.asarray(A.col_indices),
                np.asarray(A.values), self.theta, self.max_row_sum)
            if strong is not None:
                return strong
        ro = np.asarray(A.row_offsets)
        cols = np.asarray(A.col_indices)
        vals = np.asarray(A.values)
        rows = np.repeat(np.arange(n, dtype=np.int32), np.diff(ro))
        if A.has_external_diag:
            diag = np.asarray(A.diag)
        else:
            diag = np.zeros(n, vals.dtype)
            dmask = rows == cols
            # reverse order so the FIRST diagonal occurrence wins
            # (padded-duplicate CSR stores the coalesced sum first)
            diag[rows[dmask][::-1]] = vals[dmask][::-1]
        sgn = np.where(diag < 0, -1.0, 1.0)
        offdiag = rows != cols
        coupling = np.where(offdiag, -vals * sgn[rows], 0.0)
        row_max = np.maximum(
            _np_row_reduce(np.maximum, coupling, ro, n, 0.0), 0.0)
        strong = offdiag & (coupling >= self.theta * row_max[rows]) \
            & (coupling > 0)
        if self.max_row_sum < 1.0:
            rowsum = np.bincount(rows, weights=vals, minlength=n)
            if A.has_external_diag:
                rowsum = rowsum + diag
            weak_row = np.abs(rowsum) > self.max_row_sum * np.abs(diag)
            strong = strong & ~weak_row[rows]
        return strong


@registry.strength.register("ALL")
class AllStrength(Strength):
    def strong_mask(self, A: CsrMatrix):
        rows, cols, _ = A.coo()
        return rows != cols


@registry.strength.register("AFFINITY")
class AffinityStrength(Strength):
    """Affinity strength (affinity.cu): smoothed-test-vector affinity
    between neighbors. K test vectors are relaxed a few Jacobi sweeps on
    A z = 0; the affinity |<z_i, z_j>|^2 / (<z_i,z_i><z_j,z_j>) replaces
    the coefficient-based coupling."""

    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.iters = int(cfg.get("affinity_iterations", scope))
        self.k = int(cfg.get("affinity_vectors", scope))

    def strong_mask(self, A: CsrMatrix):
        import numpy as np
        from ...ops.spmv import spmv
        n = A.num_rows
        rng = np.random.default_rng(12345)
        Z = jnp.asarray(rng.uniform(-1, 1, (self.k, n)), dtype=A.dtype)
        d = A.diagonal()
        dinv = jnp.where(d == 0, 0.0, 1.0 / jnp.where(d == 0, 1.0, d))

        def sweep(_, Z):
            return Z - 0.7 * jax.vmap(lambda z: dinv * spmv(A, z))(Z)

        Z = jax.lax.fori_loop(0, self.iters, sweep, Z)
        rows, cols, _ = A.coo()
        zi = Z[:, rows]
        zj = Z[:, cols]
        num = jnp.sum(zi * zj, axis=0) ** 2
        den = jnp.sum(zi * zi, axis=0) * jnp.sum(zj * zj, axis=0)
        aff = num / jnp.where(den == 0, 1.0, den)
        aff = jnp.where(rows != cols, aff, 0.0)
        row_max = jax.ops.segment_max(aff, rows, num_segments=n,
                                      indices_are_sorted=True)
        return (rows != cols) & (aff >= self.theta * row_max[rows]) \
            & (aff > 0)
