"""Classical interpolation operators.

Analogs of src/classical/interpolators/ (distance1.cu 900 LoC,
distance2.cu 2274 LoC, multipass.cu). Round-1 surface:

- D1: Ruge-Stuben *direct* interpolation with positive-coupling lumping.
  For a fine point i with strong coarse neighbors C_i:

      w_ij = -alpha_i * a_ij / ~a_ii        for j in C_i (a_ij < 0)
      alpha_i = sum_{k != i, a_ik<0} a_ik / sum_{j in C_i, a_ij<0} a_ij
      ~a_ii   = a_ii + sum_{k != i, a_ik>0, k not in C_i} a_ik

  Coarse points interpolate by injection (P row = e_c). All assembled
  with COO masks + segment sums (no per-row loops).
- Truncation (interp_truncation_factor / interp_max_elements) trims P
  and rescales rows to preserve the row sum (truncate analog).
- MULTIPASS falls back to D1 after aggressive coarsening (full
  multipass interpolation is a later-round item, tracked in SURVEY §7).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix


def _coarse_index(cf_map):
    """coarse id per vertex (valid where cf_map==COARSE); nc."""
    is_c = cf_map == 1
    cidx = jnp.cumsum(is_c.astype(jnp.int32)) - 1
    nc = int(cidx[-1]) + 1 if cf_map.shape[0] else 0
    return jnp.where(is_c, cidx, -1), nc


class Interpolator:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope
        self.trunc_factor = float(cfg.get("interp_truncation_factor", scope))
        self.max_elements = int(cfg.get("interp_max_elements", scope))

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        raise NotImplementedError


@registry.interpolators.register("D2")
class Distance2Interpolator(Interpolator):
    """Extended+i distance-two interpolation (distance2.cu analog; the
    formula of De Sterck/Falgout/Nolting/Yang, "Distance-two
    interpolation for parallel algebraic multigrid", 2008):

        w_ij = -(1/D_i) [ a_ij 1{j in C^_i}
                          + sum_{k in F_i^s} a_ik abar_kj / d_ik ]
        d_ik = sum_{l in C^_i + {i}} abar_kl
        D_i  = a_ii + sum_{n weak, n not in C^_i} a_in
                    + sum_{k in F_i^s} a_ik abar_ki / d_ik

    with C^_i = C_i + union of strong-C neighbors of i's strong-F
    neighbors, and abar the negative-coupling part of A. Everything is
    COO expands + segment sums: the two-hop triple expansion reuses the
    SpGEMM machinery, membership tests are sorted-key searches. This is
    what makes PMIS-coarsened V-cycles scalable (the D1 rate degrades
    with depth)."""

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        from ...ops.spgemm import _expand, csr_multiply
        n = A.num_rows
        rows, cols, vals = A.coo()
        rows64 = rows.astype(jnp.int64)
        cols64 = cols.astype(jnp.int64)
        diag = A.diagonal()
        sgn = jnp.sign(jnp.where(diag == 0, 1.0, diag))
        offd = rows != cols
        neg = offd & (vals * sgn[rows] < 0)      # abar pattern
        is_C = cf_map == 1
        cidx, nc = _coarse_index(cf_map)
        strongC = strong & is_C[cols]
        strongF = strong & ~is_C[cols] & offd

        def filtered(mask):
            """CSR keeping only masked entries (host-side compress)."""
            m = np.asarray(mask)
            r = np.asarray(rows)[m]
            c = np.asarray(cols)[m]
            v = np.asarray(vals)[m]
            counts = np.bincount(r, minlength=n)
            ro = np.zeros(n + 1, np.int32)
            np.cumsum(counts, out=ro[1:])
            return CsrMatrix.from_scipy_like(ro, c.astype(np.int32),
                                             jnp.asarray(v), n, n)

        Fmat = filtered(strongF)                  # i -> k (strong F)
        Abar = filtered(neg)                      # k -> m (neg couplings)

        # C-hat membership set: strong C neighbors + two-hop through F
        Sc01 = filtered(strongC)
        Sc01 = CsrMatrix.from_scipy_like(
            Sc01.row_offsets, Sc01.col_indices,
            jnp.ones_like(Sc01.values), n, n)
        Sf01 = CsrMatrix.from_scipy_like(
            Fmat.row_offsets, Fmat.col_indices,
            jnp.ones_like(Fmat.values), n, n)
        H = csr_multiply(Sf01, Sc01)
        hr, hc, hv = H.coo()
        scr, scc, _ = Sc01.coo()
        chat_keys = np.unique(np.concatenate([
            np.asarray(scr, np.int64) * n + np.asarray(scc),
            np.asarray(hr, np.int64)[np.asarray(hv) > 0] * n
            + np.asarray(hc)[np.asarray(hv) > 0]]))
        chat_keys_j = jnp.asarray(chat_keys)

        def member(ri, cj):
            key = ri.astype(jnp.int64) * n + cj.astype(jnp.int64)
            pos = jnp.clip(jnp.searchsorted(chat_keys_j, key), 0,
                           max(len(chat_keys) - 1, 0))
            if len(chat_keys) == 0:
                return jnp.zeros(key.shape, bool)
            return chat_keys_j[pos] == key

        # two-hop triples (i -k-> m)
        t_rows, t_m, src_f, src_b = _expand(Fmat, Abar)
        t_i = t_rows
        t_k = Fmat.col_indices[src_f]
        t_aik = Fmat.values[src_f]
        t_abar = Abar.values[src_b]
        keep = member(t_i, t_m) | (t_m == t_i)
        denom = jax.ops.segment_sum(jnp.where(keep, t_abar, 0.0), src_f,
                                    num_segments=Fmat.nnz)
        bad = denom == 0                          # k distributes nowhere
        dsafe = jnp.where(bad, 1.0, denom)
        contrib = t_aik * t_abar / dsafe[src_f]
        contrib = jnp.where(bad[src_f], 0.0, contrib)

        # interpolatory entries: triples landing on C points in C-hat
        m_is_entry = keep & is_C[t_m] & (t_m != t_i)
        e_rows = t_i[m_is_entry]
        e_cols = t_m[m_is_entry]
        e_vals = contrib[m_is_entry]
        # direct part: a_ij for neighbors j in C-hat
        dmask = offd & is_C[cols] & member(rows, cols)
        # diagonal D_i: weak lumping + the "+i" feedback terms
        fb = jax.ops.segment_sum(
            jnp.where(keep & (t_m == t_i), contrib, 0.0), t_i,
            num_segments=n)
        lump_mask = offd & ~member(rows, cols) & ~strongF
        lump = jax.ops.segment_sum(jnp.where(lump_mask, vals, 0.0), rows,
                                   num_segments=n, indices_are_sorted=True)
        # strong-F neighbors whose denominator collapsed: lump them too
        f_row_ids = Fmat.coo()[0]
        bad_f = jax.ops.segment_sum(jnp.where(bad, Fmat.values, 0.0),
                                    f_row_ids, num_segments=n)
        D = diag + lump + fb + bad_f

        all_rows = jnp.concatenate([rows[dmask], e_rows])
        all_cols = jnp.concatenate([cols[dmask], e_cols])
        all_vals = jnp.concatenate([vals[dmask], e_vals])
        f_row = (cf_map == 0)[all_rows]
        w = -all_vals / jnp.where(D[all_rows] == 0, 1.0, D[all_rows])
        c_rows = jnp.where(cf_map == 1)[0].astype(jnp.int32)
        p_rows = jnp.concatenate([all_rows[f_row], c_rows])
        p_cols = jnp.concatenate([cidx[all_cols[f_row]], cidx[c_rows]])
        p_vals = jnp.concatenate([w[f_row],
                                  jnp.ones((nc,), vals.dtype)])
        P = CsrMatrix.from_coo(p_rows, p_cols, p_vals, n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


@registry.interpolators.register("D1")
class Distance1Interpolator(Interpolator):
    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        n = A.num_rows
        rows, cols, vals = A.coo()
        diag = A.diagonal()
        cidx, nc = _coarse_index(cf_map)
        is_f_row = (cf_map == 0)[rows]
        neg = vals < 0
        offd = rows != cols
        in_Ci = strong & (cidx[cols] >= 0) & neg & offd

        sum_neg = jax.ops.segment_sum(jnp.where(offd & neg, vals, 0.0),
                                      rows, num_segments=n,
                                      indices_are_sorted=True)
        sum_Ci = jax.ops.segment_sum(jnp.where(in_Ci, vals, 0.0),
                                     rows, num_segments=n,
                                     indices_are_sorted=True)
        # positive off-diagonals not interpolated from: lump into diagonal
        pos_lump = jax.ops.segment_sum(
            jnp.where(offd & ~neg, vals, 0.0), rows, num_segments=n,
            indices_are_sorted=True)
        dmod = diag + pos_lump
        alpha = sum_neg / jnp.where(sum_Ci == 0, 1.0, sum_Ci)
        alpha = jnp.where(sum_Ci == 0, 0.0, alpha)
        w = -alpha[rows] * vals / jnp.where(dmod[rows] == 0, 1.0, dmod[rows])

        # P entries: F rows interpolate from C_i; C rows inject
        mask = in_Ci & is_f_row
        p_rows = jnp.concatenate([rows[mask],
                                  jnp.where(cf_map == 1)[0].astype(jnp.int32)])
        p_cols = jnp.concatenate([cidx[cols[mask]],
                                  cidx[jnp.where(cf_map == 1)[0]]])
        p_vals = jnp.concatenate([w[mask],
                                  jnp.ones((nc,), vals.dtype)])
        P = CsrMatrix.from_coo(p_rows, p_cols, p_vals, n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


def _filtered_csr(n, rows, cols, vals, mask) -> CsrMatrix:
    """CSR keeping only masked COO entries (host-side compress; runs once
    per setup)."""
    m = np.asarray(mask)
    r = np.asarray(rows)[m]
    c = np.asarray(cols)[m]
    v = np.asarray(vals)[m]
    counts = np.bincount(r, minlength=n)
    ro = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=ro[1:])
    return CsrMatrix.from_scipy_like(ro, c.astype(np.int32),
                                     jnp.asarray(v), n, n)


@registry.interpolators.register("MULTIPASS")
class MultipassInterpolator(Interpolator):
    """Multipass interpolation for aggressive coarsening
    (multipass.cu:1, 2557 LoC; Stuben's multipass scheme). F-points are
    ranked by their strong-connection distance to the C-set ("pass"
    number); pass-1 points interpolate directly from strong C neighbors
    (the D1 formula), and pass-p points substitute the already-built P
    rows of their pass<p strong neighbors:

        w_i = -(alpha_i / ~a_ii) * sum_{j in J_i} a_ij P_j,
        alpha_i = sum_{k != i, a_ik<0} a_ik / sum_{j in J_i} a_ij,
        J_i = strong negative neighbors with pass < p

    so each pass is one filtered-SpGEMM (A restricted to pass-p rows and
    pass<p columns, times the current P) — the reference's per-pass
    kernel sweeps become a handful of sort-based SpGEMM calls.
    """

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        from ...ops.spgemm import csr_multiply
        n = A.num_rows
        rows, cols, vals = A.coo()
        diag = A.diagonal()
        cidx, nc = _coarse_index(cf_map)
        is_C = cf_map == 1
        offd = rows != cols
        neg = vals < 0
        strong_neg = strong & offd & neg
        # ~a_ii: positive off-diagonals lumped into the diagonal (D1
        # semantics)
        pos_lump = jax.ops.segment_sum(
            jnp.where(offd & ~neg, vals, 0.0), rows, num_segments=n,
            indices_are_sorted=True)
        dmod = diag + pos_lump
        sum_neg = jax.ops.segment_sum(jnp.where(offd & neg, vals, 0.0),
                                      rows, num_segments=n,
                                      indices_are_sorted=True)

        # pass numbers: BFS distance to C through strong edges
        BIG = np.int32(2 ** 30)
        pnum = jnp.where(is_C, 0, BIG).astype(jnp.int32)
        for _ in range(64):
            nbr_min = jax.ops.segment_min(
                jnp.where(strong_neg, pnum[cols], BIG), rows,
                num_segments=n, indices_are_sorted=True)
            new = jnp.where(is_C, 0, jnp.minimum(pnum, nbr_min + 1))
            if bool(jnp.all(new == pnum)):
                break
            pnum = new
        pnp = np.asarray(pnum)
        reachable = pnp < BIG
        max_pass = int(pnp[reachable].max()) if reachable.any() else 0

        # accumulate P rows pass by pass (C rows: injection)
        c_rows = np.where(np.asarray(is_C))[0].astype(np.int32)
        p_rows = [jnp.asarray(c_rows)]
        p_cols = [jnp.asarray(np.asarray(cidx)[c_rows])]
        p_vals = [jnp.ones((len(c_rows),), vals.dtype)]

        for p in range(1, max_pass + 1):
            in_pass = pnum == p
            emask = strong_neg & in_pass[rows] & (pnum[cols] < p)
            denom = jax.ops.segment_sum(jnp.where(emask, vals, 0.0), rows,
                                        num_segments=n,
                                        indices_are_sorted=True)
            alpha = jnp.where(denom != 0,
                              sum_neg / jnp.where(denom == 0, 1.0, denom),
                              0.0)
            scale = -alpha / jnp.where(dmod == 0, 1.0, dmod)
            Ap = _filtered_csr(n, rows, cols, vals, emask)
            # current P (global-column space n x nc)
            P_cur = CsrMatrix.from_coo(
                jnp.concatenate(p_rows), jnp.concatenate(p_cols),
                jnp.concatenate(p_vals), n, nc)
            raw = csr_multiply(Ap, P_cur)
            rr, rc, rv = raw.coo()
            keep = rv != 0
            p_rows.append(rr[keep])
            p_cols.append(rc[keep])
            p_vals.append((rv * scale[rr])[keep])

        P = CsrMatrix.from_coo(
            jnp.concatenate(p_rows), jnp.concatenate(p_cols),
            jnp.concatenate(p_vals), n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


def _truncate(P: CsrMatrix, factor: float, max_elements: int) -> CsrMatrix:
    """Drop small interpolation entries / cap per-row count, rescaling to
    preserve row sums (src/truncate.cu semantics for P)."""
    if factor > 1.0 and max_elements <= 0:
        return P
    rows, cols, vals = P.coo()
    n = P.num_rows
    absv = jnp.abs(vals)
    keep = jnp.ones_like(vals, bool)
    if factor <= 1.0:
        rmax = jax.ops.segment_max(absv, rows, num_segments=n,
                                   indices_are_sorted=True)
        keep &= absv >= factor * rmax[rows]
    if max_elements > 0:
        # keep only the max_elements largest |entries| per row: rank by
        # (row, -|v|) and drop ranks beyond the cap (host-side; the
        # entry count is per-level-small and this runs once at setup)
        rnp = np.asarray(rows)
        ordn = np.lexsort((-np.asarray(absv), rnp))
        _, first = np.unique(rnp[ordn], return_index=True)
        grp = np.zeros(len(ordn), np.int64)
        grp[first] = 1
        gid = np.cumsum(grp) - 1
        within = np.arange(len(ordn)) - first[gid]
        keep_np = np.array(keep)        # copy: jax buffers are read-only
        keep_np[ordn] &= within < max_elements
        keep = jnp.asarray(keep_np)
    # rescale kept entries to preserve row sums
    rowsum = jax.ops.segment_sum(vals, rows, num_segments=n,
                                 indices_are_sorted=True)
    keptsum = jax.ops.segment_sum(jnp.where(keep, vals, 0.0), rows,
                                  num_segments=n, indices_are_sorted=True)
    scale = rowsum / jnp.where(keptsum == 0, 1.0, keptsum)
    scale = jnp.where(keptsum == 0, 1.0, scale)
    kn = np.asarray(keep)
    rows_k = np.asarray(rows)[kn]
    cols_k = np.asarray(cols)[kn]
    vals_k = np.asarray(vals * scale[rows])[kn]
    return CsrMatrix.from_coo(rows_k, cols_k, vals_k, n, P.num_cols)
