"""Classical interpolation operators.

Analogs of src/classical/interpolators/ (distance1.cu 900 LoC,
distance2.cu 2274 LoC, multipass.cu). Round-1 surface:

- D1: Ruge-Stuben *direct* interpolation with positive-coupling lumping.
  For a fine point i with strong coarse neighbors C_i:

      w_ij = -alpha_i * a_ij / ~a_ii        for j in C_i (a_ij < 0)
      alpha_i = sum_{k != i, a_ik<0} a_ik / sum_{j in C_i, a_ij<0} a_ij
      ~a_ii   = a_ii + sum_{k != i, a_ik>0, k not in C_i} a_ik

  Coarse points interpolate by injection (P row = e_c). All assembled
  with COO masks + segment sums (no per-row loops).
- Truncation (interp_truncation_factor / interp_max_elements) trims P
  and rescales rows to preserve the row sum (truncate analog).
- MULTIPASS: real Stuben multipass interpolation (multipass.cu analog)
  via filtered SpGEMM passes — F-points acquire weights pass by pass
  through already-interpolated neighbors (see MultipassInterpolator).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix


def _coarse_index(cf_map):
    """coarse id per vertex (valid where cf_map==COARSE); nc."""
    is_c = cf_map == 1
    cidx = jnp.cumsum(is_c.astype(jnp.int32)) - 1
    nc = int(cidx[-1]) + 1 if cf_map.shape[0] else 0
    return jnp.where(is_c, cidx, -1), nc


def _compact_coo(rows, cols, vals, mask, n, num_cols=None):
    """Device compaction of masked COO entries into an exact-size CSR:
    one host scalar sync (the count) + a sized nonzero gather — the
    static-shape idiom the aggregation Galerkin uses, replacing the
    round-1 host-numpy compress."""
    u = int(jnp.sum(mask))                       # one sync
    m = num_cols if num_cols is not None else n
    if u == 0:
        return CsrMatrix.from_scipy_like(
            jnp.zeros((n + 1,), jnp.int32), jnp.zeros((0,), jnp.int32),
            jnp.zeros((0,), vals.dtype), n, m)
    idx = jnp.nonzero(mask, size=u)[0]           # ascending -> CSR order
    r = rows[idx].astype(jnp.int32)
    c = cols[idx].astype(jnp.int32)
    v = vals[idx]
    counts = jnp.bincount(r, length=n)
    ro = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                          jnp.cumsum(counts).astype(jnp.int32)])
    return CsrMatrix.from_scipy_like(ro, c, v, n, m)


def _coo_member(keys_sorted, key_vals, ri, cj, n):
    """(ri, cj) membership against a row-major-sorted COO whose value is
    a positive indicator — binary search, no compaction or sort. Entries
    with non-positive values (masked/padded) never match because
    searchsorted('left') lands on the first occurrence of a key, which
    holds the coalesced sum."""
    key = ri.astype(jnp.int64) * n + cj.astype(jnp.int64)
    if keys_sorted.shape[0] == 0:
        return jnp.zeros(key.shape, bool)
    pos = jnp.clip(jnp.searchsorted(keys_sorted, key), 0,
                   keys_sorted.shape[0] - 1)
    return (keys_sorted[pos] == key) & (key_vals[pos] > 0)


class Interpolator:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope
        self.trunc_factor = float(cfg.get("interp_truncation_factor", scope))
        self.max_elements = int(cfg.get("interp_max_elements", scope))

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        raise NotImplementedError


@registry.interpolators.register("D2")
class Distance2Interpolator(Interpolator):
    """Extended+i distance-two interpolation (distance2.cu analog; the
    formula of De Sterck/Falgout/Nolting/Yang, "Distance-two
    interpolation for parallel algebraic multigrid", 2008):

        w_ij = -(1/D_i) [ a_ij 1{j in C^_i}
                          + sum_{k in F_i^s} a_ik abar_kj / d_ik ]
        d_ik = sum_{l in C^_i + {i}} abar_kl
        D_i  = a_ii + sum_{n weak, n not in C^_i} a_in
                    + sum_{k in F_i^s} a_ik abar_ki / d_ik

    with C^_i = C_i + union of strong-C neighbors of i's strong-F
    neighbors, and abar the negative-coupling part of A. Everything is
    COO expands + segment sums: the two-hop triple expansion reuses the
    SpGEMM machinery, membership tests are sorted-key searches. This is
    what makes PMIS-coarsened V-cycles scalable (the D1 rate degrades
    with depth)."""

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        from ...ops.spgemm import _on_host
        if _on_host(A):
            return self._generate_host(A, cf_map, strong)
        return self._generate_jnp(A, cf_map, strong)

    def _generate_host(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        """Numpy formulation of the same formula for the host-setup
        path: eager accelerator-shaped gathers cost ~10 ms each in
        dispatch on CPU; the identical index math in numpy runs the
        whole interpolation in tens of milliseconds."""
        from ... import native
        n = A.num_rows
        if not A.has_external_diag:
            # native C++ row sweep (the distance2.cu host analog): same
            # formula, stamp-array C-hat membership instead of sorted-key
            # searches — this is the classical-setup hot path
            out = native.d2_interp_native(
                n, np.asarray(A.row_offsets), np.asarray(A.col_indices),
                np.asarray(A.values), np.asarray(strong, np.uint8),
                np.asarray(cf_map, np.int32), self.trunc_factor,
                self.max_elements)
            if out is not None:
                # truncation is fused into the native sweep; numpy-backed
                # on purpose: the host hierarchy build stays off the
                # XLA:CPU array path end to end
                p_ptr, p_col, p_val = out
                nc = int(np.sum(np.asarray(cf_map) == 1))
                return CsrMatrix(
                    row_offsets=p_ptr.astype(np.int32), col_indices=p_col,
                    values=p_val.astype(np.asarray(A.values).dtype,
                                        copy=False), num_rows=n,
                    num_cols=nc)
        ro = np.asarray(A.row_offsets)
        cols = np.asarray(A.col_indices)
        vals = np.asarray(A.values)
        rows = np.repeat(np.arange(n, dtype=np.int32), np.diff(ro))
        cf_map = np.asarray(cf_map)
        strong = np.asarray(strong)
        diag = np.asarray(A.diagonal())
        sgn = np.sign(np.where(diag == 0, 1.0, diag))
        offd = rows != cols
        neg = offd & (vals * sgn[rows] < 0)
        is_C = cf_map == 1
        cidx = np.cumsum(is_C.astype(np.int64)) - 1
        cidx = np.where(is_C, cidx, -1)
        nc = int(is_C.sum())
        strongC = strong & is_C[cols]
        strongF = strong & ~is_C[cols] & offd

        def compact_csr(mask):
            r, c, v = rows[mask], cols[mask], vals[mask]
            counts = np.bincount(r, minlength=n)
            rp = np.zeros(n + 1, np.int64)
            np.cumsum(counts, out=rp[1:])
            return rp, c, v

        f_ptr, f_col, f_val = compact_csr(strongF)
        a_ptr, a_col, a_val = compact_csr(neg)
        sc_ptr, sc_col, sc_val = compact_csr(strongC)
        # C-hat membership: strong C neighbors + two-hop through F
        out = native.spgemm_native(
            n, n, f_ptr.astype(np.int32), f_col,
            np.ones_like(f_val), sc_ptr.astype(np.int32), sc_col,
            np.ones_like(sc_val))
        if out is not None:
            hp, hc, _hv = out
            h_rows = np.repeat(np.arange(n, dtype=np.int64),
                               np.diff(hp))
            keys_h = h_rows * n + hc.astype(np.int64)
        else:       # no toolchain: use the accelerator-shaped path
            return self._generate_jnp(A, cf_map, strong)
        sc_rows = rows[strongC].astype(np.int64)
        keys_sc = sc_rows * n + cols[strongC].astype(np.int64)

        def member(ri, cj):
            key = ri.astype(np.int64) * n + cj.astype(np.int64)
            out_m = np.zeros(key.shape, bool)
            for ks in (keys_sc, keys_h):
                if ks.shape[0]:
                    pos = np.clip(np.searchsorted(ks, key), 0,
                                  ks.shape[0] - 1)
                    out_m |= ks[pos] == key
            return out_m

        # two-hop triples (i -k-> m): expand F against Abar
        f_rows = np.repeat(np.arange(n, dtype=np.int32),
                           np.diff(f_ptr))
        a_row_nnz = np.diff(a_ptr)
        counts = a_row_nnz[f_col]
        src_f = np.repeat(np.arange(f_col.shape[0]), counts)
        cum = np.zeros(f_col.shape[0] + 1, np.int64)
        np.cumsum(counts, out=cum[1:])
        offset_in_row = np.arange(int(cum[-1]), dtype=np.int64) - \
            cum[src_f]
        src_b = a_ptr[f_col[src_f]] + offset_in_row
        t_i = f_rows[src_f]
        t_m = a_col[src_b]
        t_aik = f_val[src_f]
        t_abar = a_val[src_b]
        keep = member(t_i, t_m) | (t_m == t_i)
        denom = np.zeros(f_col.shape[0])
        np.add.at(denom, src_f, np.where(keep, t_abar, 0.0))
        bad = denom == 0
        dsafe = np.where(bad, 1.0, denom)
        contrib = t_aik * t_abar / dsafe[src_f]
        contrib = np.where(bad[src_f], 0.0, contrib)

        m_is_entry = keep & is_C[t_m] & (t_m != t_i)
        e_rows = t_i[m_is_entry]
        e_cols = t_m[m_is_entry]
        e_vals = contrib[m_is_entry]
        in_chat = member(rows, cols)
        dmask = offd & is_C[cols] & in_chat
        fb = np.zeros(n)
        np.add.at(fb, t_i, np.where(keep & (t_m == t_i), contrib, 0.0))
        lump_mask = offd & ~in_chat & ~strongF
        lump = np.zeros(n)
        np.add.at(lump, rows, np.where(lump_mask, vals, 0.0))
        bad_f = np.zeros(n)
        np.add.at(bad_f, f_rows, np.where(bad, f_val, 0.0))
        D = diag + lump + fb + bad_f

        all_rows = np.concatenate([rows[dmask], e_rows])
        all_cols = np.concatenate([cols[dmask], e_cols])
        all_vals = np.concatenate([vals[dmask], e_vals])
        f_row = (cf_map == 0)[all_rows]
        w = -all_vals / np.where(D[all_rows] == 0, 1.0, D[all_rows])
        c_rows = np.nonzero(cf_map == 1)[0].astype(np.int32)
        p_rows = np.concatenate([all_rows[f_row], c_rows])
        p_cols = np.concatenate([cidx[all_cols[f_row]], cidx[c_rows]])
        p_vals = np.concatenate([w[f_row], np.ones(nc, vals.dtype)])
        order = np.lexsort((p_cols, p_rows))
        p_rows, p_cols, p_vals = (p_rows[order], p_cols[order],
                                  p_vals[order])
        # coalesce duplicates (from_coo semantics)
        first = np.concatenate([[True], (p_rows[1:] != p_rows[:-1])
                                | (p_cols[1:] != p_cols[:-1])])
        seg = np.cumsum(first) - 1
        vsum = np.zeros(int(seg[-1]) + 1 if seg.size else 0,
                        p_vals.dtype)
        np.add.at(vsum, seg, p_vals)
        pr, pc = p_rows[first], p_cols[first]
        counts = np.bincount(pr, minlength=n)
        pp = np.zeros(n + 1, np.int32)
        np.cumsum(counts, out=pp[1:])
        P = CsrMatrix.from_scipy_like(pp, pc.astype(np.int32),
                                      jnp.asarray(vsum), n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)

    def _generate_jnp(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        from ...ops.spgemm import _expand, csr_multiply
        n = A.num_rows
        rows, cols, vals = A.coo()
        rows64 = rows.astype(jnp.int64)
        cols64 = cols.astype(jnp.int64)
        diag = A.diagonal()
        sgn = jnp.sign(jnp.where(diag == 0, 1.0, diag))
        offd = rows != cols
        neg = offd & (vals * sgn[rows] < 0)      # abar pattern
        is_C = cf_map == 1
        cidx, nc = _coarse_index(cf_map)
        strongC = strong & is_C[cols]
        strongF = strong & ~is_C[cols] & offd

        Fmat = _compact_coo(rows, cols, vals, strongF, n)  # i -> k
        Abar = _compact_coo(rows, cols, vals, neg, n)      # k -> m

        # C-hat membership set: strong C neighbors + two-hop through F
        Sc01 = _compact_coo(rows, cols, jnp.ones_like(vals), strongC, n)
        Sf01 = CsrMatrix.from_scipy_like(
            Fmat.row_offsets, Fmat.col_indices,
            jnp.ones_like(Fmat.values), n, n)
        H = csr_multiply(Sf01, Sc01)
        hr, hc, hv = H.coo()
        scr, scc, scv = Sc01.coo()
        # both COO sets are row-major sorted: membership = binary search
        # in either (no host unique/merge)
        keys_sc = scr.astype(jnp.int64) * n + scc.astype(jnp.int64)
        keys_h = hr.astype(jnp.int64) * n + hc.astype(jnp.int64)

        def member(ri, cj):
            return (_coo_member(keys_sc, scv, ri, cj, n)
                    | _coo_member(keys_h, hv, ri, cj, n))

        # two-hop triples (i -k-> m)
        t_rows, t_m, src_f, src_b = _expand(Fmat, Abar)
        t_i = t_rows
        t_k = Fmat.col_indices[src_f]
        t_aik = Fmat.values[src_f]
        t_abar = Abar.values[src_b]
        keep = member(t_i, t_m) | (t_m == t_i)
        denom = jax.ops.segment_sum(jnp.where(keep, t_abar, 0.0), src_f,
                                    num_segments=Fmat.nnz)
        bad = denom == 0                          # k distributes nowhere
        dsafe = jnp.where(bad, 1.0, denom)
        contrib = t_aik * t_abar / dsafe[src_f]
        contrib = jnp.where(bad[src_f], 0.0, contrib)

        # interpolatory entries: triples landing on C points in C-hat
        m_is_entry = keep & is_C[t_m] & (t_m != t_i)
        e_rows = t_i[m_is_entry]
        e_cols = t_m[m_is_entry]
        e_vals = contrib[m_is_entry]
        # direct part: a_ij for neighbors j in C-hat (evaluated once,
        # shared with the weak-lumping mask below)
        in_chat = member(rows, cols)
        dmask = offd & is_C[cols] & in_chat
        # diagonal D_i: weak lumping + the "+i" feedback terms
        fb = jax.ops.segment_sum(
            jnp.where(keep & (t_m == t_i), contrib, 0.0), t_i,
            num_segments=n)
        lump_mask = offd & ~in_chat & ~strongF
        lump = jax.ops.segment_sum(jnp.where(lump_mask, vals, 0.0), rows,
                                   num_segments=n, indices_are_sorted=True)
        # strong-F neighbors whose denominator collapsed: lump them too
        f_row_ids = Fmat.coo()[0]
        bad_f = jax.ops.segment_sum(jnp.where(bad, Fmat.values, 0.0),
                                    f_row_ids, num_segments=n)
        D = diag + lump + fb + bad_f

        all_rows = jnp.concatenate([rows[dmask], e_rows])
        all_cols = jnp.concatenate([cols[dmask], e_cols])
        all_vals = jnp.concatenate([vals[dmask], e_vals])
        f_row = (cf_map == 0)[all_rows]
        w = -all_vals / jnp.where(D[all_rows] == 0, 1.0, D[all_rows])
        c_rows = jnp.where(cf_map == 1)[0].astype(jnp.int32)
        p_rows = jnp.concatenate([all_rows[f_row], c_rows])
        p_cols = jnp.concatenate([cidx[all_cols[f_row]], cidx[c_rows]])
        p_vals = jnp.concatenate([w[f_row],
                                  jnp.ones((nc,), vals.dtype)])
        P = CsrMatrix.from_coo(p_rows, p_cols, p_vals, n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


@registry.interpolators.register("D1")
class Distance1Interpolator(Interpolator):
    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        n = A.num_rows
        rows, cols, vals = A.coo()
        diag = A.diagonal()
        cidx, nc = _coarse_index(cf_map)
        is_f_row = (cf_map == 0)[rows]
        neg = vals < 0
        offd = rows != cols
        in_Ci = strong & (cidx[cols] >= 0) & neg & offd

        sum_neg = jax.ops.segment_sum(jnp.where(offd & neg, vals, 0.0),
                                      rows, num_segments=n,
                                      indices_are_sorted=True)
        sum_Ci = jax.ops.segment_sum(jnp.where(in_Ci, vals, 0.0),
                                     rows, num_segments=n,
                                     indices_are_sorted=True)
        # positive off-diagonals not interpolated from: lump into diagonal
        pos_lump = jax.ops.segment_sum(
            jnp.where(offd & ~neg, vals, 0.0), rows, num_segments=n,
            indices_are_sorted=True)
        dmod = diag + pos_lump
        alpha = sum_neg / jnp.where(sum_Ci == 0, 1.0, sum_Ci)
        alpha = jnp.where(sum_Ci == 0, 0.0, alpha)
        w = -alpha[rows] * vals / jnp.where(dmod[rows] == 0, 1.0, dmod[rows])

        # P entries: F rows interpolate from C_i; C rows inject
        mask = in_Ci & is_f_row
        p_rows = jnp.concatenate([rows[mask],
                                  jnp.where(cf_map == 1)[0].astype(jnp.int32)])
        p_cols = jnp.concatenate([cidx[cols[mask]],
                                  cidx[jnp.where(cf_map == 1)[0]]])
        p_vals = jnp.concatenate([w[mask],
                                  jnp.ones((nc,), vals.dtype)])
        P = CsrMatrix.from_coo(p_rows, p_cols, p_vals, n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


@registry.interpolators.register("MULTIPASS")
class MultipassInterpolator(Interpolator):
    """Multipass interpolation for aggressive coarsening
    (multipass.cu:1, 2557 LoC; Stuben's multipass scheme). F-points are
    ranked by their strong-connection distance to the C-set ("pass"
    number); pass-1 points interpolate directly from strong C neighbors
    (the D1 formula), and pass-p points substitute the already-built P
    rows of their pass<p strong neighbors:

        w_i = -(alpha_i / ~a_ii) * sum_{j in J_i} a_ij P_j,
        alpha_i = sum_{k != i, a_ik<0} a_ik / sum_{j in J_i} a_ij,
        J_i = strong negative neighbors with pass < p

    so each pass is one filtered-SpGEMM (A restricted to pass-p rows and
    pass<p columns, times the current P) — the reference's per-pass
    kernel sweeps become a handful of sort-based SpGEMM calls.
    """

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        from ...ops.spgemm import csr_multiply
        n = A.num_rows
        rows, cols, vals = A.coo()
        diag = A.diagonal()
        cidx, nc = _coarse_index(cf_map)
        is_C = cf_map == 1
        offd = rows != cols
        neg = vals < 0
        strong_neg = strong & offd & neg
        # ~a_ii: positive off-diagonals lumped into the diagonal (D1
        # semantics)
        pos_lump = jax.ops.segment_sum(
            jnp.where(offd & ~neg, vals, 0.0), rows, num_segments=n,
            indices_are_sorted=True)
        dmod = diag + pos_lump
        sum_neg = jax.ops.segment_sum(jnp.where(offd & neg, vals, 0.0),
                                      rows, num_segments=n,
                                      indices_are_sorted=True)

        # pass numbers: BFS distance to C through strong edges
        BIG = np.int32(2 ** 30)
        pnum = jnp.where(is_C, 0, BIG).astype(jnp.int32)
        for _ in range(64):
            nbr_min = jax.ops.segment_min(
                jnp.where(strong_neg, pnum[cols], BIG), rows,
                num_segments=n, indices_are_sorted=True)
            new = jnp.where(is_C, 0, jnp.minimum(pnum, nbr_min + 1))
            if bool(jnp.all(new == pnum)):
                break
            pnum = new
        max_pass = int(jnp.max(jnp.where(pnum < BIG, pnum, 0)))

        # accumulate P rows pass by pass (C rows: injection)
        nc_i = int(jnp.sum(is_C))
        c_rows = jnp.nonzero(is_C, size=max(nc_i, 1))[0].astype(jnp.int32)
        p_rows = [c_rows[:nc_i]]
        p_cols = [cidx[c_rows[:nc_i]]]
        p_vals = [jnp.ones((nc_i,), vals.dtype)]

        for p in range(1, max_pass + 1):
            in_pass = pnum == p
            emask = strong_neg & in_pass[rows] & (pnum[cols] < p)
            denom = jax.ops.segment_sum(jnp.where(emask, vals, 0.0), rows,
                                        num_segments=n,
                                        indices_are_sorted=True)
            alpha = jnp.where(denom != 0,
                              sum_neg / jnp.where(denom == 0, 1.0, denom),
                              0.0)
            scale = -alpha / jnp.where(dmod == 0, 1.0, dmod)
            Ap = _compact_coo(rows, cols, vals, emask, n)
            # current P (global-column space n x nc)
            P_cur = CsrMatrix.from_coo(
                jnp.concatenate(p_rows), jnp.concatenate(p_cols),
                jnp.concatenate(p_vals), n, nc)
            raw = csr_multiply(Ap, P_cur)
            rr, rc, rv = raw.coo()
            u = int(jnp.sum(rv != 0))            # one sync per pass
            idx = jnp.nonzero(rv != 0, size=max(u, 1))[0]
            p_rows.append(rr[idx][:u])
            p_cols.append(rc[idx][:u])
            p_vals.append((rv * scale[rr])[idx][:u])

        P = CsrMatrix.from_coo(
            jnp.concatenate(p_rows), jnp.concatenate(p_cols),
            jnp.concatenate(p_vals), n, nc)
        return _truncate(P, self.trunc_factor, self.max_elements)


def _truncate(P: CsrMatrix, factor: float, max_elements: int) -> CsrMatrix:
    """Drop small interpolation entries / cap per-row count, rescaling to
    preserve row sums (src/truncate.cu semantics for P)."""
    if factor > 1.0 and max_elements <= 0:
        return P
    from ...matrix import host_resident
    if host_resident(P.row_offsets, P.col_indices, P.values):
        return _truncate_host(P, factor, max_elements)
    rows, cols, vals = P.coo()
    n = P.num_rows
    absv = jnp.abs(vals)
    keep = jnp.ones_like(vals, bool)
    if factor <= 1.0:
        rmax = jax.ops.segment_max(absv, rows, num_segments=n,
                                   indices_are_sorted=True)
        keep &= absv >= factor * rmax[rows]
    if max_elements > 0:
        # keep only the max_elements largest |entries| per row: rank by
        # (row, -|v|) via two stable device argsorts (the int32 lexsort
        # idiom), then cap the within-row rank
        e = rows.shape[0]
        order1 = jnp.argsort(-absv, stable=True)
        order2 = jnp.argsort(rows[order1], stable=True)
        ordn = order1[order2]                    # grouped by row, desc |v|
        pos = jnp.arange(e, dtype=jnp.int32)
        first = jax.ops.segment_min(pos, rows[ordn], num_segments=n)
        within = pos - first[rows[ordn]]
        keep = keep.at[ordn].set(keep[ordn] & (within < max_elements))
    # rescale kept entries to preserve row sums
    rowsum = jax.ops.segment_sum(vals, rows, num_segments=n,
                                 indices_are_sorted=True)
    keptsum = jax.ops.segment_sum(jnp.where(keep, vals, 0.0), rows,
                                  num_segments=n, indices_are_sorted=True)
    scale = rowsum / jnp.where(keptsum == 0, 1.0, keptsum)
    scale = jnp.where(keptsum == 0, 1.0, scale)
    return _compact_coo(rows, cols, vals * scale[rows], keep, P.num_rows,
                        num_cols=P.num_cols)


def _truncate_host(P: CsrMatrix, factor: float, max_elements: int
                   ) -> CsrMatrix:
    """Numpy form of _truncate for the host-setup path (same semantics;
    keeps the hierarchy numpy-backed — the truncated P feeds straight
    into the native RAP/SWELL components)."""
    n = P.num_rows
    ro = np.asarray(P.row_offsets)
    cols = np.asarray(P.col_indices)
    vals = np.asarray(P.values)
    rows = np.repeat(np.arange(n, dtype=np.int32), np.diff(ro))
    absv = np.abs(vals)
    keep = np.ones(vals.shape[0], bool)
    from ...matrix import _np_row_reduce
    if factor <= 1.0:
        rmax = _np_row_reduce(np.maximum, absv, ro, n, 0.0)
        keep &= absv >= factor * rmax[rows]
    if max_elements > 0:
        # rank entries within each row by descending |v| (stable), cap
        order1 = np.argsort(-absv, kind="stable")
        order2 = np.argsort(rows[order1], kind="stable")
        ordn = order1[order2]
        pos = np.arange(vals.shape[0], dtype=np.int64)
        first = np.full(n, vals.shape[0], np.int64)
        np.minimum.at(first, rows[ordn], pos)
        within = pos - first[rows[ordn]]
        keep[ordn] &= within < max_elements
    rowsum = np.bincount(rows, weights=vals, minlength=n)
    keptsum = np.bincount(rows, weights=np.where(keep, vals, 0.0),
                          minlength=n)
    scale = np.where(keptsum == 0, 1.0,
                     rowsum / np.where(keptsum == 0, 1.0, keptsum))
    new_vals = (vals * scale[rows])[keep]
    new_cols = cols[keep]
    counts = np.bincount(rows[keep], minlength=n)
    new_ro = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=new_ro[1:])
    return CsrMatrix(row_offsets=new_ro, col_indices=new_cols,
                     values=new_vals.astype(vals.dtype, copy=False),
                     num_rows=n, num_cols=P.num_cols)
