"""Pipelined value-only resetup for GEO/DIA hierarchies.

The reference's structure-reuse resetup (src/amg.cu:232-262) keeps the
coarsening and re-runs only the Galerkin products. The value plan here
chains the SAME jitted building blocks the setup itself dispatches
(`_geo_compute`, `_any_wrapped`, the eager DIA pack and dense-QR ops):
new fine DIA values in, every level's coarse DIA values, the Chebyshev
taus, and the coarse dense QR factor out — all async dispatches with
exactly ONE device sync (the batched GEO wrap-check flag, which must be
re-validated because it depends on the values; matrix-free levels fold
their stencil-constancy re-check into the same fetch and get their
StencilOperator coefficients respliced from it).

Reusing the setup's own jitted pieces is load-bearing for
`resetup_first_s`: an earlier revision fused the whole plan into one
mega-`jax.jit` program, which re-traced and re-compiled a second copy
of every Galerkin product on the FIRST resetup (23 s at 256^3 — worse
than a cold setup). The chained form hits the setup's compile caches,
so the first resetup costs roughly a steady-state resetup plus the tiny
tau/QR glue compiles.

Applies when every level is a GEO-paired DIA level with an in-line
diagonal (the flagship and north-star shape), every smoother is
CHEBYSHEV_POLY or NOSOLVER, and the coarse solver is DENSE_LU.
Anything else falls back to the generic structure-reuse loop.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..matrix import CsrMatrix


def _level_plan(level, Ac_structure):
    """Static per-level recompute recipe, or None when ineligible."""
    from .aggregation import AggregationAMGLevel
    from .aggregation.galerkin import (_decompose, _geo_contrib_table,
                                       _geo_csr_structure)
    if type(level) is not AggregationAMGLevel or level.geo_axes is None:
        return None
    A = level.A
    if A.dia_offsets is None or A.is_block or A.has_external_diag or \
            Ac_structure.has_external_diag or \
            A.grid_shape != tuple(level.geo_fine_shape):
        # external diagonals live outside dia_vals — the fused program
        # reads only dia_vals, so such hierarchies must take the
        # generic reuse loop
        return None
    if A.ell_vals is not None or A.swell_vals is not None or \
            Ac_structure.ell_vals is not None or \
            Ac_structure.swell_vals is not None:
        # the splice (try_value_resetup) rewrites values/dia_vals ONLY:
        # an ELL/SWELL cache on either matrix would keep serving the OLD
        # coefficients through spmv's layout dispatch. GEO levels never
        # build these layouts today — this check turns that assumption
        # into an enforced invariant instead of a silent-wrong-answer
        # path (load-bearing for the batched subsystem's per-system
        # value splice, batch/core.py).
        return None
    nx, ny, nz = level.geo_fine_shape
    # a planned setup (spgemm_plan=auto/1) memoized its GeoRapPlan on
    # the level: consume it — the contribution table and the
    # device-resident structure arrays are NEVER rebuilt by a value
    # resetup, and the numeric phase runs the very jitted program
    # (_geo_value_phase) the setup itself dispatched, so the first
    # resetup hits the setup's compile cache
    geo_plan = (getattr(level, "_geo_plan_memo", None) or (None,))[0]
    if geo_plan is not None:
        if tuple(int(k[0]) for k in geo_plan.coffsets) != \
                Ac_structure.dia_offsets:
            return None
        return dict(
            n=A.num_rows, k=len(A.dia_offsets),
            shifts=geo_plan.shifts,
            fine_shape=tuple(level.geo_fine_shape),
            geo_plan=geo_plan,
            nc=Ac_structure.num_rows,
            kc=len(Ac_structure.dia_offsets))
    decomp = {}
    for d in A.dia_offsets:
        g = _decompose(int(d), nx, ny, nz)
        if g is None:
            return None
        decomp[int(d)] = g
    shifts = tuple(decomp[int(d)] for d in A.dia_offsets)
    coffsets, contribs = _geo_contrib_table(
        tuple(int(d) for d in A.dia_offsets), shifts,
        tuple(level.geo_axes), tuple(level.geo_coarse_shape))
    if tuple(int(k[0]) for k in coffsets) != Ac_structure.dia_offsets:
        return None      # structure drifted; generic path sorts it out
    (_ro, off_e, row_e, _col_e, _diag) = _geo_csr_structure(
        coffsets, tuple(level.geo_coarse_shape))
    return dict(
        n=A.num_rows, k=len(A.dia_offsets), shifts=shifts,
        fine_shape=tuple(level.geo_fine_shape),
        axes=tuple(level.geo_axes),
        coarse_shape=tuple(level.geo_coarse_shape),
        coffsets=coffsets, contribs=contribs, geo_plan=None,
        # device-resident ONCE at plan build: re-uploading these O(nnz)
        # gather indices per resetup call would pay a host->device
        # transfer every cycle on tunneled rigs
        off_e=jnp.asarray(off_e), row_e=jnp.asarray(row_e),
        nc=Ac_structure.num_rows, kc=len(Ac_structure.dia_offsets))


def _smoother_plan(sm):
    name = getattr(sm, "name", "")
    if name == "CHEBYSHEV_POLY":
        return ("cheb", sm.order)
    if name in ("NOSOLVER", "DUMMY"):
        return ("none",)
    return None


def _lam_rowmax(vals2d):
    # Gershgorin bound from the DIA slab: row abs-sum = sum over stored
    # diagonals (out-of-grid slots are zero-filled)
    return jnp.max(jnp.sum(jnp.abs(vals2d), axis=0))


def build_plan(amg):
    """Trace-ready plan for amg's current hierarchy, or None."""
    from ..solvers.polynomial import chebyshev_poly_coeffs
    if not amg.levels or getattr(amg, "coarse_solver", None) is None:
        return None
    if getattr(amg.coarse_solver, "name", "") != "DENSE_LU_SOLVER":
        return None
    lv_plans, sm_plans = [], []
    chain = list(amg.levels)
    for i, lv in enumerate(chain):
        nxt = (chain[i + 1].A if i + 1 < len(chain) else amg.coarsest_A)
        p = _level_plan(lv, nxt)
        if p is None:
            return None
        lv_plans.append(p)
        sp = _smoother_plan(lv.smoother)
        if sp is None:
            return None
        sm_plans.append(sp)
    Az = amg.coarsest_A
    if Az.dia_offsets is None or Az.num_rows > 4096 or \
            Az.row_ids is None:
        return None
    # coarsest dense scatter structure + damping tables: device-resident
    # once here, not re-uploaded per resetup call
    cz_rows = jnp.asarray(Az.row_ids)
    cz_cols = jnp.asarray(Az.col_indices)
    nz = Az.num_rows
    dt_cast = amg._PRECISIONS[amg.precision]
    # the coarse-solver payload (QR factors) casts to the policy's
    # f32+ coarse dtype, matching the solve_data split cast
    dt_coarse = amg.precision_policy.coarse_dtype
    l0_dtype = chain[0].A.dtype
    cheb_tabs = {o: jnp.asarray(np.asarray(chebyshev_poly_coeffs(o)),
                                l0_dtype)
                 for _, *rest in sm_plans for o in rest}

    from .aggregation.galerkin import _any_wrapped, _geo_compute
    from ..ops.pallas_spmv import LANES, dia_padded_rows
    from ..ops.stencil import stencil_candidate

    # matrix-free levels (ops/stencil.py): their StencilOperator
    # coefficients must be refreshed from the new values, and the
    # constancy invariant re-validated — new values may no longer be a
    # constant stencil. The flag folds into the same single fetch as
    # the wrap check below.
    mf_on = [getattr(lv.smoother, "_mf_stencil", None) is not None
             for lv in chain]

    def run(dia_vals0):
        # EAGER on purpose: every heavy piece below (_geo_compute,
        # _any_wrapped) is the very jitted function the setup already
        # compiled for this hierarchy, and the glue (DIA pack, dense
        # scatter, QR, casts) is small eager ops — so the first resetup
        # reuses the setup traces instead of compiling a fused twin.
        outs = {"dia": [], "vals": [], "taus": [], "mf": [],
                "cast": {}}
        dia_vals = dia_vals0
        wrapped = jnp.zeros((), bool)
        for i, p in enumerate(lv_plans):
            vals2d = dia_vals.reshape(p["k"], -1)[:, : p["n"]]
            wrapped = wrapped | _any_wrapped(vals2d, p["shifts"],
                                             p["fine_shape"])
            if mf_on[i]:
                c = None
                if i > 0 and mf_on[i - 1]:
                    gp = lv_plans[i - 1]["geo_plan"]
                    if gp is not None:
                        # constancy is inherited: a constant fine
                        # stencil with even paired extents coarsens to
                        # a constant stencil, so the derived coarse
                        # coefficients need no re-compare
                        c = gp.coarse_coeffs(outs["mf"][i - 1])
                if c is None:
                    ok_i, c = stencil_candidate(vals2d, p["shifts"],
                                                p["fine_shape"])
                    wrapped = wrapped | ~ok_i
                outs["mf"].append(c)
            else:
                outs["mf"].append(None)
            if sm_plans[i][0] == "cheb":
                lam = _lam_rowmax(vals2d)
                taus = cheb_tabs[sm_plans[i][1]].astype(
                    dia_vals0.dtype) / lam
            else:
                taus = None
            outs["taus"].append(taus)
            if p["geo_plan"] is not None:
                # the planned setup route's own jitted numeric phase
                # (galerkin._geo_value_phase): compute + gather + DIA
                # pack in one dispatch, structure arrays cache-served
                values_c, dia_c = p["geo_plan"].values(vals2d)
            else:
                cvals = _geo_compute(vals2d, p["coffsets"],
                                     p["contribs"], p["fine_shape"],
                                     p["axes"])
                values_c = cvals[p["off_e"], p["row_e"]]
                rows_pad = dia_padded_rows(p["kc"], p["nc"])
                dia_c = jnp.zeros(
                    (p["kc"], rows_pad * LANES), cvals.dtype
                ).at[:, : p["nc"]].set(cvals).reshape(
                    p["kc"], rows_pad, LANES)
            outs["dia"].append(dia_c)
            outs["vals"].append(values_c)
            dia_vals = dia_c
        # coarsest dense + QR (DenseLUSolver.solver_setup semantics)
        dense = jnp.zeros((nz, nz), dia_vals0.dtype).at[
            cz_rows, cz_cols].add(outs["vals"][-1])
        zero_rows = jnp.all(dense == 0, axis=1)
        dense = jnp.where(jnp.diag(zero_rows),
                          jnp.eye(nz, dtype=dense.dtype), dense)
        q, r = jnp.linalg.qr(dense)
        outs["qt"], outs["r"] = q.T, r
        if dt_cast is not None:
            cast = {"dia0": dia_vals0.astype(dt_cast),
                    "dia": [d.astype(dt_cast) for d in outs["dia"]],
                    "taus": [None if t is None else t.astype(dt_cast)
                             for t in outs["taus"]],
                    "qt": outs["qt"].astype(dt_coarse),
                    "r": outs["r"].astype(dt_coarse)}
            outs["cast"] = cast
        outs["wrapped"] = wrapped
        return outs

    return {"fn": run, "lv": lv_plans, "sm": sm_plans, "mf_on": mf_on,
            "l0_sig": (tuple(int(d) for d in chain[0].A.dia_offsets),
                       chain[0].A.num_rows, len(chain))}


def try_value_resetup(amg, A: CsrMatrix) -> bool:
    """Apply the one-dispatch value-only resetup. Returns False when
    the hierarchy shape is ineligible or the new values break the GEO
    wrap invariant (caller falls back to the generic reuse loop)."""
    if not A.initialized or A.dia_vals is None:
        return False
    plan = getattr(amg, "_vr_plan", None)
    if plan is None:
        plan = build_plan(amg)
        amg._vr_plan = plan if plan is not None else False
    if not plan:
        return False
    sig = (tuple(int(d) for d in A.dia_offsets), A.num_rows,
           len(amg.levels))
    if sig != plan["l0_sig"]:
        return False
    if [getattr(lv.smoother, "_mf_stencil", None) is not None
            for lv in amg.levels] != plan["mf_on"]:
        # a generic resetup flipped a level's matrix-free form since
        # this plan was traced — rebuild so the coefficient refresh
        # covers exactly the live stencils (a stale splice would leave
        # old coefficients serving new values)
        amg._vr_plan = None
        plan = build_plan(amg)
        amg._vr_plan = plan if plan is not None else False
        if not plan:
            return False
    outs = plan["fn"](A.dia_vals)
    if bool(outs["wrapped"]):     # ONE scalar fetch — the only sync
        amg._vr_plan = None       # values violate the GEO invariant
        return False
    # ---- splice (host-side bookkeeping only, no device work) ----------
    precast = {}
    cast = outs["cast"]
    amg.levels[0].A = A
    if cast:
        precast[id(A.dia_vals)] = cast["dia0"]
    fine = A
    for i, lv in enumerate(amg.levels):
        Ac_old = (amg.levels[i + 1].A if i + 1 < len(amg.levels)
                  else amg.coarsest_A)
        Ac = dataclasses.replace(Ac_old, values=outs["vals"][i],
                                 dia_vals=outs["dia"][i])
        if i + 1 < len(amg.levels):
            amg.levels[i + 1].A = Ac
        else:
            amg.coarsest_A = Ac
        if cast:
            precast[id(Ac.dia_vals)] = cast["dia"][i]
        sm = lv.smoother
        sm.A = fine
        st = getattr(sm, "_mf_stencil", None)
        if st is not None and outs["mf"][i] is not None:
            # fresh leaf on purpose: downstream solve_data caches key
            # on identity, and the stencil's static fields are unchanged
            sm._mf_stencil = dataclasses.replace(
                st, coeffs=outs["mf"][i])
        if plan["sm"][i][0] == "cheb":
            sm._taus = outs["taus"][i]
            if cast:
                precast[id(sm._taus)] = cast["taus"][i]
        fine = Ac
    cs = amg.coarse_solver
    cs.A = amg.coarsest_A
    cs._qt, cs._r = outs["qt"], outs["r"]
    if cast:
        precast[id(cs._qt)] = cast["qt"]
        precast[id(cs._r)] = cast["r"]
    amg._data_cache = None
    amg._resetup_precast = precast
    return True
