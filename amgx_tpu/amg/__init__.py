"""AMG hierarchy layer: registers level types, cycles, selectors and the
"AMG" solver (registerClasses analog for L4)."""
from . import hierarchy  # noqa: F401
from . import aggregation  # noqa: F401
from . import classical  # noqa: F401
from . import energymin  # noqa: F401
from . import solver  # noqa: F401

from .hierarchy import AMG, AMGLevel  # noqa: F401
