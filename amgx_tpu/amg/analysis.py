"""Per-level cycle convergence analysis.

Analog of src/cycles/convergence_analysis.cu (:222): for the first
`convergence_analysis` levels, run one instrumented error-propagation
cycle (b = 0, x = e random, so the cycle acts on pure error) and report
the residual reduction of each phase — pre-smoothing, coarse-grid
correction, post-smoothing — per level. The instrumented cycle runs
eagerly once (a diagnostic, not the production traced cycle).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.spmv import residual


def _nrm(v):
    return float(jnp.linalg.norm(v))


def _analyze(amg, data, lvl, b, x, rows):
    from .cycles import _coarse_solve, _smooth
    levels = amg.levels
    if lvl == len(levels):
        return _coarse_solve(amg, data, b, x)
    level = levels[lvl]
    ldata = data["levels"][lvl]
    instrument = lvl < amg.convergence_analysis
    rec = {"level": lvl, "n": level.A.num_rows}
    if instrument:
        rec["pre_in"] = _nrm(residual(ldata["A"], x, b))
    x = _smooth(level, ldata, b, x, amg._sweeps(lvl, pre=True))
    if instrument:
        rec["pre_out"] = _nrm(residual(ldata["A"], x, b))
    r = residual(ldata["A"], x, b)
    bc = level.restrict(ldata, r)
    xc = jnp.zeros_like(bc)
    xc = _analyze(amg, data, lvl + 1, bc, xc, rows)
    x = x + level.prolongate(ldata, xc)
    if instrument:
        rec["coarse_out"] = _nrm(residual(ldata["A"], x, b))
    x = _smooth(level, ldata, b, x, amg._sweeps(lvl, pre=False))
    if instrument:
        rec["post_out"] = _nrm(residual(ldata["A"], x, b))
        rows.append(rec)
    return x


def convergence_analysis(amg, data=None, seed: int = 0) -> str:
    """Run the instrumented error-propagation cycle and format the
    per-level phase-reduction report (printConvergenceAnalysis
    analog)."""
    if data is None:
        data = amg.solve_data()
    n = amg.levels[0].A.num_rows * amg.levels[0].A.block_dimx
    e = jnp.asarray(np.random.default_rng(seed).standard_normal(n),
                    amg.levels[0].A.dtype)
    b = jnp.zeros_like(e)            # b = 0: the cycle acts on x = e
    rows = []
    _analyze(amg, data, 0, b, e, rows)
    out = ["Convergence analysis (error-propagation cycle, b=0):",
           f"{'level':>5} {'rows':>10} {'presmooth':>10} "
           f"{'coarse':>10} {'postsmooth':>10} {'total':>10}"]

    def ratio(a, c):
        return c / a if a > 0 else 0.0
    for r in sorted(rows, key=lambda r: r["level"]):
        pre = ratio(r["pre_in"], r["pre_out"])
        crs = ratio(r["pre_out"], r["coarse_out"])
        post = ratio(r["coarse_out"], r["post_out"])
        tot = ratio(r["pre_in"], r["post_out"])
        out.append(f"{r['level']:>5} {r['n']:>10} {pre:>10.4f} "
                   f"{crs:>10.4f} {post:>10.4f} {tot:>10.4f}")
    return "\n".join(out)
