"""Device-memory usage tracking (MemoryInfo analog).

The reference tracks a process-wide high-water mark through
`MemoryInfo::updateMaxMemoryUsage` (include/memory_info.h:33) and prints
it in the per-iteration solve-stats table. Here the numbers come from
the backend's allocator statistics (`device.memory_stats()` on TPU; CPU
reports none and reads as zero), sampled at update points rather than
hooked into every allocation — XLA owns the allocator.
"""
from __future__ import annotations

# single process-wide tracker: per-device high-water marks, so scoped
# views (Resources over a device subset) and global views read the same
# samples instead of maintaining parallel peaks that can disagree
_peak_per_dev: dict = {}


def sum_device_stats(devices) -> dict:
    """Sum allocator statistics over `devices` (empty dict when the
    backend reports none)."""
    total: dict = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            for k, v in stats.items():
                if isinstance(v, (int, float)):
                    total[k] = total.get(k, 0) + v
    return total


def _sample(devices):
    """Sample bytes_in_use per device, folding each into its peak;
    returns (current_sum, peak_sum) over `devices`."""
    cur_sum = 0
    peak_sum = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        cur = int(stats.get("bytes_in_use", 0)) if stats else 0
        key = repr(d)
        _peak_per_dev[key] = max(_peak_per_dev.get(key, 0), cur)
        cur_sum += cur
        peak_sum += _peak_per_dev[key]
    return cur_sum, peak_sum


def update_max_memory_usage(devices=None) -> int:
    """Sample current device usage (all local devices by default), fold
    into the per-device high-water marks, and return the current bytes
    (updateMaxMemoryUsage analog)."""
    import jax
    cur, _ = _sample(devices if devices is not None
                     else jax.local_devices())
    return cur


def usage_over(devices):
    """(current, peak) bytes over the given devices, sharing the
    process-wide per-device peaks."""
    return _sample(devices)


def get_max_memory_usage() -> int:
    """High-water mark in bytes (sum of per-device peaks)."""
    return sum(_peak_per_dev.values())


def peak_bytes(devices=None) -> int:
    """Allocator high-water mark over `devices` (all local devices by
    default), preferring the backend's own `peak_bytes_in_use`
    statistic — unlike the sampled peaks, it captures TRANSIENT
    in-phase maxima (e.g. Galerkin temporaries freed before the phase
    boundary where we sample). Falls back to the sampled current bytes
    per device where the backend reports no peak, and folds every
    sample into the shared per-device peaks."""
    import jax
    devs = devices if devices is not None else jax.local_devices()
    total = 0
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        cur = int(stats.get("bytes_in_use", 0)) if stats else 0
        peak = int(stats.get("peak_bytes_in_use", cur)) if stats else 0
        key = repr(d)
        _peak_per_dev[key] = max(_peak_per_dev.get(key, 0), peak, cur)
        total += max(peak, cur)
    return total


def get_memory_usage_gb() -> float:
    import jax
    return _sample(jax.local_devices())[0] / 2**30


def reset():
    _peak_per_dev.clear()
