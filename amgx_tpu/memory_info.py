"""Device-memory usage tracking (MemoryInfo analog).

The reference tracks a process-wide high-water mark through
`MemoryInfo::updateMaxMemoryUsage` (include/memory_info.h:33) and prints
it in the per-iteration solve-stats table. Here the numbers come from
the backend's allocator statistics (`device.memory_stats()` on TPU; CPU
reports none and reads as zero), sampled at update points rather than
hooked into every allocation — XLA owns the allocator.
"""
from __future__ import annotations

_max_bytes = 0


def sum_device_stats(devices) -> dict:
    """Sum allocator statistics over `devices` (empty dict when the
    backend reports none)."""
    total: dict = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            for k, v in stats.items():
                if isinstance(v, (int, float)):
                    total[k] = total.get(k, 0) + v
    return total


def _current_bytes() -> int:
    import jax
    return int(sum_device_stats(jax.local_devices()).get(
        "bytes_in_use", 0))


def update_max_memory_usage() -> int:
    """Sample current device usage, fold into the high-water mark, and
    return the current bytes (updateMaxMemoryUsage analog)."""
    global _max_bytes
    cur = _current_bytes()
    _max_bytes = max(_max_bytes, cur)
    return cur


def get_max_memory_usage() -> int:
    """High-water mark in bytes since process start / last reset."""
    return _max_bytes


def get_memory_usage_gb() -> float:
    return _current_bytes() / 2**30


def reset():
    global _max_bytes
    _max_bytes = 0
