"""Factory registries.

The reference's backbone is a set of static string-keyed factories
(SolverFactory, CycleFactory, selectors, interpolators, ... registered in
src/core.cu:546-691). This module is the TPU-native equivalent: one
generic `Factory` class plus module-level registries for each pluggable
kind. Components self-register at import time via decorators.
"""
from __future__ import annotations

from typing import Callable, Dict

from .errors import BadParametersError


class Factory:
    """A named registry of constructors for one component kind."""

    def __init__(self, kind: str):
        self.kind = kind
        self._ctors: Dict[str, Callable] = {}

    def register(self, name: str, ctor: Callable | None = None):
        """Register a constructor. Usable as `f.register("NAME", ctor)` or as
        a class decorator `@f.register("NAME")`."""
        if ctor is None:
            def deco(c):
                self._ctors[name.upper()] = c
                return c
            return deco
        self._ctors[name.upper()] = ctor
        return ctor

    def unregister(self, name: str):
        self._ctors.pop(name.upper(), None)

    def has(self, name: str) -> bool:
        return name.upper() in self._ctors

    def get(self, name: str) -> Callable:
        try:
            return self._ctors[name.upper()]
        except KeyError:
            from .errors import did_you_mean
            raise BadParametersError(
                f"{self.kind} factory: unknown name {name!r}"
                f"{did_you_mean(name.upper(), self._ctors)}; "
                f"registered: {sorted(self._ctors)}") from None

    def create(self, name: str, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def names(self):
        return sorted(self._ctors)


# One registry per pluggable kind, mirroring registerClasses
# (src/core.cu:583-691).
solvers = Factory("Solver")
eigensolvers = Factory("EigenSolver")
cycles = Factory("Cycle")
amg_levels = Factory("AMG_Level")
classical_selectors = Factory("ClassicalSelector")
aggregation_selectors = Factory("AggregationSelector")
interpolators = Factory("Interpolator")
energymin_interpolators = Factory("EnergyminInterpolator")
strength = Factory("StrengthOfConnection")
coarse_generators = Factory("CoarseAGenerator")
matrix_coloring = Factory("MatrixColoring")
convergence = Factory("Convergence")
scalers = Factory("Scaler")
matrix_io_readers = Factory("MatrixReader")
matrix_io_writers = Factory("MatrixWriter")

ALL = {
    f.kind: f
    for f in (
        solvers, eigensolvers, cycles, amg_levels, classical_selectors,
        aggregation_selectors, interpolators, energymin_interpolators,
        strength, coarse_generators, matrix_coloring, convergence, scalers,
        matrix_io_readers, matrix_io_writers,
    )
}
