"""Shared precision policy: one resolver for every precision knob.

The reference is precision-mode templated end to end
(`TemplateConfig<MemSpace, VecPrec, MatPrec, IndPrec>`, PAPER.md §1)
with mixed modes as first-class products; this port grew three knobs
that used to guess about each other:

- ``solve_precision`` (NEW, default unset ``""``) — the user-facing
  solve-phase knob: the precision the inner multigrid cycle streams
  its operands at (``double`` = native/full, ``float`` = f32,
  ``bfloat16`` = bf16 slabs with f32 in-kernel accumulation). Setting
  it also turns on per-precision iteration accounting in the
  REFINEMENT defect-correction shell (``SolveReport.precision``).
  Unset is bitwise-off: the emitted jaxpr is identical to a build
  without the knob.
- ``amg_precision`` — the hierarchy-level spelling of the same
  quantity (precision of the stored AMG operators + cycle). Still
  works standalone; when ``solve_precision`` is also set the two must
  agree or configuration fails up front.
- ``tpu_dtype`` — legacy compute-dtype override (``float32`` /
  ``float64`` / ``bfloat16``); previously registered but read by
  nothing. It now resolves through this policy as an alias
  (``float64`` -> ``double``, ``float32`` -> ``float``) and
  contradictions with the other two knobs are rejected.

Ownership matrix (highest priority first):

    solve_precision   solve-phase effective precision + REFINEMENT
                      per-precision accounting
    tpu_dtype         legacy alias for the same effective precision
    amg_precision     hierarchy/cycle precision when the above are
                      unset

Invariants the policy enforces regardless of knob:

- reductions, convergence checks and the Krylov outer loops stay f32+
  (the monitor computes norms in the caller's dtype, never bf16);
- the DENSE_LU coarse tail stays f32+: a ``bfloat16`` hierarchy keeps
  its coarse-solver payload (QR factors, dense inverse) at f32 and
  the cycle upcasts the coarse rhs around the coarse solve
  (``amg/cycles.py _coarse_solve``);
- REFINEMENT's inner Krylov operator stays f32 (flexible Krylov
  tolerates a reduced-precision preconditioner; a bf16 Krylov basis
  would not converge) — ``bfloat16`` applies to the AMG cycle below
  it, with the f64 outer defect-correction loop restoring full
  accuracy.

Known gap: the host-built-and-shipped hierarchy path (remote
accelerators with ``amg_host_setup``) casts every shipped leaf to the
hierarchy precision, coarse payload included — the f32+ coarse rule
applies to the device-resident setup paths benchmarks and serving use.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .errors import BadConfigurationError

# knob value -> solve-data cast dtype name (None = no cast / native)
PRECISION_DTYPES = {"double": None, "float": "float32",
                    "bfloat16": "bfloat16"}

# legacy tpu_dtype spellings -> precision names
_TPU_DTYPE_ALIASES = {"float64": "double", "float32": "float",
                      "bfloat16": "bfloat16"}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Resolved precision decision for one solver/hierarchy scope."""

    name: str               # effective precision: double|float|bfloat16
    source: str             # knob that decided: solve_precision|
    #                         tpu_dtype|amg_precision|default
    solve_precision: str    # the raw solve_precision knob ("" = unset)

    @property
    def active(self) -> bool:
        """Was the solve_precision knob set at all? Gates everything
        that must be bitwise-off by default (REFINEMENT's in-state
        inner-iteration accounting)."""
        return self.solve_precision != ""

    @property
    def cast_dtype(self) -> Optional[str]:
        """Solve-data cast dtype for hierarchy LEVELS (operand slabs,
        transfer weights, smoother payloads); None = leave native."""
        return PRECISION_DTYPES[self.name]

    @property
    def coarse_dtype(self) -> Optional[str]:
        """Solve-data cast dtype for the COARSE-solver subtree:
        f32+ always — the dense factorization/back-substitution and
        the K-cycle coarse matvec never run below f32."""
        c = self.cast_dtype
        return "float32" if c == "bfloat16" else c


def _explicit(cfg, name: str, scope: str):
    """The explicitly-set value of a knob (scoped lookup, no registered
    default), or None when the config never set it."""
    for s in (scope, "default"):
        if (s, name) in cfg.values:
            return cfg.values[(s, name)]
    return None


def resolve_precision(cfg, scope: str = "default") -> PrecisionPolicy:
    """Resolve the three precision knobs into one PrecisionPolicy.

    Raises BadConfigurationError when two explicitly-set knobs name
    different precisions — a config that says both is guessing, and
    the old behavior (each consumer reading its own knob) silently
    honored whichever one the code path happened to read.
    """
    sp = str(cfg.get("solve_precision", scope))
    td_raw = _explicit(cfg, "tpu_dtype", scope)
    ap_raw = _explicit(cfg, "amg_precision", scope)

    claims = []
    if sp:
        claims.append(("solve_precision", sp))
    if td_raw:
        claims.append(("tpu_dtype", _TPU_DTYPE_ALIASES[str(td_raw)]))
    if ap_raw is not None:
        claims.append(("amg_precision", str(ap_raw)))

    names = {c[1] for c in claims}
    if len(names) > 1:
        detail = ", ".join(f"{k}={v!r}" for k, v in claims)
        raise BadConfigurationError(
            f"contradictory precision knobs: {detail}. One precision "
            f"owns the solve: set solve_precision alone (it implies "
            f"the hierarchy precision), or make the knobs agree — "
            f"see the README precision-modes knob matrix")
    if claims:
        source, name = claims[0]
    else:
        # nothing explicit: the registered amg_precision default
        source, name = "default", str(cfg.get("amg_precision", scope))
    return PrecisionPolicy(name=name, source=source, solve_precision=sp)
