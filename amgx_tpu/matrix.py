"""Core sparse-matrix container.

TPU-native analog of the reference Matrix/MatrixBase (include/matrix.h:65,
src/matrix.cu): a block-CSR container held as a JAX pytree so it can flow
through jit/shard_map. Differences from the reference, by design:

- no explicit memory spaces (XLA owns placement);
- "initialization" precomputes static gather/scatter auxiliaries
  (per-nnz row ids, diagonal indices, padded-ELL layout) instead of
  launching setup kernels — these are what make SpMV / smoothers map onto
  the TPU vector units as dense gathers + segmented reductions;
- the DIAG property (externally stored diagonal, include/matrix.h:24-26)
  is the `diag` field being non-None.

Shapes are static: one compiled program per (num_rows, nnz, block) bucket,
matching XLA's compilation model.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .errors import BadParametersError


class _DeviceSetupState(threading.local):
    """Per-thread flag for the device-resident setup pipeline
    (setup_backend=device): while set, every host-numpy fast path that
    gates on host residency reports 'not host' so the jnp/device
    implementations run instead — the same code a real accelerator
    build takes, selectable (and testable) on any backend."""

    forced = False


_device_setup = _DeviceSetupState()


@contextlib.contextmanager
def forced_device_setup(on: bool = True):
    """Force (or explicitly lift, on=False) the device-resident setup
    implementations for the enclosed block on this thread."""
    prev = _device_setup.forced
    _device_setup.forced = bool(on)
    try:
        yield
    finally:
        _device_setup.forced = prev


def device_setup_forced() -> bool:
    return _device_setup.forced

# id(device array) -> the host numpy original it was created from. Real
# AmgX matrices always originate on the host (uploads, readers, gallery);
# the host-CPU setup path (amg_host_setup) reads them back, and on a
# tunneled accelerator that pull costs ~10 s at 128^3 — retaining the
# upload-side original makes it free. jax ArrayImpl is weakref-able but
# NOT hashable, so the mirror is keyed by id() with weakref.finalize
# eviction (the entry dies with the device array, and the finalizer
# guards against id reuse).
_HOST_MIRROR: dict = {}


def _register_host_mirror(dev_arr, np_arr):
    try:
        key = id(dev_arr)
        weakref.finalize(dev_arr, _HOST_MIRROR.pop, key, None)
    except TypeError:  # pragma: no cover - non-weakrefable array type
        return
    _HOST_MIRROR[key] = np_arr


def host_mirror_asarray(x):
    """np.asarray(x), served from the retained host original when x was
    uploaded from host data (no accelerator->host transfer)."""
    if isinstance(x, np.ndarray):
        return x
    m = _HOST_MIRROR.get(id(x))
    return m if m is not None else np.asarray(x)


def host_arrays(*arrays):
    """numpy views of the given arrays with NO accelerator->host
    transfer: numpy / CPU-resident arrays pass through, accelerator
    arrays resolve via the retained host mirror. Returns None when any
    array cannot be served host-side (callers fall back to the device
    path). This is what lets setup-phase index math run in synchronous
    numpy even when the user's matrix lives on the TPU."""
    if _device_setup.forced:
        return None
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        if isinstance(a, np.ndarray):
            out.append(a)
            continue
        m = _HOST_MIRROR.get(id(a))
        if m is not None:
            out.append(m)
            continue
        try:
            if next(iter(a.devices())).platform == "cpu":
                out.append(np.asarray(a))
                continue
        except Exception:
            pass
        return None
    return out


def lexsort_rc(rows, cols):
    """Stable (rows, cols)-lexicographic order via two int32 argsorts.

    TPU-first replacement for the single int64 `row * ncols + col` key:
    the TPU has no native 64-bit integers, so an int64 sort compiles to
    (and executes as) a slow emulated form — two stable 32-bit sorts
    are strictly cheaper at every problem size."""
    order1 = jnp.argsort(cols, stable=True)
    order2 = jnp.argsort(rows[order1], stable=True)
    return order1[order2]

Array = jax.Array


def _seg_sum(data, seg_ids, num_segments):
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments,
                               indices_are_sorted=True)


def host_resident(*arrays) -> bool:
    """True when every given array is concrete host-CPU data (numpy or a
    CPU-backend jax array). Tracers and accelerator arrays return False.
    Gates the numpy fast paths of the setup-phase index math: on the
    host-CPU setup path (amg_host_setup) the same math as the jnp form,
    run synchronously in numpy, avoids hundreds of eager XLA:CPU
    dispatches per hierarchy build. Under a forced device-resident
    setup (setup_backend=device) every array reports non-host so the
    jnp implementations run."""
    if _device_setup.forced:
        return False
    for a in arrays:
        if a is None or isinstance(a, np.ndarray):
            continue
        try:
            if next(iter(a.devices())).platform != "cpu":
                return False
        except Exception:
            return False
    return True


def _np_row_reduce(op, data, ro, n, empty_val):
    """Per-row reduce over CSR-ordered data via ufunc.reduceat, with
    empty rows patched to `empty_val` (reduceat's equal-index semantics
    would otherwise leak the next row's first element)."""
    if data.shape[0] == 0:
        return np.full(n, empty_val, data.dtype)
    starts = ro[:-1].astype(np.int64)
    nonempty = ro[1:] > ro[:-1]
    out = op.reduceat(data, np.clip(starts, 0, data.shape[0] - 1))
    return np.where(nonempty, out, empty_val)


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["row_offsets", "col_indices", "values", "diag",
                 "row_ids", "diag_idx", "ell_cols", "ell_vals", "dia_vals",
                 "swell_cols", "swell_vals", "swell_c0row", "swell_nchunk",
                 "user_colors"],
    meta_fields=["num_rows", "num_cols", "block_dimx", "block_dimy",
                 "initialized", "dia_offsets", "swell_w128", "grid_shape",
                 "user_num_colors"],
)
@dataclasses.dataclass(frozen=True)
class CsrMatrix:
    """Block-CSR matrix. `values` is (nnz,) for scalar matrices or
    (nnz, block_dimx, block_dimy) for block matrices. When `diag` is not
    None the diagonal blocks are stored externally (DIAG property) and
    `values` holds only off-diagonal entries."""

    row_offsets: Array                 # (n+1,) int32
    col_indices: Array                 # (nnz,) int32
    values: Array                      # (nnz,) | (nnz, bx, by)
    diag: Optional[Array] = None       # (n,) | (n, bx, by) external diagonal
    # auxiliaries built by .init() (None until then)
    row_ids: Optional[Array] = None    # (nnz,) row of each entry
    diag_idx: Optional[Array] = None   # (n,) values-index of diagonal entry
    ell_cols: Optional[Array] = None   # (n, k) padded column ids
    ell_vals: Optional[Array] = None   # (n, k) | (n, k, bx, by)
    dia_offsets: Optional[tuple] = None  # static tuple of diagonal offsets
    dia_vals: Optional[Array] = None   # (k, rows_pad, 128) tiled diagonals
    # windowed-ELL (SWELL) layout for unstructured matrices (the Pallas
    # gather kernel's storage, ops/pallas_swell.py): slot-major
    # (nb, kpad, 128) blocks + per-block x-window starts/chunk counts
    swell_cols: Optional[Array] = None   # (nb, kpad, 128) local columns
    swell_vals: Optional[Array] = None   # (nb, kpad, 128)
    swell_c0row: Optional[Array] = None  # (nb,) window start, 128-rows
    swell_nchunk: Optional[Array] = None  # (nb,) populated chunk count
    swell_w128: int = 0                  # static window width, 128-chunks
    num_rows: int = 0
    num_cols: int = 0
    block_dimx: int = 1
    block_dimy: int = 1
    initialized: bool = False
    # structured-grid annotation (nx, ny, nz), x fastest — set by the
    # gallery generators and propagated by the GEO aggregation path so
    # every coarse level keeps the banded/DIA roofline layout
    grid_shape: Optional[tuple] = None
    # user-supplied row coloring (AMGX_matrix_attach_coloring): consumed
    # by color_matrix ahead of any computed scheme
    user_colors: Optional[Array] = None
    user_num_colors: int = 0

    # ------------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return self.values.shape[0]

    @property
    def shape(self):
        return (self.num_rows, self.num_cols)

    @property
    def block_size(self) -> int:
        return self.block_dimx * self.block_dimy

    @property
    def is_block(self) -> bool:
        return self.block_size > 1

    @property
    def has_external_diag(self) -> bool:
        return self.diag is not None

    @property
    def dtype(self):
        return self.values.dtype

    # ------------------------------------------------------------------
    def init(self, ell: str = "auto", ell_max_ratio: float = 3.0) -> "CsrMatrix":
        """`set_initialized` analog: precompute SpMV auxiliaries.

        - `row_ids`: per-nnz row index (drives segmented reductions);
        - `diag_idx`: index of each row's diagonal entry in `values`
          (or -1) — used by Jacobi/GS/DILU smoothers;
        - with `ell='auto'` (default): a banded DIA layout when the
          sparsity has few distinct diagonals (stencils; SpMV becomes
          shifted dense multiply-adds — the TPU roofline path), else a
          padded ELL layout when the row-length distribution is tight
          (dense gather+reduce); `ell='always'` forces ELL, `ell='never'`
          keeps plain CSR+segsum.
        """
        n = self.num_rows
        if not self.is_block and host_resident(
                self.row_offsets, self.col_indices, self.values):
            return self._init_host(ell, ell_max_ratio)
        if not self.is_block:
            out = self._init_from_mirrors(ell, ell_max_ratio)
            if out is not None:
                return out
        row_nnz = jnp.diff(self.row_offsets)
        row_ids = jnp.repeat(
            jnp.arange(n, dtype=jnp.int32), row_nnz,
            total_repeat_length=self.nnz)
        if self.has_external_diag:
            diag_idx = None
        else:
            # first-occurrence diagonal (rows without one keep -1) —
            # first matters for padded-duplicate CSR, where coalesced
            # duplicates trail the summed entry with zero values
            is_diag = (self.col_indices == row_ids)
            cand = jnp.where(is_diag, jnp.arange(self.nnz, dtype=jnp.int32),
                             self.nnz)
            dmin = jax.ops.segment_min(cand, row_ids, num_segments=n,
                                       indices_are_sorted=True)
            diag_idx = jnp.where(dmin >= self.nnz, -1, dmin).astype(
                jnp.int32)
        ell_cols, ell_vals, dia_offsets, dia_vals = self._choose_layout(
            row_ids, row_nnz, ell, ell_max_ratio)
        return dataclasses.replace(
            self, row_ids=row_ids, diag_idx=diag_idx,
            ell_cols=ell_cols, ell_vals=ell_vals,
            dia_offsets=dia_offsets, dia_vals=dia_vals, initialized=True)

    def _init_from_mirrors(self, ell: str,
                           ell_max_ratio: float) -> "Optional[CsrMatrix]":
        """init() for an accelerator matrix whose base arrays retain
        host mirrors (every host-originated upload does): build the
        SpMV auxiliaries host-side in numpy and ship the finished
        layout in a few large contiguous puts. The alternative — eager
        per-op init on a tunneled accelerator — costs one remote
        compile per op (~100 s at 128^3) and litters HBM with eager
        temporaries that degrade every later transfer (measured:
        device_put drops ~30x after an eager device init)."""
        import jax as _jax
        if _device_setup.forced:
            return None          # setup_backend=device: build on device
        m_ro = _HOST_MIRROR.get(id(self.row_offsets))
        m_ci = _HOST_MIRROR.get(id(self.col_indices))
        m_va = _HOST_MIRROR.get(id(self.values))
        m_dg = (None if self.diag is None
                else _HOST_MIRROR.get(id(self.diag)))
        if m_ro is None or m_ci is None or m_va is None or \
                (self.diag is not None and m_dg is None):
            return None
        try:
            dev = next(iter(self.values.devices()))
        except Exception:
            return None
        host = dataclasses.replace(
            self, row_offsets=m_ro, col_indices=m_ci, values=m_va,
            diag=m_dg)._init_host(ell, ell_max_ratio)

        def up(x):
            if x is None or not hasattr(x, "dtype"):
                return x
            x = np.ascontiguousarray(x)
            d = _jax.device_put(x, dev)
            _register_host_mirror(d, x)
            return d

        return dataclasses.replace(
            self, row_ids=up(host.row_ids), diag_idx=up(host.diag_idx),
            ell_cols=up(host.ell_cols), ell_vals=up(host.ell_vals),
            dia_offsets=host.dia_offsets, dia_vals=up(host.dia_vals),
            swell_cols=up(host.swell_cols), swell_vals=up(host.swell_vals),
            swell_c0row=up(host.swell_c0row),
            swell_nchunk=up(host.swell_nchunk),
            swell_w128=host.swell_w128, initialized=True)

    def _init_host(self, ell: str, ell_max_ratio: float) -> "CsrMatrix":
        """Numpy form of init() for host-resident scalar matrices — same
        auxiliaries, synchronous vectorized C instead of eager XLA:CPU
        dispatches (the host-setup path builds every hierarchy level
        through here)."""
        n = self.num_rows
        ro = np.asarray(self.row_offsets)
        ci = np.asarray(self.col_indices)
        vals = np.asarray(self.values)
        row_nnz = np.diff(ro)
        row_ids = np.repeat(np.arange(n, dtype=np.int32), row_nnz)
        if self.has_external_diag:
            diag_idx = None
        else:
            cand = np.where(ci == row_ids,
                            np.arange(self.nnz, dtype=np.int64), self.nnz)
            dmin = _np_row_reduce(np.minimum, cand, ro, n, self.nnz)
            diag_idx = np.where(dmin >= self.nnz, -1, dmin).astype(np.int32)
        layout = self._choose_layout_host(
            ro, ci, vals, row_ids, row_nnz, ell, ell_max_ratio)
        return dataclasses.replace(
            self, row_ids=row_ids, diag_idx=diag_idx, initialized=True,
            **layout)

    def _choose_layout_host(self, ro, ci, vals, row_ids, row_nnz, ell: str,
                            ell_max_ratio: float) -> dict:
        """Host layout choice: DIA if banded, else the windowed-ELL
        (SWELL) Pallas layout if the block windows fit, else padded ELL
        if the row lengths are tight. Returns the layout fields as a
        dict for dataclasses.replace."""
        n = self.num_rows
        out = dict(ell_cols=None, ell_vals=None, dia_offsets=None,
                   dia_vals=None, swell_cols=None, swell_vals=None,
                   swell_c0row=None, swell_nchunk=None, swell_w128=0)
        if n > 0 and self.nnz > 0 and not self.has_external_diag \
                and ell == "auto":
            diffs = ci.astype(np.int64) - row_ids
            # cheap rejection before the full O(nnz log nnz) unique:
            # distinct offsets in any subset lower-bound the full count,
            # so a >32-offset sample proves the matrix is not banded
            # (coarse AMG operators hit this every level)
            if diffs.shape[0] > (1 << 17) and \
                    np.unique(diffs[: 1 << 17]).shape[0] > \
                    self.DIA_MAX_OFFSETS:
                offs = None
            else:
                offs = np.unique(diffs)
            k = 0 if offs is None else int(offs.shape[0])
            if offs is not None and k <= self.DIA_MAX_OFFSETS and \
                    k * n <= self.DIA_FILL_RATIO * max(self.nnz, 1):
                from .ops.pallas_spmv import LANES, dia_padded_rows
                out["dia_offsets"] = tuple(int(o) for o in offs)
                d_idx = np.searchsorted(offs, diffs)
                rows_pad = dia_padded_rows(k, n)
                slots = d_idx * (rows_pad * LANES) + row_ids
                size = k * rows_pad * LANES
                if np.iscomplexobj(vals):
                    flat = (np.bincount(slots, weights=vals.real,
                                        minlength=size)
                            + 1j * np.bincount(slots, weights=vals.imag,
                                               minlength=size))
                else:
                    flat = np.bincount(slots, weights=vals,
                                       minlength=size)
                out["dia_vals"] = flat.astype(vals.dtype).reshape(
                    k, rows_pad, LANES)
                return out
        if n > 0 and self.nnz > 0 and ell == "auto":
            from .ops.pallas_swell import build_swell_host
            sw = build_swell_host(ro, ci, vals, n, self.num_cols)
            if sw is not None:
                (out["swell_cols"], out["swell_vals"], out["swell_c0row"],
                 out["swell_nchunk"], out["swell_w128"]) = sw
                return out
        if n > 0 and ell != "never" and self.nnz > 0:
            max_k = int(row_nnz.max()) if row_nnz.size else 0
            mean = max(float(self.nnz) / max(n, 1), 1e-30)
            want_ell = (ell == "always") or (
                ell == "auto" and max_k > 0 and max_k / mean <= ell_max_ratio)
            if want_ell and max_k > 0:
                flat = row_ids.astype(np.int64) * max_k + (
                    np.arange(self.nnz, dtype=np.int64) -
                    ro[row_ids].astype(np.int64))
                ec = np.zeros(n * max_k, np.int32)
                ec[flat] = ci
                ev = np.zeros(n * max_k, vals.dtype)
                ev[flat] = vals
                out["ell_cols"], out["ell_vals"] = \
                    ec.reshape(n, max_k), ev.reshape(n, max_k)
        return out

    def _choose_layout(self, row_ids, row_nnz, ell: str,
                       ell_max_ratio: float):
        """DIA-if-banded else ELL-if-tight layout choice (shared by init
        and build_spmv_layout)."""
        n = self.num_rows
        ell_cols = ell_vals = None
        dia_offsets = dia_vals = None
        if n > 0 and self.nnz > 0 and not self.is_block \
                and not self.has_external_diag and ell == "auto":
            dia_offsets, dia_vals = self._try_build_dia(row_ids)
        if dia_offsets is None and n > 0 and ell != "never" and self.nnz > 0:
            max_k = int(jnp.max(row_nnz))
            mean = max(float(self.nnz) / max(n, 1), 1e-30)
            want_ell = (ell == "always") or (
                ell == "auto" and max_k > 0 and max_k / mean <= ell_max_ratio)
            if want_ell and max_k > 0:
                ell_cols, ell_vals = self._build_ell(row_ids, row_nnz, max_k)
        return ell_cols, ell_vals, dia_offsets, dia_vals

    def build_spmv_layout(self, ell: str = "auto",
                          ell_max_ratio: float = 3.0) -> "CsrMatrix":
        """Add a DIA/ELL fast-path layout to an already-initialized
        matrix (the AMG setup produces initialized exact-size CSR coarse
        operators; without this they would SpMV through the scatter-based
        segment-sum path, which is the slow shape on TPU)."""
        if not self.initialized:
            return self.init(ell=ell, ell_max_ratio=ell_max_ratio)
        if self.dia_vals is not None or self.ell_cols is not None \
                or self.swell_cols is not None:
            return self
        if not self.is_block and host_resident(
                self.row_offsets, self.col_indices, self.values,
                self.row_ids):
            ro = np.asarray(self.row_offsets)
            vals = np.asarray(self.values)
            layout = self._choose_layout_host(
                ro, np.asarray(self.col_indices), vals,
                np.asarray(self.row_ids), np.diff(ro), ell,
                ell_max_ratio)
            return dataclasses.replace(self, **layout)
        row_nnz = jnp.diff(self.row_offsets)
        ell_cols, ell_vals, dia_offsets, dia_vals = self._choose_layout(
            self.row_ids, row_nnz, ell, ell_max_ratio)
        return dataclasses.replace(
            self, ell_cols=ell_cols, ell_vals=ell_vals,
            dia_offsets=dia_offsets, dia_vals=dia_vals)

    # ------------------------------------------------------------------
    DIA_MAX_OFFSETS = 32
    DIA_FILL_RATIO = 3.0

    def _try_build_dia(self, row_ids):
        """Diagonal (DIA) storage when the sparsity is banded with few
        distinct offsets (stencil matrices). On TPU this is the fast SpMV
        layout: shifted dense multiply-adds, no gather at all."""
        offs = jnp.unique(self.col_indices.astype(jnp.int32)
                          - row_ids.astype(jnp.int32))
        k = int(offs.shape[0])
        n = self.num_rows
        if k > self.DIA_MAX_OFFSETS or k * n > self.DIA_FILL_RATIO * \
                max(self.nnz, 1):
            return None, None
        offsets = tuple(int(o) for o in offs)
        return offsets, self._build_dia_vals(offsets, row_ids)

    def _build_dia_vals(self, offsets, row_ids):
        """Scatter-add CSR values onto per-diagonal rows (duplicates sum,
        matching the segsum/ELL paths), stored tile-aligned as
        (k, rows_pad, 128) so the Pallas SpMV kernel streams them with
        zero re-layout (see ops/pallas_spmv.py). Shared by init and
        with_values."""
        from .ops.pallas_spmv import LANES, dia_padded_rows
        offs = jnp.asarray(offsets, jnp.int32)
        d_idx = jnp.searchsorted(offs, self.col_indices.astype(jnp.int32)
                                 - row_ids.astype(jnp.int32))
        k = len(offsets)
        rows_pad = dia_padded_rows(k, self.num_rows)
        flat = jnp.zeros((k, rows_pad * LANES), self.dtype).at[
            d_idx, row_ids].add(self.values)
        return flat.reshape(k, rows_pad, LANES)

    def _ell_slots(self, row_ids, max_k: int):
        """Flat scatter targets mapping each CSR entry into (n, max_k)."""
        pos_in_row = jnp.arange(self.nnz, dtype=jnp.int32) - \
            self.row_offsets[row_ids]
        return row_ids * max_k + pos_in_row

    def _scatter_ell_vals(self, flat, max_k: int):
        n = self.num_rows
        if self.is_block:
            bx, by = self.block_dimx, self.block_dimy
            ev = jnp.zeros((n * max_k, bx, by), self.dtype).at[flat].set(
                self.values)
            return ev.reshape(n, max_k, bx, by)
        ev = jnp.zeros((n * max_k,), self.dtype).at[flat].set(self.values)
        return ev.reshape(n, max_k)

    def _build_ell(self, row_ids, row_nnz, max_k: int):
        """Scatter CSR entries into an (n, max_k) padded layout. Padding
        slots point at column 0 with zero values so gathers stay in-bounds."""
        n = self.num_rows
        flat = self._ell_slots(row_ids, max_k)
        ell_cols = jnp.zeros((n * max_k,), jnp.int32).at[flat].set(
            self.col_indices)
        return ell_cols.reshape(n, max_k), self._scatter_ell_vals(flat, max_k)

    # ------------------------------------------------------------------
    def diagonal(self) -> Array:
        """Return the diagonal, (n,) scalar or (n, bx, by) block
        (computeDiagonal analog, src/matrix.cu)."""
        if self.has_external_diag:
            return self.diag
        if self.dia_offsets is not None and 0 in self.dia_offsets:
            # O(1) from the DIA layout: row-major slice of the main
            # diagonal (avoids the values gather entirely)
            idx0 = self.dia_offsets.index(0)
            return self.dia_vals[idx0].reshape(-1)[: self.num_rows]
        A = self if self.initialized else self.init(ell="never")
        safe = jnp.maximum(A.diag_idx, 0)
        d = A.values[safe]
        missing = (A.diag_idx < 0)
        if self.is_block:
            d = jnp.where(missing[:, None, None], 0.0, d)
        else:
            d = jnp.where(missing, 0.0, d)
        return d

    def to_dense(self) -> Array:
        """Dense (n*bx, m*by) expansion — test/debug utility."""
        n, m = self.num_rows, self.num_cols
        bx, by = self.block_dimx, self.block_dimy
        row_ids = self.row_ids
        if row_ids is None:
            row_nnz = jnp.diff(self.row_offsets)
            row_ids = jnp.repeat(jnp.arange(n, dtype=jnp.int32), row_nnz,
                                 total_repeat_length=self.nnz)
        if self.is_block:
            dense = jnp.zeros((n, m, bx, by), self.dtype)
            dense = dense.at[row_ids, self.col_indices].add(self.values)
            if self.has_external_diag:
                dense = dense.at[jnp.arange(n), jnp.arange(n)].add(self.diag)
            return dense.transpose(0, 2, 1, 3).reshape(n * bx, m * by)
        dense = jnp.zeros((n, m), self.dtype)
        dense = dense.at[row_ids, self.col_indices].add(self.values)
        if self.has_external_diag:
            dense = dense + jnp.diag(self.diag)
        return dense

    def with_values(self, values: Array, diag: Optional[Array] = None
                    ) -> "CsrMatrix":
        """Replace coefficients keeping structure
        (AMGX_matrix_replace_coefficients analog)."""
        if values.shape != self.values.shape:
            raise BadParametersError(
                f"replace_coefficients: value shape {values.shape} != "
                f"{self.values.shape}")
        new_diag = diag if diag is not None else self.diag
        out = dataclasses.replace(self, values=values, diag=new_diag)
        if self.initialized and self.ell_cols is not None:
            # structure auxiliaries (row_ids, diag_idx, ell_cols) survive;
            # only the padded ELL values depend on the coefficients
            max_k = self.ell_cols.shape[1]
            flat = out._ell_slots(self.row_ids, max_k)
            out = dataclasses.replace(
                out, ell_vals=out._scatter_ell_vals(flat, max_k))
        if self.initialized and self.dia_offsets is not None:
            out = out._refill_dia(values)
        if self.initialized and self.swell_cols is not None:
            if host_resident(self.row_offsets, values):
                from .ops.pallas_swell import swell_vals_host
                out = dataclasses.replace(
                    out, swell_vals=swell_vals_host(
                        np.asarray(self.row_offsets), np.asarray(values),
                        self.num_rows, self.swell_cols.shape[2]))
            else:
                # structure kept but values not re-scatterable off-host;
                # drop the fast-path layout rather than serve stale data
                out = dataclasses.replace(
                    out, swell_cols=None, swell_vals=None,
                    swell_c0row=None, swell_nchunk=None, swell_w128=0)
        return out

    def _refill_dia(self, values) -> "CsrMatrix":
        """Values-only DIA refill for replace_coefficients. With host
        (numpy) values and mirror-backed structure the scatter runs in
        numpy and ships as one put — the eager device scatter-add +
        searchsorted chain costs seconds per resetup over a tunnel
        (the same economics as _init_from_mirrors)."""
        def host_of(a):
            if isinstance(a, np.ndarray):
                return a
            return _HOST_MIRROR.get(id(a))

        ro = host_of(self.row_offsets)
        ci = host_of(self.col_indices)
        if isinstance(values, np.ndarray) and ro is not None \
                and ci is not None and not np.iscomplexobj(values):
            from .ops.pallas_spmv import LANES, dia_padded_rows
            k = len(self.dia_offsets)
            n = self.num_rows
            row_ids = np.repeat(np.arange(n, dtype=np.int64),
                                np.diff(ro))
            offs = np.asarray(self.dia_offsets, np.int64)
            d_idx = np.searchsorted(offs, ci.astype(np.int64) - row_ids)
            rows_pad = dia_padded_rows(k, n)
            flat = np.bincount(d_idx * (rows_pad * LANES) + row_ids,
                               weights=values,
                               minlength=k * rows_pad * LANES)
            dia_np = flat.astype(values.dtype).reshape(k, rows_pad,
                                                       LANES)
            # device of the (unchanged) structure arrays — the new
            # values may be host numpy at this point
            try:
                dev = next(iter(self.row_offsets.devices()))
                on_accel = dev.platform != "cpu"
            except Exception:
                on_accel = False
            if on_accel:
                import jax as _jax
                vals_c = np.ascontiguousarray(values)
                d_vals = _jax.device_put(vals_c, dev)
                _register_host_mirror(d_vals, vals_c)
                d_dia = _jax.device_put(dia_np, dev)
                _register_host_mirror(d_dia, dia_np)
                return dataclasses.replace(self, values=d_vals,
                                           dia_vals=d_dia)
            return dataclasses.replace(self, dia_vals=jnp.asarray(dia_np))
        return dataclasses.replace(
            self, dia_vals=self._build_dia_vals(self.dia_offsets,
                                                self.row_ids))

    def interior_exterior_split(self, num_owned_cols: int):
        """INTERIOR/BOUNDARY view split (include/matrix.h:82-88 views):
        returns (A_interior, A_boundary) where A_interior keeps the
        entries whose column is owned (< num_owned_cols) and A_boundary
        the rest — y = A x == A_int x + A_bnd x. Both views share this
        matrix's shape; the split is by entry, matching the
        latency-hiding decomposition the distributed SpMV uses
        (multiply.cu:95-110, distributed/dist_matrix.py)."""
        if self.is_block:
            raise BadParametersError(
                "interior_exterior_split: scalar matrices only")
        src = self if self.initialized else self.init(ell="never")
        rows, cols, vals = src.coo()
        interior = cols < num_owned_cols
        vi = jnp.where(interior, vals, 0.0)
        vb = jnp.where(interior, 0.0, vals)
        base = dict(row_offsets=src.row_offsets,
                    col_indices=src.col_indices,
                    row_ids=rows, num_rows=src.num_rows,
                    num_cols=src.num_cols, initialized=True)
        A_int = CsrMatrix(values=vi, diag=src.diag, diag_idx=src.diag_idx,
                          ell_cols=None, ell_vals=None, dia_offsets=None,
                          dia_vals=None, **base)
        A_bnd = CsrMatrix(values=vb, diag=None,
                          diag_idx=jnp.full((src.num_rows,), -1,
                                            jnp.int32),
                          ell_cols=None, ell_vals=None, dia_offsets=None,
                          dia_vals=None, **base)
        return A_int, A_bnd

    # ------------------------------------------------------------------
    @staticmethod
    def from_coo(rows, cols, vals, num_rows: int, num_cols: int,
                 block_dims=(1, 1), coalesce: bool = True,
                 diag: Optional[Array] = None) -> "CsrMatrix":
        """Build CSR from (unsorted) COO triplets; duplicates are summed
        when `coalesce` (matches the upload semantics of
        AMGX_matrix_upload_all, src/amgx_c.cu:3039)."""
        rows = jnp.asarray(rows, jnp.int32)
        cols = jnp.asarray(cols, jnp.int32)
        vals = jnp.asarray(vals)
        bx, by = block_dims
        order = lexsort_rc(rows, cols)
        rows, cols, vals = rows[order], cols[order], vals[order]
        if coalesce and rows.shape[0] > 0:
            newseg = jnp.concatenate(
                [jnp.ones((1,), bool),
                 (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])])
            seg = jnp.cumsum(newseg) - 1
            nuniq = int(seg[-1]) + 1
            first = jnp.nonzero(newseg, size=nuniq)[0]
            vals = _seg_sum(vals, seg, nuniq)
            rows, cols = rows[first], cols[first]
        counts = jnp.bincount(rows, length=num_rows)
        row_offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(counts).astype(jnp.int32)])
        return CsrMatrix(row_offsets=row_offsets, col_indices=cols,
                         values=vals, diag=diag, num_rows=num_rows,
                         num_cols=num_cols, block_dimx=bx, block_dimy=by)

    @staticmethod
    def from_dense(dense, tol: float = 0.0) -> "CsrMatrix":
        dense = np.asarray(dense)
        rows, cols = np.nonzero(np.abs(dense) > tol)
        return CsrMatrix.from_coo(rows, cols, jnp.asarray(dense[rows, cols]),
                                  dense.shape[0], dense.shape[1])

    @staticmethod
    def from_scipy_like(row_offsets, col_indices, values, num_rows, num_cols,
                        block_dims=(1, 1), diag=None) -> "CsrMatrix":
        def put(x, dtype=None):
            if x is None:
                return None
            dev = jnp.asarray(x) if dtype is None else jnp.asarray(x, dtype)
            if isinstance(x, np.ndarray) and not isinstance(dev, np.ndarray):
                try:
                    on_accel = next(iter(dev.devices())).platform != "cpu"
                except Exception:
                    on_accel = False
                if on_accel:
                    # mirror a COPY: x may view caller-owned memory
                    # (e.g. an upload buffer) that the caller reuses
                    # after upload — the mirror must stay equal to the
                    # immutable device array. CPU-resident arrays skip
                    # the mirror (its only consumer is the host-setup
                    # pull, which is free on CPU).
                    _register_host_mirror(dev, np.array(x, dev.dtype))
            return dev

        return CsrMatrix(
            row_offsets=put(row_offsets, jnp.int32),
            col_indices=put(col_indices, jnp.int32),
            values=put(values), diag=put(diag),
            num_rows=int(num_rows), num_cols=int(num_cols),
            block_dimx=block_dims[0], block_dimy=block_dims[1])

    def slim_for_spmv(self) -> "CsrMatrix":
        """Drop every array the SpMV dispatch path does not read, given
        the built layout (DIA keeps only dia_vals; ELL keeps the padded
        arrays). Solve-phase data pytrees use this so multi-GB unused
        CSR payloads don't occupy HBM as program arguments (at 256^3 the
        fine matrix's unused values/col_indices/row_ids cost ~2 GB).
        The result supports spmv()/residual() ONLY — setup-phase
        consumers (diagonal, coo, Galerkin) need the full matrix."""
        if not self.initialized:
            return self
        dummy_i = jnp.zeros((1,), jnp.int32)
        if self.dia_vals is not None:
            return dataclasses.replace(
                self, values=jnp.zeros((1,), self.dtype),
                col_indices=dummy_i, row_ids=None, diag_idx=None,
                row_offsets=dummy_i, ell_cols=None, ell_vals=None,
                swell_cols=None, swell_vals=None, swell_c0row=None,
                swell_nchunk=None, swell_w128=0)
        if self.swell_cols is not None:
            return dataclasses.replace(
                self, values=jnp.zeros((1,), self.dtype),
                col_indices=dummy_i, row_ids=None, diag_idx=None,
                row_offsets=dummy_i, ell_cols=None, ell_vals=None)
        if self.ell_cols is not None:
            return dataclasses.replace(
                self, values=jnp.zeros((1,), self.dtype),
                col_indices=dummy_i, row_ids=None, diag_idx=None,
                row_offsets=dummy_i)
        return self

    def astype(self, dtype) -> "CsrMatrix":
        """Cast all floating-point payloads (values/diag + any built
        ELL/DIA layouts) to `dtype`, keeping structure arrays intact.
        Used by the mixed-precision execution paths (amg_precision,
        REFINEMENT) to derive the reduced-precision operator."""
        def cast(a):
            if a is not None and jnp.issubdtype(a.dtype, jnp.inexact):
                return a.astype(dtype)
            return a
        return dataclasses.replace(
            self, values=cast(self.values), diag=cast(self.diag),
            ell_vals=cast(self.ell_vals), dia_vals=cast(self.dia_vals),
            swell_vals=cast(self.swell_vals))

    def coo(self):
        """Return (row_ids, col_indices, values) COO triplets. Computes
        row_ids standalone when uninitialized (no need for the full init)."""
        if self.row_ids is not None:
            return self.row_ids, self.col_indices, self.values
        row_nnz = jnp.diff(self.row_offsets)
        row_ids = jnp.repeat(jnp.arange(self.num_rows, dtype=jnp.int32),
                             row_nnz, total_repeat_length=self.nnz)
        return row_ids, self.col_indices, self.values
