"""Scoped, typed configuration system.

TPU-native analog of AMG_Config (include/amg_config.h:126, implementation
src/amg_config.cu; parameters registered in src/core.cu:307-544). The
product-defining behaviors reproduced here:

- a global registry of typed parameters with defaults / allowed values /
  ranges (`register_parameter`);
- flat config strings  ``scope:name(new_scope)=value`` separated by
  ``,`` / ``;`` / newlines;
- JSON "config_version 2" files where nested solver objects create
  *scopes* — a parameter may hold different values per nesting site, and
  lookups fall back scope -> "default" -> registered default;
- solver-role parameters ("solver", "preconditioner", "smoother",
  "coarse_solver", ...) carry the *scope binding* of their child solver so
  the solver tree can be built recursively.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from .errors import BadConfigurationError, BadParametersError

# ---------------------------------------------------------------------------
# parameter registry
# ---------------------------------------------------------------------------


@dataclass
class ParamDesc:
    name: str
    type: type
    doc: str
    default: Any
    allowed: Optional[tuple] = None      # enumerated allowed values
    min_value: Any = None
    max_value: Any = None


_REGISTRY: Dict[str, ParamDesc] = {}


def register_parameter(name, type_, doc, default, allowed=None,
                       min_value=None, max_value=None):
    _REGISTRY[name] = ParamDesc(name, type_, doc, default,
                                tuple(allowed) if allowed else None,
                                min_value, max_value)


def parameter_registry() -> Dict[str, ParamDesc]:
    return _REGISTRY


def describe_parameters() -> str:
    """AMGX_write_parameters_description analog."""
    lines = []
    for name in sorted(_REGISTRY):
        p = _REGISTRY[name]
        lines.append(f"{name} ({p.type.__name__}, default={p.default!r}): {p.doc}")
    return "\n".join(lines)


BOOL01 = (0, 1)

# Solver-role parameters whose value names a child solver and whose JSON
# object form introduces a new scope (matches the recursion in the
# reference JSON import, include/amg_config.h:144-269).
SOLVER_ROLE_PARAMS = (
    "solver", "preconditioner", "smoother", "coarse_solver",
    "fine_smoother", "coarse_smoother", "eig_solver",
)


def _register_default_parameters():
    """Register the reference's parameter surface (src/core.cu:307-544).
    Names, defaults and docs match the reference so its config files and
    config strings work unchanged; device/CUDA-specific knobs are kept as
    accepted-but-inert for compatibility."""
    R = register_parameter
    # determinism / exception handling
    R("determinism_flag", int, "force deterministic coarsening/coloring", 0, BOOL01)
    R("exception_handling", int, "internal exception processing instead of error codes", 0, BOOL01)
    # consolidation
    R("fine_level_consolidation", int, "consolidate the fine level", 0, BOOL01)
    R("use_cuda_ipc_consolidation", int, "inert (CUDA IPC not applicable on TPU)", 0, BOOL01)
    R("amg_consolidation_flag", int, "use amg level consolidation", 0)
    R("matrix_consolidation_lower_threshold", int, "avg rows to trigger merge", 0)
    R("matrix_consolidation_upper_threshold", int, "avg rows after merge", 1000)
    # memory pools (inert on TPU -- XLA owns allocation; kept for parity)
    R("device_mem_pool_size", int, "inert", 256 * 1024 * 1024)
    R("device_consolidation_pool_size", int, "inert", 256 * 1024 * 1024)
    R("device_mem_pool_max_alloc_size", int, "inert", 20 * 1024 * 1024)
    R("device_alloc_scaling_factor", int, "inert", 10)
    R("device_alloc_scaling_threshold", int, "inert", 16 * 1024)
    R("device_mem_pool_size_limit", int, "inert", 0)
    # async framework
    R("num_streams", int, "inert (XLA owns streams)", 0)
    R("serialize_threads", int, "inert", 0, BOOL01)
    R("high_priority_stream", int, "inert", 0, BOOL01)
    # distributed
    R("communicator", str, "collective backend <ICI|MPI|MPI_DIRECT> "
      "(MPI names map to the XLA-collective backend)", "ICI")
    R("separation_interior", str, "latency-hiding separation view", "INTERIOR",
      ("INTERIOR", "OWNED", "FULL", "ALL"))
    R("separation_exterior", str, "calculation-limit view", "OWNED",
      ("INTERIOR", "OWNED", "FULL", "ALL"))
    R("min_rows_latency_hiding", int, "inert by design: the TPU build's "
      "interior/halo split is structural (ShardMatrix) and XLA overlaps "
      "the collective with the owned-part SpMV at every size, so there "
      "is no kernel-split overhead to disable", -1)
    R("exact_coarse_solve", int, "inert by design: the distributed "
      "coarse solve is ALWAYS exact on TPU (all_gather + replicated "
      "factorization, distributed/amg.py) - the stronger behavior the "
      "reference gates behind this flag", 0, BOOL01)
    R("matrix_halo_exchange", int, "0 none / 1 diagonal / 2 full", 0)
    R("boundary_coloring", str, "boundary coloring handling", "SYNC_COLORS",
      ("FIRST", "SYNC_COLORS", "LAST"))
    R("halo_coloring", str, "halo coloring handling", "LAST",
      ("FIRST", "SYNC_COLORS", "LAST"))
    R("use_sum_stopping_criteria", int, "sum rows over ranks for coarsening stop", 0)
    # data format
    R("rhs_from_a", int, "reader: synthesize rhs from A (1: A*e, 0: ones)", 0)
    R("complex_conversion", int, "complex->real K-formulation on read", 0)
    R("matrix_writer", str, "matrix write format", "matrixmarket",
      ("matrixmarket", "binary"))
    R("block_format", str, "block storage order", "ROW_MAJOR", ("ROW_MAJOR", "COL_MAJOR"))
    R("block_convert", int, "reader converts to bxb block matrix (0=off)", 0)
    # solver roles
    R("solver", str, "the solving algorithm", "AMG")
    R("preconditioner", str, "the preconditioner algorithm", "AMG")
    R("coarse_solver", str, "coarsest-level solver", "DENSE_LU_SOLVER")
    R("smoother", str, "the smoothing algorithm", "BLOCK_JACOBI")
    R("fine_smoother", str, "fine-level smoother", "BLOCK_JACOBI")
    R("coarse_smoother", str, "coarse-level smoother", "BLOCK_JACOBI")
    # gmres
    R("gmres_n_restart", int, "Krylov vectors before restart", 20)
    R("gmres_krylov_dim", int, "max Krylov dim (0 = match restart)", 0)
    # idr
    R("subspace_dim_s", int, "IDR(s) shadow-space dimension", 8)
    # dense lu
    R("dense_lu_num_rows", int, "trigger dense LU when rows <=", 128)
    R("dense_lu_max_rows", int, "never trigger when rows >= (0=unused)", 0)
    # relaxation
    R("relaxation_factor", float, "relaxation factor", 0.9, None, 0.0, 2.0)
    R("ilu_sparsity_level", int, "ILU(k) level", 0)
    R("symmetric_GS", int, "symmetric GS sweeps", 0, BOOL01)
    R("jacobi_iters", int, "inner iterations for GSINNER", 5)
    R("GS_L1_variant", int, "L1 Gauss-Seidel variant", 0, BOOL01)
    R("kpz_mu", int, "KPZ polynomial mu", 4)
    R("kpz_order", int, "KPZ polynomial order", 3)
    R("chebyshev_polynomial_order", int, "Chebyshev smoother order", 5)
    R("chebyshev_lambda_estimate_mode", int, "eigenvalue estimation mode", 0, None, 0, 3)
    R("cheby_max_lambda", float, "max-eigenvalue guess", 1.0, None, 0.0, 1.0e20)
    R("cheby_min_lambda", float, "min-eigenvalue guess", 0.125, None, 0.0, 1.0e20)
    R("kaczmarz_coloring_needed", int, "multicolor Kaczmarz", 1)
    R("cf_smoothing_mode", int, "CF-Jacobi flavour", 0)
    # amg level
    R("algorithm", str, "AMG algorithm", "CLASSICAL",
      ("CLASSICAL", "AGGREGATION", "ENERGYMIN"))
    R("amg_host_levels_rows", int, "rows below which levels run on host "
      "(-1 off). Accepted-inert by design on this backend: XLA owns "
      "placement during the solve, and the setup-phase host/device "
      "split is governed by amg_host_setup instead", -1)
    # cycles
    R("cycle", str, "cycle shape", "V", ("V", "W", "F", "CG", "CGF"))
    R("max_levels", int, "max number of levels", 100)
    R("min_fine_rows", int, "min rows in a fine level", 1)
    R("min_coarse_rows", int, "min block rows in a level", 2)
    R("max_coarse_iters", int, "max iterations of coarsest solver", 100)
    R("coarsen_threshold", float, "threshold for creating new coarse level", 1.0)
    R("presweeps", int, "presmooth iterations", 1)
    R("postsweeps", int, "postsmooth iterations", 1)
    R("finest_sweeps", int, "finest-level sweeps (-1 = use pre/post)", -1)
    R("coarsest_sweeps", int, "smoothing iterations at coarsest level", 2)
    R("cycle_iters", int, "CG-cycle inner iterations", 2)
    R("structure_reuse_levels", int, "hierarchy reuse depth on resetup", 0)
    R("distributed_setup_mode", str, "distributed AMG hierarchy build: "
      "per-shard (sharded), controller-global (global), or best "
      "available (auto)", "auto", {"auto", "sharded", "global"})
    R("amg_host_setup", str, "build the AMG hierarchy on the host CPU "
      "backend and ship it to the accelerator once (the host-level "
      "machinery analog, src/amg.cu:152-421); auto = host when the "
      "default backend is a remote accelerator and the algorithm's "
      "setup is index-heavy (CLASSICAL/ENERGYMIN)", "auto",
      {"auto", "always", "never"})
    R("setup_backend", str, "where the AMG hierarchy setup pipeline runs: "
      "device = on-accelerator eager jnp pipeline (strength, CF/aggregate "
      "selection, interpolation assembly, Galerkin triple product and "
      "DIA/ELL layout packing all stay device-resident; the host numpy "
      "fast paths are disabled), host = host-CPU numpy/native build with "
      "per-level overlapped shipping to the ambient accelerator, auto = "
      "today's heuristic (amg_host_setup decides the pull for index-heavy "
      "setups on remote accelerators; host fast paths engage wherever the "
      "data is host-resident — including every tiny coarse level)",
      "auto", ("auto", "device", "host"))
    R("setup_device_min_rows", int, "setup_backend=device: levels with "
      "fewer rows than this lift the device forcing so tiny coarse "
      "levels may take the host numpy fast paths when the data is "
      "host-resident (eager dispatch overhead beats the compute there); "
      "0 forces every level onto the device pipeline", 0, None, 0)
    R("selector_device_sweep", str, "RS/HMIS first-pass implementation: "
      "auto = the device-parallel independent-set sweep (PMIS-style "
      "fixpoint with the live RS weight as priority, "
      "amg/classical/selectors.py rs_sweep) exactly when the setup "
      "pipeline is device-forced (setup_backend=device), the host "
      "bucket queue otherwise; 1 = always the sweep (bit-deterministic "
      "across backends — the device-setup parity shape); 0 = always "
      "the host-serial bucket queue (the reference; restores "
      "bit-identical splits between host and device builds)",
      "auto", ("auto", "0", "1"))
    R("amg_precision", str, "precision of the stored hierarchy + cycle "
      "(TPU-native mixed-precision preconditioning, the dDFI-mode analog: "
      "a float32/bfloat16 cycle inside an f64 flexible Krylov solver). "
      "Resolved through the shared precision policy (precision.py) with "
      "solve_precision/tpu_dtype: contradictory combinations are "
      "rejected at configuration time",
      "double", ("double", "float", "bfloat16"))
    R("error_scaling", int, "coarse-correction scaling mode", 0, (0, 2, 3))
    R("reuse_scale", int, "reuse correction scale for next N iters", 0)
    R("scaling_smoother_steps", int, "smoother steps before computing scale", 2)
    R("intensive_smoothing", int, "drastically increase smoothing", 0)
    # aggregation
    R("coarseAgenerator", str, "Galerkin product method; all reference "
      "choices compute the same product, so every name maps to the one "
      "TPU implementation (sort/segment-sum, or the sort-free "
      "structured path for GEO levels)", "LOW_DEG",
      ("LOW_DEG", "THRUST", "HYBRID"))
    R("coarseAgenerator_coarse", str, "Galerkin method for coarser levels "
      "(same mapping as coarseAgenerator)", "LOW_DEG")
    R("interpolator", str, "classical interpolation", "D1")
    R("energymin_interpolator", str, "energymin interpolation", "EM")
    R("energymin_selector", str, "energymin selection", "CR")
    R("selector", str, "coarse-grid selection algorithm", "PMIS")
    R("aggressive_levels", int, "levels of aggressive coarsening (classical)", 0)
    R("aggressive_selector", str, "aggressive selector", "DEFAULT")
    R("aggressive_interpolator", str, "aggressive interpolator", "MULTIPASS")
    R("handshaking_phases", int, "handshaking phases in matching", 1)
    R("aggregation_edge_weight_component", int, "block component for edge weights", 0)
    R("max_matching_iterations", int, "max matching iterations", 15)
    R("max_unassigned_percentage", float, "max unaggregated fraction", 0.05)
    R("weight_formula", int, "pairwise weight formula", 0)
    R("aggregation_passes", int, "MULTI_PAIRWISE passes", 3)
    R("filter_weights", int, "remove weak edges before aggregation", 0)
    R("filter_weights_alpha", float, "weak-edge threshold alpha", 0.5, None, 0.0, 1.0)
    R("full_ghost_level", int, "full Galerkin for ghost level", 0)
    R("notay_weights", int, "Notay quality-measure weights", 0)
    R("ghost_offdiag_limit", int, "limit offdiagonals in ghost rows", 0)
    R("merge_singletons", int, "merge singleton aggregates", 1)
    R("serial_matching", int, "serial matching (study tool)", 0)
    R("modified_handshake", int, "modified handshake algorithm", 0)
    R("aggregate_size", int, "DUMMY selector aggregate size", 2)
    # classical strength / truncation
    R("strength", str, "strength of connection", "AHAT", ("AHAT", "ALL", "AFFINITY"))
    R("strength_threshold", float, "strength threshold", 0.25)
    R("max_row_sum", float, "weaken dependencies when row sum exceeds", 1.1)
    R("interp_truncation_factor", float, "interp truncation factor", 1.1)
    R("interp_max_elements", int, "max interp elements per row (-1 off)", -1)
    R("affinity_iterations", int, "affinity smoothing iterations", 4)
    R("affinity_vectors", int, "affinity test vectors", 4)
    # coloring
    R("coloring_level", int, "coloring distance (0=off)", 1)
    R("reorder_cols_by_color", int, "reorder columns by color", 0)
    R("insert_diag_while_reordering", int, "insert diagonal while reordering", 0)
    R("matrix_coloring_scheme", str, "coloring algorithm", "MIN_MAX")
    R("max_num_hash", int, "hash tables in min_max coloring", 7)
    R("num_colors", int, "colors for round_robin coloring", 10)
    R("max_uncolored_percentage", float, "max improperly-colored fraction", 0.15,
      None, 0.0, 1.0)
    R("initial_color", int, "initial color", 0)
    R("use_bsrxmv", int, "inert (cusparse expert API)", 0)
    R("fine_levels", int, "levels < N use fine_smoother, others "
      "coarse_smoother (-1 = no split, all use 'smoother')", -1)
    R("coloring_try_remove_last_colors", int, "try removing N last colors", 0)
    R("coloring_custom_arg", str, "custom coloring argument", "")
    R("print_coloring_info", int, "print coloring info", 0)
    R("weakness_bound", int, "min-max-2ring flexibility bound", 2**31 - 1)
    R("late_rejection", int, "late rejection in min-max-2ring", 0)
    R("geometric_dim", int, "uniform coloring dimension", 2)
    # spgemm knobs (accepted; the TPU SpGEMM is sort-based)
    R("spgemm_plan", str, "plan-split Galerkin RAP (ops/spgemm.py "
      "RapPlan): the structure phase (expansion gathers, lexsorted "
      "coalesce order, output CSR pattern) runs once per sparsity "
      "pattern and is memoized on the level + a digest-keyed cache, "
      "so warm setups and value resetups do ZERO symbolic work — the "
      "value phase is one fused Pallas kernel per level on TPU "
      "(ops/pallas_spgemm.py) and a sort-free gather/segment-sum (or "
      "host reduceat) program elsewhere. auto/1 = plan split on; 0 = "
      "the eager sort/expand composition, bit-for-bit (no plan "
      "machinery runs at all)", "auto", ("auto", "0", "1"))
    R("spmm_gmem_size", int, "deprecated", 1024)
    R("spmm_no_sort", int, "deprecated", 1)
    R("spmm_verbose", int, "verbose SpGEMM", 0)
    R("spmm_max_attempts", int, "inert", 6)
    R("use_opt_kernels", int, "use optimised fast-path kernels", 0)
    R("use_cusparse_spgemm", int, "inert", 0)
    R("cusparse_spgemm_alg", str, "inert", "CUSPARSE_SPGEMM_DEFAULT")
    R("cusparse_spgemm_fraction", float, "inert", 0.5)
    # stopping criteria
    R("max_iters", int, "maximum solve iterations", 100)
    R("monitor_residual", int, "compute residual every iteration", 0, BOOL01)
    R("convergence", str, "convergence criterion", "ABSOLUTE")
    R("norm", str, "norm for convergence testing", "L2", ("L1", "L2", "LMAX"))
    R("use_scalar_norm", int, "scalar norm for block matrices", 0)
    R("tolerance", float, "convergence tolerance", 1e-12)
    R("alt_rel_tolerance", float, "alternate relative tolerance (COMBINED)", 1e-12)
    R("rel_div_tolerance", float, "relative divergence tolerance (-1 off)", -1.0)
    # reporting
    R("verbosity_level", int, "output verbosity", 3)
    R("solver_verbose", int, "print solver parameters", 0)
    R("print_config", int, "print configuration", 0)
    R("print_solve_stats", int, "print per-iteration solve stats", 0)
    R("print_grid_stats", int, "print AMG hierarchy stats", 0)
    R("print_vis_data", int, "print visualization data", 0)
    R("print_aggregation_info", int, "print aggregation info", 0)
    R("obtain_timings", int, "print setup/solve timings", 0)
    R("store_res_history", int, "store residual history", 0)
    R("convergence_analysis", int, "levels to analyse", 0)
    # scaling
    R("scaling", str, "matrix scaling algorithm", "NONE",
      ("NONE", "BINORMALIZATION", "NBINORMALIZATION", "DIAGONAL_SYMMETRIC"))
    # eigensolvers (reference registers these in eigensolver registration)
    R("eig_solver", str, "eigensolver algorithm", "POWER_ITERATION")
    R("eig_max_iters", int, "eigensolver max iterations", 100)
    R("eig_tolerance", float, "eigensolver tolerance", 1e-6)
    R("eig_shift", float, "spectral shift sigma", 0.0)
    R("eig_damping_factor", float, "PageRank damping factor", 0.85)
    R("eig_which", str, "which eigenpair", "largest",
      ("smallest", "largest", "pagerank", "shift"))
    R("eig_eigenvector", int, "number of eigenvectors wanted", 0)
    R("eig_eigenvector_solver", str, "eigenvector extraction solver", "default")
    R("eig_wanted_count", int, "number of wanted eigenvalues", 1)
    R("eig_subspace_size", int, "subspace size for block/Krylov methods", -1)
    R("eig_convergence_check_freq", int, "convergence check frequency", 1)
    # TPU-specific additions (new surface; no reference analog)
    R("spmv_impl", str, "SpMV implementation <AUTO|CSR_SEGSUM|ELL|PALLAS>", "AUTO")
    R("tpu_dtype", str, "legacy compute-dtype override, resolved as an "
      "alias of the shared precision policy (precision.py: float64 -> "
      "double, float32 -> float, bfloat16 -> bfloat16); prefer "
      "solve_precision, and contradictory combinations of the three "
      "precision knobs are rejected", "",
      ("", "float32", "float64", "bfloat16"))
    R("solve_precision", str, "solve-phase precision of the inner "
      "multigrid cycle (precision.py policy; owns amg_precision/"
      "tpu_dtype when set): float = f32 operand slabs, bfloat16 = bf16 "
      "operand slabs streamed by the fused Pallas kernels with f32 "
      "in-kernel accumulation — roughly half the HBM bytes per sweep — "
      "while reductions, convergence checks and the DENSE_LU coarse "
      "tail stay f32+, and the REFINEMENT defect-correction shell "
      "(when configured) restores f64-grade answers and records "
      "per-precision iteration counts in SolveReport.precision. "
      "Unset ('') is bitwise-off: jaxpr-identical to a pre-knob build",
      "", ("", "double", "float", "bfloat16"))
    R("fused_smoother", int, "fuse damped-relaxation smoother sweeps "
      "and the trailing cycle residual into single-pass Pallas kernels "
      "on DIA/SWELL levels (ops/smooth.py); 0 restores the unfused "
      "sweep-by-sweep compose bit-for-bit", 1, BOOL01)
    R("matrix_free", str, "matrix-free form for constant-coefficient "
      "GEO levels (ops/stencil.py): a setup-time detector replaces the "
      "level's DIA value slab with a StencilOperator (k coefficients + "
      "static geometry, O(levels) operator memory) and every fused "
      "smoother/transfer/tail kernel reads the coefficients from SMEM "
      "instead of streaming the A value slab from HBM; "
      "variable-coefficient levels always keep the slab path. auto = "
      "on only on a real TPU backend (CPU rigs bit-identical to the "
      "slab build), 1 = force the detector on every backend (the XLA "
      "masked-coefficient compose off-TPU), 0 = never detect — the "
      "slab path bit-for-bit", "auto", ("auto", "0", "1"))
    R("cycle_fusion", int, "fuse the cycle's grid transfers into the "
      "smoother kernels on aggregation/DIA levels (restriction epilogue "
      "in the presmoother, prolongation+correction prologue in the "
      "postsmoother) and run the VMEM-resident coarse tail of the "
      "hierarchy as one kernel (ops/smooth.py); 0 restores the "
      "per-level smooth/restrict/prolongate composition bit-for-bit",
      1, BOOL01)
    R("cycle_fusion_tail_rows", int, "largest level row count admitted "
      "into the fused coarse-tail kernel (the dispatch-latency-bound "
      "tiny-level region; levels above it keep per-level kernels)",
      65536, None, 0)
    R("krylov_fusion", int, "fuse the Krylov shell around the cycle on "
      "DIA operators (ops/pallas_spmv.py): the direction update, SpMV "
      "and p.Ap run as ONE kernel with the dot as a per-block epilogue, "
      "the x/r updates and the monitor's r.r share a second single-pass "
      "kernel, PCG's r.z rides the cycle's last kernel, and distributed "
      "runs pack the iteration's scalars into one psum bundle; 0 "
      "restores the unfused SpMV/BLAS-1 composition bit-for-bit",
      1, BOOL01)
    R("dist_cycle_fusion", int, "bring the fused smoother kernels under "
      "shard_map on distributed DIA levels (distributed/fused.py): "
      "per-shard quota slabs with the neighbor shards' halo rows folded "
      "in, ONE packed edge-window exchange per fused smoother call "
      "(overlapped with the interior kernel, which has no data "
      "dependence on the collective), and exact XLA boundary-strip "
      "completion; 0 builds no halo-folded payloads and restores the "
      "per-sweep halo-exchange composition bit-for-bit; 2 also attaches "
      "them OFF the fused Pallas runtime (the pure-XLA window-sweep "
      "route — still one collective per fused call; the CPU bench-mesh "
      "opt-in, default-1 rigs without the kernels change nothing)",
      1, (0, 1, 2))
    # resilience subsystem (amgx_tpu/resilience/)
    R("health_guards", int, "in-trace NaN/breakdown guards in the solve "
      "loop (status classification rides the existing residual check; "
      "0 restores the bare converged/diverged monitor)", 1, BOOL01)
    R("stall_detection_window", int, "flag STALLED when the residual "
      "norm fails to improve over this many iterations (0 = off)", 0,
      None, 0)
    R("stall_tolerance", float, "minimum relative residual decrease "
      "over the stall window; 0 = any non-decrease stalls", 0.0, None,
      0.0, 1.0)
    # telemetry subsystem (amgx_tpu/telemetry/)
    R("telemetry", int, "attach a structured SolveReport to solve "
      "results and sample device-memory watermarks per phase "
      "(telemetry/report.py). Host-side only: the report rides the "
      "monitor's already-returned stats array, so the traced solve "
      "program and its device->host transfer count are IDENTICAL "
      "either way; 0 skips report construction", 1, BOOL01)
    R("diagnostics", int, "convergence diagnostics "
      "(telemetry/diagnostics.py): append ONE instrumented probe cycle "
      "to the traced solve recording per-level residual norms at the "
      "entry/post-presmooth/post-correction/post-postsmooth cycle "
      "stages, packed into the stats the monitor already returns (zero "
      "added device->host transfers); host-side derivation attaches "
      "per-level reduction factors, smoother effectiveness, an "
      "asymptotic convergence-factor estimate and a bottleneck-level "
      "attribution to SolveReport.diagnostics. Cost when on: ~one "
      "extra cycle's work per solve; 0 (default) compiles a jaxpr "
      "identical to a pre-diagnostics build", 0, BOOL01)
    R("telemetry_sync", int, "fence device work at every span boundary "
      "(telemetry/spans.py) so host spans bound device occupancy in "
      "the exported Perfetto timeline. Debugging mode: it defeats the "
      "overlapped level shipping and XLA async dispatch. Process-wide: "
      "each create_solver/DistributedSolver construction latches the "
      "mode from its config — in both directions, so building a "
      "telemetry_sync=0 solver turns fencing back off", 0, BOOL01)
    # serving subsystem (amgx_tpu/serving/)
    R("serving_chunk_iters", int, "continuous-batching cycle length: "
      "iterations every in-flight system advances per scheduler cycle "
      "before the service checks convergence/deadlines and refills "
      "drained bucket slots (serving/engine.py). Smaller = lower "
      "admission latency, more host syncs per solve", 8, None, 1)
    R("serving_bucket_slots", int, "in-flight systems per serving "
      "bucket: the fixed batch width of the continuous-batching engine "
      "(one trace serves the bucket forever; empty slots ride along "
      "converged and cost nothing)", 4, None, 1)
    R("serving_cache_bytes", int, "byte budget for the hierarchy/LRU "
      "cache of live serving buckets (solve-data footprint estimate); "
      "idle least-recently-used buckets are evicted past it. 0 = "
      "unbounded", 0, None, 0)
    R("serving_cache_entries", int, "max live serving buckets "
      "regardless of bytes (each holds a hierarchy + engine traces)",
      16, None, 1)
    R("serving_aot_dir", str, "directory persisting AOT-exported bucket "
      "executables (jax.export) keyed by (pattern fingerprint, bucket "
      "geometry): a restarted service loads them and skips the "
      "first-request trace latency. '' = AOT off", "")
    R("serving_deadline_action", str, "what an expired in-flight "
      "request completes with: 'partial' = its current iterate "
      "(best-effort degrade), 'reject' = the initial/zero iterate; "
      "either way the status is DEADLINE_EXCEEDED and the bucket keeps "
      "cycling — deadlines never stall neighbors", "partial",
      ("partial", "reject"))
    R("serving_max_queue", int, "admission control: submits beyond "
      "this many queued requests complete immediately with "
      "OVERLOADED instead of growing the queue without bound "
      "(0 = unbounded)", 0, None, 0)
    # serving fault tolerance (serving/{journal,hstore}.py + the
    # recovery/shed/supervision machinery in serving/service.py)
    R("serving_journal_dir", str, "directory for the durable request "
      "journal + solve checkpoints (serving/journal.py): submits are "
      "journaled write-ahead, in-flight states checkpoint every "
      "serving_checkpoint_cycles cycles, and a restarted service "
      "replays pending records — resuming checkpointed solves from "
      "their saved iterate. '' = journaling off", "")
    R("serving_checkpoint_cycles", int, "scheduler cycles between "
      "solve-state checkpoints of journaled in-flight requests (each "
      "checkpoint is one device->host state pull + one file write per "
      "slot). 0 = journal requests but never checkpoint mid-flight",
      4, None, 0)
    R("serving_recover", int, "replay the journal at service "
      "construction (crash recovery); 0 defers to an explicit "
      "recover() call", 1, BOOL01)
    R("serving_hierarchy_dir", str, "directory persisting hierarchy "
      "STRUCTURE snapshots next to the AOT store "
      "(serving/hstore.py): a restarted service rebuilds each "
      "bucket's hierarchy via load + structure-reuse (values only, "
      "amg.setup.restored) instead of a full multi-second coarsening. "
      "'' = off", "")
    R("serving_shed_policy", str, "load shedding beyond the hard "
      "queue bound: 'deadline' rejects requests (OVERLOADED) whose "
      "deadline the live execution-time estimate (median of recent "
      "in-bucket execs, scaled by queue-depth waves + 25% margin) "
      "says is unmeetable; '' = hard bound only",
      "", ("", "deadline"))
    R("serving_tenant_quota", int, "per-tenant fairness quota: a "
      "tenant with this many live (queued + in-flight) requests has "
      "further submits shed OVERLOADED (0 = unbounded)", 0, None, 0)
    R("serving_supervisor_cycles", int, "wedged-bucket detector: a "
      "busy bucket whose progress heartbeat (per-cycle iteration "
      "counters) flatlines for this many consecutive cycles is "
      "quarantined — salvageable slots finalize, the rest requeue. "
      "0 = supervision off", 8, None, 0)
    R("serving_fault_policy", str, "service-level failure chains "
      "'EVENT>action|...' (events: BUILD_FAILED, STEP_FAILED, WEDGED; "
      "actions: retry_backoff, requeue, reject — "
      "resilience/policy.py parse_service_policy). Multiple steps per "
      "event are tried in order across consecutive failures",
      "BUILD_FAILED>reject|STEP_FAILED>requeue|WEDGED>requeue")
    R("serving_retry_backoff_s", float, "base delay of the "
      "retry_backoff action: rebuild attempt n waits base * 2^n",
      0.05)
    R("serving_retry_max_attempts", int, "bound on per-fingerprint "
      "build/step recovery attempts; beyond it the affected tickets "
      "reject with BREAKDOWN", 3, None, 0)
    # request-path observability (telemetry/spans.py flow chains +
    # telemetry/flightrec.py)
    R("serving_tracing", int, "request-path tracing: every ticket "
      "mints a trace id and the serving pipeline emits per-lifecycle "
      "spans (submit / shed / queue / build / admit / chunk-cycle / "
      "checkpoint / finalize) tagged with it, exported as one "
      "connected Perfetto flow chain per request "
      "(spans.export_chrome_trace); the journal persists trace ids so "
      "a crash-recovered resume links its spans to the ORIGINAL "
      "trace. Host-side dict appends only — bench.py obs gates the "
      "on/off overhead at <= 2%; 0 restores the pre-tracing span set",
      1, BOOL01)
    R("serving_replica_id", str, "replica/shard label stamped on "
      "every OpenMetrics sample (replica=\"...\") so multi-replica "
      "scrapes don't collide — the fleet-router prerequisite. '' "
      "defers to the AMGX_REPLICA_ID env var; either is process-wide "
      "(one replica = one process)", "")
    R("serving_bucket_ladder", str, "mixed bucket-width ladder "
      "(serving/ladder.py): '|'-separated strictly-increasing slot "
      "widths (e.g. '1|4|16') the bucket builder draws from by queue "
      "composition — each BUILD uses the smallest rung seating every "
      "queued same-fingerprint request (capped at the top rung) "
      "instead of the fixed serving_bucket_slots width, cutting pad "
      "waste for singleton patterns and queue latency for bursts. "
      "Each rung keeps its own AOT executable (slots is part of the "
      "AOT key). '' = fixed width", "")
    # online config autotuner (serving/autotune.py): shadow-solve
    # search over diagnostics-suggested config deltas, per hot
    # fingerprint. All autotune* knobs are service-layer only — they
    # can never influence coarsening, so (like serving_*) they are
    # excluded from the hstore config signature
    R("autotune", int, "online per-fingerprint config autotuner: "
      "watch hot fingerprints, generate candidate config deltas from "
      "the diagnostics probe, SHADOW-solve them on idle bucket "
      "capacity against the journaled workload sample, and promote a "
      "measured iterations x wall win as that fingerprint's serving "
      "config overlay (persisted in the hstore; demoted on live "
      "regression). 0 (default) is bitwise inert: no tuner object, no "
      "overlay lookup, no shadow work — trace/jaxpr parity with a "
      "pre-autotune build", 0, BOOL01)
    R("autotune_hot_requests", int, "hotness threshold: completed "
      "requests a fingerprint needs before the tuner considers it "
      "(with autotune_hot_exec_share) worth a shadow search", 8,
      None, 1)
    R("autotune_hot_exec_share", float, "hotness threshold: minimum "
      "share of this service's total in-bucket execution seconds a "
      "fingerprint must account for — a rare-but-slow or "
      "frequent-and-slow pattern qualifies, background noise never "
      "does", 0.1, None, 0.0, 1.0)
    R("autotune_shadow_budget", int, "bounded search: max shadow "
      "solves (baseline probe included) the tuner may spend per "
      "fingerprint, ever — the search can never consume unbounded "
      "idle capacity", 6, None, 1)
    R("autotune_min_improvement", float, "promotion hysteresis: a "
      "candidate's measured iterations x wall score must beat the "
      "shadow baseline by at least this factor (and win iterations "
      "AND wall outright) before its deltas promote to the serving "
      "overlay", 1.2, None, 1.0)
    R("autotune_demote_factor", float, "regression hysteresis: a "
      "promoted fingerprint whose live exec median exceeds its "
      "pre-promotion median by this factor (over "
      "autotune_demote_window completions) is demoted — overlay "
      "dropped, persisted record deleted, bucket retired", 1.5,
      None, 1.0)
    R("autotune_demote_window", int, "post-promotion completions the "
      "demote watch needs before judging a regression", 4, None, 2)
    # fleet router (serving/fleet.py): N replicas behind one
    # fingerprint-affine submit/step/drain surface
    R("fleet_replicas", int, "replica count FleetRouter.build (and "
      "AMGX_fleet_create without an explicit count) fronts: N "
      "SolveService instances sharing this config, each with a "
      "derived per-service replica id (r0..rN-1, labels its metric "
      "series; the process-global serving_replica_id scrape label is "
      "left alone) and, when journaling is on, a per-replica journal "
      "subdirectory", 2, None, 1)
    R("fleet_spill_depth", int, "queue depth at which a fingerprint's "
      "home replica counts as overloaded and the router spills the "
      "request to the next rendezvous candidate (only when that "
      "candidate is strictly less loaded — a uniformly saturated "
      "fleet keeps affinity and sheds instead of ping-ponging). "
      "0 = auto: max(2 x serving_bucket_slots, 2)", 0, None, 0)
    R("fleet_fault_policy", str, "per-replica breaker chains "
      "'EVENT>action|...' (serving/health.py): events REPLICA_DEAD/"
      "REPLICA_WEDGED/REPLICA_SLOW, actions failover (rehome + move "
      "tickets + journal adoption), probe_backoff (OPEN the breaker "
      "for fleet_probe_backoff_s x 2^n, then HALF_OPEN one trial "
      "fingerprint), ignore. The Nth consecutive event takes the "
      "chain's Nth step (last repeats)",
      "REPLICA_DEAD>failover|REPLICA_WEDGED>probe_backoff"
      "|REPLICA_WEDGED>failover|REPLICA_SLOW>probe_backoff")
    R("fleet_suspect_checks", int, "consecutive rate-limited health "
      "checks a BUSY replica's scheduler-cycle counter must flatline "
      "before the monitor calls it REPLICA_WEDGED (the first "
      "flatlined check already marks it SUSPECT in the flight "
      "recorder)", 4, None, 1)
    R("fleet_probe_backoff_s", float, "base of the breaker's bounded "
      "exponential backoff: an OPEN replica is re-probed (HALF_OPEN, "
      "one trial fingerprint) after fleet_probe_backoff_s x 2^n, "
      "exponent capped at 6", 0.05, None, 0.0)
    R("fleet_health_check_s", float, "heartbeat sampling window: "
      "wedge/slow counting reads each replica's cycle counter at "
      "most once per this many seconds (dead-thread detection is "
      "never rate-limited)", 0.25, None, 0.001)
    R("fleet_warmup_s", float, "restore grace: a just-restored "
      "replica takes no COLD placements for this long, so an empty "
      "(least-loaded) returnee doesn't instantly become every new "
      "fingerprint's home; warm traffic returns at once", 1.0,
      None, 0.0)
    R("fleet_slow_cycle_s", float, "pace threshold: a busy replica "
      "whose per-scheduler-cycle wall between health checks exceeds "
      "this emits REPLICA_SLOW through the fault-policy chain. "
      "0 = disabled", 0.0, None, 0.0)
    R("flightrec_dir", str, "directory for the crash-surviving flight "
      "recorder (telemetry/flightrec.py): state transitions (bucket "
      "builds/quarantines, shed decisions + feasibility estimates, "
      "fallback hops, resetup routing, chaos injections) append one "
      "JSON line each, rotated and corruption-tolerant, for "
      "tools/flightrec.py postmortems. '' = in-memory ring only "
      "(AMGX_TPU_FLIGHTREC_DIR env also attaches a directory)", "")
    R("fallback_policy", str, "resilience chains "
      "'STATUS>action[=arg]|...' (actions: retry, rescale_retry, "
      "switch_solver=<NAME>, escalate_sweeps), applied host-side by "
      "ResilientSolver when a solve ends in that status", "")
    R("max_fallback_attempts", int, "bound on total fallback/retry "
      "attempts per solve", 2, None, 0)


_register_default_parameters()

# ---------------------------------------------------------------------------
# AMG_Config
# ---------------------------------------------------------------------------

_FLAT_RE = re.compile(
    r"^\s*(?:(?P<scope>[A-Za-z_]\w*):)?"
    r"(?P<name>[A-Za-z_]\w*)"
    r"(?:\((?P<new_scope>[A-Za-z_]\w*)\))?"
    r"\s*=\s*(?P<value>.*?)\s*$")


@dataclass
class Config:
    """Scoped parameter store (AMG_Config analog).

    Values live in `values[(scope, name)]`; solver-role parameters may have
    an attached child scope in `param_scopes[(scope, name)]`.
    """

    values: Dict[Tuple[str, str], Any] = field(default_factory=dict)
    param_scopes: Dict[Tuple[str, str], str] = field(default_factory=dict)

    # -- parsing ----------------------------------------------------------
    @classmethod
    def from_string(cls, options: str) -> "Config":
        cfg = cls()
        cfg.parse_parameter_string(options)
        return cfg

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path) as f:
            text = f.read()
        cfg = cls()
        stripped = text.lstrip()
        if stripped.startswith("{"):
            cfg.parse_json(json.loads(text))
        else:
            cfg.parse_parameter_string(text)
        return cfg

    @classmethod
    def from_dict(cls, obj: dict) -> "Config":
        cfg = cls()
        cfg.parse_json(obj)
        return cfg

    def parse_parameter_string(self, options: str):
        """Parse flat `scope:name(new_scope)=value` items separated by
        ',', ';' or newlines (reference: AMG_Config::parseParameterString)."""
        if not options:
            return
        for item in re.split(r"[,;\n]+", options):
            item = item.strip()
            if not item or item.startswith("#") or item.startswith("%"):
                continue
            if item.startswith("config_version"):
                continue
            if item.split("=", 1)[0].strip().endswith(":config_version"):
                continue  # scoped spelling (eigen_configs/JACOBI_DAVIDSON)
            m = _FLAT_RE.match(item)
            if not m:
                raise BadConfigurationError(f"cannot parse config entry {item!r}")
            scope = m.group("scope") or "default"
            name = m.group("name")
            new_scope = m.group("new_scope")
            self._set(scope, name, m.group("value"), new_scope)

    def parse_json(self, obj: dict):
        """Import a config_version-2 JSON object: nested solver objects
        create scopes (reference: include/amg_config.h:144-269)."""
        version = obj.get("config_version", 1)
        if version not in (1, 2):
            raise BadConfigurationError(f"unsupported config_version {version}")
        for key, val in obj.items():
            if key == "config_version":
                continue
            if isinstance(val, dict):
                self._import_json_solver(key, val, "default")
            else:
                self._set("default", key, val, None)

    def _import_json_solver(self, role: str, obj: dict, parent_scope: str):
        child_scope = obj.get("scope", role)
        if "solver" not in obj:
            raise BadConfigurationError(
                f"JSON solver object {role!r} missing 'solver' key")
        # bind role -> (algorithm, child scope) in the parent scope
        self._set(parent_scope, role, obj["solver"], child_scope)
        for key, val in obj.items():
            if key in ("scope", "solver"):
                continue
            if isinstance(val, dict):
                self._import_json_solver(key, val, child_scope)
            else:
                self._set(child_scope, key, val, None)

    # -- set/get ----------------------------------------------------------
    def _convert(self, desc: ParamDesc, value: Any) -> Any:
        if desc.type is int:
            v = int(value)
        elif desc.type is float:
            v = float(value)
        elif desc.type is str:
            v = str(value)
        else:
            v = desc.type(value)
        if desc.allowed is not None and v not in desc.allowed:
            # string enums are case-tolerant in the reference
            if isinstance(v, str) and v.upper() in desc.allowed:
                v = v.upper()
            elif isinstance(v, str) and v.lower() in desc.allowed:
                v = v.lower()
            else:
                raise BadConfigurationError(
                    f"value {v!r} not allowed for parameter {desc.name!r} "
                    f"(allowed: {desc.allowed})")
        if desc.min_value is not None and v < desc.min_value:
            raise BadConfigurationError(
                f"value {v!r} below minimum {desc.min_value} for {desc.name!r}")
        if desc.max_value is not None and desc.max_value != 0 and v > desc.max_value:
            raise BadConfigurationError(
                f"value {v!r} above maximum {desc.max_value} for {desc.name!r}")
        return v

    def _set(self, scope: str, name: str, value: Any, new_scope: Optional[str]):
        desc = _REGISTRY.get(name)
        if desc is None:
            from .errors import did_you_mean
            raise BadConfigurationError(
                f"unknown parameter {name!r}"
                f"{did_you_mean(name, _REGISTRY)}")
        self.values[(scope, name)] = self._convert(desc, value)
        if new_scope:
            if name not in SOLVER_ROLE_PARAMS:
                raise BadConfigurationError(
                    f"parameter {name!r} cannot declare a new scope")
            self.param_scopes[(scope, name)] = new_scope

    def set(self, name: str, value: Any, scope: str = "default",
            new_scope: Optional[str] = None):
        self._set(scope, name, value, new_scope)

    def get(self, name: str, scope: str = "default") -> Any:
        """Scoped lookup with fallback scope -> default -> registered default
        (reference: getParameter, include/amg_config.h:186)."""
        if (scope, name) in self.values:
            return self.values[(scope, name)]
        if ("default", name) in self.values:
            return self.values[("default", name)]
        desc = _REGISTRY.get(name)
        if desc is None:
            from .errors import did_you_mean
            raise BadParametersError(
                f"unknown parameter {name!r}"
                f"{did_you_mean(name, _REGISTRY)}")
        return desc.default

    def get_scope(self, name: str, scope: str = "default") -> str:
        """The child scope bound to a solver-role parameter at `scope`
        (defaults to 'default' when the parameter was set without one)."""
        if (scope, name) in self.param_scopes:
            return self.param_scopes[(scope, name)]
        if (scope, name) in self.values:
            return "default"
        if ("default", name) in self.param_scopes:
            return self.param_scopes[("default", name)]
        return "default"

    def get_solver(self, role: str, scope: str = "default") -> Tuple[str, str]:
        """Return (algorithm_name, child_scope) for a solver-role param."""
        return str(self.get(role, scope)), self.get_scope(role, scope)

    def clone(self) -> "Config":
        return Config(dict(self.values), dict(self.param_scopes))

    def __repr__(self):
        items = ", ".join(f"{s}:{n}={v!r}" for (s, n), v in sorted(self.values.items()))
        return f"Config({items})"


# keep the reference's class name available as an alias
AMG_Config = Config
