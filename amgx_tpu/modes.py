"""Precision-mode system.

TPU-native analog of the reference TemplateConfig mode system
(include/amgx_config.h:102-131). The reference explodes every algorithm
class into explicit template instantiations per mode (dDDI, dFFI, ...);
here a mode is just a small value object carrying dtypes, and every
kernel is dtype-polymorphic through JAX tracing -- one implementation,
compiled per dtype on demand.

Mode string grammar (4 letters, same as the reference):
  [0] memory space : 'd' (device) | 'h' (host) -- JAX manages placement,
      kept for API parity only.
  [1] vector precision : D=float64 F=float32 C=complex64 Z=complex128
  [2] matrix precision : same alphabet
  [3] index type : I=int32 (L=int64 accepted)
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .errors import RC, AMGXError

_PREC = {
    "D": np.float64,
    "F": np.float32,
    "C": np.complex64,
    "Z": np.complex128,
}
_IND = {"I": np.int32, "L": np.int64}


@dataclasses.dataclass(frozen=True)
class Mode:
    """Value analog of TemplateConfig<MemSpace, VecPrec, MatPrec, IndPrec>."""

    name: str
    mem_space: str          # 'd' or 'h' (informational)
    vec_dtype: np.dtype
    mat_dtype: np.dtype
    ind_dtype: np.dtype

    @property
    def is_complex(self) -> bool:
        return np.issubdtype(self.vec_dtype, np.complexfloating)

    @property
    def real_dtype(self) -> np.dtype:
        """The real dtype matching vec precision (for norms/tolerances)."""
        return np.dtype(np.zeros(0, self.vec_dtype).real.dtype)


def parse_mode(name: str) -> Mode:
    """Parse a 4-letter mode string like 'dDDI' (AMGX_mode_dDDI)."""
    if len(name) != 4 or name[0] not in "dh" or name[1] not in _PREC \
            or name[2] not in _PREC or name[3] not in _IND:
        raise AMGXError(f"invalid mode string {name!r}", RC.BAD_MODE)
    return Mode(
        name=name,
        mem_space=name[0],
        vec_dtype=np.dtype(_PREC[name[1]]),
        mat_dtype=np.dtype(_PREC[name[2]]),
        ind_dtype=np.dtype(_IND[name[3]]),
    )


# the ten "real builds" the reference instantiates (AMGX_FORALL_BUILDS,
# include/amgx_config.h) plus complex builds
ALL_MODES = tuple(
    parse_mode(m)
    for m in (
        "dDDI", "dDFI", "dFFI", "hDDI", "hDFI", "hFFI",
        "dCCI", "dZZI", "hCCI", "hZZI",
    )
)

DEFAULT_MODE = parse_mode("dDDI")
