"""Precision-mode system.

TPU-native analog of the reference TemplateConfig mode system
(include/amgx_config.h:102-131). The reference explodes every algorithm
class into explicit template instantiations per mode (dDDI, dFFI, ...);
here a mode is just a small value object carrying dtypes, and every
kernel is dtype-polymorphic through JAX tracing -- one implementation,
compiled per dtype on demand.

Mode string grammar (4 letters, same as the reference, plus TPU
low-precision extensions):
  [0] memory space : 'd' (device) | 'h' (host) -- JAX manages placement,
      kept for API parity only.
  [1] vector precision : D=float64 F=float32 C=complex64 Z=complex128
      B=bfloat16 H=float16 (B/H are TPU-native extensions)
  [2] matrix precision : same alphabet
  [3] index type : I=int32 (L=int64 accepted)

bf16 matrix storage halves the HBM traffic of the SpMV that bounds
every solver iteration — the mixed-precision play the reference's dDFI
mode makes with f32, taken to the TPU's native format (e.g. dDBI:
float64 iteration vectors over a bfloat16 matrix).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .errors import RC, AMGXError

_PREC = {
    "D": np.float64,
    "F": np.float32,
    "C": np.complex64,
    "Z": np.complex128,
}
_IND = {"I": np.int32, "L": np.int64}


def _prec_ext():
    """TPU-native precision extensions (lazy: bfloat16 comes from the
    ml_dtypes registration jax.numpy carries)."""
    import jax.numpy as jnp
    return {"B": jnp.bfloat16, "H": np.float16}


@dataclasses.dataclass(frozen=True)
class Mode:
    """Value analog of TemplateConfig<MemSpace, VecPrec, MatPrec, IndPrec>."""

    name: str
    mem_space: str          # 'd' or 'h' (informational)
    vec_dtype: np.dtype
    mat_dtype: np.dtype
    ind_dtype: np.dtype

    @property
    def is_complex(self) -> bool:
        return np.issubdtype(self.vec_dtype, np.complexfloating)

    @property
    def real_dtype(self) -> np.dtype:
        """The real dtype matching vec precision (for norms/tolerances)."""
        return np.dtype(np.zeros(0, self.vec_dtype).real.dtype)


def _prec(letter: str):
    if letter in _PREC:
        return np.dtype(_PREC[letter])
    ext = _prec_ext()
    if letter in ext:
        return np.dtype(ext[letter])
    return None


def parse_mode(name: str) -> Mode:
    """Parse a 4-letter mode string like 'dDDI' (AMGX_mode_dDDI);
    'B'/'H' are the TPU bfloat16/float16 precision extensions."""
    ok = (len(name) == 4 and name[0] in "dh" and name[3] in _IND
          and _prec(name[1]) is not None and _prec(name[2]) is not None)
    if not ok:
        raise AMGXError(f"invalid mode string {name!r}", RC.BAD_MODE)
    return Mode(
        name=name,
        mem_space=name[0],
        vec_dtype=_prec(name[1]),
        mat_dtype=_prec(name[2]),
        ind_dtype=np.dtype(_IND[name[3]]),
    )


# the ten "real builds" the reference instantiates (AMGX_FORALL_BUILDS,
# include/amgx_config.h) plus complex builds
ALL_MODES = tuple(
    parse_mode(m)
    for m in (
        "dDDI", "dDFI", "dFFI", "hDDI", "hDFI", "hFFI",
        "dCCI", "dZZI", "hCCI", "hZZI",
    )
)

DEFAULT_MODE = parse_mode("dDDI")
