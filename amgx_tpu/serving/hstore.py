"""Persistent hierarchy structures (the crash-recovery setup store).

A killed serving process loses every live AMG hierarchy, and a restart
pays the full multi-second setup per pattern before the first byte of
useful work (the r05 256^3 warm setup is 17.4 s). But the hierarchy
STRUCTURE — aggregates maps, CF splits, transfer operators, grid
pairings — is deterministic from the sparsity pattern (ROADMAP 3d), so
it can live on disk next to the AOT store: `HierarchyStore` persists
each level's `structure_snapshot()` keyed on (pattern fingerprint,
solver-config signature), and a restarted service restores it as
'ghost' levels that `AMG.adopt_structure` routes through the
structure-reuse rebuild — Galerkin values + smoother setups only, no
coarsening selection — turning the restart setup into a load +
value-resetup (amg.setup.restored, never amg.setup.full).

Failure model matches the AOT store: saves are atomic (tmp + rename),
a missing/corrupt/mismatched snapshot loads as None and the caller
does a full setup — the store can only ever make a restart cheaper,
never wrong (restored hierarchies still recompute every value from the
actual matrix).
"""
from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Any, List, Optional

import numpy as np

from ..profiling import trace_region


def _amg_nodes(root) -> List[Any]:
    """The AMG hierarchy objects inside a solver tree, in deterministic
    construction order: unwraps ResilientSolver-style `.solver`
    wrappers and descends `.preconditioner` children. Reads instance
    __dict__ directly so `__getattr__`-delegating wrappers cannot
    surface the same node twice."""
    out: List[Any] = []

    def walk(s):
        if s is None:
            return
        d = getattr(s, "__dict__", None)
        if d is None:
            return
        wrapped = d.get("solver")
        if wrapped is not None:
            walk(wrapped)
        amg = d.get("amg")
        if amg is not None and hasattr(amg, "levels"):
            out.append(amg)
        walk(d.get("preconditioner"))

    walk(root)
    return out


class HierarchyStore:
    """Directory-backed store of per-pattern hierarchy structure
    snapshots (see module docs)."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def key(self, fingerprint: str, cfg) -> str:
        # the config signature is part of the key: selector, strength,
        # max_levels, ... all shape the structure, so a config edit +
        # restart must MISS the store and re-coarsen. serving_* and
        # autotune* knobs are excluded — they are consumed by the
        # service layer only (queue bounds, store paths, checkpoint
        # cadence, tuner thresholds) and can never influence
        # coarsening, so relocating a journal dir, retuning the shed
        # policy or flipping the tuner on must NOT invalidate every
        # persisted hierarchy (a PROMOTED overlay sets real AMG knobs
        # in the engine's config, which correctly re-keys)
        h = hashlib.blake2b(digest_size=16)
        vals = tuple(sorted((k, v) for k, v in cfg.values.items()
                            if not k[1].startswith(("serving_",
                                                    "autotune"))))
        h.update(repr((str(fingerprint), vals,
                       tuple(sorted(cfg.param_scopes.items())))).encode())
        return h.hexdigest()

    def _paths(self, key: str):
        base = os.path.join(self.directory, key)
        return base + ".hier.json", base + ".hier.npz"

    # -- save -------------------------------------------------------------
    def save(self, key: str, solver_root) -> bool:
        """Snapshot every AMG node's level structures under `key`.
        Skipped (False, serving.recovery.hstore_skip) when any level
        class declines persistence; failures degrade to not-saved."""
        from ..resilience import faultinject as _fi
        from ..telemetry import metrics as _tm
        nodes = _amg_nodes(solver_root)
        if not nodes:
            return False
        metas, arrays = [], {}
        for ni, amg in enumerate(nodes):
            lvls = []
            if not amg.levels:
                _tm.inc("serving.recovery.hstore_skip")
                return False
            for li, level in enumerate(amg.levels):
                snap = level.structure_snapshot()
                if snap is None:
                    _tm.inc("serving.recovery.hstore_skip")
                    return False
                meta, arrs = snap
                meta = dict(meta)
                meta["algorithm"] = type(level).algorithm
                lvls.append(meta)
                for name, arr in arrs.items():
                    arrays[f"n{ni}.L{li}.{name}"] = np.asarray(arr)
            metas.append(lvls)
        jpath, npath = self._paths(key)
        try:
            with trace_region("serving.hstore_save"):
                buf = io.BytesIO()
                np.savez(buf, **arrays)
                blob = _fi.corrupt_blob("aot_corrupt", buf.getvalue())
                with open(npath + ".tmp", "wb") as f:
                    f.write(blob)
                os.replace(npath + ".tmp", npath)
                with open(jpath + ".tmp", "w") as f:
                    json.dump({"nodes": metas}, f)
                os.replace(jpath + ".tmp", jpath)
            _tm.inc("serving.recovery.hstore_save")
            return True
        except Exception:
            _tm.inc("serving.recovery.hstore_error")
            for p in (jpath, npath):
                try:
                    os.remove(p)
                except OSError:
                    pass
            return False

    # -- load -------------------------------------------------------------
    def load(self, key: str) -> Optional[List[List[Any]]]:
        """Ghost-level lists (one per AMG node, construction order) for
        a complete snapshot, or None (missing/corrupt/unknown level
        class — the caller then runs a full setup)."""
        from .. import registry
        from ..telemetry import metrics as _tm
        jpath, npath = self._paths(key)
        if not os.path.exists(jpath) or not os.path.exists(npath):
            return None
        try:
            with trace_region("serving.hstore_load"):
                with open(jpath) as f:
                    metas = json.load(f)["nodes"]
                with open(npath, "rb") as f:
                    data = np.load(io.BytesIO(f.read()))
                out = []
                for ni, lvls in enumerate(metas):
                    ghosts = []
                    for li, meta in enumerate(lvls):
                        cls = registry.amg_levels.get(meta["algorithm"])
                        prefix = f"n{ni}.L{li}."
                        arrs = {k[len(prefix):]: data[k] for k in data.files
                                if k.startswith(prefix)}
                        ghosts.append(cls.structure_restore(meta, arrs))
                    out.append(ghosts)
            _tm.inc("serving.recovery.hstore_load")
            return out
        except Exception:
            _tm.inc("serving.recovery.hstore_error")
            return None

    # -- tuned-config overlays (serving/autotune.py) ----------------------
    # the promoted config deltas persist BESIDE the hierarchy/AOT
    # snapshots, keyed by fingerprint ALONE (digest of the same
    # fingerprint string): the overlay must resolve BEFORE the
    # engine's config — and therefore before any (fingerprint, cfg)
    # key — exists, so a restarted replica can serve the tuned config
    # from its first request

    def _tuned_path(self, fingerprint: str) -> str:
        d = hashlib.blake2b(str(fingerprint).encode(),
                            digest_size=12).hexdigest()
        return os.path.join(self.directory, f"tuned-{d}.json")

    def save_tuned(self, fingerprint: str, record: dict) -> bool:
        """Persist one fingerprint's promoted tuner record (deltas +
        the shadow measurements that justified them). Atomic; a
        failure degrades to not-persisted (the live overlay still
        serves until restart)."""
        from ..telemetry import metrics as _tm
        path = self._tuned_path(fingerprint)
        try:
            with open(path + ".tmp", "w") as f:
                json.dump(dict(record, fingerprint=str(fingerprint)),
                          f)
            os.replace(path + ".tmp", path)
            return True
        except Exception:
            _tm.inc("serving.recovery.hstore_error")
            return False

    def load_tuned(self, fingerprint: str) -> Optional[dict]:
        """The persisted tuner record for a fingerprint, or None
        (missing/corrupt — corrupt records are dropped so they cannot
        poison every future lookup)."""
        path = self._tuned_path(fingerprint)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                rec = json.load(f)
            if not isinstance(rec.get("deltas"), list):
                raise ValueError("malformed tuned record")
            return rec
        except Exception:
            from ..telemetry import metrics as _tm
            _tm.inc("serving.recovery.hstore_error")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def drop_tuned(self, fingerprint: str):
        """Delete a fingerprint's persisted tuner record (demotion)."""
        try:
            os.remove(self._tuned_path(fingerprint))
        except OSError:
            pass

    def restore_into(self, key: str, solver_root) -> bool:
        """Load `key` and adopt the ghost levels into the tree's AMG
        nodes (their next setup() becomes a structure-reuse rebuild).
        False when the snapshot is absent/corrupt or the node count
        drifted — the tree is left untouched and sets up fully."""
        ghosts = self.load(key)
        if ghosts is None:
            return False
        nodes = _amg_nodes(solver_root)
        if len(nodes) != len(ghosts):
            return False
        for amg, g in zip(nodes, ghosts):
            amg.adopt_structure(g)
        return True
