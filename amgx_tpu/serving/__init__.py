"""Production serving subsystem.

The async multi-tenant solve service over the batch/resilience/
telemetry machinery (ROADMAP item 3). Four pieces:

- **continuous batching** (`engine.BucketEngine` on the chunked solve
  entry `Solver._build_chunk_fns`): in-flight systems advance in
  fixed-size buckets chunk-by-chunk; a converged slot is refilled at
  the next cycle boundary instead of waiting for the batch to drain;
- **hierarchy/LRU cache** (`cache.HierarchyCache`): live buckets keyed
  on pattern fingerprint, bytes-budgeted; repeat-structure traffic
  routes through value-resetup instead of a full AMG setup;
- **AOT warm paths** (`aot.AotStore`): bucket executables exported
  with `jax.export` and persisted, so a restarted service skips
  first-request trace latency;
- **per-tenant deadlines + admission control** (`service.SolveService`):
  expiry completes tickets with `SolveStatus.DEADLINE_EXCEEDED` —
  never a hung bucket — and `serving_max_queue` bounds the queue.

Plus the fault-tolerance layer (PR 11):

- **solve journal + checkpoints** (`journal.SolveJournal`): requests
  are journaled write-ahead and in-flight solve states checkpoint at
  cycle boundaries; a restarted service replays the journal and
  RESUMES checkpointed solves bit-identically;
- **persistent hierarchies** (`hstore.HierarchyStore`): structure
  snapshots next to the AOT store turn the restart's full setup into
  a load + structure-reuse rebuild;
- **backpressure/shedding + supervision** (`service.SolveService`):
  OVERLOADED load shedding driven by live latency estimates and
  per-tenant quotas, plus a wedged-bucket supervisor with bounded
  retry/backoff under the `serving_fault_policy` grammar.

And the scale-out layer (ROADMAP item 2):

- **fleet router** (`fleet.FleetRouter`): N replicas behind one
  submit/step/drain surface with fingerprint-affine rendezvous
  routing (warm|cold|spill), fleet-wide shed consults over merged
  per-replica metrics, and per-request replica attribution on the
  trace chain;
- **mixed bucket-width ladder** (`ladder`): `serving_bucket_ladder`
  draws each bucket build's width from the queue composition instead
  of one fixed `serving_bucket_slots`.

Quick start::

    from amgx_tpu.serving import SolveService
    svc = SolveService(Config.from_string(BATCHED_CG + ", ..."))
    t = svc.submit(A, b, tenant="alice", deadline_s=0.5)
    svc.drain()          # or svc.start() for the background scheduler
    print(t.result.status, t.latency_s)

Fleet::

    from amgx_tpu.serving import FleetRouter
    fleet = FleetRouter.build(cfg, n_replicas=2)
    t = fleet.submit(A, b, tenant="alice")
    fleet.drain()
    print(t.replica, t.route, fleet.stats()["routes"])
"""
from __future__ import annotations

from .aot import AotStore  # noqa: F401
from .cache import HierarchyCache, solve_data_bytes  # noqa: F401
from .engine import BucketEngine  # noqa: F401
from .fleet import FleetRouter  # noqa: F401
from .hstore import HierarchyStore  # noqa: F401
from .journal import SolveJournal  # noqa: F401
from .ladder import choose_slots, parse_ladder  # noqa: F401
from .service import ServiceTicket, SolveService  # noqa: F401
