"""Continuous-batching bucket engine.

One `BucketEngine` owns a fixed-width batch of in-flight systems that
share a sparsity pattern (and therefore one AMG hierarchy structure
and one set of XLA traces). Instead of the `RequestBatcher`'s
drain-and-wait dispatch — where a batch admitted together must finish
together — the engine steps every occupied slot by `chunk` iterations
per scheduler cycle using the chunked solve entry
(`Solver._build_chunk_fns`), checks the per-slot done flags at the
cycle boundary, finalizes and frees converged slots, and lets the
scheduler refill them with queued requests immediately. A drained
slot's state is frozen by the loop predicate (the same per-system
convergence freeze the batched subsystem relies on), so empty and
finished slots ride along at zero cost.

Slot refill never retraces: the per-slot half of the solve-data pytree
(discovered ONCE by a probe value-resetup at bucket build — the leaves
a value-only resetup replaces) is scattered row-wise, the shared
structure half stays aliased, and the engine's three functions
(single-system init, batched step, batched finalize) keep their
original traces for the bucket's lifetime. With an `AotStore` the
traces themselves are loaded from disk (`jax.export`), so a restarted
service never traces at all.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..batch.core import BatchedSolver
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..profiling import trace_region
from ..resilience import faultinject as _fi
from ..solvers.base import Solver, SolveResult
from .aot import AotStore
from .hstore import HierarchyStore

_ENGINE_FNS = ("init1", "step", "finish")


def _flat_fn(pyfn, in_tree):
    """Positional-leaves wrapper around a (data, b, state) pytree fn —
    the exportable form (serving/aot.py: containers never enter the
    serialized artifact)."""
    def flat(*leaves):
        data, b, st = jax.tree.unflatten(in_tree, list(leaves))
        return tuple(jax.tree.leaves(pyfn(data, b, st)))
    return flat


class BucketEngine:
    """Continuous-batching engine for one (pattern, dtype) bucket."""

    def __init__(self, cfg: Config, scope: str, template: CsrMatrix,
                 *, slots: int, chunk: int, dtype,
                 fingerprint: str = "", aot: Optional[AotStore] = None,
                 hstore: Optional[HierarchyStore] = None):
        self.fingerprint = fingerprint
        self.slots = int(slots)
        if self.slots < 1:
            raise BadParametersError(
                f"serving: bucket width must be >= 1 slot, got {slots}")
        self.chunk = int(chunk)
        self.dtype = jnp.dtype(dtype)
        self.trace_count = 0     # python traces of the engine functions
        self.aot_warm = False    # True when the fns came from the store
        self.hier_restored = False  # hierarchy came from the hstore
        # chaos drill: a scripted builder crash fires here, BEFORE any
        # state exists — exactly like an OOM/trace failure would
        _fi.service_crash("build_crash")
        with trace_region("serving.bucket_build"):
            t0 = time.perf_counter()
            self.bs = BatchedSolver(cfg, scope)
            hkey = None
            if hstore is not None:
                hkey = hstore.key(fingerprint, self.bs.solver.cfg)
                self.hier_restored = hstore.restore_into(
                    hkey, self.bs.solver)
            self.bs.setup(template)
            if hkey is not None and not self.hier_restored:
                hstore.save(hkey, self.bs.solver)
            slv = self.bs.solver
            if slv.scaler is not None:
                raise BadParametersError(
                    "serving: equation scaling is unsupported in "
                    "continuous batching (set scaling=NONE)")
            self.bs._check_multi_matrix_config()
            self.max_iters = slv.max_iters
            self.hist_len = slv.max_iters + 1
            self.n = template.num_rows * template.block_dimx
            self._split_data(template)
            self._build_fns(aot)
            self._B = jnp.zeros((self.slots, self.n), self.dtype)
            self._state = self._initial_state()
            self.build_time = time.perf_counter() - t0
        from ..telemetry import metrics as _tm
        _tm.set_gauge("serving.bucket_width", self.slots)
        # slot bookkeeping is the scheduler's: the engine stores the
        # occupant object opaquely (a ticket, a request, anything)
        self.occupant: List[Optional[Any]] = [None] * self.slots
        # last pulled per-slot iteration counters (progress heartbeat)
        self.iters_snapshot: Optional[np.ndarray] = None

    # -- structure/value split --------------------------------------------
    def _split_data(self, template: CsrMatrix):
        """Discover which solve-data leaves a value-only resetup
        replaces (the per-slot half) by probing with a same-valued
        copy of the template: structure leaves survive the resetup as
        the SAME objects (the identity contract the batched subsystem
        is built on, batch/core.py), value leaves come back fresh.
        The axes signature is then FIXED for the bucket's lifetime, so
        every future admit is a row scatter, never a retrace."""
        slv = self.bs.solver
        d0_flat, treedef = jax.tree.flatten(slv.solve_data())
        probe = template.with_values(jnp.asarray(template.values) + 0)
        with self.bs._keep_batched_traces():
            slv.resetup(probe)
        d1_flat, treedef1 = jax.tree.flatten(slv.solve_data())
        if treedef1 != treedef:
            raise BadParametersError(
                "serving: solve-data structure changed across a "
                "value-only resetup; continuous batching needs "
                "structure_reuse_levels=-1 so the hierarchy structure "
                "(and the engine traces) survive per-system value "
                "splices")
        self._data_treedef = treedef
        self._axes_flat = [None if a is b else 0
                           for a, b in zip(d0_flat, d1_flat)]
        # shared leaves stay aliased; per-slot leaves start as copies
        # of the probe's row and are overwritten at admit
        self._shared_ref = list(d1_flat)
        self._data_flat = [
            jnp.stack([leaf] * self.slots) if ax == 0 else leaf
            for ax, leaf in zip(self._axes_flat, d1_flat)]
        self._snap_A: Optional[CsrMatrix] = probe
        self._snap_flat = d1_flat
        # fused admit scatter: splicing a system into its slot touches
        # every per-slot value leaf (a deep hierarchy has ~100) plus
        # the rhs row and each solve-state leaf — issued eagerly
        # that's ~150 one-row scatter dispatches per admission, and at
        # small grids the dispatch overhead DOMINATES the request's
        # in-bucket wall. These two programs do all of it in two
        # calls, with the old buffers donated (in-place rows, no
        # slab copy). They are deliberately NOT _counted and NOT in
        # the AOT bundle: trace_count/serving.retrace keep meaning
        # "solve-program traces" (the zero-retrace restart contract),
        # while these host-side helpers trace once per bucket build
        # in microseconds-to-milliseconds
        self._ps_idx = [i for i, ax in enumerate(self._axes_flat)
                        if ax == 0]

        def splice_rows(leaves, B, snap, b, slot):
            return ([lf.at[slot].set(s)
                     for lf, s in zip(leaves, snap)],
                    B.at[slot].set(b))

        def scatter_state(st, row, slot):
            return {k: st[k].at[slot].set(row[k]) for k in st}

        self._splice_jit = jax.jit(splice_rows, donate_argnums=(0, 1))
        self._scatter_state = jax.jit(scatter_state,
                                      donate_argnums=(0,))

    def _data_tree(self):
        return jax.tree.unflatten(self._data_treedef, self._data_flat)

    def _snapshot_for(self, A: CsrMatrix):
        """Per-system solve-data leaves for A, via the value-resetup
        path against the bucket's shared hierarchy structure (memoized
        on the matrix object: a stream resubmitting the same matrix
        pays zero resetups)."""
        if A is self._snap_A:
            return self._snap_flat
        with self.bs._keep_batched_traces():
            self.bs.solver.resetup(A)
        flat, td = jax.tree.flatten(self.bs.solver.solve_data())
        if td != self._data_treedef:
            raise BadParametersError(
                "serving: hierarchy structure drifted across an admit "
                "resetup (same-fingerprint systems must share one "
                "structure; check structure_reuse_levels=-1)")
        for i, ax in enumerate(self._axes_flat):
            if ax is None and flat[i] is not self._shared_ref[i]:
                raise BadParametersError(
                    "serving: a solve-data leaf the bucket shares "
                    "across slots changed on a value resetup — the "
                    "probe misclassified it; this solver configuration "
                    "cannot run under continuous batching")
        self._snap_A = A
        self._snap_flat = flat
        return flat

    # -- engine functions --------------------------------------------------
    def _counted(self, fn):
        eng = self

        def counted(data, b, st):
            eng.trace_count += 1
            from ..telemetry import metrics as _tm
            _tm.inc("serving.retrace")
            return fn(data, b, st)

        return jax.jit(counted)

    def _aot_key(self, aot: AotStore) -> str:
        # the SOLVER CONFIG is part of the key: tolerance, convergence
        # mode, sweep counts, guard settings are all baked into the
        # traced program, so a config edit + restart must MISS the
        # store (and re-export), never silently serve the old program
        cfg = self.bs.solver.cfg
        cfg_sig = (tuple(sorted(cfg.values.items())),
                   tuple(sorted(cfg.param_scopes.items())))
        return aot.key((self.fingerprint, self.slots, self.chunk,
                        self.n, str(self.dtype), self.hist_len,
                        tuple(0 if a == 0 else -1
                              for a in self._axes_flat), cfg_sig))

    def _build_fns(self, aot: Optional[AotStore]):
        slv = self.bs.solver
        init1, step1, finish1 = slv._build_chunk_fns(self.chunk)
        data_axes = jax.tree.unflatten(self._data_treedef,
                                       self._axes_flat)
        bstep = jax.vmap(step1, in_axes=(data_axes, 0, 0))
        bfinish = jax.vmap(finish1, in_axes=(data_axes, 0, 0))
        self._py_fns = {"init1": init1, "step": bstep,
                        "finish": bfinish}
        self._aot_store, self._aot_saved = aot, False
        loaded = None
        if aot is not None:
            loaded = aot.load_bundle(self._aot_key(aot),
                                     list(_ENGINE_FNS))
        if loaded is not None:
            self._install_loaded(loaded)
            self.aot_warm = True
        else:
            self._init1 = self._counted(init1)
            self._bstep = self._counted(bstep)
            self._bfinish = self._counted(bfinish)

    def _install_loaded(self, loaded):
        """Serve through AOT-loaded flat executables (store load, or
        the bucket's own fresh export)."""
        self._state_keys = list(loaded["meta"]["state_keys"])
        unflat = self._unflatten_state

        def wrap_state(fn):
            return lambda data, b, st: unflat(
                fn(*jax.tree.leaves((data, b, st))))

        self._init1 = wrap_state(loaded["init1"])
        self._bstep = wrap_state(loaded["step"])
        fin = loaded["finish"]

        def bfin(data, b, st):
            out = fin(*jax.tree.leaves((data, b, st)))
            return out[0], out[1]

        self._bfinish = bfin

    def _unflatten_state(self, leaves) -> Dict[str, Any]:
        # the solve state is a flat dict of arrays, so its sorted key
        # list (the sidecar metadata) fully determines the treedef
        return dict(zip(self._state_keys, leaves))

    def _zeros_single(self):
        return jnp.zeros((self.n,), self.dtype)

    def _initial_state(self):
        """All-slots-empty batched state: one init on a zero rhs (the
        zero-norm0 path marks it CONVERGED at 0 iterations, so empty
        slots are frozen from the first cycle) stacked S-fold."""
        z = self._zeros_single()
        row = self._init1(jax.tree.unflatten(self._data_treedef,
                                             self._snap_flat), z, z)
        if not self.aot_warm:
            self._state_keys = sorted(row)
        state = {k: jnp.stack([v] * self.slots) for k, v in row.items()}
        self._maybe_export(state)
        return state

    def _maybe_export(self, state):
        """Persist the engine functions once the example operands all
        exist (serving/aot.py; failures degrade to plain tracing)."""
        aot = self._aot_store
        if aot is None or self.aot_warm or self._aot_saved:
            return
        self._aot_saved = True
        z = self._zeros_single()
        single = jax.tree.unflatten(self._data_treedef, self._snap_flat)
        args1 = (single, z, z)
        argsb = (self._data_tree(), self._B, state)
        fns = {}
        for name, args in (("init1", args1), ("step", argsb),
                           ("finish", argsb)):
            in_tree = jax.tree.structure(args)
            fns[name] = (jax.jit(_flat_fn(self._py_fns[name], in_tree)),
                         tuple(jax.tree.leaves(args)))
        key = self._aot_key(aot)
        if aot.save_bundle(key, fns,
                           {"state_keys": self._state_keys,
                            "n": self.n, "slots": self.slots,
                            "chunk": self.chunk}):
            # serve through the just-exported executables: the export
            # already traced every engine function, so keeping the
            # separate _counted jits would trace the same programs a
            # second time on first use (double cold-bucket cost)
            loaded = aot.load_bundle(key, list(_ENGINE_FNS))
            if loaded is not None:
                self._install_loaded(loaded)

    # -- scheduling surface ------------------------------------------------
    @property
    def idle(self) -> bool:
        return all(o is None for o in self.occupant)

    @property
    def inflight(self) -> int:
        return sum(o is not None for o in self.occupant)

    def free_slot(self) -> Optional[int]:
        for j, o in enumerate(self.occupant):
            if o is None:
                return j
        return None

    def footprint_tree(self):
        """The byte-accounting view (serving/cache.py
        solve_data_bytes): the stacked data plus the carried state."""
        return (self._data_flat, list(self._state.values()), self._B)

    def _splice_slot(self, slot: int, A: CsrMatrix, b):
        """Shared admit prologue: value-resetup snapshot spliced into
        the per-slot data rows + the rhs scatter — one fused donated
        program for all per-slot leaves (slot is traced, so one trace
        serves every slot)."""
        snap = self._snapshot_for(A)
        b = jnp.asarray(b, self.dtype)
        if b.shape != (self.n,):
            raise BadParametersError(
                f"serving: rhs shape {b.shape} does not fit the "
                f"bucket's ({self.n},) systems")
        rows, self._B = self._splice_jit(
            [self._data_flat[i] for i in self._ps_idx], self._B,
            [snap[i] for i in self._ps_idx], b,
            jnp.asarray(slot, jnp.int32))
        for i, leaf in zip(self._ps_idx, rows):
            self._data_flat[i] = leaf
        return snap, b

    def _check_reserved(self, slot: int, occupant: Any):
        # the lock-split scheduler reserves the slot (sets a UNIQUE
        # occupant object) under its lock, then runs the device splice
        # outside it — admitting into your own reservation is fine,
        # anything else is a caller bug. The default occupant=True is
        # not a reservation (True is True across calls), so direct
        # engine users keep the strict occupied-slot guard.
        if self.occupant[slot] is None:
            return
        if occupant is True or self.occupant[slot] is not occupant:
            raise BadParametersError(f"serving: slot {slot} is occupied")

    def _trace_args(self, *occupants):
        """Span args tagging a stage with its occupants' request trace
        ids (serving request-path tracing; None when nothing is tagged
        — tickets only carry trace ids when serving_tracing=1, so the
        knob gates this without the engine knowing it)."""
        ids = [tr for o in occupants
               for tr in [getattr(o, "trace_id", None)] if tr]
        if not ids:
            return None
        if len(ids) == 1:
            return {"trace": ids[0]}
        return {"traces": ids}

    def admit(self, slot: int, A: CsrMatrix, b, x0=None,
              occupant: Any = True):
        """Fill `slot` with a new system at a cycle boundary: splice
        its values into the per-slot data rows (value-resetup path),
        scatter its freshly initialized solve state, mark occupied."""
        self._check_reserved(slot, occupant)
        with trace_region("serving.admit",
                          args=self._trace_args(occupant)):
            snap, b = self._splice_slot(slot, A, b)
            x0 = self._zeros_single() if x0 is None \
                else jnp.asarray(x0, self.dtype)
            row = self._init1(
                jax.tree.unflatten(self._data_treedef, snap), b, x0)
            self._state = self._scatter_state(
                self._state, dict(row), jnp.asarray(slot, jnp.int32))
        self.occupant[slot] = occupant

    def admit_resume(self, slot: int, A: CsrMatrix, b, state_row,
                     occupant: Any = True):
        """Refill `slot` from a checkpointed solve-state row (crash
        recovery / quarantine requeue): the same data splice as
        `admit`, but the state is RESTORED, not re-initialized — the
        resumed system then visits bit-identical iterates to the
        uninterrupted solve (the chunk window is entry-relative).
        Raises BadParametersError on a state-layout mismatch (config
        drift across the restart); callers fall back to a fresh
        admit."""
        self._check_reserved(slot, occupant)
        if set(state_row) != set(self._state):
            raise BadParametersError(
                "serving: checkpointed state keys do not match this "
                "bucket's solve state (solver config drifted across "
                "the restart?)")
        with trace_region("serving.admit",
                          args=self._trace_args(occupant)):
            _snap, b = self._splice_slot(slot, A, b)
            for k, v in state_row.items():
                ref = self._state[k]
                v = jnp.asarray(v)
                if v.shape != ref.shape[1:] or v.dtype != ref.dtype:
                    raise BadParametersError(
                        f"serving: checkpointed state leaf {k!r} has "
                        f"{v.shape}/{v.dtype}, bucket expects "
                        f"{ref.shape[1:]}/{ref.dtype}")
            self._state = self._scatter_state(
                self._state,
                {k: jnp.asarray(v, self._state[k].dtype)
                 for k, v in state_row.items()},
                jnp.asarray(slot, jnp.int32))
        self.occupant[slot] = occupant

    def state_rows(self, slots: List[int]) -> Dict[int, Dict[str, Any]]:
        """Host-pulled solve-state rows for `slots` — the checkpoint
        source (and the quarantine salvage source). One device->host
        pull per state leaf, sliced per slot."""
        host = {k: np.asarray(v) for k, v in self._state.items()}
        return {j: {k: host[k][j] for k in host} for j in slots}

    def step(self) -> List[int]:
        """One engine cycle: every occupied, unfinished slot advances
        up to `chunk` iterations (finished/empty slots are frozen by
        the loop predicate). Returns the occupied slots that are now
        terminal (converged, failed, or out of iterations) — ONE small
        device->host sync per cycle, the scheduling cadence cost.
        `iters_snapshot` (same pulled buffer) is the supervisor's
        progress heartbeat."""
        if self.idle:
            return []
        # chaos drills: a scripted device-step exception (the
        # quarantine path's food) or a silently wedged cycle (the
        # heartbeat detector's)
        _fi.service_crash("step_crash")
        if _fi.step_wedged():
            return []
        with trace_region("serving.step",
                          args=self._trace_args(*self.occupant)):
            self._state = self._bstep(self._data_tree(), self._B,
                                      self._state)
            # one eager reduction, ONE awaited buffer: remote rigs pay
            # a full round trip per awaited output (solvers/base.py);
            # the iters row rides the same buffer for the supervisor's
            # progress heartbeat
            buf = np.asarray(jnp.stack([
                (self._state["done"]
                 | (self._state["iters"] >= self.max_iters)
                 ).astype(jnp.int32),
                self._state["iters"].astype(jnp.int32)]))
            term = buf[0].astype(bool)
            self.iters_snapshot = buf[1]
        return [j for j in range(self.slots)
                if self.occupant[j] is not None and bool(term[j])]

    def finalize(self, slot_list: List[int]) -> Dict[int, SolveResult]:
        """Per-slot SolveResults for `slot_list` (one batched finalize
        pass; mid-flight neighbors' states are read, not disturbed).
        Does NOT free the slots — the scheduler does, after deadline
        bookkeeping."""
        if not slot_list:
            return {}
        with trace_region("serving.finalize",
                          args=self._trace_args(
                              *(self.occupant[j] for j in slot_list))):
            X, stats = self._bfinish(self._data_tree(), self._B,
                                     self._state)
            stats = np.asarray(stats)
        out = {}
        store_hist = bool(getattr(self.bs.solver, "store_res_history",
                                  False))
        for j in slot_list:
            it, cv, sc, n0, rn, h = Solver.unpack_stats(
                stats[j], self.hist_len)
            out[j] = SolveResult(
                x=X[j], iterations=it, converged=cv,
                res_norm=np.asarray(rn), norm0=np.asarray(n0),
                res_history=np.asarray(h) if store_hist else None,
                setup_time=self.bs.setup_time, status_code=sc)
        return out

    def release(self, slot: int):
        """Free a slot and FREEZE its lane: a released-but-unfinished
        system (deadline expiry) must not keep burning batched
        iterations in the vacant slot, so `done` is forced True —
        idempotent for terminal slots; the next admit overwrites the
        whole state row anyway."""
        self._state["done"] = self._state["done"].at[slot].set(True)
        self.occupant[slot] = None
