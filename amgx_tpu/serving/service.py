"""The multi-tenant solve service.

`SolveService` is the production front end ROADMAP item 3 names: a
stream of (matrix, rhs, tenant, deadline) requests goes in; batched,
cached, deadline-aware solves come out. It composes the pieces this
package provides:

- requests are bucketed by (pattern fingerprint, dtype) and served by
  `BucketEngine`s — continuous batching: a converged slot is refilled
  at the next cycle boundary, never waiting for the whole batch;
- the engines live in a bytes-budgeted `HierarchyCache`: a repeat
  fingerprint is a cache hit and admission routes through the
  value-resetup path (0.43 s at 256^3) instead of a full AMG setup
  (17 s); idle LRU buckets are evicted past the byte budget;
- with `serving_aot_dir` set, engine executables round-trip through
  the `AotStore`, and with `serving_hierarchy_dir` set the hierarchy
  STRUCTURES persist too (`HierarchyStore`): a restarted service
  rebuilds each bucket via load + structure-reuse + AOT — zero full
  setups, zero retraces;
- with `serving_journal_dir` set every request is journaled
  (`SolveJournal`) and in-flight solve states are checkpointed every
  `serving_checkpoint_cycles` cycles: a crashed process's successor
  replays the journal and RESUMES mid-flight solves from their
  checkpoints (bit-identical iterates — the chunked solve entry is
  resumable by construction);
- every request may carry a deadline: expiry completes the ticket
  with `DEADLINE_EXCEEDED` (its current iterate under the default
  'partial' action, the initial iterate under 'reject') at the next
  cycle boundary — a late request can never stall its bucket;
- admission is a SHED policy, not just a bound: beyond the hard
  `serving_max_queue` cap, `serving_shed_policy=deadline` rejects
  requests whose deadline the live execution-time estimate (median
  of recent in-bucket execs scaled by queue-depth waves, 25% margin)
  says is unmeetable, and `serving_tenant_quota` bounds any one
  tenant's live footprint — all shed completions carry status
  `OVERLOADED` (the honest early rejection, never a
  queued-then-missed surprise);
- failures are supervised: bucket builds and device-step cycles that
  raise (or wedge — the per-cycle progress heartbeat flatlines) are
  routed through the `serving_fault_policy` grammar (BUILD_FAILED /
  STEP_FAILED / WEDGED > retry_backoff / requeue / reject): the
  bucket is quarantined, salvageable slots finalize with their
  current iterate, the rest requeue (resuming from live state), and
  rebuilds back off exponentially up to `serving_retry_max_attempts`.

The scheduler lock is SPLIT from the device work (ROADMAP 3e): all
hierarchy builds, admission resetups, engine chunk-stepping and
finalize pulls run OUTSIDE the service lock, so a concurrent
`submit()` contends only with microseconds of bookkeeping — never
with a cycle of device work.

Drive it synchronously (`step()` / `drain()`: deterministic, what the
tests use) or start the background scheduler thread (`start()`), in
which case `submit()` is all a caller ever touches and tickets
complete asynchronously (`ticket.wait()`).
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..batch.queue import pattern_fingerprint
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..resilience import faultinject as _fi
from ..resilience.status import SolveStatus
from ..solvers.base import SolveResult
from ..telemetry import flightrec as _fr
from ..telemetry import metrics as _tm
from ..telemetry import spans as _spans
from .aot import AotStore
from .cache import HierarchyCache, solve_data_bytes
from .engine import BucketEngine
from .hstore import HierarchyStore
from .journal import SolveJournal
from .ladder import choose_slots, parse_ladder


def _now() -> float:
    # every deadline computation reads the clock through the chaos
    # hook so clock-skew drills are deterministic (faultinject)
    return _fi.service_now()


@dataclasses.dataclass(eq=False)
class ServiceTicket:
    """One submitted request; completes with a SolveResult. Identity
    semantics (eq=False): tickets are unique live objects — a
    field-wise __eq__ over numpy members would be both meaningless
    and ambiguous."""

    A: CsrMatrix
    b: np.ndarray
    x0: Optional[np.ndarray]
    tenant: str
    fingerprint: str
    submit_t: float
    deadline_t: Optional[float]          # absolute service_now() time
    result: Optional[SolveResult] = None
    complete_t: Optional[float] = None
    # process-CPU completion stamp (time.process_time at _complete):
    # on shared-core deployments the wall stamps also count neighbor
    # steal — paired latency comparisons (bench, SLO forensics) read
    # this ruler to see only what the service itself executed
    complete_cpu_t: Optional[float] = None
    # has this request's cache routing (hit/miss) been counted yet?
    # (once per request, at its build/admission — never per poll)
    cache_counted: bool = False
    # the bucket-build exception when this request was rejected
    # because its bucket could not be built (status BREAKDOWN)
    error: Optional[Exception] = None
    # client idempotency key (submit(request_key=...)): a retried
    # submit with the same key dedupes against the live ticket or the
    # journal instead of double-enqueueing
    request_key: Optional[str] = None
    # journal linkage + crash/quarantine resume state (a checkpointed
    # solve-state row; admission then resumes instead of initializing)
    journal_id: Optional[str] = None
    resume_state: Optional[Dict[str, np.ndarray]] = None
    # fleet failover: the journal holding this ticket's pending record
    # when that is NOT the serving replica's own (a survivor adopting a
    # dead replica's work writes checkpoints/completions back to the
    # ADOPTED journal, so its records settle instead of replaying twice)
    journal_ref: Optional[SolveJournal] = None
    admit_t: Optional[float] = None
    # request trace id (telemetry/spans.py): every lifecycle span of
    # this request is tagged with it, so the Perfetto export connects
    # them into one flow chain; persisted in the journal so a
    # crash-recovered resume keeps the ORIGINAL id
    trace_id: Optional[str] = None
    # submit wall in spans' perf_counter epoch (the retroactive
    # serving.queue span's start; service_now() is skew-hookable and
    # lives in a different epoch)
    _perf_submit: float = 0.0
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    @property
    def done(self) -> bool:
        return self.result is not None

    @property
    def latency_s(self) -> Optional[float]:
        if self.complete_t is None:
            return None
        return self.complete_t - self.submit_t

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _complete(self, result: SolveResult):
        self.result = result
        self.complete_t = _now()
        self.complete_cpu_t = time.process_time()
        self._event.set()


class SolveService:
    """Async multi-tenant solve service (see module docs). One Config
    serves every bucket; knobs are the `serving_*` parameters."""

    def __init__(self, cfg: Config, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        self.chunk = int(cfg.get("serving_chunk_iters", scope))
        self.slots = int(cfg.get("serving_bucket_slots", scope))
        self.max_queue = int(cfg.get("serving_max_queue", scope))
        self.deadline_action = str(
            cfg.get("serving_deadline_action", scope))
        self.shed_policy = str(cfg.get("serving_shed_policy", scope))
        self.tenant_quota = int(cfg.get("serving_tenant_quota", scope))
        self.ckpt_cycles = int(
            cfg.get("serving_checkpoint_cycles", scope))
        self.supervisor_cycles = int(
            cfg.get("serving_supervisor_cycles", scope))
        self.retry_backoff_s = float(
            cfg.get("serving_retry_backoff_s", scope))
        self.retry_max = int(cfg.get("serving_retry_max_attempts",
                                     scope))
        from ..resilience.policy import parse_service_policy
        self._svc_policy = parse_service_policy(
            cfg.get("serving_fault_policy", scope))
        # mixed bucket-width ladder: () = fixed self.slots width
        self.ladder = parse_ladder(
            cfg.get("serving_bucket_ladder", scope))
        # request-path tracing + fleet observability knobs
        self.tracing = bool(int(cfg.get("serving_tracing", scope)))
        replica = str(cfg.get("serving_replica_id", scope)).strip()
        if replica:
            _tm.set_replica_label(replica)
        # per-SERVICE replica identity for in-process fleets: when
        # non-empty, this service's latency observations carry a
        # replica=<id> label so two replicas' per-tenant series stay
        # distinct in the shared registry. Assigned by the FleetRouter
        # (or explicitly on the attribute), NEVER from the knob above:
        # serving_replica_id sets the process-global scrape label,
        # which stamps samples at EXPOSITION time and stays clearable
        # via set_replica_label(None) — baking it into stored label
        # sets would survive the clear and break that contract.
        self.replica = ""
        frdir = str(cfg.get("flightrec_dir", scope)).strip()
        if frdir:
            _fr.configure(frdir)
        aot_dir = str(cfg.get("serving_aot_dir", scope)).strip()
        self.aot: Optional[AotStore] = \
            AotStore(aot_dir) if aot_dir else None
        hier_dir = str(cfg.get("serving_hierarchy_dir", scope)).strip()
        self.hstore: Optional[HierarchyStore] = \
            HierarchyStore(hier_dir) if hier_dir else None
        jdir = str(cfg.get("serving_journal_dir", scope)).strip()
        self.journal: Optional[SolveJournal] = \
            SolveJournal(jdir) if jdir else None
        # hit/miss is counted PER REQUEST at its build/admission (in
        # step()), not via the cache's own lookup counters — a queued
        # ticket polling a full bucket every cycle must not inflate
        # the hit rate the bench artifact records
        self.buckets = HierarchyCache(
            budget_bytes=int(cfg.get("serving_cache_bytes", scope)),
            max_entries=int(cfg.get("serving_cache_entries", scope)),
            counters={"evict": "serving.cache.evictions",
                      "bytes": "serving.cache.bytes",
                      "entries": "serving.live_buckets"},
            can_evict=lambda eng: eng.idle)
        self._queue: List[ServiceTicket] = []
        self._lock = threading.RLock()
        # serializes whole scheduler cycles (one step() at a time);
        # NEVER held while the bookkeeping lock is wanted by submit()
        self._sched_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # the exception that killed the background scheduler loop (or
        # an inline step(), captured by the FleetRouter): the fleet
        # health monitor's REPLICA_DEAD signal. None while healthy.
        self._thread_error: Optional[BaseException] = None
        # async bucket builds (background-scheduler mode): fingerprint
        # -> builder thread / finished engine / failure
        self._builds: Dict[str, threading.Thread] = {}
        self._built: Dict[str, BucketEngine] = {}
        self._build_failed: Dict[str, Exception] = {}
        # service-level fault bookkeeping (serving_fault_policy):
        # fingerprint -> {"attempts", "not_before"} retry/backoff state
        self._faulted: Dict[str, Dict[str, float]] = {}
        # fingerprint -> (iters_heartbeat, stale_cycles) wedge detector
        self._progress: Dict[str, Tuple[int, int]] = {}
        self._completed_total = 0
        self._cycle = 0
        # live request_key -> ticket (idempotent submit dedupe)
        self._keyed: Dict[str, ServiceTicket] = {}
        # recent in-bucket execution times (shed estimator window)
        import collections
        self._exec_recent = collections.deque(maxlen=64)
        # ... and the same window PER FINGERPRINT: mixed-size traffic
        # must not shed the small tenant on the big tenant's median —
        # a fingerprint with its own trained window is estimated from
        # its own history, the global window is only the cold fallback
        self._exec_fp: Dict[str, Any] = {}
        # execution-device share factor for the feasibility estimate:
        # an in-process fleet (FleetRouter) runs N replicas on ONE
        # device, so each replica's observed exec window undercounts
        # wall latency by the number of co-resident replicas competing
        # for it; the router sets this to N. Standalone services (and
        # one-replica-per-host fleets) keep 1.0
        self.exec_share = 1.0
        # completed journaled tickets awaiting their record_done write
        # (flushed outside the lock each cycle)
        self._journal_doneq: List[ServiceTicket] = []
        # flight-recorder events minted under the service lock queue
        # here and flush outside it (disk write + flush per event —
        # the PR-11 lock-split discipline applies to the recorder
        # exactly as it does to the journal); a deferred BREAKDOWN
        # dump rides along (it prints through the user's output
        # callback, which must never run lock-held)
        self._fr_q: List[Tuple[str, Optional[str], Dict[str, Any]]] = []
        self._fr_dump_reason: Optional[str] = None
        # per-tenant tallies for stats()
        self._tenants: Dict[str, Dict[str, int]] = {}
        # online config autotuner (autotune=1): default-off — a
        # disabled service never constructs the tuner, schedules no
        # shadow work and applies no overlay (bitwise-inert contract,
        # test-proven)
        self._draining = False
        self._tuner = None
        if int(cfg.get("autotune", scope)):
            from .autotune import ConfigAutotuner
            self._tuner = ConfigAutotuner(self)
        if self.journal is not None and \
                int(cfg.get("serving_recover", scope)):
            self.recover()

    # -- request-path tracing ----------------------------------------------
    # (the _raw aliases keep tools/check_spans.py honest: _tspan/_tmark
    # call sites carry the literal names the lint checks, while these
    # forwarding bodies — generic `name` parameters like the spans
    # engine itself — stay off its span-call surface)
    _raw_span = staticmethod(_spans.span)
    _raw_mark = staticmethod(_spans.mark)

    def _tspan(self, name: str, **args):
        """A lifecycle span tagged with request-trace args, or a
        no-op when serving_tracing=0 (the pre-tracing span set)."""
        if not self.tracing:
            return contextlib.nullcontext()
        return self._raw_span(name, annotate=False, args=args)

    def _tmark(self, name: str, **args):
        if self.tracing:
            self._raw_mark(name, args=args)

    def _fr_enqueue(self, kind: str, trace: Optional[str] = None,
                    **fields):
        """Queue a flight event minted while the service lock is held
        (callers: shed / build-failure / quarantine bookkeeping). The
        crash-survival window widens by at most one cycle — the same
        accepted-durable-once-returned model the journal documents."""
        self._fr_q.append((kind, trace, fields))

    def _flush_flightrec(self):
        """Write queued flight events + any deferred BREAKDOWN dump.
        File IO and output-callback work — callers must NOT hold the
        service lock."""
        with self._lock:
            q, self._fr_q = self._fr_q, []
            reason, self._fr_dump_reason = self._fr_dump_reason, None
        for kind, trace, fields in q:
            _fr.record(kind, trace=trace, **fields)
        if reason is not None:
            _fr.dump_recent(reason=reason)

    def _trace_list(self, tickets) -> Optional[List[str]]:
        """trace ids of `tickets` (None entries skipped), or None when
        tracing is off / nothing is tagged — batched stages (step /
        checkpoint / finalize) tag the whole set they touched."""
        if not self.tracing:
            return None
        ids = [t.trace_id for t in tickets
               if t is not None and t.trace_id]
        return ids or None

    def _hlabels(self, tenant: str) -> Dict[str, str]:
        """Labels for this service's histogram observations: tenant
        always, replica only when this service has an identity (so a
        plain single service keeps its historical label shape)."""
        if self.replica:
            return {"tenant": tenant, "replica": self.replica}
        return {"tenant": tenant}

    # -- submission --------------------------------------------------------
    def _tenant(self, name: str) -> Dict[str, int]:
        return self._tenants.setdefault(
            name, {"submitted": 0, "completed": 0, "deadline_miss": 0,
                   "rejected": 0, "shed": 0})

    def submit(self, A: CsrMatrix, b, x0=None, tenant: str = "default",
               deadline_s: Optional[float] = None,
               request_key: Optional[str] = None) -> ServiceTicket:
        """Enqueue one system. `deadline_s` is a relative budget from
        now; expiry completes the ticket with DEADLINE_EXCEEDED rather
        than ever blocking the bucket. `request_key` makes the submit
        idempotent: a retry with the same key returns the live ticket
        (or a fresh ticket completed from the journaled result) instead
        of enqueueing twice. Thread-safe; issues no device work of its
        own and never waits on one — the scheduler's device cycles run
        outside the bookkeeping lock (ROADMAP 3e)."""
        b = np.asarray(b)
        if b.ndim != 1:
            raise BadParametersError(
                f"service.submit: b must be one system's rhs, got "
                f"shape {b.shape}")
        if b.size != A.num_rows * A.block_dimx:
            # caller bug surfaced at the submit site, not as a
            # scheduler-cycle admission failure later
            raise BadParametersError(
                f"service.submit: rhs length {b.size} does not match "
                f"the matrix ({A.num_rows * A.block_dimx} unknowns)")
        if request_key:
            dedup = self._dedupe(request_key)
            if dedup is not None:
                return dedup
        now = _now()
        ticket = ServiceTicket(
            A=A, b=b, x0=None if x0 is None else np.asarray(x0),
            tenant=str(tenant),
            fingerprint=f"{pattern_fingerprint(A)}/{b.dtype}",
            submit_t=now,
            deadline_t=None if deadline_s is None
            else now + float(deadline_s),
            request_key=request_key or None,
            trace_id=_spans.new_trace_id() if self.tracing else None,
            _perf_submit=time.perf_counter())
        _tm.inc("serving.requests")
        # ONE lock section for dedupe-recheck + shed decision + key
        # registration + enqueue: splitting these would let concurrent
        # submits breach the queue bound / tenant quota (check-then-act)
        # or double-enqueue one request_key
        shed_early = False
        with self._tspan("serving.submit", trace=ticket.trace_id,
                         tenant=ticket.tenant), self._lock:
            if request_key:
                live = self._keyed.get(request_key)
                if live is not None:      # lost the race to a twin
                    _tm.inc("serving.dedupe")
                    return live
            self._tenant(ticket.tenant)["submitted"] += 1
            shed = self._shed_reason(ticket, deadline_s)
            if shed is not None:
                reason, est = shed
                self._shed(ticket, reason, est, deadline_s)
                shed_early = True
            else:
                if request_key:
                    self._keyed[request_key] = ticket
                self._queue.append(ticket)
                _tm.set_gauge("serving.queue_depth", len(self._queue))
        # queue-wait epoch starts where the submit span ends: the
        # retroactive serving.queue span then follows serving.submit
        # on the flow chain instead of overlapping it
        ticket._perf_submit = time.perf_counter()
        if shed_early:
            # the shed's flight event (file IO) writes off the lock
            self._flush_flightrec()
            return ticket
        # journal outside the lock (file IO must not block other
        # submitters or the scheduler). The request only counts as
        # accepted-durable once submit() RETURNS — a crash inside this
        # window is indistinguishable from one before the submit. The
        # background scheduler may complete the ticket while we write;
        # the done-check below closes that window so the journal never
        # keeps a pending record for a finished request (which would
        # re-solve it at replay).
        if self.journal is not None:
            try:
                ticket.journal_id = self.journal.record_submit(
                    fingerprint=ticket.fingerprint, tenant=ticket.tenant,
                    A=A, b=b, x0=ticket.x0,
                    deadline_remaining_s=None if deadline_s is None
                    else float(deadline_s),
                    request_key=request_key or None,
                    trace_id=ticket.trace_id)
                if ticket.done:
                    self._journal_done(ticket, ticket.result)
            except Exception:
                # durability degraded, service continues: the request
                # is live in memory, only crash replay is lost for it
                _tm.inc("serving.recovery.journal_corrupt")
        return ticket

    def _dedupe(self, request_key: str) -> Optional[ServiceTicket]:
        """Idempotent-submit lookup: the live ticket with this key, or
        a fresh ticket completed from the journaled result of an
        already-finished request. None = genuinely new."""
        with self._lock:
            live = self._keyed.get(request_key)
        if live is not None:
            _tm.inc("serving.dedupe")
            return live
        if self.journal is None:
            return None
        rec = self.journal.lookup_key(request_key)
        if rec is None or rec.get("status") != "done":
            return None
        res = self.journal.load_result(rec["id"])
        if res is None:
            return None
        x, status_code, iterations = res
        _tm.inc("serving.dedupe")
        now = _now()
        t = ServiceTicket(
            A=None, b=np.asarray(x), x0=None,
            tenant=rec.get("tenant", "default"),
            fingerprint=rec.get("fingerprint", ""), submit_t=now,
            deadline_t=None, request_key=request_key,
            # same knob gate as recover(): a serving_tracing=0
            # incarnation hands out no trace ids, journaled or not
            trace_id=rec.get("trace") if self.tracing else None)
        t._complete(SolveResult(
            x=np.asarray(x), iterations=int(iterations),
            converged=status_code == int(SolveStatus.CONVERGED),
            res_norm=np.asarray(np.nan), norm0=np.asarray(np.nan),
            status_code=int(status_code)))
        return t

    # -- load shedding -----------------------------------------------------
    def _shed_reason(self, t: ServiceTicket,
                     deadline_s: Optional[float]
                     ) -> Optional[Tuple[str, Optional[float]]]:
        """Admission control (lock held): None = admit, else (shed
        class, feasibility estimate): 'overload' queue bound / 'quota'
        tenant fairness / 'deadline' unmeetable-by-estimate — the
        estimate rides along so the shed decision is auditable (the
        flight recorder logs it with the decision)."""
        if self.max_queue and len(self._queue) >= self.max_queue:
            return "overload", None
        if self.tenant_quota:
            live = sum(1 for q in self._queue if q.tenant == t.tenant)
            for key in self.buckets.keys():
                eng = self.buckets.peek(key)
                if eng is None:
                    continue
                live += sum(1 for o in eng.occupant
                            if o is not None and getattr(o, "tenant", None)
                            == t.tenant)
            if live >= self.tenant_quota:
                return "quota", None
        if self.shed_policy == "deadline" and deadline_s is not None:
            est = self._estimate_latency_s(t.fingerprint)
            if est is not None and float(deadline_s) < est:
                return "deadline", est
        return None

    def _estimate_latency_s(self, fingerprint: Optional[str] = None
                            ) -> Optional[float]:
        """Deadline-feasibility estimate: the MEDIAN of the request's
        OWN fingerprint's recent in-bucket execution times when that
        window is trained (mixed-size traffic: the small tenant's
        tight deadline is judged on the small tenant's history, not a
        global median a co-resident 256^3 tenant drags up), falling
        back to the service-wide window (a bounded deque, so one
        cold-bucket trace outlier washes out and a restarted service
        retrains within a few requests; the process-wide
        serving.exec_s histogram p50 is the fallback before the window
        fills) scaled by how many queue 'waves' are ahead (queue
        depth over slot capacity), plus a 25% safety margin so
        admitted work keeps its deadline promise. None while fully
        untrained — an untrained estimator must never shed."""
        fpw = self._exec_fp.get(fingerprint) \
            if fingerprint is not None else None
        if fpw is not None and len(fpw) >= 3:
            window = sorted(fpw)
            est = window[len(window) // 2]
        elif len(self._exec_recent) >= 3:
            window = sorted(self._exec_recent)
            est = window[len(window) // 2]
        elif self.replica:
            # in-process fleet: train from THIS replica's labeled
            # series, not the registry-wide aggregate a co-resident
            # replica also feeds
            est = _tm.quantile_where("serving.exec_s", 0.50,
                                     {"replica": self.replica})
        else:
            est = _tm.quantile("serving.exec_s", 0.50)
        if est is None or est <= 0:
            return None
        cap = 0
        for key in self.buckets.keys():
            eng = self.buckets.peek(key)
            if eng is not None:
                cap += eng.slots
        cap = max(cap, self.slots, 1)
        return 1.25 * (1.0 + len(self._queue) / cap) * float(est) \
            * self.exec_share

    _SHED_COUNTERS = {"overload": "serving.shed.overload",
                      "quota": "serving.shed.quota",
                      "deadline": "serving.shed.deadline"}

    def _shed(self, t: ServiceTicket, reason: str,
              estimate_s: Optional[float] = None,
              deadline_s: Optional[float] = None):
        """Complete without solving: OVERLOADED + the initial iterate
        (the early honest rejection — admitted work keeps its deadline
        promise, unserviceable work finds out immediately). The
        decision is auditable: an instant span on the request's flow
        chain and a flight-recorder event carrying the feasibility
        estimate it was made on."""
        x = t.x0 if t.x0 is not None else np.zeros_like(t.b)
        _tm.inc("serving.rejected")
        _tm.inc(self._SHED_COUNTERS[reason])
        self._tmark("serving.shed", trace=t.trace_id, reason=reason,
                    estimate_s=estimate_s)
        self._fr_enqueue("shed", trace=t.trace_id, reason=reason,
                         tenant=t.tenant,
                         estimate_s=None if estimate_s is None
                         else round(float(estimate_s), 6),
                         deadline_s=None if deadline_s is None
                         else round(float(deadline_s), 6),
                         queue_depth=len(self._queue))
        tt = self._tenant(t.tenant)
        tt["rejected"] += 1
        tt["shed"] += 1
        self._finish(t, SolveResult(
            x=x, iterations=0, converged=False,
            res_norm=np.asarray(np.inf), norm0=np.asarray(np.inf),
            status_code=int(SolveStatus.OVERLOADED)))

    def _reject(self, t: ServiceTicket):
        """Complete without solving: the initial iterate and a
        DEADLINE_EXCEEDED status (queued expiry, or the
        reject-on-deadline action)."""
        x = t.x0 if t.x0 is not None else np.zeros_like(t.b)
        _tm.inc("serving.rejected")
        _tm.inc("serving.deadline_miss")
        _tm.inc("serving.deadline_action.reject")
        self._fr_enqueue("deadline.miss", trace=t.trace_id,
                         tenant=t.tenant, where="queued")
        tt = self._tenant(t.tenant)
        tt["rejected"] += 1
        tt["deadline_miss"] += 1
        self._finish(t, SolveResult(
            x=x, iterations=0, converged=False,
            res_norm=np.asarray(np.inf), norm0=np.asarray(np.inf),
            status_code=int(SolveStatus.DEADLINE_EXCEEDED)))

    def _finish(self, t: ServiceTicket, result: SolveResult):
        _tm.inc("serving.completed")
        self._tenant(t.tenant)["completed"] += 1
        self._completed_total += 1
        t._complete(result)
        # the flow chain's terminal anchor: finalize/complete, tagged
        # with the trace id minted at submit (or restored from the
        # journal — linking both incarnations' spans)
        self._tmark("serving.complete", trace=t.trace_id,
                    status=getattr(result, "status", None),
                    iterations=int(result.iterations))
        if t.request_key:
            self._keyed.pop(t.request_key, None)
        # per-tenant solve-latency distribution: recorded for EVERY
        # terminal status (a deadline miss is latency the caller saw
        # too) so the p50/p99 the scrape reports are honest
        _tm.observe("serving.solve_latency_s",
                    t.complete_t - t.submit_t,
                    labels=self._hlabels(t.tenant))
        if t.admit_t is not None:
            # the in-bucket half: what the shed estimator reads
            exec_s = t.complete_t - t.admit_t
            _tm.observe("serving.exec_s", exec_s,
                        labels=self._hlabels(t.tenant))
            self._exec_recent.append(exec_s)
            fpw = self._exec_fp.get(t.fingerprint)
            if fpw is None:
                import collections
                fpw = collections.deque(maxlen=64)
                self._exec_fp[t.fingerprint] = fpw
            fpw.append(exec_s)
            if self._tuner is not None:
                self._tuner.note_finish(t, exec_s)
        if t.journal_id is not None \
                and self._journal_for(t) is not None:
            # queued, not written: _finish runs under the service lock
            # and journal completion is file IO (the whole solution
            # vector) — the scheduler flushes the queue outside the
            # lock at the end of the cycle (lock-split contract)
            self._journal_doneq.append(t)

    def _fail_ticket(self, t: ServiceTicket, err: Exception):
        """Complete a ticket whose bucket build or admission raised:
        BREAKDOWN status + the exception on ticket.error — never a
        wedged queue or a scheduler-killing raise. The flight
        recorder's last-N events dump through the output callback:
        a BREAKDOWN is exactly the moment the event trail leading up
        to it is worth reading."""
        t.error = err
        _tm.inc("serving.rejected")
        self._tenant(t.tenant)["rejected"] += 1
        self._fr_enqueue("ticket.breakdown", trace=t.trace_id,
                         tenant=t.tenant, error=str(err)[:160])
        self._finish(t, SolveResult(
            x=np.zeros_like(t.b), iterations=0, converged=False,
            res_norm=np.asarray(np.inf), norm0=np.asarray(np.inf),
            status_code=int(SolveStatus.BREAKDOWN)))
        if self._fr_dump_reason is None:   # first failure names the dump
            self._fr_dump_reason = f"BREAKDOWN: {str(err)[:80]}"

    # -- crash recovery ----------------------------------------------------
    def recover(self) -> int:
        """Replay the journal (called automatically at construction
        when `serving_recover=1` and a journal is configured): every
        pending record re-enters the queue — resuming from its last
        checkpoint when one exists — with its remaining deadline
        budget re-anchored to the current clock. Corrupt records are
        dropped and counted; they can never wedge the replay."""
        if self.journal is None:
            return 0
        n = 0
        for meta in self.journal.pending():
            loaded = self.journal.load_request(meta)
            if loaded is None:
                self.journal.forget(meta["id"])
                continue
            A, b, x0, state, remaining = loaded
            now = _now()
            t = ServiceTicket(
                A=A, b=np.asarray(b),
                x0=None if x0 is None else np.asarray(x0),
                tenant=meta.get("tenant", "default"),
                fingerprint=meta["fingerprint"], submit_t=now,
                deadline_t=None if remaining is None
                else now + float(remaining),
                request_key=meta.get("key"),
                # the ORIGINAL trace id, persisted at submit: this
                # incarnation's spans join the dead process's flow
                # chain instead of starting an orphan one. Gated on
                # THIS incarnation's knob: a serving_tracing=0
                # successor must keep its pre-tracing span set even
                # for requests a tracing predecessor journaled
                trace_id=(meta.get("trace") or _spans.new_trace_id())
                if self.tracing else None,
                _perf_submit=time.perf_counter())
            t.journal_id = meta["id"]
            t.resume_state = state
            _tm.inc("serving.recovery.replayed")
            self._tmark("serving.resume", trace=t.trace_id,
                        journal_id=t.journal_id,
                        checkpointed=state is not None)
            t._perf_submit = time.perf_counter()
            with self._lock:
                self._tenant(t.tenant)["submitted"] += 1
                if t.request_key:
                    self._keyed[t.request_key] = t
                self._queue.append(t)
            n += 1
        with self._lock:
            _tm.set_gauge("serving.queue_depth", len(self._queue))
        self.journal.prune()       # bound the done-record history
        return n

    def adopt_journal(self, journal: SolveJournal,
                      skip=frozenset()) -> int:
        """Cross-replica recover(): replay ANOTHER replica's journal
        into this service's queue (fleet failover — the survivor
        adopting a dead replica's journal dir). Same machinery as
        recover(), with the failover deltas: tickets carry
        `journal_ref` pointing at the ADOPTED journal (checkpoints and
        completions settle the dead replica's records, never this
        service's), deadlines re-anchor as REMAINING budget against
        this adopter's service_now(), trace ids stay the originals,
        and `skip` excludes records whose live ticket the router
        already moved over (nothing double-solves). request_key dedupe
        guards the rest: a key already live here means the record's
        work is present, so its replay is skipped too."""
        n = 0
        for meta in journal.pending():
            if meta["id"] in skip:
                continue
            key = meta.get("key")
            if key:
                with self._lock:
                    if key in self._keyed:
                        continue
            loaded = journal.load_request(meta)
            if loaded is None:
                journal.forget(meta["id"])
                continue
            A, b, x0, state, remaining = loaded
            now = _now()
            t = ServiceTicket(
                A=A, b=np.asarray(b),
                x0=None if x0 is None else np.asarray(x0),
                tenant=meta.get("tenant", "default"),
                fingerprint=meta["fingerprint"], submit_t=now,
                deadline_t=None if remaining is None
                else now + float(remaining),
                request_key=key,
                trace_id=(meta.get("trace") or _spans.new_trace_id())
                if self.tracing else None,
                _perf_submit=time.perf_counter())
            t.journal_id = meta["id"]
            t.journal_ref = journal
            t.resume_state = state
            _tm.inc("serving.recovery.replayed")
            _tm.inc("fleet.health.adopted")
            self._tmark("serving.resume", trace=t.trace_id,
                        journal_id=t.journal_id,
                        checkpointed=state is not None)
            with self._lock:
                self._tenant(t.tenant)["submitted"] += 1
                if t.request_key:
                    self._keyed[t.request_key] = t
                self._queue.append(t)
            n += 1
        with self._lock:
            _tm.set_gauge("serving.queue_depth", len(self._queue))
        return n

    def _journal_for(self, t: ServiceTicket) -> Optional[SolveJournal]:
        """The journal holding this ticket's pending record: its
        adopted journal_ref when a fleet failover moved it here, else
        this service's own."""
        return t.journal_ref if t.journal_ref is not None \
            else self.journal

    def _journal_done(self, t: ServiceTicket, result: SolveResult):
        """Persist one completed ticket's journal result. File IO —
        callers must NOT hold the service lock."""
        try:
            self._journal_for(t).record_done(
                t.journal_id, np.asarray(result.x),
                int(result.status_code), int(result.iterations))
        except Exception:
            _tm.inc("serving.recovery.journal_corrupt")

    def _flush_journal_done(self):
        with self._lock:
            flush, self._journal_doneq = self._journal_doneq, []
        for t in flush:
            self._journal_done(t, t.result)

    def _checkpoint(self):
        """Journal the solve state of every journaled in-flight slot
        (serving_checkpoint_cycles cadence). Device pulls + file IO,
        all outside the service lock."""
        from ..profiling import trace_region
        with self._lock:
            busy = [self.buckets.peek(k) for k in self.buckets.keys()]
        ck_tickets = [
            eng.occupant[j]
            for eng in busy if eng is not None and not eng.idle
            for j in range(eng.slots)
            if eng.occupant[j] is not None
            and getattr(eng.occupant[j], "journal_id", None) is not None]
        ck_traces = self._trace_list(ck_tickets)
        with trace_region("serving.checkpoint",
                          args={"traces": ck_traces}
                          if ck_traces else None):
            for eng in busy:
                if eng is None or eng.idle:
                    continue
                slots = [j for j in range(eng.slots)
                         if eng.occupant[j] is not None
                         and getattr(eng.occupant[j], "journal_id",
                                     None) is not None]
                if not slots:
                    continue
                try:
                    rows = eng.state_rows(slots)
                except Exception:
                    continue          # device trouble: supervisor's job
                now = _now()
                for j in slots:
                    t = eng.occupant[j]
                    if t is None or t.done:
                        continue      # settled while we pulled
                    remaining = None if t.deadline_t is None \
                        else max(0.0, t.deadline_t - now)
                    try:
                        self._journal_for(t).record_checkpoint(
                            t.journal_id, rows[j], remaining)
                    except Exception:
                        _tm.inc("serving.recovery.journal_corrupt")

    # -- service-level fault policy ---------------------------------------
    def _fault_action(self, fp: str, event: str) -> str:
        """Next action for this fingerprint's failure chain (lock
        held): consults serving_fault_policy, bounded by
        serving_retry_max_attempts (beyond which: reject)."""
        fl = self._faulted.setdefault(
            fp, {"attempts": 0, "not_before": 0.0})
        n = int(fl["attempts"])
        fl["attempts"] = n + 1
        chain = self._svc_policy.get(event) or ["reject"]
        if n >= self.retry_max:
            return "reject"
        action = chain[min(n, len(chain) - 1)]
        if action == "retry_backoff":
            fl["not_before"] = _now() + \
                self.retry_backoff_s * (2.0 ** n)
        return action

    def _handle_build_failure(self, fp: str, err: Exception,
                              completed: List[ServiceTicket]):
        """Build failed (lock held): reject the fingerprint's queued
        tickets, or leave them queued behind a bounded backoff."""
        action = self._fault_action(fp, "BUILD_FAILED")
        self._fr_enqueue("bucket.build_failed", fingerprint=fp[:24],
                         action=action, error=str(err)[:160])
        if action == "reject":
            self._faulted.pop(fp, None)
            still = []
            for t in self._queue:
                if t.fingerprint == fp:
                    self._fail_ticket(t, err)
                    completed.append(t)
                else:
                    still.append(t)
            self._queue = still
        else:
            _tm.inc("serving.recovery.build_retries")
            self._fr_enqueue("bucket.build_retry", fingerprint=fp[:24],
                             attempts=int(self._faulted.get(
                                 fp, {}).get("attempts", 0)))

    def _quarantine(self, key: str, eng: BucketEngine, err, event: str,
                    completed: List[ServiceTicket]):
        """Remove a failed/wedged bucket from service (lock held):
        finalize the slots whose state already carries a terminal
        done-flag (salvageable — their iterate is complete), requeue
        the rest with their live solve state as the resume point, and
        route the rebuild through the fault policy.

        The salvage pulls here are device work under the lock — a
        deliberate exception to the lock split: quarantine is the rare
        failure path, and dismantling a bucket must be atomic with the
        admission bookkeeping (a concurrent submit must never observe
        a half-quarantined engine as admittable)."""
        from ..profiling import trace_region
        _tm.inc("serving.recovery.quarantined")
        self._fr_enqueue("bucket.quarantine", fingerprint=key[:24],
                         event=event, error=None if err is None
                         else str(err)[:160],
                         inflight=sum(1 for o in eng.occupant
                                      if o is not None))
        with trace_region("serving.quarantine"):
            occupied = [j for j in range(eng.slots)
                        if eng.occupant[j] is not None]
            try:
                rows = eng.state_rows(occupied)
            except Exception:
                rows = None
            salvage = [] if rows is None else \
                [j for j in occupied if bool(rows[j].get("done", False))]
            results = {}
            if salvage:
                try:
                    results = eng.finalize(salvage)
                except Exception:
                    results = {}
            requeue_tickets = []
            for j in occupied:
                t = eng.occupant[j]
                eng.occupant[j] = None
                if j in results:
                    _tm.inc("serving.recovery.salvaged")
                    self._fr_enqueue("slot.salvage", trace=t.trace_id,
                                     fingerprint=key[:24], slot=j)
                    self._finish(t, results[j])
                    completed.append(t)
                    continue
                if rows is not None:
                    t.resume_state = rows[j]
                t.admit_t = None
                _tm.inc("serving.recovery.requeued")
                self._fr_enqueue("slot.requeue", trace=t.trace_id,
                                 fingerprint=key[:24], slot=j,
                                 has_state=rows is not None)
                requeue_tickets.append(t)
            self.buckets.pop(key)
            self._progress.pop(key, None)
            error = err if err is not None else \
                RuntimeError(f"serving: bucket {event.lower()}")
            action = self._fault_action(key, event)
            if action == "reject":
                self._faulted.pop(key, None)
                for t in requeue_tickets:
                    self._fail_ticket(t, error)
                    completed.append(t)
            else:
                # front of the queue: they were in flight already
                self._queue = requeue_tickets + self._queue

    # -- scheduling --------------------------------------------------------
    def _slots_for(self, t: ServiceTicket) -> int:
        """Bucket width for the build `t` triggers: the ladder rung
        fitting the queued same-fingerprint demand at build time (the
        queue composition — `t` itself is still queued), or the fixed
        serving_bucket_slots width when no ladder is configured."""
        if not self.ladder:
            return self.slots
        with self._lock:
            pending = sum(1 for q in self._queue
                          if q.fingerprint == t.fingerprint)
        return choose_slots(self.ladder, pending, self.slots)

    def _build_engine(self, t: ServiceTicket) -> BucketEngine:
        """One bucket build, wrapped in a serving.build span tagged
        with the TRIGGERING ticket's trace (the build serves every
        same-fingerprint ticket, but the oldest unserved one caused
        it) and logged on the flight recorder."""
        slots = self._slots_for(t)
        # tuned-config overlay: a promoted (or hstore-restored)
        # fingerprint builds its bucket from the service config PLUS
        # the tuner's deltas — real AMG knobs, so the engine's
        # hstore/AOT keys change with them and a restarted replica
        # restores the TUNED hierarchy (zero full setups)
        cfg, tuned = self.cfg, None
        if self._tuner is not None:
            tuned = self._tuner.overlay_for(t.fingerprint)
            if tuned is not None:
                cfg = self._tuner.apply_overlay(self.cfg, tuned)
                _tm.inc("autotune.overlay.applied")
        with self._tspan("serving.build", trace=t.trace_id,
                         fingerprint=t.fingerprint[:24], slots=slots):
            eng = BucketEngine(
                cfg, self.scope, t.A, slots=slots,
                chunk=self.chunk, dtype=t.b.dtype,
                fingerprint=t.fingerprint, aot=self.aot,
                hstore=self.hstore)
        _fr.record("bucket.build", trace=t.trace_id,
                   fingerprint=t.fingerprint[:24],
                   slots=eng.slots,
                   wall_s=round(eng.build_time, 4),
                   aot_warm=eng.aot_warm,
                   hier_restored=eng.hier_restored,
                   tuned=tuned is not None)
        return eng

    def _builder(self, t: ServiceTicket):
        """Builder-thread body: one bucket build off the scheduler
        cycle, so in-flight buckets keep advancing during the seconds
        a cold fingerprint's setup + traces take."""
        try:
            eng = self._build_engine(t)
        except Exception as e:            # surfaced by the next step()
            with self._lock:
                self._build_failed[t.fingerprint] = e
                self._builds.pop(t.fingerprint, None)
            return
        with self._lock:
            self._built[t.fingerprint] = eng
            self._builds.pop(t.fingerprint, None)

    def step(self) -> List[ServiceTicket]:
        """One scheduler cycle: expire, build/install missing buckets,
        admit, advance, finalize, checkpoint. Returns the tickets
        completed this cycle. ALL device work — bucket builds,
        admission resetups, chunk stepping, finalize pulls — runs
        outside the service lock (ROADMAP 3e), so a concurrent
        submit() only ever contends with bookkeeping. Cycles
        themselves are serialized (one step() at a time). Driven
        synchronously (no start()), builds run inline — one per cycle,
        for the oldest unserved ticket — which keeps step()
        deterministic for tests."""
        # fleet-level chaos hooks, BEFORE the cycle lock and BEFORE
        # the cycle counter: replica_kill raises out of step() (the
        # background loop captures it and dies, an inline fleet's
        # router captures it — either way the health monitor sees a
        # dead scheduler); replica_wedge returns without advancing
        # _cycle (the heartbeat flatline); replica_slow stalls the
        # cycle so per-cycle wall blows the pace threshold
        delay = _fi.replica_delay(self.replica)
        if delay > 0.0:
            time.sleep(delay)
        if _fi.replica_wedged(self.replica):
            return []
        _fi.replica_crash(self.replica)
        with self._sched_lock:
            return self._step_impl()

    def _step_impl(self) -> List[ServiceTicket]:
        completed: List[ServiceTicket] = []
        self._cycle += 1
        cand = None
        with self._lock:
            now = _now()
            # 1. queued expiry: a request that died waiting never
            # touches a slot
            still = []
            for t in self._queue:
                if t.deadline_t is not None and now >= t.deadline_t:
                    self._reject(t)
                    completed.append(t)
                else:
                    still.append(t)
            self._queue = still
            # 2a. install builder-thread results; route failed builds
            # through the fault policy (reject / bounded retry)
            for fp in list(self._built):
                eng = self._built.pop(fp)
                if self.buckets.peek(fp) is None:
                    self.buckets.put(fp, eng,
                                     nbytes=solve_data_bytes(eng))
                # NOTE: the fault-attempt counter is NOT reset here —
                # a successful build proves nothing about stepping (a
                # deterministically crashing bucket rebuilds fine
                # every time); only a terminal completion (settle
                # phase) clears it, so serving_retry_max_attempts
                # actually bounds STEP_FAILED/WEDGED loops too
                fl = self._faulted.get(fp)
                if fl is not None:
                    fl["not_before"] = 0.0
            if self._build_failed:
                failed = dict(self._build_failed)
                self._build_failed.clear()
                for fp, err in failed.items():
                    self._handle_build_failure(fp, err, completed)
            # 2b. pick at most ONE new build per cycle, for the OLDEST
            # unserved ticket (building every missing bucket up front
            # would serialize all setups ahead of all progress);
            # fingerprints inside a retry backoff window are skipped
            for t in self._queue:
                fp = t.fingerprint
                if self.buckets.peek(fp) is not None \
                        or fp in self._builds:
                    continue
                fl = self._faulted.get(fp)
                if fl is not None and fl["not_before"] > now:
                    continue
                cand = t
                break
            if cand is not None:
                if not cand.cache_counted:
                    _tm.inc("serving.cache.miss")
                    cand.cache_counted = True
                if self._thread is not None:
                    th = threading.Thread(
                        target=self._builder, args=(cand,),
                        daemon=True, name="amgx-serving-build")
                    self._builds[cand.fingerprint] = th
                    th.start()
                    cand = None           # admission catches up later
        # 3. synchronous-mode build: inline, outside the lock; a build
        # failure routes through the fault policy exactly like the
        # threaded path (never a raise out of step(), never an
        # unbounded retry)
        if cand is not None:
            try:
                eng = self._build_engine(cand)
            except Exception as e:
                with self._lock:
                    self._handle_build_failure(cand.fingerprint, e,
                                               completed)
                eng = None
            if eng is not None:
                with self._lock:
                    if self.buckets.peek(cand.fingerprint) is None:
                        self.buckets.put(cand.fingerprint, eng,
                                         nbytes=solve_data_bytes(eng))
                    fl = self._faulted.get(cand.fingerprint)
                    if fl is not None:      # see step 2a note
                        fl["not_before"] = 0.0
        # 4. admission DECISIONS under the lock (slot reservations —
        # strictly oldest-first across ALL buckets, the fairness
        # contract), device splices outside it
        admissions: List[Tuple[BucketEngine, int, ServiceTicket]] = []
        with self._lock:
            blocked = set()
            remaining = []
            for t in self._queue:
                if t.fingerprint in blocked:
                    remaining.append(t)
                    continue
                eng = self.buckets.get(t.fingerprint)   # LRU touch
                if eng is None:
                    # not built yet / evicted under a tiny byte budget
                    # or raced an eviction: retry next cycle
                    blocked.add(t.fingerprint)
                    remaining.append(t)
                    continue
                slot = eng.free_slot()
                if slot is None:
                    blocked.add(t.fingerprint)
                    remaining.append(t)
                    continue
                if not t.cache_counted:
                    _tm.inc("serving.cache.hit")
                    t.cache_counted = True
                t.admit_t = _now()
                _tm.observe("serving.queue_wait_s",
                            t.admit_t - t.submit_t,
                            labels=self._hlabels(t.tenant))
                if self.tracing and t.trace_id:
                    # the queue wait, recorded retroactively now that
                    # it is known — the flow chain's submit->admit gap
                    # becomes a visible slice instead of dead air. On
                    # a synthetic per-request lane: on this scheduler
                    # thread's real track it would partially overlap
                    # the open cycle slices (same-track slices must
                    # nest in the Chrome trace format)
                    pnow = time.perf_counter()
                    _spans.record_span(
                        "serving.queue", t._perf_submit,
                        max(0.0, pnow - t._perf_submit),
                        args={"trace": t.trace_id,
                              "tenant": t.tenant},
                        tid=_spans.trace_track(t.trace_id))
                eng.occupant[slot] = t      # reservation
                admissions.append((eng, slot, t))
            self._queue = remaining
        # 5. the admission device work (value-resetup splice + state
        # init/restore) — outside the lock
        admit_failed: List[Tuple[ServiceTicket, Exception]] = []
        for eng, slot, t in admissions:
            try:
                if t.resume_state is not None:
                    try:
                        eng.admit_resume(slot, t.A, t.b,
                                         t.resume_state, occupant=t)
                        _tm.inc("serving.recovery.resumed")
                    except BadParametersError:
                        # layout drifted (config change across the
                        # restart): restart the solve clean
                        _tm.inc("serving.recovery.restart_fresh")
                        t.resume_state = None
                        eng.admit(slot, t.A, t.b, x0=t.x0, occupant=t)
                else:
                    eng.admit(slot, t.A, t.b, x0=t.x0, occupant=t)
            except Exception as e:
                # bad request (rhs length, structure drift): complete
                # THIS ticket with the error — an admission raise must
                # never wedge the queue or kill the scheduler
                eng.release(slot)
                admit_failed.append((t, e))
        # 6. advance every busy bucket one cycle — the device work the
        # lock split exists for — then the finalize pulls, all outside
        # the lock (engines are only ever touched by the scheduler)
        with self._lock:
            busy = [(k, self.buckets.peek(k))
                    for k in self.buckets.keys()]
        outcomes = []   # (key, eng, terminal, expired, results, err)
        for key, eng in busy:
            if eng is None or eng.idle:
                continue
            try:
                terminal = set(eng.step())
            except Exception as e:
                outcomes.append((key, eng, set(), [], {}, e))
                continue
            now = _now()
            expired = [
                j for j in range(eng.slots)
                if eng.occupant[j] is not None
                and j not in terminal
                and getattr(eng.occupant[j], "deadline_t", None)
                is not None
                and now >= eng.occupant[j].deadline_t]
            try:
                results = eng.finalize(sorted(terminal) + expired)
            except Exception as e:
                outcomes.append((key, eng, set(), [], {}, e))
                continue
            outcomes.append((key, eng, terminal, expired, results,
                             None))
        # 7. settle under the lock: complete tickets, wedge heartbeat,
        # quarantine, eviction, gauges
        with self._lock:
            for t, e in admit_failed:
                self._fail_ticket(t, e)
                completed.append(t)
            for key, eng, terminal, expired, results, err in outcomes:
                if err is not None:
                    self._quarantine(key, eng, err, "STEP_FAILED",
                                     completed)
                    continue
                # progress heartbeat: a busy bucket that neither
                # finished a slot nor advanced an iteration counter is
                # wedging; `supervisor_cycles` consecutive flatlines
                # quarantine it
                if self.supervisor_cycles and not terminal \
                        and not expired and not eng.idle:
                    beat = -1 if eng.iters_snapshot is None \
                        else int(np.sum(eng.iters_snapshot))
                    last, stale = self._progress.get(key, (None, 0))
                    stale = stale + 1 if beat == last else 0
                    self._progress[key] = (beat, stale)
                    if stale >= self.supervisor_cycles:
                        self._quarantine(key, eng, None, "WEDGED",
                                         completed)
                        continue
                else:
                    self._progress.pop(key, None)
                if terminal:
                    # proven healthy: the bucket ran a solve to a
                    # terminal status — THIS clears the fault-attempt
                    # counter (not a mere successful rebuild)
                    self._faulted.pop(key, None)
                for j in sorted(terminal):
                    t = eng.occupant[j]
                    eng.release(j)
                    self._finish(t, results[j])
                    completed.append(t)
                for j in expired:
                    t = eng.occupant[j]
                    eng.release(j)
                    res = results[j]
                    _tm.inc("serving.deadline_miss")
                    self._fr_enqueue(
                        "deadline.miss", trace=t.trace_id,
                        tenant=t.tenant, where="inflight",
                        action=self.deadline_action)
                    self._tenant(t.tenant)["deadline_miss"] += 1
                    res.converged = False
                    res.status_code = int(
                        SolveStatus.DEADLINE_EXCEEDED)
                    if self.deadline_action == "reject":
                        _tm.inc("serving.deadline_action.reject")
                        res.x = np.zeros_like(t.b) if t.x0 is None \
                            else t.x0
                    else:
                        _tm.inc("serving.deadline_action.partial")
                    self._finish(t, res)
                    completed.append(t)
                self.buckets.set_bytes(key, solve_data_bytes(eng))
            self.buckets.evict_to_budget()
            _tm.set_gauge("serving.queue_depth", len(self._queue))
            _tm.set_gauge("serving.inflight", self._inflight())
        # 8. journal completions + flight events + checkpoint cadence
        # + periodic prune (device pulls + file IO, all outside the
        # lock)
        self._flush_flightrec()
        self._flush_journal_done()
        if self.journal is not None and self.ckpt_cycles > 0 \
                and self._cycle % self.ckpt_cycles == 0:
            self._checkpoint()
        if self.journal is not None and self._cycle % 512 == 0:
            self.journal.prune()
        # the tuner's tick rides the off-lock tail too: at most one
        # shadow solve, and only when the service has idle capacity
        # (never while draining — drain() quiesces it first)
        if self._tuner is not None and not self._draining:
            self._tuner.maybe_step()
        return completed

    def _inflight(self) -> int:
        # tolerant of concurrent eviction (called lock-free from the
        # scheduler loop's pacing check)
        engines = (self.buckets.peek(k) for k in self.buckets.keys())
        return sum(e.inflight for e in engines if e is not None)

    @property
    def idle(self) -> bool:
        with self._lock:
            return (not self._queue and self._inflight() == 0
                    and not self._builds and not self._built)

    @property
    def completed_total(self) -> int:
        """Requests completed over the service lifetime (any terminal
        status) — the mode-independent progress counter the C API's
        drain reports deltas of."""
        return self._completed_total

    def drain(self, timeout_s: Optional[float] = None
              ) -> List[ServiceTicket]:
        """Step until every queued and in-flight request completed (or
        the timeout elapsed). Driven inline (no background thread) the
        return value lists the tickets completed during this call;
        with the background scheduler running it only WAITS and
        returns [] — use `completed_total` deltas (or the tickets you
        hold) for counts in that mode."""
        t0 = time.monotonic()
        done: List[ServiceTicket] = []
        # quiesce the tuner for the duration: drain waits on
        # PRODUCTION work only, so no new shadow solves may start
        # while it runs (search state is kept; the search resumes
        # after). _draining also gates the background scheduler's
        # tuner tick, which reads the flag per cycle.
        self._draining = True
        if self._tuner is not None:
            self._tuner.quiesce()
        try:
            while not self.idle:
                if timeout_s is not None \
                        and time.monotonic() - t0 > timeout_s:
                    break
                if self._thread is not None:
                    if self._thread_error is not None \
                            and not self._thread.is_alive():
                        # the background scheduler died: nothing will
                        # ever step this work — surface the captured
                        # exception on the outstanding tickets
                        # (BREAKDOWN + ticket.error) instead of
                        # spinning to timeout
                        done.extend(
                            self._fail_outstanding(self._thread_error))
                        break
                    time.sleep(0.001)
                else:
                    done.extend(self.step())
        finally:
            self._draining = False
            if self._tuner is not None:
                self._tuner.resume()
        return done

    def _fail_outstanding(self, err: BaseException
                          ) -> List[ServiceTicket]:
        """Complete every queued and in-flight ticket BREAKDOWN with
        `err` on ticket.error — the dead-scheduler terminal path (a
        drain must never wait on work nothing will ever step). Slots
        are released so the service reads idle afterwards. Shared by
        the standalone drain above and the FleetRouter's no-survivor
        failover."""
        with self._lock:
            victims = list(self._queue)
            self._queue = []
            self._builds.clear()
            self._built.clear()
            self._build_failed.clear()
            engines = [self.buckets.peek(k)
                       for k in self.buckets.keys()]
        for eng in engines:
            if eng is None:
                continue
            for j in range(eng.slots):
                t = eng.occupant[j]
                if t is None:
                    continue
                try:
                    eng.release(j)
                except Exception:
                    eng.occupant[j] = None
                if not t.done:
                    victims.append(t)
        with self._lock:
            for t in victims:
                self._fail_ticket(t, err)
        self._flush_flightrec()
        self._flush_journal_done()
        return victims

    # -- background scheduler ---------------------------------------------
    def start(self, poll_s: float = 0.0005):
        """Run the scheduler on a daemon thread: submit() from any
        thread, await tickets with ticket.wait()."""
        if self._thread is not None:
            return
        self._stopping = False
        self._thread_error = None

        def loop():
            while not self._stopping:
                try:
                    if self.idle:
                        time.sleep(poll_s)
                        continue
                    done = self.step()
                    if not done and self._inflight() == 0:
                        # nothing advanced: only waiting on builder
                        # threads or a retry backoff window — don't
                        # spin the scheduler hot
                        time.sleep(poll_s)
                except Exception as e:
                    # the scheduler thread must never die SILENTLY: a
                    # captured exception is the fleet health monitor's
                    # REPLICA_DEAD signal (and a standalone service's
                    # drain surfaces it instead of spinning forever)
                    self._thread_error = e
                    _fr.record("scheduler.died",
                               replica=self.replica or None,
                               error=str(e)[:160])
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="amgx-serving")
        self._thread.start()

    def stop(self):
        if self._thread is None:
            return
        self._stopping = True
        self._thread.join()
        self._thread = None

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "queue_depth": len(self._queue),
                "inflight": self._inflight(),
                "live_buckets": len(self.buckets),
                "cache_bytes": self.buckets.total_bytes,
                "evictions": self.buckets.evictions,
                # live latency quantiles from the process-wide
                # histograms (all tenants aggregated; per-tenant
                # series live in metrics.snapshot()/OpenMetrics)
                "solve_latency_p50_s":
                    _tm.quantile("serving.solve_latency_s", 0.50),
                "solve_latency_p99_s":
                    _tm.quantile("serving.solve_latency_s", 0.99),
                "queue_wait_p50_s":
                    _tm.quantile("serving.queue_wait_s", 0.50),
                "queue_wait_p99_s":
                    _tm.quantile("serving.queue_wait_s", 0.99),
                "exec_p99_s": _tm.quantile("serving.exec_s", 0.99),
                "journal_pending":
                    0 if self.journal is None
                    else len(self.journal.pending()),
                "quarantined_fingerprints": len(self._faulted),
                "replica": self.replica,
                "bucket_ladder": list(self.ladder),
                "tenants": {k: dict(v)
                            for k, v in self._tenants.items()},
                "autotune": {"enabled": False}
                if self._tuner is None else self._tuner.snapshot(),
            }
