"""The multi-tenant solve service.

`SolveService` is the production front end ROADMAP item 3 names: a
stream of (matrix, rhs, tenant, deadline) requests goes in; batched,
cached, deadline-aware solves come out. It composes the pieces this
package provides:

- requests are bucketed by (pattern fingerprint, dtype) and served by
  `BucketEngine`s — continuous batching: a converged slot is refilled
  at the next cycle boundary, never waiting for the whole batch;
- the engines live in a bytes-budgeted `HierarchyCache`: a repeat
  fingerprint is a cache hit and admission routes through the
  value-resetup path (0.43 s at 256^3) instead of a full AMG setup
  (17 s); idle LRU buckets are evicted past the byte budget;
- with `serving_aot_dir` set, engine executables round-trip through
  the `AotStore`, so a restarted service skips first-request tracing;
- every request may carry a deadline: expiry completes the ticket
  with `DEADLINE_EXCEEDED` (its current iterate under the default
  'partial' action, the initial iterate under 'reject') at the next
  cycle boundary — a late request can never stall its bucket — and
  `serving_max_queue` bounds admission up front.

Drive it synchronously (`step()` / `drain()`: deterministic, what the
tests use) or start the background scheduler thread (`start()`), in
which case `submit()` is all a caller ever touches and tickets
complete asynchronously (`ticket.wait()`).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..batch.queue import pattern_fingerprint
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..resilience.status import SolveStatus
from ..solvers.base import SolveResult
from ..telemetry import metrics as _tm
from .aot import AotStore
from .cache import HierarchyCache, solve_data_bytes
from .engine import BucketEngine


@dataclasses.dataclass
class ServiceTicket:
    """One submitted request; completes with a SolveResult."""

    A: CsrMatrix
    b: np.ndarray
    x0: Optional[np.ndarray]
    tenant: str
    fingerprint: str
    submit_t: float
    deadline_t: Optional[float]          # absolute time.monotonic()
    result: Optional[SolveResult] = None
    complete_t: Optional[float] = None
    # has this request's cache routing (hit/miss) been counted yet?
    # (once per request, at its build/admission — never per poll)
    cache_counted: bool = False
    # the bucket-build exception when this request was rejected
    # because its bucket could not be built (status BREAKDOWN)
    error: Optional[Exception] = None
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    @property
    def done(self) -> bool:
        return self.result is not None

    @property
    def latency_s(self) -> Optional[float]:
        if self.complete_t is None:
            return None
        return self.complete_t - self.submit_t

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _complete(self, result: SolveResult):
        self.result = result
        self.complete_t = time.monotonic()
        self._event.set()


class SolveService:
    """Async multi-tenant solve service (see module docs). One Config
    serves every bucket; knobs are the `serving_*` parameters."""

    def __init__(self, cfg: Config, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        self.chunk = int(cfg.get("serving_chunk_iters", scope))
        self.slots = int(cfg.get("serving_bucket_slots", scope))
        self.max_queue = int(cfg.get("serving_max_queue", scope))
        self.deadline_action = str(
            cfg.get("serving_deadline_action", scope))
        aot_dir = str(cfg.get("serving_aot_dir", scope)).strip()
        self.aot: Optional[AotStore] = \
            AotStore(aot_dir) if aot_dir else None
        # hit/miss is counted PER REQUEST at its build/admission (in
        # step()), not via the cache's own lookup counters — a queued
        # ticket polling a full bucket every cycle must not inflate
        # the hit rate the bench artifact records
        self.buckets = HierarchyCache(
            budget_bytes=int(cfg.get("serving_cache_bytes", scope)),
            max_entries=int(cfg.get("serving_cache_entries", scope)),
            counters={"evict": "serving.cache.evictions",
                      "bytes": "serving.cache.bytes",
                      "entries": "serving.live_buckets"},
            can_evict=lambda eng: eng.idle)
        self._queue: List[ServiceTicket] = []
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # async bucket builds (background-scheduler mode): fingerprint
        # -> builder thread / finished engine / failure
        self._builds: Dict[str, threading.Thread] = {}
        self._built: Dict[str, BucketEngine] = {}
        self._build_failed: Dict[str, Exception] = {}
        self._completed_total = 0
        # per-tenant tallies for stats()
        self._tenants: Dict[str, Dict[str, int]] = {}

    # -- submission --------------------------------------------------------
    def _tenant(self, name: str) -> Dict[str, int]:
        return self._tenants.setdefault(
            name, {"submitted": 0, "completed": 0, "deadline_miss": 0,
                   "rejected": 0})

    def submit(self, A: CsrMatrix, b, x0=None, tenant: str = "default",
               deadline_s: Optional[float] = None) -> ServiceTicket:
        """Enqueue one system. `deadline_s` is a relative budget from
        now; expiry completes the ticket with DEADLINE_EXCEEDED rather
        than ever blocking the bucket. Thread-safe; issues no device
        work of its own (it may briefly contend with the scheduler's
        bookkeeping lock, but never with a hierarchy build)."""
        b = np.asarray(b)
        if b.ndim != 1:
            raise BadParametersError(
                f"service.submit: b must be one system's rhs, got "
                f"shape {b.shape}")
        if b.size != A.num_rows * A.block_dimx:
            # caller bug surfaced at the submit site, not as a
            # scheduler-cycle admission failure later
            raise BadParametersError(
                f"service.submit: rhs length {b.size} does not match "
                f"the matrix ({A.num_rows * A.block_dimx} unknowns)")
        now = time.monotonic()
        ticket = ServiceTicket(
            A=A, b=b, x0=None if x0 is None else np.asarray(x0),
            tenant=str(tenant),
            fingerprint=f"{pattern_fingerprint(A)}/{b.dtype}",
            submit_t=now,
            deadline_t=None if deadline_s is None
            else now + float(deadline_s))
        _tm.inc("serving.requests")
        with self._lock:
            self._tenant(ticket.tenant)["submitted"] += 1
            if self.max_queue and len(self._queue) >= self.max_queue:
                self._reject(ticket, queue_full=True)
                return ticket
            self._queue.append(ticket)
            _tm.set_gauge("serving.queue_depth", len(self._queue))
        return ticket

    def _reject(self, t: ServiceTicket, queue_full: bool = False):
        """Complete without solving: the initial iterate and a
        DEADLINE_EXCEEDED status (admission control, queued expiry, or
        the reject-on-deadline action)."""
        x = t.x0 if t.x0 is not None else np.zeros_like(t.b)
        _tm.inc("serving.rejected")
        if not queue_full:
            _tm.inc("serving.deadline_miss")
            _tm.inc("serving.deadline_action.reject")
        tt = self._tenant(t.tenant)
        tt["rejected"] += 1
        if not queue_full:
            tt["deadline_miss"] += 1
        self._finish(t, SolveResult(
            x=x, iterations=0, converged=False,
            res_norm=np.asarray(np.inf), norm0=np.asarray(np.inf),
            status_code=int(SolveStatus.DEADLINE_EXCEEDED)))

    def _finish(self, t: ServiceTicket, result: SolveResult):
        _tm.inc("serving.completed")
        self._tenant(t.tenant)["completed"] += 1
        self._completed_total += 1
        t._complete(result)
        # per-tenant solve-latency distribution: recorded for EVERY
        # terminal status (a deadline miss is latency the caller saw
        # too) so the p50/p99 the scrape reports are honest
        _tm.observe("serving.solve_latency_s",
                    t.complete_t - t.submit_t,
                    labels={"tenant": t.tenant})

    def _fail_ticket(self, t: ServiceTicket, err: Exception):
        """Complete a ticket whose bucket build or admission raised:
        BREAKDOWN status + the exception on ticket.error — never a
        wedged queue or a scheduler-killing raise."""
        t.error = err
        _tm.inc("serving.rejected")
        self._tenant(t.tenant)["rejected"] += 1
        self._finish(t, SolveResult(
            x=np.zeros_like(t.b), iterations=0, converged=False,
            res_norm=np.asarray(np.inf), norm0=np.asarray(np.inf),
            status_code=int(SolveStatus.BREAKDOWN)))

    # -- scheduling --------------------------------------------------------
    def _build_engine(self, t: ServiceTicket) -> BucketEngine:
        return BucketEngine(
            self.cfg, self.scope, t.A, slots=self.slots,
            chunk=self.chunk, dtype=t.b.dtype,
            fingerprint=t.fingerprint, aot=self.aot)

    def _builder(self, t: ServiceTicket):
        """Builder-thread body: one bucket build off the scheduler
        cycle, so in-flight buckets keep advancing during the seconds
        a cold fingerprint's setup + traces take."""
        try:
            eng = self._build_engine(t)
        except Exception as e:            # surfaced by the next step()
            with self._lock:
                self._build_failed[t.fingerprint] = e
                self._builds.pop(t.fingerprint, None)
            return
        with self._lock:
            self._built[t.fingerprint] = eng
            self._builds.pop(t.fingerprint, None)

    def step(self) -> List[ServiceTicket]:
        """One scheduler cycle: expire, build/install missing buckets,
        admit, advance, finalize. Returns the tickets completed this
        cycle. Bucket builds (a full AMG setup + engine traces —
        seconds) never run under the service lock, so a concurrent
        submit() never waits on one; with the background scheduler
        running they happen on builder THREADS, so in-flight buckets
        keep stepping while a cold fingerprint builds. Driven
        synchronously (no start()), the build runs inline — one per
        cycle, for the oldest unserved ticket — which keeps step()
        deterministic for tests."""
        completed: List[ServiceTicket] = []
        with self._lock:
            now = time.monotonic()
            # 1. queued expiry: a request that died waiting never
            # touches a slot
            still = []
            for t in self._queue:
                if t.deadline_t is not None and now >= t.deadline_t:
                    self._reject(t)
                    completed.append(t)
                else:
                    still.append(t)
            self._queue = still
            # 2a. install builder-thread results; reject the queued
            # tickets of a failed build (BREAKDOWN + .error) instead
            # of retrying it forever
            for fp in list(self._built):
                eng = self._built.pop(fp)
                if self.buckets.peek(fp) is None:
                    self.buckets.put(fp, eng,
                                     nbytes=solve_data_bytes(eng))
            if self._build_failed:
                failed = dict(self._build_failed)
                self._build_failed.clear()
                still = []
                for t in self._queue:
                    err = failed.get(t.fingerprint)
                    if err is None:
                        still.append(t)
                        continue
                    self._fail_ticket(t, err)
                    completed.append(t)
                self._queue = still
            # 2b. pick at most ONE new build per cycle, for the OLDEST
            # unserved ticket (building every missing bucket up front
            # would serialize all setups ahead of all progress)
            cand = None
            for t in self._queue:
                if self.buckets.peek(t.fingerprint) is None \
                        and t.fingerprint not in self._builds:
                    cand = t
                    break
            if cand is not None:
                _tm.inc("serving.cache.miss")
                cand.cache_counted = True
                if self._thread is not None:
                    th = threading.Thread(
                        target=self._builder, args=(cand,),
                        daemon=True, name="amgx-serving-build")
                    self._builds[cand.fingerprint] = th
                    th.start()
                    cand = None           # admission catches up later
        # 3. synchronous-mode build: inline, outside the lock; a build
        # failure rejects the fingerprint's queued tickets exactly
        # like the threaded path (never a raise out of step(), never
        # an infinitely retried build)
        if cand is not None:
            try:
                eng = self._build_engine(cand)
            except Exception as e:
                with self._lock:
                    still = []
                    for t in self._queue:
                        if t.fingerprint == cand.fingerprint:
                            self._fail_ticket(t, e)
                            completed.append(t)
                        else:
                            still.append(t)
                    self._queue = still
                eng = None
            if eng is not None:
                with self._lock:
                    if self.buckets.peek(cand.fingerprint) is None:
                        self.buckets.put(cand.fingerprint, eng,
                                         nbytes=solve_data_bytes(eng))
        with self._lock:
            # 4. admission, strictly oldest-first across ALL buckets
            # (the fairness contract: a hot fingerprint's backlog
            # cannot starve a cold tenant's single request); a ticket
            # whose bucket is full blocks only ITS bucket
            blocked = set()
            remaining = []
            for t in self._queue:
                if t.fingerprint in blocked:
                    remaining.append(t)
                    continue
                eng = self.buckets.get(t.fingerprint)   # LRU touch
                if eng is None:
                    # built this cycle but immediately evicted (tiny
                    # byte budget) or raced an eviction: retry next
                    blocked.add(t.fingerprint)
                    remaining.append(t)
                    continue
                slot = eng.free_slot()
                if slot is None:
                    blocked.add(t.fingerprint)
                    remaining.append(t)
                    continue
                if not t.cache_counted:
                    _tm.inc("serving.cache.hit")
                    t.cache_counted = True
                _tm.observe("serving.queue_wait_s",
                            time.monotonic() - t.submit_t,
                            labels={"tenant": t.tenant})
                try:
                    eng.admit(slot, t.A, t.b, x0=t.x0, occupant=t)
                except Exception as e:
                    # bad request (rhs length, structure drift):
                    # complete THIS ticket with the error — an
                    # admission raise must never wedge the queue or
                    # kill the scheduler for the other tenants
                    self._fail_ticket(t, e)
                    completed.append(t)
                    continue
                _tm.set_gauge("serving.inflight", self._inflight())
            self._queue = remaining
            # 5. advance every busy bucket one cycle, then settle the
            # terminal and deadline-expired slots
            now = time.monotonic()
            for key in self.buckets.keys():
                eng = self.buckets.peek(key)
                if eng is None or eng.idle:
                    continue
                terminal = set(eng.step())
                expired = [
                    j for j in range(eng.slots)
                    if eng.occupant[j] is not None
                    and j not in terminal
                    and eng.occupant[j].deadline_t is not None
                    and now >= eng.occupant[j].deadline_t]
                results = eng.finalize(sorted(terminal) + expired)
                for j in sorted(terminal):
                    t = eng.occupant[j]
                    eng.release(j)
                    self._finish(t, results[j])
                    completed.append(t)
                for j in expired:
                    t = eng.occupant[j]
                    eng.release(j)
                    res = results[j]
                    _tm.inc("serving.deadline_miss")
                    self._tenant(t.tenant)["deadline_miss"] += 1
                    res.converged = False
                    res.status_code = int(
                        SolveStatus.DEADLINE_EXCEEDED)
                    if self.deadline_action == "reject":
                        _tm.inc("serving.deadline_action.reject")
                        res.x = np.zeros_like(t.b) if t.x0 is None \
                            else t.x0
                    else:
                        _tm.inc("serving.deadline_action.partial")
                    self._finish(t, res)
                    completed.append(t)
                self.buckets.set_bytes(key, solve_data_bytes(eng))
            self.buckets.evict_to_budget()
            _tm.set_gauge("serving.queue_depth", len(self._queue))
            _tm.set_gauge("serving.inflight", self._inflight())
        return completed

    def _inflight(self) -> int:
        # tolerant of concurrent eviction (called lock-free from the
        # scheduler loop's pacing check)
        engines = (self.buckets.peek(k) for k in self.buckets.keys())
        return sum(e.inflight for e in engines if e is not None)

    @property
    def idle(self) -> bool:
        with self._lock:
            return (not self._queue and self._inflight() == 0
                    and not self._builds and not self._built)

    @property
    def completed_total(self) -> int:
        """Requests completed over the service lifetime (any terminal
        status) — the mode-independent progress counter the C API's
        drain reports deltas of."""
        return self._completed_total

    def drain(self, timeout_s: Optional[float] = None
              ) -> List[ServiceTicket]:
        """Step until every queued and in-flight request completed (or
        the timeout elapsed). Driven inline (no background thread) the
        return value lists the tickets completed during this call;
        with the background scheduler running it only WAITS and
        returns [] — use `completed_total` deltas (or the tickets you
        hold) for counts in that mode."""
        t0 = time.monotonic()
        done: List[ServiceTicket] = []
        while not self.idle:
            if timeout_s is not None \
                    and time.monotonic() - t0 > timeout_s:
                break
            if self._thread is not None:
                time.sleep(0.001)
            else:
                done.extend(self.step())
        return done

    # -- background scheduler ---------------------------------------------
    def start(self, poll_s: float = 0.0005):
        """Run the scheduler on a daemon thread: submit() from any
        thread, await tickets with ticket.wait()."""
        if self._thread is not None:
            return
        self._stopping = False

        def loop():
            while not self._stopping:
                if self.idle:
                    time.sleep(poll_s)
                    continue
                done = self.step()
                if not done and self._inflight() == 0:
                    # nothing advanced: only waiting on builder
                    # threads — don't spin the scheduler hot
                    time.sleep(poll_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="amgx-serving")
        self._thread.start()

    def stop(self):
        if self._thread is None:
            return
        self._stopping = True
        self._thread.join()
        self._thread = None

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "queue_depth": len(self._queue),
                "inflight": self._inflight(),
                "live_buckets": len(self.buckets),
                "cache_bytes": self.buckets.total_bytes,
                "evictions": self.buckets.evictions,
                # live latency quantiles from the process-wide
                # histograms (all tenants aggregated; per-tenant
                # series live in metrics.snapshot()/OpenMetrics)
                "solve_latency_p50_s":
                    _tm.quantile("serving.solve_latency_s", 0.50),
                "solve_latency_p99_s":
                    _tm.quantile("serving.solve_latency_s", 0.99),
                "queue_wait_p50_s":
                    _tm.quantile("serving.queue_wait_s", 0.50),
                "queue_wait_p99_s":
                    _tm.quantile("serving.queue_wait_s", 0.99),
                "tenants": {k: dict(v)
                            for k, v in self._tenants.items()},
            }
