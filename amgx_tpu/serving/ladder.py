"""Mixed bucket-width padded ladder (ROADMAP 2c).

A fixed `serving_bucket_slots` pads every bucket to one batch width,
which is wrong at both ends of a size-diverse workload: a singleton
fingerprint occupies (and pays the padded FLOPs of) a wide bucket,
while a burst queues behind a narrow one in `slots`-sized waves. The
ladder replaces the fixed width with a declared rung set
(`serving_bucket_ladder`, e.g. ``1|4|16``): each bucket BUILD draws
its width from the queue composition at build time — the smallest
rung that seats every queued same-fingerprint request, capped at the
top rung.

The choice is per-build, not per-cycle: a bucket keeps the width it
was born with until it is evicted (rebuilding mid-life would throw
away its traces and its in-flight state). A burst that arrives after
a narrow build therefore drains in narrow waves until the LRU churn
gives the fingerprint a fresh, wider build — the same settling
behaviour the fixed-width engine has, with a better steady state.

Width changes never cross-serve traces: `slots` is part of the
engine's AOT key (`BucketEngine._aot_key`), so every rung keeps its
own exported executable and a ladder service warm-starts each width
independently.
"""
from __future__ import annotations

from typing import Tuple

from ..errors import BadParametersError


def parse_ladder(spec: str) -> Tuple[int, ...]:
    """``'1|4|16'`` -> ``(1, 4, 16)``; ``''`` -> ``()`` (ladder off —
    the fixed `serving_bucket_slots` width applies). Rungs must be
    positive, strictly increasing integers; ``,`` separators are
    accepted as well (config strings already use ``,`` between
    parameters, so ``|`` is the documented spelling)."""
    s = str(spec or "").strip()
    if not s:
        return ()
    parts = [p.strip() for p in s.replace(",", "|").split("|")
             if p.strip()]
    try:
        rungs = tuple(int(p) for p in parts)
    except ValueError:
        raise BadParametersError(
            f"serving_bucket_ladder: rungs must be integers, "
            f"got {spec!r}")
    if not rungs or any(r < 1 for r in rungs) \
            or list(rungs) != sorted(set(rungs)):
        raise BadParametersError(
            f"serving_bucket_ladder: rungs must be positive and "
            f"strictly increasing, got {spec!r}")
    return rungs


def choose_slots(rungs: Tuple[int, ...], pending: int,
                 default: int) -> int:
    """Bucket width for a build that will serve `pending` queued
    same-fingerprint requests: the smallest rung seating all of them,
    else the top rung (a burst larger than the ladder drains in
    top-width waves). An empty ladder defers to `default`
    (= serving_bucket_slots, the fixed-width engine)."""
    if not rungs:
        return max(int(default), 1)
    pending = max(int(pending), 1)
    for r in rungs:
        if r >= pending:
            return r
    return rungs[-1]
