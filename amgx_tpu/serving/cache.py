"""Bytes-budgeted LRU for hierarchy-holding entries.

A long-running solve service keyed on pattern fingerprints accumulates
one AMG hierarchy (plus compiled programs) per distinct sparsity
pattern it has ever seen. Each of those is worth keeping — a cache hit
routes a repeat-pattern request through the 0.43 s value-resetup path
instead of a 17 s setup — but the store must be bounded in the unit
that actually runs out: device bytes, not entry count. This LRU tracks
an estimated byte footprint per entry (``solve_data_bytes``: the
solve-data pytree's unique array leaves), evicts least-recently-used
entries past the budget, and never evicts an entry its owner marks
busy (a serving bucket with in-flight systems).

Used by the serving layer's bucket store (serving/service.py) and by
`RequestBatcher._solver_for` (batch/queue.py), each with its own
telemetry counter names.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import numpy as np


def solve_data_bytes(obj: Any) -> int:
    """Estimated device footprint of a solver/engine: the total nbytes
    of the UNIQUE array leaves in its solve-data pytree (shared
    structure leaves — stacked or aliased across systems — count
    once). `obj` may be a solver tree (anything with solve_data()), an
    already-built pytree, or an object exposing `footprint_tree()`."""
    import jax
    tree = obj
    if hasattr(obj, "footprint_tree"):
        tree = obj.footprint_tree()
    elif hasattr(obj, "solve_data"):
        tree = obj.solve_data()
    seen, total = set(), 0
    for leaf in jax.tree.leaves(tree):
        if id(leaf) in seen:
            continue
        seen.add(id(leaf))
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and np.shape(leaf) != ():
            nbytes = np.asarray(leaf).nbytes
        total += int(nbytes or 0)
    return total


class HierarchyCache:
    """LRU of fingerprint -> entry with a byte budget (see module docs).

    `budget_bytes=0` and/or `max_entries=0` disable that bound. The
    optional `counters` dict maps the events 'hit'/'miss'/'evict' to
    declared telemetry counter names and 'bytes'/'entries' to gauges;
    unset events are simply not reported (the class stays importable
    without the telemetry catalog)."""

    def __init__(self, budget_bytes: int = 0, max_entries: int = 0,
                 counters: Optional[Dict[str, str]] = None,
                 can_evict: Optional[Callable[[Any], bool]] = None,
                 on_evict: Optional[Callable[[str, Any], None]] = None):
        self.budget_bytes = int(budget_bytes)
        self.max_entries = int(max_entries)
        self.counters = dict(counters or {})
        self.can_evict = can_evict
        self.on_evict = on_evict
        self._entries: "OrderedDict[str, Any]" = OrderedDict()
        self._bytes: Dict[str, int] = {}
        self.evictions = 0

    def _report(self, event: str, value=1):
        name = self.counters.get(event)
        if not name:
            return
        from ..telemetry import metrics as _tm
        if event in ("bytes", "entries"):
            _tm.set_gauge(name, value)
        else:
            _tm.inc(name, value)

    def _gauges(self):
        self._report("bytes", self.total_bytes)
        self._report("entries", len(self._entries))

    # -- mapping surface --------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def keys(self):
        return list(self._entries)

    @property
    def total_bytes(self) -> int:
        return sum(self._bytes.values())

    def get(self, key: str):
        """LRU-touching lookup; counts a hit or a miss."""
        entry = self._entries.get(key)
        if entry is None:
            self._report("miss")
            return None
        self._entries.move_to_end(key)
        self._report("hit")
        return entry

    def peek(self, key: str):
        """Lookup without touching LRU order or hit/miss counters."""
        return self._entries.get(key)

    def put(self, key: str, entry: Any, nbytes: int = 0):
        """Insert/replace, then evict LRU entries past the budgets."""
        self._entries[key] = entry
        self._entries.move_to_end(key)
        self._bytes[key] = int(nbytes)
        self.evict_to_budget()
        self._gauges()

    def set_bytes(self, key: str, nbytes: int):
        if key in self._entries:
            self._bytes[key] = int(nbytes)
            self._gauges()

    def pop(self, key: str):
        entry = self._entries.pop(key, None)
        self._bytes.pop(key, None)
        self._gauges()
        return entry

    def evict_to_budget(self):
        """Evict least-recently-used evictable entries until both
        budgets hold. Two classes of entry are never evicted: busy
        ones (can_evict -> False — a bucket with in-flight systems
        must never vanish under the scheduler) and the most recently
        used one (evicting the entry a caller just inserted or touched
        would thrash: one oversized hierarchy must still be servable
        under any byte budget). A cache reduced to protected entries
        may legitimately exceed the budget until they drain."""
        def over():
            return ((self.budget_bytes > 0
                     and self.total_bytes > self.budget_bytes)
                    or (self.max_entries > 0
                        and len(self._entries) > self.max_entries))

        while over() and len(self._entries) > 1:
            victim = None
            newest = next(reversed(self._entries))
            for key, entry in self._entries.items():   # oldest first
                if key == newest:
                    continue
                if self.can_evict is None or self.can_evict(entry):
                    victim = key
                    break
            if victim is None:
                break
            entry = self._entries.pop(victim)
            self._bytes.pop(victim, None)
            self.evictions += 1
            self._report("evict")
            if self.on_evict is not None:
                self.on_evict(victim, entry)
        self._gauges()
