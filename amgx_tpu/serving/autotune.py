"""Online per-fingerprint config autotuner (ROADMAP item 3's last
perf lever: nobody hand-retunes a mistuned client config at
millions-of-users scale, so the service must).

`ConfigAutotuner` closes the diagnostics loop the telemetry layer
opened: served traffic runs whatever config the client shipped, and a
mistuned smoother / strength threshold / cycle / precision choice
burns capacity on every repeat of that operator. The tuner turns the
PR-9 diagnostics probe into an automatic, measured, reversible search:

1. WATCH — every completed request feeds per-fingerprint tallies
   (request count + share of total in-bucket exec seconds). A
   fingerprint crossing BOTH `autotune_hot_requests` and
   `autotune_hot_exec_share` becomes a search target; its most recent
   (matrix, rhs) is captured as the shadow workload (and retained in
   the journal's per-fingerprint workload sample when one is
   configured, so a restarted replica can keep searching).
2. GENERATE — one shadow BASELINE solve of the production config with
   `diagnostics=1` overlaid runs the in-trace probe cycle; its
   bottleneck level / per-level reduction factors map to concrete
   config deltas through `telemetry.diagnostics.suggest_config_deltas`
   (the same mapping the convergence doctor prints): smoother swap,
   relaxation re-damp, strength threshold, interpolation + truncation,
   cycle shape, `solve_precision`.
3. SHADOW — each candidate is solved OFF the production path, against
   the captured workload, only when the service has idle capacity
   (empty queue AND a free slot — or no bucket — for that
   fingerprint): shadow work may only ever occupy capacity production
   is not using. Each run is measured (iterations x solve wall, warm
   second solve so trace/compile cost never pollutes the comparison),
   spanned (`autotune.shadow`), and bounded by
   `autotune_shadow_budget` per fingerprint. A crashed shadow is
   absorbed: counted (`autotune.shadow.errors`), backed off, and can
   never fail a ticket — the chaos drill injects exactly this.
4. PROMOTE — the best converged candidate wins only if it beats the
   baseline score by `autotune_min_improvement` AND wins iterations
   and wall outright (hysteresis: noise cannot promote). The deltas
   become the fingerprint's serving overlay — the next bucket build
   clones the service config, applies them, and (the engine's normal
   machinery) re-keys the hstore/AOT entries; the idle bucket is
   retired so the win takes effect now, not at natural eviction. The
   record persists via `HierarchyStore.save_tuned` keyed by
   fingerprint alone, so a restarted replica resolves the overlay
   BEFORE its first build and serves the tuned config from the first
   request with zero full setups (the tuned structure/AOT snapshots
   are already on disk under the tuned config's keys).
5. DEMOTE — post-promotion, live exec medians are watched: a
   regression past `autotune_demote_factor` over
   `autotune_demote_window` completions drops the overlay, deletes
   the persisted record and retires the bucket. Bounded, reversible,
   honest.

Every generate/shadow/promote/demote decision lands on the flight
recorder tagged with a per-search trace id (the PR-13 substrate), so
`tools/flightrec.py` reconstructs WHY a fingerprint serves the config
it serves. `autotune=0` (the default) never constructs this class —
the serving path stays bitwise identical to a pre-autotune build.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..config import Config
from ..resilience import faultinject as _fi
from ..telemetry import flightrec as _fr
from ..telemetry import metrics as _tm
from ..telemetry import spans as _spans

# phases of one fingerprint's tuner lifecycle
_WATCH = "watch"          # tallying; not hot yet
_HOT = "hot"              # crossed thresholds; baseline probe pending
_SEARCH = "search"        # candidates generated; shadows pending
_PROMOTED = "promoted"    # overlay live; demote watch running
_EXHAUSTED = "exhausted"  # budget spent / no win / backed off — done
_DEMOTED = "demoted"      # regressed after promotion — done


def _median(seq) -> Optional[float]:
    vals = sorted(float(v) for v in seq)
    if not vals:
        return None
    return vals[len(vals) // 2]


class ConfigAutotuner:
    """Per-service online tuner (see module docs). Constructed by
    `SolveService` iff `autotune=1`; `note_finish` is the only method
    called under the service lock (dict/deque bookkeeping only), all
    shadow work runs from `maybe_step` at the scheduler cycle's
    off-lock tail."""

    def __init__(self, service):
        self.svc = service
        cfg, scope = service.cfg, service.scope
        self.hot_requests = int(
            cfg.get("autotune_hot_requests", scope))
        self.hot_share = float(
            cfg.get("autotune_hot_exec_share", scope))
        self.shadow_budget = int(
            cfg.get("autotune_shadow_budget", scope))
        self.min_improvement = float(
            cfg.get("autotune_min_improvement", scope))
        self.demote_factor = float(
            cfg.get("autotune_demote_factor", scope))
        self.demote_window = int(
            cfg.get("autotune_demote_window", scope))
        # guards _fp: note_finish mutates under the SERVICE lock while
        # maybe_step reads/mutates off it — the tuner needs its own
        self._lock = threading.Lock()
        self._fp: Dict[str, Dict[str, Any]] = {}
        self._total_exec = 0.0
        # drain quiesce: while set, maybe_step schedules NO shadow
        # work (in-flight inline shadows finish their current solve;
        # they are not production work, so drain never waits on them)
        self._quiesced = False

    # -- bookkeeping (service lock held) ----------------------------------
    def _ensure(self, fp: str) -> Dict[str, Any]:
        rec = self._fp.get(fp)
        if rec is None:
            import collections
            rec = {
                "requests": 0, "exec_s": 0.0, "phase": _WATCH,
                "sample": None, "workload_saved": False,
                "budget": self.shadow_budget,
                "candidates": [], "results": [],
                "baseline": None, "overlay": None, "knob": None,
                "trace": None, "errors": 0, "not_before": 0.0,
                "pre_exec": None, "restored": False, "retire": False,
                "hstore_checked": False,
                "post": collections.deque(maxlen=self.demote_window),
            }
            self._fp[fp] = rec
        return rec

    def note_finish(self, ticket, exec_s: float):
        """One completed in-bucket request (called from _finish, under
        the service lock — tallies and sample capture only)."""
        with self._lock:
            rec = self._ensure(ticket.fingerprint)
            rec["requests"] += 1
            rec["exec_s"] += float(exec_s)
            self._total_exec += float(exec_s)
            if rec["phase"] == _PROMOTED:
                rec["post"].append(float(exec_s))
            elif rec["phase"] in (_WATCH, _HOT, _SEARCH) \
                    and ticket.A is not None:
                # the freshest workload sample: references only (the
                # arrays already live on the ticket)
                rec["sample"] = (ticket.A, ticket.b)

    # -- overlay resolution (engine-build path, off the service lock) ------
    def overlay_for(self, fingerprint: str
                    ) -> Optional[List[Dict[str, Any]]]:
        """The promoted config deltas for a fingerprint, or None. A
        cold fingerprint consults the hstore ONCE (restart durability:
        the persisted record resolves before the first build) and
        caches the answer either way."""
        with self._lock:
            rec = self._fp.get(fingerprint)
            if rec is not None:
                if rec["overlay"] is not None:
                    return [dict(d) for d in rec["overlay"]]
                if rec["hstore_checked"]:
                    return None
        hs = self.svc.hstore
        tuned = hs.load_tuned(fingerprint) if hs is not None else None
        with self._lock:
            rec = self._ensure(fingerprint)
            rec["hstore_checked"] = True
            if rec["overlay"] is not None:    # raced a live promotion
                return [dict(d) for d in rec["overlay"]]
            if tuned is None:
                return None
            rec["overlay"] = [dict(d) for d in tuned["deltas"]]
            rec["knob"] = tuned.get("knob")
            rec["phase"] = _PROMOTED
            rec["restored"] = True
        _tm.inc("autotune.overlay.restored")
        _fr.record("autotune.restore", trace=tuned.get("trace"),
                   fingerprint=fingerprint[:24],
                   knob=tuned.get("knob"),
                   deltas=self._fmt_deltas(tuned["deltas"]))
        return [dict(d) for d in tuned["deltas"]]

    @staticmethod
    def _fmt_deltas(deltas) -> str:
        return ",".join(f"{d['param']}={d['value']}" for d in deltas)

    @staticmethod
    def apply_overlay(cfg: Config, deltas) -> Config:
        """A clone of `cfg` with each delta applied: the parameter is
        overridden at EVERY scope that sets it, else at the default
        scope (which every scoped lookup falls back to) — one generic
        applier for any solver-tree shape."""
        out = cfg.clone()
        for d in deltas:
            name, value = d["param"], d["value"]
            scopes = [s for (s, n) in cfg.values if n == name]
            for s in scopes or ["default"]:
                out.set(name, value, s)
        return out

    # -- scheduler hook (off the service lock) -----------------------------
    def maybe_step(self):
        """At most ONE unit of tuner work per scheduler cycle: a
        demote check, or (gated on idle capacity) one shadow solve.
        Called from the cycle's off-lock tail; quiesced during
        drain()."""
        if self._quiesced:
            return
        self._check_demotions()
        job = self._next_job()
        if job is None:
            return
        fp, rec, kind, payload = job
        if kind == "baseline":
            self._run_baseline(fp, rec)
        else:
            self._run_candidate(fp, rec, payload)

    def _idle_capacity(self, fp: str) -> bool:
        """Shadow gating: the queue is empty AND nothing is in flight
        — shadow work may only occupy capacity production is not
        using, and the scheduler thread that would run the shadow is
        the same one advancing in-flight chunks, so 'a free slot on a
        busy bucket' is NOT idle capacity (the shadow would stall the
        neighbors; the paired-p99 gate measures exactly this)."""
        svc = self.svc
        with svc._lock:
            return not svc._queue and svc._inflight() == 0

    def _next_job(self):
        """Pick one pending shadow job (hotness promotion happens
        here: tallies are read under the tuner lock, the decision is
        recorded off it)."""
        now = time.monotonic()
        newly_hot = []
        job = None
        with self._lock:
            for fp, rec in self._fp.items():
                if rec["phase"] == _WATCH:
                    if rec["requests"] >= self.hot_requests \
                            and self._total_exec > 0.0 \
                            and rec["exec_s"] / self._total_exec \
                            >= self.hot_share \
                            and rec["sample"] is not None:
                        rec["phase"] = _HOT
                        rec["trace"] = _spans.new_trace_id()
                        newly_hot.append((fp, rec))
                if rec["phase"] not in (_HOT, _SEARCH):
                    continue
                if rec["not_before"] > now:
                    continue
                out_of_budget = rec["budget"] <= 0
                if out_of_budget and rec["phase"] == _HOT:
                    rec["phase"] = _EXHAUSTED
                    continue
                if job is None:
                    if rec["phase"] == _HOT:
                        job = (fp, rec, "baseline", None)
                    elif rec["candidates"] and not out_of_budget:
                        job = (fp, rec, "candidate",
                               rec["candidates"][0])
                    else:
                        # candidates all measured (or budget gone):
                        # decide on what was measured
                        job = (fp, rec, "candidate", None)
        for fp, rec in newly_hot:
            _tm.inc("autotune.hot")
            _fr.record("autotune.watch", trace=rec["trace"],
                       fingerprint=fp[:24],
                       requests=rec["requests"],
                       exec_share=round(
                           rec["exec_s"] / max(self._total_exec,
                                               1e-12), 4))
            # retain the workload in the journal so a restarted
            # replica can shadow-solve this fingerprint again
            jr = self.svc.journal
            if jr is not None and not rec["workload_saved"] \
                    and rec["sample"] is not None:
                A, b = rec["sample"]
                jr.save_workload(fp, A, b)
                rec["workload_saved"] = True
        if job is not None and job[3] is None and job[2] == "candidate":
            # decision step needs no capacity
            self._decide(job[0], job[1])
            return None
        if job is not None and not self._idle_capacity(job[0]):
            return None
        return job

    def _workload(self, fp: str, rec) -> Optional[Tuple[Any, Any]]:
        if rec["sample"] is not None:
            return rec["sample"]
        jr = self.svc.journal
        if jr is not None:
            wl = jr.load_workload(fp)
            if wl is not None:
                rec["sample"] = wl
                return wl
        return None

    # -- shadow solves -----------------------------------------------------
    def _shadow_solve(self, fp: str, rec, deltas, label: str):
        """One shadow solve of the service config + `deltas` against
        the fingerprint's captured workload. Returns a measurement
        dict or None (crash absorbed + backed off). The measured wall
        is the WARM second solve — trace/compile cost must never
        pollute a comparison production would pay only once."""
        from .. import create_solver
        wl = self._workload(fp, rec)
        if wl is None:
            return None
        A, b = wl
        cfg = self.apply_overlay(self.svc.cfg, deltas)
        t0 = time.perf_counter()
        try:
            with _spans.span("autotune.shadow", args={
                    "trace": rec["trace"], "fingerprint": fp[:24],
                    "candidate": label}):
                _fi.service_crash("shadow_crash")
                slv = create_solver(cfg, self.svc.scope)
                slv.setup(A)
                slv.solve(b)               # trace + cold pass
                res = slv.solve(b)         # the measured warm pass
        except Exception as e:
            _tm.inc("autotune.shadow.errors")
            rec["errors"] += 1
            rec["budget"] -= 1
            # back off: one error pauses this fingerprint's search,
            # two retire it — a crashing candidate config must never
            # consume the idle capacity forever
            rec["not_before"] = time.monotonic() + 0.25
            if rec["errors"] >= 2:
                rec["phase"] = _EXHAUSTED
            _fr.record("autotune.shadow_crash", trace=rec["trace"],
                       fingerprint=fp[:24], candidate=label,
                       error=str(e)[:160],
                       backed_off=rec["phase"] == _EXHAUSTED)
            return None
        wall = max(float(res.solve_time), 1e-9)
        total_wall = time.perf_counter() - t0
        iters = max(int(res.iterations), 1)
        m = {"iters": iters, "wall_s": wall,
             "score": iters * wall,
             "converged": bool(getattr(res, "converged", False)),
             "report": getattr(res, "report", None)}
        _tm.inc("autotune.shadow.runs")
        _tm.observe("autotune.shadow_wall_s", total_wall)
        _fr.record("autotune.shadow", trace=rec["trace"],
                   fingerprint=fp[:24], candidate=label,
                   iters=iters, wall_s=round(wall, 6),
                   score=round(m["score"], 9),
                   converged=m["converged"])
        return m

    def _run_baseline(self, fp: str, rec):
        """The GENERATE step: probe the production config
        (diagnostics=1 + residual history overlaid — both bitwise-off
        knobs production never pays for) and map the report to
        candidates."""
        from ..telemetry.diagnostics import suggest_config_deltas
        rec["budget"] -= 1
        probe = [{"param": "diagnostics", "value": 1},
                 {"param": "store_res_history", "value": 1}]
        m = self._shadow_solve(fp, rec, probe, "baseline")
        if m is None:
            return
        rec["errors"] = 0
        rec["baseline"] = m
        diag = None
        if m["report"] is not None:
            diag = getattr(m["report"], "diagnostics", None)
        cands = suggest_config_deltas(diag)
        with self._lock:
            rec["candidates"] = cands
            rec["phase"] = _SEARCH
        _tm.inc("autotune.candidates", max(len(cands), 0))
        _fr.record("autotune.candidates", trace=rec["trace"],
                   fingerprint=fp[:24], n=len(cands),
                   baseline_iters=m["iters"],
                   baseline_wall_s=round(m["wall_s"], 6),
                   knobs=[c["knob"] for c in cands])
        if not cands:
            with self._lock:
                rec["phase"] = _EXHAUSTED
            self._decision(fp, rec, "no_candidates")

    def _run_candidate(self, fp: str, rec, cand):
        rec["budget"] -= 1
        m = self._shadow_solve(fp, rec, cand["deltas"], cand["knob"])
        with self._lock:
            if cand in rec["candidates"]:
                rec["candidates"].remove(cand)
        if m is None:
            return
        rec["errors"] = 0
        rec["results"].append((cand, m))

    def _decide(self, fp: str, rec):
        """All candidates measured (or budget gone): promote the best
        converged winner past the hysteresis gate, else retire the
        search."""
        base = rec["baseline"]
        best = None
        for cand, m in rec["results"]:
            if not m["converged"]:
                continue
            if best is None or m["score"] < best[1]["score"]:
                best = (cand, m)
        if best is not None:
            # near-ties on score are decided by iterations: the wall
            # half of the score carries single-solve timing noise,
            # iteration count is exact — within the hysteresis margin
            # the noise-free signal picks the winner
            for cand, m in rec["results"]:
                if (m["converged"]
                        and m["score"] <= best[1]["score"]
                        * self.min_improvement
                        and m["iters"] < best[1]["iters"]):
                    best = (cand, m)
        wins = (
            best is not None and base is not None
            and base["score"] / best[1]["score"]
            >= self.min_improvement
            and best[1]["iters"] <= base["iters"]
            and best[1]["wall_s"] <= base["wall_s"])
        if not wins:
            with self._lock:
                rec["phase"] = _EXHAUSTED
            self._decision(fp, rec, "no_win")
            return
        cand, m = best
        with self._lock:
            rec["overlay"] = [dict(d) for d in cand["deltas"]]
            rec["knob"] = cand["knob"]
            rec["phase"] = _PROMOTED
            rec["retire"] = True
            rec["post"].clear()
            rec["pre_exec"] = _median(
                self.svc._exec_fp.get(fp, ()))
        _tm.inc("autotune.promotions")
        _tm.set_gauge("autotune.tuned_fingerprints",
                      self._promoted_count())
        speedup = round(rec["baseline"]["score"] / m["score"], 3)
        _fr.record("autotune.promote", trace=rec["trace"],
                   fingerprint=fp[:24], knob=cand["knob"],
                   deltas=self._fmt_deltas(cand["deltas"]),
                   baseline_iters=base["iters"],
                   tuned_iters=m["iters"],
                   baseline_wall_s=round(base["wall_s"], 6),
                   tuned_wall_s=round(m["wall_s"], 6),
                   speedup_x=speedup)
        _spans.mark("autotune.decision", args={
            "trace": rec["trace"], "fingerprint": fp[:24],
            "decision": "promote", "knob": cand["knob"],
            "speedup_x": speedup})
        hs = self.svc.hstore
        if hs is not None:
            hs.save_tuned(fp, {
                "deltas": rec["overlay"], "knob": cand["knob"],
                "trace": rec["trace"],
                "baseline": {"iters": base["iters"],
                             "wall_s": base["wall_s"]},
                "tuned": {"iters": m["iters"],
                          "wall_s": m["wall_s"]}})
        self._retire_bucket(fp, rec)

    def _decision(self, fp: str, rec, verdict: str):
        _fr.record("autotune.decision", trace=rec["trace"],
                   fingerprint=fp[:24], verdict=verdict,
                   budget_left=rec["budget"],
                   shadows=len(rec["results"]))
        _spans.mark("autotune.decision", args={
            "trace": rec["trace"], "fingerprint": fp[:24],
            "decision": verdict})

    def _retire_bucket(self, fp: str, rec):
        """Drop the fingerprint's idle bucket so the next build picks
        up the overlay change now, not at natural eviction. A busy
        bucket stays (never disturb in-flight work) and retires at a
        later cycle via the pending flag."""
        svc = self.svc
        with svc._lock:
            eng = svc.buckets.peek(fp)
            if eng is None:
                rec["retire"] = False
                return
            if eng.idle:
                svc.buckets.pop(fp)
                rec["retire"] = False

    def _check_demotions(self):
        """Live regression watch over the promoted set (and pending
        bucket retirements)."""
        to_demote, to_retire = [], []
        with self._lock:
            for fp, rec in self._fp.items():
                if rec["phase"] != _PROMOTED:
                    continue
                if rec["retire"]:
                    to_retire.append((fp, rec))
                if rec["pre_exec"] is None \
                        or len(rec["post"]) < self.demote_window:
                    continue
                med = _median(rec["post"])
                if med is not None and med > \
                        rec["pre_exec"] * self.demote_factor:
                    to_demote.append((fp, rec, med))
        # bucket retirement takes the SERVICE lock — never while the
        # tuner lock is held (note_finish acquires svc -> tuner)
        for fp, rec in to_retire:
            self._retire_bucket(fp, rec)
        for fp, rec, med in to_demote:
            with self._lock:
                rec["overlay"] = None
                rec["phase"] = _DEMOTED
                rec["retire"] = True
            _tm.inc("autotune.demotions")
            _tm.set_gauge("autotune.tuned_fingerprints",
                          self._promoted_count())
            _fr.record("autotune.demote", trace=rec["trace"],
                       fingerprint=fp[:24],
                       pre_exec_s=round(rec["pre_exec"], 6),
                       post_exec_s=round(med, 6),
                       factor=round(med / rec["pre_exec"], 3))
            _spans.mark("autotune.decision", args={
                "trace": rec["trace"], "fingerprint": fp[:24],
                "decision": "demote"})
            hs = self.svc.hstore
            if hs is not None:
                hs.drop_tuned(fp)
            self._retire_bucket(fp, rec)

    def _promoted_count(self) -> int:
        return sum(1 for r in self._fp.values()
                   if r["overlay"] is not None)

    # -- drain quiesce + fleet handoff ------------------------------------
    def quiesce(self):
        """Stop scheduling shadow work (drain()): search state is
        KEPT — the search resumes after the drain."""
        self._quiesced = True

    def resume(self):
        self._quiesced = False

    def export_promoted(self) -> Dict[str, Dict[str, Any]]:
        """The promoted overlays, JSON-shaped — what drain_replica
        hands to the adopting replica alongside the journal."""
        with self._lock:
            return {fp: {"deltas": [dict(d) for d in rec["overlay"]],
                         "knob": rec["knob"],
                         "trace": rec["trace"]}
                    for fp, rec in self._fp.items()
                    if rec["overlay"] is not None}

    def adopt(self, fingerprint: str, state: Dict[str, Any]):
        """Install another replica's promoted overlay (fleet
        drain/failover handoff): served from this replica's next
        build of that fingerprint, persisted in this replica's hstore
        so the adoption survives its own restarts too."""
        with self._lock:
            rec = self._ensure(fingerprint)
            rec["overlay"] = [dict(d) for d in state["deltas"]]
            rec["knob"] = state.get("knob")
            rec["trace"] = state.get("trace") or rec["trace"]
            rec["phase"] = _PROMOTED
            rec["hstore_checked"] = True
            rec["retire"] = True
        _fr.record("autotune.adopt", trace=state.get("trace"),
                   fingerprint=fingerprint[:24],
                   knob=state.get("knob"),
                   deltas=self._fmt_deltas(state["deltas"]))
        hs = self.svc.hstore
        if hs is not None:
            hs.save_tuned(fingerprint, {
                "deltas": [dict(d) for d in state["deltas"]],
                "knob": state.get("knob"),
                "trace": state.get("trace")})

    # -- observability -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """stats()/C-API view of the tuner's live state."""
        with self._lock:
            fps = {}
            for fp, rec in self._fp.items():
                fps[fp[:24]] = {
                    "phase": rec["phase"],
                    "requests": rec["requests"],
                    "budget_left": rec["budget"],
                    "knob": rec["knob"],
                    "overlay": None if rec["overlay"] is None
                    else self._fmt_deltas(rec["overlay"]),
                    "restored": rec["restored"],
                    "errors": rec["errors"],
                }
            return {"enabled": True, "quiesced": self._quiesced,
                    "promoted": self._promoted_count(),
                    "fingerprints": fps}
