"""Replica health: heartbeat sampling + per-replica circuit breakers.

PR 11 made ONE `SolveService` crash-safe; the `FleetRouter` (PR 16)
still assumed every replica it routes to is alive. This module closes
the detection half of fleet fault tolerance: the router owns a
`HealthMonitor` and ticks it from its submit/step/drain paths, and the
monitor turns three cheap liveness signals into breaker transitions
the router acts on:

- **thread aliveness + exception capture** — `SolveService.start()`
  wraps its scheduler loop; an escaping exception lands on
  `svc._thread_error` (and the thread exits). Inline-driven fleets get
  the same capture from `FleetRouter.step()`. Either way the monitor
  sees it immediately (not rate-limited) and emits REPLICA_DEAD.
- **scheduler-cycle progress** — `svc._cycle` increments once per
  scheduler cycle. A replica that is busy (queued or in-flight work)
  whose counter flatlines across `fleet_suspect_checks` consecutive
  rate-limited checks is SUSPECT first, then REPLICA_WEDGED.
- **cycle pace** — when `fleet_slow_cycle_s` > 0, a busy replica whose
  per-cycle wall between checks exceeds it emits REPLICA_SLOW.

Events feed the per-replica circuit breaker through the
`fleet_fault_policy` chains (`resilience/policy.py` grammar,
`EVENT>action|...`): `ignore` counts only; `probe_backoff` OPENs the
breaker for a bounded exponential backoff (`fleet_probe_backoff_s *
2^failures`, exponent capped) and then HALF_OPENs — the router admits
exactly ONE trial fingerprint until the replica proves progress (a
completion since the probe began closes the breaker); `failover`
returns a verdict the router turns into the full DOWN path (rehome +
ticket move + journal adoption, serving/fleet.py).

Administrative state rides the same breaker: `draining` (rolling
restart — no new placements, in-flight finishes) and `warm_until`
(restore grace — a just-restored cold replica is skipped for COLD
placements so it is not instantly the least-loaded home for every new
fingerprint, while warm traffic may return at once).

Every transition writes a flight-recorder event (`fleet.health`), a
`fleet.health.transition` span mark, and literal `fleet.health.*`
counters, so a cross-replica postmortem reads end-to-end in
tools/flightrec.py.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..resilience.policy import parse_fleet_policy
from ..telemetry import flightrec as _fr
from ..telemetry import metrics as _tm
from ..telemetry import spans as _spans

# breaker states (the classic circuit-breaker trio; DOWN and draining
# are orthogonal flags on top — a DOWN breaker stays OPEN until
# restore_replica resets it)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

DEFAULT_FLEET_POLICY = ("REPLICA_DEAD>failover"
                        "|REPLICA_WEDGED>probe_backoff"
                        "|REPLICA_WEDGED>failover"
                        "|REPLICA_SLOW>probe_backoff")

# Verdict the monitor hands the router per transition:
# (replica_id, event, action, captured_error_or_None)
Verdict = Tuple[str, str, str, Optional[BaseException]]


class ReplicaBreaker:
    """Health + breaker state for one replica (mutated only under the
    owning HealthMonitor's lock; hot-path reads are lock-free — every
    field is a plain scalar)."""

    def __init__(self, rid: str):
        self.rid = rid
        self.state = CLOSED
        self.down = False          # failover ran; restore_replica resets
        self.draining = False      # administrative (rolling restart)
        self.failures = 0          # consecutive health events
        self.not_before = 0.0      # OPEN -> HALF_OPEN gate (monotonic)
        self.probe_fp: Optional[str] = None   # the HALF_OPEN trial
        self.probe_base = 0        # completed_total when probe began
        self.warm_until = 0.0      # restore grace (monotonic)
        self.last_event: Optional[str] = None
        # heartbeat sampling state (rate-limited by check_s)
        self.last_cycle = 0
        self.last_hb_t = 0.0
        self.stale = 0

    @property
    def available(self) -> bool:
        """May this replica take warm/queued traffic right now?
        (HALF_OPEN counts: the probe-admission decision is the
        router's, per fingerprint.)"""
        return not self.down and not self.draining and self.state != OPEN

    def snapshot(self, now: float) -> Dict[str, object]:
        return {
            "state": self.state,
            "down": self.down,
            "draining": self.draining,
            "failures": self.failures,
            "last_event": self.last_event,
            "probe_fingerprint": self.probe_fp,
            "backoff_remaining_s": round(max(0.0, self.not_before - now), 4)
            if self.state == OPEN and not self.down else 0.0,
            "warmup_remaining_s": round(max(0.0, self.warm_until - now), 4),
        }


class HealthMonitor:
    """Fleet-side health tracking over {replica_id: SolveService}.

    `check()` is the single entry point: the router calls it from its
    submit/step/drain paths. Dead-thread detection runs on EVERY call
    (a dead scheduler must not wait out a rate limiter); heartbeat
    counting (wedge/slow) runs at most once per `check_s` per replica,
    so the SUSPECT counter counts real no-progress WINDOWS, not
    back-to-back submits that never gave the scheduler a chance to
    run."""

    def __init__(self, replicas, *, policy: Optional[str] = None,
                 suspect_checks: int = 4, probe_backoff_s: float = 0.05,
                 check_s: float = 0.25, warmup_s: float = 1.0,
                 slow_cycle_s: float = 0.0):
        self.replicas = replicas
        self.policy = parse_fleet_policy(
            DEFAULT_FLEET_POLICY if policy is None else policy)
        self.suspect_checks = max(1, int(suspect_checks))
        self.probe_backoff_s = float(probe_backoff_s)
        self.check_s = float(check_s)
        self.warmup_s = float(warmup_s)
        self.slow_cycle_s = float(slow_cycle_s)
        self._lock = threading.Lock()
        self._b: Dict[str, ReplicaBreaker] = {
            rid: ReplicaBreaker(rid) for rid in replicas}
        now = time.monotonic()
        for rid, br in self._b.items():
            br.last_hb_t = now
            br.last_cycle = replicas[rid]._cycle
        self._publish_available()

    # -- reads -------------------------------------------------------------
    def breaker(self, rid: str) -> ReplicaBreaker:
        return self._b[rid]

    def available(self, rid: str) -> bool:
        return self._b[rid].available

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        now = time.monotonic()
        with self._lock:
            return {rid: br.snapshot(now) for rid, br in self._b.items()}

    def _publish_available(self):
        _tm.set_gauge("fleet.health.available",
                      sum(1 for br in self._b.values() if br.available))

    # -- transitions -------------------------------------------------------
    def _record(self, rid: str, event: str, **fields):
        """One transition: flight event + span mark (the postmortem
        trail AND the Perfetto timeline both carry it)."""
        _fr.record("fleet.health", replica=rid, event=event, **fields)
        _spans.mark("fleet.health.transition",
                    args=dict(replica=rid, event=event, **fields))

    def _apply(self, br: ReplicaBreaker, event: str,
               err: Optional[BaseException], now: float
               ) -> Optional[Verdict]:
        """Run one detected event through the policy chain (lock
        held). Returns a failover verdict for the router, or None when
        the chain handled it breaker-side."""
        chain = self.policy.get(event) or ["failover"]
        action = chain[min(br.failures, len(chain) - 1)]
        n = br.failures
        br.failures += 1
        br.last_event = event
        self._record(br.rid, event, action=action, failures=br.failures,
                     error=None if err is None else str(err)[:120])
        if action == "ignore":
            return None
        if action == "probe_backoff":
            br.state = OPEN
            br.probe_fp = None
            # bounded exponential backoff (exponent capped so a
            # repeat-offender replica re-probes within minutes, not
            # geologic time)
            br.not_before = now + self.probe_backoff_s * (2 ** min(n, 6))
            _tm.inc("fleet.health.breaker_open")
            self._publish_available()
            return None
        return (br.rid, event, "failover", err)

    def note_error(self, rid: str, err: BaseException):
        """Router-side capture: an inline-driven replica's step()
        raised. Stored on the service exactly where the background
        loop would put it, so the next check() sees one code path."""
        svc = self.replicas[rid]
        if getattr(svc, "_thread_error", None) is None:
            svc._thread_error = err

    def mark_down(self, rid: str):
        """Failover ran (router-side): pin the breaker OPEN until
        restore_replica."""
        with self._lock:
            br = self._b[rid]
            br.down = True
            br.state = OPEN
            br.probe_fp = None
            _tm.inc("fleet.health.down")
            self._record(rid, "DOWN")
            self._publish_available()

    def drain(self, rid: str):
        with self._lock:
            br = self._b[rid]
            if br.draining:
                return
            br.draining = True
            _tm.inc("fleet.health.drains")
            self._record(rid, "DRAINING")
            self._publish_available()

    def restore(self, rid: str, now: Optional[float] = None):
        """Re-enter rendezvous: breaker reset to CLOSED with a cold-
        placement warm-up grace (rehomed fingerprints are NOT pulled
        back — snap-back is by natural eviction only)."""
        now = time.monotonic() if now is None else now
        svc = self.replicas[rid]
        with self._lock:
            br = self._b[rid]
            br.down = False
            br.draining = False
            br.state = CLOSED
            br.failures = 0
            br.stale = 0
            br.probe_fp = None
            br.warm_until = now + self.warmup_s
            br.last_cycle = svc._cycle
            br.last_hb_t = now
            svc._thread_error = None
            _tm.inc("fleet.health.restores")
            self._record(rid, "RESTORED",
                         warmup_s=round(self.warmup_s, 3))
            self._publish_available()

    def probe_admit(self, rid: str, fp: str) -> bool:
        """HALF_OPEN admission control: exactly one trial fingerprint
        passes; everything else diverts until the breaker closes."""
        with self._lock:
            br = self._b[rid]
            if br.state != HALF_OPEN:
                return br.available
            if br.probe_fp is None:
                br.probe_fp = fp
                br.probe_base = self.replicas[rid].completed_total
                _tm.inc("fleet.health.probe_trials")
                self._record(rid, "PROBE", fingerprint=fp[:24])
                return True
            return br.probe_fp == fp

    # -- the periodic check ------------------------------------------------
    def check(self, now: Optional[float] = None) -> List[Verdict]:
        """Sample every replica once; returns the failover verdicts
        the router must act on. Cheap enough for the submit path: a
        few attribute reads per replica, heartbeat bookkeeping rate-
        limited to one sample per `check_s`."""
        now = time.monotonic() if now is None else now
        verdicts: List[Verdict] = []
        with self._lock:
            for rid, svc in self.replicas.items():
                br = self._b[rid]
                if br.down:
                    continue
                # OPEN -> HALF_OPEN once the backoff elapsed
                if br.state == OPEN and now >= br.not_before:
                    br.state = HALF_OPEN
                    br.probe_fp = None
                    br.probe_base = svc.completed_total
                    _tm.inc("fleet.health.breaker_half_open")
                    self._record(rid, "HALF_OPEN")
                    self._publish_available()
                # dead scheduler: captured exception, or a started
                # thread that is no longer alive without stop() — runs
                # on EVERY check (never rate-limited)
                err = getattr(svc, "_thread_error", None)
                th = svc._thread
                dead = err is not None or (
                    th is not None and not th.is_alive()
                    and not svc._stopping)
                if dead:
                    _tm.inc("fleet.health.dead")
                    v = self._apply(br, "REPLICA_DEAD", err, now)
                    if v is not None:
                        verdicts.append(v)
                    continue
                # HALF_OPEN probe success: any completion since the
                # probe began is proof of end-to-end progress
                if br.state == HALF_OPEN \
                        and svc.completed_total > br.probe_base:
                    br.state = CLOSED
                    br.failures = 0
                    br.stale = 0
                    br.probe_fp = None
                    _tm.inc("fleet.health.breaker_closed")
                    self._record(rid, "CLOSED")
                    self._publish_available()
                # heartbeat window (rate-limited)
                if now - br.last_hb_t < self.check_s:
                    continue
                cycle = svc._cycle
                dt, dc = now - br.last_hb_t, cycle - br.last_cycle
                br.last_hb_t = now
                br.last_cycle = cycle
                busy = not svc.idle
                # An active builder thread is progress even when the
                # scheduler cycle counter flatlines: long AMG setups
                # (full resetup, bucket compile) must not read as a
                # wedged scheduler.  The chaos wedge drill stalls the
                # scheduler itself, with no build in flight.
                if busy and dc == 0 and not svc._builds:
                    br.stale += 1
                    if br.stale == 1:
                        _tm.inc("fleet.health.suspect")
                        self._record(rid, "SUSPECT", cycle=cycle)
                    if br.stale >= self.suspect_checks:
                        br.stale = 0
                        _tm.inc("fleet.health.wedged")
                        v = self._apply(br, "REPLICA_WEDGED", None, now)
                        if v is not None:
                            verdicts.append(v)
                    continue
                br.stale = 0
                if busy and dc > 0 and self.slow_cycle_s > 0 \
                        and dt / dc > self.slow_cycle_s:
                    _tm.inc("fleet.health.slow")
                    v = self._apply(br, "REPLICA_SLOW", None, now)
                    if v is not None:
                        verdicts.append(v)
        return verdicts
