"""AOT-exported bucket executables (`jax.export`).

The first request of a serving bucket pays the Python trace of the
whole preconditioned solve cycle — at 256^3 that is seconds of host
work before the first byte of device compute. The hierarchy cache
amortizes it within a process; this store amortizes it ACROSS
processes: each bucket's engine functions (single-system init, batched
chunk step, batched finalize) are exported with `jax.export`, the
serialized StableHLO persisted under a key derived from the pattern
fingerprint and the bucket geometry, and a restarted service loads
them instead of retracing (`serving.retrace` stays 0; XLA compilation
of the embedded module still runs, but that hits the persistent
compilation cache).

The exported functions are FLAT (positional array leaves in, tuple of
array leaves out): pytree containers never enter the serialized
artifact, so custom nodes (CsrMatrix, level payloads) need no
serialization support — the engine flattens/unflattens around the
call using treedefs it reconstructs from the bundle's sidecar
metadata (the solve state is a flat dict of arrays; its sorted key
list fully determines the treedef).

Artifacts are keyed additionally on the jax version and backend
platform: a mismatched module fails deserialization anyway, the key
just makes the miss cheap and the store multi-platform-safe.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence

from ..profiling import trace_region


def _digest(parts) -> str:
    import jax
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((tuple(parts), jax.__version__,
                   jax.default_backend())).encode())
    return h.hexdigest()


class AotStore:
    """Directory-backed store of exported bucket executables."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, key: str, name: str) -> str:
        return os.path.join(self.directory, f"{key}.{name}")

    def key(self, parts: Sequence[Any]) -> str:
        return _digest(parts)

    # -- save -------------------------------------------------------------
    def save_bundle(self, key: str, fns: Dict[str, Any],
                    meta: Dict[str, Any]) -> bool:
        """Export and persist `fns` ({name: (flat_jit_fn, flat_args)})
        plus the sidecar metadata. All-or-nothing: a failed export
        removes the partial bundle and reports False (the engine keeps
        its traced functions; `serving.aot.error` counts it)."""
        from ..resilience import faultinject as _fi
        from ..telemetry import metrics as _tm
        try:
            from jax import export as jexport
            with trace_region("serving.aot_export"):
                blobs = {}
                for name, (fn, args) in fns.items():
                    exp = jexport.export(fn)(*args)
                    blobs[name] = exp.serialize()
                for name, blob in blobs.items():
                    # chaos torn-write drill: damage lands on disk,
                    # the load path must detect it and degrade to
                    # tracing — never serve a half-written module
                    blob = _fi.corrupt_blob("aot_corrupt", blob)
                    with open(self._path(key, name) + ".bin", "wb") as f:
                        f.write(blob)
                with open(self._path(key, "meta") + ".json", "w") as f:
                    json.dump(meta, f)
            _tm.inc("serving.aot.export")
            return True
        except Exception:
            _tm.inc("serving.aot.error")
            for name in list(fns) + ["meta"]:
                for ext in (".bin", ".json"):
                    try:
                        os.remove(self._path(key, name) + ext)
                    except OSError:
                        pass
            return False

    # -- load -------------------------------------------------------------
    def load_meta(self, key: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(key, "meta") + ".json") as f:
                return json.load(f)
        except Exception:
            return None

    def load_bundle(self, key: str, names: List[str]):
        """Load `{name: callable(*flat_leaves) -> tuple(leaves)}` for a
        complete bundle, or None (missing/corrupt/mismatched — the
        engine then traces as usual). The deserialized calls are
        wrapped in one jax.jit each so repeat invocations replay the
        compiled module instead of re-staging it."""
        from ..telemetry import metrics as _tm
        meta = self.load_meta(key)
        if meta is None:
            return None
        try:
            import jax
            from jax import export as jexport
            with trace_region("serving.aot_load"):
                out = {}
                for name in names:
                    with open(self._path(key, name) + ".bin", "rb") as f:
                        blob = f.read()
                    out[name] = jax.jit(jexport.deserialize(blob).call)
            _tm.inc("serving.aot.load")
            out["meta"] = meta
            return out
        except Exception:
            _tm.inc("serving.aot.error")
            return None
