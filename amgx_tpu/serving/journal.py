"""Durable solve journal: request records + solve checkpoints.

A killed serving process must not lose in-flight work. The journal is
the service's write-ahead record of every submitted request (matrix
values + rhs + tenant/deadline metadata, with the sparsity pattern
deduplicated per fingerprint) plus periodic CHECKPOINTS of the chunked
while_loop solve state (serving/engine.py carries it as a flat dict of
arrays, so a per-slot row snapshots losslessly). A restarted service
replays the journal: pending requests are re-admitted, and one that
was checkpointed resumes from its saved iterate — the resumed solve
visits bit-identical iterates to an uninterrupted run, because the
chunked entry (`Solver._build_chunk_fns`) was built to be resumable
across host boundaries in the first place.

Completed requests keep their result in the journal (bounded by
`prune`) so a client retrying a submit after a dropped response — the
`request_key` idempotency contract — gets the recorded result back
instead of a second solve.

Failure model: every record write is atomic (tmp + rename) and every
read is corruption-tolerant — a torn/corrupt record is dropped (and
counted, serving.recovery.journal_corrupt), never replayed wrong and
never allowed to wedge recovery of the records around it.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..matrix import CsrMatrix
from ..profiling import trace_region

_CKPT_PREFIX = "state."


def _fp_digest(fingerprint: str) -> str:
    return hashlib.blake2b(str(fingerprint).encode(),
                           digest_size=12).hexdigest()


class SolveJournal:
    """Directory-backed request journal (see module docs)."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        # guards _seq allocation and the _index/_keys maps: submit()
        # journals from caller threads while the scheduler records
        # completions — an unsynchronized _seq would mint duplicate
        # ids and silently overwrite one request's record with
        # another's
        self._lock = threading.Lock()
        # meta index built once per open: id -> record dict; corrupt
        # json records are dropped (counted at replay, where it is an
        # actual loss, not here at bookkeeping time)
        self._index: Dict[str, Dict[str, Any]] = {}
        self._keys: Dict[str, str] = {}
        seqs = [0]
        for name in sorted(os.listdir(self.directory)):
            if not (name.startswith("req-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    meta = json.load(f)
                jid = meta["id"]
            except Exception:
                continue
            self._index[jid] = meta
            if meta.get("key"):
                self._keys[meta["key"]] = jid
            seqs.append(int(meta.get("seq", 0)))
        self._seq = max(seqs) + 1

    # -- paths ------------------------------------------------------------
    def _jpath(self, jid: str, ext: str) -> str:
        return os.path.join(self.directory, f"req-{jid}.{ext}")

    def _ppath(self, fingerprint: str) -> str:
        return os.path.join(self.directory,
                            f"pattern-{_fp_digest(fingerprint)}.npz")

    def _wpath(self, fingerprint: str) -> str:
        return os.path.join(self.directory,
                            f"workload-{_fp_digest(fingerprint)}.npz")

    def _write_npz(self, path: str, arrays: Dict[str, np.ndarray]):
        """Atomic npz write, through the chaos corruption hook (the
        torn-write drill: damage lands on disk, detection is the
        reader's job)."""
        from ..resilience import faultinject as _fi
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        blob = _fi.corrupt_blob("journal_corrupt", buf.getvalue())
        with open(path + ".tmp", "wb") as f:
            f.write(blob)
        os.replace(path + ".tmp", path)

    def _write_json(self, path: str, obj: Dict[str, Any]):
        with open(path + ".tmp", "w") as f:
            json.dump(obj, f)
        os.replace(path + ".tmp", path)

    @staticmethod
    def _read_npz(path: str) -> Optional[Dict[str, np.ndarray]]:
        try:
            with open(path, "rb") as f:
                data = np.load(io.BytesIO(f.read()), allow_pickle=False)
            return {k: data[k] for k in data.files}
        except Exception:
            return None

    # -- write path --------------------------------------------------------
    def record_submit(self, *, fingerprint: str, tenant: str,
                      A: CsrMatrix, b: np.ndarray,
                      x0: Optional[np.ndarray],
                      deadline_remaining_s: Optional[float],
                      request_key: Optional[str],
                      trace_id: Optional[str] = None) -> str:
        """Persist one request; returns its journal id. The pattern
        (index arrays + shape metadata) is written once per
        fingerprint, the per-request record holds only values/rhs.
        `trace_id` is the request's span-flow trace id: persisting it
        is what lets a crash-recovered resume tag its spans with the
        ORIGINAL trace (one connected Perfetto chain across both
        service incarnations) and lets tools/flightrec.py join the
        flight-recorder trail to journal records."""
        with self._lock:
            seq, self._seq = self._seq, self._seq + 1
        jid = f"{seq:08d}"
        ppath = self._ppath(fingerprint)
        if not os.path.exists(ppath):
            pat = {"row_offsets": np.asarray(A.row_offsets),
                   "col_indices": np.asarray(A.col_indices),
                   "shape_meta": np.asarray(
                       [A.num_rows, A.num_cols, A.block_dimx,
                        A.block_dimy], np.int64)}
            if A.grid_shape is not None:
                pat["grid_shape"] = np.asarray(A.grid_shape, np.int64)
            self._write_npz(ppath, pat)
        arrays = {"values": np.asarray(A.values), "b": np.asarray(b)}
        if A.diag is not None:
            arrays["diag"] = np.asarray(A.diag)
        if x0 is not None:
            arrays["x0"] = np.asarray(x0)
        self._write_npz(self._jpath(jid, "npz"), arrays)
        meta = {"id": jid, "seq": seq, "key": request_key or None,
                "tenant": str(tenant), "fingerprint": str(fingerprint),
                "deadline_remaining_s": deadline_remaining_s,
                "trace": trace_id or None,
                "status": "pending"}
        self._write_json(self._jpath(jid, "json"), meta)
        with self._lock:
            self._index[jid] = meta
            if request_key:
                self._keys[request_key] = jid
        return jid

    def record_checkpoint(self, jid: str,
                          state_row: Dict[str, np.ndarray],
                          deadline_remaining_s: Optional[float]):
        """Snapshot one in-flight slot's solve state at a cycle
        boundary (the resumable chunk state: iterate, residual, norms,
        history, iteration counter — whatever the solver carries)."""
        from ..telemetry import metrics as _tm
        arrays = {_CKPT_PREFIX + k: np.asarray(v)
                  for k, v in state_row.items()}
        if deadline_remaining_s is not None:
            arrays["deadline_remaining_s"] = np.asarray(
                float(deadline_remaining_s))
        self._write_npz(self._jpath(jid, "ckpt.npz"), arrays)
        _tm.inc("serving.recovery.checkpoints")

    def record_done(self, jid: str, x: np.ndarray, status_code: int,
                    iterations: int):
        """Mark a request terminal and keep its result for request_key
        dedupe of retried submits."""
        with self._lock:
            meta = self._index.get(jid)
        if meta is None:
            return
        self._write_npz(self._jpath(jid, "done.npz"),
                        {"x": np.asarray(x),
                         "status_code": np.asarray(int(status_code)),
                         "iterations": np.asarray(int(iterations))})
        meta = dict(meta)
        meta["status"] = "done"
        self._write_json(self._jpath(jid, "json"), meta)
        with self._lock:
            self._index[jid] = meta
        for ext in ("npz", "ckpt.npz"):
            try:
                os.remove(self._jpath(jid, ext))
            except OSError:
                pass

    def save_workload(self, fingerprint: str, A: CsrMatrix,
                      b: np.ndarray):
        """Retain ONE (values, rhs) sample per fingerprint — the
        autotuner's shadow-solve input. Per-request records are
        deleted at record_done (the journal is a crash log, not an
        archive), so the tuner's workload persists separately: one
        bounded file per fingerprint, overwritten by newer samples,
        riding the pattern file record_submit already deduplicates.
        Best-effort: a failed write only costs the tuner its
        restart-surviving workload, never the journal's guarantees."""
        try:
            ppath = self._ppath(fingerprint)
            if not os.path.exists(ppath):
                pat = {"row_offsets": np.asarray(A.row_offsets),
                       "col_indices": np.asarray(A.col_indices),
                       "shape_meta": np.asarray(
                           [A.num_rows, A.num_cols, A.block_dimx,
                            A.block_dimy], np.int64)}
                if A.grid_shape is not None:
                    pat["grid_shape"] = np.asarray(A.grid_shape,
                                                   np.int64)
                self._write_npz(ppath, pat)
            arrays = {"values": np.asarray(A.values),
                      "b": np.asarray(b)}
            if A.diag is not None:
                arrays["diag"] = np.asarray(A.diag)
            self._write_npz(self._wpath(fingerprint), arrays)
        except Exception:
            pass

    def load_workload(self, fingerprint: str
                      ) -> Optional[Tuple[CsrMatrix, np.ndarray]]:
        """The retained (A, b) workload sample for a fingerprint, or
        None (never saved / corrupt — corruption-tolerant like every
        journal read)."""
        pat = self._read_npz(self._ppath(fingerprint))
        wl = self._read_npz(self._wpath(fingerprint))
        if pat is None or wl is None or "row_offsets" not in pat \
                or "values" not in wl or "b" not in wl:
            return None
        try:
            nr, nc, bx, by = (int(v) for v in pat["shape_meta"])
            gs = pat.get("grid_shape")
            A = CsrMatrix(
                row_offsets=pat["row_offsets"],
                col_indices=pat["col_indices"],
                values=wl["values"], diag=wl.get("diag"),
                num_rows=nr, num_cols=nc,
                block_dimx=bx, block_dimy=by,
                grid_shape=None if gs is None
                else tuple(int(v) for v in gs))
        except Exception:
            return None
        return A, wl["b"]

    # -- read path ---------------------------------------------------------
    def lookup_key(self, request_key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            jid = self._keys.get(request_key)
            return self._index.get(jid) if jid else None

    def load_result(self, jid: str):
        """(x, status_code, iterations) of a done record, or None."""
        data = self._read_npz(self._jpath(jid, "done.npz"))
        if data is None or "x" not in data:
            return None
        return (data["x"], int(data["status_code"]),
                int(data["iterations"]))

    def pending(self) -> List[Dict[str, Any]]:
        """Pending records in submit order (the replay list)."""
        with self._lock:
            recs = [m for m in self._index.values()
                    if m.get("status") == "pending"]
        return sorted(recs, key=lambda m: int(m.get("seq", 0)))

    def load_request(self, meta: Dict[str, Any]
                     ) -> Optional[Tuple[CsrMatrix, np.ndarray,
                                         Optional[np.ndarray],
                                         Optional[Dict[str, np.ndarray]],
                                         Optional[float]]]:
        """Rebuild one journaled request: (A, b, x0, checkpoint_state,
        deadline_remaining_s). None when the pattern or request record
        is corrupt (counted; the caller skips it). A corrupt CHECKPOINT
        only loses the resume point — the request restarts clean."""
        from ..telemetry import metrics as _tm
        with trace_region("serving.recover"):
            ppath = self._ppath(meta["fingerprint"])
            pat = self._read_npz(ppath)
            req = self._read_npz(self._jpath(meta["id"], "npz"))
            if pat is None or "row_offsets" not in pat:
                # SELF-HEAL: a corrupt pattern file would otherwise
                # poison every future record of this fingerprint
                # (record_submit skips existing pattern files) — drop
                # it so the next submit rewrites a clean one
                try:
                    os.remove(ppath)
                except OSError:
                    pass
                pat = None
            if pat is None or req is None \
                    or "values" not in req or "b" not in req:
                _tm.inc("serving.recovery.journal_corrupt")
                return None
            nr, nc, bx, by = (int(v) for v in pat["shape_meta"])
            gs = pat.get("grid_shape")
            A = CsrMatrix(
                row_offsets=pat["row_offsets"],
                col_indices=pat["col_indices"],
                values=req["values"], diag=req.get("diag"),
                num_rows=nr, num_cols=nc,
                block_dimx=bx, block_dimy=by,
                grid_shape=None if gs is None
                else tuple(int(v) for v in gs))
            ckpt = self._read_npz(self._jpath(meta["id"], "ckpt.npz"))
            remaining = meta.get("deadline_remaining_s")
            state = None
            if ckpt is not None:
                state = {k[len(_CKPT_PREFIX):]: v
                         for k, v in ckpt.items()
                         if k.startswith(_CKPT_PREFIX)}
                if not state:
                    state = None
                if "deadline_remaining_s" in ckpt:
                    remaining = float(ckpt["deadline_remaining_s"])
            return (A, req["b"], req.get("x0"), state,
                    None if remaining is None else float(remaining))

    def load_checkpoint(self, jid: str
                        ) -> Tuple[Optional[Dict[str, np.ndarray]],
                                   Optional[float]]:
        """(checkpoint_state, deadline_remaining_s) for one pending
        record — the fleet-failover path: a survivor adopting a dead
        replica's LIVE in-flight ticket needs only the resume point
        (it already holds A/b/x0 on the ticket object), not the full
        load_request rebuild. A missing/corrupt checkpoint returns
        (None, submit-time remaining): the solve restarts clean with
        its original budget."""
        with self._lock:
            meta = self._index.get(jid)
        remaining = None if meta is None \
            else meta.get("deadline_remaining_s")
        ckpt = self._read_npz(self._jpath(jid, "ckpt.npz"))
        state = None
        if ckpt is not None:
            state = {k[len(_CKPT_PREFIX):]: v
                     for k, v in ckpt.items()
                     if k.startswith(_CKPT_PREFIX)}
            if not state:
                state = None
            if "deadline_remaining_s" in ckpt:
                remaining = float(ckpt["deadline_remaining_s"])
        return state, (None if remaining is None else float(remaining))

    # -- maintenance -------------------------------------------------------
    def forget(self, jid: str):
        """Drop one record entirely (corrupt-record cleanup)."""
        with self._lock:
            meta = self._index.pop(jid, None)
            if meta and meta.get("key"):
                self._keys.pop(meta["key"], None)
        for ext in ("json", "npz", "ckpt.npz", "done.npz"):
            try:
                os.remove(self._jpath(jid, ext))
            except OSError:
                pass

    def prune(self, keep_done: int = 256):
        """Bound the done-record history (oldest dropped first); the
        journal must not grow without bound under steady traffic.
        Called by the service at recovery and on a periodic scheduler
        cadence."""
        with self._lock:
            done = sorted((m for m in self._index.values()
                           if m.get("status") == "done"),
                          key=lambda m: int(m.get("seq", 0)))
        for meta in done[:max(0, len(done) - keep_done)]:
            self.forget(meta["id"])
