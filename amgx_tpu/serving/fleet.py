"""Fleet serving: a fingerprint-affine router over SolveService
replicas.

PR 11 made one replica crash-safe and overload-safe; PR 13 gave it
replica-labeled metrics and cross-incarnation trace chains. This
module is the scale-out layer on top: a `FleetRouter` fronts N
`SolveService` replicas behind the same submit/step/drain/ticket API,
so a caller (or the `AMGX_fleet_*` C surface) talks to one serving
endpoint while requests land on the replica most likely to serve them
cheaply.

Why affinity keys on the PATTERN FINGERPRINT: everything expensive a
replica holds — its hierarchy cache buckets, persisted structures,
AOT-exported executables, even its retry/backoff fault state — is
fingerprint-keyed. A replica warm for a fingerprint serves it with a
value-only resetup (milliseconds); a cold one pays a full coarsening
plus traces (seconds). Placement is therefore the dominant fleet-level
lever, and it must be STICKY: rendezvous (highest-random-weight)
hashing gives every fingerprint a stable candidate order over the
replica set, so adding or removing a replica reshuffles only the
fingerprints that hashed to it.

Routing classes (counted per decision, `fleet.route.*`):

- `cold` — first sighting of a fingerprint: placed on the
  least-loaded replica (live queue depth x recent exec estimate, ties
  broken by rendezvous order) which becomes its home;
- `warm` — the home replica takes it (the steady state);
- `spill` — the home is overloaded (queue depth past
  `fleet_spill_depth` AND a strictly less-loaded candidate exists),
  quarantine-looping on this fingerprint (its fault/backoff state is
  live), or deadline-infeasible while another replica's estimate says
  feasible: the request diverts to the next rendezvous candidate and
  the flight recorder gets a `fleet.handoff` note. Quarantine spills
  REHOME the fingerprint (the sick replica stays its rendezvous
  candidate, but the warm state now grows elsewhere); load spills
  don't.

Shed decisions consult the FLEET-WIDE aggregate: per-replica
feasibility estimates plus the merged per-tenant latency histograms
(`metrics.merge_snapshots` over the replica-labeled series, read via
`metrics.quantile_where`). When every replica judges a deadline
unmeetable the router routes home anyway — the home replica's shed
policy completes the request honestly OVERLOADED — and counts
`fleet.shed.infeasible` with the estimates it decided on in the
flight recorder.

Trace attribution: every routed ticket gains `.replica`/`.route`
attributes and, when tracing is on, a `fleet.route` instant event on
its flow chain — so `tools/flightrec.py --trace <id>` and the
Perfetto export both say which replica served a request across a
cross-replica postmortem.

**Fault tolerance (PR 17).** The router owns a `HealthMonitor`
(serving/health.py) and ticks it from submit/step/drain. Routing is
availability-aware: DOWN, draining and breaker-OPEN replicas take no
traffic, a HALF_OPEN replica admits exactly one probe fingerprint,
and a just-restored replica sits out COLD placements for a warm-up
grace. When the monitor's `fleet_fault_policy` chain says `failover`,
`_failover()` runs the zero-loss DOWN path: the dead replica's queued
AND in-flight tickets move to survivors (in-flight resume from their
last journal checkpoint, deadlines re-anchored as remaining budget),
its fingerprints rehome along rendezvous order, and the least-loaded
survivor ADOPTS its journal — pending records replay cross-replica
under their original trace ids, completions settle back into the
adopted journal so nothing double-replays. With no survivor left the
outstanding tickets complete BREAKDOWN with the captured error
(`ticket.error`) instead of wedging drain. `drain_replica()` /
`restore_replica()` give rolling restarts the same guarantees
administratively.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..batch.queue import pattern_fingerprint
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..telemetry import flightrec as _fr
from ..telemetry import metrics as _tm
from ..telemetry import spans as _spans
from .health import CLOSED, HALF_OPEN, HealthMonitor
from .service import ServiceTicket, SolveService, _now


def _rendezvous_score(fingerprint: str, rid: str) -> int:
    """Highest-random-weight score of (fingerprint, replica): stable
    across processes and python hash seeds (the journal may hand a
    restarted fleet the same fingerprints)."""
    h = hashlib.blake2b(f"{fingerprint}@{rid}".encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


class FleetRouter:
    """N `SolveService` replicas behind one submit/step/drain/ticket
    surface. Accepts a dict {replica_id: service} or a list of
    services; entries without an identity (no dict key, no
    pre-assigned `.replica` attribute) get distinct derived ids
    `r0..rN-1` — two unlabeled replicas in one process must never
    scrape identically (their latency series would silently merge)."""

    def __init__(self, replicas, *, spill_depth: int = 0,
                 fault_policy: Optional[str] = None,
                 suspect_checks: int = 4,
                 probe_backoff_s: float = 0.05,
                 health_check_s: float = 0.25,
                 warmup_s: float = 1.0,
                 slow_cycle_s: float = 0.0):
        if isinstance(replicas, dict):
            items = list(replicas.items())
        else:
            items = [(None, svc) for svc in replicas]
        if not items:
            raise BadParametersError(
                "FleetRouter: at least one replica required")
        self.replicas: Dict[str, SolveService] = {}
        taken = {rid for rid, svc in items
                 if rid or getattr(svc, "replica", "")}
        auto = 0
        for rid, svc in items:
            rid = str(rid or getattr(svc, "replica", "") or "")
            if not rid:
                while f"r{auto}" in taken:
                    auto += 1
                rid = f"r{auto}"
                taken.add(rid)
            if rid in self.replicas:
                raise BadParametersError(
                    f"FleetRouter: duplicate replica id {rid!r}")
            svc.replica = rid      # labels this replica's metric series
            self.replicas[rid] = svc
        for svc in self.replicas.values():
            # in-process replicas share ONE execution device: each
            # one's exec window undercounts wall latency by the number
            # of co-residents competing for the core, so feasibility
            # estimates (shed decisions, spill reads, fleet consults)
            # scale by the fleet size
            svc.exec_share = float(len(self.replicas))
        self.spill_depth = int(spill_depth)
        self._lock = threading.Lock()
        # fingerprint -> home replica id (sticky placement)
        self._placed: Dict[str, str] = {}
        # request_key -> replica id: a retried idempotent submit must
        # land on the replica holding (or journaling) the original
        self._keyed: Dict[str, str] = {}
        self.route_counts: Dict[str, Dict[str, int]] = {
            rid: {"warm": 0, "cold": 0, "spill": 0}
            for rid in self.replicas}
        self.health = HealthMonitor(
            self.replicas, policy=fault_policy,
            suspect_checks=suspect_checks,
            probe_backoff_s=probe_backoff_s, check_s=health_check_s,
            warmup_s=warmup_s, slow_cycle_s=slow_cycle_s)
        # the poll cadence start() last used: restore_replica restarts
        # a restored replica's scheduler iff the fleet runs background
        self._bg_poll: Optional[float] = None
        _tm.set_gauge("fleet.replicas", len(self.replicas))

    @classmethod
    def build(cls, cfg: Config, n_replicas: Optional[int] = None,
              scope: str = "default") -> "FleetRouter":
        """N replicas from ONE config (default `fleet_replicas`).
        Each gets a derived replica id; a configured
        `serving_journal_dir` gains a per-replica subdirectory — a
        journal's replay owns its records, two replicas must not
        replay each other's — while the AOT and hierarchy stores stay
        shared (fingerprint-keyed: one replica's export warms every
        replica's restart)."""
        n = int(cfg.get("fleet_replicas", scope)
                if n_replicas is None else n_replicas)
        if n < 1:
            raise BadParametersError(
                f"FleetRouter.build: need >= 1 replica, got {n}")
        jdir = str(cfg.get("serving_journal_dir", scope)).strip()
        base_id = str(cfg.get("serving_replica_id", scope)).strip()
        replicas: Dict[str, SolveService] = {}
        for i in range(n):
            rid = f"{base_id}{i}" if base_id else f"r{i}"
            c = cfg.clone()
            # the id is assigned as the service ATTRIBUTE below (via
            # __init__), not through serving_replica_id — the knob
            # also sets the process-global scrape label, and N
            # in-process replicas must not fight over it
            if jdir:
                c.set("serving_journal_dir",
                      os.path.join(jdir, rid), scope)
            svc = SolveService(c, scope=scope)
            svc.replica = rid
            replicas[rid] = svc
        return cls(replicas,
                   spill_depth=int(cfg.get("fleet_spill_depth",
                                           scope)),
                   fault_policy=str(cfg.get("fleet_fault_policy",
                                            scope)),
                   suspect_checks=int(cfg.get("fleet_suspect_checks",
                                              scope)),
                   probe_backoff_s=float(
                       cfg.get("fleet_probe_backoff_s", scope)),
                   health_check_s=float(
                       cfg.get("fleet_health_check_s", scope)),
                   warmup_s=float(cfg.get("fleet_warmup_s", scope)),
                   slow_cycle_s=float(
                       cfg.get("fleet_slow_cycle_s", scope)))

    # -- load/feasibility reads -------------------------------------------
    def _queue_depth(self, svc: SolveService) -> int:
        with svc._lock:
            return len(svc._queue)

    def _load(self, svc: SolveService) -> float:
        """Live load: (queue depth + in-flight) x the replica's recent
        exec estimate (1.0 while untrained, so cold placement on an
        empty fleet degenerates to fewest-requests)."""
        with svc._lock:
            depth = len(svc._queue) + svc._inflight()
            if len(svc._exec_recent) >= 1:
                window = sorted(svc._exec_recent)
                est = float(window[len(window) // 2])
            else:
                est = 1.0
        return depth * max(est, 1e-9) + 1e-12 * depth

    def _estimate(self, svc: SolveService) -> Optional[float]:
        with svc._lock:
            return svc._estimate_latency_s()

    def _spill_limit(self, svc: SolveService) -> int:
        return self.spill_depth or max(2 * svc.slots, 2)

    # -- routing -----------------------------------------------------------
    def _healthy(self, rid: str, now: float,
                 cold: bool = False) -> bool:
        """May `rid` take regular (non-probe) traffic? CLOSED breaker,
        not down, not draining — and for COLD placements, past its
        restore warm-up grace (a just-restored empty replica would
        otherwise instantly be the least-loaded home for every new
        fingerprint). Lock-free: breaker fields are plain scalars."""
        br = self.health.breaker(rid)
        if br.down or br.draining or br.state != CLOSED:
            return False
        if cold and now < br.warm_until:
            return False
        return True

    def _route(self, fp: str, tenant: str,
               deadline_s: Optional[float]):
        """(replica id, route class, handoff, consult): the whole
        decision under the router lock — placement map reads/writes
        must not interleave across concurrent submits."""
        now_m = time.monotonic()
        with self._lock:
            order = sorted(
                self.replicas,
                key=lambda r: _rendezvous_score(fp, r), reverse=True)
            home = self._placed.get(fp)
            if home is None or home not in self.replicas:
                # cold placement: healthy-and-warmed-up first, then
                # healthy, then anything not down — an all-down fleet
                # still routes (the ticket waits for a restore; a
                # refusal would lose it outright)
                cands = [r for r in order
                         if self._healthy(r, now_m, cold=True)] \
                    or [r for r in order if self._healthy(r, now_m)] \
                    or [r for r in order
                        if not self.health.breaker(r).down] \
                    or order
                loads = {rid: self._load(self.replicas[rid])
                         for rid in cands}
                rid = min(cands,
                          key=lambda r: (loads[r], order.index(r)))
                self._placed[fp] = rid
                return rid, "cold", None, None
            home_svc = self.replicas[home]
            br_home = self.health.breaker(home)
            if br_home.down or br_home.draining \
                    or br_home.state != CLOSED:
                # the home can't take regular traffic. HALF_OPEN
                # admits exactly ONE trial fingerprint (the breaker
                # probe); everything else diverts to the next healthy
                # rendezvous candidate
                if br_home.state == HALF_OPEN and not br_home.down \
                        and not br_home.draining \
                        and self.health.probe_admit(home, fp):
                    return home, "warm", None, None
                reason = ("draining" if br_home.draining
                          else "down" if br_home.down else "breaker")
                target = next(
                    (r for r in order
                     if r != home and self._healthy(r, now_m)), None)
                if target is None:
                    # no healthy alternative: degraded beats refused
                    return home, "warm", None, None
                if br_home.down:
                    # failover rehomes placements, but a submit can
                    # race it — make the diversion sticky so the warm
                    # state grows in ONE place
                    self._placed[fp] = target
                return target, "spill", \
                    (home, reason, self._queue_depth(home_svc)), None
            cands = [r for r in order
                     if r != home and self._healthy(r, now_m)]
            # 1. quarantine-looping home: its fault/backoff state for
            # this fingerprint is live — rebuild-crash loops there
            # while a healthy replica could just serve. Rehome.
            fl = home_svc._faulted.get(fp)
            if fl is not None and cands:
                target = next(
                    (r for r in cands
                     if fp not in self.replicas[r]._faulted),
                    cands[0])
                self._placed[fp] = target
                return target, "spill", \
                    (home, "quarantine", self._queue_depth(home_svc)), \
                    None
            # 2. overloaded home: spill only toward a STRICTLY less
            # loaded candidate — a uniformly saturated fleet keeps
            # affinity (and sheds) instead of ping-ponging cold builds
            depth = self._queue_depth(home_svc)
            if cands and depth >= self._spill_limit(home_svc):
                home_load = self._load(home_svc)
                target = next(
                    (r for r in cands
                     if self._load(self.replicas[r]) < home_load
                     and self._queue_depth(self.replicas[r]) < depth),
                    None)
                if target is not None:
                    return target, "spill", \
                        (home, "overload", depth), None
            # 3. fleet-wide deadline feasibility consult. A
            # deadline-driven spill is only eligible toward a replica
            # already holding this fingerprint's bucket WARM: moving a
            # warm fingerprint to a cold replica trades a sub-second
            # value-resetup for a multi-second setup — the one hop
            # guaranteed to bust the very deadline being rescued
            if deadline_s is not None:
                est_home = self._estimate(home_svc)
                if est_home is not None \
                        and est_home > float(deadline_s):
                    ests = {rid: self._estimate(self.replicas[rid])
                            for rid in order}
                    feas = [r for r in cands
                            if (ests[r] is None
                                or ests[r] <= float(deadline_s))
                            and self.replicas[r].buckets.peek(fp)
                            is not None]
                    if feas:
                        return feas[0], "spill", \
                            (home, "deadline", depth), None
                    # infeasible everywhere: route home for the
                    # honest per-replica OVERLOADED shed, and record
                    # the fleet-wide evidence the verdict rests on
                    consult = {
                        "deadline_s": round(float(deadline_s), 6),
                        "estimates_s": {
                            rid: None if e is None
                            else round(float(e), 6)
                            for rid, e in ests.items()},
                        "tenant_p50_s": _tm.quantile_where(
                            "serving.solve_latency_s", 0.50,
                            {"tenant": tenant}),
                        "tenant_p99_s": _tm.quantile_where(
                            "serving.solve_latency_s", 0.99,
                            {"tenant": tenant}),
                    }
                    return home, "warm", None, consult
            return home, "warm", None, None

    # -- the serving surface ----------------------------------------------
    def submit(self, A: CsrMatrix, b, x0=None,
               tenant: str = "default",
               deadline_s: Optional[float] = None,
               request_key: Optional[str] = None) -> ServiceTicket:
        """Route one request to a replica and submit it there. The
        returned ticket is the replica's own (same wait/result API),
        plus `.replica` and `.route` attribution."""
        self._health_tick()
        fp = f"{pattern_fingerprint(A)}/{np.asarray(b).dtype}"
        if request_key:
            with self._lock:
                prior = self._keyed.get(request_key)
            if prior is not None and prior in self.replicas \
                    and not self.health.breaker(prior).down:
                # idempotent retry: the original's replica holds the
                # live ticket (or its journal holds the result) —
                # routing elsewhere would re-solve it
                t = self.replicas[prior].submit(
                    A, b, x0=x0, tenant=tenant,
                    deadline_s=deadline_s, request_key=request_key)
                t.replica = prior
                t.route = "warm"
                return t
        rid, route, handoff, consult = self._route(
            fp, str(tenant), deadline_s)
        svc = self.replicas[rid]
        t = svc.submit(A, b, x0=x0, tenant=tenant,
                       deadline_s=deadline_s,
                       request_key=request_key)
        t.replica = rid
        t.route = route
        # literal route-class counters (the check_spans dead-metric
        # lint wants write sites it can see)
        if route == "warm":
            _tm.inc("fleet.route.warm")
        elif route == "spill":
            _tm.inc("fleet.route.spill")
        else:
            _tm.inc("fleet.route.cold")
        with self._lock:
            self.route_counts[rid][route] += 1
            if request_key:
                self._keyed[request_key] = rid
        if t.trace_id:
            # replica attribution on the request's flow chain
            _spans.mark("fleet.route", args={
                "trace": t.trace_id, "replica": rid, "route": route})
        if handoff is not None:
            from_rid, reason, home_depth = handoff
            _fr.record("fleet.handoff", trace=t.trace_id,
                       fingerprint=fp[:24], from_replica=from_rid,
                       to_replica=rid, reason=reason,
                       home_queue_depth=home_depth)
        if consult is not None:
            _tm.inc("fleet.shed.infeasible")
            _fr.record("fleet.shed", trace=t.trace_id,
                       tenant=str(tenant), verdict="infeasible",
                       **consult)
        return t

    def step(self) -> List[ServiceTicket]:
        """One scheduler cycle on every LIVE replica (round-robin
        inline driving — the single-process analog of N schedulers);
        returns the tickets completed across the fleet. A step() that
        raises (chaos replica_kill, a real scheduler bug) is captured
        for the health monitor exactly where a background loop would
        put it, then the health tick runs the policy chain."""
        done: List[ServiceTicket] = []
        for rid, svc in self.replicas.items():
            if self.health.breaker(rid).down:
                continue
            try:
                done.extend(svc.step())
            except Exception as e:
                self.health.note_error(rid, e)
        done.extend(self._health_tick())
        return done

    @property
    def idle(self) -> bool:
        """DOWN replicas are excluded: their outstanding work was
        moved or failed terminal by _failover, and a racing builder
        thread repopulating their install map must not wedge drain."""
        return all(svc.idle for rid, svc in self.replicas.items()
                   if not self.health.breaker(rid).down)

    @property
    def completed_total(self) -> int:
        return sum(svc.completed_total
                   for svc in self.replicas.values())

    def drain(self, timeout_s: Optional[float] = None
              ) -> List[ServiceTicket]:
        """Step until every live replica is idle (or timeout).
        Replicas running their own background scheduler are waited on;
        inline-driven ones are stepped. The health monitor ticks every
        loop, so a replica whose scheduler thread died mid-drain is
        failed over (tickets move to survivors, or complete BREAKDOWN
        with the captured error when none remain) instead of spinning
        this loop to its timeout."""
        t0 = time.monotonic()
        done: List[ServiceTicket] = []
        done.extend(self._health_tick())
        while not self.idle:
            if timeout_s is not None \
                    and time.monotonic() - t0 > timeout_s:
                break
            stepped = False
            for rid, svc in self.replicas.items():
                if self.health.breaker(rid).down:
                    continue
                if svc._thread is None:
                    try:
                        done.extend(svc.step())
                    except Exception as e:
                        self.health.note_error(rid, e)
                    stepped = True
            done.extend(self._health_tick())
            if not stepped:
                time.sleep(0.001)
        return done

    def start(self, poll_s: float = 0.0005):
        self._bg_poll = poll_s
        for rid, svc in self.replicas.items():
            if not self.health.breaker(rid).down:
                svc.start(poll_s=poll_s)

    def stop(self):
        self._bg_poll = None
        for svc in self.replicas.values():
            svc.stop()

    # -- fault tolerance ---------------------------------------------------
    def _health_tick(self) -> List[ServiceTicket]:
        """One health check + the actions its verdicts demand. Called
        from submit/step/drain — cheap when nothing is wrong (a few
        scalar reads per replica). Returns tickets a no-survivor
        failover completed BREAKDOWN, so drain loops can report
        them."""
        done: List[ServiceTicket] = []
        for rid, _event, _action, err in self.health.check():
            done.extend(self._failover(rid, err, _event))
        # straggler rescue: a submit that raced a failover may have
        # queued onto a replica marked down in between — move it
        for rid, svc in self.replicas.items():
            if self.health.breaker(rid).down and svc._queue:
                self._rescue_queue(rid)
        return done

    def _failover(self, rid: str, err: Optional[BaseException],
                  event: str = "REPLICA_DEAD") -> List[ServiceTicket]:
        """The DOWN path: mark `rid` down, extract its queued AND
        in-flight tickets, rehome its fingerprints along rendezvous
        order, re-submit the tickets to survivors at the FRONT of
        their queues (in-flight ones resume from their last journal
        checkpoint with deadlines re-anchored as remaining budget),
        and have the least-loaded survivor adopt the dead replica's
        journal so its other pending records replay exactly once.
        With no survivor, everything outstanding completes BREAKDOWN
        with the captured error — terminal honesty over a wedged
        drain. Returns the tickets completed here (empty on the
        survivor path: moved work completes later, on its adopter)."""
        t0 = time.monotonic()
        svc = self.replicas[rid]
        self.health.mark_down(rid)
        svc._stopping = True       # a still-breathing loop exits
        # a DEAD scheduler's cycle lock is free; a truly WEDGED one
        # may never release it — bounded acquire keeps failover from
        # hanging on the very replica it is rescuing
        got = svc._sched_lock.acquire(timeout=0.1)
        try:
            with svc._lock:
                queued = list(svc._queue)
                svc._queue = []
                svc._builds.clear()
                svc._built.clear()
                svc._build_failed.clear()
                engines = [svc.buckets.peek(k)
                           for k in svc.buckets.keys()]
            inflight: List[ServiceTicket] = []
            for eng in engines:
                if eng is None:
                    continue
                for j in range(eng.slots):
                    t = eng.occupant[j]
                    if t is None:
                        continue
                    try:
                        eng.release(j)
                    except Exception:
                        eng.occupant[j] = None
                    if not t.done:
                        inflight.append(t)
            with svc._lock:
                for t in queued + inflight:
                    if t.request_key:
                        svc._keyed.pop(t.request_key, None)
        finally:
            if got:
                svc._sched_lock.release()
        jr = svc.journal
        now = _now()
        for t in inflight:
            # resume from the last DURABLE checkpoint (what a
            # cross-process adoption would see); the journal's
            # remaining deadline budget re-anchors against the
            # adopter's service_now() — same contract as recover().
            # Without a journal the live absolute deadline stands
            # (in-process replicas share one skew-hookable clock)
            state = remaining = None
            if jr is not None and t.journal_id is not None:
                try:
                    state, remaining = jr.load_checkpoint(t.journal_id)
                except Exception:
                    state = remaining = None
            if state is not None:
                t.resume_state = state
            if remaining is not None:
                t.deadline_t = now + float(remaining)
            t.admit_t = None
        victims = queued + inflight
        for t in victims:
            if jr is not None and t.journal_id is not None:
                # completions settle the DEAD replica's records —
                # the adopted journal must never replay moved work
                t.journal_ref = jr
        now_m = time.monotonic()
        surv = [r for r in self.replicas
                if r != rid and self._healthy(r, now_m)]
        survset = set(surv)
        rehomed = 0
        with self._lock:
            for fp, h in list(self._placed.items()):
                if h != rid:
                    continue
                order = sorted(
                    self.replicas,
                    key=lambda r: _rendezvous_score(fp, r),
                    reverse=True)
                target = next((r for r in order if r in survset),
                              None)
                if target is None:
                    self._placed.pop(fp)
                else:
                    self._placed[fp] = target
                    rehomed += 1
        if rehomed:
            _tm.inc("fleet.health.rehomed", rehomed)
        if not surv:
            e = err if isinstance(err, Exception) else RuntimeError(
                f"replica {rid} {event.lower()}"
                + ("" if err is None else f": {err}"))
            with svc._lock:
                for t in victims:
                    if not t.done:
                        svc._fail_ticket(t, e)
            svc._flush_flightrec()
            svc._flush_journal_done()
            _fr.record("fleet.failover", replica=rid, event=event,
                       survivors=0, failed=len(victims),
                       error=None if err is None else str(err)[:120])
            _spans.mark("fleet.failover", args={
                "replica": rid, "event": event, "survivors": 0,
                "failed": len(victims)})
            return [t for t in victims if t.done]
        per: Dict[str, List[ServiceTicket]] = {}
        with self._lock:
            for t in victims:
                target = self._placed.get(t.fingerprint)
                if target not in survset:
                    order = sorted(
                        self.replicas,
                        key=lambda r: _rendezvous_score(
                            t.fingerprint, r), reverse=True)
                    target = next(
                        (r for r in order if r in survset), surv[0])
                per.setdefault(target, []).append(t)
                if t.request_key:
                    self._keyed[t.request_key] = target
        for trid, ts in per.items():
            tsvc = self.replicas[trid]
            with tsvc._lock:
                # FRONT of the queue: moved work was submitted before
                # anything already waiting here
                tsvc._queue[0:0] = ts
                for t in ts:
                    if t.request_key:
                        tsvc._keyed[t.request_key] = t
                _tm.set_gauge("serving.queue_depth",
                              len(tsvc._queue))
            for t in ts:
                t.replica = trid
        if victims:
            _tm.inc("fleet.health.requeued", len(victims))
        adopter = None
        adopted = 0
        if jr is not None:
            adopter = min(surv,
                          key=lambda r: self._load(self.replicas[r]))
            skipids = frozenset(t.journal_id for t in victims
                                if t.journal_id is not None)
            adopted = self.replicas[adopter].adopt_journal(
                jr, skip=skipids)
            _fr.record("fleet.adopt", from_replica=rid,
                       to_replica=adopter, replayed=adopted,
                       skipped=len(skipids))
        # the victim's tuned-config overlays ride along with the
        # journal: the fingerprints rehome to survivors, and a
        # survivor rebuilding one must rebuild it TUNED
        self._handoff_tuned(rid, surv)
        wall_ms = round((time.monotonic() - t0) * 1e3, 3)
        _fr.record("fleet.failover", replica=rid, event=event,
                   survivors=len(surv), queued=len(queued),
                   inflight=len(inflight), rehomed=rehomed,
                   adopter=adopter, adopted=adopted,
                   wall_ms=wall_ms,
                   error=None if err is None else str(err)[:120])
        _spans.mark("fleet.failover", args={
            "replica": rid, "event": event,
            "survivors": len(surv), "requeued": len(victims),
            "rehomed": rehomed, "adopter": adopter,
            "adopted": adopted, "wall_ms": wall_ms})
        return []

    def _rescue_queue(self, rid: str) -> List[ServiceTicket]:
        """Move a draining/down replica's QUEUED tickets to healthy
        survivors. In-flight work is NOT touched: a draining replica
        finishes its slots in place (rolling restart), and a down
        one's slots were already extracted by _failover. The source
        journal rides along on journal_ref so completions settle the
        original records. Placements are NOT rehomed — a drained
        replica keeps its homes and takes them back on restore."""
        svc = self.replicas[rid]
        now_m = time.monotonic()
        surv = [r for r in self.replicas
                if r != rid and self._healthy(r, now_m)]
        if not surv:
            return []
        survset = set(surv)
        with svc._lock:
            moved = list(svc._queue)
            svc._queue = []
            for t in moved:
                if t.request_key:
                    svc._keyed.pop(t.request_key, None)
        if not moved:
            return []
        jr = svc.journal
        per: Dict[str, List[ServiceTicket]] = {}
        for t in moved:
            if jr is not None and t.journal_id is not None:
                t.journal_ref = jr
            order = sorted(
                self.replicas,
                key=lambda r: _rendezvous_score(t.fingerprint, r),
                reverse=True)
            target = next((r for r in order if r in survset),
                          surv[0])
            per.setdefault(target, []).append(t)
        for trid, ts in per.items():
            tsvc = self.replicas[trid]
            with tsvc._lock:
                tsvc._queue[0:0] = ts
                for t in ts:
                    if t.request_key:
                        tsvc._keyed[t.request_key] = t
                _tm.set_gauge("serving.queue_depth",
                              len(tsvc._queue))
            for t in ts:
                t.replica = trid
        with self._lock:
            for trid, ts in per.items():
                for t in ts:
                    if t.request_key:
                        self._keyed[t.request_key] = trid
        _tm.inc("fleet.health.requeued", len(moved))
        _fr.record("fleet.rehome", from_replica=rid,
                   moved=len(moved),
                   targets={trid: len(ts)
                            for trid, ts in per.items()})
        return moved

    def _handoff_tuned(self, rid: str, surv: List[str]) -> int:
        """Hand the victim replica's promoted tuned-config overlays to
        the survivors its fingerprints rehome to (rendezvous order —
        the same replica the next request for that fingerprint routes
        to). Adoption installs the overlay live AND persists it in the
        adopter's own hstore, so the tuned config survives the
        adopter's restarts too. Best-effort: a replica without a tuner
        (autotune=0) exports/adopts nothing."""
        tuner = self.replicas[rid]._tuner
        if tuner is None or not surv:
            return 0
        survset = set(surv)
        handed = 0
        for fp, state in tuner.export_promoted().items():
            order = sorted(
                self.replicas,
                key=lambda r: _rendezvous_score(fp, r), reverse=True)
            target = next((r for r in order if r in survset), surv[0])
            tsvc = self.replicas[target]
            if tsvc._tuner is None:
                continue
            tsvc._tuner.adopt(fp, state)
            handed += 1
            _tm.inc("autotune.handoffs")
            _fr.record("fleet.tuned_handoff", from_replica=rid,
                       to_replica=target, fingerprint=fp[:24],
                       knob=state.get("knob"))
        return handed

    def drain_replica(self, rid: str) -> int:
        """Rolling-restart entry: stop NEW placements on `rid`, hand
        its queued tickets to survivors, let in-flight work finish in
        place (or hand off via the journal if the process is killed
        anyway — the DOWN path covers that). The replica's promoted
        tuned-config overlays hand off with the queue, so a rehomed
        fingerprint rebuilds TUNED on its adopter. Returns the number
        of queued tickets handed off. The replica keeps serving its
        slots; wait for `replicas[rid].idle` (or fleet drain) before
        actually restarting it."""
        if rid not in self.replicas:
            raise BadParametersError(
                f"drain_replica: unknown replica {rid!r}")
        self.health.drain(rid)
        moved = len(self._rescue_queue(rid))
        now_m = time.monotonic()
        surv = [r for r in self.replicas
                if r != rid and self._healthy(r, now_m)]
        self._handoff_tuned(rid, surv)
        return moved

    def restore_replica(self, rid: str):
        """Re-enter `rid` into the rendezvous: breaker reset, error
        cleared, warm-up grace started (no COLD placements until it
        elapses; warm traffic returns at once). Rehomed fingerprints
        are NOT pulled back — they stay with their adopter until
        natural eviction, so a restore never thunders the herd. A
        dead scheduler thread's corpse is cleared and, when the fleet
        runs background, a fresh one started."""
        if rid not in self.replicas:
            raise BadParametersError(
                f"restore_replica: unknown replica {rid!r}")
        svc = self.replicas[rid]
        th = svc._thread
        if th is not None and not th.is_alive():
            svc._thread = None
        svc._stopping = False
        self.health.restore(rid)
        if self._bg_poll is not None and svc._thread is None:
            svc.start(poll_s=self._bg_poll)
        _fr.record("fleet.restore", replica=rid,
                   background=self._bg_poll is not None)

    def health_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """The monitor's per-replica breaker view plus live scheduler
        facts (cycle counter, thread aliveness, captured error, queue
        depth) — what `AMGX_fleet_health` serializes."""
        snap = self.health.snapshot()
        for rid, svc in self.replicas.items():
            th = svc._thread
            snap[rid].update({
                "cycle": svc._cycle,
                "thread_alive": bool(th is not None
                                     and th.is_alive()),
                "error": None if svc._thread_error is None
                else str(svc._thread_error)[:160],
                "queue_depth": self._queue_depth(svc),
            })
        return snap

    # -- fleet observability ----------------------------------------------
    def snapshots(self) -> Dict[str, Dict[str, Any]]:
        """One metrics view per replica: the labeled histogram series
        its observations carry (replica="<id>"). Counters/gauges are
        process-wide and excluded here — in a one-process-per-replica
        deployment each process's full snapshot() goes straight into
        merge_snapshots instead."""
        full = _tm.snapshot()
        views: Dict[str, Dict[str, Any]] = {
            rid: {} for rid in self.replicas}
        for key, val in full.items():
            if not (isinstance(val, dict) and "counts" in val):
                continue
            _name, pairs = _tm._parse_entry_key(key)
            rid = dict(pairs).get("replica")
            if rid in views:
                views[rid][key] = val
        return views

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The merged fleet-wide view (metrics.merge_snapshots over
        the per-replica views): per-tenant-per-replica series side by
        side plus recomputed fleet aggregates."""
        return _tm.merge_snapshots(self.snapshots())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routes = {rid: dict(c)
                      for rid, c in self.route_counts.items()}
            placed = len(self._placed)
        return {
            "replicas": {rid: svc.stats()
                         for rid, svc in self.replicas.items()},
            "routes": routes,
            "placed_fingerprints": placed,
        }
