"""Fleet serving: a fingerprint-affine router over SolveService
replicas.

PR 11 made one replica crash-safe and overload-safe; PR 13 gave it
replica-labeled metrics and cross-incarnation trace chains. This
module is the scale-out layer on top: a `FleetRouter` fronts N
`SolveService` replicas behind the same submit/step/drain/ticket API,
so a caller (or the `AMGX_fleet_*` C surface) talks to one serving
endpoint while requests land on the replica most likely to serve them
cheaply.

Why affinity keys on the PATTERN FINGERPRINT: everything expensive a
replica holds — its hierarchy cache buckets, persisted structures,
AOT-exported executables, even its retry/backoff fault state — is
fingerprint-keyed. A replica warm for a fingerprint serves it with a
value-only resetup (milliseconds); a cold one pays a full coarsening
plus traces (seconds). Placement is therefore the dominant fleet-level
lever, and it must be STICKY: rendezvous (highest-random-weight)
hashing gives every fingerprint a stable candidate order over the
replica set, so adding or removing a replica reshuffles only the
fingerprints that hashed to it.

Routing classes (counted per decision, `fleet.route.*`):

- `cold` — first sighting of a fingerprint: placed on the
  least-loaded replica (live queue depth x recent exec estimate, ties
  broken by rendezvous order) which becomes its home;
- `warm` — the home replica takes it (the steady state);
- `spill` — the home is overloaded (queue depth past
  `fleet_spill_depth` AND a strictly less-loaded candidate exists),
  quarantine-looping on this fingerprint (its fault/backoff state is
  live), or deadline-infeasible while another replica's estimate says
  feasible: the request diverts to the next rendezvous candidate and
  the flight recorder gets a `fleet.handoff` note. Quarantine spills
  REHOME the fingerprint (the sick replica stays its rendezvous
  candidate, but the warm state now grows elsewhere); load spills
  don't.

Shed decisions consult the FLEET-WIDE aggregate: per-replica
feasibility estimates plus the merged per-tenant latency histograms
(`metrics.merge_snapshots` over the replica-labeled series, read via
`metrics.quantile_where`). When every replica judges a deadline
unmeetable the router routes home anyway — the home replica's shed
policy completes the request honestly OVERLOADED — and counts
`fleet.shed.infeasible` with the estimates it decided on in the
flight recorder.

Trace attribution: every routed ticket gains `.replica`/`.route`
attributes and, when tracing is on, a `fleet.route` instant event on
its flow chain — so `tools/flightrec.py --trace <id>` and the
Perfetto export both say which replica served a request across a
cross-replica postmortem.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..batch.queue import pattern_fingerprint
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..telemetry import flightrec as _fr
from ..telemetry import metrics as _tm
from ..telemetry import spans as _spans
from .service import ServiceTicket, SolveService


def _rendezvous_score(fingerprint: str, rid: str) -> int:
    """Highest-random-weight score of (fingerprint, replica): stable
    across processes and python hash seeds (the journal may hand a
    restarted fleet the same fingerprints)."""
    h = hashlib.blake2b(f"{fingerprint}@{rid}".encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


class FleetRouter:
    """N `SolveService` replicas behind one submit/step/drain/ticket
    surface. Accepts a dict {replica_id: service} or a list of
    services; entries without an identity (no dict key, no
    pre-assigned `.replica` attribute) get distinct derived ids
    `r0..rN-1` — two unlabeled replicas in one process must never
    scrape identically (their latency series would silently merge)."""

    def __init__(self, replicas, *, spill_depth: int = 0):
        if isinstance(replicas, dict):
            items = list(replicas.items())
        else:
            items = [(None, svc) for svc in replicas]
        if not items:
            raise BadParametersError(
                "FleetRouter: at least one replica required")
        self.replicas: Dict[str, SolveService] = {}
        taken = {rid for rid, svc in items
                 if rid or getattr(svc, "replica", "")}
        auto = 0
        for rid, svc in items:
            rid = str(rid or getattr(svc, "replica", "") or "")
            if not rid:
                while f"r{auto}" in taken:
                    auto += 1
                rid = f"r{auto}"
                taken.add(rid)
            if rid in self.replicas:
                raise BadParametersError(
                    f"FleetRouter: duplicate replica id {rid!r}")
            svc.replica = rid      # labels this replica's metric series
            self.replicas[rid] = svc
        for svc in self.replicas.values():
            # in-process replicas share ONE execution device: each
            # one's exec window undercounts wall latency by the number
            # of co-residents competing for the core, so feasibility
            # estimates (shed decisions, spill reads, fleet consults)
            # scale by the fleet size
            svc.exec_share = float(len(self.replicas))
        self.spill_depth = int(spill_depth)
        self._lock = threading.Lock()
        # fingerprint -> home replica id (sticky placement)
        self._placed: Dict[str, str] = {}
        # request_key -> replica id: a retried idempotent submit must
        # land on the replica holding (or journaling) the original
        self._keyed: Dict[str, str] = {}
        self.route_counts: Dict[str, Dict[str, int]] = {
            rid: {"warm": 0, "cold": 0, "spill": 0}
            for rid in self.replicas}
        _tm.set_gauge("fleet.replicas", len(self.replicas))

    @classmethod
    def build(cls, cfg: Config, n_replicas: Optional[int] = None,
              scope: str = "default") -> "FleetRouter":
        """N replicas from ONE config (default `fleet_replicas`).
        Each gets a derived replica id; a configured
        `serving_journal_dir` gains a per-replica subdirectory — a
        journal's replay owns its records, two replicas must not
        replay each other's — while the AOT and hierarchy stores stay
        shared (fingerprint-keyed: one replica's export warms every
        replica's restart)."""
        n = int(cfg.get("fleet_replicas", scope)
                if n_replicas is None else n_replicas)
        if n < 1:
            raise BadParametersError(
                f"FleetRouter.build: need >= 1 replica, got {n}")
        jdir = str(cfg.get("serving_journal_dir", scope)).strip()
        base_id = str(cfg.get("serving_replica_id", scope)).strip()
        replicas: Dict[str, SolveService] = {}
        for i in range(n):
            rid = f"{base_id}{i}" if base_id else f"r{i}"
            c = cfg.clone()
            # the id is assigned as the service ATTRIBUTE below (via
            # __init__), not through serving_replica_id — the knob
            # also sets the process-global scrape label, and N
            # in-process replicas must not fight over it
            if jdir:
                c.set("serving_journal_dir",
                      os.path.join(jdir, rid), scope)
            svc = SolveService(c, scope=scope)
            svc.replica = rid
            replicas[rid] = svc
        return cls(replicas,
                   spill_depth=int(cfg.get("fleet_spill_depth",
                                           scope)))

    # -- load/feasibility reads -------------------------------------------
    def _queue_depth(self, svc: SolveService) -> int:
        with svc._lock:
            return len(svc._queue)

    def _load(self, svc: SolveService) -> float:
        """Live load: (queue depth + in-flight) x the replica's recent
        exec estimate (1.0 while untrained, so cold placement on an
        empty fleet degenerates to fewest-requests)."""
        with svc._lock:
            depth = len(svc._queue) + svc._inflight()
            if len(svc._exec_recent) >= 1:
                window = sorted(svc._exec_recent)
                est = float(window[len(window) // 2])
            else:
                est = 1.0
        return depth * max(est, 1e-9) + 1e-12 * depth

    def _estimate(self, svc: SolveService) -> Optional[float]:
        with svc._lock:
            return svc._estimate_latency_s()

    def _spill_limit(self, svc: SolveService) -> int:
        return self.spill_depth or max(2 * svc.slots, 2)

    # -- routing -----------------------------------------------------------
    def _route(self, fp: str, tenant: str,
               deadline_s: Optional[float]):
        """(replica id, route class, handoff, consult): the whole
        decision under the router lock — placement map reads/writes
        must not interleave across concurrent submits."""
        with self._lock:
            order = sorted(
                self.replicas,
                key=lambda r: _rendezvous_score(fp, r), reverse=True)
            home = self._placed.get(fp)
            if home is None or home not in self.replicas:
                loads = {rid: self._load(self.replicas[rid])
                         for rid in order}
                rid = min(order,
                          key=lambda r: (loads[r], order.index(r)))
                self._placed[fp] = rid
                return rid, "cold", None, None
            home_svc = self.replicas[home]
            cands = [r for r in order if r != home]
            # 1. quarantine-looping home: its fault/backoff state for
            # this fingerprint is live — rebuild-crash loops there
            # while a healthy replica could just serve. Rehome.
            fl = home_svc._faulted.get(fp)
            if fl is not None and cands:
                target = next(
                    (r for r in cands
                     if fp not in self.replicas[r]._faulted),
                    cands[0])
                self._placed[fp] = target
                return target, "spill", \
                    (home, "quarantine", self._queue_depth(home_svc)), \
                    None
            # 2. overloaded home: spill only toward a STRICTLY less
            # loaded candidate — a uniformly saturated fleet keeps
            # affinity (and sheds) instead of ping-ponging cold builds
            depth = self._queue_depth(home_svc)
            if cands and depth >= self._spill_limit(home_svc):
                home_load = self._load(home_svc)
                target = next(
                    (r for r in cands
                     if self._load(self.replicas[r]) < home_load
                     and self._queue_depth(self.replicas[r]) < depth),
                    None)
                if target is not None:
                    return target, "spill", \
                        (home, "overload", depth), None
            # 3. fleet-wide deadline feasibility consult. A
            # deadline-driven spill is only eligible toward a replica
            # already holding this fingerprint's bucket WARM: moving a
            # warm fingerprint to a cold replica trades a sub-second
            # value-resetup for a multi-second setup — the one hop
            # guaranteed to bust the very deadline being rescued
            if deadline_s is not None:
                est_home = self._estimate(home_svc)
                if est_home is not None \
                        and est_home > float(deadline_s):
                    ests = {rid: self._estimate(self.replicas[rid])
                            for rid in order}
                    feas = [r for r in cands
                            if (ests[r] is None
                                or ests[r] <= float(deadline_s))
                            and self.replicas[r].buckets.peek(fp)
                            is not None]
                    if feas:
                        return feas[0], "spill", \
                            (home, "deadline", depth), None
                    # infeasible everywhere: route home for the
                    # honest per-replica OVERLOADED shed, and record
                    # the fleet-wide evidence the verdict rests on
                    consult = {
                        "deadline_s": round(float(deadline_s), 6),
                        "estimates_s": {
                            rid: None if e is None
                            else round(float(e), 6)
                            for rid, e in ests.items()},
                        "tenant_p50_s": _tm.quantile_where(
                            "serving.solve_latency_s", 0.50,
                            {"tenant": tenant}),
                        "tenant_p99_s": _tm.quantile_where(
                            "serving.solve_latency_s", 0.99,
                            {"tenant": tenant}),
                    }
                    return home, "warm", None, consult
            return home, "warm", None, None

    # -- the serving surface ----------------------------------------------
    def submit(self, A: CsrMatrix, b, x0=None,
               tenant: str = "default",
               deadline_s: Optional[float] = None,
               request_key: Optional[str] = None) -> ServiceTicket:
        """Route one request to a replica and submit it there. The
        returned ticket is the replica's own (same wait/result API),
        plus `.replica` and `.route` attribution."""
        fp = f"{pattern_fingerprint(A)}/{np.asarray(b).dtype}"
        if request_key:
            with self._lock:
                prior = self._keyed.get(request_key)
            if prior is not None and prior in self.replicas:
                # idempotent retry: the original's replica holds the
                # live ticket (or its journal holds the result) —
                # routing elsewhere would re-solve it
                t = self.replicas[prior].submit(
                    A, b, x0=x0, tenant=tenant,
                    deadline_s=deadline_s, request_key=request_key)
                t.replica = prior
                t.route = "warm"
                return t
        rid, route, handoff, consult = self._route(
            fp, str(tenant), deadline_s)
        svc = self.replicas[rid]
        t = svc.submit(A, b, x0=x0, tenant=tenant,
                       deadline_s=deadline_s,
                       request_key=request_key)
        t.replica = rid
        t.route = route
        # literal route-class counters (the check_spans dead-metric
        # lint wants write sites it can see)
        if route == "warm":
            _tm.inc("fleet.route.warm")
        elif route == "spill":
            _tm.inc("fleet.route.spill")
        else:
            _tm.inc("fleet.route.cold")
        with self._lock:
            self.route_counts[rid][route] += 1
            if request_key:
                self._keyed[request_key] = rid
        if t.trace_id:
            # replica attribution on the request's flow chain
            _spans.mark("fleet.route", args={
                "trace": t.trace_id, "replica": rid, "route": route})
        if handoff is not None:
            from_rid, reason, home_depth = handoff
            _fr.record("fleet.handoff", trace=t.trace_id,
                       fingerprint=fp[:24], from_replica=from_rid,
                       to_replica=rid, reason=reason,
                       home_queue_depth=home_depth)
        if consult is not None:
            _tm.inc("fleet.shed.infeasible")
            _fr.record("fleet.shed", trace=t.trace_id,
                       tenant=str(tenant), verdict="infeasible",
                       **consult)
        return t

    def step(self) -> List[ServiceTicket]:
        """One scheduler cycle on EVERY replica (round-robin inline
        driving — the single-process analog of N schedulers); returns
        the tickets completed across the fleet."""
        done: List[ServiceTicket] = []
        for svc in self.replicas.values():
            done.extend(svc.step())
        return done

    @property
    def idle(self) -> bool:
        return all(svc.idle for svc in self.replicas.values())

    @property
    def completed_total(self) -> int:
        return sum(svc.completed_total
                   for svc in self.replicas.values())

    def drain(self, timeout_s: Optional[float] = None
              ) -> List[ServiceTicket]:
        """Step until every replica is idle (or timeout). Replicas
        running their own background scheduler are waited on;
        inline-driven ones are stepped."""
        t0 = time.monotonic()
        done: List[ServiceTicket] = []
        while not self.idle:
            if timeout_s is not None \
                    and time.monotonic() - t0 > timeout_s:
                break
            stepped = False
            for svc in self.replicas.values():
                if svc._thread is None:
                    done.extend(svc.step())
                    stepped = True
            if not stepped:
                time.sleep(0.001)
        return done

    def start(self, poll_s: float = 0.0005):
        for svc in self.replicas.values():
            svc.start(poll_s=poll_s)

    def stop(self):
        for svc in self.replicas.values():
            svc.stop()

    # -- fleet observability ----------------------------------------------
    def snapshots(self) -> Dict[str, Dict[str, Any]]:
        """One metrics view per replica: the labeled histogram series
        its observations carry (replica="<id>"). Counters/gauges are
        process-wide and excluded here — in a one-process-per-replica
        deployment each process's full snapshot() goes straight into
        merge_snapshots instead."""
        full = _tm.snapshot()
        views: Dict[str, Dict[str, Any]] = {
            rid: {} for rid in self.replicas}
        for key, val in full.items():
            if not (isinstance(val, dict) and "counts" in val):
                continue
            _name, pairs = _tm._parse_entry_key(key)
            rid = dict(pairs).get("replica")
            if rid in views:
                views[rid][key] = val
        return views

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The merged fleet-wide view (metrics.merge_snapshots over
        the per-replica views): per-tenant-per-replica series side by
        side plus recomputed fleet aggregates."""
        return _tm.merge_snapshots(self.snapshots())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routes = {rid: dict(c)
                      for rid, c in self.route_counts.items()}
            placed = len(self._placed)
        return {
            "replicas": {rid: svc.stats()
                         for rid, svc in self.replicas.items()},
            "routes": routes,
            "placed_fingerprints": placed,
        }
