"""Batched solve subsystem: vmapped multi-RHS / multi-matrix AMG.

The reference AmgX serves one matrix/RHS per solve handle (amgx_c.h);
on TPU the leverage is the opposite direction — amortize ONE XLA trace
across many simultaneous solves. Two batching shapes, both compiled
into a single jitted program:

- multi-RHS: many right-hand sides against one matrix (the solve data
  is shared; only b/x carry the batch axis);
- multi-matrix: many matrices sharing one sparsity pattern, each with
  its own RHS. The AMG hierarchy *structure* is built once from the
  shared pattern; per-system Galerkin values are spliced through the
  existing structure-reuse / value-resetup path and stacked along a
  leading batch axis. Structure arrays (colorings, aggregates, ELL
  layouts) stay unbatched — `jax.vmap` maps only the value leaves.

Per-system convergence comes free from the `lax.while_loop` batching
rule: the loop runs while ANY system is unconverged and early-converged
systems' states freeze via per-element select, so a stiff straggler
never corrupts an already-converged neighbor.

`queue.RequestBatcher` adds the serving layer: incoming solve requests
are bucketed by (sparsity-pattern fingerprint, dtype), padded within a
bucket to a small ladder of batch sizes so the jit cache stays bounded,
and dispatched as one batched solve per bucket.
"""
from .core import BatchedSolveResult, BatchedSolver
from .queue import PAD_SIZES, RequestBatcher, SolveRequest, pattern_fingerprint

__all__ = [
    "BatchedSolver", "BatchedSolveResult", "RequestBatcher",
    "SolveRequest", "pattern_fingerprint", "PAD_SIZES",
]
