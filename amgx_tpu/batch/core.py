"""BatchedSolver: one jitted program solving many systems at once.

Execution model
---------------
`Solver._build_solve_fn()` already returns a pure function
``solve_fn(data, b, x0) -> (x, stats)`` whose whole iteration loop is a
`lax.while_loop`. Batching is therefore `jax.vmap(solve_fn)` with:

- `b`/`x0` mapped along a leading batch axis,
- the solve-data pytree mapped selectively: leaves that are IDENTICAL
  across systems (structure arrays, aggregates, colorings — everything
  the shared sparsity pattern determines) stay unbatched, leaves that
  differ (matrix values, smoother inverses, coarse factors, Chebyshev
  taus) are stacked and mapped along axis 0.

Per-system convergence needs no bespoke masking: the `while_loop`
batching rule runs the body while ANY system's predicate holds and
freezes finished systems' carries with a per-element select — each
system keeps its own iteration count, and an early-converged system's
state is bit-identical to what a solo solve would have returned at its
own stopping iteration.

Multi-matrix setup reuse
------------------------
For same-pattern matrix batches the hierarchy structure is built ONCE
(`setup(A0)`); each system's coefficients are then spliced through the
existing `resetup` path (structure_reuse_levels / the fused
value-resetup), and the per-system solve-data snapshots are stacked.
Because structure arrays survive resetup as the *same objects*, the
identity-based stacking recovers exactly the pattern/value split — the
batched program holds one copy of the structure and B copies of the
values. Caveat: the split is by object IDENTITY, so this holds for the
coarse hierarchy (reused across resetups) and for fine matrices derived
from one template via `with_values` (which keeps the index/layout
arrays); B separately-constructed matrices carry B distinct (equal)
fine-level structure objects, and any ELL/SWELL index payloads among
them stack B-fold — derive batch members from a shared template when
that matters.
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
import weakref
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..solvers.base import SolveResult, Solver


@dataclasses.dataclass
class BatchedSolveResult:
    """Per-system results of one batched solve (leading axis = system)."""

    x: jax.Array                    # (B, n)
    iterations: np.ndarray          # (B,) int
    converged: np.ndarray           # (B,) bool
    res_norm: np.ndarray            # (B,) or (B, block)
    norm0: np.ndarray               # (B,) or (B, block)
    res_history: Optional[np.ndarray] = None   # (B, hist_len[, block]);
    #                                 entries past a system's own stop
    #                                 iteration are NaN-masked
    setup_time: float = 0.0
    solve_time: float = 0.0
    # per-system SolveStatus codes (resilience/status.py), (B,) int —
    # one system's NaN storm or breakdown is distinguishable from a
    # neighbor's honest max-iters exit
    status: Optional[np.ndarray] = None
    # per-system structured reports (telemetry/report.py SolveReport),
    # built when the wrapped solver's `telemetry` knob is on
    reports: Optional[List[Any]] = None

    @property
    def batch_size(self) -> int:
        return int(self.x.shape[0])

    @property
    def all_converged(self) -> bool:
        return bool(np.all(self.converged))

    def per_system(self) -> List[SolveResult]:
        """Split into per-system SolveResult views (the shape downstream
        consumers of the single-solve API expect)."""
        out = []
        for i in range(self.batch_size):
            hist = None
            if self.res_history is not None:
                hist = self.res_history[i][: int(self.iterations[i]) + 1]
            out.append(SolveResult(
                x=self.x[i], iterations=int(self.iterations[i]),
                converged=bool(self.converged[i]),
                res_norm=self.res_norm[i], norm0=self.norm0[i],
                res_history=hist, setup_time=self.setup_time,
                solve_time=self.solve_time,
                status_code=int(self.status[i])
                if self.status is not None else 1,
                report=self.reports[i]
                if self.reports is not None else None))
        return out


def stack_solve_datas(datas: Sequence[Any]):
    """Stack per-system solve-data pytrees along a new leading axis,
    sharing leaves that are identical (by object identity) across every
    system. Returns (stacked_data, in_axes_tree) where in_axes_tree has
    a 0 at stacked leaf positions and None at shared ones — directly
    usable as jax.vmap in_axes for the data argument."""
    flat0, treedef = jax.tree.flatten(datas[0])
    flats = [flat0]
    for d in datas[1:]:
        f, td = jax.tree.flatten(d)
        flats.append(f)
        if td != treedef:
            raise BadParametersError(
                "batched solve: solve-data structure differs between "
                "systems (the sparsity pattern or hierarchy structure "
                "drifted; multi-matrix batching requires "
                "structure_reuse_levels=-1 so every system reuses one "
                "hierarchy)")
    stacked, axes = [], []
    for leaves in zip(*flats):
        first = leaves[0]
        if all(lv is first for lv in leaves[1:]):
            stacked.append(first)
            axes.append(None)
        else:
            shapes = {np.shape(lv) for lv in leaves}
            if len(shapes) != 1:
                raise BadParametersError(
                    f"batched solve: per-system solve-data leaf shapes "
                    f"differ ({sorted(shapes)}); matrices must share one "
                    f"sparsity pattern and hierarchy structure")
            stacked.append(jnp.stack(leaves))
            axes.append(0)
    return (jax.tree.unflatten(treedef, stacked),
            jax.tree.unflatten(treedef, axes))


def _solver_tree(s: Solver):
    """Every solver node reachable from s: the preconditioner chain,
    plus AMG level smoothers and coarse solvers."""
    while s is not None:
        yield s
        amg = getattr(s, "amg", None)
        if amg is not None:
            for lv in amg.levels:
                if lv.smoother is not None:
                    yield from _solver_tree(lv.smoother)
            if getattr(amg, "coarse_solver", None) is not None:
                yield from _solver_tree(amg.coarse_solver)
        s = s.preconditioner


def _amg_nodes(s: Solver):
    for node in _solver_tree(s):
        if hasattr(node, "amg"):
            yield node


class BatchedSolver:
    """Solve many systems in one jitted program (see module docs).

    Construct from a Config (builds its own solver tree) or wrap an
    existing root solver with ``BatchedSolver(solver=slv)``. The wrapped
    solver keeps working for single solves; `setup`/`resetup` on either
    object stay coherent because they are the same tree.
    """

    def __init__(self, cfg: Optional[Config] = None, scope: str = "default",
                 solver: Optional[Solver] = None):
        if (cfg is None) == (solver is None):
            raise BadParametersError(
                "BatchedSolver: pass exactly one of cfg or solver")
        if solver is None:
            from .. import create_solver
            solver = create_solver(cfg, scope)
        self.solver = solver
        # register on the wrapped tree root: a setup/resetup that
        # invalidates the solver's own traces (base.py __setup_impl)
        # clears every registered wrapper's jit cache; Solver.solve_many
        # reuses `_batched` (latest wrapper wins for that convenience,
        # but ALL wrappers stay linked for invalidation). The WeakSet
        # keeps dropped wrappers collectable (they hold the solver, not
        # vice versa).
        solver._batched = self
        if not hasattr(solver, "_batched_wrappers"):
            solver._batched_wrappers = weakref.WeakSet()
        solver._batched_wrappers.add(self)
        self._suppress_invalidation = False
        self._jit_cache = {}
        # number of Python traces of the batched solve function — one per
        # (batch size, dtype, sharing signature) bucket; serving tests
        # assert this stays at 1 while a bucket is reused
        self.trace_count = 0
        self.setup_time = 0.0

    # -- setup ----------------------------------------------------------
    def setup(self, A: CsrMatrix) -> "BatchedSolver":
        """Build the solver (and for AMG, the hierarchy structure) from
        the batch's shared-pattern template matrix."""
        t0 = time.perf_counter()
        self.solver.setup(A)
        self.setup_time = time.perf_counter() - t0
        return self

    def _check_multi_matrix_config(self):
        for s in _amg_nodes(self.solver):
            if int(s.cfg.get("structure_reuse_levels", s.scope)) == 0:
                raise BadParametersError(
                    "multi-matrix batching needs the AMG hierarchy "
                    "structure shared across systems: set "
                    "structure_reuse_levels=-1 in the AMG scope so "
                    "resetup splices values instead of re-coarsening")
        for s in _solver_tree(self.solver):
            if s.trace_bakes_values:
                raise BadParametersError(
                    f"multi-matrix batching: solver {s.name} bakes "
                    f"value-derived scalars into its trace (see "
                    f"Solver.trace_bakes_values) — one batched trace "
                    f"cannot serve per-system coefficients; use a "
                    f"solver whose value state flows through solve_data "
                    f"(e.g. JACOBI_L1, CHEBYSHEV_POLY)")

    @contextlib.contextmanager
    def _keep_batched_traces(self):
        """Suppress the base-layer wrapper-cache invalidation around a
        resetup KNOWN to keep every static trace ingredient — used for
        the multi-matrix splice loop, where structure reuse is enforced
        and trace-baking solvers are rejected up front."""
        self._suppress_invalidation = True
        try:
            yield
        finally:
            self._suppress_invalidation = False

    def _per_system_data(self, matrices: Sequence[CsrMatrix]):
        """Resetup the solver per system against the shared structure and
        snapshot each system's solve data (the per-system Galerkin values
        flow through the existing value-resetup / structure-reuse path).
        Snapshots are memoized per matrix OBJECT, so padded batches that
        replicate a system (batch/queue.py) pay one resetup, not one per
        duplicate."""
        fresh = self.solver.A is None
        if fresh:
            self.setup(matrices[0])
        # after setup: the AMG level smoothers exist and are scanned too
        self._check_multi_matrix_config()
        datas, memo = [], {}
        with self._keep_batched_traces():
            if fresh:
                # the setup above already installed matrices[0]'s
                # values — don't pay a redundant Galerkin resetup
                memo[id(matrices[0])] = self.solver.solve_data()
            for A_i in matrices:
                if id(A_i) not in memo:
                    self.solver.resetup(A_i)
                    memo[id(A_i)] = self.solver.solve_data()
                datas.append(memo[id(A_i)])
        return datas

    # -- solve -----------------------------------------------------------
    def _build_batched_fn(self, data_axes):
        # diag=False: the per-row stats unpack below assumes the bare
        # layout; the diagnostics probe is a single-solve surface
        solve_fn = self.solver._build_solve_fn(diag=False)

        def batched(data, b, x0):
            self.trace_count += 1
            return jax.vmap(solve_fn, in_axes=(data_axes, 0, 0))(data, b, x0)

        return jax.jit(batched)

    def solve_many(self, bs, matrices: Optional[Sequence[CsrMatrix]] = None,
                   x0s=None, zero_initial_guess: bool = False
                   ) -> BatchedSolveResult:
        """Solve the batch: `bs` is (B, n) (or a sequence of B vectors).

        matrices=None       -> multi-RHS against the already-set-up matrix;
        matrices=[A_0..A_b] -> same-pattern multi-matrix batch (hierarchy
                               structure reused, values spliced per system).
        """
        slv = self.solver
        if slv.scaler is not None:
            raise BadParametersError(
                "batched solve: equation scaling is unsupported "
                "(set scaling=NONE)")
        B = jnp.stack([jnp.asarray(b) for b in bs]) \
            if not hasattr(bs, "ndim") else jnp.asarray(bs)
        if B.ndim != 2:
            raise BadParametersError(
                f"batched solve: rhs must stack to (batch, n), got "
                f"{B.shape}")
        nb = int(B.shape[0])
        if matrices is not None:
            if len(matrices) != nb:
                raise BadParametersError(
                    f"batched solve: {len(matrices)} matrices for "
                    f"{nb} right-hand sides")
            data, data_axes = stack_solve_datas(
                self._per_system_data(matrices))
        else:
            if slv.A is None:
                raise BadParametersError(
                    "batched solve: solve_many() before setup()")
            data, data_axes = slv.solve_data(), None
        if x0s is None or zero_initial_guess:
            X0 = jnp.zeros_like(B)
        else:
            X0 = jnp.stack([jnp.asarray(x) for x in x0s]) \
                if not hasattr(x0s, "ndim") else jnp.asarray(x0s)
        # cache key: batch geometry + which leaves carry the batch axis
        # (the sharing signature is stable for a bucket, so a reused
        # bucket reuses ONE trace)
        axes_sig = (None if data_axes is None
                    else tuple(jax.tree.leaves(
                        data_axes, is_leaf=lambda a: a is None)))
        from ..resilience import faultinject as _fi
        key = (B.shape, str(B.dtype), axes_sig, _fi.epoch())
        if key not in self._jit_cache:
            from ..telemetry import metrics as _tm
            _tm.inc("solver.retrace.solve_batched")
            _fi.evict_stale_epochs(self._jit_cache, key[-1])
            self._jit_cache[key] = self._build_batched_fn(data_axes)
        t0 = time.perf_counter()
        X, stats = jax.block_until_ready(self._jit_cache[key](data, B, X0))
        solve_time = time.perf_counter() - t0
        hist_len = slv.max_iters + 1
        iters = np.zeros(nb, np.int64)
        conv = np.zeros(nb, bool)
        status = np.zeros(nb, np.int32)
        norm0, res_norm, hists = [], [], []
        for i, row in enumerate(np.asarray(stats)):
            it, cv, sc, n0, rn, h = Solver.unpack_stats(row, hist_len)
            iters[i], conv[i], status[i] = it, cv, sc
            norm0.append(n0)
            res_norm.append(rn)
            # unpack_stats trims to each system's own stop iteration;
            # re-pad with NaN so the batch stacks rectangular while
            # post-exit garbage stays unmistakably masked
            h = np.asarray(h)
            pad = np.full((hist_len,) + h.shape[1:], np.nan, h.dtype)
            pad[: h.shape[0]] = h
            hists.append(pad)
        out = BatchedSolveResult(
            x=X, iterations=iters, converged=conv,
            res_norm=np.asarray(res_norm), norm0=np.asarray(norm0),
            res_history=np.asarray(hists)
            if slv.store_res_history else None,
            setup_time=self.setup_time, solve_time=solve_time,
            status=status)
        if getattr(slv, "telemetry", False):
            # per-system structured reports from the already-unpacked
            # numpy stats (telemetry/report.py: zero added transfers,
            # no per-system x slicing); each system's history is
            # trimmed to its own stop iteration
            from ..telemetry import build_report
            out.reports = [
                build_report(slv, SolveResult(
                    x=None, iterations=int(iters[i]),
                    converged=bool(conv[i]), res_norm=res_norm[i],
                    norm0=norm0[i], setup_time=self.setup_time,
                    solve_time=solve_time, status_code=int(status[i])),
                    hist=hists[i][: iters[i] + 1])
                for i in range(nb)]
        return out
