"""Serving-style request batching for solves.

A jax_graft deployment sees a stream of solve requests, not one matrix:
many users posting same-shaped systems (one mesh, perturbed
coefficients), a few distinct meshes, mixed dtypes. The batcher turns
that stream into a small number of batched dispatches:

- requests are bucketed by (sparsity-pattern fingerprint, dtype): only
  systems that can share one hierarchy structure and one XLA trace land
  in the same bucket;
- within a bucket, a batch is padded UP to the next size in a fixed
  ladder (`PAD_SIZES`) by replicating the last system, so the jit cache
  holds at most len(PAD_SIZES) entries per bucket instead of one per
  observed request count;
- each bucket keeps its own `BatchedSolver` (structure built once from
  the first request's pattern; later requests splice values only).

Sync callers use `solve_many()`; streaming callers use
`submit()`/`drain()` — submit enqueues and returns a `SolveRequest`
ticket, drain dispatches every pending bucket and fills the tickets.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix, host_mirror_asarray
from ..solvers.base import SolveResult
from .core import BatchedSolver

# batch-size ladder: requests pad up to the next rung, bounding the
# number of distinct (batch, n) programs XLA ever compiles per bucket
PAD_SIZES: Tuple[int, ...] = (1, 2, 4, 8, 16, 32)


# id(CsrMatrix) -> digest, weakref-evicted with the matrix (hashing a
# 128^3 system's index arrays costs tens of ms — a request stream
# resubmitting the same matrix object must not repay it per request)
_FP_CACHE: Dict[int, str] = {}


def pattern_fingerprint(A: CsrMatrix) -> str:
    """Digest of the sparsity pattern + shape/block/dtype — systems with
    equal fingerprints can share one hierarchy structure and one jitted
    batched program. Values do NOT enter the digest. Index arrays are
    read through the retained host mirror, so fingerprinting a matrix
    that lives on the accelerator costs no device pull for uploaded
    matrices. Memoized per matrix object (CsrMatrix is immutable)."""
    cached = _FP_CACHE.get(id(A))
    if cached is not None:
        return cached
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((A.num_rows, A.num_cols, A.block_dimx, A.block_dimy,
                   str(A.dtype), A.has_external_diag,
                   A.grid_shape)).encode())
    ro = np.ascontiguousarray(host_mirror_asarray(A.row_offsets))
    ci = np.ascontiguousarray(host_mirror_asarray(A.col_indices))
    h.update(ro.tobytes())
    h.update(ci.tobytes())
    digest = h.hexdigest()
    try:
        weakref.finalize(A, _FP_CACHE.pop, id(A), None)
        _FP_CACHE[id(A)] = digest
    except TypeError:  # pragma: no cover - non-weakrefable subclass
        pass
    return digest


def pad_to_bucket_size(n: int, sizes: Sequence[int] = PAD_SIZES) -> int:
    """Smallest ladder rung >= n (requests beyond the top rung are split
    into top-rung chunks by the caller)."""
    for s in sizes:
        if n <= s:
            return s
    return sizes[-1]


@dataclasses.dataclass
class SolveRequest:
    """One pending solve. `result` is filled by drain()."""

    A: CsrMatrix
    b: np.ndarray
    x0: Optional[np.ndarray] = None
    fingerprint: str = ""
    result: Optional[SolveResult] = None
    # submission wall time (time.monotonic): drain() dispatches buckets
    # oldest-first by their earliest pending submit
    submit_t: float = 0.0

    @property
    def done(self) -> bool:
        return self.result is not None


class RequestBatcher:
    """Pattern-bucketed batching front end over BatchedSolver (see
    module docs). One Config serves every bucket — requests needing a
    different solver configuration belong to a different batcher."""

    def __init__(self, cfg: Config, scope: str = "default",
                 batch_sizes: Sequence[int] = PAD_SIZES,
                 max_buckets: int = 16, max_bucket_bytes: int = 0):
        if not batch_sizes or list(batch_sizes) != sorted(set(batch_sizes)):
            raise BadParametersError(
                "RequestBatcher: batch_sizes must be a sorted ladder of "
                "distinct sizes")
        self.cfg = cfg
        self.scope = scope
        self.batch_sizes = tuple(int(s) for s in batch_sizes)
        # bounded LRU of live buckets: each holds a full hierarchy plus
        # up to len(batch_sizes) compiled programs — a long-running
        # server seeing many distinct meshes must not grow without
        # bound, in entry count OR in device bytes (serving/cache.py;
        # max_bucket_bytes=0 leaves the byte budget off). Evictions and
        # the live-bucket count surface through the declared telemetry
        # gauges (batch.bucket_evictions / batch.live_buckets).
        self.max_buckets = int(max_buckets)
        self.max_bucket_bytes = int(max_bucket_bytes)
        from ..serving.cache import HierarchyCache
        self._solvers = HierarchyCache(
            budget_bytes=self.max_bucket_bytes,
            max_entries=self.max_buckets,
            counters={"evict": "batch.bucket_evictions",
                      "entries": "batch.live_buckets"},
            on_evict=lambda key, _bs: self._templates.pop(key, None))
        # the matrix object each bucket's solver currently holds values
        # from (detects when a shared-matrix bucket needs a resetup)
        self._templates: Dict[str, CsrMatrix] = {}
        self._pending: Dict[str, List[SolveRequest]] = {}
        # observability: dispatch log of (bucket_key, real, padded)
        self.dispatch_log: List[Tuple[str, int, int]] = []

    @property
    def live_buckets(self) -> int:
        return len(self._solvers)

    @property
    def bucket_evictions(self) -> int:
        return self._solvers.evictions

    # -- submit/drain -----------------------------------------------------
    def _bucket_key(self, A: CsrMatrix, b) -> str:
        return f"{pattern_fingerprint(A)}/{np.asarray(b).dtype}"

    def submit(self, A: CsrMatrix, b, x0=None) -> SolveRequest:
        """Enqueue one system; returns a ticket whose .result is filled
        by the next drain()."""
        b = np.asarray(b)
        if b.ndim != 1:
            raise BadParametersError(
                f"submit: b must be one system's rhs, got shape {b.shape}")
        req = SolveRequest(A=A, b=b,
                           x0=None if x0 is None else np.asarray(x0),
                           fingerprint=self._bucket_key(A, b),
                           submit_t=time.monotonic())
        self._pending.setdefault(req.fingerprint, []).append(req)
        from ..telemetry import metrics as _tm
        _tm.inc("batch.requests")
        return req

    def pending_count(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def drain(self) -> List[SolveRequest]:
        """Dispatch every pending bucket (each as one or more batched
        solves, padded to the ladder) and fill the tickets. Returns the
        completed requests in submission order per bucket.

        Buckets dispatch OLDEST-FIRST by their earliest pending submit
        time — not in dict-insertion order — so a hot fingerprint's
        backlog cannot starve a cold tenant's single request: the
        longest-waiting request's bucket always goes first, whatever
        interleaving produced the pending map."""
        done: List[SolveRequest] = []
        pending, self._pending = self._pending, {}
        for key in sorted(pending,
                          key=lambda k: min(r.submit_t
                                            for r in pending[k])):
            reqs = pending[key]
            top = self.batch_sizes[-1]
            for i in range(0, len(reqs), top):
                self._dispatch(key, reqs[i:i + top])
            done.extend(reqs)
        return done

    def solve_many(self, matrices: Sequence[CsrMatrix], bs,
                   x0s=None) -> List[SolveResult]:
        """Sync convenience: submit every system, drain, return results
        in order."""
        if x0s is None:
            x0s = [None] * len(matrices)
        reqs = [self.submit(A, b, x0)
                for A, b, x0 in zip(matrices, bs, x0s)]
        self.drain()
        return [r.result for r in reqs]

    # -- dispatch ---------------------------------------------------------
    def _solver_for(self, key: str, template: CsrMatrix) -> BatchedSolver:
        bs = self._solvers.get(key)          # LRU-touching lookup
        if bs is None:
            from ..serving.cache import solve_data_bytes
            bs = BatchedSolver(self.cfg, self.scope)
            bs.setup(template)
            self._templates[key] = template
            # put() evicts LRU buckets past the entry/byte budgets
            # (bytes = the hierarchy's solve-data footprint estimate)
            self._solvers.put(key, bs,
                              nbytes=solve_data_bytes(bs.solver))
        return bs

    def _dispatch(self, key: str, reqs: List[SolveRequest]):
        size = pad_to_bucket_size(len(reqs), self.batch_sizes)
        pad = size - len(reqs)
        self.dispatch_log.append((key, len(reqs), size))
        # bucket occupancy + pad waste (telemetry/metrics.py): the
        # serving-layer signal for whether the ladder rungs fit traffic
        from ..telemetry import metrics as _tm
        _tm.inc("batch.dispatches")
        _tm.inc("batch.padded_systems", pad)
        _tm.set_gauge("batch.bucket_occupancy", len(reqs) / size)
        solver = self._solver_for(key, reqs[0].A)
        matrices = [r.A for r in reqs] + [reqs[-1].A] * pad
        bs = np.stack([r.b for r in reqs] + [reqs[-1].b] * pad)
        if any(r.x0 is not None for r in reqs):
            zeros = np.zeros_like(reqs[0].b)
            x0s = np.stack([r.x0 if r.x0 is not None else zeros
                            for r in reqs] + [zeros] * pad)
        else:
            x0s = None
        # single-matrix fast path: every request references the same
        # matrix object -> multi-RHS (no per-system data stacking at all)
        if all(r.A is reqs[0].A for r in reqs[1:]):
            if self._templates.get(key) is not reqs[0].A:
                try:
                    # same-pattern bucket + splice-safe tree: the batched
                    # traces stay valid across the values-only resetup
                    solver._check_multi_matrix_config()
                    keep = solver._keep_batched_traces()
                except Exception:
                    keep = contextlib.nullcontext()
                with keep:
                    solver.solver.resetup(reqs[0].A)
                self._templates[key] = reqs[0].A
            res = solver.solve_many(bs, x0s=x0s)
        else:
            res = solver.solve_many(bs, matrices=matrices, x0s=x0s)
            # the solver now holds the values of the last system the
            # memoized resetup loop actually visited — NOT necessarily
            # matrices[-1] (duplicates are skipped). Drop the template
            # so the next fast-path dispatch resetups instead of
            # trusting stale bookkeeping.
            self._templates.pop(key, None)
        for req, r in zip(reqs, res.per_system()):
            req.result = r
