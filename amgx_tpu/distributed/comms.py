"""Collective-communication context.

Analog of DistributedComms (include/distributed/distributed_comms.h:
26-250) re-imagined for single-program SPMD: there is no comms *object*
with send/recv — XLA collectives (psum / pmax / ppermute / all_gather)
are emitted by the traced program itself. What remains of the reference
interface is (a) this context, which tells the BLAS reductions which mesh
axis to psum over while a distributed solve is being traced, and (b) the
halo-exchange implementations in dist_matrix.py (the exchange_halo /
add_from_halo analogs).

The reference's two backends (MPI host-buffer staging vs GPU-direct,
comms_mpi_hostbuffer_stream.cu / comms_mpi_gpudirect.cu) collapse to one:
collectives ride ICI/DCN directly, chosen by the mesh topology.

COMMS TELEMETRY: collectives are emitted by the traced program, so
nothing host-side can count executed exchanges — but every exchange
SITE passes through here exactly once per trace, with its window
shapes statically known. `record_exchange` is that hook: each halo /
packed-edge exchange site reports its mode and per-direction window
element counts AT TRACE TIME; the modeled bytes (window elements x
itemsize x sending ranks — exact by construction from the partition
metadata, the number AmgX's interior/boundary split reasons about)
feed the declared dist.* counters and, inside a `collect_exchanges()`
scope, a per-site table the distributed solver merges into
`report.distributed["comms"]` — the data needed to attribute the
multi-chip per-chip-throughput gate.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional

_ACTIVE_AXIS: Optional[str] = None


@contextlib.contextmanager
def collective_axis(name: Optional[str]):
    """Declare the mesh axis reductions must finish over (active during
    tracing of a shard_mapped solve)."""
    global _ACTIVE_AXIS
    prev = _ACTIVE_AXIS
    _ACTIVE_AXIS = name
    try:
        yield
    finally:
        _ACTIVE_AXIS = prev


def active_axis() -> Optional[str]:
    return _ACTIVE_AXIS


# -- trace-time exchange telemetry -------------------------------------

_collect_lock = threading.Lock()
_collecting: Optional[List[Dict[str, Any]]] = None


@contextlib.contextmanager
def collect_exchanges():
    """Collect the exchange sites traced inside the block into the
    yielded list (one dict per site: site/mode/elems + modeled bytes
    per direction). The distributed solver wraps the first call of a
    freshly built shard_map program in this scope — tracing happens
    there — and keeps the table for `report.distributed`."""
    global _collecting
    table: List[Dict[str, Any]] = []
    with _collect_lock:
        prev, _collecting = _collecting, table
    try:
        yield table
    finally:
        with _collect_lock:
            _collecting = prev


def record_exchange(site: str, mode: str, elems_fwd: int,
                    elems_bwd: int, itemsize: int, n_ranks: int):
    """Report one traced exchange site (called by the halo exchange
    implementations while their program is being traced).

    `elems_fwd`/`elems_bwd` are the PER-HOP window element counts in
    the forward (toward rank+1) / backward (toward rank-1) direction;
    the modeled per-direction bytes multiply by itemsize and by the
    number of ranks that actually send in that direction:
    - ring / packed-edge permutes: n_ranks - 1 hops per direction;
    - a2a: every rank ships its full (n_ranks x max_pair) send buffer
      — callers pass elems = n_ranks * max_pair per direction-half
      with both directions folded into fwd (the collective has no
      direction), bwd = 0;
    - gather: every rank broadcasts its tile to the other n_ranks - 1
      — callers fold the n_ranks sending tiles into elems
      (n_ranks * tile), same direction folding.
    Counters count traced SITES (one per site per traced program),
    never executed iterations — documented in the catalog."""
    from ..telemetry import metrics as _tm
    bytes_fwd = int(elems_fwd) * int(itemsize) * max(n_ranks - 1, 0)
    bytes_bwd = int(elems_bwd) * int(itemsize) * max(n_ranks - 1, 0)
    _tm.inc("dist.exchange.calls")
    _tm.inc(f"dist.exchange.{mode}")
    if bytes_fwd:
        _tm.inc("dist.comms.bytes_fwd", bytes_fwd)
    if bytes_bwd:
        _tm.inc("dist.comms.bytes_bwd", bytes_bwd)
    with _collect_lock:
        if _collecting is not None:
            _collecting.append({
                "site": str(site), "mode": str(mode),
                "n_ranks": int(n_ranks),
                "elems_fwd": int(elems_fwd),
                "elems_bwd": int(elems_bwd),
                "itemsize": int(itemsize),
                "bytes_fwd": bytes_fwd, "bytes_bwd": bytes_bwd,
            })


def edge_permutes(n_ranks: int):
    """(forward, backward) ppermute pair lists for nearest-neighbor
    edge exchange along a 1-D mesh axis: forward ships rank i's buffer
    to rank i+1, backward ships rank i+1's buffer to rank i. Ranks with
    no source (rank 0 forward, last rank backward) receive zeros from
    `lax.ppermute` — exactly the DIA zero-padding semantics at the
    global matrix edges. The single implementation shared by the ring
    halo exchange (dist_matrix.py) and the fused-path edge-window
    exchange (fused.py)."""
    fwd = [(i, i + 1) for i in range(n_ranks - 1)]
    bwd = [(i + 1, i) for i in range(n_ranks - 1)]
    return fwd, bwd
