"""Collective-communication context.

Analog of DistributedComms (include/distributed/distributed_comms.h:
26-250) re-imagined for single-program SPMD: there is no comms *object*
with send/recv — XLA collectives (psum / pmax / ppermute / all_gather)
are emitted by the traced program itself. What remains of the reference
interface is (a) this context, which tells the BLAS reductions which mesh
axis to psum over while a distributed solve is being traced, and (b) the
halo-exchange implementations in dist_matrix.py (the exchange_halo /
add_from_halo analogs).

The reference's two backends (MPI host-buffer staging vs GPU-direct,
comms_mpi_hostbuffer_stream.cu / comms_mpi_gpudirect.cu) collapse to one:
collectives ride ICI/DCN directly, chosen by the mesh topology.
"""
from __future__ import annotations

import contextlib
from typing import Optional

_ACTIVE_AXIS: Optional[str] = None


@contextlib.contextmanager
def collective_axis(name: Optional[str]):
    """Declare the mesh axis reductions must finish over (active during
    tracing of a shard_mapped solve)."""
    global _ACTIVE_AXIS
    prev = _ACTIVE_AXIS
    _ACTIVE_AXIS = name
    try:
        yield
    finally:
        _ACTIVE_AXIS = prev


def active_axis() -> Optional[str]:
    return _ACTIVE_AXIS
