"""Collective-communication context.

Analog of DistributedComms (include/distributed/distributed_comms.h:
26-250) re-imagined for single-program SPMD: there is no comms *object*
with send/recv — XLA collectives (psum / pmax / ppermute / all_gather)
are emitted by the traced program itself. What remains of the reference
interface is (a) this context, which tells the BLAS reductions which mesh
axis to psum over while a distributed solve is being traced, and (b) the
halo-exchange implementations in dist_matrix.py (the exchange_halo /
add_from_halo analogs).

The reference's two backends (MPI host-buffer staging vs GPU-direct,
comms_mpi_hostbuffer_stream.cu / comms_mpi_gpudirect.cu) collapse to one:
collectives ride ICI/DCN directly, chosen by the mesh topology.
"""
from __future__ import annotations

import contextlib
from typing import Optional

_ACTIVE_AXIS: Optional[str] = None


@contextlib.contextmanager
def collective_axis(name: Optional[str]):
    """Declare the mesh axis reductions must finish over (active during
    tracing of a shard_mapped solve)."""
    global _ACTIVE_AXIS
    prev = _ACTIVE_AXIS
    _ACTIVE_AXIS = name
    try:
        yield
    finally:
        _ACTIVE_AXIS = prev


def active_axis() -> Optional[str]:
    return _ACTIVE_AXIS


def edge_permutes(n_ranks: int):
    """(forward, backward) ppermute pair lists for nearest-neighbor
    edge exchange along a 1-D mesh axis: forward ships rank i's buffer
    to rank i+1, backward ships rank i+1's buffer to rank i. Ranks with
    no source (rank 0 forward, last rank backward) receive zeros from
    `lax.ppermute` — exactly the DIA zero-padding semantics at the
    global matrix edges. The single implementation shared by the ring
    halo exchange (dist_matrix.py) and the fused-path edge-window
    exchange (fused.py)."""
    fwd = [(i, i + 1) for i in range(n_ranks - 1)]
    bwd = [(i + 1, i) for i in range(n_ranks - 1)]
    return fwd, bwd
