"""Row-block partitioning and halo-map construction.

TPU-native analog of DistributedManager + DistributedArranger
(src/distributed/distributed_manager.cu, distributed_arranger.cu). The
reference machinery — detect neighbors from global column ids, build
per-neighbor B2L (boundary-to-local) index maps, renumber
interior->boundary->halo — collapses in the SPMD mesh formulation:

- every shard owns `n_local = ceil(n / n_shards)` contiguous rows, padded
  to equal size (the XLA static-shape requirement); empty padded rows are
  harmless (zero values, zero rhs);
- off-owned column references become one *halo gather map* per shard,
  padded to the max halo size over shards;
- when the partition is a 1-D domain decomposition whose halos only touch
  ranks +/- 1 (the Poisson-slab case), per-neighbor send/recv maps are
  built for a `ppermute` ring exchange (the B2L ring analog); otherwise
  the exchange falls back to all_gather + static gather.

Rectangular operators (the P/R transfer matrices of a distributed AMG
hierarchy, classical_amg_level.cu:297-315) partition rows by the
row-side decomposition and columns by the column-side one; the halo
exchange then reads the *column-side* distributed vector.

Partitioning happens once at upload time on host (numpy), mirroring the
reference's uploadMatrix/renumber path (SURVEY §3.5); everything
downstream is device SPMD.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..errors import BadParametersError
from ..matrix import CsrMatrix


@dataclasses.dataclass(frozen=True)
class DistPartition:
    """Host-side partition product: stacked (n_ranks, ...) device arrays
    ready to be shard_mapped over the mesh axis. Entries are split into
    owned-column and halo-column sets (the interior/boundary overlap
    split of src/multiply.cu:95-110)."""

    rid_own: jnp.ndarray            # (R, e_own) int32 row id (pad n_local)
    ci_own: jnp.ndarray             # (R, e_own) int32 owned col (pad 0)
    va_own: jnp.ndarray             # (R, e_own)
    rid_halo: jnp.ndarray           # (R, e_halo) int32 (pad n_local)
    ci_halo: jnp.ndarray            # (R, e_halo) int32 halo slot (pad 0)
    va_halo: jnp.ndarray            # (R, e_halo)
    diag: jnp.ndarray               # (R, n_local) local diagonal (pad 1.0)
    halo_src: jnp.ndarray           # (R, n_halo) global col id (pad 0)
    # ring maps (None unless ring mode): send rows / recv halo slots
    send_prev: Optional[jnp.ndarray]   # (R, max_send) local col (pad n_lc)
    send_next: Optional[jnp.ndarray]
    recv_prev: Optional[jnp.ndarray]   # (R, max_send) halo slot (pad n_halo)
    recv_next: Optional[jnp.ndarray]
    # all-to-all maps (None unless a2a mode)
    a2a_send: Optional[jnp.ndarray]    # (R, R, max_pair) local col (pad n_lc)
    a2a_recv: Optional[jnp.ndarray]    # (R, R, max_pair) halo slot (pad n_h)
    n_global: int                   # global rows
    n_global_cols: int              # global cols
    n_local: int                    # local rows per shard
    n_local_cols: int               # local (owned) cols per shard
    n_halo: int
    n_ranks: int
    exchange_mode: str              # "ring" | "a2a" | "gather"
    # original block dims (entries are expanded to scalars; block rows
    # never split across shards) + per-block-row diagonal blocks
    block_dimx: int = 1
    block_dimy: int = 1
    diag_block: Optional[jnp.ndarray] = None   # (R, nb_local, bx, by)

    @property
    def neighbor_only(self) -> bool:
        return self.exchange_mode == "ring"


def partition_matrix(A: CsrMatrix, n_ranks: int) -> DistPartition:
    """Split a global CsrMatrix into equal row blocks with halo maps
    (loadDistributedMatrix / create_B2L / renumber_to_local analog).
    Columns are partitioned by their own dimension, so rectangular
    transfer operators shard consistently with the vectors they act on."""
    if A.has_external_diag:
        raise BadParametersError("fold external diagonal before partitioning")
    bx, by = A.block_dimx, A.block_dimy
    diag_block_g = None
    if A.is_block:
        # expand b x b blocks to scalar entries (the scalar decomposition
        # is exact); keep the block diagonal for block-exact smoothers
        if bx != by:
            raise BadParametersError(
                "distributed block matrices must be square-blocked")
        diag_block_g = np.asarray(A.diagonal())
        A = _expand_blocks(A)
    n = A.num_rows
    m = A.num_cols
    n_local = -(-n // n_ranks)
    n_local_cols = -(-m // n_ranks)
    if bx > 1:
        # block rows must stay rank-local so block-diagonal smoother
        # applications see whole blocks
        n_local = -(-n_local // bx) * bx
        n_local_cols = -(-n_local_cols // by) * by
    row_offsets = np.asarray(A.row_offsets)
    col_indices = np.asarray(A.col_indices)
    values = np.asarray(A.values)

    pieces = []
    for r in range(n_ranks):
        lo = min(r * n_local, n)
        hi = min(lo + n_local, n)
        s, e = int(row_offsets[lo]), int(row_offsets[hi])
        pieces.append((row_offsets[lo:hi + 1] - row_offsets[lo],
                       col_indices[s:e], values[s:e]))
    return _partition_from_pieces(
        pieces, n, m, n_local, n_local_cols, bx, by, diag_block_g)


def partition_from_pieces(pieces, n_global: int,
                          n_global_cols: Optional[int] = None,
                          dtype=np.float64) -> DistPartition:
    """Build a DistPartition directly from per-rank matrix pieces — the
    DistributedArranger analog (include/distributed/distributed_arranger
    .h:28-117): neighbors are detected from global column ids and halo
    maps built per rank, WITHOUT ever assembling a global matrix. This
    is the upload path behind AMGX_matrix_upload_distributed /
    AMGX_matrix_upload_all_global.

    pieces: list of (row_ptrs_local (n_r+1,), col_indices_global,
    values) per rank, rows in contiguous global blocks (rank r owns
    rows [sum(n_<r), sum(n_<=r))). Ranks may own unequal row counts;
    the stacked layout pads to the largest."""
    n_ranks = len(pieces)
    counts = [len(p[0]) - 1 for p in pieces]
    if sum(counts) != n_global:
        raise BadParametersError(
            f"pieces cover {sum(counts)} of {n_global} global rows")
    m = n_global_cols if n_global_cols is not None else n_global
    pieces = [
        (np.asarray(p[0], np.int64), np.asarray(p[1], np.int64),
         np.asarray(p[2], dtype)) for p in pieces]
    n_local = -(-n_global // n_ranks)
    if any(c != n_local for c in counts[:-1]) or counts[-1] > n_local:
        # uneven contiguous blocks: the equal-block physical layout
        # (rank = id // n_local) requires re-slicing — rows are already
        # globally contiguous across pieces, so the block boundaries
        # just move (no renumbering, columns unchanged)
        pieces = _reslice_equal(pieces, n_global, n_local)
    n_local_cols = n_local if m == n_global else -(-m // n_ranks)
    return _partition_from_pieces(
        pieces, n_global, m, n_local, n_local_cols, 1, 1, None)


def _reslice_equal(pieces, n_global: int, n_local: int):
    """Re-slice contiguous per-rank pieces into equal row blocks (the
    stacked-layout requirement). Pure slicing of the concatenated entry
    stream — no renumbering."""
    counts = np.concatenate([np.diff(p[0]) for p in pieces])
    cols = np.concatenate([p[1] for p in pieces])
    vals = np.concatenate([p[2] for p in pieces])
    ro = np.zeros(n_global + 1, np.int64)
    np.cumsum(counts, out=ro[1:])
    out = []
    for r in range(len(pieces)):
        lo = min(r * n_local, n_global)
        hi = min(lo + n_local, n_global)
        s, e = int(ro[lo]), int(ro[hi])
        out.append((ro[lo:hi + 1] - ro[lo], cols[s:e], vals[s:e]))
    return out


def _partition_from_pieces(pieces, n, m, n_local, n_local_cols, bx, by,
                           diag_block_g) -> DistPartition:
    """Shared assembly: per-rank pieces -> stacked halo-split arrays +
    exchange maps."""
    n_ranks = len(pieces)
    square = (n == m)
    ranks = []
    max_own = 1
    max_hal = 1
    max_halo = 1
    vdtype = None
    for r in range(n_ranks):
        ro_r, cols_g, vals_r = pieces[r]
        ro_r = np.asarray(ro_r)
        cols_g = np.asarray(cols_g)
        vals_r = np.asarray(vals_r)
        vdtype = vals_r.dtype
        lo = r * n_local
        clo = min(r * n_local_cols, m)
        chi = min(clo + n_local_cols, m)
        owned = (cols_g >= clo) & (cols_g < chi)
        halo_global = np.unique(cols_g[~owned])
        ranks.append((lo, ro_r, clo, cols_g, vals_r, owned, halo_global))
        max_own = max(max_own, int(owned.sum()))
        max_hal = max(max_hal, int((~owned).sum()))
        max_halo = max(max_halo, halo_global.size)

    R = n_ranks
    rid_own = np.full((R, max_own), n_local, np.int32)
    ci_own = np.zeros((R, max_own), np.int32)
    va_own = np.zeros((R, max_own), vdtype)
    rid_hal = np.full((R, max_hal), n_local, np.int32)
    ci_hal = np.zeros((R, max_hal), np.int32)
    va_hal = np.zeros((R, max_hal), vdtype)
    dg = np.ones((R, n_local), vdtype)
    halo_src = np.zeros((R, max_halo), np.int64)
    for r, (lo, ro_r, clo, cols_g, vals_r, owned, hg) in enumerate(ranks):
        nr = ro_r.shape[0] - 1
        lrows = np.repeat(np.arange(nr), np.diff(ro_r))
        no = int(owned.sum())
        rid_own[r, :no] = lrows[owned]
        ci_own[r, :no] = cols_g[owned] - clo
        va_own[r, :no] = vals_r[owned]
        nh = lrows.shape[0] - no
        rid_hal[r, :nh] = lrows[~owned]
        ci_hal[r, :nh] = np.searchsorted(hg, cols_g[~owned])
        va_hal[r, :nh] = vals_r[~owned]
        halo_src[r, : hg.size] = hg
        if square:
            is_diag = (cols_g == lrows + lo)
            dg[r, lrows[is_diag]] = vals_r[is_diag]

    # exchange mode: ring if all halo cols owned by ranks r-1 / r+1;
    # else all-to-all when the padded pair buffers beat the full gather;
    # else all_gather fallback
    neighbor_only = n_ranks > 1
    for r, (*_, hg) in enumerate(ranks):
        if hg.size and not np.all((hg // n_local_cols >= r - 1)
                                  & (hg // n_local_cols <= r + 1)):
            neighbor_only = False
            break

    send_prev = send_next = recv_prev = recv_next = None
    a2a_send = a2a_recv = None
    if neighbor_only:
        max_send = 1
        sp = [np.zeros(0, np.int64)] * R
        sn = [np.zeros(0, np.int64)] * R
        rp = [np.zeros(0, np.int64)] * R
        rn_ = [np.zeros(0, np.int64)] * R
        for r, (*_, hg) in enumerate(ranks):
            src_rank = np.clip(hg // n_local_cols, 0, R - 1)
            from_prev = hg[src_rank == r - 1]
            from_next = hg[src_rank == r + 1]
            # my halo slots for those cols (hg sorted -> searchsorted)
            rp[r] = np.searchsorted(hg, from_prev)
            rn_[r] = np.searchsorted(hg, from_next)
            # the neighbor must send those cols (local to the neighbor)
            if r - 1 >= 0:
                sn[r - 1] = from_prev - (r - 1) * n_local_cols
            if r + 1 < R:
                sp[r + 1] = from_next - (r + 1) * n_local_cols
        for r in range(R):
            max_send = max(max_send, sp[r].size, sn[r].size)
        send_prev = np.full((R, max_send), n_local_cols, np.int32)
        send_next = np.full((R, max_send), n_local_cols, np.int32)
        recv_prev = np.full((R, max_send), max_halo, np.int32)
        recv_next = np.full((R, max_send), max_halo, np.int32)
        for r in range(R):
            send_prev[r, : sp[r].size] = sp[r]
            send_next[r, : sn[r].size] = sn[r]
            recv_prev[r, : rp[r].size] = rp[r]
            recv_next[r, : rn_[r].size] = rn_[r]
        send_prev = jnp.asarray(send_prev)
        send_next = jnp.asarray(send_next)
        recv_prev = jnp.asarray(recv_prev)
        recv_next = jnp.asarray(recv_next)
        exchange_mode = "ring"
    else:
        # all-to-all maps: what each peer p owes rank r (and where r
        # scatters it). hg is sorted, so per-peer slices stay aligned on
        # both sides.
        pair_send = [[np.zeros(0, np.int64)] * R for _ in range(R)]
        pair_recv = [[np.zeros(0, np.int64)] * R for _ in range(R)]
        max_pair = 0
        for r, (*_, hg) in enumerate(ranks):
            if not hg.size:
                continue
            src_rank = np.clip(hg // n_local_cols, 0, R - 1)
            for p in np.unique(src_rank):
                need = hg[src_rank == p]
                pair_send[int(p)][r] = need - int(p) * n_local_cols
                pair_recv[r][int(p)] = np.searchsorted(hg, need)
                max_pair = max(max_pair, need.size)
        # a2a beats the full gather when the padded buffers are smaller
        if n_ranks > 1 and max_pair * R < n_local_cols * R // 2:
            a2a_send = np.full((R, R, max(max_pair, 1)), n_local_cols,
                               np.int32)
            a2a_recv = np.full((R, R, max(max_pair, 1)), max_halo,
                               np.int32)
            for r in range(R):
                for p in range(R):
                    a2a_send[r, p, : pair_send[r][p].size] = pair_send[r][p]
                    a2a_recv[r, p, : pair_recv[r][p].size] = pair_recv[r][p]
            a2a_send = jnp.asarray(a2a_send)
            a2a_recv = jnp.asarray(a2a_recv)
            exchange_mode = "a2a"
        else:
            exchange_mode = "gather"

    diag_block = None
    if diag_block_g is not None:
        nb_local = n_local // bx
        pad = n_ranks * nb_local - diag_block_g.shape[0]
        db = np.concatenate([
            diag_block_g,
            np.broadcast_to(np.eye(bx, dtype=diag_block_g.dtype),
                            (pad, bx, bx))]) if pad else diag_block_g
        diag_block = jnp.asarray(db.reshape(n_ranks, nb_local, bx, bx))

    return DistPartition(
        rid_own=jnp.asarray(rid_own), ci_own=jnp.asarray(ci_own),
        va_own=jnp.asarray(va_own), rid_halo=jnp.asarray(rid_hal),
        ci_halo=jnp.asarray(ci_hal), va_halo=jnp.asarray(va_hal),
        diag=jnp.asarray(dg), halo_src=jnp.asarray(halo_src),
        send_prev=send_prev, send_next=send_next,
        recv_prev=recv_prev, recv_next=recv_next,
        a2a_send=a2a_send, a2a_recv=a2a_recv,
        n_global=n, n_global_cols=m, n_local=n_local,
        n_local_cols=n_local_cols, n_halo=max_halo, n_ranks=n_ranks,
        exchange_mode=exchange_mode, block_dimx=bx, block_dimy=by,
        diag_block=diag_block)


def _expand_blocks(A: CsrMatrix) -> CsrMatrix:
    """Host-side expansion of a block-CSR matrix into the equivalent
    scalar CSR (each b x b block becomes b^2 scalar entries). Exact: the
    scalar operator is the same linear map over the flat vector."""
    bx, by = A.block_dimx, A.block_dimy
    rows, cols, vals = (np.asarray(x) for x in A.coo())
    e = rows.shape[0]
    r_s = (rows[:, None, None] * bx
           + np.arange(bx)[None, :, None]).repeat(by, axis=2).reshape(-1)
    c_s = (cols[:, None, None] * by
           + np.arange(by)[None, None, :]).repeat(bx, axis=1).reshape(-1)
    v_s = np.asarray(vals).reshape(e, bx, by).reshape(-1)
    order = np.lexsort((c_s, r_s))
    r_s, c_s, v_s = r_s[order], c_s[order], v_s[order]
    n, m = A.num_rows * bx, A.num_cols * by
    counts = np.bincount(r_s, minlength=n)
    row_offsets = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    return CsrMatrix.from_scipy_like(
        row_offsets, c_s.astype(np.int32), jnp.asarray(v_s), n, m)


def partition_vector(v, n_ranks: int, n_local: Optional[int] = None):
    """Split + zero-pad a global vector into stacked (n_ranks, n_local).
    Pass the partition's n_local for block systems (partition_matrix
    rounds it up so block rows stay rank-local)."""
    v = np.asarray(v)
    n = v.shape[0]
    if n_local is None:
        n_local = -(-n // n_ranks)
    out = np.zeros((n_ranks, n_local), v.dtype)
    out.reshape(-1)[:n] = v
    return jnp.asarray(out)


def unpartition_vector(vl, n_global: int):
    """Inverse of partition_vector (gather shards back to one host array)."""
    return jnp.asarray(np.asarray(vl).reshape(-1)[:n_global])
