"""Distributed solver wrapper: run any supported solver tree SPMD over a
device mesh.

The reference runs one MPI rank per GPU, each executing the same solver
code against its partition (SURVEY §2.6). Here a single program is
shard_mapped over a 1-D `jax.sharding.Mesh` axis: the *same* solver
classes trace their solve loop per shard, `ops.spmv` dispatches to the
halo-exchanging ShardMatrix, and the BLAS reductions finish with psum via
the collective-axis context — the MPI_Allreduce analog. Host code stays
single-controller (no mpirun).

Round-1 scope: Krylov solvers (CG/BiCGSTAB/GMRES/FGMRES/PCG/PCGF/
PBICGSTAB) with NOSOLVER / BLOCK_JACOBI / JACOBI_L1 preconditioning.
Distributed AMG arrives with the coarse-consolidation layer.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from .._compat import shard_map

from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..solvers.base import SolveResult, make_solver
from . import comms
from .dist_matrix import ShardMatrix, shard_matrix_from_partition
from .partition import (partition_matrix, partition_vector,
                        unpartition_vector)

# preconditioners with hand-built per-shard data (diagonal-derived);
# ANY other solver is admitted when its solve-data partitions row-wise
# (the same data-driven test the distributed AMG smoother sharding
# uses, amg.py _shard_smoother_data) — matching the reference's
# any-tree-any-rank-count composability (include/solvers/solver.h:271)
_DIAG_PRECONDS = {"NOSOLVER", "DUMMY", "BLOCK_JACOBI", "JACOBI",
                  "JACOBI_L1", "AMG"}


def default_mesh(n_devices: Optional[int] = None, axis: str = "p",
                 devices=None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise BadParametersError(
            f"default_mesh: {n} devices requested but only {len(devs)} "
            f"visible ({devs[0].platform}); on CPU force virtual devices "
            "before any jax call (see _cpu_backend.force_cpu)")
    return Mesh(np.array(devs[:n]), (axis,))


class DistributedSolver:
    """Solve A x = b with row-block domain decomposition over a mesh."""

    def __init__(self, cfg: Config, mesh: Mesh, scope: str = "default"):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_ranks = mesh.devices.size
        name, sscope = cfg.get_solver("solver", scope)
        # process-wide span-fencing mode, latched both ways like
        # create_solver (env toggle ORed in; see telemetry/spans.py)
        from ..telemetry import spans as _spans
        _spans.set_sync(bool(int(cfg.get("telemetry_sync", sscope)))
                        or _spans.env_sync())
        self.solver = make_solver(name, cfg, sscope)
        if self.solver.scaling not in ("NONE", ""):
            raise BadParametersError(
                "distributed solve: scaling is not yet supported (the "
                "distributed path bypasses Solver.setup; scale the system "
                "before partitioning)")
        # non-diagonal preconditioners are validated data-driven at
        # setup time (their solve-data must partition row-wise)
        self._fn = None

    # -- setup -----------------------------------------------------------
    def setup(self, A: CsrMatrix):
        if not A.initialized:
            A = A.init()
        return self.setup_from_partition(
            partition_matrix(A, self.n_ranks), _global_A=A)

    def setup_from_partition(self, part, _global_A: Optional[CsrMatrix]
                             = None):
        """Set up from per-rank pieces (a DistPartition built by
        partition_from_pieces — the AMGX_matrix_upload_distributed
        path). With the sharded hierarchy build no global matrix is
        needed; configs that fall back to the controller-global setup
        require one and raise without it."""
        t0 = time.perf_counter()
        A = _global_A
        if part.n_ranks != self.n_ranks:
            raise BadParametersError(
                f"partition has {part.n_ranks} ranks, mesh has "
                f"{self.n_ranks}")
        self.shard_A = shard_matrix_from_partition(part, self.axis)
        self.part = part
        self._upload_user_colors = (A is not None
                                    and A.user_colors is not None)
        # wire the solver chain: A views + per-shard Jacobi data. AMG
        # members build their hierarchy SHARDED when the config supports
        # it (distributed/setup.py — per-rank level build, no global
        # coarse operator); otherwise the hierarchy is built on the
        # GLOBAL matrix on the controller, then every level is sharded
        # (distributed/amg.py — the round-2 fallback path).
        self._sharded_amg = {}
        self._precond_shard_data = {}
        s = self.solver
        while s is not None:
            if s.name not in _DIAG_PRECONDS and s is not self.solver:
                # data-driven admission: set up on the global matrix,
                # shard the solve-data row-wise (raises when a data key
                # does not partition by rows)
                if A is None:
                    raise BadParametersError(
                        f"distributed preconditioner {s.name} from "
                        "per-rank pieces is not supported (its setup "
                        "needs the global matrix on the controller)")
                from .amg import _shard_smoother_data
                s._owns_scaling = False
                s.setup(A)
                self._precond_shard_data[id(s)] = _shard_smoother_data(
                    s, self.shard_A, self.n_ranks, self.axis)
            if s.name == "AMG":
                data = self._try_sharded_setup(s, A)
                if data is not None:
                    self._sharded_amg[id(s)] = data
                elif A is not None:
                    s.amg.setup(A)
                else:
                    raise BadParametersError(
                        "distributed AMG from per-rank pieces requires "
                        "the sharded setup (this config fell back to "
                        "the controller-global path, which needs the "
                        "global matrix); see distributed_setup_mode")
            s.A = self.shard_A           # duck-typed operator view
            s = s.preconditioner
        self._data = self._build_data()
        self._fn = None
        self._comms_table = None      # filled at first (re)trace
        self._shard_stats = self._compute_shard_stats(part)
        self.setup_time = time.perf_counter() - t0
        return self

    def _compute_shard_stats(self, part):
        """Per-shard rows/nnz tallies + imbalance gauges (host
        arithmetic on the partition's index metadata, setup-time
        only). max/mean imbalance is the load-balance number the
        per-chip-throughput attribution reads: a shard at 1.3x mean
        nnz IS a 1.3x per-chip gate on a bandwidth-bound sweep."""
        from ..telemetry import metrics as _tm
        R, nl, n = part.n_ranks, part.n_local, part.n_global
        rows = [min((r + 1) * nl, n) - min(r * nl, n) for r in range(R)]
        rid_own = np.asarray(part.rid_own)
        rid_halo = np.asarray(part.rid_halo)
        nnz = (np.sum(rid_own < nl, axis=1)
               + (np.sum(rid_halo < nl, axis=1)
                  if rid_halo.size else np.zeros(R, np.int64)))
        nnz = [int(v) for v in nnz]
        rows_imb = max(rows) / max(np.mean(rows), 1e-300)
        nnz_imb = max(nnz) / max(np.mean(nnz), 1e-300) if max(nnz) \
            else 1.0
        _tm.set_gauge("dist.shard.rows_imbalance", round(rows_imb, 4))
        _tm.set_gauge("dist.shard.nnz_imbalance", round(nnz_imb, 4))
        return {"rows": rows, "nnz": nnz,
                "rows_imbalance": round(float(rows_imb), 4),
                "nnz_imbalance": round(float(nnz_imb), 4)}

    def _try_sharded_setup(self, s, global_A=None):
        """Run the per-shard hierarchy build when the config supports it
        (distributed_setup_mode=auto|sharded). Returns the stacked AMG
        solve-data, or None to fall back to the global-setup path.
        `global_A` (absent on the pieces path) only feeds the finest
        level's halo-folded fused-smoother payload."""
        from .setup import build_sharded_hierarchy, sharded_eligible
        mode = str(self.cfg.get("distributed_setup_mode", s.amg.scope))
        if mode == "global":
            return None
        reason = sharded_eligible(s.amg, self.shard_A)
        if reason is None and getattr(self, "_upload_user_colors", False):
            names = {s.amg.cfg.get_solver(k, s.amg.scope)[0].upper()
                     for k in ("smoother", "fine_smoother",
                               "coarse_smoother")}
            if any(n.startswith("MULTICOLOR") or n == "FIXCOLOR_GS"
                   for n in names):
                # a user-attached coloring (AMGX_matrix_attach_coloring)
                # must drive the color-sweep smoothers; the sharded
                # setup always runs its own JPL — fall back so the
                # attached colors are honored (single-device _color()
                # semantics). Jacobi-family smoothers never read
                # colors, so they stay sharded-eligible.
                reason = ("user-attached matrix coloring requires the "
                          "global setup")
        # aggregation decisions need |a_ji| == |a_ij|; the classical
        # reverse-edge strength additionally uses the owned value's
        # SIGN as the transpose proxy, so it needs signed symmetry
        if reason is None and not self._value_symmetry_probe(
                signed=s.amg.algorithm == "CLASSICAL"):
            # the sharded selectors assume |a_ji| = |a_ij| (setup.py
            # module docs); on value-asymmetric matrices their decisions
            # would silently diverge from the single-device path —
            # fail fast / fall back instead
            reason = ("matrix is not value-symmetric (sharded setup "
                      "decisions assume |a_ji| = |a_ij|)")
        if reason is not None:
            if mode == "sharded":
                raise BadParametersError(
                    f"distributed_setup_mode=sharded: {reason}")
            return None
        data = build_sharded_hierarchy(s.amg, self.shard_A, self.mesh,
                                       self.axis, global_A=global_A)
        if data is None and mode == "sharded":
            raise BadParametersError(
                "distributed_setup_mode=sharded: problem too small for "
                "one sharded level (fits a single shard's budget)")
        return data

    def _value_symmetry_probe(self, signed: bool = False) -> bool:
        """Randomized on-device symmetry check: <y, A x> == <x, A y>
        for symmetric A (shard_mapped SpMVs + psum dots — no global
        matrix is ever materialized, preserving the pieces path's
        contract). The sharded selectors assume value symmetry
        (setup.py module docs; the classical reverse-edge strength
        additionally relies on signs), and a generically asymmetric
        matrix fails this probe with probability ~1 — it then falls
        back to the global setup (auto) or raises (sharded). The probe
        is signed-strict, so a |.|-symmetric sign-flipped matrix also
        falls back: conservative, and correct for the Notay weights
        which read signed values.

        TWO independent probe pairs must both agree, and the dots
        accumulate in f64 regardless of the value dtype: with f64
        accumulation the probe's own rounding no longer grows with
        sqrt(n) (only the SpMV's per-row rounding in the value dtype
        remains), so the tolerance is a small dtype-eps multiple instead
        of the old 100*sqrt(n)*eps — at 128^3/f32 that was ~2e-2
        relative slack, wide enough to wave through mildly nonsymmetric
        f32 matrices whose selector decisions then silently diverged."""
        from . import comms
        from ..ops.spmv import spmv
        del signed    # the dot probe is signed-strict for all callers
        n = self.part.n_global
        R = self.n_ranks
        axis = self.axis

        def body(M, xs, ys):
            Ml = jax.tree.map(lambda a: a[0], M)
            x64 = xs[0].astype(jnp.float64)
            y64 = ys[0].astype(jnp.float64)
            with comms.collective_axis(axis):
                ax = spmv(Ml, xs[0]).astype(jnp.float64)
                ay = spmv(Ml, ys[0]).astype(jnp.float64)
                s1 = jax.lax.psum(jnp.vdot(y64, ax), axis)
                s2 = jax.lax.psum(jnp.vdot(x64, ay), axis)
                norms2 = jax.lax.psum(jnp.stack([
                    jnp.vdot(x64, x64), jnp.vdot(y64, y64),
                    jnp.vdot(ax, ax), jnp.vdot(ay, ay)]), axis)
            return jnp.concatenate([jnp.stack([s1, s2]), norms2])

        pspec = jax.tree.map(lambda _: P(axis), self.shard_A)
        fn = jax.jit(shard_map(
            body, mesh=self.mesh, in_specs=(pspec, P(axis), P(axis)),
            out_specs=P(), check_vma=False))
        vdt = np.dtype(self.shard_A.va_own.dtype)
        if vdt.kind != "f":
            vdt = np.dtype(np.float64)
        tol = max(1e-12, 100.0 * np.finfo(vdt).eps)
        for seed in (0xA317, 0x5C12):
            rng = np.random.default_rng(seed)
            xl = partition_vector(rng.standard_normal(n), R,
                                  self.part.n_local)
            yl = partition_vector(rng.standard_normal(n), R,
                                  self.part.n_local)
            s1, s2, nx2, ny2, nax2, nay2 = (
                float(v) for v in fn(self.shard_A, xl, yl))
            scale = max(abs(s1), abs(s2), 1e-300)
            # the probe's own noise floor: the value-dtype SpMV rounding
            # reaches the f64 dots as |y^T δ(Ax)| <~ eps_v * ||y||*||Ax||
            # — without this term a symmetric matrix whose quadratic
            # form happens to cancel (|s1| << ||y||*||Ax||) would be
            # misclassified as asymmetric
            floor = 100.0 * np.finfo(vdt).eps * max(
                np.sqrt(ny2 * nax2), np.sqrt(nx2 * nay2))
            if abs(s1 - s2) > max(tol * scale, floor):
                return False
        return True

    def _build_data(self):
        """Hand-build the solve-data pytree (stacked arrays); per-shard
        Jacobi inverses come from the partitioned diagonal."""
        def chain_data(s):
            d = {"A": self.shard_A}
            if s.name in ("BLOCK_JACOBI", "JACOBI"):
                if self.part.diag_block is not None:
                    # block-exact Jacobi: batched inverse of the block
                    # diagonal, partitioned by block rows
                    from ..ops.dense import safe_inverse
                    d["dinv"] = safe_inverse(self.part.diag_block)
                else:
                    d["dinv"] = _dinv(self.part.diag)
            elif s.name == "JACOBI_L1":
                if self.part.diag_block is not None:
                    raise BadParametersError(
                        "distributed JACOBI_L1: scalar matrices only; "
                        "use BLOCK_JACOBI for block systems")
                d["dinv"] = _dinv_l1(self.part)
            elif s.name == "AMG":
                if id(s) in self._sharded_amg:
                    d["amg"] = self._sharded_amg[id(s)]
                else:
                    from .amg import shard_amg
                    d["amg"] = shard_amg(s.amg, self.n_ranks, self.axis)
            elif id(s) in self._precond_shard_data:
                d.update({k: v for k, v in
                          self._precond_shard_data[id(s)].items()
                          if k != "A"})
            if s.preconditioner is not None:
                d["precond"] = chain_data(s.preconditioner)
            return d

        return chain_data(self.solver)

    # -- solve -----------------------------------------------------------
    def _build_fn(self):
        # diag=False: a sharded probe would record per-shard norms
        # (needs a psum to mean anything); the stats unpack below
        # assumes the bare layout
        raw = self.solver._build_solve_fn(diag=False)
        axis = self.axis

        def shard_fn(data, b, x0):
            local = jax.tree.map(lambda a: a[0], data)
            with comms.collective_axis(axis):
                x, stats = raw(local, b[0], x0[0])
                # all-reduce the SolveStatus (packed at stats[2]) so
                # every shard reports the same outcome: the codes are
                # severity-ordered (resilience/status.py), so pmax
                # picks the worst — e.g. one shard's corrupted halo
                # NaN beats a neighbor's locally-converged view. The
                # converged flag (stats[1]) is re-derived from the
                # reduced code: a shard-local converged=1 must not
                # survive a peer's failure (SolveResult treats
                # converged as authoritative)
                worst = jax.lax.pmax(stats[2], axis)
                stats = stats.at[2].set(worst).at[1].set(
                    (worst == 0).astype(stats.dtype))
            return x[None], stats

        pspec = jax.tree.map(lambda _: P(axis), self._data)
        mapped = shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(pspec, P(axis), P(axis)),
            out_specs=(P(axis), P()),
            check_vma=False)
        return jax.jit(mapped)

    def solve(self, b, x0=None) -> SolveResult:
        from ..resilience import faultinject as _fi
        n = self.part.n_global
        bl = partition_vector(np.asarray(b), self.n_ranks,
                              self.part.n_local)
        xl = partition_vector(
            np.zeros(n, bl.dtype) if x0 is None else np.asarray(x0),
            self.n_ranks, self.part.n_local)
        fresh_trace = self._fn is None or \
            getattr(self, "_fn_epoch", 0) != _fi.epoch()
        if fresh_trace:
            # the faultinject epoch invalidates the cached shard_map
            # program (same contract as the base solver's jit key)
            from ..telemetry import metrics as _tm
            _tm.inc("solver.retrace.distributed")
            self._fn = self._build_fn()
            self._fn_epoch = _fi.epoch()
        t0 = time.perf_counter()
        if fresh_trace:
            # tracing happens on this first call: collect the exchange
            # sites it contains (comms.record_exchange) into the
            # per-site comms table report.distributed carries
            with comms.collect_exchanges() as tbl:
                x, stats = jax.block_until_ready(
                    self._fn(self._data, bl, xl))
            if tbl:
                self._comms_table = tbl
        else:
            x, stats = jax.block_until_ready(
                self._fn(self._data, bl, xl))
        solve_time = time.perf_counter() - t0
        iters_i, conv, status, n0, rn, hist = self.solver.unpack_stats(
            stats, self.solver.max_iters + 1)
        res = SolveResult(
            x=unpartition_vector(x, n), iterations=iters_i,
            converged=conv, res_norm=np.asarray(rn),
            norm0=np.asarray(n0),
            res_history=np.asarray(hist)
            if self.solver.store_res_history else None,
            setup_time=self.setup_time, solve_time=solve_time,
            status_code=status)
        if getattr(self.solver, "telemetry", False):
            # controller = rank-0 analog: ONE report per solve, with
            # the per-shard tallies (already on the controller via the
            # partition metadata) gathered into the distributed block
            from ..telemetry import build_report, spans as _spans
            res.report = build_report(
                self.solver, res, hist=np.asarray(hist),
                distributed={
                    "n_ranks": int(self.n_ranks),
                    "axis": str(self.axis),
                    "n_global": int(n),
                    "rows_per_shard": int(self.part.n_local),
                    # comms table: every exchange site the traced
                    # program contains, with modeled per-direction
                    # bytes (comms.record_exchange docs)
                    "comms": self._comms_table,
                    "shards": dict(self._shard_stats)
                    if getattr(self, "_shard_stats", None) else None,
                })
            # one Perfetto track per shard: the per-shard tallies as
            # synthetic solve-length slices (record_span tid override)
            # so the trace viewer shows the mesh, not just the
            # controller thread
            stats_tbl = getattr(self, "_shard_stats", None)
            for r in range(self.n_ranks):
                _spans.record_span(
                    "shard.solve", t0, solve_time,
                    args={"shard": r,
                          "rows": None if stats_tbl is None
                          else stats_tbl["rows"][r],
                          "nnz": None if stats_tbl is None
                          else stats_tbl["nnz"][r]},
                    tid=1_000_000 + r)
        return res


def _dinv(diag):
    safe = jnp.where(diag == 0, 1.0, diag)
    return jnp.where(diag == 0, 0.0, 1.0 / safe)


def _dinv_l1(part):
    """Per-shard L1-strengthened diagonal inverse. The off-diagonal row L1
    sums include halo columns — matching the reference's OWNED-view
    semantics."""
    R, n_local = part.diag.shape

    def one(vo, ro, co, vh, rh):
        off = jnp.where(co == ro, 0.0, jnp.abs(vo))
        return jax.ops.segment_sum(off, ro, num_segments=n_local) + \
            jax.ops.segment_sum(jnp.abs(vh), rh, num_segments=n_local)

    l1 = jax.vmap(one)(part.va_own, part.rid_own, part.ci_own,
                       part.va_halo, part.rid_halo)
    d = part.diag
    dl1 = d + jnp.sign(d) * l1
    return _dinv(dl1)
