"""Shard-local distributed matrix operator.

The solve-phase object: a registered pytree that duck-types the SpMV
operator interface (ops.spmv dispatches to .spmv), performing the halo
exchange with XLA collectives. This is the TPU-native replacement for the
reference's DistributedManager gather kernels + MPI Isend/Irecv ring
(include/distributed/distributed_manager.h:75-170,
comms_mpi_hostbuffer_stream.cu:321-676):

- ring mode: gather boundary values into per-neighbor send buffers
  (B2L gather analog) and `lax.ppermute` them one hop along the mesh
  axis — two permutes (toward prev, toward next) ride ICI;
- general mode: `lax.all_gather(tiled)` + static gather by global id.

Rectangular shards (the P/R transfer operators of a distributed AMG
hierarchy) partition rows by the row-side decomposition and columns by
the column-side one; `spmv` consumes the column-side local vector and
produces the row-side local vector, so restriction/prolongation are the
same halo-exchange + local SpMV as the operator itself
(classical_amg_level.cu restrict/prolongate analog).

Latency hiding (interior SpMV overlapped with the exchange,
src/multiply.cu:95-110) is left to XLA's async collectives: the exchange
and the owned-column part of the SpMV have no data dependence, so the
scheduler overlaps them within the fused program.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from ..matrix import CsrMatrix


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["csr", "diag", "halo_src", "send_prev", "send_next",
                 "recv_prev", "recv_next"],
    meta_fields=["n_global", "n_local", "n_local_cols", "n_halo", "n_ranks",
                 "axis_name", "neighbor_only"],
)
@dataclasses.dataclass(frozen=True)
class ShardMatrix:
    """One shard of a distributed CSR matrix (fields may be stacked with a
    leading mesh axis outside shard_map; inside, use .local())."""

    csr: CsrMatrix
    diag: jax.Array
    halo_src: jax.Array
    send_prev: jax.Array | None
    send_next: jax.Array | None
    recv_prev: jax.Array | None
    recv_next: jax.Array | None
    n_global: int
    n_local: int
    n_local_cols: int
    n_halo: int
    n_ranks: int
    axis_name: str = "p"
    neighbor_only: bool = False

    # -- operator interface (duck-typed CsrMatrix surface) ---------------
    @property
    def num_rows(self):
        return self.n_local

    @property
    def num_cols(self):
        return self.n_local_cols

    @property
    def block_dimx(self):
        return 1

    @property
    def block_dimy(self):
        return 1

    @property
    def is_block(self):
        return False

    @property
    def dtype(self):
        return self.csr.values.dtype

    def exchange_halo(self, x):
        """Fill the halo buffer from remote shards (exchange_halo analog).
        `x` is the shard-local owned column-side vector (n_local_cols,)."""
        if self.n_ranks == 1:
            return jnp.zeros((self.n_halo,), x.dtype)
        ax = self.axis_name
        if self.neighbor_only:
            xp = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])  # pad slot
            buf_next = xp[self.send_next]       # cols for rank+1
            buf_prev = xp[self.send_prev]       # cols for rank-1
            n = self.n_ranks
            fwd = [(i, i + 1) for i in range(n - 1)]
            bwd = [(i + 1, i) for i in range(n - 1)]
            from_prev = jax.lax.ppermute(buf_next, ax, fwd)
            from_next = jax.lax.ppermute(buf_prev, ax, bwd)
            halo = jnp.zeros((self.n_halo + 1,), x.dtype)
            halo = halo.at[self.recv_prev].set(from_prev)
            halo = halo.at[self.recv_next].set(from_next)
            return halo[: self.n_halo]
        x_all = jax.lax.all_gather(x, ax, tiled=True)   # padded global
        idx = jnp.clip(self.halo_src, 0, x_all.shape[0] - 1)
        return x_all[idx]

    def spmv(self, x):
        """Distributed y = A x: halo exchange + local SpMV over the
        concatenated [owned | halo] vector (multiply w/ halo analog,
        src/multiply.cu:95-119)."""
        halo = self.exchange_halo(x)
        xa = jnp.concatenate([x, halo])
        from ..ops.spmv import spmv_csr_segsum
        return spmv_csr_segsum(self.csr, xa)

    def diagonal(self):
        return self.diag

    def local(self):
        """Strip the leading mesh axis after shard_map slicing."""
        return jax.tree.map(lambda a: a[0], self)


def shard_matrix_from_partition(p, axis_name: str = "p") -> ShardMatrix:
    """Build the stacked ShardMatrix pytree from a DistPartition."""
    if p.n_ranks * p.n_local_cols < p.n_global_cols:
        raise ValueError(
            f"partition covers {p.n_ranks * p.n_local_cols} of "
            f"{p.n_global_cols} global columns")
    csr = CsrMatrix(
        row_offsets=p.row_offsets, col_indices=p.col_indices,
        values=p.values, row_ids=p.row_ids,
        num_rows=p.n_local, num_cols=p.n_local_cols + p.n_halo,
        initialized=True)
    return ShardMatrix(
        csr=csr, diag=p.diag, halo_src=p.halo_src,
        send_prev=p.send_prev, send_next=p.send_next,
        recv_prev=p.recv_prev, recv_next=p.recv_next,
        n_global=p.n_global, n_local=p.n_local,
        n_local_cols=p.n_local_cols, n_halo=p.n_halo,
        n_ranks=p.n_ranks, axis_name=axis_name,
        neighbor_only=p.neighbor_only)
