"""Shard-local distributed matrix operator.

The solve-phase object: a registered pytree that duck-types the SpMV
operator interface (ops.spmv dispatches to .spmv), performing the halo
exchange with XLA collectives. This is the TPU-native replacement for the
reference's DistributedManager gather kernels + MPI Isend/Irecv ring
(include/distributed/distributed_manager.h:75-170,
comms_mpi_hostbuffer_stream.cu:321-676):

- "ring" mode: gather boundary values into per-neighbor send buffers
  (B2L gather analog) and `lax.ppermute` them one hop along the mesh
  axis — two permutes (toward prev, toward next) ride ICI;
- "a2a" mode (general partitions): per-peer send buffers swapped with
  one `lax.all_to_all` — O(n_ranks * max_pair) traffic, the all-pairs
  generalization of the B2L maps, replacing the old O(n_global)
  full-vector all_gather;
- "gather" mode: `lax.all_gather(tiled)` + static gather by global id —
  the fallback when boundaries are so dense the all-to-all buffers
  would exceed the gathered vector itself.

Rectangular shards (the P/R transfer operators of a distributed AMG
hierarchy) partition rows by the row-side decomposition and columns by
the column-side one; `spmv` consumes the column-side local vector and
produces the row-side local vector, so restriction/prolongation are the
same halo-exchange + local SpMV as the operator itself
(classical_amg_level.cu restrict/prolongate analog).

Latency hiding is structural, matching the reference's
interior/boundary split (src/multiply.cu:95-110): local entries are
stored split into an *owned-column* part and a *halo-column* part, and
y = A_own x + A_halo h where only the second term depends on the
exchange — XLA's latency-hiding scheduler overlaps the collective with
the owned-part SpMV because there is no data dependence between them.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["rid_own", "ci_own", "va_own", "rid_halo", "ci_halo",
                 "va_halo", "diag", "halo_src", "send_prev", "send_next",
                 "recv_prev", "recv_next", "a2a_send", "a2a_recv"],
    meta_fields=["n_global", "n_local", "n_local_cols", "n_halo", "n_ranks",
                 "axis_name", "exchange_mode", "bdimx", "bdimy"],
)
@dataclasses.dataclass(frozen=True)
class ShardMatrix:
    """One shard of a distributed CSR matrix (fields may be stacked with a
    leading mesh axis outside shard_map; inside, use .local()).

    Entries live in two row-sorted COO sets: owned-column entries
    (ci_own indexes the local x) and halo-column entries (ci_halo
    indexes the exchanged halo buffer). Padding uses rid == n_local
    (dropped by the segment sums)."""

    rid_own: jax.Array          # (e_own,) int32, row id (pad n_local)
    ci_own: jax.Array           # (e_own,) int32, owned local col (pad 0)
    va_own: jax.Array           # (e_own,)
    rid_halo: jax.Array         # (e_halo,) int32 (pad n_local)
    ci_halo: jax.Array          # (e_halo,) int32, halo slot (pad 0)
    va_halo: jax.Array          # (e_halo,)
    diag: jax.Array
    halo_src: jax.Array
    send_prev: jax.Array | None
    send_next: jax.Array | None
    recv_prev: jax.Array | None
    recv_next: jax.Array | None
    a2a_send: jax.Array | None  # (n_ranks, max_pair) local col (pad n_lc)
    a2a_recv: jax.Array | None  # (n_ranks, max_pair) halo slot (pad n_halo)
    n_global: int
    n_local: int
    n_local_cols: int
    n_halo: int
    n_ranks: int
    axis_name: str = "p"
    exchange_mode: str = "gather"
    # original block dims: entries are stored scalar-expanded, but the
    # block shape drives block-diagonal smoother applications and norms
    bdimx: int = 1
    bdimy: int = 1

    # -- operator interface (duck-typed CsrMatrix surface) ---------------
    @property
    def num_rows(self):
        return self.n_local // self.bdimx

    @property
    def num_cols(self):
        return self.n_local_cols // self.bdimy

    @property
    def block_dimx(self):
        return self.bdimx

    @property
    def block_dimy(self):
        return self.bdimy

    @property
    def is_block(self):
        return self.bdimx * self.bdimy > 1

    @property
    def dtype(self):
        return self.va_own.dtype

    def exchange_halo(self, x):
        """Fill the halo buffer from remote shards (exchange_halo analog).
        `x` is the shard-local owned column-side vector (n_local_cols,).

        The resilience fault harness hooks the received buffer
        (`halo_corrupt` — the link-fault model, faultinject.py): a
        trace-time no-op unless armed inside a solve-loop iteration."""
        from ..resilience import faultinject as _fault
        from . import comms as _comms
        if self.n_ranks == 1:
            return jnp.zeros((self.n_halo,), x.dtype)
        ax = self.axis_name
        itemsize = jnp.dtype(x.dtype).itemsize
        site = f"halo/{self.n_local}"
        if self.exchange_mode == "ring":
            xp = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])  # pad slot
            buf_next = xp[self.send_next]       # cols for rank+1
            buf_prev = xp[self.send_prev]       # cols for rank-1
            # trace-time site report: per-hop window = the gathered
            # boundary buffers (exactly what each ppermute ships)
            _comms.record_exchange(
                site, "ring", int(self.send_next.shape[0]),
                int(self.send_prev.shape[0]), itemsize, self.n_ranks)
            fwd, bwd = _comms.edge_permutes(self.n_ranks)
            from_prev = jax.lax.ppermute(buf_next, ax, fwd)
            from_next = jax.lax.ppermute(buf_prev, ax, bwd)
            halo = jnp.zeros((self.n_halo + 1,), x.dtype)
            halo = halo.at[self.recv_prev].set(from_prev)
            halo = halo.at[self.recv_next].set(from_next)
            return _fault.corrupt_halo(halo[: self.n_halo])
        if self.exchange_mode == "a2a":
            xp = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])
            bufs = xp[self.a2a_send]            # (n_ranks, max_pair)
            # direction-free collective: every rank ships its whole
            # send matrix; folded into fwd (comms.record_exchange docs)
            _comms.record_exchange(
                site, "a2a", int(bufs.shape[0] * bufs.shape[1]), 0,
                itemsize, self.n_ranks)
            recv = jax.lax.all_to_all(bufs, ax, split_axis=0,
                                      concat_axis=0, tiled=True)
            halo = jnp.zeros((self.n_halo + 1,), x.dtype)
            halo = halo.at[self.a2a_recv].set(recv)
            return _fault.corrupt_halo(halo[: self.n_halo])
        # all_gather: EVERY rank broadcasts its tile to the other
        # n_ranks - 1 — fold the n_ranks sending tiles into elems so
        # the (n_ranks - 1) hop factor yields the mesh total, matching
        # the ring/a2a accounting convention
        _comms.record_exchange(
            site, "gather", int(self.n_local_cols) * self.n_ranks,
            0, itemsize, self.n_ranks)
        x_all = jax.lax.all_gather(x, ax, tiled=True)   # padded global
        idx = jnp.clip(self.halo_src, 0, x_all.shape[0] - 1)
        return _fault.corrupt_halo(x_all[idx])

    def spmv(self, x):
        """Distributed y = A x with the interior/boundary overlap split
        (multiply.cu:95-119): the owned-column product has no data
        dependence on the exchange, so XLA overlaps them."""
        halo = self.exchange_halo(x)
        y = jax.ops.segment_sum(
            self.va_own * x[self.ci_own], self.rid_own,
            num_segments=self.n_local, indices_are_sorted=True)
        if self.va_halo.shape[0]:
            hp = halo if self.n_halo else jnp.zeros((1,), x.dtype)
            y = y + jax.ops.segment_sum(
                self.va_halo * hp[self.ci_halo], self.rid_halo,
                num_segments=self.n_local, indices_are_sorted=True)
        return y

    def diagonal(self):
        return self.diag

    def local(self):
        """Strip the leading mesh axis after shard_map slicing."""
        return jax.tree.map(lambda a: a[0], self)


def shard_matrix_from_partition(p, axis_name: str = "p") -> ShardMatrix:
    """Build the stacked ShardMatrix pytree from a DistPartition."""
    if p.n_ranks * p.n_local_cols < p.n_global_cols:
        raise ValueError(
            f"partition covers {p.n_ranks * p.n_local_cols} of "
            f"{p.n_global_cols} global columns")
    return ShardMatrix(
        rid_own=p.rid_own, ci_own=p.ci_own, va_own=p.va_own,
        rid_halo=p.rid_halo, ci_halo=p.ci_halo, va_halo=p.va_halo,
        diag=p.diag, halo_src=p.halo_src,
        send_prev=p.send_prev, send_next=p.send_next,
        recv_prev=p.recv_prev, recv_next=p.recv_next,
        a2a_send=p.a2a_send, a2a_recv=p.a2a_recv,
        n_global=p.n_global, n_local=p.n_local,
        n_local_cols=p.n_local_cols, n_halo=p.n_halo,
        n_ranks=p.n_ranks, axis_name=axis_name,
        exchange_mode=p.exchange_mode, bdimx=p.block_dimx,
        bdimy=p.block_dimy)
