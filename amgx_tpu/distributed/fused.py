"""Halo-folded fused smoother path for sharded (distributed) DIA levels.

Everything PRs 4-5 fused — all smoother sweeps + the trailing cycle
residual in ONE Pallas kernel per level — was single-chip only: a
distributed level smooths through `ShardMatrix.spmv`, paying one full
halo exchange AND one HBM pass over A per sweep. This module brings the
fused kernels under `shard_map` (ROADMAP item 1; the AmgX distributed
SpMV latency-hiding pattern of src/multiply.cu:95-110 generalized to
the whole fused sweep chain; JAXMg, arXiv:2601.14466 shows the same
structure in JAX).

The key observation: a contiguous equal-block row partition of a DIA
(banded) operator preserves the band per shard — shard r's rows
[r*nl, r*nl + nl) only reference global elements in
[r*nl - m, r*nl + nl + M) (m/M = the band reach below/above the
diagonal). And the quota-padded operand slabs the single-chip fused
kernel already DMAs row windows from (`ops/pallas_spmv.smooth_quota_rows`)
reserve exactly (SMOOTH_MAX_APPS-1)*mr0 front rows of ZERO padding for
the temporal-blocking halo. The per-shard slabs built here FILL that
quota with the neighbor shards' rows instead — the "halo-folded" slab —
so every remote coefficient a temporally-blocked sweep chain can reach
is already inside the kernel's row-window DMA.

Per fused smoother call (k sweeps + optional residual = n_app
applications) each shard then runs:

1. ONE packed edge-window exchange: the x window (n_app*m / n_app*M
   elements) and b window ((n_app-1)*m / (n_app-1)*M) ride a single
   `lax.ppermute` per direction — versus one full halo exchange per
   sweep in the unfused composition, and hop-free (only +/-1 neighbors
   hold a banded shard's halo).
2. The UNMODIFIED single-chip fused kernel on the shard's local
   operands with zero pads. Every row further than n_app*m (n_app*M)
   elements from the shard's lower (upper) boundary is exact, and the
   call has NO data dependence on the collective — XLA's latency-hiding
   scheduler runs the exchange concurrently with the interior kernel
   (the interior/boundary overlap, now covering the whole sweep chain
   instead of one SpMV).
3. Exact boundary strips recomputed in XLA once the exchange lands:
   `ops.batched.affine_window_sweeps` (the kernel's temporal blocking
   in element units) over the received windows + the folded slab's halo
   rows, spliced over the kernel's boundary rows. Strip cost is
   O(n_app * band) elements per side — negligible against nl.

Off the Pallas runtime (f64 solves; the CPU bench mesh) the same
exchange feeds `affine_window_sweeps` over the WHOLE shard — still one
collective per fused call and dense shifted adds instead of per-sweep
gather/segment-sum SpMVs, so `dist_cycle_fusion` pays on every backend.
`dist_cycle_fusion=0` builds no payloads and restores the per-sweep
halo-exchange composition bit-for-bit.

Payloads attach wherever a level's global DIA operator is visible at
setup: every sharded DIA level of the controller-global path
(distributed/amg.py `shard_amg`) and the finest level of the per-shard
setup (distributed/setup.py — coarse sharded levels are COO-built with
no DIA view, they keep the unfused path)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import comms
from ..ops import batched as _bt
from ..ops import pallas_spmv as _ps


@jax.tree_util.register_pytree_node_class
class DistFusedSlabs:
    """Per-shard halo-folded fused-smoother payload of one distributed
    DIA level (leaves stacked (n_ranks, ...) outside shard_map; inside
    the shard_mapped solve the leading mesh axis is stripped with the
    rest of the solve-data pytree).

    Children: `vals_q` ((R,) k, Q, 128) quota-padded value slabs with
    the quota rows carrying the NEIGHBOR shards' rows (zero only where
    the global matrix ends); `dinv_q` ((R,) Q, 128) likewise, or None
    for smoothers without a diagonal scaling (CHEBYSHEV_POLY). Static
    aux: the DIA `offsets`, the per-shard row count `n_local`, and
    `n_ranks`."""

    def __init__(self, vals_q, dinv_q, offsets, n_local, n_ranks):
        self.vals_q = vals_q
        self.dinv_q = dinv_q
        self.offsets = offsets
        self.n_local = n_local
        self.n_ranks = n_ranks

    def tree_flatten(self):
        return ((self.vals_q, self.dinv_q),
                (self.offsets, self.n_local, self.n_ranks))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


def band_reach(offsets):
    """(m, M): band reach in elements below/above the diagonal."""
    return max(0, -min(offsets)), max(0, max(offsets))


def build_dist_fused(A, n_ranks: int, n_local: int, dinv=None):
    """Stacked halo-folded quota slabs from the GLOBAL DIA operator of
    a contiguous equal-block row partition (shard r owns rows
    [r*n_local, (r+1)*n_local); the partition_matrix / sharded-setup
    level-0 layout). Host numpy build, one device upload per (re)setup.
    Returns None when A has no eligible DIA layout or the shards are
    too narrow for even a single fused application's halo."""
    from ..ops import smooth as fsm
    if not fsm._slab_eligible(A):
        return None
    offsets = A.dia_offsets
    k = len(offsets)
    m, M = band_reach(offsets)
    # narrowest useful schedule: 1 sweep + residual (n_app = 2)
    if n_local < 2 * (m + M) or n_local < 1:
        return None
    qf, qc, qb = _ps.smooth_quota_rows(offsets, n_local)
    L = _ps.LANES
    span = (qf + qc + qb) * L
    gv = np.asarray(A.dia_vals).reshape(k, -1)
    idx = (np.arange(n_ranks)[:, None] * n_local - qf * L
           + np.arange(span)[None, :])
    valid = (idx >= 0) & (idx < gv.shape[1])
    idxc = np.clip(idx, 0, gv.shape[1] - 1)
    # (k, R, span) -> (R, k, rows, 128); elements past the matrix end
    # stay zero (dia_vals tile padding is already zero past num_rows)
    vq = np.where(valid[None], gv[:, idxc], 0).transpose(1, 0, 2)
    vals_q = jnp.asarray(
        np.ascontiguousarray(vq.reshape(n_ranks, k, qf + qc + qb, L)))
    dinv_q = None
    if dinv is not None:
        d = np.asarray(dinv).reshape(-1)
        gd = np.zeros(n_ranks * n_local, d.dtype)
        gd[: d.shape[0]] = d
        validd = (idx >= 0) & (idx < gd.shape[0])
        dq = np.where(validd, gd[np.clip(idx, 0, gd.shape[0] - 1)], 0)
        dinv_q = jnp.asarray(
            np.ascontiguousarray(dq.reshape(n_ranks, qf + qc + qb, L)))
    return DistFusedSlabs(vals_q, dinv_q, tuple(int(o) for o in offsets),
                          int(n_local), int(n_ranks))


def fusion_gates(cfg, scope: str, smoother) -> bool:
    """The cheap (no-array-touching) gates of `attach_shard_fused`:
    the `dist_cycle_fusion` knob, the fused runtime (non-TPU rigs
    build no payloads unless knob=2 opts into the XLA window route),
    and the smoother family. Callers with an EXPENSIVE operand to
    materialize (e.g. a device->host dinv pull) check this first so a
    declined attach costs nothing."""
    from ..ops import smooth as fsm
    knob = int(cfg.get("dist_cycle_fusion", scope))
    if knob == 0:
        return False
    # knob=2: attach even off the fused Pallas runtime — the solve then
    # takes the pure-XLA window-sweep route (one collective per fused
    # call instead of one per sweep; the CPU bench-mesh opt-in)
    if knob < 2 and not fsm.fused_runtime_on():
        return False
    if smoother is None or not getattr(smoother, "fused_smoother", False):
        return False
    if getattr(smoother, "fused_tail_spec", None) is None:
        return False          # not a damped-relaxation-family smoother
    return True


def attach_shard_fused(smd: dict, A, smoother, n_ranks: int,
                       n_local: int, cfg, scope: str,
                       dinv_global=None, dinv_key=None) -> bool:
    """Attach the halo-folded payload to a sharded level's smoother
    solve-data dict (key "dist_fused"), or do nothing. Gated on
    `fusion_gates` (knob / runtime / smoother family — non-TPU rigs
    build no payloads and change nothing, same contract as
    fused_smoother / cycle_fusion). Memoized on the identity of the
    value-carrying arrays, so a value resetup that swaps in new
    coefficients rebuilds the halo-extended slabs while repeated
    setups on the same values reuse them. A caller whose dinv is
    EXPENSIVE to materialize (the setup.py device->host slice) passes
    a zero-arg callable as `dinv_global` plus the stable source array
    as `dinv_key`: the callable runs only on a memo MISS, so a memo
    hit costs no transfer at all."""
    if not fusion_gates(cfg, scope, smoother):
        return False
    if dinv_global is None:
        dinv_global = getattr(smoother, "_dinv", None)
    if dinv_key is None:
        dinv_key = dinv_global
    memo = getattr(smoother, "_dist_fused_memo", None)
    # the memo RETAINS the source arrays and compares by `is` (see
    # ops/smooth.solver_fused_slabs for why id() alone is unsafe)
    if memo is not None and memo[0] is A.dia_vals \
            and memo[1] is dinv_key \
            and memo[2] == (n_ranks, n_local):
        fd = memo[3]
    else:
        if callable(dinv_global):
            dinv_global = dinv_global()
        if dinv_global is not None \
                and np.asarray(dinv_global).ndim != 1:
            return False      # block diagonal: not a scalar DIA level
        fd = build_dist_fused(A, n_ranks, n_local, dinv=dinv_global)
        smoother._dist_fused_memo = (A.dia_vals, dinv_key,
                                     (n_ranks, n_local), fd)
    if fd is None:
        return False
    smd["dist_fused"] = fd
    return True


# ---------------------------------------------------------------------------
# solve-phase entry (runs inside the shard_mapped trace)
# ---------------------------------------------------------------------------


def _exchange_windows(x, b, fx, bx, fb, bb, axis, n_ranks):
    """One packed ppermute per direction: my tail (x[-fx:], b[-fb:]) to
    the next rank (its front halo), my head (x[:bx], b[:bb]) to the
    previous rank (its back halo). Edge ranks receive zeros — the DIA
    zero-padding semantics at the global matrix boundary. The received
    buffers pass through the resilience link-fault hook, matching
    ShardMatrix.exchange_halo."""
    from ..resilience import faultinject as _fault
    nl = x.shape[0]
    fwd, bwd = comms.edge_permutes(n_ranks)
    # trace-time site report: the packed (x window + b window) buffer
    # each direction's single ppermute ships per fused call — the
    # exact bytes the halo-folded path pays instead of one full halo
    # per sweep. Both-windows-empty emits NO collective below, so it
    # reports no site either (a counted site must mean real traffic)
    if fx + fb > 0 or bx + bb > 0:
        comms.record_exchange(
            f"edge/{nl}", "edge_fused", fx + fb, bx + bb,
            jnp.dtype(x.dtype).itemsize, n_ranks)
    hx_f = hb_f = hx_b = hb_b = None
    if fx + fb > 0:
        send_f = jnp.concatenate([x[nl - fx:], b[nl - fb:]]) \
            if fb else x[nl - fx:]
        got_f = _fault.corrupt_halo(jax.lax.ppermute(send_f, axis, fwd))
        hx_f, hb_f = got_f[:fx], got_f[fx:]
    if bx + bb > 0:
        send_b = jnp.concatenate([x[:bx], b[:bb]]) if bb else x[:bx]
        got_b = _fault.corrupt_halo(jax.lax.ppermute(send_b, axis, bwd))
        hx_b, hb_b = got_b[:bx], got_b[bx:]
    return hx_f, hb_f, hx_b, hb_b


def dist_fused_smooth(fd: DistFusedSlabs, b, x, taus, dinv,
                      with_residual: bool):
    """x' (and r when `with_residual`) after len(taus) damped sweeps of
    this shard's rows, or None when the fused distributed path does not
    apply (caller falls back to the per-sweep halo-exchange compose).

    Routes: f32 with a feasible kernel plan -> the single-chip fused
    Pallas kernel on zero-padded local operands (overlapped with the
    edge-window exchange) + exact XLA boundary strips; otherwise (f64,
    no plan) -> `affine_window_sweeps` over the whole halo-extended
    shard — one exchange either way."""
    axis = comms.active_axis()
    if axis is None or fd is None:
        return None
    if (dinv is None) != (fd.dinv_q is None):
        return None
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    offsets = fd.offsets
    k = len(offsets)
    nl = fd.n_local
    if x.shape[0] != nl or b.shape[0] != nl:
        return None
    m, M = band_reach(offsets)
    n_app = n_steps + (1 if with_residual else 0)
    if n_app > _ps.SMOOTH_MAX_APPS or n_app * (m + M) > nl:
        return None           # shard too narrow for the halo cone
    if fd.vals_q.dtype != x.dtype:
        return None
    from ..ops import smooth as fsm
    # bf16 shards ride the same kernel (per-block upcast, f32
    # accumulation) AND halve the packed edge-window exchange bytes —
    # the comms site below models the narrower itemsize automatically
    use_kernel = (
        jnp.dtype(x.dtype).name in _ps.SMOOTH_DTYPES
        and fsm.fused_runtime_on()
        and _ps.dia_smooth_plan(
            offsets, k, nl, n_steps, with_residual,
            itemsize=jnp.dtype(x.dtype).itemsize) is not None)

    # 1. edge-window exchange (the only collective of the fused call)
    fx, bx = n_app * m, n_app * M
    fb, bb = (n_app - 1) * m, (n_app - 1) * M
    hx_f, hb_f, hx_b, hb_b = _exchange_windows(
        x, b, fx, bx, fb, bb, axis, fd.n_ranks)

    qf, _, _ = _ps.smooth_quota_rows(offsets, nl)
    base = qf * _ps.LANES     # flat slab index of local element 0
    vflat = fd.vals_q.reshape(k, -1)
    dflat = fd.dinv_q.reshape(-1) if fd.dinv_q is not None else None

    def win(flat, lo, ln):
        return jax.lax.slice_in_dim(flat, base + lo, base + lo + ln,
                                    1, flat.ndim - 1)

    if not use_kernel:
        # XLA route: the whole shard is one window sweep over the
        # halo-extended arrays (exact; same math as the kernel)
        Wv = nl + (n_app - 1) * (m + M)
        vals_w = win(vflat, -(n_app - 1) * m, Wv)
        dinv_w = win(dflat, -(n_app - 1) * m, Wv) \
            if dflat is not None else None
        b_w = _cat(hb_f, b, hb_b)
        x_w = _cat(hx_f, x, hx_b)
        return _bt.affine_window_sweeps(offsets, vals_w, b_w, x_w, taus,
                                        dinv_w, nl, with_residual)

    # 2. Pallas route: the fused kernel on zero-padded local operands —
    # no data dependence on the exchange, so the collective overlaps
    out = _ps._dia_smooth_call(fd.vals_q, fd.dinv_q, taus, b, x,
                               offsets, nl, with_residual,
                               interpret=_ps._FORCE_INTERPRET)
    xk, rk = out if with_residual else (out, None)

    # 3. exact boundary strips from the received windows + the folded
    # slab halo rows (rows within n_app*m / n_app*M elements of a
    # shard boundary are the only ones whose cone left the shard)
    def splice(y, r, strip, at):
        ys = jax.lax.dynamic_update_slice(y, strip[0] if r is not None
                                          else strip, (at,))
        if r is None:
            return ys, None
        return ys, jax.lax.dynamic_update_slice(r, strip[1], (at,))

    if fx:                    # front strip: target [0, n_app*m)
        W = fx
        Wv = W + (n_app - 1) * (m + M)
        strip = _bt.affine_window_sweeps(
            offsets, win(vflat, -(n_app - 1) * m, Wv),
            _cat(hb_f, b[: W + (n_app - 1) * M], None),
            _cat(hx_f, x[: W + n_app * M], None),
            taus,
            win(dflat, -(n_app - 1) * m, Wv) if dflat is not None
            else None,
            W, with_residual)
        xk, rk = splice(xk, rk, strip, 0)
    if bx:                    # back strip: target [nl - n_app*M, nl)
        W = bx
        t0 = nl - W
        Wv = W + (n_app - 1) * (m + M)
        strip = _bt.affine_window_sweeps(
            offsets, win(vflat, t0 - (n_app - 1) * m, Wv),
            _cat(None, b[t0 - (n_app - 1) * m:], hb_b),
            _cat(None, x[t0 - n_app * m:], hx_b),
            taus,
            win(dflat, t0 - (n_app - 1) * m, Wv) if dflat is not None
            else None,
            W, with_residual)
        xk, rk = splice(xk, rk, strip, t0)
    return (xk, rk) if with_residual else xk


def _cat(front, mid, back):
    parts = [p for p in (front, mid, back) if p is not None
             and p.shape[0]]
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]
