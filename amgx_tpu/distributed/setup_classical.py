"""Sharded (per-rank) classical AMG setup: PMIS + D1 + distributed RAP.

The classical analog of distributed/setup.py's aggregation build — the
reference's per-rank classical level construction
(src/classical/classical_amg_level.cu:254-341: strength + CF-splitting
on the rank-local matrix, distributed Galerkin RAP over exchanged halo
rows of P, one-ring renumbering via
src/distributed/distributed_manager.cu `createOneRingHaloRows`). TPU
redesign on the same primitives the aggregation setup uses:

- strength (AHAT) and the D1 interpolation formula are row-local under
  the row-wise partition: every owned row's entries are shard-resident,
  so both compute with zero communication beyond per-vertex halo state
  (diag sign, row threshold, CF state, coarse ids);
- reverse-edge strength (the PMIS graph is symmetrized) is computed
  locally from exchanged per-vertex thresholds under the module's
  value-symmetry assumption (|a_ji| = |a_ij|, setup.py module docs);
- PMIS is the same synchronous fixed point as the single-device
  selector (amg/classical/selectors.py pmis_split) with semantic-id
  hashes, so the CF split is bit-identical to the single-device path;
- the Galerkin triple product replaces the reference's halo-row
  exchange with triple routing: every fine entry a_kl expands against
  the P rows of k and l into (CI, CJ, P[k,CI] * a_kl * P[l,CJ])
  triples routed to CI's owner. The remote P row of a halo column l
  arrives by exchanging the per-slot (cid, weight) vectors — the
  one-ring halo-row exchange, vectorized per slot;
- level assembly (halo lists, a2a maps, transfer shards) mirrors the
  aggregation phase C, generalized to weighted multi-entry P rows.

Scope: selector PMIS, interpolator D1 (with interp_truncation_factor /
interp_max_elements truncation — src/truncate.cu semantics on the slot
vectors), strength AHAT, scalar matrices; aggressive levels and the
other interpolators fall back to the global-setup path
(setup.sharded_eligible).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .dist_matrix import ShardMatrix
from .setup import (_SENT, _Edges, _a2a_maps, _owner_of_sem,
                    _remote_uniq_flags, _route, _seg_max,
                    _sorted_by_rid, _take, _unique_remote)

FINE, COARSE, UNDECIDED = 0, 1, -1


def _hash01_sem(sem_ids):
    """selectors._hash01 on semantic global ids (bit-identical PMIS
    weights to the single-device fixed point)."""
    i = sem_ids.astype(jnp.uint32)
    h = i * jnp.uint32(2654435761)
    h = (h ^ (h >> 16)) * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.float64) / float(1 << 20)


def _strength_masks(E: _Edges, M: ShardMatrix, theta: float,
                    max_row_sum: float):
    """(strong_out, strong_in) per local edge. strong_out is the AHAT
    mask of the owned row; strong_in is the mask of the REVERSE edge
    (col -> row), computed locally from exchanged per-vertex thresholds
    under the value-symmetry assumption (a_ji == a_ij)."""
    n = E.n_local
    diag = M.diag
    rows_c = jnp.minimum(E.rows, n)
    offd = E.valid & (E.row_sem != E.col_sem)
    sgn = jnp.where(diag < 0, -1.0, 1.0)
    sl = jnp.concatenate([sgn, jnp.ones((1,), sgn.dtype)])
    c_out = jnp.where(offd, -E.vals * sl[rows_c], 0.0)
    rowmax = jnp.maximum(_seg_max(c_out, rows_c, n + 1, 0.0)[:n], 0.0)
    thr = theta * rowmax
    weak = jnp.zeros((n,), bool)
    if max_row_sum < 1.0:
        rowsum = jax.ops.segment_sum(
            jnp.where(E.valid, E.vals, 0.0), rows_c,
            num_segments=n + 1)[:n]
        weak = jnp.abs(rowsum) > max_row_sum * jnp.abs(diag)
    tl = jnp.concatenate([thr, jnp.zeros((1,), thr.dtype)])
    wl = jnp.concatenate([weak, jnp.zeros((1,), bool)])
    strong_out = offd & (c_out > 0) & (c_out >= tl[rows_c]) \
        & ~wl[rows_c]
    c_col = E.col_state(sgn, E.exchange(sgn), 1.0)
    thr_col = E.col_state(thr, E.exchange(thr), 0.0)
    weak_col = E.col_state(weak, E.exchange(weak), True)
    c_in = jnp.where(offd, -E.vals * c_col, 0.0)
    strong_in = offd & (c_in > 0) & (c_in >= thr_col) & ~weak_col
    return strong_out, strong_in


def _pmis_body(E: _Edges, active, strong_out, strong_in, me, offsets,
               axis: str, max_iters: int):
    """Sharded synchronous PMIS fixed point — bit-identical rounds to
    pmis_split (same weights, same two-phase round structure)."""
    n = E.n_local
    adj = strong_out | strong_in
    rows_c = jnp.minimum(E.rows, n)

    def seg_any(mask):
        return jax.ops.segment_max(
            jnp.concatenate([mask, jnp.zeros((1,), bool)]),
            jnp.concatenate([rows_c, jnp.full((1,), n, jnp.int32)]),
            num_segments=n + 1)[:n]

    outdeg = jax.ops.segment_sum(
        strong_out.astype(jnp.float64), rows_c, num_segments=n + 1)[:n]
    indeg = jax.ops.segment_sum(
        strong_in.astype(jnp.float64), rows_c, num_segments=n + 1)[:n]
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    w = 0.5 * (outdeg + indeg) + _hash01_sem(idx_sem)
    w = jnp.where(active, w, -1.0)
    has_nbr = seg_any(adj)
    state0 = jnp.where(active & ~has_nbr, COARSE,
                       jnp.where(active, UNDECIDED, FINE)
                       ).astype(jnp.int32)
    halo_w = E.exchange(w)

    def cond(carry):
        it, state = carry
        any_und = jax.lax.psum(
            jnp.sum((state == UNDECIDED).astype(jnp.int32)), axis) > 0
        return (it < max_iters) & any_und

    def body(carry):
        it, state = carry
        und = state == UNDECIDED
        halo_st = E.exchange(state)
        und_c = E.col_state(und, halo_st == UNDECIDED, False)
        w_c = E.col_state(w, halo_w, -1.0)
        nbr_max = _seg_max(
            jnp.where(adj & und_c, w_c, -jnp.inf), rows_c, n + 1,
            -jnp.inf)[:n]
        state = jnp.where(und & (w > nbr_max), COARSE, state)
        # phase 2 sees this round's new COARSE points (incl. remote)
        halo_st2 = E.exchange(state)
        c_col = E.col_state(state == COARSE, halo_st2 == COARSE, False)
        c_nbr = seg_any(adj & c_col)
        state = jnp.where((state == UNDECIDED) & c_nbr, FINE, state)
        return it + 1, state

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state0))
    state = jnp.where(state == UNDECIDED, FINE, state)
    return jnp.where(active, state, FINE).astype(jnp.int32)


def _cids_of_cf(cf, active, offsets_c, me):
    """Contiguous semantic coarse ids of the owned C points."""
    is_c = active & (cf == COARSE)
    rank = jnp.cumsum(is_c.astype(jnp.int32)) - 1
    return jnp.where(is_c, offsets_c[me] + rank, -1).astype(jnp.int32)


def _truncate_slots(p_cid, p_w, factor: float, max_elements: int):
    """Interpolation truncation on the (n, PK) D1 slot vectors
    (src/truncate.cu semantics, bit-matching interpolators._truncate):
    entries rank in ascending-cid order — the assembled P's CSR entry
    order — with a stable descending-|w| pass, so equal-weight ties
    resolve exactly as the single-device path resolves them; dropped
    slots become (-1, 0) and kept weights rescale to preserve the row
    sum. Slot-local: no communication."""
    if factor > 1.0 and max_elements <= 0:
        return p_cid, p_w
    n, PK = p_cid.shape
    valid = p_cid >= 0
    absw = jnp.where(valid, jnp.abs(p_w), -1.0)
    keep = valid
    if factor <= 1.0:
        rmax = jnp.maximum(jnp.max(absw, axis=1, keepdims=True), 0.0)
        keep = keep & (jnp.abs(p_w) >= factor * rmax)
    if max_elements > 0 and PK > max_elements:
        big = jnp.int32(2**31 - 1)
        ord1 = jnp.argsort(jnp.where(valid, p_cid, big), axis=1,
                           stable=True)
        a_s = jnp.take_along_axis(absw, ord1, axis=1)
        ord2 = jnp.argsort(-a_s, axis=1, stable=True)
        comp = jnp.take_along_axis(ord1, ord2, axis=1)
        ranks = jnp.zeros_like(p_cid).at[
            jnp.arange(n)[:, None], comp].set(
            jnp.broadcast_to(jnp.arange(PK, dtype=jnp.int32)[None],
                             (n, PK)))
        keep = keep & (ranks < max_elements)
    rowsum = jnp.sum(jnp.where(valid, p_w, 0.0), axis=1)
    keptsum = jnp.sum(jnp.where(keep, p_w, 0.0), axis=1)
    scale = jnp.where(keptsum == 0, 1.0,
                      rowsum / jnp.where(keptsum == 0, 1.0, keptsum))
    return (jnp.where(keep, p_cid, -1),
            jnp.where(keep, p_w * scale[:, None], 0.0))


def _d1_rows(E: _Edges, M: ShardMatrix, cf, cid_sem, strong_out,
             PK: int, trunc_factor: float = 1.1,
             max_elements: int = -1):
    """Per-vertex D1 interpolation rows as (n, PK) padded slot vectors
    of (semantic cid, weight) — the Distance1Interpolator formula
    (amg/classical/interpolators.py:336), row-local. C rows inject.
    Truncation applies per slot vector (see _truncate_slots)."""
    n = E.n_local
    rows_c = jnp.minimum(E.rows, n)
    cf_col = E.col_state(cf, E.exchange(cf), jnp.int32(FINE))
    cid_col = E.col_state(cid_sem, E.exchange(cid_sem), jnp.int32(-1))
    offd = E.valid & (E.row_sem != E.col_sem)
    neg = E.vals < 0
    in_Ci = strong_out & (cid_col >= 0) & neg & offd
    sum_neg = jax.ops.segment_sum(
        jnp.where(offd & neg, E.vals, 0.0), rows_c,
        num_segments=n + 1)[:n]
    sum_Ci = jax.ops.segment_sum(
        jnp.where(in_Ci, E.vals, 0.0), rows_c, num_segments=n + 1)[:n]
    pos_lump = jax.ops.segment_sum(
        jnp.where(offd & ~neg, E.vals, 0.0), rows_c,
        num_segments=n + 1)[:n]
    dmod = M.diag + pos_lump
    alpha = jnp.where(sum_Ci == 0, 0.0,
                      sum_neg / jnp.where(sum_Ci == 0, 1.0, sum_Ci))
    al = jnp.concatenate([alpha, jnp.zeros((1,), alpha.dtype)])
    dl = jnp.concatenate([jnp.where(dmod == 0, 1.0, dmod),
                          jnp.ones((1,), dmod.dtype)])
    w_e = -al[rows_c] * E.vals / dl[rows_c]
    fl = jnp.concatenate([cf == FINE, jnp.zeros((1,), bool)])
    entry = in_Ci & fl[rows_c]
    # within-row rank of each entry: sort entries by row (stable), rank
    # = position - first position of that row
    order = jnp.argsort(
        jnp.where(entry, rows_c, n).astype(jnp.int32), stable=True)
    r_s = rows_c[order]
    e_s = entry[order]
    pos = jnp.arange(r_s.shape[0], dtype=jnp.int32)
    first_of = jax.ops.segment_min(
        jnp.where(e_s, pos, r_s.shape[0]), r_s, num_segments=n + 1)
    rank = pos - first_of[jnp.minimum(r_s, n)]
    slot_ok = e_s & (rank < PK)
    tgt_row = jnp.where(slot_ok, r_s, n)
    tgt_slot = jnp.clip(jnp.where(slot_ok, rank, 0), 0, PK - 1)
    p_cid = jnp.full((n + 1, PK), -1, jnp.int32).at[
        tgt_row, tgt_slot].set(
        jnp.where(slot_ok, cid_col[order], -1), mode="drop")
    p_w = jnp.zeros((n + 1, PK), E.vals.dtype).at[
        tgt_row, tgt_slot].set(
        jnp.where(slot_ok, w_e[order], 0.0), mode="drop")
    is_c = cf == COARSE
    p_cid = p_cid.at[:n, 0].set(jnp.where(is_c, cid_sem, p_cid[:n, 0]))
    p_w = p_w.at[:n, 0].set(jnp.where(is_c, 1.0, p_w[:n, 0]))
    return _truncate_slots(p_cid[:n], p_w[:n], trunc_factor,
                           max_elements)


def classical_phase_a(M: ShardMatrix, offsets, axis: str, theta: float,
                      max_row_sum: float, max_iters: int):
    """CF split + counts [nc_local, PK_local] (PK = max D1 entries per
    row; >= 1 covers injection rows)."""
    me = jax.lax.axis_index(axis)
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    strong_out, strong_in = _strength_masks(E, M, theta, max_row_sum)
    cf = _pmis_body(E, active, strong_out, strong_in, me, offsets,
                    axis, max_iters)
    nc_local = jnp.sum((active & (cf == COARSE)).astype(jnp.int32))
    cf_col = E.col_state(cf, E.exchange(cf), jnp.int32(FINE))
    offd = E.valid & (E.row_sem != E.col_sem)
    cnt = jax.ops.segment_sum(
        (strong_out & (cf_col == COARSE) & (E.vals < 0) & offd
         ).astype(jnp.int32),
        jnp.minimum(E.rows, n), num_segments=n + 1)[:n]
    pk = jnp.maximum(jnp.max(jnp.where(active, cnt, 0)), 1)
    return cf, jnp.concatenate([nc_local[None], pk[None]])


def classical_phase_b1(M: ShardMatrix, offsets, cf, offsets_c,
                       axis: str, theta: float, max_row_sum: float,
                       PK: int, trunc_factor: float = 1.1,
                       max_elements: int = -1):
    """Routing budgets, packed (2R,): per-dest triple counts followed
    by per-dest R-member record counts."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    strong_out, _ = _strength_masks(E, M, theta, max_row_sum)
    cid_sem = _cids_of_cf(cf, active, offsets_c, me)
    p_cid, _p_w = _d1_rows(E, M, cf, cid_sem, strong_out, PK,
                           trunc_factor, max_elements)
    pv = p_cid >= 0
    plen = jnp.sum(pv, axis=1).astype(jnp.int32)
    own_p = _owner_of_sem(p_cid.reshape(-1), offsets_c, R,
                          pv.reshape(-1)).reshape(n, PK)
    plen_col = E.col_state(plen, E.exchange(plen), jnp.int32(0))
    rows_c = jnp.minimum(E.rows, n)
    safe_r = jnp.clip(rows_c, 0, n - 1)
    cnt_t = jnp.zeros((R,), jnp.int32)
    for a in range(PK):
        d_a = jnp.where(E.valid & (rows_c < n), own_p[safe_r, a], R)
        cnt_t = cnt_t.at[jnp.clip(d_a, 0, R - 1)].add(
            jnp.where(d_a < R, plen_col, 0))
    dest_m = jnp.where(own_p == me, R, own_p).reshape(-1)
    cnt_m = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(dest_m, 0, R - 1)].add(
        (dest_m < R).astype(jnp.int32))
    return jnp.concatenate([cnt_t, cnt_m])


def classical_phase_b2(M: ShardMatrix, offsets, cf, offsets_c,
                       axis: str, theta: float, max_row_sum: float,
                       PK: int, NCL_c: int, maxt: int, maxm: int,
                       trunc_factor: float = 1.1,
                       max_elements: int = -1):
    """Expand + route + dedup the weighted Galerkin triples, route the
    R-operator member records, count phase-C buffer sizes."""
    from ..matrix import lexsort_rc
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    strong_out, _ = _strength_masks(E, M, theta, max_row_sum)
    cid_sem = _cids_of_cf(cf, active, offsets_c, me)
    p_cid, p_w = _d1_rows(E, M, cf, cid_sem, strong_out, PK,
                          trunc_factor, max_elements)
    pv = p_cid >= 0
    rank_p = jnp.clip(_owner_of_sem(p_cid.reshape(-1), offsets_c, R,
                                    pv.reshape(-1)), 0, R - 1
                      ).reshape(n, PK)
    p_phys = jnp.where(
        pv, rank_p * NCL_c + (p_cid - offsets_c[rank_p]),
        -1).astype(jnp.int32)
    # one-ring halo P rows: exchange each (cid, weight) slot vector
    halo_cid = [E.exchange(p_cid[:, a]) for a in range(PK)]
    halo_w = [E.exchange(p_w[:, a]) for a in range(PK)]
    rows_c = jnp.minimum(E.rows, n)
    Etot = E.ci.shape[0]
    pcid_l = jnp.concatenate([p_cid, jnp.full((1, PK), -1, jnp.int32)])
    pw_l = jnp.concatenate([p_w, jnp.zeros((1, PK), p_w.dtype)])
    CI_a = pcid_l[rows_c]                               # (E, PK)
    WI_a = pw_l[rows_c]
    CJ_b = jnp.stack(
        [E.col_state(p_cid[:, a], halo_cid[a], jnp.int32(-1))
         for a in range(PK)], axis=1)                   # (E, PK)
    WJ_b = jnp.stack(
        [E.col_state(p_w[:, a], halo_w[a], 0.0)
         for a in range(PK)], axis=1)
    own_CI = _owner_of_sem(CI_a.reshape(-1), offsets_c, R,
                           (CI_a >= 0).reshape(-1)).reshape(Etot, PK)
    shape3 = (Etot, PK, PK)
    tri_ci = jnp.broadcast_to(CI_a[:, :, None], shape3).reshape(-1)
    tri_cj = jnp.broadcast_to(CJ_b[:, None, :], shape3).reshape(-1)
    tri_v = (WI_a[:, :, None] * E.vals[:, None, None]
             * WJ_b[:, None, :]).reshape(-1)
    tri_ok = ((CI_a >= 0)[:, :, None] & (CJ_b >= 0)[:, None, :]
              & E.valid[:, None, None]).reshape(-1)
    dest_t = jnp.where(
        tri_ok,
        jnp.broadcast_to(own_CI[:, :, None], shape3).reshape(-1), R)
    rank_cj = jnp.clip(
        _owner_of_sem(tri_cj, offsets_c, R, tri_ok), 0, R - 1)
    cj_phys = jnp.where(
        tri_ok, rank_cj * NCL_c + (tri_cj - offsets_c[rank_cj]),
        _SENT).astype(jnp.int32)
    ci_flat = jnp.where(tri_ok, tri_ci, _SENT)
    v_flat = jnp.where(tri_ok, tri_v, 0.0)
    rCI, rCJ, rv = _route(
        (ci_flat, cj_phys, v_flat),
        jnp.where(dest_t == me, R, dest_t), me, axis, R, maxt,
        (_SENT, _SENT, jnp.zeros((), v_flat.dtype)))
    keep = tri_ok & (dest_t == me)
    aCI = jnp.concatenate([jnp.where(keep, ci_flat, _SENT), rCI])
    aCJ = jnp.concatenate([jnp.where(keep, cj_phys, _SENT), rCJ])
    av = jnp.concatenate([jnp.where(keep, v_flat, 0.0), rv])
    slot = jnp.where(aCI != _SENT, aCI - offsets_c[me],
                     NCL_c).astype(jnp.int32)
    cj = jnp.where(aCJ != _SENT, aCJ, _SENT).astype(jnp.int32)
    order = lexsort_rc(slot, cj)
    slot_s, cj_s, v_s = slot[order], cj[order], av[order]
    valid_s = slot_s < NCL_c
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (slot_s[1:] != slot_s[:-1]) | (cj_s[1:] != cj_s[:-1])]) & valid_s
    seg = jnp.cumsum(first) - 1
    T = slot_s.shape[0]
    vsum = jax.ops.segment_sum(jnp.where(valid_s, v_s, 0.0), seg,
                               num_segments=T, indices_are_sorted=True)
    v_out = jnp.where(first, vsum[jnp.clip(seg, 0, T - 1)], 0.0)
    n_unique = jnp.sum(first.astype(jnp.int32))
    # member records for R: (CI sem, fine gid, weight) per P entry
    gid_phys = me * n + jnp.arange(n, dtype=jnp.int32)
    gid_b = jnp.broadcast_to(gid_phys[:, None], (n, PK)).reshape(-1)
    own_p = _owner_of_sem(p_cid.reshape(-1), offsets_c, R,
                          pv.reshape(-1))
    mcid, mgid, mw = _route(
        (p_cid.reshape(-1), gid_b, p_w.reshape(-1)),
        jnp.where(own_p == me, R, own_p), me, axis, R, maxm,
        (_SENT, _SENT, jnp.zeros((), p_w.dtype)))

    def cnt_uniq(vals_phys, mask, NCL):
        _, uniq = _remote_uniq_flags(vals_phys, mask, me, NCL)
        return jnp.sum(uniq.astype(jnp.int32))

    owner_cj = jnp.clip(cj_s // NCL_c, 0, R)
    counts = jnp.concatenate([
        n_unique[None],
        jnp.sum((first & (owner_cj == me)).astype(jnp.int32))[None],
        jnp.sum((first & (owner_cj != me)).astype(jnp.int32))[None],
        cnt_uniq(cj_s, first, NCL_c)[None],
        cnt_uniq(p_phys.reshape(-1),
                 pv.reshape(-1) & jnp.repeat(active, PK), NCL_c)[None],
        cnt_uniq(mgid, mcid != _SENT, n)[None]])
    return slot_s, cj_s, v_out, p_phys, p_w, mcid, mgid, mw, counts


def classical_phase_c(M: ShardMatrix, offsets, triples, p_phys, p_w,
                      mcid, mgid, mw, offsets_c, axis: str, NCL_c: int,
                      PK: int, E_own: int, E_halo: int, H_c: int,
                      mp_c: int, H_p: int, mp_p: int, H_r: int,
                      mp_r: int):
    """Assemble the coarse ShardMatrix + weighted P/R transfer shards
    (the multi-entry generalization of setup._phase_c_body)."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    slot_s, cj_s, v_s = triples
    Etot = slot_s.shape[0]
    nc_local = offsets_c[me + 1] - offsets_c[me]
    valid_s = slot_s < NCL_c
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (slot_s[1:] != slot_s[:-1]) | (cj_s[1:] != cj_s[:-1])]) & valid_s
    owner_cj = jnp.clip(cj_s // NCL_c, 0, R)
    oidx, osel, _ = _take(first & (owner_cj == me), E_own, Etot - 1)
    rid_own = jnp.where(osel, slot_s[oidx], NCL_c).astype(jnp.int32)
    ci_own = jnp.where(osel, cj_s[oidx] - me * NCL_c, 0).astype(jnp.int32)
    va_own = jnp.where(osel, v_s[oidx], 0.0)
    hlist, hcnt = _unique_remote(cj_s, first, me, NCL_c, H_c)
    hidx, hsel, _ = _take(first & (owner_cj != me), E_halo, Etot - 1)
    rid_halo = jnp.where(hsel, slot_s[hidx], NCL_c).astype(jnp.int32)
    ci_halo = jnp.where(
        hsel, jnp.searchsorted(hlist, cj_s[hidx]), 0).astype(jnp.int32)
    va_halo = jnp.where(hsel, v_s[hidx], 0.0)
    send_c, recv_c = _a2a_maps(hlist, hcnt, me, NCL_c, NCL_c, axis, R,
                               mp_c)
    isd = first & (cj_s == me * NCL_c + slot_s)
    diag = jnp.zeros((NCL_c,), v_s.dtype).at[
        jnp.where(isd, slot_s, NCL_c)].add(
        jnp.where(isd, v_s, 0.0), mode="drop")
    diag = jnp.where(jnp.arange(NCL_c) < nc_local, diag, 1.0)
    A_c = dict(rid_own=rid_own, ci_own=ci_own, va_own=va_own,
               rid_halo=rid_halo, ci_halo=ci_halo, va_halo=va_halo,
               diag=diag, halo_src=hlist, a2a_send=send_c,
               a2a_recv=recv_c)
    dt = v_s.dtype
    # ---- P shard: flatten the (n, PK) slot vectors -------------------
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    pv = (p_phys >= 0) & active[:, None]
    owner_p = jnp.where(pv, jnp.clip(p_phys // NCL_c, 0, R - 1), R)
    rid_flat = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], (n, PK)).reshape(-1)
    pp_flat = p_phys.reshape(-1)
    pw_flat = p_w.reshape(-1)
    ow_flat = owner_p.reshape(-1)
    plist, pcnt = _unique_remote(pp_flat, pv.reshape(-1), me, NCL_c,
                                 H_p)
    own_m = pv.reshape(-1) & (ow_flat == me)
    halo_m = pv.reshape(-1) & (ow_flat != me) & (ow_flat < R)
    p_rid_o, p_ci_o, p_va_o = _sorted_by_rid(
        jnp.where(own_m, rid_flat, n).astype(jnp.int32),
        jnp.where(own_m, pp_flat - me * NCL_c, 0).astype(jnp.int32),
        jnp.where(own_m, pw_flat, 0.0).astype(dt), n_sent=n)
    p_rid_h, p_ci_h, p_va_h = _sorted_by_rid(
        jnp.where(halo_m, rid_flat, n).astype(jnp.int32),
        jnp.where(halo_m, jnp.searchsorted(plist, pp_flat), 0
                  ).astype(jnp.int32),
        jnp.where(halo_m, pw_flat, 0.0).astype(dt), n_sent=n)
    send_p, recv_p = _a2a_maps(plist, pcnt, me, NCL_c, NCL_c, axis, R,
                               mp_p)
    P_sh = dict(rid_own=p_rid_o, ci_own=p_ci_o, va_own=p_va_o,
                rid_halo=p_rid_h, ci_halo=p_ci_h, va_halo=p_va_h,
                diag=jnp.ones((n,), dt), halo_src=plist,
                a2a_send=send_p, a2a_recv=recv_p)
    # ---- R shard: rows = my coarse slots, cols = fine vertices -------
    # local part: my fine vertices whose P entries target my coarse rows
    r_rid_o, r_ci_o, r_va_o = _sorted_by_rid(
        jnp.where(own_m, pp_flat - me * NCL_c, NCL_c).astype(jnp.int32),
        jnp.where(own_m, rid_flat, 0).astype(jnp.int32),
        jnp.where(own_m, pw_flat, 0.0).astype(dt), n_sent=NCL_c)
    mvalid = mcid != _SENT
    rlist, rcnt = _unique_remote(mgid, mvalid, me, n, H_r)
    r_rid_h, r_ci_h, r_va_h = _sorted_by_rid(
        jnp.where(mvalid, mcid - offsets_c[me], NCL_c).astype(jnp.int32),
        jnp.where(mvalid, jnp.searchsorted(rlist, mgid), 0
                  ).astype(jnp.int32),
        jnp.where(mvalid, mw, 0.0).astype(dt), n_sent=NCL_c)
    send_r, recv_r = _a2a_maps(rlist, rcnt, me, n, n, axis, R, mp_r)
    R_sh = dict(rid_own=r_rid_o, ci_own=r_ci_o, va_own=r_va_o,
                rid_halo=r_rid_h, ci_halo=r_ci_h, va_halo=r_va_h,
                diag=jnp.ones((NCL_c,), dt), halo_src=rlist,
                a2a_send=send_r, a2a_recv=recv_r)
    return A_c, P_sh, R_sh


def run_classical_levels(amg, mesh, axis: str, M: ShardMatrix, offsets,
                         R: int, consolidate_at: int):
    """Host orchestration of the sharded classical level loop (the
    classical counterpart of build_sharded_hierarchy's aggregation
    loop; same three-phase count-sync structure). Returns (levels,
    levels_data, M, offsets, lvl, offsets_last, ncl_last) or None when
    no sharded level could be built."""
    from .setup import DistAMGLevel, _mk_shard, _wrap
    cfg, scope = amg.cfg, amg.scope
    theta = float(cfg.get("strength_threshold", scope))
    mrs = float(cfg.get("max_row_sum", scope))
    tf = float(cfg.get("interp_truncation_factor", scope))
    mel = int(cfg.get("interp_max_elements", scope))
    levels, levels_data = [], []
    offsets_last = ncl_last = None
    lvl = 0
    while True:
        n = int(offsets[-1])
        if (lvl + 1 >= amg.max_levels or n <= max(amg.min_coarse_rows, 1)
                or n < amg.min_fine_rows
                or (n <= amg.dense_lu_num_rows and lvl > 0)):
            break
        if lvl > 0 and n <= consolidate_at:
            break      # tail fits the consolidation budget
        offs = jnp.asarray(offsets)

        def fa(Mx, _o=offs):
            cf, c = classical_phase_a(Mx.local(), _o, axis, theta, mrs,
                                      30)
            return cf[None], c[None]
        cf_s, countsA = _wrap(mesh, axis, M, fa)(M)
        ca = np.asarray(countsA)
        nc_locals = ca[:, 0].astype(np.int64)
        nc_g = int(nc_locals.sum())
        if nc_g <= 0 or nc_g >= n or \
                (n / max(nc_g, 1)) < amg.coarsen_threshold:
            break
        PK = max(int(ca[:, 1].max()), 1)
        NCL_c = max(int(nc_locals.max()), 1)
        offsets_c = np.concatenate(
            [[0], np.cumsum(nc_locals)]).astype(np.int32)
        offs_c = jnp.asarray(offsets_c)

        def fb1(args, _o=offs, _oc=offs_c, _pk=PK):
            Mx, cf_ = args
            return classical_phase_b1(Mx.local(), _o, cf_[0], _oc,
                                      axis, theta, mrs, _pk, tf,
                                      mel)[None]
        cb1 = np.asarray(_wrap(mesh, axis, (M, cf_s), fb1)((M, cf_s)))
        maxt = max(int(cb1[:, :R].max()), 1)
        maxm = max(int(cb1[:, R:].max()), 1)

        def fb2(args, _o=offs, _oc=offs_c, _pk=PK, _ncl=NCL_c,
                _mt=maxt, _mm=maxm):
            Mx, cf_ = args
            out = classical_phase_b2(Mx.local(), _o, cf_[0], _oc, axis,
                                     theta, mrs, _pk, _ncl, _mt, _mm,
                                     tf, mel)
            return jax.tree.map(lambda a: a[None], out)
        outB = _wrap(mesh, axis, (M, cf_s), fb2)((M, cf_s))
        (slot_s, cj_s, v_s, p_phys, p_w, mcid, mgid, mw, countsB) = outB
        cb = np.asarray(countsB)
        E_own, E_halo, H_c, H_p, H_r = (
            max(int(cb[:, i].max()), 1) for i in (1, 2, 3, 4, 5))

        def fcc(args, _o=offs, _oc=offs_c, _ncl=NCL_c, _pk=PK,
                _eo=E_own, _eh=E_halo, _hc=H_c, _hp=H_p, _hr=H_r):
            (Mx, s1, c1, v1, pp, pw, mc, mg, mww) = args
            out = classical_phase_c(
                Mx.local(), _o, (s1[0], c1[0], v1[0]), pp[0], pw[0],
                mc[0], mg[0], mww[0], _oc, axis, _ncl, _pk, _eo, _eh,
                _hc, max(_hc, 1), _hp, max(_hp, 1), _hr, max(_hr, 1))
            return jax.tree.map(lambda a: a[None], out)
        argsC = (M, slot_s, cj_s, v_s, p_phys, p_w, mcid, mgid, mw)
        A_c_f, P_f, R_f = _wrap(mesh, axis, argsC, fcc)(argsC)
        A_c = _mk_shard(A_c_f, R * NCL_c, NCL_c, NCL_c, H_c, R, axis)
        P_sh = _mk_shard(P_f, n, M.n_local, NCL_c, H_p, R, axis)
        R_sh = _mk_shard(R_f, R * NCL_c, NCL_c, M.n_local, H_r, R, axis)
        levels.append(DistAMGLevel(M, lvl, offsets=np.asarray(offsets)))
        levels_data.append({"A": M, "P": P_sh, "R": R_sh})
        offsets_last, ncl_last = offsets_c, NCL_c
        M, offsets = A_c, offsets_c
        lvl += 1
    if not levels:
        return None
    return levels, levels_data, M, offsets, lvl, offsets_last, ncl_last
