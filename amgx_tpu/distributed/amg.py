"""Distributed AMG: shard a built hierarchy for SPMD cycles over a mesh.

The reference distributes AMG by making every rank build its partition of
every level (distributed Galerkin RAP with halo-row exchange,
src/classical/classical_amg_level.cu:297-315) and consolidating small
coarse levels onto fewer ranks (include/distributed/glue.h:200), with the
coarsest solve replicated via all_gather
(src/solvers/dense_lu_solver.cu:783-930 `exact_coarse_solve`).

TPU-native redesign: setup is a once-per-structure host-orchestrated
phase on the single controller — the hierarchy (levels, transfer
operators, smoother data) is built globally, then *every level is
partitioned into row-block shards with halo maps*:

- each level's A becomes a square ShardMatrix (halo exchange per SpMV);
- P (fine x coarse) and R (coarse x fine) become rectangular
  ShardMatrices, so restriction/prolongation perform the same
  halo-exchange + local SpMV — the communication pattern of the
  reference's distributed transfer operators;
- smoother device data (Jacobi/L1 dinv, DILU Einv, colorings, CF masks)
  is partitioned row-wise; the masked-SpMV sweeps then execute
  identically per shard, so iteration counts match the single-device
  hierarchy exactly;
- the coarsest level is REPLICATED: the rhs is all_gathered, every shard
  applies the same dense LU redundantly and keeps its slice — precisely
  the reference's exact_coarse_solve.

The multigrid cycle itself (amg/cycles.py) is unchanged: inside
shard_map its SpMVs dispatch to ShardMatrix, its reductions finish with
psum, and the whole V-cycle traces into one SPMD XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..ops.transpose import transpose
from .dist_matrix import ShardMatrix, shard_matrix_from_partition
from .partition import partition_matrix

# smoother solve-data keys that partition row-wise (leading dim = rows);
# any other key (nested preconditioners, ILU factors, permutations) marks
# the smoother as not distribution-aware
_ROWWISE_KEYS = {"dinv", "Einv", "colors", "is_coarse", "gs_diag"}


def _partition_rowwise(arr, n_ranks: int, n_local: int):
    """Stack a (n, ...) per-row array into (n_ranks, n_local, ...) with
    zero padding on the last shard."""
    a = np.asarray(arr)
    pad = n_ranks * n_local - a.shape[0]
    if pad:
        a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
    return jnp.asarray(a.reshape((n_ranks, n_local) + a.shape[1:]))


def _shard(A: CsrMatrix, n_ranks: int, axis: str) -> ShardMatrix:
    return shard_matrix_from_partition(partition_matrix(A, n_ranks), axis)


def _replicate(tree, n_ranks: int):
    """Tile every leaf with a leading mesh axis (replicated data)."""
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (n_ranks,) + a.shape), tree)


def _transfer_ops(level):
    """Global P/R of a level. Classical levels carry them; aggregation
    levels materialize P[i, agg[i]] = 1 and R = P^T (the CSR view of the
    aggregate map, aggregation_amg_level.cu:238)."""
    if hasattr(level, "P"):
        return level.P, level.R
    agg = np.asarray(level.aggregates)
    n, nc = agg.shape[0], level.coarse_size
    P = CsrMatrix.from_scipy_like(
        np.arange(n + 1, dtype=np.int32), agg.astype(np.int32),
        np.ones(n, level.A.dtype), n, nc)
    return P, transpose(P)


def _shard_smoother_data(sm, A_sh: ShardMatrix, n_ranks: int):
    """Partition a smoother's solve-data pytree row-wise."""
    data = sm.solve_data()
    out = {"A": A_sh}
    n_local = A_sh.n_local
    for k, v in data.items():
        if k == "A":
            continue
        if k not in _ROWWISE_KEYS:
            raise BadParametersError(
                f"distributed AMG: smoother {sm.name} is not "
                f"distribution-aware (data key {k!r}); use BLOCK_JACOBI, "
                f"JACOBI_L1, MULTICOLOR_GS, MULTICOLOR_DILU or CF_JACOBI")
        out[k] = _partition_rowwise(v, n_ranks, n_local)
    return out


class _ConsolidationBoundaryLevel:
    """Wraps the last SHARDED level when coarse-level consolidation is
    on (glue_matrices analog, include/distributed/glue.h:200): its
    restriction all_gathers the coarse rhs so every deeper level runs
    REPLICATED on full vectors (no halo traffic — the right trade once
    a level's per-shard row count is small enough that latency
    dominates), and its prolongation slices the local piece back out.
    The reference merges shards onto sub-communicators; on a TPU mesh
    the latency-optimal merge target is full replication, which is also
    what its exact_coarse_solve does one level further down."""

    def __init__(self, level, axis: str, n_ranks: int, nc_global: int):
        self._level = level
        self._axis = axis
        self._n_ranks = n_ranks
        self._nc_global = nc_global
        self._nc_local = -(-nc_global // n_ranks)

    def __getattr__(self, name):
        return getattr(self._level, name)

    def restrict(self, data, r):
        bc_local = self._level.restrict(data, r)[: self._nc_local]
        bc = jax.lax.all_gather(bc_local, self._axis, tiled=True)
        return bc[: self._nc_global]

    def prolongate(self, data, xc):
        pad = self._n_ranks * self._nc_local - self._nc_global
        xp = jnp.pad(xc, (0, pad))
        rank = jax.lax.axis_index(self._axis)
        xc_local = jax.lax.dynamic_slice(xp, (rank * self._nc_local,),
                                         (self._nc_local,))
        return self._level.prolongate(data, xc_local)


class DistributedCoarseSolver:
    """exact_coarse_solve analog (dense_lu_solver.cu:783-930): all_gather
    the coarse rhs, apply the replicated inner solver redundantly on
    every shard, keep the local slice."""

    is_smoother = False

    def __init__(self, inner, axis: str, n_ranks: int, nc_global: int,
                 nc_local: int, coarsest_sweeps: int):
        self.inner = inner
        self.name = "DIST_" + inner.name
        self.axis = axis
        self.n_ranks = n_ranks
        self.nc_global = nc_global
        self.nc_local = nc_local
        self.coarsest_sweeps = coarsest_sweeps

    def apply(self, data, rhs):
        from ..amg.cycles import apply_coarse_solver
        bc = jax.lax.all_gather(rhs, self.axis, tiled=True)[: self.nc_global]
        xg = apply_coarse_solver(self.inner, data, bc, jnp.zeros_like(bc),
                                 self.coarsest_sweeps)
        pad = self.n_ranks * self.nc_local - self.nc_global
        xp = jnp.pad(xg, (0, pad))
        r = jax.lax.axis_index(self.axis)
        return jax.lax.dynamic_slice(xp, (r * self.nc_local,),
                                     (self.nc_local,))


def shard_amg(amg, n_ranks: int, axis: str):
    """Convert a set-up (global) AMG hierarchy for SPMD solving: returns
    the stacked solve-data pytree and rewires the hierarchy's coarse
    solver + transfer dispatch for mesh execution."""
    if amg.cycle_name in ("CG", "CGF"):
        raise BadParametersError(
            "distributed AMG: K-cycles (CG/CGF) not yet supported; "
            "use cycle=V, W or F")
    if amg.levels and amg.levels[0].A.is_block:
        raise BadParametersError(
            "distributed AMG: scalar matrices only (distributed Krylov + "
            "block-Jacobi supports block systems)")
    if isinstance(amg.coarse_solver, DistributedCoarseSolver) or any(
            isinstance(lv, _ConsolidationBoundaryLevel)
            for lv in amg.levels):
        raise BadParametersError(
            "shard_amg: hierarchy is already sharded; re-run setup() "
            "before sharding again")
    # coarse-level consolidation (amg_consolidation_flag +
    # matrix_consolidation_lower_threshold, src/core.cu:316-322): once a
    # level's per-shard row count falls below the threshold, that level
    # and everything deeper run replicated
    boundary = len(amg.levels)
    if bool(amg.cfg.get("amg_consolidation_flag", amg.scope)):
        lower = int(amg.cfg.get("matrix_consolidation_lower_threshold",
                                amg.scope))
        if lower > 0:
            for k, lvl in enumerate(amg.levels):
                if lvl.A.num_rows / n_ranks < lower:
                    boundary = max(k, 1)     # finest level stays sharded
                    break
    levels_data = []
    for k, lvl in enumerate(amg.levels):
        if k >= boundary:                    # replicated (glued) level
            levels_data.append(_replicate(lvl.level_data(), n_ranks))
            continue
        A_sh = _shard(lvl.A, n_ranks, axis)
        P, R = _transfer_ops(lvl)
        ld = {
            "A": A_sh,
            "P": _shard(P, n_ranks, axis),
            "R": _shard(R, n_ranks, axis),
        }
        if lvl.smoother is not None:
            ld["smoother"] = _shard_smoother_data(lvl.smoother, A_sh,
                                                  n_ranks)
        levels_data.append(ld)
    nc = amg.coarsest_A.num_rows
    coarse_data = _replicate(amg.coarse_solver.solve_data(), n_ranks)
    if boundary < len(amg.levels):
        # vectors are already global below the boundary: the coarse
        # solver applies directly, and the boundary level's transfers
        # gather/slice across the mesh
        nb = amg.levels[boundary].A.num_rows
        amg.levels[boundary - 1] = _ConsolidationBoundaryLevel(
            amg.levels[boundary - 1], axis, n_ranks, nb)
    else:
        nc_local = -(-nc // n_ranks)
        amg.coarse_solver = DistributedCoarseSolver(
            amg.coarse_solver, axis, n_ranks, nc, nc_local,
            amg.coarsest_sweeps)
    return {"levels": levels_data, "coarse": coarse_data}
