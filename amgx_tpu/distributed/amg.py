"""Distributed AMG: shard a built hierarchy for SPMD cycles over a mesh.

The reference distributes AMG by making every rank build its partition of
every level (distributed Galerkin RAP with halo-row exchange,
src/classical/classical_amg_level.cu:297-315) and consolidating small
coarse levels onto fewer ranks (include/distributed/glue.h:200), with the
coarsest solve replicated via all_gather
(src/solvers/dense_lu_solver.cu:783-930 `exact_coarse_solve`).

TPU-native redesign: setup is a once-per-structure host-orchestrated
phase on the single controller — the hierarchy (levels, transfer
operators, smoother data) is built globally, then *every level is
partitioned into row-block shards with halo maps*:

- each level's A becomes a square ShardMatrix (halo exchange per SpMV);
- P (fine x coarse) and R (coarse x fine) become rectangular
  ShardMatrices, so restriction/prolongation perform the same
  halo-exchange + local SpMV — the communication pattern of the
  reference's distributed transfer operators;
- smoother device data (Jacobi/L1 dinv, DILU Einv, colorings, CF masks)
  is partitioned row-wise; the masked-SpMV sweeps then execute
  identically per shard, so iteration counts match the single-device
  hierarchy exactly;
- the coarsest level is REPLICATED: the rhs is all_gathered, every shard
  applies the same dense LU redundantly and keeps its slice — precisely
  the reference's exact_coarse_solve.

The multigrid cycle itself (amg/cycles.py) is unchanged: inside
shard_map its SpMVs dispatch to ShardMatrix, its reductions finish with
psum, and the whole V-cycle traces into one SPMD XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..ops.transpose import transpose
from .dist_matrix import ShardMatrix, shard_matrix_from_partition
from .partition import partition_matrix

# smoother solve-data keys that partition row-wise (leading dim = rows);
# CsrMatrix-valued entries (the ILU factors) shard like the level
# operator itself; _REPLICATED_KEYS are small row-independent arrays
# (polynomial coefficients) that tile across the mesh. Any other key
# (nested preconditioners, global permutations) marks the smoother as
# not distribution-aware.
_ROWWISE_KEYS = {"dinv", "Einv", "colors", "is_coarse", "gs_diag",
                 "u_diag"}
_REPLICATED_KEYS = {"taus"}


def _partition_rowwise(arr, n_ranks: int, n_local: int):
    """Stack a (n, ...) per-row array into (n_ranks, n_local, ...) with
    zero padding on the last shard."""
    a = np.asarray(arr)
    pad = n_ranks * n_local - a.shape[0]
    if pad:
        a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
    return jnp.asarray(a.reshape((n_ranks, n_local) + a.shape[1:]))


def _shard(A: CsrMatrix, n_ranks: int, axis: str) -> ShardMatrix:
    return shard_matrix_from_partition(partition_matrix(A, n_ranks), axis)


def _replicate(tree, n_ranks: int):
    """Tile every leaf with a leading mesh axis (replicated data). A
    host-built hierarchy (amg_host_setup) holds CPU-committed arrays;
    normalize to the default device so the shard_mapped solve does not
    mix committed placements."""
    def rep(a):
        # host round trip drops any committed placement (host-built
        # hierarchies commit to cpu:0, which jit would refuse to mix
        # with mesh-sharded arguments); replicated levels are small
        a = jnp.asarray(np.asarray(a))
        return jnp.broadcast_to(a[None], (n_ranks,) + a.shape)
    return jax.tree.map(rep, tree)


def gather_global(v_local, axis: str, n_global: int):
    """Shard-local -> replicated global vector (drop padding)."""
    return jax.lax.all_gather(v_local, axis, tiled=True)[:n_global]


def keep_local_slice(v_global, axis: str, n_ranks: int, n_local: int,
                     n_global: int):
    """Replicated global vector -> this shard's padded local slice (the
    single implementation of the replicate-then-keep-local ritual used
    by the consolidation boundary, the exact coarse solve and the
    K-cycle's coarsest matvec)."""
    pad = n_ranks * n_local - n_global
    vp = jnp.pad(v_global, (0, pad))
    r = jax.lax.axis_index(axis)
    return jax.lax.dynamic_slice(vp, (r * n_local,), (n_local,))


def _transfer_ops(level):
    """Global P/R of a level. Classical levels carry them; aggregation
    levels materialize P[i, agg[i]] = 1 and R = P^T (the CSR view of the
    aggregate map, aggregation_amg_level.cu:238). Block levels expand
    P to the scalar unknown space (P (x) I_b), matching the
    scalar-expanded distributed operators."""
    if hasattr(level, "P"):
        return level.P, level.R
    agg = np.asarray(level.aggregates)
    n, nc = agg.shape[0], level.coarse_size
    bx = level.A.block_dimx
    if bx > 1:
        # block form P_block[i, agg[i]] = I_b: partition_matrix then
        # scalar-expands P/R with the SAME block-aligned row rounding as
        # the level operators, keeping per-shard vector layouts aligned
        eye = np.broadcast_to(np.eye(bx, dtype=level.A.dtype),
                              (n, bx, bx))
        P = CsrMatrix.from_scipy_like(
            np.arange(n + 1, dtype=np.int32), agg.astype(np.int32),
            jnp.asarray(eye), n, nc, block_dims=(bx, bx))
        order = np.argsort(agg, kind="stable")
        counts = np.bincount(agg, minlength=nc)
        ro = np.zeros(nc + 1, np.int32)
        np.cumsum(counts, out=ro[1:])
        Rm = CsrMatrix.from_scipy_like(
            ro, order.astype(np.int32), jnp.asarray(eye), nc, n,
            block_dims=(bx, bx))
        return P, Rm
    P = CsrMatrix.from_scipy_like(
        np.arange(n + 1, dtype=np.int32), agg.astype(np.int32),
        np.ones(n, level.A.dtype), n, nc)
    return P, transpose(P)


def _shard_smoother_data(sm, A_sh: ShardMatrix, n_ranks: int, axis: str):
    """Partition a smoother's solve-data pytree row-wise; CsrMatrix
    entries (triangular ILU factors) become halo-exchanging shards."""
    data = sm.solve_data()
    out = {"A": A_sh}
    # smoother per-row arrays are per BLOCK row (dinv (nb,bx,by),
    # colors (nb,)); the shard stores scalar-expanded rows
    n_local = A_sh.n_local // A_sh.bdimx
    for k, v in data.items():
        if k in ("A", "precond"):
            # 'precond' is rebuilt by the distributed chain walk
            # (solver.py chain_data) — every chain member is admitted
            # and sharded individually
            continue
        if k == "fused":
            # the SINGLE-CHIP quota-padded operand slabs (ops/smooth.py
            # solver_fused_slabs) are global-layout; the sharded fused
            # path carries its own halo-folded per-shard form instead
            # ("dist_fused", attach_shard_fused below the caller)
            continue
        if isinstance(v, CsrMatrix):
            out[k] = _shard(v, n_ranks, axis)
            continue
        if k in _REPLICATED_KEYS:
            out[k] = _replicate(v, n_ranks)
            continue
        if k not in _ROWWISE_KEYS:
            raise BadParametersError(
                f"distributed AMG: smoother {sm.name} is not "
                f"distribution-aware (data key {k!r}); use BLOCK_JACOBI, "
                f"JACOBI_L1, MULTICOLOR_GS, MULTICOLOR_DILU, "
                f"MULTICOLOR_ILU or CF_JACOBI")
        out[k] = _partition_rowwise(v, n_ranks, n_local)
    return out


class _ConsolidationBoundaryLevel:
    """Wraps the last SHARDED level when coarse-level consolidation is
    on (glue_matrices analog, include/distributed/glue.h:200): its
    restriction all_gathers the coarse rhs so every deeper level runs
    REPLICATED on full vectors (no halo traffic — the right trade once
    a level's per-shard row count is small enough that latency
    dominates), and its prolongation slices the local piece back out.
    The reference merges shards onto sub-communicators; on a TPU mesh
    the latency-optimal merge target is full replication, which is also
    what its exact_coarse_solve does one level further down."""

    def __init__(self, level, axis: str, n_ranks: int, nc_global: int,
                 bx: int = 1):
        self._level = level
        self._axis = axis
        self._n_ranks = n_ranks
        self._nc_global = nc_global
        # per-shard slice must match the block-aligned row rounding of
        # the sharded transfer operators (block rows never split)
        self._nc_local = -(-(nc_global // bx) // n_ranks) * bx

    def __getattr__(self, name):
        return getattr(self._level, name)

    def restrict(self, data, r):
        bc_local = self._level.restrict(data, r)[: self._nc_local]
        return gather_global(bc_local, self._axis, self._nc_global)

    def prolongate(self, data, xc):
        xc_local = keep_local_slice(xc, self._axis, self._n_ranks,
                                    self._nc_local, self._nc_global)
        return self._level.prolongate(data, xc_local)

    # Cycle-fusion hooks: none, and the wrapped level's must never be
    # reached through __getattr__ delegation — they would
    # restrict/prolongate in ITS (shard-local) space, skipping this
    # wrapper's gather into the replicated-tail numbering. The cycle's
    # class-resolved capability check (amg/cycles.py _fusion_caps)
    # guarantees that: no class-level surface here means the plain
    # compose runs, with the smoother's "dist_fused" payload fusing
    # the sweeps and the gathered tail levels downstream qualifying
    # for the single-chip VMEM coarse-tail megakernel unchanged.


class DistributedCoarseSolver:
    """exact_coarse_solve analog (dense_lu_solver.cu:783-930): all_gather
    the coarse rhs, apply the replicated inner solver redundantly on
    every shard, keep the local slice."""

    is_smoother = False

    def __init__(self, inner, axis: str, n_ranks: int, nc_global: int,
                 nc_local: int, coarsest_sweeps: int):
        self.inner = inner
        self.name = "DIST_" + inner.name
        self.axis = axis
        self.n_ranks = n_ranks
        self.nc_global = nc_global
        self.nc_local = nc_local
        self.coarsest_sweeps = coarsest_sweeps

    def gather_apply_slice(self, fn, v):
        """Replicated apply: gather v, run fn on the global vector on
        every shard, keep the local slice."""
        vg = gather_global(v, self.axis, self.nc_global)
        yg = fn(vg)
        return keep_local_slice(yg, self.axis, self.n_ranks,
                                self.nc_local, self.nc_global)

    def apply(self, data, rhs):
        from ..amg.cycles import apply_coarse_solver
        return self.gather_apply_slice(
            lambda bc: apply_coarse_solver(self.inner, data, bc,
                                           jnp.zeros_like(bc),
                                           self.coarsest_sweeps), rhs)


def shard_amg(amg, n_ranks: int, axis: str):
    """Convert a set-up (global) AMG hierarchy for SPMD solving: returns
    the stacked solve-data pytree and rewires the hierarchy's coarse
    solver + transfer dispatch for mesh execution."""
    if isinstance(amg.coarse_solver, DistributedCoarseSolver) or any(
            isinstance(lv, _ConsolidationBoundaryLevel)
            for lv in amg.levels):
        raise BadParametersError(
            "shard_amg: hierarchy is already sharded; re-run setup() "
            "before sharding again")
    # coarse-level consolidation (amg_consolidation_flag +
    # matrix_consolidation_lower_threshold, src/core.cu:316-322): once a
    # level's per-shard row count falls below the threshold, that level
    # and everything deeper run replicated
    boundary = len(amg.levels)
    if bool(amg.cfg.get("amg_consolidation_flag", amg.scope)):
        lower = int(amg.cfg.get("matrix_consolidation_lower_threshold",
                                amg.scope))
        if lower > 0:
            for k, lvl in enumerate(amg.levels):
                if lvl.A.num_rows / n_ranks < lower:
                    boundary = max(k, 1)     # finest level stays sharded
                    break
    levels_data = []
    for k, lvl in enumerate(amg.levels):
        if k >= boundary:                    # replicated (glued) level
            levels_data.append(_replicate(lvl.level_data(), n_ranks))
            continue
        A_sh = _shard(lvl.A, n_ranks, axis)
        P, R = _transfer_ops(lvl)
        ld = {
            "A": A_sh,
            "P": _shard(P, n_ranks, axis),
            "R": _shard(R, n_ranks, axis),
        }
        if lvl.smoother is not None:
            ld["smoother"] = _shard_smoother_data(lvl.smoother, A_sh,
                                                  n_ranks, axis)
            # halo-folded fused-smoother payload (distributed/fused.py):
            # sharded DIA levels run all sweeps + the cycle residual in
            # ONE per-shard kernel with one edge-window exchange;
            # dist_cycle_fusion=0 (or an ineligible layout/smoother)
            # attaches nothing and changes nothing
            from .fused import attach_shard_fused
            attach_shard_fused(ld["smoother"], lvl.A, lvl.smoother,
                               n_ranks, A_sh.n_local, amg.cfg, amg.scope)
        levels_data.append(ld)
    # vectors in the sharded cycle are scalar-expanded: size counts are
    # in scalar unknowns (block rows never split across shards, so the
    # equal-block slicing stays block-aligned)
    nc = amg.coarsest_A.num_rows * amg.coarsest_A.block_dimx
    coarse_data = _replicate(amg.coarse_solver.solve_data(), n_ranks)
    if boundary < len(amg.levels):
        # vectors are already global below the boundary: the coarse
        # solver applies directly, and the boundary level's transfers
        # gather/slice across the mesh
        Ab = amg.levels[boundary].A
        nb = Ab.num_rows * Ab.block_dimx
        amg.levels[boundary - 1] = _ConsolidationBoundaryLevel(
            amg.levels[boundary - 1], axis, n_ranks, nb, Ab.block_dimx)
    else:
        bx = amg.coarsest_A.block_dimx
        nc_local = -(-(nc // bx) // n_ranks) * bx
        amg.coarse_solver = DistributedCoarseSolver(
            amg.coarse_solver, axis, n_ranks, nc, nc_local,
            amg.coarsest_sweeps)
    return {"levels": levels_data, "coarse": coarse_data}
