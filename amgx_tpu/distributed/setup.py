"""Sharded (per-rank) distributed AMG setup.

This is the TPU-native analog of the reference's distributed hierarchy
build, where every rank constructs its partition of every AMG level and
no rank ever materializes a global coarse operator
(src/aggregation/aggregation_amg_level.cu ghost-aggregate handling,
src/classical/classical_amg_level.cu:297-315 distributed Galerkin RAP,
src/distributed/distributed_manager.cu `createOneRingHaloRows` /
`renumberMatrixOneRing`). The single-controller `shard_amg` path
(distributed/amg.py) builds the hierarchy globally then shards it; this
module replaces that global phase: the whole level build — edge weights,
handshaking matching, aggregate numbering, Galerkin RAP, coarse halo-map
construction — runs as shard_mapped SPMD programs over the mesh, with
per-shard peak memory O(n/p).

Key design decisions (vs the reference's MPI machinery):

- **Two id spaces.** Decisions (matching tie-break hash, orderings,
  dedup keys) use *semantic* contiguous global ids — identical to the
  ids the single-device setup uses, so the sharded selector makes
  bit-identical aggregation decisions and the hierarchy matches the
  global-setup hierarchy exactly (the reference instead renumbers
  owned-interior/boundary/halo per rank and accepts layout-dependent
  hierarchies). Storage and exchange use *physical* block-aligned ids
  (`rank * NCL + slot`, NCL = max per-shard coarse count), which keep
  the equal-block ShardMatrix machinery (rank = id // NCL) working
  unchanged; `offsets` arrays convert between the two.
- **Routing is all_to_all.** Cross-rank aggregates make RAP
  contributions land on remote coarse rows; the reference exchanges
  halo rows (B2L rings). Here every cross contribution is a (CI, CJ, v)
  triple routed to CI's owner with one `lax.all_to_all` of per-peer
  padded buffers — hop-count-free (an aggregate rooted two ranks away
  is routed identically to a neighbor's).
- **Static shapes via per-level count syncs.** Each level build is
  three jitted phases; between phases the host reads a small packed
  count vector (one device round trip) and re-invokes with exact
  padded sizes. Value buffers keep first-occurrence-summed duplicates
  (zero-valued, inert — the single-device Galerkin uses the same
  trick) until the final compaction.
- **Consolidation boundary.** Once the global coarse size fits a single
  shard's budget, the level is gathered, compacted to the semantic
  (single-device) numbering, and the *existing* global setup builds the
  remaining levels replicated — the `glue_matrices` endpoint
  (include/distributed/glue.h:200) that distributed/amg.py already
  implements for the solve phase.

Scope (v1): aggregation AMG with the matching selectors
(SIZE_2/4/8, PARALLEL_GREEDY, MULTI_PAIRWISE) and row-partitionable
smoothers (JACOBI, BLOCK_JACOBI on scalar systems, JACOBI_L1,
NOSOLVER). Cross-rank edge weights assume a value-symmetric matrix
(|a_ji| = |a_ij|; exact for the SPD systems aggregation targets —
documented deviation: the single-device path handles pattern-symmetric
non-value-symmetric matrices via its positional-transpose alignment).
Everything else falls back to the global-setup + shard_amg path.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..errors import BadParametersError
from .dist_matrix import ShardMatrix

_SENT = jnp.int32(2**31 - 1)          # sentinel global id (sorts last)


# ---------------------------------------------------------------------------
# generic SPMD primitives (per-shard bodies; collectives over `axis`)
# ---------------------------------------------------------------------------

def _bucket_by_owner(owner, R: int, maxq: int, valid):
    """Stable-sort positions by owner rank; per-peer contiguous segments.

    Returns (ord_, idx, in_seg, cnt): `ord_[start[p] + k]` is the source
    position of the k-th item for peer p; `idx[p, k]` indexes into the
    sorted order; `in_seg[p, k]` masks real items."""
    Q = owner.shape[0]
    key = jnp.where(valid, owner, R)            # invalid sorts last
    ord_ = jnp.argsort(key, stable=True)
    sorted_owner = key[ord_]
    start = jnp.searchsorted(sorted_owner, jnp.arange(R + 1))
    cnt = start[1:] - start[:-1]
    k = jnp.arange(maxq)
    idx = jnp.clip(start[:-1, None] + k[None, :], 0, Q - 1)
    in_seg = k[None, :] < cnt[:, None]
    return ord_, idx, in_seg, cnt


def _remote_lookup(table, queries, owner, offsets, me, n_owner_local,
                   axis, R: int, maxq: int, fill):
    """values = table[queries] where each query's answer lives on
    `owner`'s shard (request/response over two all_to_alls). `queries`
    are semantic ids; the owner indexes its table at
    `query - offsets[owner]`."""
    Q = queries.shape[0]
    valid = owner < R
    ord_, idx, in_seg, _ = _bucket_by_owner(owner, R, maxq, valid)
    sortedq = queries[ord_]
    req = jnp.where(in_seg, sortedq[idx], _SENT)
    got = jax.lax.all_to_all(req, axis, split_axis=0, concat_axis=0,
                             tiled=True)
    ok = got != _SENT
    loc = jnp.clip(got - offsets[me], 0, table.shape[0] - 1)
    ans = jnp.where(ok, table[loc], fill)
    back = jax.lax.all_to_all(ans, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    out = jnp.full((Q,), fill, back.dtype)
    scatter_pos = jnp.where(in_seg, ord_[idx], Q)
    return out.at[scatter_pos.reshape(-1)].set(
        back.reshape(-1), mode="drop")


def _route(payloads, dest, me, axis, R: int, maxq: int, fills):
    """Route per-item payload tuples to `dest` ranks; returns the
    received (R * maxq,)-flat payloads (fill-padded). The receiving
    order is (source rank, sender's bucketed order) — deterministic."""
    valid = dest < R
    ord_, idx, in_seg, _ = _bucket_by_owner(dest, R, maxq, valid)
    outs = []
    for arr, fill in zip(payloads, fills):
        buf = jnp.where(in_seg, arr[ord_[idx]], fill)
        got = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                 tiled=True)
        outs.append(got.reshape(-1))
    return outs


def _a2a_maps(halo_phys, n_halo, me, NCL: int, n_local_cols: int,
              axis, R: int, maxpair: int):
    """Build all_to_all send/recv maps from a sorted physical halo list
    (device-side DistributedArranger analog, distributed_arranger.h:
    28-117: neighbor detection from global ids + B2L map construction).

    halo_phys: (H,) sorted physical global col ids, _SENT-padded past
    n_halo. Returns (a2a_send (R, maxpair) local col slots,
    a2a_recv (R, maxpair) halo slots) compatible with
    ShardMatrix.exchange_halo's "a2a" mode."""
    H = halo_phys.shape[0]
    valid = jnp.arange(H) < n_halo
    src = jnp.where(valid, halo_phys // NCL, R)
    # per-peer contiguous segments (halo list sorted by physical id)
    start = jnp.searchsorted(src, jnp.arange(R + 1))
    cnt = start[1:] - start[:-1]
    k = jnp.arange(maxpair)
    idx = jnp.clip(start[:-1, None] + k[None, :], 0, H - 1)
    in_seg = k[None, :] < cnt[:, None]
    req = jnp.where(in_seg, halo_phys[idx], _SENT)
    got = jax.lax.all_to_all(req, axis, split_axis=0, concat_axis=0,
                             tiled=True)
    a2a_send = jnp.where(got != _SENT, got - me * NCL,
                         n_local_cols).astype(jnp.int32)
    a2a_recv = jnp.where(in_seg, start[:-1, None] + k[None, :],
                         H).astype(jnp.int32)
    return a2a_send, a2a_recv


# ---------------------------------------------------------------------------
# per-shard edge view of a ShardMatrix level
# ---------------------------------------------------------------------------

class _Edges:
    """Local edge list of one shard: rows (local ids, sentinel n_local),
    semantic global col ids, values, and col-state accessors that read
    either the local state vector or the exchanged halo buffer."""

    def __init__(self, M: ShardMatrix, offsets, me):
        self.M = M
        self.n_local = M.n_local
        self.e_own = M.rid_own.shape[0]
        self.rows = jnp.concatenate([M.rid_own, M.rid_halo])
        self.is_halo = jnp.concatenate([
            jnp.zeros(M.rid_own.shape, bool),
            jnp.ones(M.rid_halo.shape, bool)])
        self.ci = jnp.concatenate([M.ci_own, M.ci_halo])
        self.vals = jnp.concatenate([M.va_own, M.va_halo])
        # sentinel entries: padded slots carry rid == n_local
        self.valid = self.rows < M.n_local
        halo_phys = jnp.where(
            jnp.arange(M.halo_src.shape[0]) < M.n_halo,
            M.halo_src.astype(jnp.int32), _SENT)
        self._halo_phys = halo_phys
        hp = jnp.concatenate([halo_phys, jnp.full((1,), _SENT)])
        cp_own = me * M.n_local_cols + jnp.clip(
            M.ci_own.astype(jnp.int32), 0, M.n_local_cols - 1)
        cp_halo = hp[jnp.clip(M.ci_halo, 0, hp.shape[0] - 1)]
        col_phys = jnp.concatenate([cp_own, cp_halo])
        self.col_phys = jnp.where(self.valid, col_phys, _SENT)
        self.col_sem = _sem_of(self.col_phys, offsets, M.n_local_cols)
        self.row_sem = jnp.where(
            self.valid, offsets[me] + self.rows, _SENT).astype(jnp.int32)

    def exchange(self, vec):
        """Halo-exchange a per-vertex state vector (square level)."""
        return self.M.exchange_halo(vec)

    def col_state(self, local_vec, halo_vec, fill):
        """Per-edge state of the column vertex (local or exchanged)."""
        lv = jnp.concatenate([local_vec,
                              jnp.full((1,), fill, local_vec.dtype)])
        hv = jnp.concatenate([halo_vec,
                              jnp.full((1,), fill, halo_vec.dtype)])
        own = lv[jnp.clip(self.ci[: self.e_own], 0, lv.shape[0] - 1)]
        hal = hv[jnp.clip(self.ci[self.e_own:], 0, hv.shape[0] - 1)]
        out = jnp.concatenate([own, hal])
        return jnp.where(self.valid, out, fill)


def _owner_of_sem(sem, offsets, R: int, valid):
    """Owner rank of a semantic id: the shard whose [offsets[r],
    offsets[r+1]) range contains it (coarse levels are unevenly
    partitioned in semantic space)."""
    own = jnp.searchsorted(offsets, sem, side="right") - 1
    return jnp.where(valid, jnp.clip(own, 0, R - 1), R).astype(jnp.int32)


def _sem_of(phys, offsets, NCL: int):
    """Physical block-aligned id -> semantic contiguous id."""
    rank = jnp.clip(phys // NCL, 0, offsets.shape[0] - 2)
    return jnp.where(phys == _SENT, _SENT,
                     offsets[rank] + (phys - rank * NCL)).astype(jnp.int32)


def _edge_hash_sem(a_sem, b_sem):
    """The selector's symmetric tie-break hash on semantic ids (the
    single implementation — sharded matching must perturb identically
    to the single-device pass for bit-identical decisions)."""
    from ..amg.aggregation.selectors import _edge_hash
    return _edge_hash(a_sem, b_sem)


# ---------------------------------------------------------------------------
# phase A: sharded handshaking matching (+ singleton merge + root counts)
# ---------------------------------------------------------------------------

def _sharded_weights(E: _Edges, diag, halo_diag, formula: int):
    """selectors._edge_weights under the value-symmetry assumption:
    w_ij = |a_ij| / max(|a_ii|, |a_jj|) (formula 0) computed per local
    edge; |a_ji| = |a_ij| so the 0.5(|a_ij|+|a_ji|) average collapses."""
    v = jnp.abs(E.vals)
    dl = jnp.concatenate([diag, jnp.ones((1,), diag.dtype)])
    d_r = dl[jnp.minimum(E.rows, E.n_local)]
    d_c = E.col_state(diag, halo_diag, 0.0)
    if formula == 1:
        # Notay coupling -0.5 (a_ij/a_ii + a_ji/a_jj)
        # (common_selector.h:113-119); a_ji = a_ij under the documented
        # value-symmetry assumption
        w = -0.5 * (E.vals / jnp.where(d_r == 0, 1.0, d_r)
                    + E.vals / jnp.where(d_c == 0, 1.0, d_c))
    else:
        denom = jnp.maximum(jnp.abs(d_r), jnp.abs(d_c))
        w = v / jnp.where(denom == 0, 1.0, denom)
    w = jnp.where(E.row_sem == E.col_sem, 0.0, w)
    return jnp.where(E.valid, w, 0.0)


def _seg_max(vals, rows, n, fill):
    return jax.ops.segment_max(
        jnp.concatenate([vals, jnp.full((1,), fill, vals.dtype)]),
        jnp.concatenate([rows, jnp.full((1,), n - 1, rows.dtype)]),
        num_segments=n)


def _seg_min(vals, rows, n, fill):
    return jax.ops.segment_min(
        jnp.concatenate([vals, jnp.full((1,), fill, vals.dtype)]),
        jnp.concatenate([rows, jnp.full((1,), n - 1, rows.dtype)]),
        num_segments=n)


def _sharded_matching(E: _Edges, w, active, me, offsets, axis,
                      max_iters: int):
    """selectors._matching_pass distributed: the same synchronized
    fixed point, with the column-vertex state (unaggregated flag, best
    proposal) halo-exchanged each sweep. Decisions are bit-identical to
    the single-device pass (same weights, same semantic-id tie-breaks,
    same smallest-index selection)."""
    exchange = E.exchange
    n = E.n_local
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    w = w * (1.0 + 1e-3 * _edge_hash_sem(E.row_sem, E.col_sem).astype(
        w.dtype))

    def cond(state):
        it, agg, paired = state
        un_any = jnp.any((agg < 0) & active)
        return (it < max_iters) & (
            jax.lax.psum(un_any.astype(jnp.int32), axis) > 0)

    def body(state):
        it, agg, paired = state
        un = (agg < 0) & active
        un_h = exchange(un.astype(jnp.int8)) > 0
        un_r = jnp.concatenate(
            [un, jnp.zeros((1,), bool)])[jnp.minimum(E.rows, n)]
        un_c = E.col_state(un, un_h, False)
        valid = un_r & un_c & (w > 0)
        we = jnp.where(valid, w, -1.0)
        wmax = _seg_max(we, E.rows, n, -1.0)
        has = wmax > 0
        is_best = valid & (we == wmax[jnp.clip(E.rows, 0, n - 1)])
        best = _seg_min(jnp.where(is_best, E.col_sem, _SENT), E.rows, n,
                        _SENT)
        best = jnp.where(has, best, _SENT)
        # handshake: the column vertex's own best proposal, per edge
        best_h = exchange(best)
        ebob = E.col_state(best, best_h, _SENT)
        bl = jnp.concatenate([best, jnp.full((1,), _SENT)])
        row_best = bl[jnp.minimum(E.rows, n)]
        hand = (E.col_sem == row_best) & (ebob == jnp.where(
            E.valid, jnp.concatenate(
                [idx_sem, jnp.full((1,), _SENT, jnp.int32)])[
                jnp.minimum(E.rows, n)], _SENT))
        paired_now = _seg_max(hand.astype(jnp.int8), E.rows, n,
                              jnp.int8(0)) > 0
        paired_now = paired_now & (best < _SENT)
        leader = paired_now & (idx_sem < best)
        agg = jnp.where(leader, idx_sem, agg)
        agg = jnp.where(paired_now & ~leader, best, agg)
        return it + 1, agg, paired | paired_now

    _, agg, paired = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.full((n,), -1, jnp.int32),
         jnp.zeros((n,), bool)))
    agg = jnp.where((agg < 0) & active, idx_sem, agg)
    return agg, paired


def _sharded_merge_singletons(E: _Edges, w, agg, paired, active, me,
                              offsets):
    """selectors._merge_singletons distributed: a singleton (never
    paired) vertex joins its strongest non-singleton neighbor's
    aggregate."""
    exchange = E.exchange
    n = E.n_local
    singleton = active & ~paired
    s_h = exchange(singleton.astype(jnp.int8)) > 0
    agg_h = exchange(agg)
    sl = jnp.concatenate([singleton, jnp.zeros((1,), bool)])
    s_r = sl[jnp.minimum(E.rows, n)]
    s_c = E.col_state(singleton, s_h, True)
    valid = s_r & ~s_c & (w > 0) & E.valid
    we = jnp.where(valid, w, -1.0)
    wmax = _seg_max(we, E.rows, n, -1.0)
    has = wmax > 0
    is_best = valid & (we == wmax[jnp.clip(E.rows, 0, n - 1)])
    best = _seg_min(jnp.where(is_best, E.col_sem, _SENT), E.rows, n,
                    _SENT)
    bl = jnp.concatenate([best, jnp.full((1,), _SENT)])
    row_best = bl[jnp.minimum(E.rows, n)]
    agg_c = E.col_state(agg, agg_h, _SENT)
    tgt = _seg_min(jnp.where(is_best & (E.col_sem == row_best), agg_c,
                             _SENT), E.rows, n, _SENT)
    return jnp.where(singleton & has & (tgt < _SENT), tgt, agg)

# ---------------------------------------------------------------------------
# phase B: coarse numbering, cid lookup, routed Galerkin triples
# ---------------------------------------------------------------------------

def _coarse_numbering(agg, active, offsets, me, n_local: int, axis):
    """Global coarse numbering identical to the single-device
    selectors._renumber: aggregates ordered by root semantic id. Returns
    (is_root, slot, nc_local, offsets_c) — offsets_c identical on every
    shard (all_gather of counts)."""
    idx_sem = offsets[me] + jnp.arange(n_local, dtype=jnp.int32)
    is_root = active & (agg == idx_sem)
    nc_local = jnp.sum(is_root.astype(jnp.int32))
    counts = jax.lax.all_gather(nc_local, axis)          # (R,)
    offsets_c = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)]).astype(jnp.int32)
    slot = (jnp.cumsum(is_root.astype(jnp.int32)) - 1).astype(jnp.int32)
    return is_root, slot, nc_local, offsets_c


def _assign_cids(agg, active, is_root, slot, offsets, offsets_c, me,
                 n_local: int, NCL_c: int, axis, R: int, maxq: int):
    """Per-vertex coarse ids (semantic + physical). Remote roots are
    resolved with one request/response lookup on the root's owner —
    the renumbering exchange of distributed_manager.cu
    `renumberMatrixOneRing`, minus the renumbering (two id spaces
    instead)."""
    cid_table = jnp.where(is_root, offsets_c[me] + slot, -1)
    owner = _owner_of_sem(agg, offsets, R, active & (agg >= 0))
    local_ans = cid_table[jnp.clip(agg - offsets[me], 0, n_local - 1)]
    remote_owner = jnp.where(owner == me, R, owner)      # self answered
    looked = _remote_lookup(cid_table, agg, remote_owner, offsets, me,
                            n_local, axis, R, maxq, jnp.int32(-1))
    cid_sem = jnp.where(owner == me, local_ans, looked)
    cid_sem = jnp.where(active, cid_sem, -1)
    rank_r = jnp.clip(owner, 0, R - 1)
    cid_phys = jnp.where(
        active & (cid_sem >= 0),
        rank_r * NCL_c + (cid_sem - offsets_c[rank_r]), -1)
    return cid_sem.astype(jnp.int32), cid_phys.astype(jnp.int32)


def _rap_triples(E: _Edges, cid_sem, cid_phys, owner_of_root, me,
                 offsets_c, NCL_c: int, axis, R: int, maxt: int,
                 values=None):
    """Distributed Galerkin triples: every local entry (i, j, v) becomes
    (CI, CJ, v); contributions to remote coarse rows are all_to_all'd
    to the owner (classical_amg_level.cu:297-315's halo-row RAP
    exchange, hop-count-free). Returns the shard's coarse entries
    sorted by (local slot, physical CJ) with duplicate values summed
    onto first occurrences (zeros elsewhere, inert — the single-device
    Galerkin keeps the same representation)."""
    from ..matrix import lexsort_rc  # local import: avoid cycle at init
    n = E.n_local
    halo_cs = E.exchange(cid_sem)
    halo_cp = E.exchange(cid_phys)
    cs_l = jnp.concatenate([cid_sem, jnp.full((1,), -1, jnp.int32)])
    CI = cs_l[jnp.minimum(E.rows, n)]
    CJ_phys = E.col_state(cid_phys, halo_cp, jnp.int32(-1))
    vals = E.vals if values is None else values
    ok = E.valid & (CI >= 0) & (CJ_phys >= 0)
    ol = jnp.concatenate([owner_of_root, jnp.full((1,), R, jnp.int32)])
    dest = jnp.where(ok, ol[jnp.minimum(E.rows, n)], R)
    # remote contributions: routed; local ones kept in place
    rCI, rCJ, rv = _route(
        (CI, CJ_phys, vals), jnp.where(dest == me, R, dest), me, axis,
        R, maxt, (_SENT, _SENT, jnp.zeros((), vals.dtype)))
    keep = ok & (dest == me)
    aCI = jnp.concatenate([jnp.where(keep, CI, _SENT), rCI])
    aCJ = jnp.concatenate([jnp.where(keep, CJ_phys, _SENT), rCJ])
    av = jnp.concatenate([jnp.where(keep, vals, 0.0), rv])
    slot = jnp.where(aCI != _SENT, aCI - offsets_c[me],
                     NCL_c).astype(jnp.int32)
    cj = jnp.where(aCJ != _SENT, aCJ, _SENT).astype(jnp.int32)
    order = lexsort_rc(slot, cj)
    slot_s, cj_s, v_s = slot[order], cj[order], av[order]
    valid_s = slot_s < NCL_c
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (slot_s[1:] != slot_s[:-1]) | (cj_s[1:] != cj_s[:-1])]) & valid_s
    seg = jnp.cumsum(first) - 1
    Etot = slot_s.shape[0]
    vsum = jax.ops.segment_sum(jnp.where(valid_s, v_s, 0.0), seg,
                               num_segments=Etot, indices_are_sorted=True)
    v_out = jnp.where(first, vsum[jnp.clip(seg, 0, Etot - 1)], 0.0)
    n_unique = jnp.sum(first.astype(jnp.int32))
    return slot_s, cj_s, v_out, first, n_unique


def _remote_uniq_flags(vals_phys, mask, me, NCL: int):
    """Shared core of the halo-list builders: sorted remote ids with
    first-occurrence flags."""
    remote = mask & (vals_phys // NCL != me) & (vals_phys != _SENT) & \
        (vals_phys >= 0)
    k = jnp.sort(jnp.where(remote, vals_phys, _SENT))
    uniq = jnp.concatenate([jnp.ones((1,), bool), k[1:] != k[:-1]]) & \
        (k != _SENT)
    return k, uniq


def _unique_remote(vals_phys, mask, me, NCL: int, size: int):
    """Sorted unique physical ids with owner != me (halo-list builder).
    Returns (_SENT-padded (size,) list, count)."""
    k, uniq = _remote_uniq_flags(vals_phys, mask, me, NCL)
    cnt = jnp.sum(uniq.astype(jnp.int32))
    idx = jnp.nonzero(uniq, size=size, fill_value=k.shape[0] - 1)[0]
    lst = jnp.where(jnp.arange(size) < cnt, k[idx], _SENT)
    return lst, cnt


def _per_peer_counts(list_phys, cnt, NCL: int, R: int):
    """Per-peer segment sizes of a sorted physical halo list."""
    valid = jnp.arange(list_phys.shape[0]) < cnt
    src = jnp.where(valid, list_phys // NCL, R)
    start = jnp.searchsorted(src, jnp.arange(R + 1))
    return start[1:] - start[:-1]


def _sorted_by_rid(rid, *arrs, n_sent: int):
    """Stable-sort entry arrays by row id (ShardMatrix.spmv declares
    indices_are_sorted)."""
    order = jnp.argsort(jnp.where(rid < n_sent, rid, n_sent),
                        stable=True)
    return (rid[order],) + tuple(a[order] for a in arrs)


def _take(mask, size: int, fill_idx: int):
    """Compact positions where mask holds into a (size,) index buffer."""
    cnt = jnp.sum(mask.astype(jnp.int32))
    idx = jnp.nonzero(mask, size=size, fill_value=fill_idx)[0]
    sel = jnp.arange(size) < cnt
    return idx, sel, cnt


# ---------------------------------------------------------------------------
# the three per-level phases (shard_map bodies)
# ---------------------------------------------------------------------------

def _phase_a_body(M: ShardMatrix, offsets, axis: str, max_iters: int,
                  formula: int, merge: bool, graph_values: bool):
    """Matching + root counts. Returns (agg, paired, countsA) where
    countsA = [nc_local, triples_to_peer*R, members_to_peer*R]."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    if graph_values:
        # coarse matching pass: entry values ARE the summed edge
        # weights (selectors._coarse_graph semantics)
        w = jnp.where(E.valid & (E.row_sem != E.col_sem), E.vals, 0.0)
    else:
        halo_diag = E.exchange(M.diag)
        w = _sharded_weights(E, M.diag, halo_diag, formula)
    agg, paired = _sharded_matching(E, w, active, me, offsets, axis,
                                    max_iters)
    if merge:
        agg = _sharded_merge_singletons(E, w, agg, paired, active, me,
                                        offsets)
    is_root = active & (agg == idx_sem)
    nc_local = jnp.sum(is_root.astype(jnp.int32))
    # routing budgets: triples by dest (owner of the row's root), member
    # records by owner of each vertex's root
    owner_root = _owner_of_sem(agg, offsets, R, active & (agg >= 0))
    ol = jnp.concatenate([owner_root, jnp.full((1,), R, jnp.int32)])
    dest_e = ol[jnp.minimum(E.rows, n)]
    dest_e = jnp.where(E.valid, dest_e, R)
    tri_cnt = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(dest_e, 0, R - 1)].add((dest_e < R).astype(jnp.int32))
    mem_remote = jnp.where(owner_root == me, R, owner_root)
    mem_cnt = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(mem_remote, 0, R - 1)].add(
        (mem_remote < R).astype(jnp.int32))
    counts = jnp.concatenate([nc_local[None], tri_cnt, mem_cnt])
    return agg, paired, w, counts


def _phase_b_body(M: ShardMatrix, offsets, agg, w_vals, axis: str,
                  NCL_c: int, maxq: int, maxt: int, maxm: int,
                  graph_rap: bool):
    """Numbering + cid lookup + routed RAP triples + member routing.

    graph_rap=True builds the next matching pass's weight graph (values
    = summed w) instead of the coarse operator (and skips members)."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    is_root, slot, nc_local, offsets_c = _coarse_numbering(
        agg, active, offsets, me, n, axis)
    cid_sem, cid_phys = _assign_cids(agg, active, is_root, slot,
                                     offsets, offsets_c, me, n, NCL_c,
                                     axis, R, maxq)
    owner_root = _owner_of_sem(agg, offsets, R, active & (agg >= 0))
    slot_s, cj_s, v_s, first, n_unique = _rap_triples(
        E, cid_sem, cid_phys, owner_root, me, offsets_c, NCL_c, axis, R,
        maxt, values=w_vals if graph_rap else None)
    # halo-list / map-size counts for phase C
    hlist_cnt = _count_unique_remote(cj_s, first, me, NCL_c)
    owner_cj = jnp.clip(cj_s // NCL_c, 0, R)
    n_own_u = jnp.sum((first & (owner_cj == me)).astype(jnp.int32))
    n_halo_u = jnp.sum((first & (owner_cj != me)).astype(jnp.int32))
    if graph_rap:
        mcid = jnp.full((R * maxm,), _SENT, jnp.int32)
        mgid = jnp.full((R * maxm,), _SENT, jnp.int32)
        n_p_halo = jnp.zeros((), jnp.int32)
        n_r_halo = jnp.zeros((), jnp.int32)
    else:
        # member records -> root owners (for the explicit R operator)
        gid_phys = me * n + jnp.arange(n, dtype=jnp.int32)
        dest_m = jnp.where(owner_root == me, R, owner_root)
        mcid, mgid = _route((cid_sem, gid_phys), dest_m, me, axis, R,
                            maxm, (_SENT, _SENT))
        n_p_halo = _count_unique_remote(cid_phys,
                                        active & (cid_phys >= 0), me,
                                        NCL_c)
        n_r_halo = _count_unique_remote(mgid, mcid != _SENT, me, n)
    counts = jnp.concatenate([
        nc_local[None], n_unique[None], n_own_u[None], n_halo_u[None],
        hlist_cnt[None], n_p_halo[None], n_r_halo[None]])
    return (slot_s, cj_s, v_s, cid_sem, cid_phys, mcid, mgid, counts)


def _count_unique_remote(vals_phys, mask, me, NCL: int):
    _, uniq = _remote_uniq_flags(vals_phys, mask, me, NCL)
    return jnp.sum(uniq.astype(jnp.int32))


def _phase_c_body(M: ShardMatrix, offsets, triples, cid_sem, cid_phys,
                  mcid, mgid, offsets_c, axis: str, NCL_c: int,
                  E_own: int, E_halo: int, H_c: int, mp_c: int,
                  H_p: int, mp_p: int, H_r: int, mp_r: int,
                  build_transfers: bool):
    """Assemble the coarse ShardMatrix (+ P and R transfer shards) from
    phase B's sorted triples, building the coarse halo maps on device.
    Everything row-placement derives from the per-vertex coarse ids
    (works for both the single-pass and the composed multipass path)."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    slot_s, cj_s, v_s = triples
    Etot = slot_s.shape[0]
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    nc_local = offsets_c[me + 1] - offsets_c[me]
    valid_s = slot_s < NCL_c
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (slot_s[1:] != slot_s[:-1]) | (cj_s[1:] != cj_s[:-1])]) & valid_s
    owner_cj = jnp.clip(cj_s // NCL_c, 0, R)
    # owned-column entries
    oidx, osel, _ = _take(first & (owner_cj == me), E_own, Etot - 1)
    rid_own = jnp.where(osel, slot_s[oidx], NCL_c).astype(jnp.int32)
    ci_own = jnp.where(osel, cj_s[oidx] - me * NCL_c, 0).astype(jnp.int32)
    va_own = jnp.where(osel, v_s[oidx], 0.0)
    # halo-column entries + device-built halo list and maps
    hlist, hcnt = _unique_remote(cj_s, first, me, NCL_c, H_c)
    hidx, hsel, _ = _take(first & (owner_cj != me), E_halo, Etot - 1)
    rid_halo = jnp.where(hsel, slot_s[hidx], NCL_c).astype(jnp.int32)
    ci_halo = jnp.where(
        hsel, jnp.searchsorted(hlist, cj_s[hidx]), 0).astype(jnp.int32)
    va_halo = jnp.where(hsel, v_s[hidx], 0.0)
    send_c, recv_c = _a2a_maps(hlist, hcnt, me, NCL_c, NCL_c, axis, R,
                               mp_c)
    # coarse diagonal (pad slots -> 1.0)
    isd = first & (cj_s == me * NCL_c + slot_s)
    diag = jnp.zeros((NCL_c,), v_s.dtype).at[
        jnp.where(isd, slot_s, NCL_c)].add(
        jnp.where(isd, v_s, 0.0), mode="drop")
    diag = jnp.where(jnp.arange(NCL_c) < nc_local, diag, 1.0)
    A_c = dict(rid_own=rid_own, ci_own=ci_own, va_own=va_own,
               rid_halo=rid_halo, ci_halo=ci_halo, va_halo=va_halo,
               diag=diag, halo_src=hlist, a2a_send=send_c,
               a2a_recv=recv_c)
    if not build_transfers:
        return A_c, None, None
    dt = v_s.dtype
    # P: one entry per active fine row at column cid
    owner_p = jnp.clip(cid_phys // NCL_c, 0, R)
    own_p = active & (cid_phys >= 0) & (owner_p == me)
    halo_p = active & (cid_phys >= 0) & (owner_p != me)
    ar = jnp.arange(n, dtype=jnp.int32)
    plist, pcnt = _unique_remote(cid_phys, active & (cid_phys >= 0),
                                 me, NCL_c, H_p)
    p_own = dict(rid=jnp.where(own_p, ar, n).astype(jnp.int32),
                 ci=jnp.where(own_p, cid_phys - me * NCL_c, 0
                              ).astype(jnp.int32),
                 va=jnp.where(own_p, 1.0, 0.0).astype(dt))
    p_halo = dict(rid=jnp.where(halo_p, ar, n).astype(jnp.int32),
                  ci=jnp.where(halo_p,
                               jnp.searchsorted(plist, cid_phys), 0
                               ).astype(jnp.int32),
                  va=jnp.where(halo_p, 1.0, 0.0).astype(dt))
    send_p, recv_p = _a2a_maps(plist, pcnt, me, NCL_c, NCL_c, axis, R,
                               mp_p)
    P_sh = dict(rid_own=p_own["rid"], ci_own=p_own["ci"],
                va_own=p_own["va"], rid_halo=p_halo["rid"],
                ci_halo=p_halo["ci"], va_halo=p_halo["va"],
                diag=jnp.ones((n,), dt), halo_src=plist,
                a2a_send=send_p, a2a_recv=recv_p)
    # R: rows = my coarse slots; columns = fine member vertices
    owner_f = _owner_of_sem(cid_sem, offsets_c, R,
                            active & (cid_sem >= 0))
    local_m = active & (owner_f == me)
    r_rid_o = jnp.where(local_m, cid_sem - offsets_c[me], NCL_c
                        ).astype(jnp.int32)
    r_rid_o, r_ci_o, r_va_o = _sorted_by_rid(
        r_rid_o, ar, jnp.where(local_m, 1.0, 0.0).astype(dt),
        n_sent=NCL_c)
    mvalid = mcid != _SENT
    rlist, rcnt = _unique_remote(mgid, mvalid, me, n, H_r)
    r_rid_h = jnp.where(mvalid, mcid - offsets_c[me], NCL_c
                        ).astype(jnp.int32)
    r_ci_h = jnp.where(mvalid, jnp.searchsorted(rlist, mgid), 0
                       ).astype(jnp.int32)
    r_rid_h, r_ci_h, r_va_h = _sorted_by_rid(
        r_rid_h, r_ci_h, jnp.where(mvalid, 1.0, 0.0).astype(dt),
        n_sent=NCL_c)
    send_r, recv_r = _a2a_maps(rlist, rcnt, me, n, n, axis, R, mp_r)
    R_sh = dict(rid_own=r_rid_o, ci_own=r_ci_o, va_own=r_va_o,
                rid_halo=r_rid_h, ci_halo=r_ci_h, va_halo=r_va_h,
                diag=jnp.ones((NCL_c,), dt), halo_src=rlist,
                a2a_send=send_r, a2a_recv=recv_r)
    return A_c, P_sh, R_sh


# ---------------------------------------------------------------------------
# level objects + host orchestration
# ---------------------------------------------------------------------------

class DistAMGLevel:
    """A sharded hierarchy level: transfers apply through the explicit
    P/R ShardMatrix shards in the solve-data (the same duck-typed spmv
    dispatch the solve-phase sharding uses)."""

    def __init__(self, A_sh: ShardMatrix, level_index: int,
                 offsets: Optional[np.ndarray] = None):
        self.A = A_sh
        self.level_index = level_index
        self.smoother = None
        # semantic row-offset vector of this level's numbering (used by
        # the sharded coloring to hash semantic ids)
        self.offsets = offsets

    def restrict(self, data, r):
        from ..ops.spmv import spmv
        return spmv(data["R"], r)

    def prolongate(self, data, xc):
        from ..ops.spmv import spmv
        return spmv(data["P"], xc)

    # Cycle-fusion hooks: none needed. The cycle consults
    # `supports_fusion` through the CLASS (amg/cycles.py _fusion_caps)
    # and this class defines no capability surface, so the plain
    # smooth_residual -> restrict / prolongate -> smooth compose runs —
    # which IS the fused distributed path: the halo-folded per-shard
    # kernel (distributed/fused.py, attached as the smoother's
    # "dist_fused" payload) dispatches inside smooth/smooth_residual
    # (ops/smooth.fused_smooth), and the sharded R/P's owned-aggregate
    # segment sums are shard-local by construction of the partition
    # (remote members arrive through R's own halo map). The PR-5
    # AttributeError class of bug is structurally impossible: an
    # unimplemented hook is never invoked.


class ShardedConsolidationLevel:
    """Boundary between the sharded levels and the replicated tail
    (glue_matrices endpoint, include/distributed/glue.h:200): restrict
    gathers the padded block-aligned coarse vector and compacts it to
    the semantic (single-device) numbering the replicated tail was
    built in; prolongate re-expands."""

    def __init__(self, level, axis: str, offsets_c: np.ndarray,
                 NCL_c: int):
        self._level = level
        self._axis = axis
        self._offsets = jnp.asarray(offsets_c, jnp.int32)
        self._NCL = NCL_c
        self._nc_g = int(offsets_c[-1])
        # semantic -> physical gather map (static, tiny)
        ranks = np.searchsorted(offsets_c, np.arange(self._nc_g),
                                side="right") - 1
        self._sem2phys = jnp.asarray(
            ranks * NCL_c + (np.arange(self._nc_g) - offsets_c[ranks]),
            jnp.int32)

    def __getattr__(self, name):
        return getattr(self._level, name)

    def restrict(self, data, r):
        bc_local = self._level.restrict(data, r)          # (NCL_c,)
        bc_phys = jax.lax.all_gather(bc_local, self._axis, tiled=True)
        return bc_phys[self._sem2phys]                    # semantic

    def prolongate(self, data, xc):
        me = jax.lax.axis_index(self._axis)
        k = jnp.arange(self._NCL)
        lo = self._offsets[me]
        cnt = self._offsets[me + 1] - lo
        xp = jnp.concatenate([xc, jnp.zeros((1,), xc.dtype)])
        xc_local = jnp.where(
            k < cnt, xp[jnp.clip(lo + k, 0, self._nc_g)], 0.0)
        return self._level.prolongate(data, xc_local)

    # Cycle-fusion hooks: none — and none may be ADDED via __getattr__
    # delegation: the wrapped level's hooks would finish with ITS
    # transfers (the shard-local R/P), skipping this wrapper's
    # gather/compact into the replicated tail's numbering. The cycle's
    # class-resolved capability check (amg/cycles.py _fusion_caps)
    # guarantees the delegation is never consulted; the plain compose
    # runs, the smoother's "dist_fused" dispatch fuses the sweeps, and
    # the replicated tail levels below the boundary feed the
    # single-chip VMEM coarse-tail megakernel
    # (ops/smooth.coarse_tail_cycle) unchanged.


def _mk_shard(fields: dict, n_global: int, n_local: int,
              n_local_cols: int, n_halo: int, R: int, axis: str
              ) -> ShardMatrix:
    return ShardMatrix(
        rid_own=fields["rid_own"], ci_own=fields["ci_own"],
        va_own=fields["va_own"], rid_halo=fields["rid_halo"],
        ci_halo=fields["ci_halo"], va_halo=fields["va_halo"],
        diag=fields["diag"], halo_src=fields["halo_src"],
        send_prev=None, send_next=None, recv_prev=None, recv_next=None,
        a2a_send=fields["a2a_send"], a2a_recv=fields["a2a_recv"],
        n_global=n_global, n_local=n_local, n_local_cols=n_local_cols,
        n_halo=n_halo, n_ranks=R, axis_name=axis, exchange_mode="a2a")


def _smoother_data(name: str, M: ShardMatrix, solver, mesh=None,
                   axis=None, offsets=None):
    """Row-partitioned smoother solve-data from stacked shard fields
    (JACOBI dinv; JACOBI_L1 dinv with halo-inclusive off-diagonal L1
    sums — solver._dinv_l1 semantics; MULTICOLOR_DILU/GS via the
    sharded JPL coloring + per-color halo-exchanging Einv recurrence)."""
    if name in ("NOSOLVER", "DUMMY"):
        return {"A": M}
    d = M.diag

    def dinv_of(dd):
        safe = jnp.where(dd == 0, 1.0, dd)
        return jnp.where(dd == 0, 0.0, 1.0 / safe)

    if name in ("JACOBI", "BLOCK_JACOBI"):
        return {"A": M, "dinv": jax.jit(dinv_of)(d)}
    if name in ("MULTICOLOR_DILU", "MULTICOLOR_GS"):
        colors_s, nc = sharded_coloring(M, mesh, axis, offsets)
        # the solve-phase color sweeps read num_colors off the solver
        # (solver_setup never runs — there is no global matrix)
        solver.num_colors = nc
        solver.row_colors = None
        if name == "MULTICOLOR_GS":
            return {"A": M, "dinv": jax.jit(dinv_of)(d),
                    "colors": colors_s}
        Einv = _sharded_dilu_einv(M, mesh, axis, colors_s, nc)
        return {"A": M, "Einv": Einv, "colors": colors_s}
    if name == "CHEBYSHEV_POLY":
        # taus need only the global Gershgorin bound: per-shard absolute
        # row sums (owned + halo entries are all shard-local), global
        # max across shards (polynomial.py solver_setup semantics)
        n_local = M.n_local

        @jax.jit
        def lam_of(vo, ro, vh, rh):
            def one(vo, ro, vh, rh):
                s = jax.ops.segment_sum(jnp.abs(vo), ro,
                                        num_segments=n_local) + \
                    jax.ops.segment_sum(jnp.abs(vh), rh,
                                        num_segments=n_local)
                return jnp.max(s)
            return jnp.max(jax.vmap(one)(vo, ro, vh, rh))

        from ..solvers.polynomial import chebyshev_poly_coeffs
        lam = lam_of(M.va_own, M.rid_own, M.va_halo, M.rid_halo)
        taus = jnp.asarray(chebyshev_poly_coeffs(solver.order),
                           M.dtype) / lam.astype(M.dtype)
        R = M.rid_own.shape[0]
        return {"A": M,
                "taus": jnp.broadcast_to(taus[None], (R,) + taus.shape)}
    if name == "JACOBI_L1":
        n_local = M.n_local

        @jax.jit
        def l1(vo, ro, co, vh, rh, dd):
            def one(vo, ro, co, vh, rh, dd):
                off = jnp.where((co == ro) & (ro < n_local), 0.0,
                                jnp.abs(vo))
                s = jax.ops.segment_sum(off, ro, num_segments=n_local) \
                    + jax.ops.segment_sum(jnp.abs(vh), rh,
                                          num_segments=n_local)
                return dinv_of(dd + jnp.sign(dd) * s)
            return jax.vmap(one)(vo, ro, co, vh, rh, dd)

        return {"A": M,
                "dinv": l1(M.va_own, M.rid_own, M.ci_own, M.va_halo,
                           M.rid_halo, d)}
    raise BadParametersError(
        f"sharded setup: smoother {name} not row-partitionable")


# ---------------------------------------------------------------------------
# sharded coloring + strong smoothers (MULTICOLOR_DILU / MULTICOLOR_GS)
# ---------------------------------------------------------------------------

def _hash_w_sem(sem_ids, rnd):
    """ops.coloring._hash_w on explicit semantic ids with a traced
    round (identical uint32 math, so the sharded JPL fixed point makes
    the same per-round decisions as the single-device one)."""
    i = sem_ids.astype(jnp.uint32)
    h = (i + rnd.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)) * \
        jnp.uint32(2654435761)
    h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
    return h ^ (h >> 13)


def sharded_coloring(M: ShardMatrix, mesh, axis: str, offsets_np,
                     max_rounds: int = 64):
    """Per-shard Jones-Plassmann-Luby MIN_MAX coloring with a halo
    color-state exchange each round — the boundary_coloring=SYNC_COLORS
    policy (src/core.cu:353-354; min_max.cu): boundary rows always see
    their cross-rank neighbors' true color state, so the coloring is
    globally proper. Hash weights are keyed on SEMANTIC global ids,
    which makes the result the EXACT coloring ops.coloring._jpl_min_max
    computes on the assembled matrix — bit-identical colors, hence
    bit-identical DILU factors and iteration parity with the
    single-device path. Assumes a pattern-symmetric matrix (the sharded
    envelope's value-symmetry probe already guarantees it).

    Returns (stacked row colors (R, n_local) int32, num_colors)."""
    n_local = M.n_local
    offsets = jnp.asarray(offsets_np, jnp.int32)
    pspec = jax.tree.map(lambda _: P(axis), M)

    def init_body(Ms):
        Mx = jax.tree.map(lambda a: a[0], Ms)
        offd = (Mx.ci_own != Mx.rid_own).astype(jnp.int32)
        has = jax.ops.segment_max(offd, Mx.rid_own,
                                  num_segments=n_local,
                                  indices_are_sorted=True)
        if Mx.rid_halo.shape[0]:
            has = jnp.maximum(has, jax.ops.segment_max(
                jnp.ones_like(Mx.rid_halo), Mx.rid_halo,
                num_segments=n_local, indices_are_sorted=True))
        # rows with no neighbors (and last-rank pad rows, which have no
        # entries at all) take color 0 immediately
        return jnp.where(has > 0, jnp.int32(-1), jnp.int32(0))[None]

    def round_body(Ms, colors_s, rnd, nc0):
        Mx = jax.tree.map(lambda a: a[0], Ms)
        colors = colors_s[0]
        me = jax.lax.axis_index(axis)
        sem = offsets[me] + jnp.arange(n_local, dtype=jnp.int32)
        w = _hash_w_sem(sem, rnd)
        offd = Mx.ci_own != Mx.rid_own

        def extract(colors, ncol, maximize):
            un = colors < 0
            fill = jnp.uint32(0) if maximize else jnp.uint32(0xFFFFFFFF)
            wm = jnp.where(un, w, fill)
            seg = jax.ops.segment_max if maximize else jax.ops.segment_min
            nbest = seg(jnp.where(offd, wm[Mx.ci_own], fill), Mx.rid_own,
                        num_segments=n_local, indices_are_sorted=True)
            if Mx.rid_halo.shape[0]:
                halo_w = Mx.exchange_halo(wm)
                hp = halo_w if Mx.n_halo else jnp.full((1,), fill,
                                                       jnp.uint32)
                nb2 = seg(hp[Mx.ci_halo], Mx.rid_halo,
                          num_segments=n_local, indices_are_sorted=True)
                nbest = jnp.maximum(nbest, nb2) if maximize \
                    else jnp.minimum(nbest, nb2)
            take = un & ((w > nbest) if maximize else (w < nbest))
            return jnp.where(take, ncol, colors)

        colors = extract(colors, nc0, True)
        un1 = jax.lax.psum(jnp.sum((colors < 0).astype(jnp.int32)), axis)
        colors = extract(colors, nc0 + 1, False)
        un2 = jax.lax.psum(jnp.sum((colors < 0).astype(jnp.int32)), axis)
        return colors[None], jnp.stack([un1, un2])

    def fin_body(colors_s, nxt):
        c = jnp.where(colors_s[0] < 0, nxt, colors_s[0])
        num = jax.lax.pmax(jnp.max(c), axis) + 1
        return c[None], num

    init_fn = jax.jit(shard_map(init_body, mesh=mesh, in_specs=(pspec,),
                                out_specs=P(axis), check_vma=False))
    step_fn = jax.jit(shard_map(
        round_body, mesh=mesh, in_specs=(pspec, P(axis), P(), P()),
        out_specs=(P(axis), P()), check_vma=False))
    fin_fn = jax.jit(shard_map(
        fin_body, mesh=mesh, in_specs=(P(axis), P()),
        out_specs=(P(axis), P()), check_vma=False))

    colors_s = init_fn(M)
    next_color = 0
    for rnd in range(max_rounds):
        colors_s, cnt = step_fn(M, colors_s, jnp.uint32(rnd),
                                jnp.int32(next_color))
        after_max, after_min = (int(v) for v in np.asarray(cnt))
        if after_max == 0:
            next_color += 1          # min phase was a no-op
            break
        next_color += 2
        if after_min == 0:
            break
    colors_s, num = fin_fn(colors_s, jnp.int32(next_color))
    return colors_s, int(num)


def _sharded_dilu_einv(M: ShardMatrix, mesh, axis: str, colors_s,
                       num_colors: int):
    """Per-shard DILU E^{-1} recurrence color-by-color with a halo Einv
    exchange per color (multicolor_dilu_solver.cu:650-810 setup). The
    reverse-edge value a_ji equals the stored a_ij because the sharded
    envelope admits only (probe-verified) value-symmetric matrices —
    the transpose lookup the single-device _match_transpose performs
    collapses to the owned value. Einv_j is zero until color_j is
    processed, so the color_j < color_i predicate falls out for free,
    exactly as in the single-device setup."""
    n_local = M.n_local
    pspec = jax.tree.map(lambda _: P(axis), M)

    def body(Ms, cs):
        Mx = jax.tree.map(lambda a: a[0], Ms)
        colors = cs[0]
        d = Mx.diag
        Einv = jnp.zeros((n_local,), Mx.va_own.dtype)
        for c in range(num_colors):
            e = jax.ops.segment_sum(
                Mx.va_own * Einv[Mx.ci_own] * Mx.va_own, Mx.rid_own,
                num_segments=n_local, indices_are_sorted=True)
            if Mx.rid_halo.shape[0]:
                halo_E = Mx.exchange_halo(Einv)
                hp = halo_E if Mx.n_halo else jnp.zeros((1,), Einv.dtype)
                e = e + jax.ops.segment_sum(
                    Mx.va_halo * hp[Mx.ci_halo] * Mx.va_halo,
                    Mx.rid_halo, num_segments=n_local,
                    indices_are_sorted=True)
            blk = d - e
            new = jnp.where(blk == 0, 0.0, 1.0 / jnp.where(blk == 0, 1.0,
                                                           blk))
            Einv = jnp.where(colors == c, new, Einv)
        return Einv[None]

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(pspec, P(axis)),
                           out_specs=P(axis), check_vma=False))
    return fn(M, colors_s)


# MIN_MAX-equivalent schemes the sharded coloring reproduces exactly.
# GREEDY_RECOLOR is deliberately excluded: its single-device form adds
# a recoloring pass on top of MIN_MAX (ops/coloring.py), which the
# sharded JPL does not reproduce — it falls back to the global setup.
_SHARDED_COLORINGS = {"MIN_MAX", "PARALLEL_GREEDY", "LOCALLY_DOWNWIND"}

_SHARDED_SMOOTHERS = {"JACOBI", "BLOCK_JACOBI", "JACOBI_L1", "NOSOLVER",
                      "DUMMY", "CHEBYSHEV_POLY", "MULTICOLOR_DILU",
                      "MULTICOLOR_GS"}
# selector -> matching passes. MULTI_PAIRWISE's entry marks membership
# only; its real pass count comes from cfg aggregation_passes.
_SHARDED_SELECTORS = {"SIZE_2": 1, "PARALLEL_GREEDY": 1, "SIZE_4": 2,
                      "SIZE_8": 3, "MULTI_PAIRWISE": 2}


def sharded_eligible(amg, A) -> Optional[str]:
    """None if the sharded setup supports this AMG config; else the
    reason string (callers fall back to the global-setup path)."""
    if amg.algorithm == "CLASSICAL":
        # sharded classical (setup_classical.py): PMIS + D1 + AHAT only
        sel = str(amg.cfg.get("selector", amg.scope)).upper()
        if sel != "PMIS":
            return f"classical selector {sel} not sharded (PMIS only)"
        interp = str(amg.cfg.get("interpolator", amg.scope)).upper()
        if interp != "D1":
            return (f"classical interpolator {interp} not sharded "
                    "(D1 only)")
        if str(amg.cfg.get("strength", amg.scope)).upper() != "AHAT":
            return "classical strength != AHAT not sharded"
        if int(amg.cfg.get("aggressive_levels", amg.scope)) > 0:
            return "aggressive coarsening uses the global setup"
        # interp_max_elements / interp_truncation_factor are supported:
        # truncation is a per-row top-k on the D1 slot vectors
        # (setup_classical._truncate_slots, src/truncate.cu semantics)
    elif amg.algorithm != "AGGREGATION":
        return "energymin algorithms use the global setup"
    else:
        sel = str(amg.cfg.get("selector", amg.scope)).upper()
        if sel not in _SHARDED_SELECTORS:
            return (f"selector {sel} not sharded (geo/dummy use global "
                    "setup)")
    if A.is_block:
        return "block systems use the global setup"
    if amg.cycle_name in ("CG", "CGF"):
        return "K-cycles use the global setup"
    pairs = [amg.cfg.get_solver("smoother", amg.scope)]
    if int(amg.cfg.get("fine_levels", amg.scope)) >= 0:
        pairs.append(amg.cfg.get_solver("fine_smoother", amg.scope))
        pairs.append(amg.cfg.get_solver("coarse_smoother", amg.scope))
    bad = {n.upper() for n, _ in pairs} - _SHARDED_SMOOTHERS
    if bad:
        return f"smoother(s) {sorted(bad)} not row-partitionable"
    for n, scp in pairs:
        if n.upper() not in ("MULTICOLOR_DILU", "MULTICOLOR_GS"):
            continue
        scheme = str(amg.cfg.get("matrix_coloring_scheme", scp)).upper()
        if scheme not in _SHARDED_COLORINGS:
            return (f"coloring scheme {scheme} has no sharded analog "
                    "(MIN_MAX-family only)")
        if int(amg.cfg.get("coloring_level", scp)) != 1:
            return "sharded coloring supports coloring_level=1 only"
    if float(amg.cfg.get("error_scaling", amg.scope)):
        return "error_scaling uses the global setup"
    return None


def _wrap(mesh, axis, in_tree, fn):
    pspec = jax.tree.map(lambda _: P(axis), in_tree)
    mapped = shard_map(fn, mesh=mesh, in_specs=(pspec,),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(mapped)


def _gather_compact(M: ShardMatrix, offsets: np.ndarray):
    """Gather a (small) stacked shard level to the host and compact it
    to the semantic contiguous numbering — the matrix the single-device
    setup would hold at this level. Runs once per solve setup at the
    consolidation boundary; size is bounded by one shard's budget."""
    from ..matrix import CsrMatrix
    R = offsets.shape[0] - 1
    NCL = M.n_local
    rid_o = np.asarray(M.rid_own)
    ci_o = np.asarray(M.ci_own)
    va_o = np.asarray(M.va_own)
    rid_h = np.asarray(M.rid_halo)
    ci_h = np.asarray(M.ci_halo)
    va_h = np.asarray(M.va_halo)
    hsrc = np.asarray(M.halo_src)
    rows, cols, vals = [], [], []
    for r in range(R):
        vo = rid_o[r] < NCL
        rows.append(offsets[r] + rid_o[r][vo])
        cols.append(offsets[r] + ci_o[r][vo])
        vals.append(va_o[r][vo])
        vh = rid_h[r] < NCL
        rows.append(offsets[r] + rid_h[r][vh])
        ph = hsrc[r][np.clip(ci_h[r][vh], 0, hsrc.shape[1] - 1)]
        cols.append(offsets[np.clip(ph // NCL, 0, R - 1)] + ph % NCL)
        vals.append(va_h[r][vh])
    rows = np.concatenate(rows).astype(np.int64)
    cols = np.concatenate(cols).astype(np.int64)
    vals = np.concatenate(vals)
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    first = np.concatenate([[True], (rows[1:] != rows[:-1])
                            | (cols[1:] != cols[:-1])])
    seg = np.cumsum(first) - 1
    vsum = np.zeros(int(seg[-1]) + 1 if seg.size else 0, vals.dtype)
    np.add.at(vsum, seg, vals)
    rows_u, cols_u = rows[first], cols[first]
    n = int(offsets[-1])
    counts = np.bincount(rows_u, minlength=n)
    row_offsets = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    return CsrMatrix.from_scipy_like(
        row_offsets, cols_u.astype(np.int32), jnp.asarray(vsum), n, n)


def _smoother_assignment(amg):
    cfg, scope = amg.cfg, amg.scope
    sm = cfg.get_solver("smoother", scope)
    fine_levels = int(cfg.get("fine_levels", scope))
    fs = cfg.get_solver("fine_smoother", scope)
    cs2 = cfg.get_solver("coarse_smoother", scope)

    def assign(k: int):
        if fine_levels < 0:
            return sm
        return fs if k < fine_levels else cs2
    return assign


def build_sharded_hierarchy(amg, shard_A: ShardMatrix, mesh, axis: str,
                            global_A=None):
    """Build the distributed AMG hierarchy per-shard (no global level is
    ever materialized above the consolidation boundary). Mutates `amg`
    (levels, coarse solver) and returns the stacked solve-data pytree
    {"levels": [...], "coarse": ...}, or None when the problem is too
    small for even one sharded level (caller falls back to the global
    setup path). `global_A`, when the caller holds it (the
    non-pieces upload path), enables the halo-folded fused-smoother
    payload on the finest level (distributed/fused.py) — its DIA slabs
    are the only global view this build ever touches, and coarse
    levels stay strictly per-shard."""
    from ..solvers.base import make_solver
    from .amg import _replicate
    cfg, scope = amg.cfg, amg.scope
    R = int(mesh.devices.size)
    max_it = int(cfg.get("max_matching_iterations", scope))
    merge = bool(int(cfg.get("merge_singletons", scope)))
    formula = int(cfg.get("weight_formula", scope))
    n_local0 = shard_A.n_local
    n_g0 = shard_A.n_global
    # consolidation boundary: by default a coarse level consolidates to
    # the replicated tail when its global size fits one shard's initial
    # budget; matrix_consolidation_lower_threshold (the reference's
    # consolidation knob, an AVERAGE-rows-per-rank threshold) overrides
    # it so deeper levels stay sharded
    thr = int(cfg.get("matrix_consolidation_lower_threshold", scope))
    consolidate_at = thr * R if thr > 0 else n_local0
    offsets = np.minimum(np.arange(R + 1) * n_local0, n_g0
                         ).astype(np.int32)
    M = shard_A
    levels, levels_data, ncl_last = [], [], None
    offsets_last = None
    lvl = 0
    if amg.algorithm == "CLASSICAL":
        from .setup_classical import run_classical_levels
        res = run_classical_levels(amg, mesh, axis, M, offsets, R,
                                   consolidate_at)
        if res is None:
            return None
        (levels, levels_data, M, offsets, lvl, offsets_last,
         ncl_last) = res
        return _finish_sharded(amg, mesh, axis, M, offsets, lvl,
                               levels, levels_data, offsets_last,
                               ncl_last, R, global_A=global_A)
    sel = str(cfg.get("selector", scope)).upper()
    passes = _SHARDED_SELECTORS.get(sel, 1)
    if sel == "MULTI_PAIRWISE":
        passes = max(int(cfg.get("aggregation_passes", scope)), 1)
        if int(cfg.get("notay_weights", scope)):
            formula = 1

    def runA(Ms, offs_np, graph):
        offs = jnp.asarray(offs_np)

        def fa(Mx, _offs=offs, _g=graph):
            out = _phase_a_body(Mx.local(), _offs, axis, max_it,
                                formula, merge, _g)
            return jax.tree.map(lambda a: a[None], out)
        return _wrap(mesh, axis, Ms, fa)(Ms)

    def runB(Ms, offs_np, agg_s, w_s, NCL, mq, mt, graph_rap):
        offs = jnp.asarray(offs_np)

        def fb(args, _offs=offs):
            Mx, a_, w_ = args
            out = _phase_b_body(Mx.local(), _offs, a_[0], w_[0], axis,
                                NCL, mq, mt, mq, graph_rap)
            return jax.tree.map(lambda a: a[None], out)
        return _wrap(mesh, axis, (Ms, agg_s, w_s), fb)((Ms, agg_s, w_s))

    def runC(Ms, offs_np, offsets_c_np, triples, cid_sem_s, cid_phys_s,
             mcid_s, mgid_s, sizes, build_transfers):
        offs = jnp.asarray(offs_np)
        offs_c = jnp.asarray(offsets_c_np)
        E_own, E_halo, H_c, H_p, H_r = sizes

        def fc(args, _offs=offs, _offs_c=offs_c):
            (Mx, s1, c1, v1, cs, cp, mc, mg) = args
            out = _phase_c_body(
                Mx.local(), _offs, (s1[0], c1[0], v1[0]), cs[0], cp[0],
                mc[0], mg[0], _offs_c, axis, _NCL_of(offsets_c_np),
                E_own, E_halo, H_c, max(H_c, 1), H_p, max(H_p, 1),
                H_r, max(H_r, 1), build_transfers)
            return jax.tree.map(
                lambda a: a[None] if a is not None else None, out)
        argsC = (Ms, *triples, cid_sem_s, cid_phys_s, mcid_s, mgid_s)
        return _wrap(mesh, axis, argsC, fc)(argsC)

    def _NCL_of(offsets_c_np):
        return max(int(np.diff(offsets_c_np).max()), 1)

    while True:
        n = int(offsets[-1])
        if (lvl + 1 >= amg.max_levels or n <= max(amg.min_coarse_rows, 1)
                or n < amg.min_fine_rows
                or (n <= amg.dense_lu_num_rows and lvl > 0)):
            break
        if lvl > 0 and n <= consolidate_at:
            break      # tail fits the consolidation budget
        # -- pass 1: matching on this level's matrix --------------------
        agg, paired, w, countsA = runA(M, offsets, False)
        ca = np.asarray(countsA)
        nc_locals = ca[:, 0].astype(np.int64)
        nc_g = int(nc_locals.sum())
        if nc_g <= 0 or nc_g >= n:
            break
        if passes == 1 and (n / max(nc_g, 1)) < amg.coarsen_threshold:
            # multipass selectors apply the threshold to the COMPOSED
            # ratio below (hierarchy._build_levels semantics)
            break
        NCL_c = max(int(nc_locals.max()), 1)
        maxt = max(int(ca[:, 1:1 + R].max()), 1)
        maxm = max(int(ca[:, 1 + R:1 + 2 * R].max()), 1)
        outB = runB(M, offsets, agg, w, NCL_c, maxm, maxt,
                    graph_rap=(passes > 1))
        (slot_s, cj_s, v_s, cid_sem, cid_phys, mcid, mgid,
         countsB) = outB
        cb = np.asarray(countsB)
        sizes = tuple(max(int(cb[:, i].max()), 1) for i in
                      (2, 3, 4, 5, 6))
        offsets_c = np.concatenate(
            [[0], np.cumsum(nc_locals)]).astype(np.int32)
        # -- passes 2..P: matching on the coarse weight graph -----------
        if passes > 1:
            G_f, _, _ = runC(M, offsets, offsets_c,
                             (slot_s, cj_s, v_s), cid_sem, cid_phys,
                             mcid, mgid, sizes, False)
            G = _mk_shard(G_f, R * NCL_c, NCL_c, NCL_c, sizes[2], R,
                          axis)
            offs_g = offsets_c
            cid_fine = cid_sem          # per-FINE-vertex coarse id
            for p in range(2, passes + 1):
                aggp, pairedp, wp, countsAp = runA(G, offs_g, True)
                cap = np.asarray(countsAp)
                ncl_p = cap[:, 0].astype(np.int64)
                if int(ncl_p.sum()) <= 0:
                    break               # pass made no progress
                NCLp = max(int(ncl_p.max()), 1)
                mtp = max(int(cap[:, 1:1 + R].max()), 1)
                mmp = max(int(cap[:, 1 + R:1 + 2 * R].max()), 1)
                outBp = runB(G, offs_g, aggp, wp, NCLp, mmp, mtp,
                             graph_rap=True)
                (gs, gc, gv, Tp, _Tphys, _mc, _mg, countsBp) = outBp
                # compose: fine vertex -> its pass-p coarse id
                offs_gj = jnp.asarray(offs_g)

                def fcnt(args, _o=offs_gj):
                    c_, = args
                    return _compose_counts_body(c_[0], _o, axis)[None]
                qc = np.asarray(_wrap(mesh, axis, (cid_fine,), fcnt)(
                    (cid_fine,)))
                maxq = max(int(qc.max()), 1)

                def fcomp(args, _o=offs_gj, _mq=maxq):
                    c_, t_ = args
                    return _compose_body(c_[0], t_[0], _o, axis,
                                         _mq)[None]
                cid_fine = _wrap(mesh, axis, (cid_fine, Tp), fcomp)(
                    (cid_fine, Tp))
                offsets_c = np.concatenate(
                    [[0], np.cumsum(ncl_p)]).astype(np.int32)
                nc_locals = ncl_p
                if p < passes:
                    cbp = np.asarray(countsBp)
                    sizes_p = tuple(max(int(cbp[:, i].max()), 1)
                                    for i in (2, 3, 4, 5, 6))
                    G_f, _, _ = runC(G, offs_g, offsets_c,
                                     (gs, gc, gv), Tp, _Tphys, _mc,
                                     _mg, sizes_p, False)
                    G = _mk_shard(G_f, R * NCLp, NCLp, NCLp,
                                  sizes_p[2], R, axis)
                offs_g = offsets_c
            nc_g = int(nc_locals.sum())
            if nc_g >= n or (n / max(nc_g, 1)) < amg.coarsen_threshold:
                break
            NCL_c = max(int(np.diff(offsets_c).max()), 1)  # composed
            # -- final RAP on the fine matrix with composed cids --------
            offs_j = jnp.asarray(offsets)
            offs_cj = jnp.asarray(offsets_c)

            # per-dest budgets for the final routing
            def ffin(args, _o=offs_j, _oc=offs_cj):
                Mx, c_ = args
                return _final_route_counts(Mx.local(), _o, c_[0], _oc,
                                           axis)[None]
            fc2 = np.asarray(_wrap(mesh, axis, (M, cid_fine), ffin)(
                (M, cid_fine)))
            maxt2 = max(int(fc2[:, :R].max()), 1)
            maxm2 = max(int(fc2[:, R:].max()), 1)

            def fb2(args, _o=offs_j, _oc=offs_cj, _NCL=NCL_c,
                    _mt=maxt2, _mm=maxm2):
                Mx, c_ = args
                out = _phase_b2_full(Mx.local(), _o, c_[0], _oc, axis,
                                     _NCL, _mt, _mm)
                return jax.tree.map(lambda a: a[None], out)
            outB2 = _wrap(mesh, axis, (M, cid_fine), fb2)((M, cid_fine))
            (slot_s, cj_s, v_s, cid_phys2, mcid, mgid, countsB2) = outB2
            cid_sem = cid_fine
            cid_phys = cid_phys2
            cb2 = np.asarray(countsB2)
            sizes = tuple(max(int(cb2[:, i].max()), 1) for i in
                          (2, 3, 4, 5, 6))
        A_c_f, P_f, R_f = runC(M, offsets, offsets_c,
                               (slot_s, cj_s, v_s), cid_sem, cid_phys,
                               mcid, mgid, sizes, True)
        NCL_c = max(int(np.diff(offsets_c).max()), 1)  # final numbering
        A_c = _mk_shard(A_c_f, R * NCL_c, NCL_c, NCL_c, sizes[2], R,
                        axis)
        P_sh = _mk_shard(P_f, n_g0, M.n_local, NCL_c, sizes[3], R, axis)
        R_sh = _mk_shard(R_f, R * NCL_c, NCL_c, M.n_local, sizes[4], R,
                         axis)
        level = DistAMGLevel(M, lvl, offsets=np.asarray(offsets))
        levels.append(level)
        levels_data.append({"A": M, "P": P_sh, "R": R_sh})
        offsets_last, ncl_last = offsets_c, NCL_c
        M, offsets = A_c, offsets_c
        lvl += 1
    if not levels:
        return None
    return _finish_sharded(amg, mesh, axis, M, offsets, lvl, levels,
                           levels_data, offsets_last, ncl_last, R,
                           global_A=global_A)


def _finish_sharded(amg, mesh, axis, M, offsets, lvl, levels,
                    levels_data, offsets_last, ncl_last, R,
                    global_A=None):
    """Shared tail of the sharded build (aggregation and classical):
    gather + compact the consolidation-boundary level, build the
    replicated tail with the existing global setup, attach smoothers."""
    from ..solvers.base import make_solver
    from .amg import _replicate
    cfg, scope = amg.cfg, amg.scope
    # ---- replicated tail: gather + compact + existing global setup ----
    A_tail = _gather_compact(M, offsets).init()
    amg.levels = list(levels)
    # this function owns the smoother assignment for every level (incl.
    # the replicated tail below) — suppress the hierarchy's per-level
    # inline attach so tail smoothers are not set up twice
    amg._defer_smoothers = True
    try:
        amg._build_levels(A_tail, lvl)
    finally:
        amg._defer_smoothers = False
    assign = _smoother_assignment(amg)
    boundary = len(levels)
    for k, lv in enumerate(levels):
        name, scp = assign(k)
        lv.smoother = make_solver(name, cfg, scp)
        lv.smoother._owns_scaling = False
        # duck-typed operator view: color-sweep smoothers read static
        # metadata (is_block, block_dimx) off self.A at trace time
        lv.smoother.A = levels_data[k]["A"]
        levels_data[k]["smoother"] = _smoother_data(
            name.upper(), levels_data[k]["A"], lv.smoother,
            mesh=mesh, axis=axis, offsets=lv.offsets)
    # halo-folded fused payload for the FINEST level (its global DIA
    # operator is the caller's upload; coarse levels are COO-built
    # per-shard with no DIA view and keep the unfused path)
    if global_A is not None and levels:
        from .fused import attach_shard_fused, fusion_gates
        # cheap gates FIRST: the dinv materialization below is a full
        # device->host pull, wasted on every knob=0 / unfused-runtime
        # setup if done unconditionally
        if fusion_gates(cfg, scope, levels[0].smoother):
            smd0 = levels_data[0]["smoother"]
            dinv_src = smd0.get("dinv")
            dinv_g = None
            if dinv_src is not None:
                # thunk + dinv_key: the flatten is a full device->host
                # pull, deferred past the memo check (keyed on the
                # stacked source array's identity — a slice would be a
                # fresh object every setup) so repeated setups on the
                # same values transfer nothing
                dinv_g = lambda: np.asarray(dinv_src).reshape(-1)[
                    : global_A.num_rows]
            attach_shard_fused(smd0, global_A, levels[0].smoother, R,
                               levels_data[0]["A"].n_local, cfg, scope,
                               dinv_global=dinv_g, dinv_key=dinv_src)
    tail_data = []
    for k in range(boundary, len(amg.levels)):
        lv = amg.levels[k]
        name, scp = assign(k)
        lv.smoother = make_solver(name, cfg, scp)
        lv.smoother._owns_scaling = False
        if getattr(lv.smoother, "needs_cf_map", False) and \
                getattr(lv, "cf_map", None) is not None:
            lv.smoother.set_cf_map(lv.cf_map)
        lv.smoother.setup(lv.A)
        tail_data.append(_replicate(lv.level_data(), R))
    cs_name, cs_scope = cfg.get_solver("coarse_solver", scope)
    amg.coarse_solver = make_solver(cs_name, cfg, cs_scope)
    amg.coarse_solver._owns_scaling = False
    amg.coarse_solver.setup(amg.coarsest_A)
    amg.num_levels = len(amg.levels) + 1
    coarse_data = _replicate(amg.coarse_solver.solve_data(), R)
    # wrap the last sharded level: gather/compact into the tail's space
    amg.levels[boundary - 1] = ShardedConsolidationLevel(
        levels[-1], axis, offsets_last, ncl_last)
    return {"levels": levels_data + tail_data, "coarse": coarse_data}


# ---------------------------------------------------------------------------
# multipass (SIZE_4 / SIZE_8 / MULTI_PAIRWISE) support: matching repeats
# on the coarse weight graph, composed cids drive one final RAP
# ---------------------------------------------------------------------------

def _phase_b2_body(M: ShardMatrix, offsets, cid_sem, cid_phys,
                   offsets_c, axis: str, NCL_c: int, maxt: int,
                   maxm: int):
    """RAP + member routing from PRE-COMPOSED per-vertex coarse ids
    (the multipass path: ids come from matching rounds on coarse weight
    graphs, not from this level's own aggregate roots)."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    owner_final = _owner_of_sem(cid_sem, offsets_c, R,
                                active & (cid_sem >= 0))
    slot_s, cj_s, v_s, first, n_unique = _rap_triples(
        E, cid_sem, cid_phys, owner_final, me, offsets_c, NCL_c, axis,
        R, maxt)
    hlist_cnt = _count_unique_remote(cj_s, first, me, NCL_c)
    owner_cj = jnp.clip(cj_s // NCL_c, 0, R)
    n_own_u = jnp.sum((first & (owner_cj == me)).astype(jnp.int32))
    n_halo_u = jnp.sum((first & (owner_cj != me)).astype(jnp.int32))
    gid_phys = me * n + jnp.arange(n, dtype=jnp.int32)
    dest_m = jnp.where(owner_final == me, R, owner_final)
    mcid, mgid = _route((cid_sem, gid_phys), dest_m, me, axis, R, maxm,
                        (_SENT, _SENT))
    n_p_halo = _count_unique_remote(cid_phys, active & (cid_phys >= 0),
                                    me, NCL_c)
    n_r_halo = _count_unique_remote(mgid, mcid != _SENT, me, n)
    counts = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), n_unique[None], n_own_u[None],
        n_halo_u[None], hlist_cnt[None], n_p_halo[None],
        n_r_halo[None]])
    return slot_s, cj_s, v_s, mcid, mgid, counts


def _compose_counts_body(cid_sem, offsets_c, axis: str):
    """Per-peer query counts for the compose lookup (fine vertex ->
    owner of its current coarse id)."""
    R = offsets_c.shape[0] - 1
    me = jax.lax.axis_index(axis)
    valid = cid_sem >= 0
    owner = _owner_of_sem(cid_sem, offsets_c, R, valid)
    remote = jnp.where(owner == me, R, owner)
    cnt = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(remote, 0, R - 1)].add((remote < R).astype(jnp.int32))
    return cnt


def _compose_body(cid_sem, table_sem, offsets_c, axis: str,
                  maxq: int):
    """cid_new[i] = table[cid_sem[i]] — the pass-composition lookup
    (table maps this pass's coarse vertices, shard-local, to the next
    pass's semantic coarse ids)."""
    R = offsets_c.shape[0] - 1
    me = jax.lax.axis_index(axis)
    n_local_c = table_sem.shape[0]
    valid = cid_sem >= 0
    owner = _owner_of_sem(cid_sem, offsets_c, R, valid)
    local_ans = table_sem[jnp.clip(cid_sem - offsets_c[me], 0,
                                   n_local_c - 1)]
    remote_owner = jnp.where(owner == me, R, owner)
    looked = _remote_lookup(table_sem, cid_sem, remote_owner, offsets_c,
                            me, n_local_c, axis, R, maxq,
                            jnp.int32(-1))
    out = jnp.where(owner == me, local_ans, looked)
    return jnp.where(valid, out, -1).astype(jnp.int32)


def _final_route_counts(M: ShardMatrix, offsets, cid_sem, offsets_c,
                        axis: str):
    """Per-dest triple + member counts for the final multipass RAP
    (packed (2R,)): [triples_to_peer*R, members_to_peer*R]."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    E = _Edges(M, offsets, me)
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    owner = _owner_of_sem(cid_sem, offsets_c, R, active & (cid_sem >= 0))
    ol = jnp.concatenate([owner, jnp.full((1,), R, jnp.int32)])
    dest_e = jnp.where(E.valid, ol[jnp.minimum(E.rows, n)], R)
    tri = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(dest_e, 0, R - 1)].add((dest_e < R).astype(jnp.int32))
    mem_r = jnp.where(owner == me, R, owner)
    mem = jnp.zeros((R,), jnp.int32).at[
        jnp.clip(mem_r, 0, R - 1)].add((mem_r < R).astype(jnp.int32))
    return jnp.concatenate([tri, mem])


def _phase_b2_full(M: ShardMatrix, offsets, cid_sem, offsets_c,
                   axis: str, NCL_c: int, maxt: int, maxm: int):
    """Final multipass RAP: derive physical ids from the composed
    semantic cids, route triples and member records, dedup-sum."""
    me = jax.lax.axis_index(axis)
    R = offsets.shape[0] - 1
    n = M.n_local
    idx_sem = offsets[me] + jnp.arange(n, dtype=jnp.int32)
    active = idx_sem < offsets[me + 1]
    valid = active & (cid_sem >= 0)
    rank_c = _owner_of_sem(cid_sem, offsets_c, R, valid)
    rr = jnp.clip(rank_c, 0, R - 1)
    cid_phys = jnp.where(valid, rr * NCL_c + (cid_sem - offsets_c[rr]),
                         -1).astype(jnp.int32)
    (slot_s, cj_s, v_s, mcid, mgid, counts) = _phase_b2_body(
        M, offsets, cid_sem, cid_phys, offsets_c, axis, NCL_c, maxt,
        maxm)
    return slot_s, cj_s, v_s, cid_phys, mcid, mgid, counts
