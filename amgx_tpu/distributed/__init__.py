"""Distributed runtime: domain decomposition over a jax.sharding.Mesh.

TPU-native replacement of src/distributed/ (SURVEY §2.6): partitioning +
halo maps (partition.py), halo exchange via XLA collectives
(dist_matrix.py), the psum reduction context (comms.py), and the SPMD
solve wrapper (solver.py).
"""
from . import comms  # noqa: F401
from .partition import (partition_matrix, partition_vector,  # noqa: F401
                        unpartition_vector, DistPartition)
from .dist_matrix import ShardMatrix, shard_matrix_from_partition  # noqa: F401
from .solver import DistributedSolver, default_mesh  # noqa: F401


def generate_distributed_poisson7pt(nx, ny, nz, n_ranks):
    """AMGX_generate_distributed_poisson_7pt analog
    (src/amgx_c.cu:4731): a 7-pt Poisson partitioned into z-slabs whose
    halos are rank +/- 1 (exercises the ppermute ring path)."""
    from ..gallery import poisson
    A = poisson("7pt", nx, ny, nz)
    return A, partition_matrix(A, n_ranks)
