"""Tracing / profiling subsystem.

TPU-native analog of the reference's nvtx ranges + profiler hooks
(src/amgx_timer.cu, include/profile.h nvtxRange, AMGX_pin_memory-era
instrumentation): named trace regions that show up in a captured device
profile, plus a lightweight wall-clock accumulator for setup/solve
stage breakdowns (the reference's AMGX_timer tree).

- `trace_region(name)`: context manager annotating device work with
  `jax.profiler.TraceAnnotation` (visible in TensorBoard/Perfetto
  traces) and accumulating host wall-clock per name.
- `start_trace(logdir)` / `stop_trace()`: capture a device profile for
  the enclosed region (jax.profiler wrapper; the XLA/TPU answer to
  nsight ranges).
- `timers()` / `reset_timers()`: the accumulated (calls, seconds) per
  region, printed by AMGX_print_timers via the output callback.

Regions are cheap no-ops for device latency (annotation only); the
wall-clock numbers measure host-observed span, which for async
dispatch means "time until the region's Python body returned", not
device occupancy — use start_trace for real device timelines.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Tuple

import jax

_lock = threading.Lock()
_timers: Dict[str, Tuple[int, float]] = {}
_tracing = False


@contextlib.contextmanager
def trace_region(name: str):
    """nvtxRange analog: annotate + accumulate wall-clock under `name`
    (accounted even when the body raises)."""
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            calls, tot = _timers.get(name, (0, 0.0))
            _timers[name] = (calls + 1, tot + dt)


def annotate(name: str):
    """Decorator form of trace_region."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with trace_region(name):
                return fn(*a, **k)
        return wrapper
    return deco


def start_trace(logdir: str):
    """Begin a device profile capture (jax.profiler.start_trace)."""
    global _tracing
    jax.profiler.start_trace(logdir)
    _tracing = True


def stop_trace():
    global _tracing
    if _tracing:
        jax.profiler.stop_trace()
        _tracing = False


def timers() -> Dict[str, Tuple[int, float]]:
    with _lock:
        return dict(_timers)


def reset_timers():
    with _lock:
        _timers.clear()


def timers_total(prefix: str) -> float:
    """Total wall seconds accumulated under regions starting with
    `prefix`. The amg.* setup regions are maintained as DISJOINT leaf
    spans (no nesting; the overlapped ship worker reports under ship.*)
    precisely so `timers_total("amg.") / wall` is an honest accounted
    fraction of a setup's main-thread wall time."""
    with _lock:
        return sum(tot for name, (_c, tot) in _timers.items()
                   if name.startswith(prefix))


def format_timers() -> str:
    """AMGX_timer-style report (src/amgx_timer.cu print tree role)."""
    rows = sorted(timers().items(), key=lambda kv: -kv[1][1])
    if not rows:
        return "no trace regions recorded\n"
    w = max(len(k) for k, _ in rows)
    out = [f"{'region':<{w}}  calls   total_s     avg_ms"]
    for name, (calls, tot) in rows:
        out.append(f"{name:<{w}}  {calls:5d}  {tot:8.3f}  {tot/calls*1e3:9.3f}")
    return "\n".join(out) + "\n"
