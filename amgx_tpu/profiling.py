"""Tracing / profiling subsystem.

TPU-native analog of the reference's nvtx ranges + profiler hooks
(src/amgx_timer.cu, include/profile.h nvtxRange, AMGX_pin_memory-era
instrumentation): named trace regions that show up in a captured device
profile, plus wall-clock accumulation for setup/solve stage breakdowns
(the reference's AMGX_timer tree).

Since the telemetry subsystem landed, the recording engine lives in
`telemetry/spans.py`: every region is a node in a parent/child span
tree (exportable as Chrome/Perfetto trace-event JSON via
`telemetry.spans.export_chrome_trace`), and this module is the stable
thin API over it:

- `trace_region(name)`: context manager annotating device work with
  `jax.profiler.TraceAnnotation` (visible in TensorBoard/Perfetto
  traces), recording a hierarchical span, and accumulating host
  wall-clock per name.
- `start_trace(logdir)` / `stop_trace()`: capture a device profile for
  the enclosed region (jax.profiler wrapper; the XLA/TPU answer to
  nsight ranges).
- `timers()` / `reset_timers()`: the accumulated (calls, seconds) per
  region, printed by AMGX_print_timers via the output callback.

Regions are cheap no-ops for device latency (annotation only); the
wall-clock numbers measure host-observed span, which for async
dispatch means "time until the region's Python body returned", not
device occupancy — use start_trace for real device timelines, or set
`telemetry_sync=1` to fence device work at span boundaries (debugging
mode; it defeats the overlapped shipping/dispatch pipelining).
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax

from .telemetry import spans as _spans

_tracing = False

# the recording engine: hierarchical span + flat accumulator + optional
# device fencing (telemetry/spans.py)
trace_region = _spans.span


def annotate(name: str):
    """Decorator form of trace_region."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with trace_region(name):
                return fn(*a, **k)
        return wrapper
    return deco


def start_trace(logdir: str):
    """Begin a device profile capture (jax.profiler.start_trace)."""
    global _tracing
    jax.profiler.start_trace(logdir)
    _tracing = True


def stop_trace():
    global _tracing
    if _tracing:
        jax.profiler.stop_trace()
        _tracing = False


def timers() -> Dict[str, Tuple[int, float]]:
    return _spans.flat_timers()


def reset_timers():
    _spans.reset()


def timers_total(prefix: str) -> float:
    """Total wall seconds accumulated under regions starting with
    `prefix`. The amg.* setup regions are maintained as DISJOINT leaf
    spans (no nesting; the overlapped ship worker reports under ship.*;
    tools/check_spans.py lints the registry) precisely so
    `timers_total("amg.") / wall` is an honest accounted fraction of a
    setup's main-thread wall time."""
    return _spans.timers_total(prefix)


def format_timers() -> str:
    """AMGX_timer-style report (src/amgx_timer.cu print tree role),
    printed through the output callback by capi.AMGX_print_timers:
    regions sorted by total time, aligned columns, calls / mean /
    share-of-recorded columns."""
    rows = sorted(timers().items(), key=lambda kv: -kv[1][1])
    if not rows:
        return "no trace regions recorded\n"
    grand = sum(tot for _, (_c, tot) in rows) or 1e-30
    w = max(len("region"), max(len(k) for k, _ in rows))
    header = (f"{'region':<{w}}  {'calls':>6}  {'total_s':>9}  "
              f"{'mean_ms':>9}  {'share':>6}")
    out = [header, "-" * len(header)]
    for name, (calls, tot) in rows:
        out.append(f"{name:<{w}}  {calls:6d}  {tot:9.3f}  "
                   f"{tot / calls * 1e3:9.3f}  {tot / grand:6.1%}")
    return "\n".join(out) + "\n"
