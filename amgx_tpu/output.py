"""Print-callback indirection.

Analog of the reference's registered print callback +
`amgx_distributed_output` (src/amgx_c.cu AMGX_register_print_callback;
only rank 0 prints). All framework output (solve stats, grid stats,
warnings meant for the library user) goes through `amgx_output` so a
host application can capture it; the single-controller JAX model plays
the role of rank 0.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

_callback: Optional[Callable[[str, int], None]] = None


def register_print_callback(cb: Optional[Callable[[str, int], None]]):
    global _callback
    _callback = cb


def amgx_output(msg: str):
    if _callback is not None:
        _callback(msg, len(msg))
    else:
        sys.stdout.write(msg)
        # flush: under redirected/block-buffered stdio a long-running
        # solve otherwise buffers its status output indefinitely
        sys.stdout.flush()


def amgx_printf(*args, **kwargs):
    """print()-style convenience routed through the callback."""
    end = kwargs.pop("end", "\n")
    sep = kwargs.pop("sep", " ")
    amgx_output(sep.join(str(a) for a in args) + end)
