"""amgx_tpu — a TPU-native algebraic-multigrid solver framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
NVIDIA AmgX (reference: mattmartineau/AMGX): Classical Ruge-Stuben and
Unsmoothed-Aggregation AMG, standalone or preconditioning CG / BiCGSTAB /
GMRES / FGMRES / IDR, over scalar or small-block CSR matrices, in
fp32/fp64/mixed precision, on one TPU core or a multi-chip mesh via
jax.sharding + XLA collectives.

Quick start::

    import amgx_tpu as amgx
    amgx.initialize()
    A = amgx.gallery.poisson("7pt", 32, 32, 32)
    cfg = amgx.Config.from_file("configs/FGMRES_AGGREGATION.json")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    sol = slv.solve(b)
"""
from __future__ import annotations

import jax

# double precision is the reference's default mode (dDDI); enable x64 so
# float64 vectors/matrices work (TPU executes f64 via emulation, CPU natively)
jax.config.update("jax_enable_x64", True)

from . import config as _config_mod  # noqa: E402
from . import errors, modes, registry, gallery  # noqa: E402,F401
from .config import Config, AMG_Config  # noqa: E402,F401
from .matrix import CsrMatrix  # noqa: E402,F401
from .errors import RC, AMGXError  # noqa: E402,F401
from . import ops  # noqa: E402,F401
from . import profiling  # noqa: E402,F401
from . import determinism  # noqa: E402,F401
from . import memory_info  # noqa: E402,F401
from . import thread_manager  # noqa: E402,F401
from .resources import Resources  # noqa: E402,F401

_initialized = False


def initialize():
    """AMGX_initialize analog (src/amgx_c.cu:2360 -> src/core.cu:723):
    imports all pluggable components so they self-register into the
    factories. Safe to call more than once."""
    global _initialized
    if _initialized:
        return
    from . import solvers  # noqa: F401  (registers solvers + convergence)
    from . import amg  # noqa: F401      (registers levels/cycles/selectors)
    from . import eigen  # noqa: F401    (registers eigensolvers)
    from . import io  # noqa: F401       (registers readers/writers)
    from . import scalers  # noqa: F401  (registers scalers)
    _initialized = True


def finalize():
    global _initialized
    _initialized = False


def create_solver(cfg: Config, scope: str = "default"):
    """Build the root solver tree from a config (AMG_Solver analog).
    A non-empty `fallback_policy` wraps the tree in a ResilientSolver
    (resilience/policy.py) so failed solves run their configured
    recovery chains transparently."""
    initialize()
    from .solvers.base import make_solver
    name, child_scope = cfg.get_solver("solver", scope)
    # span fencing is a process-wide mode: the most recently
    # constructed root solver's telemetry_sync setting wins, in BOTH
    # directions (a debug solver must not leave fencing stuck on for
    # later production solvers in the same process). The env toggle
    # ORs in so AMGX_TPU_TELEMETRY_SYNC=1 survives config defaults.
    from .telemetry import spans as _spans
    _spans.set_sync(bool(int(cfg.get("telemetry_sync", child_scope)))
                    or _spans.env_sync())
    slv = make_solver(name, cfg, child_scope)
    if str(cfg.get("fallback_policy", child_scope)).strip():
        from .resilience.policy import ResilientSolver
        return ResilientSolver(cfg, child_scope, solver=slv)
    return slv


def __getattr__(name):
    # lazy: batch/resilience pull in the solver registry, which stays
    # an initialize()-time side effect for plain `import amgx_tpu`
    if name == "batch":
        from . import batch
        return batch
    if name == "resilience":
        from . import resilience
        return resilience
    if name == "serving":
        from . import serving
        return serving
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def create_eigensolver(cfg: Config, scope: str = "default"):
    """Build an eigensolver from a config (AMG_EigenSolver analog,
    src/amg_eigensolver.cu; configs/eigen_configs presets)."""
    initialize()
    from .eigen import create_eigensolver as _ces
    return _ces(cfg, scope)


__version__ = "0.1.0"
# API-parity version info (AMGX_get_api_version)
API_VERSION = (2, 0)
