"""System IO: MatrixMarket + binary readers/writers (src/matrix_io.cu
analog). `read_system`/`write_system` sniff the format."""
from __future__ import annotations

from . import matrix_market, binary  # noqa: F401  (registers formats)
from ..errors import IOError_


def read_system(path: str, dtype=None):
    """Read (A, b|None, x|None), sniffing MatrixMarket vs binary."""
    with open(path, "rb") as f:
        head = f.read(16)
    if head.startswith(binary._MAGIC):
        return binary.read_system(path)
    kwargs = {} if dtype is None else {"dtype": dtype}
    if head.startswith(b"%%MatrixMarket"):
        return matrix_market.read_system(path, **kwargs)
    raise IOError_(f"{path}: unrecognized system file format")


def write_system(path: str, A, b=None, x=None, fmt: str = "matrixmarket"):
    if fmt.lower() == "matrixmarket":
        return matrix_market.write_system(path, A, b, x)
    if fmt.lower() == "binary":
        return binary.write_system(path, A, b, x)
    raise IOError_(f"unknown matrix_writer format {fmt!r}")
