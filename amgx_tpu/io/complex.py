"""Complex -> real system conversion (ERF K1..K4 formulations).

Analog of the reference reader's complex_conversion path
(src/readers.cu:200-420): a complex n x n system is rewritten as a real
system the solvers can handle, either as a 2n scalar system (modes
1..4) or as an n x n system of 2x2 blocks (modes 221..224), using the
equivalent-real-formulation K<k>:

    K1: [[ Re, -Im], [ Im,  Re]]   b = [Re; Im]   x = [Re;  Im]
    K2: [[ Re,  Im], [ Im, -Re]]   b = [Re; Im]   x = [Re; -Im]
    K3: [[ Im,  Re], [ Re, -Im]]   b = [Im; Re]   x = [Re;  Im]
    K4: [[ Im, -Re], [ Re,  Im]]   b = [Im; Re]   x = [Re; -Im]

(K-formulation naming after Day & Heroux, "Solving complex-valued
linear systems via equivalent real formulations".)
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..errors import BadParametersError
from ..matrix import CsrMatrix

# per-mode 2x2 coefficient stencil: entries are (source, sign) with
# source 're' or 'im', laid out [[TL, TR], [BL, BR]]
_K = {
    1: ((("re", 1), ("im", -1)), (("im", 1), ("re", 1))),
    2: ((("re", 1), ("im", 1)), (("im", 1), ("re", -1))),
    3: ((("im", 1), ("re", 1)), (("re", 1), ("im", -1))),
    4: ((("im", 1), ("re", -1)), (("re", 1), ("im", 1))),
}


def _parts(vals, spec):
    src, sign = spec
    v = np.real(vals) if src == "re" else np.imag(vals)
    return sign * v


def complex_system_to_real(A: CsrMatrix, b=None, x=None, mode: int = 1):
    """Convert a complex system to its K<mode> real form.

    Modes 1..4 produce the 2n scalar system; 221..224 the n-row system
    of 2x2 blocks (same K stencil per entry). Returns (A, b, x)."""
    block = False
    if 220 < mode < 225:
        block, mode = True, mode - 220
    if mode not in _K:
        raise BadParametersError(
            f"complex_conversion={mode}: supported modes are 1..4 "
            "(scalar ERF) and 221..224 (2x2-block ERF)")
    if A.is_block:
        raise BadParametersError(
            "complex_conversion supports scalar complex input only "
            "(the reference has the same restriction for block input)")
    rows, cols, vals = [np.asarray(v) for v in A.coo()]
    n = A.num_rows
    m = A.num_cols
    ((tl, tr), (bl, br)) = _K[mode]

    rdtype = np.real(vals[:0]).dtype       # matching real dtype
    if block:
        bvals = np.empty((vals.shape[0], 2, 2), rdtype)
        bvals[:, 0, 0] = _parts(vals, tl)
        bvals[:, 0, 1] = _parts(vals, tr)
        bvals[:, 1, 0] = _parts(vals, bl)
        bvals[:, 1, 1] = _parts(vals, br)
        diag = None
        if A.has_external_diag:
            dv = np.asarray(A.diag)
            diag = np.empty((n, 2, 2), rdtype)
            diag[:, 0, 0] = _parts(dv, tl)
            diag[:, 0, 1] = _parts(dv, tr)
            diag[:, 1, 0] = _parts(dv, bl)
            diag[:, 1, 1] = _parts(dv, br)
            diag = jnp.asarray(diag)
        A2 = CsrMatrix.from_coo(rows, cols, jnp.asarray(bvals), n, m,
                                block_dims=(2, 2), coalesce=False,
                                diag=diag)
    else:
        if A.has_external_diag:
            raise BadParametersError(
                "scalar ERF of an external-diagonal matrix: fold the "
                "diagonal first")
        r2 = np.concatenate([rows, rows, rows + n, rows + n])
        c2 = np.concatenate([cols, cols + m, cols, cols + m])
        v2 = np.concatenate([_parts(vals, tl), _parts(vals, tr),
                             _parts(vals, bl), _parts(vals, br)])
        A2 = CsrMatrix.from_coo(r2, c2, jnp.asarray(v2), 2 * n, 2 * m,
                                coalesce=False)

    def conv_vec(v, order):
        if v is None:
            return None
        v = np.asarray(v)
        re, im = np.real(v), np.imag(v)
        if order == "re_im":
            parts = (re, im)
        elif order == "im_re":
            parts = (im, re)
        else:  # "re_negim"
            parts = (re, -im)
        if block:
            return jnp.asarray(np.stack(parts, axis=1).reshape(-1))
        return jnp.asarray(np.concatenate(parts))

    b_order = "re_im" if mode in (1, 2) else "im_re"
    x_order = "re_im" if mode in (1, 3) else "re_negim"
    return A2, conv_vec(b, b_order), conv_vec(x, x_order)


def real_solution_to_complex(x, mode: int = 1):
    """Recover the complex solution from the real ERF solution."""
    block = False
    if 220 < mode < 225:
        block, mode = True, mode - 220
    if mode not in _K:
        raise BadParametersError(
            f"complex_conversion={mode}: supported modes are 1..4 "
            "(scalar ERF) and 221..224 (2x2-block ERF)")
    x = np.asarray(x)
    if block:
        xr = x.reshape(-1, 2)
        re, im = xr[:, 0], xr[:, 1]
    else:
        n = x.shape[0] // 2
        re, im = x[:n], x[n:]
    if mode in (2, 4):
        im = -im
    return jnp.asarray(re + 1j * im)
