"""Distributed system IO.

TPU-native analog of src/distributed/distributed_io.cu (776 LoC):
reading a global system together with a *partition vector* (row -> rank
map), renumbering rows so each partition is contiguous, and
consolidating partitions onto fewer ranks on read.

Redesign note: the reference runs one process per rank, each reading its
row subset (`AMGX_read_system_distributed`); under single-controller JAX
the controller reads the global system once and produces the
partition-contiguous renumbering + offsets that the distributed layer's
row-block sharding consumes — same on-disk formats, same resulting data
layout per shard.

Partition-vector file formats (matching the reference reader):
- raw binary int32 array of length n (the `partition_vector` files the
  reference examples ship);
- whitespace-separated text integers.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..errors import IOError_
from ..matrix import CsrMatrix
from . import read_system as _read_system


def read_partition_vector(path: str, n: Optional[int] = None) -> np.ndarray:
    """Row -> rank map from file (binary int32 or text)."""
    with open(path, "rb") as f:
        raw = f.read()
    is_text = False
    try:
        txt = raw.decode("ascii")
        is_text = bool(txt.strip()) and \
            set(txt) <= set("0123456789- \t\r\n")
    except UnicodeDecodeError:
        pass
    if is_text:
        try:
            vec = np.array(txt.split(), dtype=np.int64)
        except ValueError as e:
            raise IOError_(f"malformed text partition vector {path}: {e}")
    else:
        if len(raw) % 4:
            raise IOError_(
                f"binary partition vector {path} has size {len(raw)} "
                "not a multiple of int32")
        vec = np.frombuffer(raw, dtype=np.int32).astype(np.int64)
    if n is not None and len(vec) != n:
        raise IOError_(
            f"partition vector length {len(vec)} != matrix rows {n}")
    if len(vec) and vec.min() < 0:
        raise IOError_("partition vector has negative ranks")
    return vec


def sizes_to_partition_vector(partition_sizes, n: int) -> np.ndarray:
    """Per-rank contiguous block sizes -> row -> rank map."""
    sizes = np.asarray(partition_sizes, np.int64)
    if sizes.sum() != n:
        raise IOError_(
            f"partition_sizes sum {sizes.sum()} != matrix rows {n}")
    return np.repeat(np.arange(len(sizes)), sizes)


def consolidate_partitions(part_vec: np.ndarray, n_target: int
                           ) -> np.ndarray:
    """Map a partitioning onto fewer ranks (the read-time consolidation
    of distributed_io.cu): partitions are assigned to target ranks in
    contiguous groups, preserving locality."""
    n_parts = int(part_vec.max()) + 1 if len(part_vec) else 0
    if n_target <= 0:
        raise IOError_("n_target must be positive")
    if n_parts <= n_target:
        return part_vec.copy()
    group = (np.arange(n_parts) * n_target) // n_parts
    return group[part_vec]


def renumber_by_partition(A: CsrMatrix, part_vec: np.ndarray,
                          b=None, x=None, n_ranks: Optional[int] = None
                          ) -> Tuple[CsrMatrix, Optional[np.ndarray],
                                     Optional[np.ndarray], np.ndarray,
                                     np.ndarray]:
    """Permute the system so each rank's rows (and matching columns) are
    contiguous, ordered by rank (the renumber-to-local step of the
    reference upload path, distributed_arranger.h renumber_to_local).

    Returns (A_perm, b_perm, x_perm, part_offsets, perm) where
    `part_offsets[r]` is the first global row of rank r after
    renumbering and `perm` maps new index -> old index.
    """
    n = A.num_rows
    if len(part_vec) != n:
        raise IOError_(
            f"partition vector length {len(part_vec)} != rows {n}")
    if len(part_vec) and part_vec.min() < 0:
        raise IOError_("partition vector has negative ranks")
    perm = np.argsort(part_vec, kind="stable")   # new -> old
    iperm = np.empty(n, np.int64)
    iperm[perm] = np.arange(n)
    rows, cols, vals = [np.asarray(v) for v in A.coo()]
    new_rows = iperm[rows]
    new_cols = iperm[cols]
    diag = np.asarray(A.diag)[perm] if A.has_external_diag else None
    A2 = CsrMatrix.from_coo(new_rows, new_cols, vals, n, A.num_cols,
                            block_dims=(A.block_dimx, A.block_dimy),
                            diag=diag)
    nr = n_ranks if n_ranks is not None else (
        int(part_vec.max()) + 1 if len(part_vec) else 1)
    counts = np.bincount(np.asarray(part_vec, np.int64), minlength=nr)
    part_offsets = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=part_offsets[1:])
    # b has num_rows*block_dimy scalars, x has num_rows*block_dimx:
    # permute whole blocks, each with its own block size.
    def _vperm(bd):
        return perm if bd == 1 else (
            perm[:, None] * bd + np.arange(bd)).ravel()
    bp = None if b is None else np.asarray(b)[_vperm(A.block_dimy)]
    xp = None if x is None else np.asarray(x)[_vperm(A.block_dimx)]
    return A2.init(), bp, xp, part_offsets, perm


def read_system_distributed(path: str, partition_path: Optional[str] = None,
                            partition_vector: Optional[np.ndarray] = None,
                            partition_sizes=None,
                            num_ranks: Optional[int] = None, dtype=None):
    """AMGX_read_system_distributed analog: global system + partition
    vector -> partition-contiguous system.

    Returns (A, b, x, part_offsets, perm). Partition input precedence
    mirrors the reference reader: explicit vector, then vector file,
    then per-rank `partition_sizes` (contiguous blocks of those sizes),
    then `num_ranks` equal blocks."""
    A, b, x = _read_system(path, dtype=dtype)
    n = A.num_rows
    if partition_vector is not None:
        pv = np.asarray(partition_vector, np.int64)
        if len(pv) and pv.min() < 0:
            raise IOError_("partition vector has negative ranks")
    elif partition_path is not None:
        pv = read_partition_vector(partition_path, n)
    elif partition_sizes is not None:
        pv = sizes_to_partition_vector(partition_sizes, n)
    else:
        r = num_ranks or 1
        block = -(-n // r)
        pv = np.arange(n) // block
    if num_ranks is not None:
        pv = consolidate_partitions(pv, num_ranks)
    nr = num_ranks if num_ranks is not None else (
        int(pv.max()) + 1 if len(pv) else 1)
    return renumber_by_partition(A, pv, b, x, n_ranks=nr)


def write_system_distributed(path: str, A: CsrMatrix, b=None, x=None,
                             partition_vector=None,
                             fmt: str = "matrixmarket"):
    """AMGX_write_system_distributed analog: the global system plus the
    partition vector as a sidecar file `<path>.partition` (raw int32 —
    readable back by read_partition_vector)."""
    from . import write_system as _write_system
    _write_system(path, A, b, x, fmt=fmt)
    if partition_vector is not None:
        pv = np.asarray(partition_vector, np.int32)
        if len(pv) != A.num_rows:
            raise IOError_(
                f"partition vector length {len(pv)} != rows {A.num_rows}")
        with open(path + ".partition", "wb") as f:
            f.write(pv.tobytes())
