"""MatrixMarket system reader/writer.

Analog of the reference MatrixMarket IO (src/matrix_io.cu,
src/readers.cu): standard ``%%MatrixMarket matrix coordinate
<field> <symmetry>`` files plus the AMGX extension line

    %%AMGX <token>...

with tokens: ``diagonal`` (externally-stored diagonal follows the
entries), ``rhs`` / ``solution`` (vectors appended after the matrix),
``base0`` (0-based indices), and one or two integers giving block
dimensions. Parsing is host-side (numpy); returned containers are device
pytrees.

Unlike the reference we also accept ``pattern`` matrices (values of 1.0)
rather than erroring.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..errors import IOError_
from ..matrix import CsrMatrix
from .. import registry


def _parse_header(lines):
    if not lines or not lines[0].startswith("%%MatrixMarket"):
        raise IOError_("missing %%MatrixMarket header")
    tokens = lines[0].split()[1:]
    if not tokens or tokens[0] != "matrix":
        raise IOError_("expecting 'matrix' keyword in MatrixMarket header")
    fmt = tokens[1] if len(tokens) > 1 else "coordinate"
    field = tokens[2] if len(tokens) > 2 else "real"
    symmetry = tokens[3] if len(tokens) > 3 else "general"
    amgx_tokens = []
    body_start = 1
    for i, ln in enumerate(lines[1:], start=1):
        s = ln.strip()
        if s.startswith("%%AMGX"):
            amgx_tokens += s.split()[1:]
            continue
        if s.startswith("%") or not s:
            continue
        body_start = i
        break
    return fmt, field, symmetry, amgx_tokens, body_start


def _parse_body(body_lines, expected_total: int) -> np.ndarray:
    """Parse the numeric body: native C parser (one pass, memory speed)
    with the pure-numpy tokenizer as fallback."""
    from ..native import lib
    native = lib()
    if native is not None and hasattr(native, "amgx_mm_parse"):
        import ctypes
        text = "".join(body_lines).encode()
        out = np.empty(expected_total, np.float64)
        native.amgx_mm_parse.restype = ctypes.c_longlong
        got = native.amgx_mm_parse(
            ctypes.c_char_p(text), ctypes.c_longlong(len(text)),
            ctypes.c_longlong(expected_total),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        if got >= 0:
            return out[:got]
        # malformed for the fast path -> let the fallback report it
    body_vals = []
    for ln in body_lines:
        s = ln.split()
        if not s or s[0].startswith("%"):
            continue
        body_vals.extend(s)
    return np.array(body_vals, dtype=np.float64)


def read_system(path: str, dtype=np.float64
                ) -> Tuple[CsrMatrix, Optional[jnp.ndarray],
                           Optional[jnp.ndarray]]:
    """Read (A, rhs | None, solution | None) from a MatrixMarket file."""
    with open(path) as f:
        lines = f.readlines()
    fmt, field, symmetry, amgx_tokens, body = _parse_header(lines)
    if fmt != "coordinate":
        raise IOError_(f"unsupported MatrixMarket format {fmt!r} "
                       "(only 'coordinate')")
    is_complex = field == "complex"
    is_pattern = field == "pattern"
    if is_complex:
        dtype = np.complex128 if np.dtype(dtype) == np.float64 else np.complex64
    symmetric = symmetry in ("symmetric", "skew-symmetric", "hermitian")
    skew = symmetry == "skew-symmetric"
    hermitian = symmetry == "hermitian"

    has_diag = "diagonal" in amgx_tokens
    has_rhs = "rhs" in amgx_tokens
    has_soln = "solution" in amgx_tokens
    base = 0 if "base0" in amgx_tokens else 1
    block_sizes = [int(t) for t in amgx_tokens if t.isdigit()]
    if len(block_sizes) == 2:
        bx, by = block_sizes
    elif len(block_sizes) == 1:
        bx = by = block_sizes[0]
    else:
        bx = by = 1

    size_line = lines[body].split()
    rows_s, cols_s, entries_s = (int(size_line[0]), int(size_line[1]),
                                 int(size_line[2]))
    if rows_s % bx or cols_s % by or entries_s % (bx * by):
        raise IOError_("matrix dimensions do not match block sizes")
    n, m = rows_s // bx, cols_s // by

    per_entry = 2 + (0 if is_pattern else (2 if is_complex else 1))
    need = entries_s * per_entry
    # everything the sections can hold, for the one-pass native parse
    # (diag is stored as reals — matching its consumption below)
    cmul = 2 if is_complex else 1
    expected_total = need \
        + (n * bx * by if has_diag else 0) \
        + (n * bx * cmul if has_rhs else 0) \
        + (m * by * cmul if has_soln else 0)
    data = _parse_body(lines[body + 1:], expected_total)
    if data.size < need:
        raise IOError_(f"matrix body truncated: {data.size} < {need} numbers")
    ent = data[:need].reshape(entries_s, per_entry)
    rest = data[need:]
    r = ent[:, 0].astype(np.int64) - base
    c = ent[:, 1].astype(np.int64) - base
    if is_pattern:
        v = np.ones(entries_s, dtype)
    elif is_complex:
        v = (ent[:, 2] + 1j * ent[:, 3]).astype(dtype)
    else:
        v = ent[:, 2].astype(dtype)

    if symmetric:
        off = r != c
        rs, cs, vs = c[off], r[off], v[off]
        if skew:
            vs = -vs
        elif hermitian:
            vs = np.conj(vs)
        r = np.concatenate([r, rs])
        c = np.concatenate([c, cs])
        v = np.concatenate([v, vs])

    if bx * by > 1:
        # scalar entries of an expanded block matrix: fold (r, c) into
        # (block row, block col, in-block position)
        br, ir = r // bx, r % bx
        bc, ic = c // by, c % by
        key = ((br * m + bc) * bx + ir) * by + ic
        order = np.argsort(key, kind="stable")
        nb = br.size // (bx * by)
        blocks = v[order].reshape(nb, bx, by)
        rb = br[order][:: bx * by]
        cb = bc[order][:: bx * by]
        A = CsrMatrix.from_coo(rb, cb, jnp.asarray(blocks), n, m,
                               block_dims=(bx, by))
    else:
        A = CsrMatrix.from_coo(r, c, jnp.asarray(v), n, m)

    pos = 0
    if has_diag:
        ndiag = n * bx * by
        dvals = rest[pos:pos + ndiag].astype(dtype)
        pos += ndiag
        diag = jnp.asarray(dvals.reshape(n, bx, by) if bx * by > 1 else dvals)
        A = CsrMatrix(row_offsets=A.row_offsets, col_indices=A.col_indices,
                      values=A.values, diag=diag, num_rows=A.num_rows,
                      num_cols=A.num_cols, block_dimx=bx, block_dimy=by)
    b = x = None
    if has_rhs:
        nb_ = n * bx * (2 if is_complex else 1)
        raw = rest[pos:pos + nb_]
        pos += nb_
        b = jnp.asarray(raw[0::2] + 1j * raw[1::2] if is_complex
                        else raw.astype(dtype))
    if has_soln:
        nx_ = m * by * (2 if is_complex else 1)
        raw = rest[pos:pos + nx_]
        pos += nx_
        x = jnp.asarray(raw[0::2] + 1j * raw[1::2] if is_complex
                        else raw.astype(dtype))
    return A, b, x


def read_matrix(path: str, dtype=np.float64) -> CsrMatrix:
    return read_system(path, dtype)[0]


def write_system(path: str, A: CsrMatrix, b=None, x=None):
    """Write (A [, rhs][, solution]) in MatrixMarket + %%AMGX format
    (AMGX_write_system analog, src/matrix_io.cu)."""
    n, m = A.num_rows, A.num_cols
    bx, by = A.block_dimx, A.block_dimy
    is_complex = np.issubdtype(np.asarray(A.values).dtype, np.complexfloating)
    field = "complex" if is_complex else "real"
    tokens = []
    if bx * by > 1:
        tokens += [str(bx), str(by)]
    if A.has_external_diag:
        tokens.append("diagonal")
    if b is not None:
        tokens.append("rhs")
    if x is not None:
        tokens.append("solution")
    rows, cols, vals = (np.asarray(t) for t in A.coo())
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        if tokens:
            f.write("%%AMGX " + " ".join(tokens) + "\n")
        f.write(f"{n * bx} {m * by} {A.nnz * bx * by}\n")

        def emit(i, j, val):
            if is_complex:
                f.write(f"{i} {j} {val.real:.17g} {val.imag:.17g}\n")
            else:
                f.write(f"{i} {j} {val:.17g}\n")

        if bx * by > 1:
            for e in range(vals.shape[0]):
                for ii in range(bx):
                    for jj in range(by):
                        emit(rows[e] * bx + ii + 1, cols[e] * by + jj + 1,
                             vals[e, ii, jj])
        else:
            for e in range(vals.size):
                emit(int(rows[e]) + 1, int(cols[e]) + 1, vals[e])
        if A.has_external_diag:
            d = np.asarray(A.diag).reshape(-1)
            for val in d:
                f.write(f"{val:.17g}\n")
        for vec in (b, x):
            if vec is None:
                continue
            v = np.asarray(vec).reshape(-1)
            for val in v:
                if is_complex:
                    f.write(f"{val.real:.17g} {val.imag:.17g}\n")
                else:
                    f.write(f"{val:.17g}\n")


registry.matrix_io_readers.register("MATRIXMARKET", read_system)
registry.matrix_io_writers.register("MATRIXMARKET", write_system)
