"""Binary system format.

Analog of the reference's NVAMGBinary reader/writer (src/readers.cu:1700,
src/matrix_io.cu:301-390). The format here is our own (little-endian
header + raw arrays) — the goal is a fast round-trip for large systems,
not byte compatibility with the CUDA tool chain.

Layout:
  magic   b"AMGXTPU1"
  header  7 x int64: num_rows num_cols nnz block_dimx block_dimy
                     flags (bit0 diag, bit1 rhs, bit2 soln) dtype_code
  arrays  row_offsets int32[n+1], col_indices int32[nnz],
          values dtype[nnz*bx*by], [diag dtype[n*bx*by]],
          [rhs dtype[n*bx]], [soln dtype[m*by]]
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..errors import IOError_
from ..matrix import CsrMatrix
from .. import registry

_MAGIC = b"AMGXTPU1"
_DTYPES = {0: np.float32, 1: np.float64, 2: np.complex64, 3: np.complex128}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def write_system(path: str, A: CsrMatrix, b=None, x=None):
    vals = np.asarray(A.values)
    flags = (1 if A.has_external_diag else 0) | \
            (2 if b is not None else 0) | (4 if x is not None else 0)
    header = np.array(
        [A.num_rows, A.num_cols, A.nnz, A.block_dimx, A.block_dimy, flags,
         _CODES[vals.dtype]], np.int64)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(header.tobytes())
        f.write(np.asarray(A.row_offsets, np.int32).tobytes())
        f.write(np.asarray(A.col_indices, np.int32).tobytes())
        f.write(vals.tobytes())
        if A.has_external_diag:
            f.write(np.asarray(A.diag, vals.dtype).tobytes())
        if b is not None:
            f.write(np.asarray(b, vals.dtype).tobytes())
        if x is not None:
            f.write(np.asarray(x, vals.dtype).tobytes())


def read_system(path: str, dtype=None):
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise IOError_(f"{path}: not an AMGXTPU binary system file")
        header = np.frombuffer(f.read(7 * 8), np.int64)
        n, m, nnz, bx, by, flags, code = (int(v) for v in header)
        vdtype = np.dtype(_DTYPES[code])
        row_offsets = np.frombuffer(f.read(4 * (n + 1)), np.int32)
        col_indices = np.frombuffer(f.read(4 * nnz), np.int32)
        bs = bx * by
        values = np.frombuffer(f.read(vdtype.itemsize * nnz * bs), vdtype)
        if bs > 1:
            values = values.reshape(nnz, bx, by)
        diag = b = x = None
        if flags & 1:
            diag = np.frombuffer(f.read(vdtype.itemsize * n * bs), vdtype)
            if bs > 1:
                diag = diag.reshape(n, bx, by)
        if flags & 2:
            b = jnp.asarray(np.frombuffer(f.read(vdtype.itemsize * n * bx),
                                          vdtype))
        if flags & 4:
            x = jnp.asarray(np.frombuffer(f.read(vdtype.itemsize * m * by),
                                          vdtype))
    A = CsrMatrix.from_scipy_like(row_offsets, col_indices,
                                  jnp.asarray(values), n, m, (bx, by),
                                  diag=diag)
    return A, b, x


registry.matrix_io_readers.register("BINARY", read_system)
registry.matrix_io_writers.register("BINARY", write_system)
