"""Matrix permutation / sorting / analysis kernels.

Analogs of the reference's misc kernel set (src/permute.cu, sort
utilities, and the matrix-analysis diagnostics of
src/matrix_analysis.cu): symmetric and unsymmetric row/column
permutations of CSR matrices, row sorting by key, and structural
analysis (symmetry, diagonal dominance, bandwidth) used by diagnostics
and test harnesses. All static-shape device code (sort + segment ops).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..matrix import CsrMatrix


def _iperm(perm):
    n = perm.shape[0]
    ip = jnp.zeros((n,), perm.dtype).at[perm].set(
        jnp.arange(n, dtype=perm.dtype))
    return ip


def permute_matrix(A: CsrMatrix, row_perm=None, col_perm=None) -> CsrMatrix:
    """B = P_r A P_c^T: B[i, j] = A[row_perm[i], col_perm[j]].

    `row_perm`/`col_perm` map new index -> old index (pass the same
    array for the symmetric reordering of src/permute.cu). Either may
    be None (identity)."""
    if A.has_external_diag and not (
            row_perm is col_perm
            or (row_perm is not None and col_perm is not None
                and np.array_equal(np.asarray(row_perm),
                                   np.asarray(col_perm)))):
        raise ValueError(
            "permute_matrix: external-diagonal matrices support only the "
            "symmetric permutation (row_perm == col_perm)")
    rows, cols, vals = A.coo()
    if row_perm is not None:
        row_perm = jnp.asarray(row_perm, jnp.int32)
        rows = _iperm(row_perm)[rows]
    if col_perm is not None:
        col_perm = jnp.asarray(col_perm, jnp.int32)
        cols = _iperm(col_perm)[cols]
    diag = A.diag
    if diag is not None and row_perm is not None:
        diag = diag[row_perm]
    return CsrMatrix.from_coo(rows, cols, vals, A.num_rows, A.num_cols,
                              block_dims=(A.block_dimx, A.block_dimy),
                              coalesce=False, diag=diag)


def permute_vector(x, perm, block_dim: int = 1):
    """y[i] = x[perm[i]] blockwise (reference reorder kernels)."""
    if block_dim == 1:
        return x[perm]
    return x.reshape(-1, block_dim)[perm].reshape(-1)


def sort_rows_by(A: CsrMatrix, key) -> tuple:
    """Symmetric reordering sorting rows (and matching columns) by `key`
    ascending (stable). Returns (permuted matrix, perm) — the row-sort
    utility role. Square matrices only (the permutation applies to both
    sides)."""
    if A.num_rows != A.num_cols:
        raise ValueError(
            "sort_rows_by: symmetric reordering requires a square matrix; "
            "use permute_matrix with separate row/col permutations")
    perm = jnp.argsort(jnp.asarray(key), stable=True).astype(jnp.int32)
    return permute_matrix(A, row_perm=perm, col_perm=perm), perm


class MatrixAnalysis(NamedTuple):
    """Structural diagnostics (matrix_analysis.cu role)."""
    is_structurally_symmetric: bool
    is_symmetric: bool
    diag_dominant_rows: int      # rows with |a_ii| >= sum_j |a_ij|
    num_rows: int
    nnz: int
    bandwidth: int               # max |i - j| over stored entries
    min_row_nnz: int
    max_row_nnz: int
    has_zero_diag: bool


def analyze_matrix(A: CsrMatrix, tol: float = 0.0) -> MatrixAnalysis:
    """Compute structural/numerical diagnostics in one device pass."""
    A = A if A.initialized else A.init(ell="never")
    rows, cols, vals = A.coo()
    if A.is_block:
        vals = vals[:, 0, 0]
    n = A.num_rows
    key = rows.astype(jnp.int64) * A.num_cols + cols.astype(jnp.int64)
    key_t = cols.astype(jnp.int64) * A.num_cols + rows.astype(jnp.int64)
    order = jnp.argsort(key_t, stable=True)
    kt_sorted = key_t[order]
    pos = jnp.clip(jnp.searchsorted(kt_sorted, key), 0,
                   max(rows.shape[0] - 1, 0))
    struct_sym = bool(jnp.all(kt_sorted[pos] == key)) if rows.shape[0] \
        else True
    vt = vals[order][pos]
    num_sym = struct_sym and bool(
        jnp.all(jnp.abs(vt - vals) <= tol + 1e-12 * jnp.abs(vals)))
    d = A.diagonal()
    if A.is_block:
        d = d[:, 0, 0]
    absrow = jax.ops.segment_sum(jnp.abs(vals), rows, num_segments=n,
                                 indices_are_sorted=True)
    # |a_ii| >= off-diagonal row sum (absrow includes the diagonal only
    # when it is stored in the CSR part)
    off = absrow if A.has_external_diag else absrow - jnp.abs(d)
    dom = int(jnp.sum(jnp.abs(d) >= off))
    row_nnz = jnp.diff(A.row_offsets)
    bw = int(jnp.max(jnp.abs(rows.astype(jnp.int64)
                             - cols.astype(jnp.int64)))) \
        if rows.shape[0] else 0
    return MatrixAnalysis(
        is_structurally_symmetric=struct_sym,
        is_symmetric=num_sym,
        diag_dominant_rows=dom,
        num_rows=n, nnz=A.nnz, bandwidth=bw,
        min_row_nnz=int(jnp.min(row_nnz)) if n else 0,
        max_row_nnz=int(jnp.max(row_nnz)) if n else 0,
        has_zero_diag=bool(jnp.any(d == 0)))
