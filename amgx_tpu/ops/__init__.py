from .spmv import spmv, multiply, residual, axmb  # noqa: F401
from .blas import (  # noqa: F401
    axpy, axpby, axpbypcz, scal, fill, dot, nrm1, nrm2, nrmmax, norm,
    get_norm,
)
from .transpose import transpose  # noqa: F401
from .spgemm import csr_multiply, csr_add, galerkin_rap  # noqa: F401
