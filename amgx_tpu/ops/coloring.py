"""Parallel graph coloring for multicolor smoothers.

Analog of src/matrix_coloring/ (10 schemes, 6860 LoC of CUDA; registry
src/core.cu:669-678). The workhorse is Jones-Plassmann-Luby expressed as
segment-max fixed points (the same machinery as PMIS/matching):

- MIN_MAX: per round, uncolored local *maxima* of a hash weight get the
  round's low color and local *minima* the round's high color (two colors
  per round, min_max.cu behavior);
- MULTI_HASH: several independent hashes per round (multi_hash.cu);
- MIN_MAX_2RING / GREEDY_MIN_MAX_2RING: the same fixed point run on the
  squared adjacency graph (distance-2 coloring, needed by ILU/DILU with
  reordering);
- ROUND_ROBIN / UNIFORM: trivial index-based colorings (round_robin.cu,
  uniform.cu);
- SERIAL_GREEDY_BFS: host-side deterministic greedy (quality reference).

Returns a Coloring(row_colors, num_colors). Colorings are validated by
tests the way src/tests/valid_coloring.cu does: no edge joins two
vertices of one color.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from ..matrix import CsrMatrix


@dataclasses.dataclass(frozen=True)
class Coloring:
    row_colors: jax.Array          # (n,) int32
    num_colors: int

    def color_counts(self):
        return jnp.bincount(self.row_colors, length=self.num_colors)


def _hash_w(n, salt: int):
    i = jnp.arange(n, dtype=jnp.uint32)
    h = (i + jnp.uint32((salt * 0x9E3779B9) & 0xFFFFFFFF)) * \
        jnp.uint32(2654435761)
    h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    return h


def _sym_edges(A: CsrMatrix):
    rows, cols, _ = A.coo()
    offd = rows != cols
    r = jnp.concatenate([rows[offd], cols[offd]])
    c = jnp.concatenate([cols[offd], rows[offd]])
    order = jnp.argsort(r, stable=True)
    return r[order], c[order]


def _hash_w_np(n, salt: int):
    i = np.arange(n, dtype=np.uint32)
    with np.errstate(over="ignore"):
        h = (i + np.uint32((salt * 0x9E3779B9) & 0xFFFFFFFF)) * \
            np.uint32(2654435761)
        h = (h ^ (h >> 15)) * np.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
    return h


def _jpl_min_max_np(n: int, sr, sc, max_rounds: int, use_min: bool):
    """Host (numpy) twin of the JPL fixed point below — identical hash,
    round structure, and straggler handling, so colors are bit-equal.
    The host-setup hierarchy build (amg_host_setup) runs smoother
    setup on numpy-backed matrices; one eager XLA:CPU dispatch per
    round per color would otherwise dominate the whole classical setup
    (measured: ~minutes at 96^3)."""
    order = np.argsort(sr, kind="stable")
    sr, sc = sr[order], sc[order]
    ro = np.zeros(n + 1, np.int64)
    np.cumsum(np.bincount(sr, minlength=n), out=ro[1:])
    colors = np.full(n, -1, np.int32)
    has_nbr = np.zeros(n, bool)
    has_nbr[sr] = True
    colors[~has_nbr] = 0
    next_color = 0

    def extract(colors, w, ncol, maximize):
        from ..matrix import _np_row_reduce
        un = colors < 0
        fill = np.uint32(0) if maximize else np.uint32(0xFFFFFFFF)
        wm = np.where(un, w, fill)
        op = np.maximum if maximize else np.minimum
        nbest = _np_row_reduce(op, wm[sc], ro, n, fill)
        take = un & ((w > nbest) if maximize else (w < nbest))
        colors[take] = ncol

    for rnd in range(max_rounds):
        if not (colors < 0).any():
            break
        w = _hash_w_np(n, rnd)
        extract(colors, w, next_color, True)
        next_color += 1
        if use_min:
            if not (colors < 0).any():
                break
            extract(colors, w, next_color, False)
            next_color += 1
    colors[colors < 0] = next_color
    num = int(colors.max()) + 1 if n else 0
    return Coloring(jnp.asarray(colors), num)


def _host_sym_edges(A: CsrMatrix):
    """Host (numpy) symmetrized off-diagonal edge lists via the
    mirrors, or None when the arrays cannot be served host-side."""
    from ..matrix import host_arrays
    ha = host_arrays(A.row_offsets, A.col_indices)
    if ha is None:
        return None
    ro, ci = ha
    rows = np.repeat(np.arange(A.num_rows, dtype=np.int32), np.diff(ro))
    offd = rows != ci
    return (np.concatenate([rows[offd], ci[offd]]),
            np.concatenate([ci[offd], rows[offd]]))


def _jpl_min_max(A: CsrMatrix, max_rounds: int = 64, use_min: bool = True,
                 edges=None):
    """Jones-Plassmann-Luby with (max, min) extraction per round."""
    n = A.num_rows
    if edges is None:
        he = _host_sym_edges(A)
        if he is not None:
            return _jpl_min_max_np(n, he[0], he[1], max_rounds, use_min)
    sr, sc = _sym_edges(A) if edges is None else edges
    colors = jnp.full((n,), -1, jnp.int32)
    has_nbr = jnp.zeros((n,), bool).at[sr].set(True)
    colors = jnp.where(~has_nbr, 0, colors)       # isolated: color 0
    next_color = 0
    for rnd in range(max_rounds):
        un = colors < 0
        if not bool(jnp.any(un)):
            break
        w = _hash_w(n, rnd)
        active = un[sr] & un[sc]
        nmax = jax.ops.segment_max(
            jnp.where(active, w[sc], jnp.uint32(0)), sr, num_segments=n,
            indices_are_sorted=True)
        is_max = un & (w > nmax)
        colors = jnp.where(is_max, next_color, colors)
        next_color += 1
        if use_min:
            un = colors < 0
            if not bool(jnp.any(un)):
                break
            active = un[sr] & un[sc]
            nmin = jax.ops.segment_min(
                jnp.where(active, w[sc], jnp.uint32(0xFFFFFFFF)), sr,
                num_segments=n, indices_are_sorted=True)
            is_min = un & (w < nmin)
            colors = jnp.where(is_min, next_color, colors)
            next_color += 1
    colors = jnp.where(colors < 0, next_color, colors)  # stragglers
    num = int(jnp.max(colors)) + 1 if n else 0
    return Coloring(colors.astype(jnp.int32), num)


def _square_edges(A: CsrMatrix):
    """Distance-2 adjacency (pattern of A@A) as symmetric edges."""
    from .spgemm import csr_multiply
    rows, cols, _ = A.coo()
    pattern = CsrMatrix(row_offsets=A.row_offsets,
                        col_indices=A.col_indices,
                        values=jnp.ones((A.nnz,), jnp.float64),
                        num_rows=A.num_rows, num_cols=A.num_cols)
    S2 = csr_multiply(pattern, pattern)
    r2, c2, v2 = S2.coo()
    keep = (np.asarray(v2) > 0) & (np.asarray(r2) != np.asarray(c2))
    r = jnp.concatenate([r2[keep], c2[keep]])
    c = jnp.concatenate([c2[keep], r2[keep]])
    order = jnp.argsort(r, stable=True)
    return r[order], c[order]


class MatrixColoring:
    """Base (include/matrix_coloring/matrix_coloring.h:27)."""

    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope
        self.coloring_level = int(cfg.get("coloring_level", scope))

    def color_matrix(self, A: CsrMatrix) -> Coloring:
        raise NotImplementedError


@registry.matrix_coloring.register("MIN_MAX")
@registry.matrix_coloring.register("PARALLEL_GREEDY")
@registry.matrix_coloring.register("LOCALLY_DOWNWIND")
class MinMaxColoring(MatrixColoring):
    """LOCALLY_DOWNWIND documented deviation: the reference's downwind
    ordering (locally_downwind.cu) targets DILU sweep quality on
    convection problems; here it aliases MIN_MAX (GREEDY_RECOLOR below
    is the real quality scheme of this port)."""

    def color_matrix(self, A):
        if self.coloring_level >= 2:
            return _jpl_min_max(A, edges=_square_edges(A))
        return _jpl_min_max(A)


def _greedy_recolor_np(n, ro_e, sc, colors, num_colors):
    """Descending-class first-fit recolor over the symmetrized edge
    lists (rows CSR-ordered): each color class is an independent set,
    so its vertices reassign simultaneously to their smallest
    neighbor-free color. One pass; the count never increases (a
    vertex's own class is always free). O(nnz) per class sweep total."""
    colors = colors.copy()
    K = int(num_colors)
    if K <= 2 or n == 0:
        return colors, K
    for c in range(K - 1, 0, -1):
        rows_c = np.flatnonzero(colors == c)
        if rows_c.size == 0:
            continue
        used = np.zeros((rows_c.size, K), bool)
        # neighbor colors of each class-c vertex (fresh gather — earlier
        # classes may already have moved); flat edge positions of the
        # class rows, fully vectorized
        cnt = ro_e[rows_c + 1] - ro_e[rows_c]
        tot = int(cnt.sum())
        if tot:
            tgt = np.repeat(np.arange(rows_c.size), cnt)
            pos = (np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt)
                   + np.repeat(ro_e[rows_c], cnt))
            used[tgt, colors[sc[pos]]] = True
        new = np.argmax(~used, axis=1)      # smallest free color (<= c)
        colors[rows_c] = new
    return colors, int(colors.max()) + 1


@registry.matrix_coloring.register("GREEDY_RECOLOR")
class GreedyRecolorColoring(MatrixColoring):
    """JPL MIN_MAX followed by a greedy recoloring pass that shrinks
    the color count (greedy_recolor.cu:1-1172 role): fewer colors
    directly cuts the serial sweep depth of MULTICOLOR_DILU/GS.
    Reassignment runs class-by-class in descending color order; each
    class is an independent set, so the whole class moves at once to
    its smallest neighbor-free color."""

    def color_matrix(self, A):
        n = A.num_rows
        # one edge build serves both the base JPL and the recolor pass
        # (at distance 2 the _square_edges SpGEMM is the dominant cost;
        # at distance 1 the host edge lists are shared via
        # _host_sym_edges)
        sq_edges = _square_edges(A) if self.coloring_level >= 2 else None
        he = _host_sym_edges(A) if self.coloring_level < 2 else None
        if he is not None:
            base = _jpl_min_max_np(n, he[0], he[1], 64, True)
        else:
            base = _jpl_min_max(A, edges=sq_edges) \
                if sq_edges is not None else _jpl_min_max(A)
        if base.num_colors <= 2:
            return base
        if he is not None:
            sr, sc = he
        else:
            sr, sc = sq_edges if sq_edges is not None else _sym_edges(A)
            sr, sc = np.asarray(sr), np.asarray(sc)
        order = np.argsort(sr, kind="stable")
        sr, sc = sr[order], sc[order]
        ro_e = np.zeros(n + 1, np.int64)
        np.cumsum(np.bincount(sr, minlength=n), out=ro_e[1:])
        colors, num = _greedy_recolor_np(
            n, ro_e, sc, np.asarray(base.row_colors), base.num_colors)
        return Coloring(jnp.asarray(colors), num)


@registry.matrix_coloring.register("MIN_MAX_2RING")
@registry.matrix_coloring.register("GREEDY_MIN_MAX_2RING")
class MinMax2RingColoring(MatrixColoring):
    def color_matrix(self, A):
        return _jpl_min_max(A, edges=_square_edges(A))


@registry.matrix_coloring.register("MULTI_HASH")
class MultiHashColoring(MatrixColoring):
    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.max_num_hash = int(cfg.get("max_num_hash", scope))

    def color_matrix(self, A):
        # several independent hash rounds folded into the same fixed point
        return _jpl_min_max(A, max_rounds=max(self.max_num_hash * 4, 16))


@registry.matrix_coloring.register("ROUND_ROBIN")
class RoundRobinColoring(MatrixColoring):
    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.num_colors = int(cfg.get("num_colors", scope))

    def color_matrix(self, A):
        c = jnp.arange(A.num_rows, dtype=jnp.int32) % self.num_colors
        return Coloring(c, min(self.num_colors, max(A.num_rows, 1)))


@registry.matrix_coloring.register("UNIFORM")
class UniformColoring(MatrixColoring):
    """Geometric striping (uniform.cu): valid for banded stencils whose
    bandwidth is below num_colors."""

    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.num_colors = int(cfg.get("num_colors", scope))

    def color_matrix(self, A):
        return RoundRobinColoring.color_matrix(self, A)


@registry.matrix_coloring.register("SERIAL_GREEDY_BFS")
class SerialGreedyBfsColoring(MatrixColoring):
    """Host-side first-fit greedy in BFS order (serial_greedy_bfs.cu):
    the quality/determinism reference the parallel schemes are judged
    against."""

    def color_matrix(self, A):
        n = A.num_rows
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        colors = np.full(n, -1, np.int32)
        for i in range(n):
            nbr = ci[ro[i]:ro[i + 1]]
            used = set(colors[j] for j in nbr if j != i and colors[j] >= 0)
            c = 0
            while c in used:
                c += 1
            colors[i] = c
        return Coloring(jnp.asarray(colors), int(colors.max()) + 1 if n else 0)


def color_matrix(A: CsrMatrix, cfg, scope: str = "default") -> Coloring:
    """MatrixColoringFactory entry (src/core.cu:669). A user-attached
    coloring (AMGX_matrix_attach_coloring) overrides the configured
    scheme, matching the reference's attach semantics."""
    if A.user_colors is not None:
        return Coloring(A.user_colors, int(A.user_num_colors))
    name = str(cfg.get("matrix_coloring_scheme", scope))
    return registry.matrix_coloring.create(name, cfg, scope).color_matrix(A)
