"""Sparse general matrix-matrix multiply and the Galerkin triple product.

TPU-native analog of CSR_Multiply / csr_galerkin_product
(include/csr_multiply.h:78-96, src/csr_multiply.cu,
src/csr_multiply_detail.cu). The reference uses GPU hash tables; hash
tables do not map onto the TPU vector units, so this implementation is the
sort-based expand/coalesce formulation:

  expand:   every (i,k,a) of A pairs with every (k,j,b) of row k of B,
            producing candidate triplets (i, j, a*b) — pure gathers with a
            repeat-by-row-length index expansion;
  coalesce: sort candidates by (i,j) and segment-sum duplicates.

This is a *setup-time* operation (Galerkin products happen once per
hierarchy build); it runs eagerly with concrete shapes so the output nnz
can be data-dependent, every step dispatching XLA sort/gather/segment
kernels on device.

PLAN SPLIT (device-SpGEMM strategies, arXiv:1606.00545; SParSH-AMG's
symbolic/numeric setup split, arXiv:2007.00056): the sparsity pattern of
a Galerkin product is identical across every warm setup and resetup of
the same problem, yet the eager formulation re-dispatches the whole
sort/gather/segment chain each time. `RapPlan` separates the two
phases: the STRUCTURE phase runs once per pattern (host numpy: the
(A·P) expansion gather indices, the lexsorted coalesce order, segment
boundaries, and the output CSR pattern, memoized in a digest-keyed
cache) and the VALUE phase recomputes all numerics from the current
coefficients through those static indices — one fused Pallas kernel on
TPU (ops/pallas_spgemm.py), a sort-free gather/segment-sum program on
XLA rigs, or a reduceat sweep on host numpy hierarchies. `spgemm_plan=0`
short-circuits before any plan machinery runs, restoring the eager
composition bit-for-bit.
"""
from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..matrix import CsrMatrix, lexsort_rc


def _fold_diag(A: CsrMatrix) -> CsrMatrix:
    """Fold an externally-stored diagonal (DIAG property) back into the
    CSR entries so the expand/coalesce formulation sees the full matrix."""
    if not A.has_external_diag:
        return A
    rows, cols, vals = A.coo()
    n = A.num_rows
    d_rows = jnp.arange(n, dtype=jnp.int32)
    return CsrMatrix.from_coo(
        jnp.concatenate([rows, d_rows]),
        jnp.concatenate([cols, d_rows]),
        jnp.concatenate([vals, A.diag]),
        n, A.num_cols, block_dims=(A.block_dimx, A.block_dimy))


def _expand(A: CsrMatrix, B: CsrMatrix):
    """Candidate COO triplets of A@B (indices only + source pointers)."""
    a_rows, a_cols, _ = A.coo()
    b_row_nnz = jnp.diff(B.row_offsets)
    counts = b_row_nnz[a_cols]                       # per-A-nnz expansion
    total = int(jnp.sum(counts))
    cum = jnp.concatenate([jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)])
    src_a = jnp.repeat(jnp.arange(A.nnz, dtype=jnp.int32), counts,
                       total_repeat_length=total)
    offset_in_row = jnp.arange(total, dtype=jnp.int32) - \
        cum[src_a].astype(jnp.int32)
    src_b = B.row_offsets[a_cols[src_a]] + offset_in_row
    out_rows = a_rows[src_a]
    out_cols = B.col_indices[src_b]
    return out_rows, out_cols, src_a, src_b


def _on_host(A: CsrMatrix) -> bool:
    import numpy as np
    from ..matrix import device_setup_forced
    if device_setup_forced():
        return False             # setup_backend=device: jnp pipeline
    if isinstance(A.values, np.ndarray):
        return True
    try:
        return next(iter(A.values.devices())).platform == "cpu"
    except Exception:
        return False


def csr_multiply(A: CsrMatrix, B: CsrMatrix) -> CsrMatrix:
    """C = A @ B for scalar or block CSR (block: bxb @ bxb -> bxb).

    On the host backend the product runs through the native Gustavson
    sweep (native/src/spgemm.cpp — the csr_multiply.h analog): the
    sort-based jnp formulation below is shaped for accelerators, where
    it is the only option, but costs ~1 s per product at 32^3 scale on
    a single CPU thread."""
    assert A.num_cols == B.num_rows, (A.shape, B.shape)
    A, B = _fold_diag(A), _fold_diag(B)
    if not A.is_block and _on_host(A) and _on_host(B):
        from .. import native
        import numpy as np
        out = native.spgemm_native(
            A.num_rows, B.num_cols, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values),
            np.asarray(B.row_offsets), np.asarray(B.col_indices),
            np.asarray(B.values))
        if out is not None:
            cp, cc, cv = out
            return CsrMatrix.from_scipy_like(
                cp.astype(np.int32), cc,
                jnp.asarray(cv.astype(np.asarray(A.values).dtype)),
                A.num_rows, B.num_cols)
    out_rows, out_cols, src_a, src_b = _expand(A, B)
    if A.is_block:
        prods = jnp.einsum("nxk,nky->nxy", A.values[src_a], B.values[src_b])
    else:
        prods = A.values[src_a] * B.values[src_b]
    order = lexsort_rc(out_rows, out_cols)
    out_rows, out_cols, prods = (out_rows[order], out_cols[order],
                                 prods[order])
    if out_rows.shape[0] == 0:
        return CsrMatrix.from_scipy_like(
            jnp.zeros(A.num_rows + 1, jnp.int32), out_cols, prods,
            A.num_rows, B.num_cols, (A.block_dimx, B.block_dimy))
    newseg = jnp.concatenate(
        [jnp.ones((1,), bool),
         (out_rows[1:] != out_rows[:-1]) | (out_cols[1:] != out_cols[:-1])])
    seg = jnp.cumsum(newseg) - 1
    nuniq = int(seg[-1]) + 1
    first = jnp.nonzero(newseg, size=nuniq)[0]
    vals = jax.ops.segment_sum(prods, seg, num_segments=nuniq,
                               indices_are_sorted=True)
    rows_u, cols_u = out_rows[first], out_cols[first]
    counts = jnp.bincount(rows_u, length=A.num_rows)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return CsrMatrix.from_scipy_like(
        row_offsets, cols_u, vals, A.num_rows, B.num_cols,
        (A.block_dimx, B.block_dimy))


def csr_add(A: CsrMatrix, B: CsrMatrix) -> CsrMatrix:
    """C = A + B by COO concatenation + coalesce (csr_RAP_sparse_add
    analog, include/csr_multiply.h)."""
    assert A.shape == B.shape
    ar, ac, av = _fold_diag(A).coo()
    br, bc, bv = _fold_diag(B).coo()
    rows = jnp.concatenate([ar, br])
    cols = jnp.concatenate([ac, bc])
    vals = jnp.concatenate([av, bv])
    return CsrMatrix.from_coo(rows, cols, vals, A.num_rows, A.num_cols,
                              block_dims=(A.block_dimx, A.block_dimy))


def galerkin_rap(R: CsrMatrix, A: CsrMatrix, P: CsrMatrix) -> CsrMatrix:
    """Coarse operator A_c = R @ A @ P (csr_galerkin_product analog,
    include/csr_multiply.h:96).

    Host path: ONE fused native sweep (native/src/rap.cpp) — the R*A
    intermediate never materializes or crosses the Python boundary, and
    the result stays numpy-backed so the rest of the host hierarchy
    build (amg_host_setup) never round-trips through XLA:CPU arrays."""
    import numpy as np
    if not (A.is_block or R.has_external_diag or A.has_external_diag
            or P.has_external_diag) and _on_host(A) and _on_host(R) \
            and _on_host(P) and np.asarray(A.values).dtype.kind == "f" \
            and np.asarray(P.values).dtype.kind == "f" \
            and np.asarray(R.values).dtype.kind == "f":
        from .. import native
        out = native.rap_native(
            R.num_rows, A.num_rows, P.num_cols,
            np.asarray(R.row_offsets), np.asarray(R.col_indices),
            np.asarray(R.values),
            np.asarray(A.row_offsets), np.asarray(A.col_indices),
            np.asarray(A.values),
            np.asarray(P.row_offsets), np.asarray(P.col_indices),
            np.asarray(P.values))
        if out is not None:
            cp, cc, cv = out
            return CsrMatrix(
                row_offsets=cp.astype(np.int32), col_indices=cc,
                values=cv.astype(np.asarray(A.values).dtype, copy=False),
                num_rows=R.num_rows, num_cols=P.num_cols)
    return csr_multiply(csr_multiply(R, A), P)


# ---------------------------------------------------------------------------
# plan-split RAP: the structure phase (RapPlan) + value-phase dispatch
# ---------------------------------------------------------------------------


def plan_enabled(cfg, scope) -> bool:
    """`spgemm_plan` knob gate: '0' restores the eager composition
    (no plan machinery runs at all); 'auto'/'1' take the plan split."""
    return str(cfg.get("spgemm_plan", scope)) != "0"


class RapPlan:
    """Static recipe for one Galerkin product's numerics.

    Built once per sparsity pattern from the operand STRUCTURES only
    (host numpy); the value phase then reads the current coefficients
    through precomputed gather indices and sorted-segment boundaries —
    no sort, argsort, unique, or data-dependent shape anywhere.

    Two forms share the class:

    - kind="agg" (piecewise-constant P): the product collapses to
      relabeling A's entries by aggregate id. `st` is the lexsorted
      candidate permutation into the (diag-folded) value vector and
      `seg2`/`starts2` the coalesce segments. `sr` is None (unit
      weights); the output mirrors `_compact_coarse` (structure-
      complete, initialized).
    - kind="rap" (general CSR R/A/P): stage 1 expands T = A·P
      (`sa`/`sp` candidate gathers + `seg1`), stage 2 expands
      C = R·T (`sr`/`st` + `seg2`); the output mirrors the eager
      `galerkin_rap` CSR (the caller init()s it).

    Index arrays live as host numpy (the numpy reduceat route and the
    kernel-chunk builder read them); `dev()` uploads device twins once
    per plan (the slab/kernel routes), exactly like the GEO structure
    cache — a warm setup re-uploads nothing."""

    kind = "rap"

    def __init__(self, kind, stage1, sr, st, seg2, starts2, nU,
                 fold_diag, row_offsets, col_indices, row_ids,
                 diag_idx, num_rows, num_cols):
        self.kind = kind
        self.stage1 = stage1      # None | dict(sa, sp, seg1, starts1, nT)
        self.sr = sr
        self.st = st
        self.seg2 = seg2
        self.starts2 = starts2
        self.nU = int(nU)
        self.fold_diag = bool(fold_diag)
        self.row_offsets = row_offsets
        self.col_indices = col_indices
        self.row_ids = row_ids
        self.diag_idx = diag_idx
        self.num_rows = int(num_rows)
        self.num_cols = int(num_cols)
        self._dev = None
        self._kernel = None       # None = unbuilt, False = declined

    def nbytes(self) -> int:
        total = 0
        for a in (self.sr, self.st, self.seg2, self.starts2,
                  self.row_offsets, self.col_indices, self.row_ids,
                  self.diag_idx):
            if a is not None:
                total += int(a.nbytes)
        if self.stage1 is not None:
            for k in ("sa", "sp", "seg1", "starts1"):
                total += int(self.stage1[k].nbytes)
        return total

    def dev(self):
        """Device twins of the gather/segment arrays (uploaded once)."""
        if self._dev is None:
            d = {"st": jnp.asarray(self.st),
                 "seg2": jnp.asarray(self.seg2)}
            if self.sr is not None:
                d["sr"] = jnp.asarray(self.sr)
            if self.stage1 is not None:
                d["sa"] = jnp.asarray(self.stage1["sa"])
                d["sp"] = jnp.asarray(self.stage1["sp"])
                d["seg1"] = jnp.asarray(self.stage1["seg1"])
            self._dev = d
        return self._dev

    def dev_structure(self):
        """Device twins of the output CSR structure (uploaded once)."""
        d = self.dev()
        if "row_offsets" not in d:
            d["row_offsets"] = jnp.asarray(self.row_offsets)
            d["col_indices"] = jnp.asarray(self.col_indices)
            d["row_ids"] = jnp.asarray(self.row_ids)
            d["diag_idx"] = jnp.asarray(self.diag_idx)
        return d


def _np_expand_pattern(a_ro, a_ci, b_ro, b_ci):
    """Candidate COO triplets of A@B from patterns (numpy mirror of
    `_expand`): (out_rows, out_cols, src_a, src_b), int64."""
    a_rows = np.repeat(np.arange(a_ro.shape[0] - 1, dtype=np.int64),
                       np.diff(a_ro))
    counts = np.diff(b_ro)[a_ci]
    total = int(counts.sum())
    src_a = np.repeat(np.arange(a_ci.shape[0], dtype=np.int64), counts)
    cum = np.concatenate([np.zeros(1, np.int64),
                          np.cumsum(counts, dtype=np.int64)])
    off = np.arange(total, dtype=np.int64) - cum[src_a]
    src_b = b_ro[a_ci[src_a]].astype(np.int64) + off
    return a_rows[src_a], b_ci[src_b].astype(np.int64), src_a, src_b


def _np_coalesce(rows, cols):
    """Lexsorted coalesce of candidate coordinates: (order, seg,
    starts, rows_u, cols_u). `order` is the stable (row, col) sort of
    the candidates, `seg` the segment id per sorted candidate, `starts`
    the (nU+1,) segment boundaries."""
    order = np.lexsort((cols, rows))
    r_s, c_s = rows[order], cols[order]
    if r_s.shape[0] == 0:
        return (order, np.zeros(0, np.int32), np.zeros(1, np.int64),
                r_s, c_s)
    first = np.concatenate(
        [np.ones(1, bool), (r_s[1:] != r_s[:-1]) | (c_s[1:] != c_s[:-1])])
    seg = (np.cumsum(first) - 1).astype(np.int32)
    # int32 boundaries: candidate totals are guarded < 2^31 by the
    # builders, and halving these arrays matters — a 128^3 classical
    # L0 plan is GB-scale
    starts = np.concatenate([np.flatnonzero(first).astype(np.int32),
                             np.asarray([r_s.shape[0]], np.int32)])
    return order, seg, starts, r_s[first], c_s[first]


def _np_csr_structure(rows_u, cols_u, num_rows):
    """Output CSR structure of the coalesced entries (sorted by
    (row, col)): row_offsets, col_indices, row_ids, diag_idx — the
    same fields the eager `_compact_coarse` emits."""
    counts = np.bincount(rows_u, minlength=num_rows)
    row_offsets = np.zeros(num_rows + 1, np.int32)
    row_offsets[1:] = np.cumsum(counts).astype(np.int32)
    diag_idx = np.full(num_rows, -1, np.int32)
    is_diag = cols_u == rows_u
    diag_idx[rows_u[is_diag].astype(np.int64)] = \
        np.flatnonzero(is_diag).astype(np.int32)
    return (row_offsets, cols_u.astype(np.int32),
            rows_u.astype(np.int32), diag_idx)


def _host_pattern(*arrays):
    """Host numpy views of pattern arrays regardless of backend
    forcing (the plan is a host-side artifact; `host_arrays` respects
    the device forcing, `np.asarray` is the fallback pull)."""
    return [None if a is None else np.asarray(a) for a in arrays]


def build_agg_plan(A: CsrMatrix, agg, nc: int):
    """Structure phase of the aggregation relabel Galerkin: candidates
    are A's (diag-folded) entries relabeled by aggregate id, in the
    lexsorted coalesce order. Returns None for block matrices."""
    if A.is_block:
        return None
    ro, ci, ri = _host_pattern(A.row_offsets, A.col_indices, A.row_ids)
    aggv = np.asarray(agg).ravel().astype(np.int64)
    if ri is not None and ri.shape[0] == ci.shape[0]:
        rows = ri.astype(np.int64)
    else:
        rows = np.repeat(np.arange(A.num_rows, dtype=np.int64),
                         np.diff(ro))
    cols = ci.astype(np.int64)
    r2 = aggv[rows]
    c2 = aggv[cols]
    fold = A.has_external_diag
    if fold:
        r2 = np.concatenate([r2, aggv])
        c2 = np.concatenate([c2, aggv])
    if r2.shape[0] >= np.iinfo(np.int32).max:
        return None
    order, seg, starts, rows_u, cols_u = _np_coalesce(r2, c2)
    structure = _np_csr_structure(rows_u, cols_u, int(nc))
    return RapPlan("agg", None, None, order.astype(np.int32), seg,
                   starts, rows_u.shape[0], fold, *structure,
                   num_rows=int(nc), num_cols=int(nc))


def build_rap_plan(R: CsrMatrix, A: CsrMatrix, P: CsrMatrix):
    """Structure phase of the general Galerkin triple product: stage 1
    expands/coalesces T = A·P, stage 2 expands/coalesces C = R·T.
    Returns None for block matrices or external diagonals on R/P (the
    eager path handles those; A's external diagonal folds in)."""
    if A.is_block or R.is_block or P.is_block or \
            R.has_external_diag or P.has_external_diag:
        return None
    a_ro, a_ci = _host_pattern(A.row_offsets, A.col_indices)
    p_ro, p_ci = _host_pattern(P.row_offsets, P.col_indices)
    r_ro, r_ci = _host_pattern(R.row_offsets, R.col_indices)
    fold = A.has_external_diag
    if fold:
        n = A.num_rows
        rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(a_ro))
        cols = a_ci.astype(np.int64)
        rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
        cols = np.concatenate([cols, np.arange(n, dtype=np.int64)])
        order = np.lexsort((cols, rows))
        # folded pattern, sorted: entry e reads value vector slot
        # fold_src[e] of concat(values, diag)
        fold_src = order.astype(np.int64)
        rows, cols = rows[order], cols[order]
        counts = np.bincount(rows, minlength=n)
        a_ro = np.zeros(n + 1, np.int64)
        a_ro[1:] = np.cumsum(counts)
        a_ci = cols
    else:
        fold_src = None
    # stage 1: T = A @ P
    t_rows_c, t_cols_c, s1a, s1p = _np_expand_pattern(
        a_ro, a_ci, p_ro, p_ci)
    if t_rows_c.shape[0] >= np.iinfo(np.int32).max:
        return None
    order1, seg1, starts1, t_rows, t_cols = _np_coalesce(
        t_rows_c, t_cols_c)
    sa = s1a[order1]
    if fold_src is not None:
        sa = fold_src[sa]
    sp = s1p[order1]
    nT = t_rows.shape[0]
    t_counts = np.bincount(t_rows, minlength=A.num_rows)
    t_ro = np.zeros(A.num_rows + 1, np.int64)
    t_ro[1:] = np.cumsum(t_counts)
    # stage 2: C = R @ T
    c_rows_c, c_cols_c, s2r, s2t = _np_expand_pattern(
        r_ro, r_ci, t_ro, t_cols)
    if c_rows_c.shape[0] >= np.iinfo(np.int32).max:
        return None
    order2, seg2, starts2, c_rows, c_cols = _np_coalesce(
        c_rows_c, c_cols_c)
    sr = s2r[order2].astype(np.int32)
    st = s2t[order2].astype(np.int32)
    stage1 = {"sa": sa.astype(np.int32), "sp": sp.astype(np.int32),
              "seg1": seg1, "starts1": starts1, "nT": int(nT)}
    structure = _np_csr_structure(c_rows, c_cols, R.num_rows)
    return RapPlan("rap", stage1, sr, st, seg2, starts2,
                   c_rows.shape[0], fold, *structure,
                   num_rows=R.num_rows, num_cols=P.num_cols)


# -- plan cache (digest-keyed; survives level objects across warm
#    setups of the same pattern) ---------------------------------------------

_PLAN_CACHE = {}                        # digest -> RapPlan, LRU order
# sized so one 128^3-grade classical hierarchy's plans (L0 alone is
# GB-scale index arrays) co-reside with headroom; host RAM, not HBM
_PLAN_CACHE_MAX_BYTES = 6 << 30


def _pattern_digest(meta, *arrays) -> bytes:
    h = hashlib.blake2b(repr(meta).encode(), digest_size=16)
    for a in arrays:
        if a is None:
            h.update(b"<none>")
            continue
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str(a.dtype).encode() + str(a.shape).encode())
        h.update(memoryview(a))
    return h.digest()


def _cache_get(key):
    from ..telemetry import metrics as _tm
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        _PLAN_CACHE[key] = _PLAN_CACHE.pop(key)       # LRU bump
        _tm.inc("amg.spgemm.plan_hit")
    return hit


def _cache_put(key, plan):
    from ..telemetry import metrics as _tm
    _tm.inc("amg.spgemm.plan_build")
    _PLAN_CACHE[key] = plan
    total = 0
    for k in reversed(list(_PLAN_CACHE)):
        total += _PLAN_CACHE[k].nbytes()
        if total > _PLAN_CACHE_MAX_BYTES and k != key:
            del _PLAN_CACHE[k]


def get_agg_plan(A: CsrMatrix, agg, nc: int):
    """Digest-cached relabel plan for (A pattern, aggregates map)."""
    key = _pattern_digest(
        ("agg", A.num_rows, A.num_cols, int(nc), A.has_external_diag),
        A.row_offsets, A.col_indices, np.asarray(agg))
    plan = _cache_get(key)
    if plan is None:
        plan = build_agg_plan(A, agg, nc)
        if plan is not None:
            _cache_put(key, plan)
    return plan


def get_rap_plan(R: CsrMatrix, A: CsrMatrix, P: CsrMatrix):
    """Digest-cached triple-product plan for (R, A, P) patterns."""
    if A.is_block or R.is_block or P.is_block:
        return None
    key = _pattern_digest(
        ("rap", R.num_rows, A.num_rows, P.num_cols,
         A.has_external_diag),
        R.row_offsets, R.col_indices, A.row_offsets, A.col_indices,
        P.row_offsets, P.col_indices)
    plan = _cache_get(key)
    if plan is None:
        plan = build_rap_plan(R, A, P)
        if plan is not None:
            _cache_put(key, plan)
    return plan


# -- value phase --------------------------------------------------------------


def _np_reduce_segments(cand, starts):
    if cand.shape[0] == 0:
        return cand
    return np.add.reduceat(cand, starts[:-1])


def _rap_values_numpy(plan: RapPlan, af, r_vals, p_vals):
    """Host value phase: the native flat-FMA sweep through the plan's
    precomputed indices (native/src/rap_values.cpp — the route
    host-built hierarchies take, keeping the result numpy-backed like
    the native RAP it replaces), or two numpy reduceat passes when the
    toolchain is unavailable. Both sum each segment strictly
    left-to-right, so the routes agree to the last bit."""
    if af.dtype == np.float64 \
            and (r_vals is None or r_vals.dtype == np.float64) \
            and (p_vals is None or p_vals.dtype == np.float64):
        from .. import native
        out = native.rap_plan_values_native(
            plan.stage1, plan.sr, plan.st, plan.starts2, plan.nU,
            af, p_vals, r_vals)
        if out is not None:
            return out
    if plan.stage1 is not None:
        s1 = plan.stage1
        cand1 = af[s1["sa"]] * p_vals[s1["sp"]]
        base = _np_reduce_segments(cand1, s1["starts1"])
    else:
        base = af
    cand2 = base[plan.st]
    if plan.sr is not None:
        cand2 = r_vals[plan.sr] * cand2
    return _np_reduce_segments(cand2, plan.starts2)


@functools.partial(jax.jit, static_argnames=("nT", "nU", "has1",
                                             "has_r"))
def _rap_values_slab(af, r_vals, p_vals, sa, sp, seg1, sr, st, seg2,
                     nT: int, nU: int, has1: bool, has_r: bool):
    """XLA value phase (CPU meshes / f64 / kernel-declined): gathers +
    sorted segment-sums through the static plan indices — zero sort /
    argsort / unique primitives in the jaxpr (the acceptance contract
    of the plan split's CPU route)."""
    if has1:
        cand1 = af[sa] * p_vals[sp]
        base = jax.ops.segment_sum(cand1, seg1, num_segments=nT,
                                   indices_are_sorted=True)
    else:
        base = af
    cand2 = base[st]
    if has_r:
        cand2 = r_vals[sr] * cand2
    return jax.ops.segment_sum(cand2, seg2, num_segments=nU,
                               indices_are_sorted=True)


def _fold_values(plan, A: CsrMatrix, np_route: bool):
    vals = A.values
    if not plan.fold_diag:
        return np.asarray(vals) if np_route else vals
    if np_route:
        return np.concatenate([np.asarray(vals), np.asarray(A.diag)])
    return jnp.concatenate([jnp.asarray(vals), jnp.asarray(A.diag)])


def rap_values(plan: RapPlan, A: CsrMatrix, R=None, P=None):
    """Value phase dispatch: recompute the product's numerics from the
    CURRENT coefficients through the plan. Route order: host numpy
    (host-resident operands outside a forced-device setup), the fused
    Pallas kernel (TPU / interpret-forced, f32, within budget —
    ops/pallas_spgemm.py), the XLA slab program otherwise."""
    r_vals = None if R is None else R.values
    p_vals = None if P is None else P.values
    if _on_host(A) and (R is None or _on_host(R)) \
            and (P is None or _on_host(P)):
        af = _fold_values(plan, A, np_route=True)
        return _rap_values_numpy(
            plan, af,
            None if r_vals is None else np.asarray(r_vals),
            None if p_vals is None else np.asarray(p_vals))
    af = _fold_values(plan, A, np_route=False)
    from . import pallas_spgemm as _pk
    if _pk.rap_kernel_ready(plan, af.dtype):
        return _pk.rap_value_call(plan, af, r_vals, p_vals)
    d = plan.dev()
    s1 = plan.stage1
    return _rap_values_slab(
        af,
        None if r_vals is None else jnp.asarray(r_vals),
        None if p_vals is None else jnp.asarray(p_vals),
        d.get("sa"), d.get("sp"), d.get("seg1"), d.get("sr"),
        d["st"], d["seg2"],
        0 if s1 is None else s1["nT"], plan.nU,
        s1 is not None, plan.sr is not None)


def plan_coarse_matrix(plan: RapPlan, A: CsrMatrix, R=None,
                       P=None) -> CsrMatrix:
    """Value phase + output assembly. kind="agg" emits the structure-
    complete initialized CSR `_compact_coarse` emits (the hierarchy
    builds the SpMV layout on top); kind="rap" emits the plain CSR the
    eager `galerkin_rap` emits (the caller init()s it). The structure
    arrays come from the plan (device twins uploaded once per plan on
    jnp routes — only the VALUES are new work per setup)."""
    vals = rap_values(plan, A, R, P)
    target = A.values
    if hasattr(vals, "dtype") and vals.dtype != target.dtype:
        vals = vals.astype(target.dtype)
    if isinstance(vals, np.ndarray):
        ro, ci, ri, di = (plan.row_offsets, plan.col_indices,
                          plan.row_ids, plan.diag_idx)
    else:
        d = plan.dev_structure()
        ro, ci, ri, di = (d["row_offsets"], d["col_indices"],
                          d["row_ids"], d["diag_idx"])
    if plan.kind == "agg":
        return CsrMatrix(
            row_offsets=ro, col_indices=ci, values=vals, diag=None,
            row_ids=ri, diag_idx=di, ell_cols=None, ell_vals=None,
            dia_offsets=None, dia_vals=None, num_rows=plan.num_rows,
            num_cols=plan.num_cols, block_dimx=1, block_dimy=1,
            initialized=True)
    return CsrMatrix(row_offsets=ro, col_indices=ci, values=vals,
                     num_rows=plan.num_rows, num_cols=plan.num_cols)
