"""Sparse general matrix-matrix multiply and the Galerkin triple product.

TPU-native analog of CSR_Multiply / csr_galerkin_product
(include/csr_multiply.h:78-96, src/csr_multiply.cu,
src/csr_multiply_detail.cu). The reference uses GPU hash tables; hash
tables do not map onto the TPU vector units, so this implementation is the
sort-based expand/coalesce formulation:

  expand:   every (i,k,a) of A pairs with every (k,j,b) of row k of B,
            producing candidate triplets (i, j, a*b) — pure gathers with a
            repeat-by-row-length index expansion;
  coalesce: sort candidates by (i,j) and segment-sum duplicates.

This is a *setup-time* operation (Galerkin products happen once per
hierarchy build); it runs eagerly with concrete shapes so the output nnz
can be data-dependent, every step dispatching XLA sort/gather/segment
kernels on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..matrix import CsrMatrix, lexsort_rc


def _fold_diag(A: CsrMatrix) -> CsrMatrix:
    """Fold an externally-stored diagonal (DIAG property) back into the
    CSR entries so the expand/coalesce formulation sees the full matrix."""
    if not A.has_external_diag:
        return A
    rows, cols, vals = A.coo()
    n = A.num_rows
    d_rows = jnp.arange(n, dtype=jnp.int32)
    return CsrMatrix.from_coo(
        jnp.concatenate([rows, d_rows]),
        jnp.concatenate([cols, d_rows]),
        jnp.concatenate([vals, A.diag]),
        n, A.num_cols, block_dims=(A.block_dimx, A.block_dimy))


def _expand(A: CsrMatrix, B: CsrMatrix):
    """Candidate COO triplets of A@B (indices only + source pointers)."""
    a_rows, a_cols, _ = A.coo()
    b_row_nnz = jnp.diff(B.row_offsets)
    counts = b_row_nnz[a_cols]                       # per-A-nnz expansion
    total = int(jnp.sum(counts))
    cum = jnp.concatenate([jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)])
    src_a = jnp.repeat(jnp.arange(A.nnz, dtype=jnp.int32), counts,
                       total_repeat_length=total)
    offset_in_row = jnp.arange(total, dtype=jnp.int32) - \
        cum[src_a].astype(jnp.int32)
    src_b = B.row_offsets[a_cols[src_a]] + offset_in_row
    out_rows = a_rows[src_a]
    out_cols = B.col_indices[src_b]
    return out_rows, out_cols, src_a, src_b


def _on_host(A: CsrMatrix) -> bool:
    import numpy as np
    from ..matrix import device_setup_forced
    if device_setup_forced():
        return False             # setup_backend=device: jnp pipeline
    if isinstance(A.values, np.ndarray):
        return True
    try:
        return next(iter(A.values.devices())).platform == "cpu"
    except Exception:
        return False


def csr_multiply(A: CsrMatrix, B: CsrMatrix) -> CsrMatrix:
    """C = A @ B for scalar or block CSR (block: bxb @ bxb -> bxb).

    On the host backend the product runs through the native Gustavson
    sweep (native/src/spgemm.cpp — the csr_multiply.h analog): the
    sort-based jnp formulation below is shaped for accelerators, where
    it is the only option, but costs ~1 s per product at 32^3 scale on
    a single CPU thread."""
    assert A.num_cols == B.num_rows, (A.shape, B.shape)
    A, B = _fold_diag(A), _fold_diag(B)
    if not A.is_block and _on_host(A) and _on_host(B):
        from .. import native
        import numpy as np
        out = native.spgemm_native(
            A.num_rows, B.num_cols, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values),
            np.asarray(B.row_offsets), np.asarray(B.col_indices),
            np.asarray(B.values))
        if out is not None:
            cp, cc, cv = out
            return CsrMatrix.from_scipy_like(
                cp.astype(np.int32), cc,
                jnp.asarray(cv.astype(np.asarray(A.values).dtype)),
                A.num_rows, B.num_cols)
    out_rows, out_cols, src_a, src_b = _expand(A, B)
    if A.is_block:
        prods = jnp.einsum("nxk,nky->nxy", A.values[src_a], B.values[src_b])
    else:
        prods = A.values[src_a] * B.values[src_b]
    order = lexsort_rc(out_rows, out_cols)
    out_rows, out_cols, prods = (out_rows[order], out_cols[order],
                                 prods[order])
    if out_rows.shape[0] == 0:
        return CsrMatrix.from_scipy_like(
            jnp.zeros(A.num_rows + 1, jnp.int32), out_cols, prods,
            A.num_rows, B.num_cols, (A.block_dimx, B.block_dimy))
    newseg = jnp.concatenate(
        [jnp.ones((1,), bool),
         (out_rows[1:] != out_rows[:-1]) | (out_cols[1:] != out_cols[:-1])])
    seg = jnp.cumsum(newseg) - 1
    nuniq = int(seg[-1]) + 1
    first = jnp.nonzero(newseg, size=nuniq)[0]
    vals = jax.ops.segment_sum(prods, seg, num_segments=nuniq,
                               indices_are_sorted=True)
    rows_u, cols_u = out_rows[first], out_cols[first]
    counts = jnp.bincount(rows_u, length=A.num_rows)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return CsrMatrix.from_scipy_like(
        row_offsets, cols_u, vals, A.num_rows, B.num_cols,
        (A.block_dimx, B.block_dimy))


def csr_add(A: CsrMatrix, B: CsrMatrix) -> CsrMatrix:
    """C = A + B by COO concatenation + coalesce (csr_RAP_sparse_add
    analog, include/csr_multiply.h)."""
    assert A.shape == B.shape
    ar, ac, av = _fold_diag(A).coo()
    br, bc, bv = _fold_diag(B).coo()
    rows = jnp.concatenate([ar, br])
    cols = jnp.concatenate([ac, bc])
    vals = jnp.concatenate([av, bv])
    return CsrMatrix.from_coo(rows, cols, vals, A.num_rows, A.num_cols,
                              block_dims=(A.block_dimx, A.block_dimy))


def galerkin_rap(R: CsrMatrix, A: CsrMatrix, P: CsrMatrix) -> CsrMatrix:
    """Coarse operator A_c = R @ A @ P (csr_galerkin_product analog,
    include/csr_multiply.h:96).

    Host path: ONE fused native sweep (native/src/rap.cpp) — the R*A
    intermediate never materializes or crosses the Python boundary, and
    the result stays numpy-backed so the rest of the host hierarchy
    build (amg_host_setup) never round-trips through XLA:CPU arrays."""
    import numpy as np
    if not (A.is_block or R.has_external_diag or A.has_external_diag
            or P.has_external_diag) and _on_host(A) and _on_host(R) \
            and _on_host(P) and np.asarray(A.values).dtype.kind == "f" \
            and np.asarray(P.values).dtype.kind == "f" \
            and np.asarray(R.values).dtype.kind == "f":
        from .. import native
        out = native.rap_native(
            R.num_rows, A.num_rows, P.num_cols,
            np.asarray(R.row_offsets), np.asarray(R.col_indices),
            np.asarray(R.values),
            np.asarray(A.row_offsets), np.asarray(A.col_indices),
            np.asarray(A.values),
            np.asarray(P.row_offsets), np.asarray(P.col_indices),
            np.asarray(P.values))
        if out is not None:
            cp, cc, cv = out
            return CsrMatrix(
                row_offsets=cp.astype(np.int32), col_indices=cc,
                values=cv.astype(np.asarray(A.values).dtype, copy=False),
                num_rows=R.num_rows, num_cols=P.num_cols)
    return csr_multiply(csr_multiply(R, A), P)
