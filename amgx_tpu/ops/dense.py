"""TPU-safe small dense linear algebra.

XLA:TPU expands `lu` via LuDecompositionExpander, which only implements
F32/C64 — so `jnp.linalg.inv/det/solve` and `jax.scipy.linalg.lu_factor`
fail to compile for f64 operands on TPU (the dDDI default mode).
TriangularSolve and the QR expander *are* implemented for f64, so every
dense factorization here goes through Householder QR instead:

    A = Q R   =>   A^{-1} = R^{-1} Q^T,  |det A| = prod |r_ii|.

These cover the reference's cuSolverDn/LAPACK uses (dense LU coarse
solver getrf/getrs, src/solvers/dense_lu_solver.cu:514-580; batched
block-diagonal inverses, src/solvers/block_jacobi_solver.cu) with one
dtype-polymorphic implementation that compiles on both CPU and TPU.
All routines accept batched (..., n, n) operands.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl


def solve_qr(a, b):
    """Solve a x = b via QR (TPU-safe jnp.linalg.solve replacement).

    `b` may be (..., n) or (..., n, k).
    """
    q, r = jnp.linalg.qr(a)
    vec = b.ndim == a.ndim - 1
    if vec:
        b = b[..., None]
    y = jnp.swapaxes(q, -1, -2) @ b
    x = jsl.solve_triangular(r, y, lower=False)
    return x[..., 0] if vec else x


def inverse(a):
    """A^{-1} via QR (TPU-safe jnp.linalg.inv replacement)."""
    q, r = jnp.linalg.qr(a)
    return jsl.solve_triangular(r, jnp.swapaxes(q, -1, -2), lower=False)


def abs_det(a):
    """|det A| = prod |diag(R)| (TPU-safe |jnp.linalg.det| replacement;
    used only for singularity checks, so the sign is not needed)."""
    _, r = jnp.linalg.qr(a)
    return jnp.abs(jnp.prod(jnp.diagonal(r, axis1=-2, axis2=-1), axis=-1))


def safe_inverse(a):
    """Batched inverse with singular blocks replaced by identity (the
    block analog of safe_recip's 1/0 -> 0 policy)."""
    n = a.shape[-1]
    eye = jnp.eye(n, dtype=a.dtype)
    ok = abs_det(a) > 0
    a_safe = jnp.where(ok[..., None, None], a, eye)
    return jnp.where(ok[..., None, None], inverse(a_safe), eye)
