"""Multi-RHS sparse matrix products: Y = A @ X for X of shape (B, n).

The batched-solve subsystem (amgx_tpu/batch/) drives the existing
solver/cycle code under `jax.vmap`; most ops batch through their standard
batching rules, but the SpMV layouts have better shapes available when
only the *vector* carries the batch axis and the matrix is shared:

- DIA: each stored diagonal multiplies a shifted (B, n) slab — the whole
  batch is one dense multiply-add per diagonal (the batch axis rides the
  sublane dimension for free; no per-system re-streaming of the values);
- ELL: one (n, k) gather of X produces (B, n, k); the reduction is an
  einsum the MXU handles as a batched matvec;
- CSR/SWELL: fall back to `jax.vmap` of the single-vector form.

These are also the implementations the Pallas kernels' `custom_vmap`
rules route to when the matrix operand is unbatched, so a vmapped solve
over many RHS against one matrix never pays a per-system values stream.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..matrix import CsrMatrix


def _cdt(dtype):
    """Accumulation dtype of the slab forms: sub-f32 (bf16) slabs
    upcast and accumulate in f32, mirroring the fused Pallas kernels
    (identity for f32/f64 — the casts fold away)."""
    from .pallas_spmv import compute_dtype
    return compute_dtype(dtype)


def spmv_dia_multi(A: CsrMatrix, X: jax.Array) -> jax.Array:
    """Y = A @ X for DIA-layout A and X of shape (B, n): one shifted
    dense multiply-add per stored diagonal, batch axis untouched."""
    n = A.num_rows
    offs = A.dia_offsets
    vals = A.dia_vals.reshape(len(offs), -1)[:, :n]
    left = max(0, -min(offs))
    right = max(0, n - A.num_cols + max(offs))
    Xp = jnp.pad(X, ((0, 0), (left, right)))
    Y = jnp.zeros((X.shape[0], n), X.dtype)
    for i, d in enumerate(offs):
        Y = Y + vals[i][None, :] * jax.lax.dynamic_slice_in_dim(
            Xp, left + d, n, axis=1)
    return Y


def spmv_ell_multi(A: CsrMatrix, X: jax.Array) -> jax.Array:
    """Y = A @ X for padded-ELL A and X of shape (B, n)."""
    Y = jnp.einsum("nk,bnk->bn", A.ell_vals, X[:, A.ell_cols])
    if A.has_external_diag:
        Y = Y + A.diag[None, :] * X[:, : A.num_rows]
    return Y


def spmv_multi(A: CsrMatrix, X: jax.Array) -> jax.Array:
    """Y = A @ X with X of shape (B, num_cols): the multi-RHS form of
    ops.spmv.spmv, dispatching on the layout chosen at init. Scalar
    matrices only (block batching goes through jax.vmap)."""
    from .spmv import spmv
    if X.ndim != 2:
        raise ValueError(f"spmv_multi: X must be (batch, n), got {X.shape}")
    if isinstance(A, CsrMatrix) and not A.is_block:
        if A.dia_offsets is not None and not A.has_external_diag:
            return spmv_dia_multi(A, X)
        if A.ell_cols is not None and A.swell_cols is None:
            return spmv_ell_multi(A, X)
    return jax.vmap(lambda x: spmv(A, x))(X)


def residual_multi(A: CsrMatrix, X: jax.Array, B: jax.Array) -> jax.Array:
    """R = B - A @ X, row per system."""
    return B - spmv_multi(A, X)


def smooth_dia_multi(A: CsrMatrix, B: jax.Array, X: jax.Array, taus,
                     dinv=None, with_residual: bool = True):
    """Multi-RHS form of the fused smoother (+ residual epilogue):
    X' = X after len(taus) damped sweeps

        X <- X + tau_s * dinv . (B - A X)

    and, when `with_residual`, R = B - A X'. Each sweep's SpMV is one
    shifted dense multiply-add per stored diagonal over the whole (B, n)
    slab — this is the route the fused Pallas kernels' custom_vmap rules
    take when only the vectors carry the batch axis (solve_many's
    shared-matrix shape), so a vmapped cycle's presmooth+residual pair
    streams A's values once per slab pass instead of once per system.
    The update order matches the Pallas kernel: (tau * residual) * dinv.
    bf16 slabs accumulate in f32 like the kernels (only the values
    stream stays narrow; outputs round back to the input dtype)."""
    dt = X.dtype
    cdt = _cdt(dt)
    X = X.astype(cdt)
    B = B.astype(cdt)
    for t in range(taus.shape[0]):
        upd = taus[t].astype(cdt) * (B - spmv_dia_multi(A, X))
        if dinv is not None:
            upd = upd * dinv[None, :].astype(cdt)
        X = X + upd
    if with_residual:
        return X.astype(dt), (B - spmv_dia_multi(A, X)).astype(dt)
    return X.astype(dt)


def affine_window_sweeps(offsets, vals_w, b_w, x_w, taus, dinv_w,
                         W: int, with_residual: bool):
    """Damped-relaxation sweeps on a contiguous 1-D element window —
    the XLA mirror, in ELEMENT units, of the fused Pallas kernel's
    temporal blocking (ops/pallas_spmv.py `_dia_smooth_kernel`).

    Computes x' (and r when `with_residual`) EXACTLY for the W target
    elements [t0, t0 + W) of a DIA operator, given windows wide enough
    for the full dependence cone (m = max(0, -min(offsets)),
    M = max(0, max(offsets)), n_app = len(taus) + residual):

      x_w    (Wx,)   covering [t0 - n_app*m,       t0 + W + n_app*M)
      vals_w (k, Wv), b_w / dinv_w (Wv,)
                     covering [t0 - (n_app-1)*m,   t0 + W + (n_app-1)*M)

    Out-of-range window elements must be ZERO-filled (the DIA
    zero-padding semantics — a matrix edge and a zero-filled window
    edge are indistinguishable). Each sweep recomputes the Wv interior
    and zero-fills the shrinking cone edges, exactly like the kernel,
    so the W target elements come out bit-exact in exact arithmetic.

    This is the distributed fused path's workhorse (boundary-strip
    completion next to the per-shard kernel, and the whole-shard f64 /
    non-Pallas route — distributed/fused.py) and the parity reference
    the kernel tests compare against. bf16 windows upcast and the
    sweeps accumulate in f32, exactly like the kernel's per-block
    upcast — so the spliced boundary strips and the kernel interior
    share one arithmetic."""
    n_steps = int(taus.shape[0])
    n_app = n_steps + (1 if with_residual else 0)
    m = max(0, -min(offsets))
    M = max(0, max(offsets))
    Wv = W + (n_app - 1) * (m + M)
    out_dt = x_w.dtype
    dt = _cdt(out_dt)
    x_w = x_w.astype(dt)
    b_w = b_w.astype(dt)
    dinv_w = None if dinv_w is None else dinv_w.astype(dt)

    def apply_a(s):
        acc = jnp.zeros((Wv,), dt)
        for i, d in enumerate(offsets):
            acc = acc + vals_w[i].astype(dt) * jax.lax.slice_in_dim(
                s, m + d, m + d + Wv, 1, 0)
        return acc

    s = x_w
    for t in range(n_steps):
        corr = taus[t].astype(dt) * (b_w - apply_a(s))
        if dinv_w is not None:
            corr = corr * dinv_w
        mid = jax.lax.slice_in_dim(s, m, m + Wv, 1, 0) + corr
        pieces = [mid]
        if m:
            pieces.insert(0, jnp.zeros((m,), dt))
        if M:
            pieces.append(jnp.zeros((M,), dt))
        s = jnp.concatenate(pieces) if len(pieces) > 1 else mid
    y = jax.lax.slice_in_dim(s, n_app * m, n_app * m + W,
                             1, 0).astype(out_dt)
    if not with_residual:
        return y
    r = b_w - apply_a(s)
    return y, jax.lax.slice_in_dim(r, (n_app - 1) * m,
                                   (n_app - 1) * m + W, 1, 0
                                   ).astype(out_dt)


# ---------------------------------------------------------------------------
# Krylov shell slab forms (the custom_vmap fallbacks of the fused
# SpMV+dot / cg_update kernels in ops/pallas_spmv.py — and the f64
# parity reference; solve_many's vector-only batches land here)
# ---------------------------------------------------------------------------


def spmv_dot_multi(A: CsrMatrix, P: jax.Array, Z=None, beta=None,
                   D=None, self_dot: bool = False):
    """Multi-RHS form of the fused SpMV + dot shell kernel
    (`_dia_spmv_dot_call`): optional direction-update prologue
    P' = Z + beta*P (beta per-system), AP = A @ P', the paired dot
    sum(d . AP) per system (d = D when a separate dot operand is
    streamed, else P'), and optionally AP . AP (BiCGStab's t.t).
    Returns the kernel call's tuple layout with a leading batch axis:
    (AP, pdot[, sdot]) or, with the prologue, (P', AP, pdot[, sdot]).
    bf16 slabs accumulate the prologue and the dots in f32 like the
    kernel; for f32/f64 the casts fold away, making this the f64
    parity reference."""
    dt = P.dtype
    cdt = _cdt(dt)
    if Z is not None:
        P = (Z.astype(cdt)
             + beta[..., None].astype(cdt) * P.astype(cdt)).astype(dt)
    AP = spmv_dia_multi(A, P)
    dvec = (P if D is None else D).astype(cdt)
    pdot = jnp.sum(dvec * AP.astype(cdt), axis=1)
    out = (AP, pdot) if Z is None else (P, AP, pdot)
    if self_dot:
        out = out + (jnp.sum(AP.astype(cdt) ** 2, axis=1),)
    return out


def cg_update_multi(X: jax.Array, P: jax.Array, R: jax.Array,
                    AP: jax.Array, alpha):
    """Multi-RHS form of the single-pass CG update kernel
    (`_cg_update_call`): X' = X + alpha P, R' = R - alpha AP, and the
    per-system r'.r' dot (alpha per-system). The dot reduces the
    UNROUNDED accumulation-dtype R' exactly like the kernel's f32
    epilogue; outputs round back to the input dtype."""
    dt = X.dtype
    cdt = _cdt(dt)
    a = alpha[..., None].astype(cdt)
    Xn = X.astype(cdt) + a * P.astype(cdt)
    Rn = R.astype(cdt) - a * AP.astype(cdt)
    rr = jnp.sum(Rn * Rn, axis=1)
    return Xn.astype(dt), Rn.astype(dt), rr


# ---------------------------------------------------------------------------
# cycle fusion slab forms (the custom_vmap fallbacks of the fused
# grid-transfer / coarse-tail kernels in ops/smooth.py — and the f64
# reference the kernel parity tests compare against)
# ---------------------------------------------------------------------------


def restrict_multi(R: jax.Array, xfer) -> jax.Array:
    """BC = R-restriction of the residual slab (B, n) via the
    child-index slab (m gathers, no scatter): the aggregation
    segment-sum, or — when the slab carries weights (general CSR
    interpolation, classical levels) — the weighted row-segment sum
    bc[c] = sum_j cwt[j][c] * r[ctab[j][c]]."""
    ctab = xfer.ctab.reshape(xfer.m, -1)
    valid = ctab >= 0
    idx = jnp.where(valid, ctab, 0)
    g = R[:, idx]                                   # (B, m, ncr*128)
    if xfer.cwt is not None:
        g = g * xfer.cwt.reshape(xfer.m, -1)[None]
    bc = jnp.where(valid[None], g, 0.0).sum(axis=1)
    return bc[:, : xfer.nc]


def _agg_content(A: CsrMatrix, xfer) -> jax.Array:
    """Aggregate id per fine row (n,) — the content slice of the
    quota-padded atab slab."""
    from .pallas_spmv import LANES, transfer_quota_rows
    aqf = transfer_quota_rows(A.dia_offsets, A.num_rows)[0]
    return xfer.atab.reshape(-1)[aqf * LANES: aqf * LANES + A.num_rows]


def prolong_corr_multi(A: CsrMatrix, X: jax.Array, XC: jax.Array,
                       xfer) -> jax.Array:
    """X + P XC for (B, n) X and (B, nc) XC: gather by aggregate id
    (piecewise-constant aggregation P), or the weighted row-segment
    gather X += sum_j pwt[j] * XC[ptab[j]] (general CSR P)."""
    if xfer.ptab is None:
        return X + XC[:, _agg_content(A, xfer)]
    from .pallas_spmv import LANES, transfer_quota_rows
    aqf = transfer_quota_rows(A.dia_offsets, A.num_rows)[0]
    n = A.num_rows
    lo, hi = aqf * LANES, aqf * LANES + n
    pt = xfer.ptab.reshape(xfer.mp, -1)[:, lo:hi]   # (mp, n)
    pw = xfer.pwt.reshape(xfer.mp, -1)[:, lo:hi]
    valid = pt >= 0
    g = XC[:, jnp.where(valid, pt, 0)]              # (B, mp, n)
    corr = (jnp.where(valid, pw, 0.0)[None] * g).sum(axis=1)
    return X + corr


def smooth_restrict_dia_multi(A: CsrMatrix, B: jax.Array, X: jax.Array,
                              taus, dinv, xfer):
    """Multi-RHS form of the fused presmooth + restriction epilogue:
    (X', BC) with BC = R (B - A X'). bf16 inputs run the whole chain
    at f32 (the kernel's restriction partial sums are f32 too) and
    round the outputs back."""
    dt = X.dtype
    cdt = _cdt(dt)
    X, R = smooth_dia_multi(A, B.astype(cdt), X.astype(cdt), taus,
                            dinv, True)
    return X.astype(dt), restrict_multi(R, xfer).astype(dt)


def corr_smooth_dia_multi(A: CsrMatrix, B: jax.Array, X: jax.Array,
                          XC: jax.Array, taus, dinv, xfer):
    """Multi-RHS form of the fused prolongation prologue + postsmooth:
    X' = smooth(B, X + P XC). bf16 inputs accumulate the correction
    gather in f32 and round back (kernel-mirroring)."""
    dt = X.dtype
    cdt = _cdt(dt)
    X = prolong_corr_multi(A, X.astype(cdt), XC.astype(cdt), xfer)
    return smooth_dia_multi(A, B.astype(cdt), X, taus,
                            dinv, False).astype(dt)


def rap_values_multi(sarrs, AF: jax.Array, r_vals, p_vals, nT: int,
                     nU: int, has1: bool, has_r: bool,
                     r_batched: bool = False, p_batched: bool = False):
    """Multi-coefficient form of the plan-split RAP value phase
    (ops/spgemm.py RapPlan / ops/pallas_spgemm.py kernel): the batch
    axis rides the candidate gathers and sorted segment-sums with the
    plan's index slabs shared across systems. This is both the
    `custom_vmap` route of the fused value kernel (a vmapped
    coefficient stream over one pattern never re-streams the index
    slabs per system) and the f64 parity reference the kernel tests
    compare against — like `affine_window_sweeps` for the smoother
    suite. Zero sort/argsort/unique primitives by construction."""
    if has1:
        PV = p_vals[:, sarrs["sp"]] if p_batched else \
            p_vals[sarrs["sp"]][None]
        cand1 = AF[:, sarrs["sa"]] * PV
        base = jax.ops.segment_sum(
            cand1.T, sarrs["seg1"], num_segments=nT,
            indices_are_sorted=True).T
    else:
        base = AF
    cand2 = base[:, sarrs["st"]]
    if has_r:
        RV = r_vals[:, sarrs["sr"]] if r_batched else \
            r_vals[sarrs["sr"]][None]
        cand2 = RV * cand2
    return jax.ops.segment_sum(cand2.T, sarrs["seg2"],
                               num_segments=nU,
                               indices_are_sorted=True).T


def tail_cycle_multi(arrs, B: jax.Array, X: jax.Array, spec):
    """Multi-RHS form of the VMEM-resident coarse-tail sub-cycle: the
    SAME _tail_compute the Pallas kernel body runs, vmapped over the
    batch with the matrix slabs shared — XLA streams each level's
    values once per slab pass."""
    from .pallas_spmv import LANES, _tail_compute

    l0 = spec.levels[0]

    def single(b, x):
        b2 = jnp.zeros((l0.qc * LANES,), b.dtype)
        b2 = jax.lax.dynamic_update_slice(b2, b, (0,))
        x2 = jnp.zeros((l0.qc * LANES,), x.dtype)
        x2 = jax.lax.dynamic_update_slice(x2, x, (0,))
        out = _tail_compute(arrs, b2.reshape(l0.qc, LANES),
                            x2.reshape(l0.qc, LANES), spec)
        # _tail_compute returns the f32+ accumulation dtype; round
        # back so the vmapped cycle's state dtype is stable
        return out.reshape(-1)[: l0.n].astype(b.dtype)

    return jax.vmap(single)(B, X)
