"""Fused Galerkin RAP value kernel (the plan split's TPU numeric phase).

The structure phase (ops/spgemm.py `RapPlan`) fixes, once per sparsity
pattern, the (A·P) expansion gather indices, the lexsorted coalesce
order and the output CSR pattern. This module turns the VALUE phase —
today a chain of XLA gather/segment dispatches — into ONE pallas_call:

    cand1[e] = a[sa[e]] * p[sp[e]]            # segment-gather-multiply
    t[k]     = sum_{j<len1[k]} cand1[start1[k]+j]   # sorted-segment sum
    cand2[f] = r[sr[f]] * t[st[f]]
    out[u]   = sum_{j<len2[f]} cand2[start2[u]+j]

All indices are precomputed and window-rebased at plan time (host
numpy), so the kernel is pure VMEM-resident gathers over static index
slabs — no data-dependent addressing, no sort, no scatter. Because the
candidates are stored in lexsorted output order, the contributors of
any contiguous output range are a contiguous candidate range, and the
candidate sources of a contiguous row range are contiguous windows of
the operand value vectors: a chunk of output entries needs only
contiguous slices of a/p/r — the chained-block fallback splits the
output into such chunks when one VMEM-resident call does not fit
(mirroring ops/smooth.py's chained fused sub-calls). A plan that still
does not fit (or exceeds the contributor caps) declines, and the
caller runs the XLA slab program instead — never a wrong answer.

The call is `custom_vmap`-wrapped like `dia_smooth`: vector-only
batches (a batched coefficient stream over one pattern) route to the
multi-RHS slab form in ops/batched.py (`rap_values_multi`), which is
also the f64 parity reference of the kernel tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_spmv as _ps

LANES = _ps.LANES
_RAP_VMEM_BUDGET = 11 * 1024 * 1024
RAP_MAX_CONTRIB = 64        # largest per-entry contributor run the
# kernel's masked j-loop unrolls; longer segments decline to the slab
# route (segment_sum handles any length)
RAP_MAX_CHUNKS = 32         # longest chained-call fallback
_RAP_MIN_CHUNK = 512        # smallest output chunk before declining


def _rows(n: int) -> int:
    """Padded 128-lane row count (f32 tile: multiples of 8 rows)."""
    r = max(1, -(-max(int(n), 1) // LANES))
    return -(-r // 8) * 8


def _pad2(a: np.ndarray, rows: int) -> jnp.ndarray:
    out = np.zeros((rows * LANES,), a.dtype)
    out[: a.shape[0]] = a
    return jnp.asarray(out.reshape(rows, LANES))


class _ChunkSpec:
    """Static window geometry of one chained kernel call."""

    __slots__ = ("a_lo", "a_n", "p_lo", "p_n", "r_lo", "r_n", "m1",
                 "m2", "r_c1", "r_t", "r_c2", "r_u", "n_u", "has1",
                 "has_r")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])

    def key(self):
        return tuple(getattr(self, k) for k in self.__slots__)


def _measure_chunk(plan, lo: int, hi: int):
    """(spec, operand arrays, bytes) for output entries [lo, hi)."""
    starts2 = plan.starts2
    e2lo, e2hi = int(starts2[lo]), int(starts2[hi])
    st = plan.st[e2lo:e2hi].astype(np.int64)
    len2 = (starts2[lo + 1: hi + 1] - starts2[lo: hi]).astype(np.int64)
    m2 = int(len2.max()) if len2.size else 1
    has_r = plan.sr is not None
    has1 = plan.stage1 is not None
    arrs = {}
    if has_r:
        sr = plan.sr[e2lo:e2hi].astype(np.int64)
        r_lo, r_hi = int(sr.min()), int(sr.max()) + 1
        arrs["sr"] = sr - r_lo
    else:
        r_lo, r_hi = 0, 0
    if has1:
        s1 = plan.stage1
        tlo, thi = int(st.min()), int(st.max()) + 1
        e1lo, e1hi = int(s1["starts1"][tlo]), int(s1["starts1"][thi])
        sa = s1["sa"][e1lo:e1hi].astype(np.int64)
        sp = s1["sp"][e1lo:e1hi].astype(np.int64)
        a_lo, a_hi = int(sa.min()), int(sa.max()) + 1
        p_lo, p_hi = int(sp.min()), int(sp.max()) + 1
        len1 = (s1["starts1"][tlo + 1: thi + 1]
                - s1["starts1"][tlo: thi]).astype(np.int64)
        m1 = int(len1.max()) if len1.size else 1
        arrs["sa"] = sa - a_lo
        arrs["sp"] = sp - p_lo
        arrs["s1"] = (s1["starts1"][tlo:thi] - e1lo).astype(np.int64)
        arrs["l1"] = len1
        arrs["st"] = st - tlo
        n_t = thi - tlo
        n_c1 = e1hi - e1lo
    else:
        # relabel form: st indexes the (folded) A value vector directly
        a_lo, a_hi = int(st.min()), int(st.max()) + 1
        p_lo, p_hi = 0, 0
        arrs["st"] = st - a_lo
        m1, n_t, n_c1 = 1, 0, 0
    arrs["s2"] = (starts2[lo:hi] - e2lo).astype(np.int64)
    arrs["l2"] = len2
    n_c2 = e2hi - e2lo
    spec = _ChunkSpec(
        a_lo=a_lo, a_n=a_hi - a_lo, p_lo=p_lo, p_n=p_hi - p_lo,
        r_lo=r_lo, r_n=r_hi - r_lo, m1=m1, m2=m2,
        r_c1=_rows(n_c1) if has1 else 0, r_t=_rows(n_t) if has1 else 0,
        r_c2=_rows(n_c2), r_u=_rows(hi - lo), n_u=hi - lo,
        has1=has1, has_r=has_r)
    # VMEM estimate: f32 value windows + int32 index slabs + the
    # kernel's flat intermediates (cand1/t/cand2/out), x2 headroom for
    # the take temporaries the compiler materializes
    words = (_rows(spec.a_n) + _rows(spec.p_n) + _rows(spec.r_n)
             + 2 * spec.r_c1 + 2 * spec.r_t + 2 * spec.r_c2
             + 2 * spec.r_u) * LANES
    words += (spec.r_c1 + spec.r_t + spec.r_c2 + spec.r_u) * LANES
    return spec, arrs, 2 * 4 * words


def _plan_chunks(plan, lo: int, hi: int, depth: int = 0):
    spec, arrs, nbytes = _measure_chunk(plan, lo, hi)
    if spec.m1 > RAP_MAX_CONTRIB or spec.m2 > RAP_MAX_CONTRIB:
        return None
    if nbytes <= _RAP_VMEM_BUDGET:
        return [(spec, arrs)]
    if hi - lo <= _RAP_MIN_CHUNK or depth > 12:
        return None
    mid = (lo + hi) // 2
    left = _plan_chunks(plan, lo, mid, depth + 1)
    if left is None:
        return None
    right = _plan_chunks(plan, mid, hi, depth + 1)
    if right is None:
        return None
    out = left + right
    return out if len(out) <= RAP_MAX_CHUNKS else None


def build_rap_kernel(plan):
    """Kernel route of a RapPlan: (static spec tuple, per-chunk device
    operand dicts) or None (decline -> slab route). Memoized on the
    plan (`plan._kernel`); the index windows upload once per plan."""
    if plan._kernel is not None:
        return plan._kernel or None
    # cheap upfront bound BEFORE any slicing: _measure_chunk copies
    # window-rebased int64 twins of the index slabs, so a GB-scale
    # plan that could only ever decline (its total operand footprint
    # exceeds every chunk's budget times the chunk cap) must not pay
    # O(plan_bytes x bisection_depth) transient allocations first
    e1 = 0 if plan.stage1 is None else plan.stage1["sa"].shape[0]
    n_t = 0 if plan.stage1 is None else plan.stage1["nT"]
    est = 2 * 4 * (3 * e1 + 2 * n_t + 3 * plan.st.shape[0]
                   + 2 * plan.nU)
    if est > _RAP_VMEM_BUDGET * RAP_MAX_CHUNKS:
        plan._kernel = False
        return None
    chunks = None
    if plan.nU > 0:
        chunks = _plan_chunks(plan, 0, plan.nU)
    if not chunks:
        plan._kernel = False
        return None
    specs = []
    arrs = []
    for spec, a in chunks:
        specs.append(spec.key())
        up = {}
        for k, v in a.items():
            rows = {"sa": spec.r_c1, "sp": spec.r_c1,
                    "s1": spec.r_t, "l1": spec.r_t,
                    "st": spec.r_c2, "sr": spec.r_c2,
                    "s2": spec.r_u, "l2": spec.r_u}[k]
            up[k] = _pad2(v.astype(np.int32), rows)
        arrs.append(up)
    plan._kernel = (tuple(specs), tuple(arrs))
    return plan._kernel


def rap_kernel_ready(plan, dtype) -> bool:
    """Trace-time gate for the fused value-kernel route."""
    if jax.default_backend() != "tpu" and not _ps._FORCE_INTERPRET:
        return False
    if jnp.dtype(dtype) != jnp.float32:
        return False
    return build_rap_kernel(plan) is not None


def _rap_kernel(spec_key):
    """Kernel body factory for one chunk's static geometry."""
    spec = _ChunkSpec(**dict(zip(_ChunkSpec.__slots__, spec_key)))

    def kernel(*refs):
        it = iter(refs)
        a_ref = next(it)
        p_ref = next(it) if spec.has1 else None
        r_ref = next(it) if spec.has_r else None
        if spec.has1:
            sa_ref, sp_ref, s1_ref, l1_ref = (next(it), next(it),
                                              next(it), next(it))
        st_ref = next(it)
        sr_ref = next(it) if spec.has_r else None
        s2_ref, l2_ref = next(it), next(it)
        out_ref = next(it)

        aw = a_ref[...].reshape(-1)
        if spec.has1:
            pw = p_ref[...].reshape(-1)
            cand1 = jnp.take(aw, sa_ref[...].reshape(-1)) \
                * jnp.take(pw, sp_ref[...].reshape(-1))
            s1 = s1_ref[...].reshape(-1)
            l1 = l1_ref[...].reshape(-1)
            base = jnp.zeros((spec.r_t * LANES,), jnp.float32)
            for j in range(spec.m1):
                base = base + jnp.where(
                    j < l1, jnp.take(cand1, s1 + j), 0.0)
        else:
            base = aw
        cand2 = jnp.take(base, st_ref[...].reshape(-1))
        if spec.has_r:
            rw = r_ref[...].reshape(-1)
            cand2 = cand2 * jnp.take(rw, sr_ref[...].reshape(-1))
        s2 = s2_ref[...].reshape(-1)
        l2 = l2_ref[...].reshape(-1)
        out = jnp.zeros((spec.r_u * LANES,), jnp.float32)
        for j in range(spec.m2):
            out = out + jnp.where(j < l2, jnp.take(cand2, s2 + j), 0.0)
        out_ref[...] = out.reshape(spec.r_u, LANES)

    return kernel


def _value_window(vec, lo: int, n: int):
    """Zero-padded (rows, 128) window of a flat value vector (static
    slice bounds — plan-time constants)."""
    rows = _rows(n)
    w = jax.lax.slice_in_dim(vec, lo, lo + n, 1, 0)
    buf = jnp.zeros((rows * LANES,), vec.dtype)
    buf = jax.lax.dynamic_update_slice(buf, w, (0,))
    return buf.reshape(rows, LANES)


@functools.partial(jax.jit, static_argnames=("specs", "interpret"))
def _rap_kernel_program(specs, arrs, af, r_vals, p_vals,
                        interpret=False):
    """The whole planned value phase: one pallas_call per chunk (ONE
    for every plan that fits the budget), chained over static output
    ranges. Outer prims are only the window slices/pads and the final
    concat — zero sort/gather/segment-sum outside the kernel."""
    pieces = []
    for key, a in zip(specs, arrs):
        spec = _ChunkSpec(**dict(zip(_ChunkSpec.__slots__, key)))
        operands = [_value_window(af, spec.a_lo, spec.a_n)]
        if spec.has1:
            operands.append(_value_window(p_vals, spec.p_lo, spec.p_n))
        if spec.has_r:
            operands.append(_value_window(r_vals, spec.r_lo, spec.r_n))
        if spec.has1:
            operands += [a["sa"], a["sp"], a["s1"], a["l1"]]
        operands.append(a["st"])
        if spec.has_r:
            operands.append(a["sr"])
        operands += [a["s2"], a["l2"]]
        out = pl.pallas_call(
            _rap_kernel(key),
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)
                      for _ in operands],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((spec.r_u, LANES),
                                           jnp.float32),
            cost_estimate=pl.CostEstimate(
                flops=2 * (spec.r_c1 + spec.r_c2) * LANES,
                bytes_accessed=4 * (spec.a_n + spec.p_n + spec.r_n
                                    + (2 * spec.r_c1 + 2 * spec.r_t
                                       + 2 * spec.r_c2 + 2 * spec.r_u)
                                    * LANES),
                transcendentals=0),
            interpret=interpret,
        )(*operands)
        pieces.append(out.reshape(-1)[: spec.n_u])
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


@functools.lru_cache(maxsize=None)
def _rap_call_fn(specs, has1: bool, has_r: bool, nT: int, nU: int,
                 interpret: bool):
    """custom_vmap-wrapped kernel call: vector-only batches (a batched
    coefficient stream over one pattern) take the multi-RHS slab form
    in ops/batched.py; batched plan operands fall back to vmapped slab
    singles."""
    tu = jax.tree_util

    @jax.custom_batching.custom_vmap
    def call(karrs, sarrs, af, r_vals, p_vals):
        return _rap_kernel_program(specs, karrs, af, r_vals, p_vals,
                                   interpret=interpret)

    @call.def_vmap
    def _rule(axis_size, in_batched, karrs, sarrs, af, r_vals, p_vals):
        from .batched import rap_values_multi
        plan_b = any(tu.tree_leaves(in_batched[0])) \
            or any(tu.tree_leaves(in_batched[1]))
        if not plan_b:
            AF = af if in_batched[2] else jnp.broadcast_to(
                af, (axis_size,) + af.shape)
            r_b = bool(r_vals is not None
                       and any(tu.tree_leaves(in_batched[3])))
            p_b = bool(p_vals is not None
                       and any(tu.tree_leaves(in_batched[4])))
            y = rap_values_multi(sarrs, AF, r_vals, p_vals, nT, nU,
                                 has1, has_r, r_batched=r_b,
                                 p_batched=p_b)
            return y, True
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        y = jax.vmap(lambda k_, s_, a_, r_, p_: call(k_, s_, a_, r_,
                                                     p_),
                     in_axes=axes, axis_size=axis_size)(
            karrs, sarrs, af, r_vals, p_vals)
        return y, True

    return call


def rap_value_call(plan, af, r_vals, p_vals):
    """Planned value phase through the fused kernel route. Caller must
    have checked `rap_kernel_ready`."""
    specs, karrs = plan._kernel
    sarrs = plan.dev()
    s1 = plan.stage1
    return _rap_call_fn(
        specs, s1 is not None, plan.sr is not None,
        0 if s1 is None else s1["nT"], plan.nU,
        _ps._FORCE_INTERPRET)(
        karrs, sarrs, af,
        None if r_vals is None else jnp.asarray(r_vals),
        None if p_vals is None else jnp.asarray(p_vals))
