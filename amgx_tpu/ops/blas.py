"""BLAS-1 vector ops and norms.

Analog of src/blas.cu + src/norm.cu (include/blas.h:17-85). On TPU these
are trivially fused by XLA, so they are plain jnp expressions; the value
of this module is the distributed contract: every reduction takes an
optional `axis_name` and finishes with a `psum`/`pmax` so the same code
runs inside shard_map over a device mesh (the reference finishes its
device reductions with MPI allreduce, src/distributed/).

Block norms: for block matrices the reference computes one norm per block
component unless `use_scalar_norm` (src/core.cu:520-524); `norm` mirrors
that via the `block_size` / `use_scalar_norm` arguments.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def axpy(x, y, a):
    return a * x + y


def axpby(x, y, a, b):
    return a * x + b * y


def axpbypcz(x, y, z, a, b, c):
    return a * x + b * y + c * z


def scal(x, a):
    return a * x


def fill(x, value):
    return jnp.full_like(x, value)


def _axis(axis_name):
    if axis_name is not None:
        return axis_name
    from ..distributed import comms
    return comms.active_axis()


def _psum(v, axis_name):
    axis_name = _axis(axis_name)
    return jax.lax.psum(v, axis_name) if axis_name else v


def _pmax(v, axis_name):
    axis_name = _axis(axis_name)
    return jax.lax.pmax(v, axis_name) if axis_name else v


def dot(x, y, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    """<x, y> (conjugating x for complex); distributed-safe via psum over
    owned entries only."""
    if num_owned is not None:
        x, y = x[:num_owned], y[:num_owned]
    return _psum(jnp.vdot(x, y), axis_name)


def mdot(V, w, axis_name: Optional[str] = None,
         num_owned: Optional[int] = None):
    """Row-wise dots <V[j], w> as ONE (m, n) @ (n,) matvec (the
    MXU-friendly shape for Gram-Schmidt panels); distributed-safe via
    psum like `dot`."""
    if num_owned is not None:
        V, w = V[:, :num_owned], w[:num_owned]
    return _psum(V @ w, axis_name)


def nrm1(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return _psum(jnp.sum(jnp.abs(x)), axis_name)


def nrm2(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return jnp.sqrt(_psum(jnp.sum(jnp.abs(x) ** 2), axis_name))


def nrmmax(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return _pmax(jnp.max(jnp.abs(x)), axis_name)


_NORMS = {"L1": nrm1, "L2": nrm2, "LMAX": nrmmax}


def norm(x, norm_type: str = "L2", block_size: int = 1,
         use_scalar_norm: bool = True, axis_name: Optional[str] = None,
         num_owned: Optional[int] = None):
    """Norm of a (flat) vector. With block_size>1 and use_scalar_norm=False
    returns a (block_size,) per-component norm vector."""
    fn = _NORMS[norm_type.upper()]
    if block_size <= 1 or use_scalar_norm:
        return fn(x, axis_name, num_owned)
    xb = x.reshape(-1, block_size)
    if num_owned is not None:
        xb = xb[:num_owned]
    if norm_type.upper() == "L1":
        return _psum(jnp.sum(jnp.abs(xb), axis=0), axis_name)
    if norm_type.upper() == "L2":
        return jnp.sqrt(_psum(jnp.sum(jnp.abs(xb) ** 2, axis=0), axis_name))
    return _pmax(jnp.max(jnp.abs(xb), axis=0), axis_name)


def get_norm(norm_type: str):
    return _NORMS[norm_type.upper()]
