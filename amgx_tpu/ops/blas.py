"""BLAS-1 vector ops and norms.

Analog of src/blas.cu + src/norm.cu (include/blas.h:17-85). On TPU these
are trivially fused by XLA, so they are plain jnp expressions; the value
of this module is the distributed contract: every reduction takes an
optional `axis_name` and finishes with a `psum`/`pmax` so the same code
runs inside shard_map over a device mesh (the reference finishes its
device reductions with MPI allreduce, src/distributed/).

Block norms: for block matrices the reference computes one norm per block
component unless `use_scalar_norm` (src/core.cu:520-524); `norm` mirrors
that via the `block_size` / `use_scalar_norm` arguments.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def axpy(x, y, a):
    return a * x + y


def axpby(x, y, a, b):
    return a * x + b * y


def axpbypcz(x, y, z, a, b, c):
    return a * x + b * y + c * z


def scal(x, a):
    return a * x


def fill(x, value):
    return jnp.full_like(x, value)


def _axis(axis_name):
    if axis_name is not None:
        return axis_name
    from ..distributed import comms
    return comms.active_axis()


def _psum(v, axis_name):
    axis_name = _axis(axis_name)
    return jax.lax.psum(v, axis_name) if axis_name else v


def _pmax(v, axis_name):
    axis_name = _axis(axis_name)
    return jax.lax.pmax(v, axis_name) if axis_name else v


def dot(x, y, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    """<x, y> (conjugating x for complex); distributed-safe via psum over
    owned entries only."""
    if num_owned is not None:
        x, y = x[:num_owned], y[:num_owned]
    return _psum(jnp.vdot(x, y), axis_name)


def mdot(V, w, axis_name: Optional[str] = None,
         num_owned: Optional[int] = None):
    """Row-wise dots <V[j], w> as ONE (m, n) @ (n,) matvec (the
    MXU-friendly shape for Gram-Schmidt panels); distributed-safe via
    psum like `dot`."""
    if num_owned is not None:
        V, w = V[:, :num_owned], w[:num_owned]
    return _psum(V @ w, axis_name)


def nrm1(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return _psum(jnp.sum(jnp.abs(x)), axis_name)


def nrm2(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return jnp.sqrt(_psum(jnp.sum(jnp.abs(x) ** 2), axis_name))


def nrmmax(x, axis_name: Optional[str] = None, num_owned: Optional[int] = None):
    if num_owned is not None:
        x = x[:num_owned]
    return _pmax(jnp.max(jnp.abs(x)), axis_name)


_NORMS = {"L1": nrm1, "L2": nrm2, "LMAX": nrmmax}


def norm(x, norm_type: str = "L2", block_size: int = 1,
         use_scalar_norm: bool = True, axis_name: Optional[str] = None,
         num_owned: Optional[int] = None):
    """Norm of a (flat) vector. With block_size>1 and use_scalar_norm=False
    returns a (block_size,) per-component norm vector."""
    fn = _NORMS[norm_type.upper()]
    if block_size <= 1 or use_scalar_norm:
        return fn(x, axis_name, num_owned)
    xb = x.reshape(-1, block_size)
    if num_owned is not None:
        xb = xb[:num_owned]
    if norm_type.upper() == "L1":
        return _psum(jnp.sum(jnp.abs(xb), axis=0), axis_name)
    if norm_type.upper() == "L2":
        return jnp.sqrt(_psum(jnp.sum(jnp.abs(xb) ** 2, axis=0), axis_name))
    return _pmax(jnp.max(jnp.abs(xb), axis=0), axis_name)


def get_norm(norm_type: str):
    return _NORMS[norm_type.upper()]


# ---------------------------------------------------------------------------
# Krylov shell fusion: the single-pass CG update and the packed scalar
# collective
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cg_update_fn():
    """custom_vmap-wrapped single-pass CG update kernel: every vmap
    batch (there is no matrix operand) takes the multi-RHS slab form
    in ops/batched.py, so solve_many's update stays one slab pass."""

    @jax.custom_batching.custom_vmap
    def call(x, p, r, ap, alpha):
        from . import pallas_spmv as _ps
        return _ps._cg_update_call(x, p, r, ap, alpha,
                                   interpret=_ps._FORCE_INTERPRET)

    @call.def_vmap
    def _rule(axis_size, in_batched, x, p, r, ap, alpha):
        from .batched import cg_update_multi

        def bc(v, b):
            return v if b else jnp.broadcast_to(
                v, (axis_size,) + jnp.shape(v))

        return (cg_update_multi(
            bc(x, in_batched[0]), bc(p, in_batched[1]),
            bc(r, in_batched[2]), bc(ap, in_batched[3]),
            bc(alpha, in_batched[4])), (True, True, True))

    return call


def cg_update(x, p, r, ap, alpha):
    """Single-pass CG state update: (x + alpha p, r - alpha Ap, r'.r')
    — the Pallas kernel streams the four vectors once and emits the
    residual dot as a free epilogue (the monitor's norm pass); the
    plain XLA compose (identical unfused expressions) covers f64 / CPU.
    The rr scalar is LOCAL — distributed callers psum it (packed)."""
    from . import pallas_spmv as _ps
    from ..telemetry import metrics as _tm
    if _ps.cg_update_supported(x.dtype):
        _tm.inc("krylov.fused_dispatch")
        return _cg_update_fn()(x, p, r, ap, alpha)
    _tm.inc("krylov.fused_declined")
    a = jnp.asarray(alpha).astype(x.dtype)
    xn = x + a * p
    rn = r - a * ap
    # f32+ accumulation like the kernel's epilogue (rr keeps ONE dtype
    # across the kernel/fallback routes, so loop state stays stable)
    rc = rn.astype(jnp.promote_types(x.dtype, jnp.float32))
    return xn, rn, jnp.vdot(rc, rc)


def psum_bundle(scalars, axis_name: Optional[str] = None):
    """Sum a tuple of LOCAL scalars across the mesh with ONE packed
    collective (stack + psum — the per-iteration collective count
    stays independent of how many dots the iteration needs); the
    identity when no mesh axis is active. Returns the tuple back."""
    axis_name = _axis(axis_name)
    if not axis_name:
        return tuple(scalars)
    packed = jax.lax.psum(jnp.stack([jnp.asarray(s) for s in scalars]),
                          axis_name)
    return tuple(packed[i] for i in range(len(scalars)))
