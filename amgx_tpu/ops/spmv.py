"""Sparse matrix-vector product.

TPU-native analog of the reference SpMV stack (src/multiply.cu:74-121,
block dispatch :50, cuSPARSE wrappers src/amgx_cusparse.cu). Two execution
shapes, both fully jittable with static shapes:

- CSR + segmented-sum: gather x at col_indices, multiply, segment-sum by
  precomputed per-nnz row ids (`indices_are_sorted=True` — CSR order).
- padded ELL: dense (n, k) gather + row reduction. For stencil-like
  matrices (bounded row length) this is the fast path on TPU: it is pure
  dense vector-unit work with no scatter.

The choice is made at Matrix.init() time; `spmv` dispatches on which
auxiliaries are present. Block (bxb) matrices contract each block with an
einsum so XLA can batch them onto the MXU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..matrix import CsrMatrix
from ..resilience import faultinject as _fault


def _ensure_init(A: CsrMatrix, x: jax.Array) -> CsrMatrix:
    if not A.initialized:
        raise ValueError(
            "spmv requires an initialized matrix (call A.init() at setup "
            "time; inside jit, pass the initialized matrix in)")
    expect = A.num_cols * A.block_dimy
    if x.shape != (expect,):
        raise ValueError(
            f"spmv: x has shape {x.shape}, expected ({expect},) for a "
            f"{A.num_rows}x{A.num_cols} matrix with block_dimy="
            f"{A.block_dimy} (JAX would silently clamp the gather)")
    return A


def spmv_csr_segsum(A: CsrMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x via gather + segmented sum over CSR order."""
    n = A.num_rows
    if A.is_block:
        bx, by = A.block_dimx, A.block_dimy
        xb = x.reshape(-1, by)
        prod = jnp.einsum("nxy,ny->nx", A.values, xb[A.col_indices])
        y = jax.ops.segment_sum(prod, A.row_ids, num_segments=n,
                                indices_are_sorted=True)
        if A.has_external_diag:
            y = y + jnp.einsum("nxy,ny->nx", A.diag, xb[:n])
        return y.reshape(-1)
    prod = A.values * x[A.col_indices]
    y = jax.ops.segment_sum(prod, A.row_ids, num_segments=n,
                            indices_are_sorted=True)
    if A.has_external_diag:
        y = y + A.diag * x[:n]
    return y


def spmv_ell(A: CsrMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x via the padded-ELL layout (dense gather + reduce)."""
    n = A.num_rows
    if A.is_block:
        by = A.block_dimy
        xb = x.reshape(-1, by)
        y = jnp.einsum("nkxy,nky->nx", A.ell_vals, xb[A.ell_cols])
        if A.has_external_diag:
            y = y + jnp.einsum("nxy,ny->nx", A.diag, xb[:n])
        return y.reshape(-1)
    y = (A.ell_vals * x[A.ell_cols]).sum(axis=1)
    if A.has_external_diag:
        y = y + A.diag * x[:n]
    return y


def _spmv_dia_xla(A: CsrMatrix, x: jax.Array) -> jax.Array:
    """XLA form of the DIA SpMV (f64/CPU/batched fallback) — the
    single-vector view of the multi-RHS slab form, so the DIA
    padding/shift arithmetic lives in exactly one place."""
    from .batched import spmv_dia_multi
    return spmv_dia_multi(A, x[None])[0]


@jax.custom_batching.custom_vmap
def _spmv_dia_pallas(A: CsrMatrix, x: jax.Array) -> jax.Array:
    from .pallas_spmv import dia_spmv
    return dia_spmv(A, x)


@_spmv_dia_pallas.def_vmap
def _spmv_dia_pallas_vmap(axis_size, in_batched, A, x):
    """pallas_call has no batching rule for ANY-space operands; batched
    SpMV (AffinityStrength, eigen block solvers, the batch/ subsystem's
    vmapped solves) takes the XLA form. When only the vector is batched
    (multi-RHS against one matrix — the batch subsystem's shared-pattern
    shape) the dedicated multi-RHS slab form avoids restreaming the
    diagonal values per system."""
    A_b, x_b = in_batched
    if x_b and not any(jax.tree_util.tree_leaves(A_b)):
        from .batched import spmv_dia_multi
        return spmv_dia_multi(A, x), True
    in_axes = (jax.tree_util.tree_map(lambda b: 0 if b else None, A_b),
               0 if x_b else None)
    y = jax.vmap(_spmv_dia_xla, in_axes=in_axes,
                 axis_size=axis_size)(A, x)
    return y, True


@jax.custom_batching.custom_vmap
def _spmv_swell_pallas(A: CsrMatrix, x: jax.Array) -> jax.Array:
    from .pallas_swell import swell_spmv
    return swell_spmv(A, x)


@_spmv_swell_pallas.def_vmap
def _spmv_swell_pallas_vmap(axis_size, in_batched, A, x):
    from .pallas_swell import swell_spmv_xla
    A_b, x_b = in_batched
    in_axes = (jax.tree_util.tree_map(lambda b: 0 if b else None, A_b),
               0 if x_b else None)
    y = jax.vmap(swell_spmv_xla, in_axes=in_axes, axis_size=axis_size)(A, x)
    return y, True


def spmv_swell(A: CsrMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x in the windowed-ELL (SWELL) layout: the Pallas
    lane-gather kernel on TPU/f32 (ops/pallas_swell.py — the unstructured
    analog of the DIA fast path), the XLA gather form elsewhere."""
    from .pallas_swell import swell_spmv_supported, swell_spmv_xla
    if swell_spmv_supported(A, x.dtype):
        y = _spmv_swell_pallas(A, x)
    else:
        y = swell_spmv_xla(A, x)
    if A.has_external_diag:
        y = y + A.diag * x[: A.num_rows]
    return y


def spmv_dia(A: CsrMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x in DIA (diagonal) storage: for each stored diagonal with
    offset d, y += vals_d * shift(x, d). Pure dense vector multiply-adds
    with static slices — the TPU roofline layout for stencil matrices
    (no gather; ~2 HBM streams per diagonal). On TPU/f32 the fused
    Pallas kernel (ops/pallas_spmv.py) does the whole reduction in one
    HBM pass; the XLA form covers f64, CPU, and vmapped callers."""
    from .pallas_spmv import dia_spmv_supported
    if dia_spmv_supported(A, x.dtype):
        return _spmv_dia_pallas(A, x)
    return _spmv_dia_xla(A, x)


def spmv(A, x: jax.Array) -> jax.Array:
    """y = A @ x; dispatches on the layout chosen at init
    (multiply_block_size analog, src/multiply.cu:50). Non-CsrMatrix
    operands (distributed shard matrices, solve-operators) provide their
    own .spmv — the Operator abstraction of include/operators/operator.h.

    The resilience fault harness hooks the output here: a trace-time
    no-op unless an `spmv_nan` fault is armed AND a solve-loop
    iteration scope is active (resilience/faultinject.py)."""
    if not isinstance(A, CsrMatrix):
        return _fault.corrupt_spmv(A.spmv(x))
    _ensure_init(A, x)
    if A.dia_offsets is not None:
        return _fault.corrupt_spmv(spmv_dia(A, x))
    if A.swell_cols is not None:
        return _fault.corrupt_spmv(spmv_swell(A, x))
    if A.ell_cols is not None:
        return _fault.corrupt_spmv(spmv_ell(A, x))
    return _fault.corrupt_spmv(spmv_csr_segsum(A, x))


# ---------------------------------------------------------------------------
# Krylov shell fusion dispatch: SpMV with dot epilogue (+ optional
# direction-update prologue). The Pallas kernel runs under the same
# custom_vmap contract as the fused smoother suite: vector-only vmap
# batches (solve_many) take the multi-RHS slab forms in ops/batched.py,
# batched matrices take the vmapped XLA compose. The returned dot
# scalars are LOCAL sums — distributed callers psum them (packed,
# blas.psum_bundle).
# ---------------------------------------------------------------------------


def _spmv_pdot_xla(A, p, z, beta):
    """Unfused XLA compose of the prologue variant — exactly the
    pre-fusion expressions, so the f64 route of a `krylov_fusion=1`
    solver reproduces the unfused arithmetic identically."""
    p = (z + beta * p).astype(p.dtype)
    ap = spmv(A, p)
    return p, ap, jnp.vdot(p, ap)


def _spmv_ddot_xla(A, p, d, self_dot):
    ap = spmv(A, p)
    out = (ap, jnp.vdot(d, ap))
    if self_dot:
        out = out + (jnp.vdot(ap, ap),)
    return out


def _bcast(v, batched, axis_size):
    return v if batched else jnp.broadcast_to(
        v, (axis_size,) + jnp.shape(v))


@functools.lru_cache(maxsize=None)
def _spmv_pdot_fn():
    tu = jax.tree_util

    @jax.custom_batching.custom_vmap
    def call(A, p, z, beta):
        from .pallas_spmv import dia_spmv_dot
        return dia_spmv_dot(A, p, z=z, beta=beta)

    @call.def_vmap
    def _rule(axis_size, in_batched, A, p, z, beta):
        mat_b = any(tu.tree_leaves(in_batched[0]))
        if not mat_b:
            from .batched import spmv_dot_multi
            return (spmv_dot_multi(
                A, _bcast(p, in_batched[1], axis_size),
                _bcast(z, in_batched[2], axis_size),
                _bcast(beta, in_batched[3], axis_size)),
                (True, True, True))
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        y = jax.vmap(_spmv_pdot_xla, in_axes=axes,
                     axis_size=axis_size)(A, p, z, beta)
        return y, (True, True, True)

    return call


@functools.lru_cache(maxsize=None)
def _spmv_ddot_fn(self_dot: bool):
    tu = jax.tree_util
    ob = (True,) * (3 if self_dot else 2)

    @jax.custom_batching.custom_vmap
    def call(A, p, d):
        from .pallas_spmv import dia_spmv_dot
        return dia_spmv_dot(A, p, d=d, self_dot=self_dot)

    @call.def_vmap
    def _rule(axis_size, in_batched, A, p, d):
        mat_b = any(tu.tree_leaves(in_batched[0]))
        if not mat_b:
            from .batched import spmv_dot_multi
            return (spmv_dot_multi(
                A, _bcast(p, in_batched[1], axis_size),
                D=_bcast(d, in_batched[2], axis_size),
                self_dot=self_dot), ob)
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        fn = lambda A_, p_, d_: _spmv_ddot_xla(A_, p_, d_, self_dot)  # noqa: E731
        y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(A, p, d)
        return y, ob

    return call


def _shell_kernel_ok(A, dtype) -> bool:
    from .pallas_spmv import dia_spmv_dot_supported
    return (isinstance(A, CsrMatrix) and not A.is_block
            and getattr(A, "dia_vals", None) is not None
            and dia_spmv_dot_supported(A, dtype))


def spmv_pdot(A, p, z, beta):
    """Fused direction-update + SpMV + dot: p' = z + beta p,
    Ap' = A @ p', and the LOCAL p'.Ap' scalar — one HBM pass over p/z
    plus the values stream when the Pallas shell kernel applies, the
    exact unfused XLA compose otherwise (f64, CPU, non-DIA layouts,
    distributed operators)."""
    from ..telemetry import metrics as _tm
    if _shell_kernel_ok(A, p.dtype):
        _tm.inc("krylov.fused_dispatch")
        return _spmv_pdot_fn()(A, p, z, beta)
    _tm.inc("krylov.fused_declined")
    return _spmv_pdot_xla(A, p, z, beta)


def spmv_ddot(A, p, d, self_dot: bool = False):
    """Fused SpMV + dot against a streamed operand: Ap = A @ p with
    the LOCAL d.Ap scalar (and Ap.Ap when `self_dot` — BiCGStab's
    t.s / t.t pair) from the kernel epilogue; the exact unfused XLA
    compose otherwise."""
    from ..telemetry import metrics as _tm
    if _shell_kernel_ok(A, p.dtype):
        _tm.inc("krylov.fused_dispatch")
        return _spmv_ddot_fn(self_dot)(A, p, d)
    _tm.inc("krylov.fused_declined")
    return _spmv_ddot_xla(A, p, d, self_dot)


def multiply(A: CsrMatrix, x: jax.Array, view: str = "OWNED") -> jax.Array:
    """`multiply` entry point (src/multiply.cu:74). For local matrices the
    view argument is inert; the distributed overlap path lives in
    distributed/dist_spmv.py and is selected by the DistMatrix type."""
    return spmv(A, x)


def axmb(A: CsrMatrix, x: jax.Array, b: jax.Array) -> jax.Array:
    """r = A@x - b (reference blas axmb, include/blas.h)."""
    return spmv(A, x) - b


def residual(A: CsrMatrix, x: jax.Array, b: jax.Array) -> jax.Array:
    """r = b - A@x (the sign convention used by the solve loops)."""
    return b - spmv(A, x)
