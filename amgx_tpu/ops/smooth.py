"""Fused smoother+residual dispatch for the V-cycle hot path.

The multigrid solve phase spends its time in presmooth -> residual ->
restrict and prolongate -> postsmooth; on a memory-bound TPU each
smoother sweep and the residual is a separate HBM pass over A. This
module routes the damped-relaxation smoother family

    x_{s+1} = x_s + tau_s * dinv . (b - A x_s)        (dinv optional)

(BLOCK_JACOBI / JACOBI_L1: tau_s = relaxation_factor, dinv = D^{-1};
CHEBYSHEV_POLY: tau_s = the magic-damping taus, no dinv) through the
fused Pallas kernels:

- DIA: all sweeps AND the trailing residual in ONE pallas_call
  (ops/pallas_spmv.py temporal blocking) — A's diagonal slab streams
  from HBM once instead of sweeps+1 times. When the full fusion misses
  the VMEM/traffic budget (deep halos at very large grids), the
  dispatcher chains the largest supported fused sub-calls, each still
  one pass over A.
- SWELL: each sweep is one pallas_call with the Jacobi update in the
  kernel epilogue (ops/pallas_swell.py) — the lane-gather layout cannot
  temporally block (window reach is unbounded), but fusing the update
  removes the separate elementwise pass and its 4 HBM streams; the
  final residual stays a plain SpMV pass.

Every entry point returns None when no fused plan applies, and the
calling smoother falls back to its unfused compose — so `fused_smoother=0`
(or any unsupported layout/dtype/backend) reproduces the pre-fusion
computation exactly. All Pallas routes are wrapped in `custom_vmap`
like `spmv_dia`: under `jax.vmap` (the batched-solve subsystem) the
multi-RHS slab forms in ops/batched.py run instead, so `solve_many`
gets the same fused-epilogue semantics without a per-system values
stream.

The DIA kernel needs its values/dinv operands with front-halo padding
the tile-aligned dia_vals store does not carry; `solver_fused_slabs`
builds those quota-padded slabs ONCE per (re)setup and the smoother
carries them in its solve_data pytree (so a value-only resetup refreshes
them and no per-cycle re-layout of A ever happens).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import pallas_spmv as _ps


def fused_runtime_on() -> bool:
    """Would the fused Pallas kernels run on this rig (or under the
    interpreter-forcing test hook)?"""
    return jax.default_backend() == "tpu" or _ps._FORCE_INTERPRET


# ---------------------------------------------------------------------------
# setup-time payloads (carried in smoother solve_data)
# ---------------------------------------------------------------------------


def _slab_eligible(A) -> bool:
    return (getattr(A, "dia_vals", None) is not None
            and not A.is_block and not A.has_external_diag
            and A.num_rows == A.num_cols)


def build_fused_slabs(A, dinv=None, dtype=None):
    """Quota-padded DIA operand slabs {vals_q[, dinv_q]} for the fused
    smoother kernel (eager device ops; see smooth_quota_rows for the
    layout). `dtype` emits the slabs in the hierarchy's EFFECTIVE
    precision (precision.py policy — e.g. bf16 slabs at half the HBM
    bytes) instead of A's native dtype, so the solve-data cast later
    finds them already narrow and never materializes a second copy.
    Returns None when A has no eligible DIA layout."""
    if not _slab_eligible(A):
        return None
    qf, qc, qb = _ps.smooth_quota_rows(A.dia_offsets, A.num_rows)
    k, rows_pad, _ = A.dia_vals.shape
    src = A.dia_vals[:, :qc] if rows_pad >= qc else jnp.pad(
        A.dia_vals, ((0, 0), (0, qc - rows_pad), (0, 0)))
    if dtype is not None:
        src = src.astype(dtype)
    out = {"vals_q": jnp.pad(src, ((0, 0), (qf, qb), (0, 0)))}
    if dinv is not None:
        dt = dinv.dtype if dtype is None else dtype
        d = jnp.zeros((qc * _ps.LANES,), dt)
        d = jax.lax.dynamic_update_slice(d, dinv.astype(dt), (0,))
        out["dinv_q"] = jnp.pad(d.reshape(qc, _ps.LANES),
                                ((qf, qb), (0, 0)))
    return out


def solver_fused_slabs(solver, A, dinv=None):
    """Memoized per-solver fused-operand slabs, or None. Built only
    when the fused kernels can actually run (TPU backend, or the
    interpret-forcing test hook) so CPU rigs pay nothing. The memo key
    is the identity of the value-carrying arrays, so a resetup (full or
    value-only splice) that swaps in new coefficients rebuilds the
    slabs and the solve-data contract (fresh leaves after a value
    change) holds. `solver._slab_dtype` (set by the hierarchy from the
    precision policy when the smoother attaches to a level) emits the
    slabs directly in the effective precision."""
    if not fused_runtime_on() or not _slab_eligible(A):
        return None
    dtype = getattr(solver, "_slab_dtype", None)
    memo = getattr(solver, "_fused_slab_memo", None)
    # the memo RETAINS the source arrays and compares by `is`: a key of
    # bare id()s could alias a freed-then-reallocated array address and
    # silently serve slabs built from the previous coefficients
    if memo is not None and memo[0] is A.dia_vals and memo[1] is dinv \
            and memo[2] == dtype:
        return memo[3]
    slabs = build_fused_slabs(A, dinv, dtype=dtype)
    solver._fused_slab_memo = (A.dia_vals, dinv, dtype, slabs)
    return slabs


def _fused_dtype_ok(A, x_dtype) -> bool:
    """Dtype gate that COUNTS its declines: a level carrying a fused
    payload whose effective dtype is off the kernel whitelist is the
    exact silent reroute that used to drop `amg_precision=bfloat16`
    configs back to the unfused composition with no trace. Returns
    True when the dtype is fine; False — after counting
    `fusion.declined_dtype` (trace-time host work only) — when the
    caller must fall back. SolveReport's kernel-activity table
    surfaces the same routing per level."""
    if _ps.smooth_dtype_ok(A, x_dtype):
        return True
    from ..telemetry import metrics as _tm
    _tm.inc("fusion.declined_dtype")
    return False


# ---------------------------------------------------------------------------
# custom_vmap-wrapped fused calls (DIA)
# ---------------------------------------------------------------------------


def _out_batched(with_residual):
    return (True, True) if with_residual else True


def _xla_single(A, taus, b, x, dinv, with_residual):
    """XLA single-vector form (vmap fallback): the slab form with a
    unit batch, so the DIA shift arithmetic lives in one place."""
    from .batched import smooth_dia_multi
    out = smooth_dia_multi(A, b[None], x[None], taus, dinv,
                           with_residual)
    if with_residual:
        return out[0][0], out[1][0]
    return out[0]


@functools.lru_cache(maxsize=None)
def _fused_dia_fn(with_residual: bool, has_dinv: bool):
    """custom_vmap-wrapped fused DIA call. Batched matrices / taus /
    dinv take the vmapped XLA form; a batch that only carries the
    vectors (multi-RHS against one matrix — the batch subsystem's
    shared-pattern shape) takes the multi-RHS slab form so the values
    stream once per slab pass."""
    tu = jax.tree_util

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, vals_q, dinv_q, dinv, taus, b, x):
            return _ps._dia_smooth_call(vals_q, dinv_q, taus, b, x,
                                        A.dia_offsets, A.num_rows,
                                        with_residual,
                                        interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, vals_q, dinv_q, dinv, taus,
                  b, x):
            mat_b = any(tu.tree_leaves(in_batched[:5]))
            b_b, x_b = in_batched[5], in_batched[6]
            if not mat_b:
                from .batched import smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_dia_multi(A, B, X, taus, dinv,
                                         with_residual),
                        _out_batched(with_residual))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, vq_, dq_, dv_, t_, b_, x_: _xla_single(  # noqa: E731
                A_, t_, b_, x_, dv_, with_residual)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, vals_q, dinv_q, dinv, taus, b, x)
            return y, _out_batched(with_residual)
    else:
        @jax.custom_batching.custom_vmap
        def call(A, vals_q, taus, b, x):
            return _ps._dia_smooth_call(vals_q, None, taus, b, x,
                                        A.dia_offsets, A.num_rows,
                                        with_residual,
                                        interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, vals_q, taus, b, x):
            mat_b = any(tu.tree_leaves(in_batched[:3]))
            b_b, x_b = in_batched[3], in_batched[4]
            if not mat_b:
                from .batched import smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_dia_multi(A, B, X, taus, None,
                                         with_residual),
                        _out_batched(with_residual))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, vq_, t_, b_, x_: _xla_single(  # noqa: E731
                A_, t_, b_, x_, None, with_residual)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, vals_q, taus, b, x)
            return y, _out_batched(with_residual)

    return call


def _dia_call(A, fused, taus, b, x, dinv, with_residual):
    if dinv is not None:
        return _fused_dia_fn(with_residual, True)(
            A, fused["vals_q"], fused["dinv_q"], dinv, taus, b, x)
    return _fused_dia_fn(with_residual, False)(
        A, fused["vals_q"], taus, b, x)


def dia_fused_smooth(A, fused, b, x, taus, dinv=None,
                     with_residual=True):
    """Fused DIA smoother dispatch: x' (and r when `with_residual`)
    after len(taus) damped sweeps, or None when no fused plan applies
    (caller falls back to its unfused compose). One pallas_call when
    the whole schedule fits the plan budget; otherwise the largest
    supported fused sub-calls are chained — each still a single HBM
    pass over A's values."""
    if fused is None or getattr(A, "dia_vals", None) is None:
        return None
    if dinv is not None and "dinv_q" not in fused:
        return None
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    if not _fused_dtype_ok(A, x.dtype):
        return None
    sup = functools.partial(_ps.dia_smooth_supported, A, x.dtype)
    if sup(n_steps, with_residual):
        return _dia_call(A, fused, taus, b, x, dinv, with_residual)
    if not sup(1, False):
        return None
    # supported fused sweep-chunk sizes (no residual), largest first
    sizes = [c for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS), 0, -1)
             if sup(c, False)]
    # largest tail segment that can fuse WITH the residual epilogue
    tail = 0
    if with_residual:
        for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS - 1), 0, -1):
            if sup(c, True):
                tail = c
                break
    done = 0
    while n_steps - done - tail > 0:
        rem = n_steps - done - tail
        take = next((c for c in sizes if c <= rem), None)
        if take is None:        # tail too greedy for the remainder
            tail = 0
            continue
        x = _dia_call(A, fused, taus[done:done + take], b, x, dinv,
                      False)
        done += take
    if not with_residual:
        return x
    if tail:
        return _dia_call(A, fused, taus[done:], b, x, dinv, True)
    from .spmv import spmv
    return x, b - spmv(A, x)


# ---------------------------------------------------------------------------
# SWELL fused sweep (partial fusion: update in the kernel epilogue)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fused_swell_fn(has_dinv: bool):
    tu = jax.tree_util

    def _xla_step(A, b, x, tau, dinv):
        from .pallas_swell import swell_spmv_xla
        upd = tau * (b - swell_spmv_xla(A, x))
        if dinv is not None:
            upd = upd * dinv
        # round back to the vector dtype: bf16 states with f32 taus
        # would otherwise drift the state dtype across sweeps
        return (x + upd).astype(x.dtype)

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, b, x, tau, dinv):
            from .pallas_swell import swell_smooth_step
            return swell_smooth_step(A, b, x, tau, dinv)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, b, x, tau, dinv):
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(lambda A_, b_, x_, t_, d_: _xla_step(
                A_, b_, x_, t_, d_), in_axes=axes,
                axis_size=axis_size)(A, b, x, tau, dinv)
            return y, True
    else:
        @jax.custom_batching.custom_vmap
        def call(A, b, x, tau):
            from .pallas_swell import swell_smooth_step
            return swell_smooth_step(A, b, x, tau, None)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, b, x, tau):
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(lambda A_, b_, x_, t_: _xla_step(
                A_, b_, x_, t_, None), in_axes=axes,
                axis_size=axis_size)(A, b, x, tau)
            return y, True

    return call


def swell_fused_smooth(A, b, x, taus, dinv=None, with_residual=True):
    """Fused-epilogue SWELL smoother: each sweep is one kernel pass
    computing x' directly (no separate elementwise pass); the trailing
    residual — which needs A applied to the fully-updated x' — stays a
    plain SpMV pass. None when the SWELL fused path does not apply."""
    from .pallas_swell import swell_smooth_supported
    if not swell_smooth_supported(A, x.dtype):
        return None
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    for t in range(n_steps):
        if dinv is not None:
            x = _fused_swell_fn(True)(A, b, x, taus[t], dinv)
        else:
            x = _fused_swell_fn(False)(A, b, x, taus[t])
    if not with_residual:
        return x
    from .spmv import spmv
    return x, b - spmv(A, x)


# ---------------------------------------------------------------------------
# solver-facing entry
# ---------------------------------------------------------------------------


def fused_smooth(data, b, x, taus, dinv=None, with_residual=True):
    """Try every fused route for the smoother data pytree: DIA first
    (full fusion), then SWELL (epilogue fusion). Returns x' (, r) or
    None — callers keep their unfused compose as the fallback, so a
    missing layout/backend/dtype changes nothing.

    Distributed (ShardMatrix) levels route through the halo-folded
    per-shard form when the setup attached a "dist_fused" payload
    (distributed/fused.py): one edge-window exchange + one fused kernel
    per shard instead of a full halo exchange per sweep."""
    A = data["A"]
    from ..matrix import CsrMatrix
    # taus carry at the ACCUMULATION dtype (f32 for bf16 operands):
    # a bf16-rounded damping schedule would waste precision the f32
    # in-kernel arithmetic keeps; identity for f32/f64 vectors
    taus = jnp.asarray(taus, _ps.compute_dtype(x.dtype))
    if not isinstance(A, CsrMatrix) or A.is_block:
        fd = data.get("dist_fused")
        if fd is not None:
            from ..distributed.fused import dist_fused_smooth
            return dist_fused_smooth(fd, b, x, taus, dinv,
                                     with_residual)
        return None
    out = dia_fused_smooth(A, data.get("fused"), b, x, taus, dinv,
                           with_residual)
    if out is not None:
        return out
    return swell_fused_smooth(A, b, x, taus, dinv, with_residual)


# ---------------------------------------------------------------------------
# cycle fusion: grid-transfer epilogues + VMEM-resident coarse tail
# ---------------------------------------------------------------------------


def _coarse_window_tables(crmin, crmax, n: int, ncr: int, offsets):
    """Per-candidate-block-size coarse window sizes + base tables from
    per-128-lane-row coarse-ROW min/max reach arrays (sentinel `big`
    min / -1 max for rows referencing nothing). Shared by the
    aggregation and the general-CSR slab builders so the window math
    the plans budget against can never fork."""
    import numpy as np
    L = _ps.LANES
    rows128 = max(1, -(-n // L))
    big = np.int64(1) << 60
    mr0, Mr0 = _ps.smooth_halo_rows(offsets)
    K1 = _ps.SMOOTH_MAX_APPS * mr0
    K2 = _ps.SMOOTH_MAX_APPS * Mr0

    def _block_minmax(lo_off, hi_off, br, nb):
        mn = np.full(nb, big)
        mx = np.full(nb, np.int64(-1))
        for i in range(nb):
            lo = max(0, i * br + lo_off)
            hi = min(rows128, i * br + br + hi_off)
            if hi > lo:
                mn[i] = crmin[lo:hi].min()
                mx[i] = crmax[lo:hi].max()
        return mn, mx

    windows = []
    bases = {}
    for br in _ps.smooth_br_candidates(n):
        nb = -(-rows128 // br)
        if nb > 4096:
            continue        # base-table build cost guard (tiny brs at
            # huge n are never picked by the plans anyway)
        mn, mx = _block_minmax(0, 0, br, nb)
        mn = np.where(mx < 0, 0, np.minimum(mn, ncr - 1))
        mx = np.maximum(mx, mn)
        cw = int(min(ncr, -(-int((mx - mn).max() + 1) // 8) * 8))
        cb = np.clip(mn, 0, ncr - cw).astype(np.int32)
        mn2, mx2 = _block_minmax(-K1, K2, br, nb)
        mn2 = np.where(mx2 < 0, 0, np.minimum(mn2, ncr - 1))
        mx2 = np.maximum(mx2, mn2)
        pcw = int(min(ncr, -(-int((mx2 - mn2).max() + 1) // 8) * 8))
        pcb = np.clip(mn2, 0, ncr - pcw).astype(np.int32)
        windows.append((br, cw, pcw))
        bases[br] = (jnp.asarray(cb), jnp.asarray(pcb))
    return tuple(windows), bases


def build_transfer_slabs(A, agg, nc: int):
    """Structure-only transfer payloads for the fused grid-transfer
    kernels (host numpy build, one device upload per (re)setup):
    child-index slab ctab[j][c] = fine slot of aggregate c's j-th
    child (-1 absent), aggregate-id slab atab[slot] = coarse id (-1 at
    padding), and the per-candidate-block-size coarse window bases the
    kernels DMA coarse rows through. Returns None when A has no
    eligible DIA layout or an aggregate exceeds TRANSFER_MAX_CHILD."""
    import numpy as np
    if not _slab_eligible(A) or A.dia_offsets is None:
        return None
    offsets = A.dia_offsets
    n = A.num_rows
    agg = np.asarray(agg).ravel().astype(np.int64)
    if agg.shape[0] != n or nc < 1:
        return None
    counts = np.bincount(agg, minlength=nc)
    m = int(counts.max()) if n else 0
    if m < 1 or m > _ps.TRANSFER_MAX_CHILD:
        return None
    ncr = _ps.coarse_pad_rows(nc)
    L = _ps.LANES
    order = np.argsort(agg, kind="stable")
    starts = np.zeros(nc + 1, np.int64)
    starts[1:] = np.cumsum(counts)
    pos = np.arange(n, dtype=np.int64) - starts[agg[order]]
    ctab = np.full((m, ncr * L), -1, np.int32)
    ctab[pos, agg[order]] = order.astype(np.int32)
    ctab = ctab.reshape(m, ncr, L)
    aqf, aqc, aqb = _ps.transfer_quota_rows(offsets, n)
    atab = np.full(((aqf + aqc + aqb) * L,), -1, np.int32)
    atab[aqf * L: aqf * L + n] = agg
    atab = atab.reshape(-1, L)
    # per-fine-row coarse row min/max -> per-block window bases for
    # every block size the plans could pick
    rows128 = max(1, -(-n // L))
    aggp = np.full((rows128 * L,), -1, np.int64)
    aggp[:n] = agg
    a2 = aggp.reshape(rows128, L)
    big = np.int64(1) << 60
    crmin = np.where(a2 >= 0, a2 // L, big).min(axis=1)
    crmax = np.where(a2 >= 0, a2 // L, -1).max(axis=1)
    windows, bases = _coarse_window_tables(crmin, crmax, n, ncr,
                                           offsets)
    if not windows:
        return None
    return _ps.TransferSlabs(jnp.asarray(ctab), jnp.asarray(atab),
                             bases, int(nc), ncr, m, windows)


def build_csr_transfer_slabs(A, P, R, dtype=None):
    """WEIGHTED row-segment transfer payloads for the fused
    grid-transfer kernels over general CSR interpolation (classical
    Ruge-Stuben levels; host numpy build, one device upload). `dtype`
    emits the weight slabs (cwt/pwt) in the hierarchy's effective
    precision (precision.py) — the index tables stay int32 either way.
    The aggregation slabs generalize entrywise:

    - restriction (R = P^T, nc x n): ctab[j][c] = fine slot of R row
      c's j-th entry (-1 absent), cwt[j][c] = its weight — the kernel
      epilogue computes bc[c] = sum_j cwt[j][c] * r[ctab[j][c]];
    - prolongation (P, n x nc): ptab[j][slot] / pwt[j][slot] = the
      j-th (coarse id, weight) entry of P's row at that fine slot,
      quota-padded like atab — the prologue folds
      x += sum_j pwt[j] * xc[ptab[j]] into the postsmoother's first
      application.

    Classical structure reuse keeps P/R (values included) across
    value resetups, so these slabs are structure-lifetime payloads
    exactly like the aggregation child tables. Returns None when A
    has no eligible DIA layout, P/R shapes disagree with A, or a row
    exceeds the child caps (CSR_TRANSFER_MAX_CHILD restriction /
    TRANSFER_MAX_CHILD prolongation)."""
    import numpy as np
    if not _slab_eligible(A) or A.dia_offsets is None:
        return None
    if P is None or R is None or getattr(P, "is_block", True):
        return None
    offsets = A.dia_offsets
    n = A.num_rows
    nc = int(P.num_cols)
    if int(P.num_rows) != n or nc < 1 or int(R.num_rows) != nc \
            or int(R.num_cols) != n:
        return None
    pro = np.asarray(P.row_offsets).astype(np.int64)
    pci = np.asarray(P.col_indices).astype(np.int64)
    pv = np.asarray(P.values)
    rro = np.asarray(R.row_offsets).astype(np.int64)
    rci = np.asarray(R.col_indices).astype(np.int64)
    rv = np.asarray(R.values)
    rlen = np.diff(rro)
    plen = np.diff(pro)
    m = int(rlen.max()) if nc else 0
    mp = int(plen.max()) if n else 0
    if m < 1 or m > _ps.CSR_TRANSFER_MAX_CHILD \
            or mp < 1 or mp > _ps.TRANSFER_MAX_CHILD:
        return None
    ncr = _ps.coarse_pad_rows(nc)
    L = _ps.LANES
    # restriction row segments, entry j of R row c
    jpos = np.arange(rci.shape[0], dtype=np.int64) \
        - np.repeat(rro[:-1], rlen)
    crow = np.repeat(np.arange(nc, dtype=np.int64), rlen)
    ctab = np.full((m, ncr * L), -1, np.int32)
    cwt = np.zeros((m, ncr * L), rv.dtype)
    ctab[jpos, crow] = rci.astype(np.int32)
    cwt[jpos, crow] = rv
    ctab = ctab.reshape(m, ncr, L)
    cwt = cwt.reshape(m, ncr, L)
    # prolongation row segments, entry j of P row i, quota-padded
    aqf, aqc, aqb = _ps.transfer_quota_rows(offsets, n)
    rows_q = aqf + aqc + aqb
    jp = np.arange(pci.shape[0], dtype=np.int64) \
        - np.repeat(pro[:-1], plen)
    prow = np.repeat(np.arange(n, dtype=np.int64), plen)
    ptab = np.full((mp, rows_q * L), -1, np.int32)
    pwt = np.zeros((mp, rows_q * L), pv.dtype)
    ptab[jp, aqf * L + prow] = pci.astype(np.int32)
    pwt[jp, aqf * L + prow] = pv
    ptab = ptab.reshape(mp, rows_q, L)
    pwt = pwt.reshape(mp, rows_q, L)
    # per-fine-slot coarse reach (min/max coarse id P's row touches)
    # -> per-128-row coarse-ROW reach -> per-block window bases
    big = np.int64(1) << 60
    minc = np.full(n, big, np.int64)
    maxc = np.full(n, np.int64(-1), np.int64)
    np.minimum.at(minc, prow, pci)
    np.maximum.at(maxc, prow, pci)
    rows128 = max(1, -(-n // L))
    minp = np.full((rows128 * L,), big, np.int64)
    maxp = np.full((rows128 * L,), np.int64(-1), np.int64)
    minp[:n] = minc
    maxp[:n] = maxc
    mn2 = minp.reshape(rows128, L)
    mx2 = maxp.reshape(rows128, L)
    crmin = np.where(mx2 >= 0, mn2 // L, big).min(axis=1)
    crmax = np.where(mx2 >= 0, mx2 // L, -1).max(axis=1)
    windows, bases = _coarse_window_tables(crmin, crmax, n, ncr,
                                           offsets)
    if not windows:
        return None
    wavg = max(1, -(-int(rlen.sum()) // max(nc, 1)))
    pavg = max(1, -(-int(plen.sum()) // max(n, 1)))
    if dtype is not None:
        # numpy-side cast (ml_dtypes covers bfloat16): the weight
        # slabs upload already-narrow, no full-precision twin
        cwt = cwt.astype(jnp.dtype(dtype))
        pwt = pwt.astype(jnp.dtype(dtype))
    return _ps.TransferSlabs(
        jnp.asarray(ctab), None, bases, int(nc), ncr, m, windows,
        cwt=jnp.asarray(cwt), ptab=jnp.asarray(ptab),
        pwt=jnp.asarray(pwt), mp=mp, wavg=wavg, pavg=pavg)


def _xla_restrict_single(A, taus, b, x, dinv, xfer):
    from .batched import smooth_restrict_dia_multi
    X, BC = smooth_restrict_dia_multi(A, b[None], x[None], taus, dinv,
                                      xfer)
    return X[0], BC[0]


def _xla_corr_single(A, taus, b, x, xc, dinv, xfer):
    from .batched import corr_smooth_dia_multi
    return corr_smooth_dia_multi(A, b[None], x[None], xc[None], taus,
                                 dinv, xfer)[0]


@functools.lru_cache(maxsize=None)
def _fused_restrict_fn(has_dinv: bool):
    """custom_vmap-wrapped fused presmooth+restrict call: vector-only
    batches (solve_many) take the multi-RHS slab form in ops/batched.py;
    batched matrices take the vmapped XLA compose."""
    tu = jax.tree_util

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, xfer, vals_q, dinv_q, dinv, taus, b, x):
            return _ps._dia_smooth_restrict_call(
                vals_q, dinv_q, taus, b, x, xfer, A.dia_offsets,
                A.num_rows, interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, xfer, vals_q, dinv_q, dinv,
                  taus, b, x):
            mat_b = any(tu.tree_leaves(in_batched[:6]))
            b_b, x_b = in_batched[6], in_batched[7]
            if not mat_b:
                from .batched import smooth_restrict_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_restrict_dia_multi(A, B, X, taus, dinv,
                                                  xfer), (True, True))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, xf_, vq_, dq_, dv_, t_, b_, x_: \
                _xla_restrict_single(A_, t_, b_, x_, dv_, xf_)  # noqa: E731
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, xfer, vals_q, dinv_q, dinv, taus, b, x)
            return y, (True, True)
    else:
        @jax.custom_batching.custom_vmap
        def call(A, xfer, vals_q, taus, b, x):
            return _ps._dia_smooth_restrict_call(
                vals_q, None, taus, b, x, xfer, A.dia_offsets,
                A.num_rows, interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, xfer, vals_q, taus, b, x):
            mat_b = any(tu.tree_leaves(in_batched[:4]))
            b_b, x_b = in_batched[4], in_batched[5]
            if not mat_b:
                from .batched import smooth_restrict_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_restrict_dia_multi(A, B, X, taus, None,
                                                  xfer), (True, True))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, xf_, vq_, t_, b_, x_: \
                _xla_restrict_single(A_, t_, b_, x_, None, xf_)  # noqa: E731
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, xfer, vals_q, taus, b, x)
            return y, (True, True)

    return call


def _xb_dot(y, b):
    """The x'.b dot epilogue's XLA twin (the cycle-borne r.z of the
    Krylov shell): accumulation-dtype reduction over the last axis, so
    the batched/vmapped routes agree with the kernel's f32 partials."""
    cdt = _ps.compute_dtype(y.dtype)
    return jnp.sum(y.astype(cdt) * b.astype(cdt), axis=-1)


@functools.lru_cache(maxsize=None)
def _fused_corr_fn(has_dinv: bool, with_dot: bool = False):
    """custom_vmap-wrapped prolongation-prologue+postsmooth call.
    `with_dot` appends the x'.b dot epilogue (the Krylov shell's
    cycle-borne r.z reduction — b IS the preconditioner rhs r and x'
    IS z, so x'.b = r.z) and makes every route return (x', dot)."""
    tu = jax.tree_util
    ob = (True, True) if with_dot else True

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, xfer, vals_q, dinv_q, dinv, taus, b, x, xc):
            return _ps._dia_prolong_smooth_call(
                vals_q, dinv_q, taus, b, x, xc, xfer, A.dia_offsets,
                A.num_rows, with_dot=with_dot,
                interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, xfer, vals_q, dinv_q, dinv,
                  taus, b, x, xc):
            mat_b = any(tu.tree_leaves(in_batched[:6]))
            b_b, x_b, xc_b = in_batched[6], in_batched[7], in_batched[8]
            if not mat_b:
                from .batched import corr_smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                XC = xc if xc_b else jnp.broadcast_to(
                    xc, (axis_size,) + xc.shape)
                y = corr_smooth_dia_multi(A, B, X, XC, taus, dinv,
                                          xfer)
                return ((y, _xb_dot(y, B)) if with_dot else y), ob

            def fn(A_, xf_, vq_, dq_, dv_, t_, b_, x_, xc_):
                y_ = _xla_corr_single(A_, t_, b_, x_, xc_, dv_, xf_)
                return (y_, _xb_dot(y_, b_)) if with_dot else y_

            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, xfer, vals_q, dinv_q, dinv, taus, b, x, xc)
            return y, ob
    else:
        @jax.custom_batching.custom_vmap
        def call(A, xfer, vals_q, taus, b, x, xc):
            return _ps._dia_prolong_smooth_call(
                vals_q, None, taus, b, x, xc, xfer, A.dia_offsets,
                A.num_rows, with_dot=with_dot,
                interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, xfer, vals_q, taus, b, x,
                  xc):
            mat_b = any(tu.tree_leaves(in_batched[:4]))
            b_b, x_b, xc_b = in_batched[4], in_batched[5], in_batched[6]
            if not mat_b:
                from .batched import corr_smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                XC = xc if xc_b else jnp.broadcast_to(
                    xc, (axis_size,) + xc.shape)
                y = corr_smooth_dia_multi(A, B, X, XC, taus, None,
                                          xfer)
                return ((y, _xb_dot(y, B)) if with_dot else y), ob

            def fn(A_, xf_, vq_, t_, b_, x_, xc_):
                y_ = _xla_corr_single(A_, t_, b_, x_, xc_, None, xf_)
                return (y_, _xb_dot(y_, b_)) if with_dot else y_

            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, xfer, vals_q, taus, b, x, xc)
            return y, ob

    return call


def _restrict_call(A, fused, xfer, taus, b, x, dinv):
    if dinv is not None:
        return _fused_restrict_fn(True)(
            A, xfer, fused["vals_q"], fused["dinv_q"], dinv, taus, b, x)
    return _fused_restrict_fn(False)(A, xfer, fused["vals_q"], taus,
                                     b, x)


def _corr_call(A, fused, xfer, taus, b, x, xc, dinv, with_dot=False):
    if dinv is not None:
        return _fused_corr_fn(True, with_dot)(
            A, xfer, fused["vals_q"], fused["dinv_q"], dinv, taus, b,
            x, xc)
    return _fused_corr_fn(False, with_dot)(A, xfer, fused["vals_q"],
                                           taus, b, x, xc)


def _transfer_ready(data, xfer, dinv):
    A = data["A"]
    from ..matrix import CsrMatrix
    if not isinstance(A, CsrMatrix) or A.is_block:
        return None
    fused = data.get("fused")
    if xfer is None or fused is None \
            or getattr(A, "dia_vals", None) is None:
        return None
    if dinv is not None and "dinv_q" not in fused:
        return None
    return A, fused


def fused_smooth_restrict(data, b, x, taus, xfer, dinv=None):
    """Fused presmooth + restriction: (x', bc) after len(taus) damped
    sweeps with bc = R (b - A x') emitted by the kernel epilogue, or
    None when no fused plan applies (caller composes smooth_residual +
    level.restrict). Oversized schedules chain plain fused sweep
    chunks, with the restriction riding the final chunk's epilogue."""
    ready = _transfer_ready(data, xfer, dinv)
    if ready is None:
        return None
    A, fused = ready
    taus = jnp.asarray(taus, _ps.compute_dtype(x.dtype))
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    if not _fused_dtype_ok(A, x.dtype):
        return None
    sup_r = functools.partial(_ps.dia_restrict_supported, A, x.dtype,
                              xfer=xfer)
    if sup_r(n_steps):
        return _restrict_call(A, fused, xfer, taus, b, x, dinv)
    tail = next((c for c in range(
        min(n_steps - 1, _ps.SMOOTH_MAX_APPS - 1), 0, -1)
        if sup_r(c)), 0)
    if not tail or not _ps.dia_smooth_supported(A, x.dtype, 1, False):
        return None
    head = dia_fused_smooth(A, fused, b, x, taus[:n_steps - tail],
                            dinv=dinv, with_residual=False)
    if head is None:
        return None
    return _restrict_call(A, fused, xfer, taus[n_steps - tail:], b,
                          head, dinv)


def fused_corr_smooth(data, b, x, xc, taus, xfer, dinv=None,
                      want_dot=False):
    """Fused prolongation/correction + postsmooth: x' after len(taus)
    damped sweeps starting from x + P xc (the correction folded into
    the first kernel's prologue), or None when no fused plan applies.
    Oversized schedules run the prologue chunk first, then chain plain
    fused sweep chunks. `want_dot` asks for the cycle-borne x'.b dot
    (PCG's r.z) from the LAST kernel's epilogue: the single-call route
    returns (x', dot); the chunked route returns (x', None) — the dot
    would have to ride a mid-chain kernel, so the caller reduces it
    with one standalone pass instead."""
    ready = _transfer_ready(data, xfer, dinv)
    if ready is None:
        return None
    A, fused = ready
    taus = jnp.asarray(taus, _ps.compute_dtype(x.dtype))
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    if not _fused_dtype_ok(A, x.dtype):
        return None
    sup_p = functools.partial(_ps.dia_prolong_supported, A, x.dtype,
                              xfer=xfer)
    if sup_p(n_steps):
        return _corr_call(A, fused, xfer, taus, b, x, xc, dinv,
                          with_dot=want_dot)
    head = next((c for c in range(
        min(n_steps - 1, _ps.SMOOTH_MAX_APPS), 0, -1) if sup_p(c)), 0)
    if not head or not _ps.dia_smooth_supported(A, x.dtype, 1, False):
        return None
    x = _corr_call(A, fused, xfer, taus[:head], b, x, xc, dinv)
    x = dia_fused_smooth(A, fused, b, x, taus[head:], dinv=dinv,
                         with_residual=False)
    return (x, None) if want_dot else x


# ---------------------------------------------------------------------------
# VMEM-resident coarse-tail dispatch
# ---------------------------------------------------------------------------


def _tail_single_xla(arrs, b, x, spec):
    from .batched import tail_cycle_multi
    return tail_cycle_multi(arrs, b[None], x[None], spec)[0]


@functools.lru_cache(maxsize=None)
def _tail_fn(spec, with_dot: bool = False):
    """custom_vmap-wrapped coarse-tail call for one static TailSpec:
    vector-only batches (solve_many's shared-hierarchy shape) take the
    slab form in ops/batched.py; batched hierarchies (multi-matrix
    solves) take the vmapped XLA compose. `with_dot` appends the x'.b
    dot epilogue (cycle-borne r.z) on every route."""
    tu = jax.tree_util
    ob = (True, True) if with_dot else True

    @jax.custom_batching.custom_vmap
    def call(arrs, b, x):
        return _ps._dia_coarse_tail_call(arrs, b, x, spec,
                                         with_dot=with_dot,
                                         interpret=_ps._FORCE_INTERPRET)

    @call.def_vmap
    def _rule(axis_size, in_batched, arrs, b, x):
        mat_b = any(tu.tree_leaves(in_batched[0]))
        b_b, x_b = in_batched[1], in_batched[2]
        if not mat_b:
            from .batched import tail_cycle_multi
            B = b if b_b else jnp.broadcast_to(b, (axis_size,) + b.shape)
            X = x if x_b else jnp.broadcast_to(x, (axis_size,) + x.shape)
            y = tail_cycle_multi(arrs, B, X, spec)
            return ((y, _xb_dot(y, B)) if with_dot else y), ob

        def one(a_, b_, x_):
            y_ = _tail_single_xla(a_, b_, x_, spec)
            return (y_, _xb_dot(y_, b_)) if with_dot else y_

        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        y = jax.vmap(one, in_axes=axes, axis_size=axis_size)(arrs, b, x)
        return y, ob

    return call


def _tail_taus(taus, dtype):
    """(padded taus array, static application count): zero-sweep levels
    carry a 1-entry dummy the kernel never reads (0-sized VMEM operands
    are not portable)."""
    n = int(taus.shape[0])
    if n == 0:
        return jnp.zeros((1,), dtype), 0
    return taus.astype(dtype), n


def coarse_tail_cycle(amg, shape: str, data, lvl: int, b, x,
                      want_dot=False):
    """Run the whole sub-cycle at levels >= lvl as ONE pallas_call with
    every intermediate vector VMEM-resident, or None when the tail is
    ineligible (caller recurses per level). Eligible when: fixed cycle
    shape, f32, every tail level is an aggregation/DIA level with
    transfer+fused slabs and a fused-capable smoother, the coarse
    solver is NOSOLVER or exposes its dense inverse, the entry level is
    under cycle_fusion_tail_rows, and everything fits the VMEM budget
    together. `want_dot` (Krylov shell) makes the megakernel also emit
    the x'.b dot — the whole-cycle-resident case's cycle-borne r.z —
    and the return becomes (x', dot)."""
    if shape not in ("V", "W", "F") or not fused_runtime_on():
        return None
    if jnp.dtype(x.dtype).name not in _ps.SMOOTH_DTYPES:
        return None
    levels = amg.levels
    nlv = len(levels)
    if lvl >= nlv:
        return None
    if levels[lvl].A.num_rows > int(
            getattr(amg, "cycle_fusion_tail_rows", 0)):
        return None
    specs = []
    arrs = []
    total = 0
    for i in range(lvl, nlv):
        lv = levels[i]
        ld = data["levels"][i]
        if "R" in ld or "P" in ld:
            return None
        xfer = ld.get("xfer")
        smd = ld.get("smoother")
        if xfer is None or smd is None:
            return None
        if xfer.ptab is not None:
            # weighted (classical) slabs: _tail_compute's gathers are
            # unit-weight — those levels keep per-level kernels
            return None
        fused = smd.get("fused")
        mfst = smd.get("stencil")
        A = ld["A"]
        if mfst is None and (fused is None
                             or not _ps.smooth_dtype_ok(A, x.dtype)):
            return None
        spec_fn = getattr(lv.smoother, "fused_tail_spec", None)
        if spec_fn is None:
            return None
        cdt = _ps.compute_dtype(x.dtype)
        pre = spec_fn(smd, amg._sweeps(i, pre=True), cdt)
        post = spec_fn(smd, amg._sweeps(i, pre=False), cdt)
        if pre is None or post is None:
            return None
        taus_pre, n_pre = _tail_taus(pre[0], cdt)
        taus_post, n_post = _tail_taus(post[0], cdt)
        dinv = pre[1]
        offsets = A.dia_offsets
        qf, qc, _ = _ps.smooth_quota_rows(offsets, A.num_rows)
        aqf = _ps.transfer_quota_rows(offsets, A.num_rows)[0]
        ar = {
            "taus_pre": taus_pre,
            "taus_post": taus_post,
            "ctab": xfer.ctab,
            "atab_c": jax.lax.slice_in_dim(xfer.atab, aqf, aqf + qc,
                                           1, 0),
        }
        if mfst is not None:
            # matrix-free level: k coefficients instead of the value
            # slab; dinv is synthesized in-kernel from the stencil
            ar["coeffs"] = mfst.coeffs.astype(cdt)
            specs.append(_ps.TailLevelSpec(
                offsets=tuple(int(o) for o in offsets), n=A.num_rows,
                qc=qc, has_dinv=False, n_pre=n_pre, n_post=n_post,
                nc=xfer.nc, ncr=xfer.ncr, m=xfer.m, mf=mfst.spec()))
            total += sum(v.size * v.dtype.itemsize
                         for v in jax.tree_util.tree_leaves(ar))
            arrs.append(ar)
            continue
        ar["vals"] = jax.lax.slice_in_dim(fused["vals_q"], qf, qf + qc,
                                          1, 1)
        if dinv is not None:
            if "dinv_q" not in fused:
                return None
            ar["dinv"] = jax.lax.slice_in_dim(fused["dinv_q"], qf,
                                              qf + qc, 1, 0)
        specs.append(_ps.TailLevelSpec(
            offsets=tuple(int(o) for o in offsets), n=A.num_rows,
            qc=qc, has_dinv=dinv is not None, n_pre=n_pre,
            n_post=n_post, nc=xfer.nc, ncr=xfer.ncr, m=xfer.m))
        total += sum(v.size * v.dtype.itemsize
                     for v in jax.tree_util.tree_leaves(ar))
        arrs.append(ar)
    cd = data["coarse"]
    cs = amg.coarse_solver
    nz = specs[-1].nc
    ncrz = _ps.coarse_pad_rows(nz)
    if getattr(cs, "name", "") in ("NOSOLVER", "DUMMY"):
        coarse = ("none", nz, ncrz)
    elif "inv" in cd and cd["inv"].shape == (nz, nz) \
            and cd["inv"].dtype == jnp.float32:
        F = ncrz * _ps.LANES
        invT = jnp.zeros((F, F), jnp.float32)
        invT = jax.lax.dynamic_update_slice(invT, cd["inv"].T, (0, 0))
        arrs.append({"invT": invT})
        total += F * F * 4
        coarse = ("inv", nz, ncrz)
    else:
        return None
    # all slabs + ~2x working vectors must co-reside in VMEM
    if 2 * total > _ps._SMOOTH_VMEM_BUDGET:
        return None
    spec = _ps.TailSpec(shape, tuple(specs), coarse)
    # telemetry: remember (at trace time, zero solve-phase cost) the
    # outermost level the VMEM tail megakernel absorbed — SolveReport's
    # per-level activity table reads it back (telemetry/report.py)
    prev = getattr(amg, "_tail_entry_level", None)
    amg._tail_entry_level = lvl if prev is None else min(prev, lvl)
    return _tail_fn(spec, want_dot)(tuple(arrs), b, x)
