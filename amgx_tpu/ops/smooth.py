"""Fused smoother+residual dispatch for the V-cycle hot path.

The multigrid solve phase spends its time in presmooth -> residual ->
restrict and prolongate -> postsmooth; on a memory-bound TPU each
smoother sweep and the residual is a separate HBM pass over A. This
module routes the damped-relaxation smoother family

    x_{s+1} = x_s + tau_s * dinv . (b - A x_s)        (dinv optional)

(BLOCK_JACOBI / JACOBI_L1: tau_s = relaxation_factor, dinv = D^{-1};
CHEBYSHEV_POLY: tau_s = the magic-damping taus, no dinv) through the
fused Pallas kernels:

- DIA: all sweeps AND the trailing residual in ONE pallas_call
  (ops/pallas_spmv.py temporal blocking) — A's diagonal slab streams
  from HBM once instead of sweeps+1 times. When the full fusion misses
  the VMEM/traffic budget (deep halos at very large grids), the
  dispatcher chains the largest supported fused sub-calls, each still
  one pass over A.
- SWELL: each sweep is one pallas_call with the Jacobi update in the
  kernel epilogue (ops/pallas_swell.py) — the lane-gather layout cannot
  temporally block (window reach is unbounded), but fusing the update
  removes the separate elementwise pass and its 4 HBM streams; the
  final residual stays a plain SpMV pass.

Every entry point returns None when no fused plan applies, and the
calling smoother falls back to its unfused compose — so `fused_smoother=0`
(or any unsupported layout/dtype/backend) reproduces the pre-fusion
computation exactly. All Pallas routes are wrapped in `custom_vmap`
like `spmv_dia`: under `jax.vmap` (the batched-solve subsystem) the
multi-RHS slab forms in ops/batched.py run instead, so `solve_many`
gets the same fused-epilogue semantics without a per-system values
stream.

The DIA kernel needs its values/dinv operands with front-halo padding
the tile-aligned dia_vals store does not carry; `solver_fused_slabs`
builds those quota-padded slabs ONCE per (re)setup and the smoother
carries them in its solve_data pytree (so a value-only resetup refreshes
them and no per-cycle re-layout of A ever happens).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import pallas_spmv as _ps


def fused_runtime_on() -> bool:
    """Would the fused Pallas kernels run on this rig (or under the
    interpreter-forcing test hook)?"""
    return jax.default_backend() == "tpu" or _ps._FORCE_INTERPRET


# ---------------------------------------------------------------------------
# setup-time payloads (carried in smoother solve_data)
# ---------------------------------------------------------------------------


def _slab_eligible(A) -> bool:
    return (getattr(A, "dia_vals", None) is not None
            and not A.is_block and not A.has_external_diag
            and A.num_rows == A.num_cols)


def build_fused_slabs(A, dinv=None):
    """Quota-padded DIA operand slabs {vals_q[, dinv_q]} for the fused
    smoother kernel (eager device ops; see smooth_quota_rows for the
    layout). Returns None when A has no eligible DIA layout."""
    if not _slab_eligible(A):
        return None
    qf, qc, qb = _ps.smooth_quota_rows(A.dia_offsets, A.num_rows)
    k, rows_pad, _ = A.dia_vals.shape
    src = A.dia_vals[:, :qc] if rows_pad >= qc else jnp.pad(
        A.dia_vals, ((0, 0), (0, qc - rows_pad), (0, 0)))
    out = {"vals_q": jnp.pad(src, ((0, 0), (qf, qb), (0, 0)))}
    if dinv is not None:
        d = jnp.zeros((qc * _ps.LANES,), dinv.dtype)
        d = jax.lax.dynamic_update_slice(d, dinv, (0,))
        out["dinv_q"] = jnp.pad(d.reshape(qc, _ps.LANES),
                                ((qf, qb), (0, 0)))
    return out


def solver_fused_slabs(solver, A, dinv=None):
    """Memoized per-solver fused-operand slabs, or None. Built only
    when the fused kernels can actually run (TPU backend, or the
    interpret-forcing test hook) so CPU rigs pay nothing. The memo key
    is the identity of the value-carrying arrays, so a resetup (full or
    value-only splice) that swaps in new coefficients rebuilds the
    slabs and the solve-data contract (fresh leaves after a value
    change) holds."""
    if not fused_runtime_on() or not _slab_eligible(A):
        return None
    memo = getattr(solver, "_fused_slab_memo", None)
    # the memo RETAINS the source arrays and compares by `is`: a key of
    # bare id()s could alias a freed-then-reallocated array address and
    # silently serve slabs built from the previous coefficients
    if memo is not None and memo[0] is A.dia_vals and memo[1] is dinv:
        return memo[2]
    slabs = build_fused_slabs(A, dinv)
    solver._fused_slab_memo = (A.dia_vals, dinv, slabs)
    return slabs


# ---------------------------------------------------------------------------
# custom_vmap-wrapped fused calls (DIA)
# ---------------------------------------------------------------------------


def _out_batched(with_residual):
    return (True, True) if with_residual else True


def _xla_single(A, taus, b, x, dinv, with_residual):
    """XLA single-vector form (vmap fallback): the slab form with a
    unit batch, so the DIA shift arithmetic lives in one place."""
    from .batched import smooth_dia_multi
    out = smooth_dia_multi(A, b[None], x[None], taus, dinv,
                           with_residual)
    if with_residual:
        return out[0][0], out[1][0]
    return out[0]


@functools.lru_cache(maxsize=None)
def _fused_dia_fn(with_residual: bool, has_dinv: bool):
    """custom_vmap-wrapped fused DIA call. Batched matrices / taus /
    dinv take the vmapped XLA form; a batch that only carries the
    vectors (multi-RHS against one matrix — the batch subsystem's
    shared-pattern shape) takes the multi-RHS slab form so the values
    stream once per slab pass."""
    tu = jax.tree_util

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, vals_q, dinv_q, dinv, taus, b, x):
            return _ps._dia_smooth_call(vals_q, dinv_q, taus, b, x,
                                        A.dia_offsets, A.num_rows,
                                        with_residual,
                                        interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, vals_q, dinv_q, dinv, taus,
                  b, x):
            mat_b = any(tu.tree_leaves(in_batched[:5]))
            b_b, x_b = in_batched[5], in_batched[6]
            if not mat_b:
                from .batched import smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_dia_multi(A, B, X, taus, dinv,
                                         with_residual),
                        _out_batched(with_residual))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, vq_, dq_, dv_, t_, b_, x_: _xla_single(  # noqa: E731
                A_, t_, b_, x_, dv_, with_residual)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, vals_q, dinv_q, dinv, taus, b, x)
            return y, _out_batched(with_residual)
    else:
        @jax.custom_batching.custom_vmap
        def call(A, vals_q, taus, b, x):
            return _ps._dia_smooth_call(vals_q, None, taus, b, x,
                                        A.dia_offsets, A.num_rows,
                                        with_residual,
                                        interpret=_ps._FORCE_INTERPRET)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, vals_q, taus, b, x):
            mat_b = any(tu.tree_leaves(in_batched[:3]))
            b_b, x_b = in_batched[3], in_batched[4]
            if not mat_b:
                from .batched import smooth_dia_multi
                B = b if b_b else jnp.broadcast_to(
                    b, (axis_size,) + b.shape)
                X = x if x_b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
                return (smooth_dia_multi(A, B, X, taus, None,
                                         with_residual),
                        _out_batched(with_residual))
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            fn = lambda A_, vq_, t_, b_, x_: _xla_single(  # noqa: E731
                A_, t_, b_, x_, None, with_residual)
            y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
                A, vals_q, taus, b, x)
            return y, _out_batched(with_residual)

    return call


def _dia_call(A, fused, taus, b, x, dinv, with_residual):
    if dinv is not None:
        return _fused_dia_fn(with_residual, True)(
            A, fused["vals_q"], fused["dinv_q"], dinv, taus, b, x)
    return _fused_dia_fn(with_residual, False)(
        A, fused["vals_q"], taus, b, x)


def dia_fused_smooth(A, fused, b, x, taus, dinv=None,
                     with_residual=True):
    """Fused DIA smoother dispatch: x' (and r when `with_residual`)
    after len(taus) damped sweeps, or None when no fused plan applies
    (caller falls back to its unfused compose). One pallas_call when
    the whole schedule fits the plan budget; otherwise the largest
    supported fused sub-calls are chained — each still a single HBM
    pass over A's values."""
    if fused is None or getattr(A, "dia_vals", None) is None:
        return None
    if dinv is not None and "dinv_q" not in fused:
        return None
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    sup = functools.partial(_ps.dia_smooth_supported, A, x.dtype)
    if sup(n_steps, with_residual):
        return _dia_call(A, fused, taus, b, x, dinv, with_residual)
    if not sup(1, False):
        return None
    # supported fused sweep-chunk sizes (no residual), largest first
    sizes = [c for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS), 0, -1)
             if sup(c, False)]
    # largest tail segment that can fuse WITH the residual epilogue
    tail = 0
    if with_residual:
        for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS - 1), 0, -1):
            if sup(c, True):
                tail = c
                break
    done = 0
    while n_steps - done - tail > 0:
        rem = n_steps - done - tail
        take = next((c for c in sizes if c <= rem), None)
        if take is None:        # tail too greedy for the remainder
            tail = 0
            continue
        x = _dia_call(A, fused, taus[done:done + take], b, x, dinv,
                      False)
        done += take
    if not with_residual:
        return x
    if tail:
        return _dia_call(A, fused, taus[done:], b, x, dinv, True)
    from .spmv import spmv
    return x, b - spmv(A, x)


# ---------------------------------------------------------------------------
# SWELL fused sweep (partial fusion: update in the kernel epilogue)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fused_swell_fn(has_dinv: bool):
    tu = jax.tree_util

    def _xla_step(A, b, x, tau, dinv):
        from .pallas_swell import swell_spmv_xla
        upd = tau * (b - swell_spmv_xla(A, x))
        if dinv is not None:
            upd = upd * dinv
        return x + upd

    if has_dinv:
        @jax.custom_batching.custom_vmap
        def call(A, b, x, tau, dinv):
            from .pallas_swell import swell_smooth_step
            return swell_smooth_step(A, b, x, tau, dinv)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, b, x, tau, dinv):
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(lambda A_, b_, x_, t_, d_: _xla_step(
                A_, b_, x_, t_, d_), in_axes=axes,
                axis_size=axis_size)(A, b, x, tau, dinv)
            return y, True
    else:
        @jax.custom_batching.custom_vmap
        def call(A, b, x, tau):
            from .pallas_swell import swell_smooth_step
            return swell_smooth_step(A, b, x, tau, None)

        @call.def_vmap
        def _rule(axis_size, in_batched, A, b, x, tau):
            axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                         for ib in in_batched)
            y = jax.vmap(lambda A_, b_, x_, t_: _xla_step(
                A_, b_, x_, t_, None), in_axes=axes,
                axis_size=axis_size)(A, b, x, tau)
            return y, True

    return call


def swell_fused_smooth(A, b, x, taus, dinv=None, with_residual=True):
    """Fused-epilogue SWELL smoother: each sweep is one kernel pass
    computing x' directly (no separate elementwise pass); the trailing
    residual — which needs A applied to the fully-updated x' — stays a
    plain SpMV pass. None when the SWELL fused path does not apply."""
    from .pallas_swell import swell_smooth_supported
    if not swell_smooth_supported(A, x.dtype):
        return None
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    for t in range(n_steps):
        if dinv is not None:
            x = _fused_swell_fn(True)(A, b, x, taus[t], dinv)
        else:
            x = _fused_swell_fn(False)(A, b, x, taus[t])
    if not with_residual:
        return x
    from .spmv import spmv
    return x, b - spmv(A, x)


# ---------------------------------------------------------------------------
# solver-facing entry
# ---------------------------------------------------------------------------


def fused_smooth(data, b, x, taus, dinv=None, with_residual=True):
    """Try every fused route for the smoother data pytree: DIA first
    (full fusion), then SWELL (epilogue fusion). Returns x' (, r) or
    None — callers keep their unfused compose as the fallback, so a
    missing layout/backend/dtype changes nothing."""
    A = data["A"]
    from ..matrix import CsrMatrix
    if not isinstance(A, CsrMatrix) or A.is_block:
        return None
    taus = jnp.asarray(taus, x.dtype)
    out = dia_fused_smooth(A, data.get("fused"), b, x, taus, dinv,
                           with_residual)
    if out is not None:
        return out
    return swell_fused_smooth(A, b, x, taus, dinv, with_residual)
