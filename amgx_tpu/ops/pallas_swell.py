"""Pallas TPU SpMV kernel for the windowed-ELL (SWELL) layout —
unstructured matrices.

The reference's workhorse is a CUDA csrmv over arbitrary CSR
(src/multiply.cu:74-121); AMG coarse operators and the P/R transfer
operators are exactly such matrices. On TPU the XLA lowering of the
gather `x[col_indices]` is catastrophically slow (tens of ms per call at
level sizes) and Mosaic has no arbitrary-gather primitive — but it DOES
support `take_along_axis` within a (rows, 128) tile along lanes. This
kernel builds an SpMV out of that primitive:

- rows are tiled into super-blocks of 1024 (8 sublane groups x 128
  lanes); each super-block's columns all fall inside a window
  [c0_b, c0_b + W) of x, where W is the static max block span (AMG and
  interpolation matrices inherit the fine grid's locality, so
  W ~ bandwidth << num_cols);
- per block, the x window is DMA'd HBM->VMEM (double-buffered, like the
  DIA kernel) as (W/128, 128) chunks;
- entry slots are stored slot-major as (8, kpad, 128): sublane group =
  row-group, sublane = ELL slot, lane = row-in-group. Viewed as
  (8*kpad, 128), the gather decomposes per 128-wide window chunk c:
  take_along_axis(chunk broadcast, lo, axis=1) selected where the local
  column's hi bits == c;
- a fori_loop runs only the block's populated chunk count (nchunk_b,
  from SMEM), then y = sum over slots of acc * vals.

Traffic per block: 8*kpad*128 values + cols (the ELL-padded minimum)
plus a W-element window of x. Compute is ~3 VPU ops per (8*kpad, 128)
tile per chunk — compute-bound relative to HBM, but 50-500x faster than
the XLA gather form it replaces. float32 only (like the DIA kernel);
the XLA gather form below covers f64/CPU/batched callers.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import enable_x64

LANES = 128
SUBS = 8                      # sublane groups per super-block
BLOCK_ROWS = SUBS * LANES     # rows per super-block
SWELL_MAX_W = 512 * 1024      # max window elements (2 MB f32 a buffer)
SWELL_MAX_K = 256             # max padded slots per row
_VMEM_BUDGET = 10 * 1024 * 1024


def swell_budget(kmax, w128_raw, nb, nnz):
    """Single source of the SWELL layout-budget decisions, shared by the
    numpy builder below and the native-wrapper path
    (native/__init__.py swell_build_native) — the two drifted once and
    an un-rounded w128 lets the kernel's slab loop read past the VMEM
    window. Returns (kpad, w128) or None when the layout does not pay:
    - kpad: exact for short rows (interpolation operators, kmax 4-5,
      where round-to-8 inflated HBM and wire bytes ~2x), 8-aligned
      above (Mosaic relayouts large unaligned slot dims through
      scoped-VMEM copies);
    - w128: rounded to whole 8-chunk slabs (kernel slab loop + aligned
      VMEM scratch);
    - fill guard: one long row would otherwise inflate the padded
      layout to n*kpad slots; small layouts are exempt (round-to-8
      alone inflates tiny matrices past any ratio, and a <1M-slot
      layout cannot blow memory)."""
    if kmax == 0 or kmax > SWELL_MAX_K:
        return None
    w128 = -(-int(w128_raw) // 8) * 8
    if w128 * LANES > SWELL_MAX_W:
        return None
    kpad = kmax if kmax <= 24 else -(-kmax // 8) * 8
    slots = nb * SUBS * kpad * LANES
    if slots > 6 * max(nnz, 1) and slots > (1 << 20):
        return None
    return kpad, w128


def build_swell_host(ro, ci, vals, num_rows, num_cols):
    """Numpy construction of the SWELL layout for a host-resident CSR.

    Returns (cols4, vals4, c0row, nchunk, w128) or None when the layout
    does not pay (window or slot budget exceeded). cols4/vals4 are
    (nb, 8, kpad, 128) slot-major super-blocks; c0row is each block's
    window start in 128-rows of the padded x; nchunk its populated
    chunk count.
    """
    n = int(num_rows)
    if n == 0 or ci.shape[0] == 0:
        return None
    from .. import native
    out = native.swell_build_native(ro, ci, vals, n)
    if out is not False:                  # None = layout doesn't pay
        return out
    nb = -(-n // BLOCK_ROWS)
    row_nnz = np.diff(ro)
    kmax = int(row_nnz.max())
    if kmax == 0 or kmax > SWELL_MAX_K:
        return None                        # cheap reject before the scan
    # per-row col extents -> per-super-block window
    starts = ro[:-1].astype(np.int64)
    nonempty = ro[1:] > ro[:-1]
    idx = np.clip(starts, 0, ci.shape[0] - 1)
    big = np.iinfo(np.int32).max
    rmin = np.where(nonempty, np.minimum.reduceat(ci, idx), big)
    rmax = np.where(nonempty, np.maximum.reduceat(ci, idx), -1)
    pad = nb * BLOCK_ROWS - n
    if pad:
        rmin = np.concatenate([rmin, np.full(pad, big)])
        rmax = np.concatenate([rmax, np.full(pad, -1)])
    bmin = rmin.reshape(nb, BLOCK_ROWS).min(axis=1)
    bmax = rmax.reshape(nb, BLOCK_ROWS).max(axis=1)
    empty_b = bmax < 0
    bmin = np.where(empty_b, 0, bmin)
    bmax = np.where(empty_b, 0, bmax)
    c0 = (bmin // LANES) * LANES
    span = bmax - c0 + 1
    budget = swell_budget(kmax, -(-int(span.max()) // LANES), nb,
                          ci.shape[0])
    if budget is None:
        return None
    kpad, _w128 = budget
    w = _w128 * LANES
    nchunk = (-(-span // LANES)).astype(np.int32)
    # scatter entries into (nb, 8, kpad, 128) slot-major blocks
    row_ids = np.repeat(np.arange(n, dtype=np.int64), row_nnz)
    slot = np.arange(ci.shape[0], dtype=np.int64) - \
        ro[row_ids].astype(np.int64)
    b = row_ids // BLOCK_ROWS
    sub = (row_ids % BLOCK_ROWS) // LANES
    lane = row_ids & (LANES - 1)
    flat = (((b * SUBS + sub) * kpad) + slot) * LANES + lane
    cols4 = np.zeros(nb * SUBS * kpad * LANES, np.int32)
    cols4[flat] = ci - c0[b]
    vals4 = np.zeros(nb * SUBS * kpad * LANES, vals.dtype)
    vals4[flat] = vals
    return (cols4.reshape(nb, SUBS, kpad, LANES),
            vals4.reshape(nb, SUBS, kpad, LANES),
            (c0 // LANES).astype(np.int32), nchunk, w // LANES)


def swell_vals_host(ro, vals, num_rows, kpad):
    """Re-scatter new coefficients into an existing SWELL layout
    (replace_coefficients with structure reuse)."""
    n = int(num_rows)
    from .. import native
    out = native.swell_refill_native(ro, vals, n, int(kpad))
    if out is not None:
        return out
    nb = -(-n // BLOCK_ROWS)
    row_nnz = np.diff(ro)
    row_ids = np.repeat(np.arange(n, dtype=np.int64), row_nnz)
    slot = np.arange(vals.shape[0], dtype=np.int64) - \
        ro[row_ids].astype(np.int64)
    b = row_ids // BLOCK_ROWS
    sub = (row_ids % BLOCK_ROWS) // LANES
    flat = (((b * SUBS + sub) * kpad) + slot) * LANES + \
        (row_ids & (LANES - 1))
    vals4 = np.zeros(nb * SUBS * kpad * LANES, vals.dtype)
    vals4[flat] = vals
    return vals4.reshape(nb, SUBS, kpad, LANES)


def _swell_runtime_payload_ok(A) -> bool:
    """Backend + payload-presence checks shared by the SWELL gates."""
    from .pallas_spmv import _FORCE_INTERPRET
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    return A.swell_cols is not None and A.swell_vals is not None


def _swell_budget_ok(A, val_itemsize: int, out_blocks: int) -> bool:
    """One VMEM budget formula for both SWELL gates: the x window,
    the double-buffered cols(int32)+vals entry slabs (`val_itemsize`
    narrows for bf16 values), and `out_blocks` double-buffered
    (SUBS, 128) pipeline blocks (1 = SpMV's y; 4 = the fused sweep's
    x/b/dinv/out)."""
    w128 = A.swell_w128
    kpad = A.swell_vals.shape[2]
    win_bytes = 2 * w128 * LANES * 4
    ent_bytes = 2 * SUBS * kpad * LANES * (4 + val_itemsize)
    out_bytes = 2 * out_blocks * SUBS * LANES * 4
    return win_bytes + ent_bytes + out_bytes <= _VMEM_BUDGET


def swell_spmv_supported(A, x_dtype) -> bool:
    """Trace-time gate for the Pallas path (f32 only: the plain SpMV's
    output dtype is the caller's vector-dtype contract)."""
    if not _swell_runtime_payload_ok(A):
        return False
    if A.swell_vals.dtype != jnp.float32 or x_dtype != jnp.float32:
        return False
    return _swell_budget_ok(A, 4, 1)


def _swell_kernel(w128, kpad, n_blocks):
    rows = SUBS * kpad

    def kernel(c0_ref, nch_ref, xp_ref, cols_ref, vals_ref, y_ref,
               xbuf, sems):
        b = pl.program_id(0)
        slot = jax.lax.rem(b, jnp.int32(2))

        def dma(s, blk):
            return pltpu.make_async_copy(
                xp_ref.at[pl.ds(c0_ref[blk], w128)],
                xbuf.at[jnp.int32(s)], sems.at[jnp.int32(s)])

        @pl.when(b == 0)
        def _():
            dma(0, 0).start()

        @pl.when(b + 1 < n_blocks)
        def _():
            dma(jax.lax.rem(b + 1, jnp.int32(2)), b + 1).start()

        dma(slot, b).wait()

        cols = cols_ref[0].reshape(rows, LANES)   # slot-major local cols
        vals = vals_ref[0].reshape(rows, LANES)
        hi = jax.lax.shift_right_logical(cols, jnp.int32(7))
        lo = jax.lax.bitwise_and(cols, jnp.int32(LANES - 1))

        def slab_step(s, acc):
            # 8 window chunks per loop iteration: the fori overhead was
            # a measured ~40% of kernel time on wide-window operators
            # (AMG restriction matrices reach nchunk ~500); w128 is
            # 8-aligned by the builders so the last slab stays in range
            base = s * jnp.int32(8)
            for j in range(8):
                c = base + jnp.int32(j)
                chunk = xbuf[slot, pl.ds(c, 1)]   # (1, 128)
                src = jnp.broadcast_to(chunk, (rows, LANES))
                # keep the gather's index math int32 (Mosaic has no
                # i64; the package-level x64 default would promote)
                with enable_x64(False):
                    g = jnp.take_along_axis(src, lo, axis=1)
                acc = jnp.where(hi == c, g, acc)
            return acc

        nslab = jax.lax.div(nch_ref[b] + jnp.int32(7), jnp.int32(8))
        acc = jax.lax.fori_loop(jnp.int32(0), nslab, slab_step,
                                jnp.zeros((rows, LANES), jnp.float32))
        y_ref[...] = jnp.sum(
            (acc * vals).reshape(SUBS, kpad, LANES), axis=1)

    return kernel


@functools.partial(jax.jit, static_argnames=("w128", "num_rows",
                                             "interpret"))
def _swell_spmv_call(cols4, vals4, c0row, nchunk, x, w128, num_rows,
                     interpret=False):
    nb, _, kpad, _ = vals4.shape
    n = num_rows
    ncols = x.shape[0]
    # pad x to whole 128-rows plus the window overhang past the end
    xp_rows = -(-ncols // LANES) + w128
    xp = jnp.zeros((xp_rows * LANES,), jnp.float32)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(jnp.float32), (0,))
    xp = xp.reshape(xp_rows, LANES)

    kernel = _swell_kernel(w128, kpad, nb)
    y2 = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            # explicit shapes + int32 index maps: the default full-array
            # spec's index map emits i64 constants under the package's
            # x64 default, which Mosaic cannot legalize
            pl.BlockSpec((nb,), lambda b: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((nb,), lambda b: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, SUBS, kpad, LANES),
                         lambda b: (b, jnp.int32(0), jnp.int32(0),
                                    jnp.int32(0)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBS, kpad, LANES),
                         lambda b: (b, jnp.int32(0), jnp.int32(0),
                                    jnp.int32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((SUBS, LANES),
                               lambda b: (b, jnp.int32(0)),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * SUBS, LANES), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, w128, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * nb * SUBS * kpad * LANES,
            bytes_accessed=(2 * kpad + 1) * nb * SUBS * LANES * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(c0row, nchunk, xp, cols4, vals4)
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    return y


def swell_spmv(A, x, interpret=False):
    """Fused SWELL SpMV; caller must have checked swell_spmv_supported
    (`interpret=True` runs the Pallas interpreter — CPU test path)."""
    from .pallas_spmv import _FORCE_INTERPRET
    return _swell_spmv_call(A.swell_cols, A.swell_vals, A.swell_c0row,
                            A.swell_nchunk, x, A.swell_w128, A.num_rows,
                            interpret=interpret or _FORCE_INTERPRET)


# ---------------------------------------------------------------------------
# Fused smoother sweep: SpMV + damped-Jacobi update in one pass
#
# x' = x + tau * dinv . (b - A x) for the windowed-ELL layout. The
# lane-gather layout cannot temporally block like the DIA kernel (a
# block's x window reaches arbitrarily far, so a second in-kernel sweep
# would need other blocks' updated values), but fusing the elementwise
# update into the kernel epilogue removes the separate XLA pass and its
# 4 HBM streams (read y/x/b/dinv, write x') per sweep — the unfused
# shape materializes y to HBM because XLA cannot fuse into pallas_call
# outputs. x/b/dinv arrive as exact row blocks via auto-pipelined
# BlockSpecs (no halo needed: the update is pointwise in the row).
# ---------------------------------------------------------------------------


def swell_smooth_supported(A, x_dtype) -> bool:
    """Trace-time gate for the fused-sweep SWELL path. Unlike the
    plain-SpMV gate (f32-only: its output dtype is the caller's vector
    dtype contract), the fused sweep also accepts bf16 value slabs —
    the kernel already upcasts the gathered x window to f32 and the
    value multiply promotes, so only the value stream narrows; the
    wrapper rounds x' back to the vector dtype."""
    from .pallas_spmv import SMOOTH_DTYPES
    if not _swell_runtime_payload_ok(A):
        return False
    dt = jnp.dtype(A.swell_vals.dtype)
    if dt != jnp.dtype(x_dtype) or dt.name not in SMOOTH_DTYPES:
        return False
    if A.has_external_diag or A.num_rows != A.num_cols:
        return False
    # three extra (SUBS, 128) double-buffered blocks ride the pipeline
    return _swell_budget_ok(A, dt.itemsize, 4)


def _swell_smooth_kernel(w128, kpad, n_blocks, has_dinv):
    rows = SUBS * kpad

    def kernel(*refs):
        # refs: c0, nch, tau, xp, cols, vals, xblk, bblk, [dinvblk],
        #       out, xbuf, sems
        (c0_ref, nch_ref, tau_ref, xp_ref, cols_ref, vals_ref,
         xb_ref, bb_ref) = refs[:8]
        db_ref = refs[8] if has_dinv else None
        out_ref = refs[8 + (1 if has_dinv else 0)]
        xbuf = refs[9 + (1 if has_dinv else 0)]
        sems = refs[10 + (1 if has_dinv else 0)]

        b = pl.program_id(0)
        slot = jax.lax.rem(b, jnp.int32(2))

        def dma(s, blk):
            return pltpu.make_async_copy(
                xp_ref.at[pl.ds(c0_ref[blk], w128)],
                xbuf.at[jnp.int32(s)], sems.at[jnp.int32(s)])

        @pl.when(b == 0)
        def _():
            dma(0, 0).start()

        @pl.when(b + 1 < n_blocks)
        def _():
            dma(jax.lax.rem(b + 1, jnp.int32(2)), b + 1).start()

        dma(slot, b).wait()

        cols = cols_ref[0].reshape(rows, LANES)
        vals = vals_ref[0].reshape(rows, LANES)
        hi = jax.lax.shift_right_logical(cols, jnp.int32(7))
        lo = jax.lax.bitwise_and(cols, jnp.int32(LANES - 1))

        def slab_step(s, acc):
            base = s * jnp.int32(8)
            for j in range(8):
                c = base + jnp.int32(j)
                chunk = xbuf[slot, pl.ds(c, 1)]
                src = jnp.broadcast_to(chunk, (rows, LANES))
                with enable_x64(False):
                    g = jnp.take_along_axis(src, lo, axis=1)
                acc = jnp.where(hi == c, g, acc)
            return acc

        nslab = jax.lax.div(nch_ref[b] + jnp.int32(7), jnp.int32(8))
        acc = jax.lax.fori_loop(jnp.int32(0), nslab, slab_step,
                                jnp.zeros((rows, LANES), jnp.float32))
        y = jnp.sum((acc * vals).reshape(SUBS, kpad, LANES), axis=1)
        corr = tau_ref[0] * (bb_ref[...] - y)
        if has_dinv:
            corr = corr * db_ref[...]
        out_ref[...] = xb_ref[...] + corr

    return kernel


@functools.partial(jax.jit, static_argnames=("w128", "num_rows",
                                             "has_dinv", "interpret"))
def _swell_smooth_call(cols4, vals4, c0row, nchunk, x, b, dinv, tau,
                       w128, num_rows, has_dinv, interpret=False):
    nb, _, kpad, _ = vals4.shape
    n = num_rows
    ncols = x.shape[0]
    xp_rows = -(-ncols // LANES) + w128
    xp = jnp.zeros((xp_rows * LANES,), jnp.float32)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(jnp.float32), (0,))
    xp = xp.reshape(xp_rows, LANES)

    def rowpad(v):
        out = jnp.zeros((nb * BLOCK_ROWS,), jnp.float32)
        out = jax.lax.dynamic_update_slice(out, v.astype(jnp.float32),
                                           (0,))
        return out.reshape(nb * SUBS, LANES)

    blk = pl.BlockSpec((SUBS, LANES), lambda i: (i, jnp.int32(0)),
                       memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((nb,), lambda i: (jnp.int32(0),),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((nb,), lambda i: (jnp.int32(0),),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda i: (jnp.int32(0),),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec((1, SUBS, kpad, LANES),
                     lambda i: (i, jnp.int32(0), jnp.int32(0),
                                jnp.int32(0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, SUBS, kpad, LANES),
                     lambda i: (i, jnp.int32(0), jnp.int32(0),
                                jnp.int32(0)),
                     memory_space=pltpu.VMEM),
        blk,            # x block
        blk,            # b block
    ]
    operands = [c0row, nchunk, jnp.reshape(tau, (1,)).astype(jnp.float32),
                xp, cols4, vals4, rowpad(x), rowpad(b)]
    if has_dinv:
        in_specs.append(blk)
        operands.append(rowpad(dinv))
    kernel = _swell_smooth_kernel(w128, kpad, nb, has_dinv)
    y2 = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((nb * SUBS, LANES), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, w128, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * nb * SUBS * kpad * LANES,
            bytes_accessed=(2 * kpad + 5) * nb * SUBS * LANES * 4,
            transcendentals=0,
        ),
        # `interpret` resolved by the un-jitted wrapper below so the
        # flag rides the jit cache key (see _dia_smooth_call)
        interpret=interpret,
    )(*operands)
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    return y


def swell_smooth_step(A, b, x, tau, dinv=None, interpret=False):
    """One fused damped sweep x' = x + tau * dinv . (b - A x); caller
    must have checked swell_smooth_supported. The kernel computes in
    f32 (bf16 value slabs promote at the multiply); the result rounds
    back to the vector dtype so the cycle's state dtype is stable."""
    from .pallas_spmv import _FORCE_INTERPRET
    y = _swell_smooth_call(
        A.swell_cols, A.swell_vals, A.swell_c0row, A.swell_nchunk,
        x, b, dinv, tau, A.swell_w128, A.num_rows,
        dinv is not None, interpret=interpret or _FORCE_INTERPRET)
    return y.astype(x.dtype)


def swell_spmv_xla(A, x):
    """XLA gather form of the same layout (f64/CPU/batched fallback).
    Semantically identical to the kernel: absolute column = block window
    start + stored local column."""
    nb, _, kpad, _ = A.swell_vals.shape
    dtype = jnp.promote_types(A.swell_vals.dtype, x.dtype)
    ncols = A.num_cols
    xp_len = (-(-ncols // LANES) + A.swell_w128) * LANES
    xp = jnp.zeros((xp_len,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype), (0,))
    abscol = (A.swell_c0row.astype(jnp.int32) * LANES)[:, None, None, None] \
        + A.swell_cols
    y = (A.swell_vals.astype(dtype) * xp[abscol]).sum(axis=2).reshape(-1)
    if y.shape[0] != A.num_rows:
        y = y[: A.num_rows]
    return y
