"""Pallas TPU SpMV kernel for the DIA (banded stencil) layout.

The reference's SpMV fast path is a hand-tuned CUDA csrmv
(src/multiply.cu:74-121 and the CHANGELOG "fast path" entry). The TPU
equivalent is not a translation of that kernel: on TPU the roofline
layout for stencil matrices is DIA — y = sum_d vals_d * shift(x, d) —
because every stream is a dense sequential read (no gather hardware).
XLA alone materializes each partial sum in HBM, so a 7-diagonal SpMV
pays ~4x the minimum traffic. This kernel performs the whole reduction
in one fused pass:

- grid over row blocks of BLOCK_ROWS*128 elements, sequential on core;
- diagonal values arrive via an auto-pipelined (k, BR, 128) block;
- the x window (block + halo rows for every diagonal offset) is DMA'd
  from HBM into a manually double-buffered VMEM scratch, so the next
  block's halo loads while the current block computes;
- lane-crossing shifts (offset % 128 != 0) use the two-row roll+select
  trick: W[p, q] = a[p, q+r] for q < 128-r else b[p, q+r-128], where
  a/b are consecutive row views of the window — pure VPU work.

Traffic per output element for a k-diagonal matrix: k value floats +
~1 x float + 1 y float, i.e. the HBM minimum (plus a halo sliver).

The matrix stores dia_vals tile-aligned as (k, rows_pad, 128) — see
CsrMatrix._build_dia_vals — so the kernel reads values with zero
re-layout cost. float32 only (TPU has no native f64; the XLA spmv_dia
path covers f64/CPU).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
_VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom under ~16 MB/core

# Testing hook: the CPU test rig runs the Pallas kernels through the
# interpreter; flipping this (via force_pallas_interpret) makes the
# trace-time gates report "supported" off-TPU and routes every kernel
# call through interpret mode, so kernel-consuming code paths (spmv
# dispatch, fused smoothers, the cycle) are exercised end to end.
_FORCE_INTERPRET = False


import contextlib


@contextlib.contextmanager
def force_pallas_interpret():
    """Route the DIA Pallas kernels through the interpreter and make
    their support gates ignore the backend check (CPU test path)."""
    global _FORCE_INTERPRET
    prev = _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = prev


def pick_block_rows(k: int, rows128: int) -> int:
    """Rows (of 128 lanes) per grid block. Shared by matrix init (which
    pads dia_vals to a multiple of this) and the kernel wrapper, so the
    two always agree. Sized so the double-buffered values block fits
    VMEM comfortably."""
    budget_rows = _VMEM_BUDGET // (max(k, 1) * LANES * 4 * 2)
    br = 512
    while br > 8 and br > budget_rows:
        br //= 2
    if rows128 <= br:
        # single block: round the whole matrix up to a tile of 8 rows
        return max(8, -(-rows128 // 8) * 8)
    return br


def dia_padded_rows(k: int, n: int) -> int:
    """Padded row count (of 128 lanes) for the tiled dia_vals store."""
    rows128 = max(1, -(-n // LANES))
    br = pick_block_rows(k, rows128)
    return -(-rows128 // br) * br


def _dia_kernel(offsets, left, block_rows, halo_rows, n_blocks, dtype):
    """Build the kernel body. All layout numbers are static."""
    ro = [(left + o) // LANES for o in offsets]   # window row offset
    rl = [(left + o) % LANES for o in offsets]    # lane shift
    win_rows = block_rows + halo_rows

    def kernel(xp_ref, vals_ref, y_ref, xbuf, sems):
        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dma(s, blk):
            return pltpu.make_async_copy(
                xp_ref.at[pl.ds(jnp.int32(blk) * jnp.int32(block_rows),
                                win_rows)],
                xbuf.at[jnp.int32(s)], sems.at[jnp.int32(s)])

        @pl.when(i == 0)
        def _():
            dma(0, 0).start()

        @pl.when(i + 1 < n_blocks)
        def _():
            dma(jax.lax.rem(i + 1, jnp.int32(2)), i + 1).start()

        dma(slot, i).wait()

        col = jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANES), 1)
        acc = jnp.zeros((block_rows, LANES), dtype)
        xv = xbuf[slot]          # (win_rows, 128) view of this block's x
        for k, _ in enumerate(offsets):
            vk = vals_ref[k]
            if rl[k] == 0:
                w = jax.lax.slice_in_dim(xv, ro[k], ro[k] + block_rows, 1, 0)
            else:
                a = jax.lax.slice_in_dim(xv, ro[k], ro[k] + block_rows, 1, 0)
                b = jax.lax.slice_in_dim(xv, ro[k] + 1,
                                         ro[k] + 1 + block_rows, 1, 0)
                shift = LANES - rl[k]
                wa = pltpu.roll(a, jnp.int32(shift), 1)
                wb = pltpu.roll(b, jnp.int32(shift), 1)
                w = jnp.where(col < shift, wa, wb)
            acc = acc + vk * w
        y_ref[...] = acc

    return kernel


def _layout(offsets, k: int, num_rows: int):
    """Shared layout math: (left pad, halo rows, block rows). The gate
    and the kernel wrapper both call this so they can never diverge."""
    left = -(-max(0, -min(offsets)) // LANES) * LANES
    halo_rows = (left + max(max(offsets), 0)) // LANES + 1
    br = pick_block_rows(k, max(1, -(-num_rows // LANES)))
    return left, halo_rows, br


def dia_spmv_supported(A, x_dtype) -> bool:
    """Trace-time gate for the Pallas path."""
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    if A.dia_vals is None or A.dia_vals.dtype != jnp.float32 \
            or x_dtype != jnp.float32:
        return False
    if A.num_rows != A.num_cols:
        return False
    k, rows_pad, _ = A.dia_vals.shape
    left, halo_rows, br = _layout(A.dia_offsets, k, A.num_rows)
    if rows_pad % br != 0:
        return False
    # window scratch must fit alongside the values pipeline
    win_bytes = 2 * (br + halo_rows) * LANES * 4
    vals_bytes = 2 * k * br * LANES * 4
    return win_bytes + vals_bytes + 2 * br * LANES * 4 <= \
        _VMEM_BUDGET + 4 * 1024 * 1024


@functools.partial(jax.jit,
                   static_argnames=("offsets", "num_rows", "interpret"))
def _dia_spmv_call(dia_vals, x, offsets, num_rows, interpret=False):
    k, rows_pad, _ = dia_vals.shape
    dtype = dia_vals.dtype
    n = num_rows
    left, halo_rows, br = _layout(offsets, k, n)
    n_blocks = rows_pad // br
    xp_rows = rows_pad + halo_rows
    xp = jnp.zeros((xp_rows * LANES,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype), (left,))
    xp = xp.reshape(xp_rows, LANES)

    kernel = _dia_kernel(offsets, left, br, halo_rows, n_blocks, dtype)
    y2 = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (k, br, LANES),
                lambda i: (jnp.int32(0), i, jnp.int32(0)),
                memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, LANES),
                               lambda i: (i, jnp.int32(0)),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_pad, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, br + halo_rows, LANES), dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * k * rows_pad * LANES,
            bytes_accessed=(k + 2) * rows_pad * LANES * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(xp, dia_vals)
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    return y


def dia_spmv(A, x, interpret=False):
    """Fused DIA SpMV; caller must have checked dia_spmv_supported
    (`interpret=True` runs the Pallas interpreter — CPU test path)."""
    return _dia_spmv_call(A.dia_vals, x, A.dia_offsets, A.num_rows,
                          interpret=interpret or _FORCE_INTERPRET)


# ---------------------------------------------------------------------------
# Fused multi-sweep smoother (+ residual epilogue)
#
# The V-cycle's hot pair is presmooth -> residual: S damped sweeps
#   x_{s+1} = x_s + tau_s * dinv . (b - A x_s)
# (Jacobi/Jacobi-L1: tau_s = relaxation_factor, dinv = D^{-1};
#  CHEBYSHEV_POLY: tau_s = magic-damping taus, dinv absent) followed by
# r = b - A x_S. Unfused, that is S+1 HBM passes over A's diagonal slab
# plus an elementwise pass per sweep. This kernel runs all S sweeps AND
# the residual epilogue in ONE pallas_call via temporal blocking: each
# grid block loads a row window wide enough to compute all applications
# locally (redundant halo compute), so A's values stream from HBM once.
#
# Window math (rows of 128 lanes). Per application the data dependence
# grows mr0 rows downward and Mr0 rows upward (mr0 = ceil(max(0,-min d)
# / 128), Mr0 = max(0, max d)//128 + 1). With n_app applications
# (n_app = sweeps + 1 when the residual is fused):
#   win_v = br + (n_app-1)*(mr0+Mr0)    # vals/b/dinv window (compute rows)
#   win_x = win_v + mr0 + Mr0           # x window (read halo on top)
# The x state buffer lives in "window coordinates" (row j = x row
# i*br - n_app*mr0 + j); each application computes rows [mr0, mr0+win_v)
# of the next state and zero-fills the shrinking edges — the zeros land
# exactly on rows already invalidated by the dependence cone, so the
# final block rows [n_app*mr0, n_app*mr0+br) are exact.
#
# The values/b/dinv operands need (n_app-1)*mr0 front-halo rows, which
# the tile-aligned dia_vals store does not carry; callers pass PRE-PADDED
# operand slabs (built once per setup/resetup by ops.smooth and carried
# in the smoother's solve_data) so no per-cycle re-layout of A happens.
# ---------------------------------------------------------------------------

_SMOOTH_VMEM_BUDGET = 11 * 1024 * 1024
SMOOTH_MAX_APPS = 8          # sweeps + residual cap for one fused call
_BR_CAP = 2048               # largest candidate block size

# Fused-kernel operand-dtype whitelist. bf16 slabs stream at half the
# HBM bytes of f32 (the kernels are bandwidth-bound, so ~2x per sweep)
# and halve the VMEM the DMA windows occupy (bigger blocks fit under
# the budget — a second, compounding win); the kernels upcast each
# block in VMEM and accumulate every sweep + the trailing residual in
# f32, so only the OPERAND stream is narrow, never the arithmetic.
SMOOTH_DTYPES = ("float32", "bfloat16")


def compute_dtype(dtype):
    """In-kernel accumulation dtype for an operand stream: sub-f32
    operands (bf16) upcast per block and accumulate in f32; f32/f64
    pass through unchanged (identity casts fold away, keeping the f32
    jaxprs bit-identical to the pre-mixed-precision build)."""
    return jnp.float32 if jnp.dtype(dtype).itemsize < 4 else \
        jnp.dtype(dtype)


def smooth_dtype_ok(A, x_dtype) -> bool:
    """Operand-dtype gate shared by every fused-smoother-suite entry:
    the matrix slab dtype and the vector dtype must agree and sit on
    the kernel whitelist. Callers that find a fused payload but fail
    THIS gate count `fusion.declined_dtype` (ops/smooth.py) so a
    config that falls off the fused path is visible, not silent."""
    if getattr(A, "dia_vals", None) is None:
        return False
    dt = jnp.dtype(A.dia_vals.dtype)
    return dt == jnp.dtype(x_dtype) and dt.name in SMOOTH_DTYPES


def smooth_halo_rows(offsets):
    """(mr0, Mr0): per-application dependence growth in 128-lane rows."""
    m = max(0, -min(offsets))
    M = max(0, max(offsets))
    return -(-m // LANES), M // LANES + 1


def smooth_br_candidates(num_rows: int):
    """Candidate block sizes shared by the plan functions AND the
    transfer-slab builder (which precomputes per-block coarse window
    bases for every br the plans could pick — the two lists must never
    diverge or a planned br would have no window metadata)."""
    rows128 = max(1, -(-num_rows // LANES))
    single = max(8, -(-rows128 // 8) * 8)
    cands = [c for c in (_BR_CAP, 1536, 1024, 768, 512, 384, 256, 192,
                         128, 96, 64, 32, 16, 8) if c < single]
    return ([single] if single <= _BR_CAP else []) + cands


def smooth_quota_rows(offsets, num_rows: int):
    """(front, content, back) rows of the quota-padded operand slabs
    (values / dinv) the fused kernel DMAs windows from. The quota is
    sized for ANY plan up to SMOOTH_MAX_APPS applications and _BR_CAP
    block rows, so ONE padded slab per matrix (built at setup by
    ops.smooth) serves every sweep count the cycle asks for — the
    sweep count is only known at trace time, after the solve-data
    pytree is already fixed."""
    mr0, Mr0 = smooth_halo_rows(offsets)
    rows128 = max(1, -(-num_rows // LANES))
    content = max(8, -(-rows128 // 8) * 8)
    front = (SMOOTH_MAX_APPS - 1) * mr0
    # block rounding never exceeds one block (every candidate block
    # size is <= min(content, _BR_CAP)), so the back quota stays
    # proportional to the matrix instead of a fixed _BR_CAP slab that
    # would double tiny coarse levels
    back = (SMOOTH_MAX_APPS - 1) * Mr0 + min(content, _BR_CAP)
    return front, content, back


# ---------------------------------------------------------------------------
# Matrix-free (coeffs) mode: constant-coefficient stencil levels pass a
# static `mf` spec (a namedtuple with fields offsets/shifts/shape/n/
# dinv/diag_rank — ops.stencil.StencilSpec) instead of the quota-padded
# vals/dinv slabs. The kernels synthesize each diagonal's masked value
# rows in-register from k SMEM scalars: a row's entry for grid shift
# (dx,dy,dz) is coeffs[t] where the shifted point stays inside the
# (nx,ny,nz) grid and the row itself is a real matrix row, else 0 —
# exactly the slab the matrix build would have materialized, so the
# compute below the value fetch is shared, unchanged, and bit-equal.
# ---------------------------------------------------------------------------

# rows-of-f32 working-set charge per win_v row the plans budget for the
# coeffs mode's in-register coordinates and masks (idx + 3 grid coords
# + mask temporaries, ~6 int32/bool planes)
_MF_WORK_ROWS = 6


def _mf_coords(shape, idx):
    """(gx, gy, gz) grid coordinates of linear element indices
    (x fastest). Truncating div/rem: negative indices (front-halo pad
    rows) produce garbage coordinates that the caller's row-valid mask
    kills."""
    nx, ny, _nz = shape
    gx = jax.lax.rem(idx, jnp.int32(nx))
    t1 = jax.lax.div(idx, jnp.int32(nx))
    gy = jax.lax.rem(t1, jnp.int32(ny))
    gz = jax.lax.div(t1, jnp.int32(ny))
    return gx, gy, gz


def _mf_ok(shape, coords, shift, base):
    """`base` AND the in-grid mask of one stencil shift — static
    bounds, so axes the shift does not cross cost nothing."""
    nx, ny, nz = shape
    dx, dy, dz = shift
    gx, gy, gz = coords
    ok = base
    if dx < 0:
        ok = ok & (gx >= -dx)
    if dx > 0:
        ok = ok & (gx < nx - dx)
    if dy < 0:
        ok = ok & (gy >= -dy)
    if dy > 0:
        ok = ok & (gy < ny - dy)
    if dz < 0:
        ok = ok & (gz >= -dz)
    if dz > 0:
        ok = ok & (gz < nz - dz)
    return ok


def _mf_vals_dinv(mf, cget, coords, valid, cdt):
    """(val(t), dinv rows | None) synthesized from coefficient scalars.
    `cget(t)` reads diagonal t's scalar at `cdt` (SMEM ref or array);
    `valid` is the row-valid mask of the window. val(t) reproduces the
    slab row (coefficient on in-grid rows, 0 on halo/off-grid rows);
    the dinv rows reproduce safe_recip of the plain ("jacobi") or
    L1-strengthened ("l1") diagonal the smoother would have shipped."""

    def val(t):
        ok = _mf_ok(mf.shape, coords, mf.shifts[t], valid)
        return jnp.where(ok, cget(t), jnp.zeros((), cdt))

    if mf.dinv is None:
        return val, None
    c0 = cget(mf.diag_rank)
    if mf.dinv == "jacobi":
        den = jnp.where(valid, c0, jnp.zeros((), cdt))
    else:                           # "l1": diag + sign(diag)*sum|off|
        l1 = jnp.zeros(valid.shape, cdt)
        for t in range(len(mf.shifts)):
            if t == mf.diag_rank:
                continue
            ok = _mf_ok(mf.shape, coords, mf.shifts[t], valid)
            l1 = l1 + jnp.where(ok, jnp.abs(cget(t)),
                                jnp.zeros((), cdt))
        den = jnp.where(valid, c0 + jnp.sign(c0) * l1,
                        jnp.zeros((), cdt))
    dw = jnp.where(den == 0, jnp.zeros((), cdt),
                   1 / jnp.where(den == 0, jnp.ones((), cdt), den))
    return val, dw


def _mf_block_vals(mf, coeffs_ref, row0, win_v, col, cdt):
    """Coeffs-mode replacement for a block kernel's vals/dinv VMEM
    windows: masked value rows + dinv rows for the compute region whose
    first row is x row `row0` (traced). Coordinates are computed once
    per block; each diagonal's mask is a handful of VPU compares."""
    row = jax.lax.broadcasted_iota(jnp.int32, (win_v, LANES), 0)
    idx = (row0 + row) * jnp.int32(LANES) + col
    coords = _mf_coords(mf.shape, idx)
    valid = (idx >= 0) & (idx < jnp.int32(mf.n))
    return _mf_vals_dinv(mf, lambda t: coeffs_ref[t].astype(cdt),
                         coords, valid, cdt)


def dia_smooth_plan(offsets, k: int, num_rows: int, n_steps: int,
                    with_residual: bool, itemsize: int = 4,
                    coeffs: bool = False):
    """Block plan for the fused smoother or None when it does not pay.

    Returns (br, n_app, mr0, Mr0, win_x, win_v, n_blocks). The block
    size is the largest that fits the double-buffered windows in the
    VMEM budget; the plan is rejected when the halo recompute would
    cost more HBM traffic than the unfused n_app passes it replaces
    (callers then chain shorter fused calls instead). `itemsize` is
    the operand-slab byte width: bf16 slabs (2) halve the DMA-window
    footprint so larger blocks fit, at the cost of the f32 upcast
    working set the budget accounts below. `coeffs` plans the
    matrix-free form: the values/dinv slabs (the k-stream that
    dominates both the HBM traffic and the VMEM budget) vanish — the
    kernel synthesizes masked value rows in-register from k SMEM
    scalars, paying only a coordinate/mask working set — so the
    halved traffic model admits larger blocks and the guard almost
    never rejects."""
    if not offsets:
        return None
    n_app = int(n_steps) + (1 if with_residual else 0)
    if n_app < 1 or n_app > SMOOTH_MAX_APPS:
        return None
    ib = int(itemsize)
    mr0, Mr0 = smooth_halo_rows(offsets)
    H = mr0 + Mr0
    rows128 = max(1, -(-num_rows // LANES))
    for br in smooth_br_candidates(num_rows):
        win_v = br + (n_app - 1) * H
        win_x = win_v + H
        n_out = 2 if with_residual else 1
        if coeffs:
            vmem = (2 * (win_v + win_x)  # b/x windows, 2 slots
                    + 2 * n_out * br     # pipelined output blocks
                    ) * LANES * ib \
                + _MF_WORK_ROWS * win_v * LANES * 4   # coord/mask set
        else:
            vmem = (2 * k * win_v        # values, double-buffered
                    + 2 * (2 * win_v + win_x)  # b/dinv/x windows
                    + 2 * n_out * br     # pipelined output blocks
                    ) * LANES * ib
        if ib < 4:
            # sub-f32 operands: the f32 state + per-application upcast
            # temporaries ride on top of the narrow DMA buffers
            vmem += (win_x + 3 * win_v) * LANES * 4
        if vmem > _SMOOTH_VMEM_BUDGET:
            continue
        # traffic guard: the fused windows must undercut the n_app
        # separate passes (matrix-free: A contributes no stream on
        # either side, so only the b/x/y vectors count)
        if coeffs:
            fused = 2 * win_v + win_x
            unfused = n_app * 4 * br
        else:
            fused = (k + 2) * win_v + win_x
            unfused = n_app * (k + 3) * br
        if n_app > 1 and fused >= 0.9 * unfused:
            return None     # halo dominates; caller chains smaller calls
        n_blocks = -(-rows128 // br)
        return br, n_app, mr0, Mr0, win_x, win_v, n_blocks
    return None


def dia_smooth_supported(A, x_dtype, n_steps: int,
                         with_residual: bool) -> bool:
    """Trace-time gate for the fused smoother Pallas path."""
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    if not smooth_dtype_ok(A, x_dtype):
        return False
    if A.num_rows != A.num_cols or A.has_external_diag:
        return False
    k = A.dia_vals.shape[0]
    return dia_smooth_plan(A.dia_offsets, k, A.num_rows, n_steps,
                           with_residual,
                           itemsize=jnp.dtype(x_dtype).itemsize) \
        is not None


def _dia_smooth_kernel(offsets, br, n_app, mr0, Mr0, win_x, win_v,
                       n_steps, with_residual, has_dinv, n_blocks,
                       slab_shift, dtype, mf=None, with_dot=False):
    """Kernel body factory. Buffer coordinates: state row j = x row
    i*br - n_app*mr0 + j; vals/b/dinv compute-region row j' = x row
    i*br - (n_app-1)*mr0 + j' (so an application's output row j'
    aligns with operand-window row j' directly). `slab_shift` is the
    static extra front padding of the quota-padded vals/dinv slabs
    beyond this plan's (n_app-1)*mr0 need. Sub-f32 operand dtypes
    (bf16) stream/DMA narrow and upcast per block in VMEM; the state
    and every accumulation run in `cdt` (f32+), and only the final
    stores round back to the operand dtype. `mf` (matrix-free): no
    vals/dinv operands or windows — value and dinv rows synthesize
    in-register from k SMEM coefficient scalars (_mf_block_vals);
    `has_dinv` must be False (the dinv, if any, comes from mf.dinv)."""
    ro = [mr0 + (o - (o % LANES)) // LANES for o in offsets]
    rl = [o % LANES for o in offsets]
    cdt = compute_dtype(dtype)

    def kernel(*refs):
        # refs: xp, vals_q, bp, [dinv_q], taus, out_x, [out_r],
        #       xbuf, vbuf, bbuf, [dbuf], sems
        # mf:   xp, bp, coeffs, taus, out_x, [out_r], xbuf, bbuf, sems
        if mf is None:
            xp_ref, vals_ref, bp_ref = refs[0], refs[1], refs[2]
            dinv_ref = refs[3] if has_dinv else None
            coeffs_ref = None
            taus_ref = refs[3 + (1 if has_dinv else 0)]
            off = 4 + (1 if has_dinv else 0)
            y_ref = refs[off]
            r_ref = refs[off + 1] if with_residual else None
            off += 2 if with_residual else 1
            d_ref = refs[off] if with_dot else None
            off += 1 if with_dot else 0
            xbuf, vbuf, bbuf = refs[off], refs[off + 1], refs[off + 2]
            dbuf = refs[off + 3] if has_dinv else None
            sems = refs[off + 3 + (1 if has_dinv else 0)]
        else:
            xp_ref, bp_ref = refs[0], refs[1]
            vals_ref = dinv_ref = None
            coeffs_ref, taus_ref = refs[2], refs[3]
            y_ref = refs[4]
            r_ref = refs[5] if with_residual else None
            off = 6 if with_residual else 5
            d_ref = refs[off] if with_dot else None
            off += 1 if with_dot else 0
            xbuf, bbuf = refs[off], refs[off + 1]
            vbuf = dbuf = None
            sems = refs[off + 2]

        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dmas(s, blk):
            base = jnp.int32(blk) * jnp.int32(br)
            qbase = base + jnp.int32(slab_shift)
            ops = [
                pltpu.make_async_copy(xp_ref.at[pl.ds(base, win_x)],
                                      xbuf.at[jnp.int32(s)],
                                      sems.at[jnp.int32(s), 0]),
            ]
            if mf is None:
                ops.append(pltpu.make_async_copy(
                    vals_ref.at[:, pl.ds(qbase, win_v)],
                    vbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 1]))
            ops.append(pltpu.make_async_copy(
                bp_ref.at[pl.ds(base, win_v)], bbuf.at[jnp.int32(s)],
                sems.at[jnp.int32(s), 1 if mf is not None else 2]))
            if has_dinv:
                ops.append(pltpu.make_async_copy(
                    dinv_ref.at[pl.ds(qbase, win_v)],
                    dbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 3]))
            return ops

        @pl.when(i == 0)
        def _():
            for d in dmas(0, 0):
                d.start()

        @pl.when(i + 1 < n_blocks)
        def _():
            for d in dmas(jax.lax.rem(i + 1, jnp.int32(2)), i + 1):
                d.start()

        for d in dmas(slot, i):
            d.wait()

        col = jax.lax.broadcasted_iota(jnp.int32, (win_v, LANES), 1)
        bw = bbuf[slot].astype(cdt)     # (win_v, 128)
        if mf is None:
            vals = vbuf[slot]           # (k, win_v, 128) operand dtype
            def val(t):
                return vals[t].astype(cdt)
            dw = dbuf[slot].astype(cdt) if has_dinv else None
        else:
            row0 = i * jnp.int32(br) - jnp.int32((n_app - 1) * mr0)
            val, dw = _mf_block_vals(mf, coeffs_ref, row0, win_v, col,
                                     cdt)

        def apply_A(s):
            """A @ state on the compute region (win_v rows)."""
            acc = jnp.zeros((win_v, LANES), cdt)
            for t, _ in enumerate(offsets):
                a = jax.lax.slice_in_dim(s, ro[t], ro[t] + win_v, 1, 0)
                if rl[t] == 0:
                    w = a
                else:
                    b2 = jax.lax.slice_in_dim(s, ro[t] + 1,
                                              ro[t] + 1 + win_v, 1, 0)
                    shift = LANES - rl[t]
                    wa = pltpu.roll(a, jnp.int32(shift), 1)
                    wb = pltpu.roll(b2, jnp.int32(shift), 1)
                    w = jnp.where(col < shift, wa, wb)
                acc = acc + val(t) * w
            return acc

        s = xbuf[slot].astype(cdt)      # (win_x, 128) state, f32+
        for t in range(n_steps):
            tau = taus_ref[t]
            mid = jax.lax.slice_in_dim(s, mr0, mr0 + win_v, 1, 0)
            corr = tau * (bw - apply_A(s))
            if dw is not None:
                corr = corr * dw
            pieces = [mid + corr, jnp.zeros((Mr0, LANES), cdt)]
            if mr0:
                pieces.insert(0, jnp.zeros((mr0, LANES), cdt))
            s = jnp.concatenate(pieces, axis=0)
        y_ref[...] = jax.lax.slice_in_dim(
            s, n_app * mr0, n_app * mr0 + br, 1, 0).astype(dtype)
        if with_residual:
            r = bw - apply_A(s)
            r_ref[...] = jax.lax.slice_in_dim(
                r, (n_app - 1) * mr0, (n_app - 1) * mr0 + br, 1, 0
            ).astype(dtype)
        if with_dot:
            # dot epilogue: the block's final-x rows against the
            # aligned b rows (x row i*br+t <-> b-window row
            # (n_app-1)*mr0+t) — lanes stay unreduced; the caller's
            # cheap XLA combine sums the (nb, 128) partials
            xb = jax.lax.slice_in_dim(
                s, n_app * mr0, n_app * mr0 + br, 1, 0)
            bb = jax.lax.slice_in_dim(
                bw, (n_app - 1) * mr0, (n_app - 1) * mr0 + br, 1, 0)
            d_ref[...] = jnp.sum(xb * bb, axis=0,
                                 keepdims=True).astype(jnp.float32)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "offsets", "num_rows", "with_residual", "mf", "with_dot",
    "interpret"))
def _dia_smooth_call(vals_q, dinv_q, taus, b, x, offsets, num_rows,
                     with_residual, mf=None, coeffs=None,
                     with_dot=False, interpret=False):
    """Run the fused smoother kernel. `vals_q` (k, Q, 128) and `dinv_q`
    ((Q, 128) or None) are the QUOTA-PADDED operand slabs from
    ops.smooth (built once per setup, smooth_quota_rows layout); b and
    x are padded in-trace (the same cost the plain SpMV kernel already
    pays for x). Caller must have checked dia_smooth_supported.
    Matrix-free form (`mf` spec + `coeffs` (k,)): vals_q/dinv_q are
    None — the A-operand stream vanishes and the k coefficients ride
    SMEM next to the taus. `with_dot` (postsmoother-only, exclusive
    with with_residual) appends the x'.b dot epilogue and returns
    (x', dot) — the Krylov shell's cycle-borne r.z reduction."""
    assert not (with_dot and with_residual)
    n_steps = taus.shape[0]
    has_dinv = dinv_q is not None
    if mf is None:
        k = vals_q.shape[0]
        dtype = vals_q.dtype
    else:
        k = len(offsets)
        dtype = x.dtype
    ib = jnp.dtype(dtype).itemsize
    plan = dia_smooth_plan(offsets, k, num_rows, n_steps, with_residual,
                           itemsize=ib, coeffs=mf is not None)
    br, n_app, mr0, Mr0, win_x, win_v, nb = plan
    if mf is None:
        qf, qc, qb = smooth_quota_rows(offsets, num_rows)
        assert vals_q.shape[1] == qf + qc + qb, \
            f"fused slab rows {vals_q.shape[1]} != quota {qf + qc + qb}"
        # quota slab row qf == x row 0; this plan's window base (block
        # i) is x row i*br - (n_app-1)*mr0, i.e. slab row i*br +
        # slab_shift
        slab_shift = qf - (n_app - 1) * mr0
    else:
        slab_shift = 0
    n = num_rows
    # x window coordinates: front pad n_app*mr0 rows
    xp_rows = n_app * mr0 + nb * br + n_app * Mr0
    xp = jnp.zeros((xp_rows * LANES,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype),
                                      (n_app * mr0 * LANES,))
    xp = xp.reshape(xp_rows, LANES)
    front_v = (n_app - 1) * mr0
    rows_v = front_v + nb * br + (n_app - 1) * Mr0
    bp = jnp.zeros((rows_v * LANES,), dtype)
    bp = jax.lax.dynamic_update_slice(bp, b.astype(dtype),
                                      (front_v * LANES,))
    bp = bp.reshape(rows_v, LANES)

    kernel = _dia_smooth_kernel(offsets, br, n_app, mr0, Mr0, win_x,
                                win_v, n_steps, with_residual, has_dinv,
                                nb, slab_shift, dtype, mf=mf,
                                with_dot=with_dot)
    if mf is None:
        n_sem = 4 if has_dinv else 3
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # vals_q
            pl.BlockSpec(memory_space=pl.ANY),          # bp
        ]
        operands = [xp, vals_q, bp]
        if has_dinv:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            operands.append(dinv_q)
    else:
        n_sem = 2
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # bp
            pl.BlockSpec((k,), lambda i: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),      # coeffs
        ]
        # coefficients ride SMEM at the accumulation dtype (like taus)
        operands = [xp, bp, coeffs.astype(compute_dtype(dtype))]
    in_specs.append(pl.BlockSpec((n_steps,), lambda i: (jnp.int32(0),),
                                 memory_space=pltpu.SMEM))
    # taus stay at the ACCUMULATION dtype: a bf16-rounded damping
    # factor would throw away Chebyshev coefficient precision the f32
    # arithmetic can keep (identity for f32/f64 operands)
    operands.append(taus.astype(compute_dtype(dtype)))
    out_block = pl.BlockSpec((br, LANES), lambda i: (i, jnp.int32(0)),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((nb * br, LANES), dtype)
    scratch = [pltpu.VMEM((2, win_x, LANES), dtype)]
    if mf is None:
        scratch.append(pltpu.VMEM((2, k, win_v, LANES), dtype))
    scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    if has_dinv:
        scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2, n_sem)))
    n_out = 2 if with_residual else 1
    nbytes = ((k + 2) * win_v + win_x + n_out * br) if mf is None \
        else (2 * win_v + win_x + n_out * br)
    out_specs_t = tuple([out_block] * n_out)
    out_shape_t = tuple([out_shape] * n_out)
    if with_dot:
        out_specs_t = out_specs_t + (pl.BlockSpec(
            (1, LANES), lambda i: (i, jnp.int32(0)),
            memory_space=pltpu.VMEM),)
        out_shape_t = out_shape_t + (jax.ShapeDtypeStruct(
            (nb, LANES), jnp.float32),)
    multi_out = with_residual or with_dot
    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs_t if multi_out else out_block,
        out_shape=out_shape_t if multi_out else out_shape,
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_app * k * nb * br * LANES,
            bytes_accessed=nbytes * nb * LANES * ib,
            transcendentals=0,
        ),
        # NOTE: `interpret` must be resolved by the (un-jitted) caller —
        # reading the _FORCE_INTERPRET global here would bake it into a
        # trace whose jit cache key does not carry it, so an interpret-
        # mode trace could outlive the forcing context
        interpret=interpret,
    )(*operands)
    outs = out if multi_out else (out,)
    vec_outs = outs[:-1] if with_dot else outs
    trimmed = []
    for o in vec_outs:
        v = o.reshape(-1)
        trimmed.append(v[:n] if v.shape[0] != n else v)
    if with_dot:
        trimmed.append(jnp.sum(outs[-1]))
    return tuple(trimmed) if multi_out else trimmed[0]


def _dia_stencil_smooth_call(coeffs, taus, b, x, spec, with_residual,
                             with_dot=False, interpret=False):
    """Matrix-free fused smoother: the dia_smooth kernel with the
    quota-padded vals/dinv slabs replaced by k SMEM scalars. `spec` is
    the level's StencilSpec (ops.stencil); caller must have checked
    stencil_smooth_supported."""
    return _dia_smooth_call(None, None, taus, b, x, spec.offsets,
                            spec.n, with_residual, mf=spec,
                            coeffs=coeffs, with_dot=with_dot,
                            interpret=interpret)


# ---------------------------------------------------------------------------
# Cycle fusion: grid-transfer epilogues + VMEM-resident coarse tail
#
# After the fused smoother removed the standalone residual pass (above),
# the remaining solve-phase HBM traffic of an aggregation level is the
# grid-transfer chain: restrict reads the residual the smoother just
# wrote, and prolongate+correction makes one more full-vector pass
# before the post-smoother reads x again. Both fold into the smoother
# kernels:
#
# - RESTRICTION EPILOGUE (`_dia_smooth_restrict_call`): the presmooth
#   kernel already holds r in VMEM — instead of writing it to HBM, each
#   grid block emits the partial segment-sums of its OWN fine rows into
#   the (static) coarse row window the block touches, gathered through a
#   precomputed child-index slab (ctab[j][c] = fine slot of aggregate
#   c's j-th child, -1 when absent). Aggregates straddling a block
#   boundary complete in the cheap XLA combine that adds the per-block
#   windows into the coarse rhs — each fine slot belongs to exactly one
#   block, so the partials sum exactly. r never round-trips HBM and
#   `level.restrict` disappears from the cycle.
#
# - PROLONGATION PROLOGUE (`_dia_prolong_smooth_call`): the postsmooth
#   kernel's first application folds x + P xc in: each block DMAs the
#   coarse window its x-window references (per-block base from an SMEM
#   table) and gathers xc through the aggregate-id slab (atab[slot] =
#   coarse id, -1 at padding) before the first sweep — the correction
#   add's full-vector pass disappears.
#
# - COARSE TAIL (`_dia_coarse_tail_call`): when every level >= k fits
#   the VMEM budget simultaneously (the dispatch-latency-bound tiny
#   levels), the whole sub-cycle — smooth, restrict, ..., coarsest
#   solve (dense inverse matmul), ..., prolongate, smooth — runs as ONE
#   grid=(1,) kernel with every intermediate vector VMEM-resident.
#   `_tail_compute` is the single source of truth: the Pallas kernel
#   body and the XLA fallback (f64 / vmapped batches, ops/batched.py)
#   both call it.
#
# The child/aggregate index slabs are STRUCTURE-only (built once per
# (re)setup from the aggregates map by ops.smooth.build_transfer_slabs;
# value-only resetups keep them). In-kernel gathers use precomputed
# indices only — no data-dependent addressing.
# ---------------------------------------------------------------------------

TRANSFER_MAX_CHILD = 16     # largest aggregate the epilogue fuses
# weighted (general-CSR) transfer slabs: classical interpolation rows
# are short (interp_max_elements-truncated) but a coarse point's
# R-row — the set of fine points it interpolates — runs longer than
# any aggregate, so the weighted child table gets its own cap (the
# plans still arbitrate the real VMEM/traffic cost per block size)
CSR_TRANSFER_MAX_CHILD = 32


def coarse_pad_rows(nc: int) -> int:
    """Padded 128-lane row count of kernel-side coarse vectors."""
    return max(1, -(-nc // LANES))


def transfer_quota_rows(offsets, num_rows: int):
    """(front, content, back) rows of the quota-padded aggregate-id
    slab (atab): sized like smooth_quota_rows but one application
    deeper in front (the prolongation prologue covers the x window,
    which reaches n_app*mr0 rows before the block)."""
    mr0, Mr0 = smooth_halo_rows(offsets)
    rows128 = max(1, -(-num_rows // LANES))
    content = max(8, -(-rows128 // 8) * 8)
    front = SMOOTH_MAX_APPS * mr0
    back = SMOOTH_MAX_APPS * Mr0 + min(content, _BR_CAP)
    return front, content, back


@jax.tree_util.register_pytree_node_class
class TransferSlabs:
    """Setup-built transfer payloads of one aggregation OR classical
    level.

    Children (device arrays): `ctab` (m, ncr, 128) int32 child-index
    slab (restriction: fine slot of coarse row c's j-th source entry,
    -1 absent); `atab` (quota rows, 128) int32 aggregate-id slab
    (aggregation prolongation: ONE unit-weight coarse id per fine
    slot); `bases` {br: (cb, pcb)} per-candidate-block-size int32
    coarse window bases (restriction / prolongation). General-CSR
    (classical interpolation) levels add the WEIGHTED row-segment
    slabs: `cwt` (m, ncr, 128) restriction weights aligned with ctab,
    and `ptab`/`pwt` (mp, quota rows, 128) — the j-th (coarse id,
    weight) entry of P's row per fine slot, replacing atab. Static
    aux: `nc` coarse rows, `ncr` padded coarse 128-lane rows, `m` max
    restriction row length, `windows` ((br, cw, pcw), ...) — the
    static coarse-window row counts the plan functions check VMEM
    against — `mp` max prolongation row length, and `wavg`/`pavg`
    (ceil average R/P row lengths: the plans' honest unfused-traffic
    term for the weighted forms)."""

    def __init__(self, ctab, atab, bases, nc, ncr, m, windows,
                 cwt=None, ptab=None, pwt=None, mp=1, wavg=None,
                 pavg=None):
        self.ctab = ctab
        self.atab = atab
        self.bases = bases
        self.nc = nc
        self.ncr = ncr
        self.m = m
        self.windows = windows
        self.cwt = cwt
        self.ptab = ptab
        self.pwt = pwt
        self.mp = mp
        self.wavg = m if wavg is None else wavg
        self.pavg = mp if pavg is None else pavg

    def tree_flatten(self):
        return ((self.ctab, self.atab, self.bases, self.cwt,
                 self.ptab, self.pwt),
                (self.nc, self.ncr, self.m, self.windows, self.mp,
                 self.wavg, self.pavg))

    @classmethod
    def tree_unflatten(cls, aux, children):
        nc, ncr, m, windows, mp, wavg, pavg = aux
        return cls(children[0], children[1], children[2], nc, ncr, m,
                   windows, cwt=children[3], ptab=children[4],
                   pwt=children[5], mp=mp, wavg=wavg, pavg=pavg)


def dia_restrict_plan(offsets, k: int, num_rows: int, n_steps: int,
                      m: int, windows, weighted: bool = False,
                      wavg=None, itemsize: int = 4,
                      coeffs: bool = False):
    """Block plan for the smoother+restriction-epilogue kernel, or
    None. Mirrors dia_smooth_plan(with_residual=True) plus the epilogue
    buffers: m double-buffered child-index windows (and, `weighted`,
    the matching weight windows of the general-CSR form) and the
    pipelined partial-coarse output block. `wavg` (weighted only) is
    the ceil-average R row length — the honest per-window cost of the
    unfused SWELL restriction the fusion replaces. `itemsize` is the
    operand byte width (value/vector/weight streams; the index tables
    are always int32)."""
    cap = CSR_TRANSFER_MAX_CHILD if weighted else TRANSFER_MAX_CHILD
    if not offsets or m < 1 or m > cap:
        return None
    n_app = int(n_steps) + 1
    if n_steps < 1 or n_app > SMOOTH_MAX_APPS:
        return None
    ib = int(itemsize)
    wavg = m if wavg is None else wavg
    tabs = 2 if weighted else 1          # index (+ weight) tables
    wmap = {w[0]: w[1] for w in windows}
    mr0, Mr0 = smooth_halo_rows(offsets)
    H = mr0 + Mr0
    rows128 = max(1, -(-num_rows // LANES))
    for br in smooth_br_candidates(num_rows):
        if br not in wmap:
            continue
        cw = wmap[br]
        win_v = br + (n_app - 1) * H
        win_x = win_v + H
        if coeffs:
            vmem = (2 * (win_v + win_x) + 2 * br + 2 * cw) * LANES \
                * ib + 2 * m * cw * LANES * 4 \
                + _MF_WORK_ROWS * win_v * LANES * 4
        else:
            vmem = (2 * k * win_v + 2 * (2 * win_v + win_x)
                    + 2 * br             # x output pipeline
                    + 2 * cw             # partial-coarse output pipeline
                    ) * LANES * ib \
                + 2 * m * cw * LANES * 4   # child-index windows (int32)
        if weighted:
            vmem += 2 * m * cw * LANES * ib   # weight windows
        if ib < 4:
            # f32 state + upcast temporaries + f32 partial sums
            vmem += (win_x + 3 * win_v + cw) * LANES * 4
        if vmem > _SMOOTH_VMEM_BUDGET:
            continue
        # traffic guard vs the unfused compose: n_app passes over A
        # plus the standalone restrict pass (r write + r read + bc
        # write ~ 3*br + cw; weighted: + the R vals/cols stream the
        # unfused SWELL SpMV would read, ~ 2*wavg*cw)
        if coeffs:
            fused = 2 * win_v + win_x + (m + 1) * cw
            unfused = n_app * 4 * br + 3 * br + cw
        else:
            fused = (k + 2) * win_v + win_x + (tabs * m + 1) * cw
            unfused = n_app * (k + 3) * br + 3 * br + cw \
                + (2 * wavg * cw if weighted else 0)
        if n_app > 1 and fused >= 0.95 * unfused:
            continue
        n_blocks = -(-rows128 // br)
        return br, n_app, mr0, Mr0, win_x, win_v, n_blocks, cw
    return None


def dia_prolong_plan(offsets, k: int, num_rows: int, n_steps: int,
                     windows, mp: int = 1, weighted: bool = False,
                     pavg=None, itemsize: int = 4,
                     coeffs: bool = False):
    """Block plan for the prolongation-prologue+smoother kernel, or
    None. with_residual is never true here (the correction folds into
    the POST-smoother); the prologue adds the aggregate-id window (or,
    general CSR, mp index+weight window pairs) and the coarse-vector
    window to the budget. `itemsize` is the operand byte width (the
    id tables stay int32)."""
    if not offsets or mp < 1 or mp > TRANSFER_MAX_CHILD:
        return None
    n_app = int(n_steps)
    if n_app < 1 or n_app > SMOOTH_MAX_APPS:
        return None
    ib = int(itemsize)
    pavg = mp if pavg is None else pavg
    tabs = 2 if weighted else 1
    wmap = {w[0]: w[2] for w in windows}
    mr0, Mr0 = smooth_halo_rows(offsets)
    H = mr0 + Mr0
    rows128 = max(1, -(-num_rows // LANES))
    for br in smooth_br_candidates(num_rows):
        if br not in wmap:
            continue
        pcw = wmap[br]
        win_v = br + (n_app - 1) * H
        win_x = win_v + H
        if coeffs:
            vmem = (2 * (win_v + win_x) + 2 * br + 2 * pcw) * LANES \
                * ib + 2 * win_x * LANES * 4 \
                + _MF_WORK_ROWS * win_v * LANES * 4
        else:
            vmem = (2 * k * win_v + 2 * (2 * win_v + win_x)
                    + 2 * br             # x output pipeline
                    + 2 * pcw            # coarse-vector windows
                    ) * LANES * ib \
                + 2 * mp * win_x * LANES * 4      # id windows (int32)
        if weighted:
            vmem += 2 * mp * win_x * LANES * ib   # weight windows
        if ib < 4:
            vmem += (win_x + 3 * win_v + pcw) * LANES * 4
        if vmem > _SMOOTH_VMEM_BUDGET:
            continue
        # guard vs unfused: n_app passes plus the correction pass
        # (x read + xc read + x write ~ 2*br + pcw; weighted: + the P
        # vals/cols stream of the unfused SWELL prolongation)
        if coeffs:
            fused = 2 * win_v + win_x + win_x + pcw
            unfused = n_app * 4 * br + 2 * br + pcw
        else:
            fused = (k + 2) * win_v + win_x + tabs * mp * win_x + pcw
            unfused = n_app * (k + 3) * br + 2 * br + pcw \
                + (2 * pavg * br if weighted else 0)
        if fused >= 0.95 * unfused and n_app > 1:
            continue
        n_blocks = -(-rows128 // br)
        return br, n_app, mr0, Mr0, win_x, win_v, n_blocks, pcw
    return None


def _transfer_gate(A, x_dtype) -> bool:
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    if not smooth_dtype_ok(A, x_dtype):
        return False
    return A.num_rows == A.num_cols and not A.has_external_diag


def dia_restrict_supported(A, x_dtype, n_steps: int, xfer) -> bool:
    if xfer is None or not _transfer_gate(A, x_dtype):
        return False
    k = A.dia_vals.shape[0]
    return dia_restrict_plan(A.dia_offsets, k, A.num_rows, n_steps,
                             xfer.m, xfer.windows,
                             weighted=xfer.cwt is not None,
                             wavg=xfer.wavg,
                             itemsize=jnp.dtype(x_dtype).itemsize) \
        is not None


def dia_prolong_supported(A, x_dtype, n_steps: int, xfer) -> bool:
    if xfer is None or not _transfer_gate(A, x_dtype):
        return False
    k = A.dia_vals.shape[0]
    return dia_prolong_plan(A.dia_offsets, k, A.num_rows, n_steps,
                            xfer.windows, mp=xfer.mp,
                            weighted=xfer.ptab is not None,
                            pavg=xfer.pavg,
                            itemsize=jnp.dtype(x_dtype).itemsize) \
        is not None


def _dia_smooth_restrict_kernel(offsets, br, n_app, mr0, Mr0, win_x,
                                win_v, n_steps, has_dinv, n_blocks,
                                slab_shift, m, cw, has_w, dtype,
                                mf=None):
    """Kernel body factory: the dia_smooth body (window coordinates
    documented on _dia_smooth_kernel) with the residual epilogue
    replaced by per-block partial coarse segment-sums — r is gathered
    through the child-index window into the block's coarse rows and
    never written to HBM. `has_w` (general-CSR / classical form)
    gathers a weight window next to each child-index window and the
    partial sums become weighted: bc[c] = sum_j w[j][c] * r[ct[j][c]]
    (the aggregation form is the unit-weight special case). Sub-f32
    operands upcast per block and every partial sum accumulates in
    `cdt` (f32+) — see _dia_smooth_kernel."""
    ro = [mr0 + (o - (o % LANES)) // LANES for o in offsets]
    rl = [o % LANES for o in offsets]
    cdt = compute_dtype(dtype)

    def kernel(*refs):
        # refs: xp, vals_q, bp, [dinv_q], ctab, [cwt], cb, taus,
        #       out_x, out_bc, xbuf, vbuf, bbuf, [dbuf], cbuf, [wbuf],
        #       sems
        # mf:   xp, bp, ctab, coeffs, cb, taus, out_x, out_bc,
        #       xbuf, bbuf, cbuf, sems
        if mf is None:
            xp_ref, vals_ref, bp_ref = refs[0], refs[1], refs[2]
            coeffs_ref = None
            off = 3
            dinv_ref = refs[off] if has_dinv else None
            off += 1 if has_dinv else 0
            ctab_ref = refs[off]
            off += 1
            cwt_ref = refs[off] if has_w else None
            off += 1 if has_w else 0
            cb_ref, taus_ref = refs[off], refs[off + 1]
            off += 2
            y_ref, bc_ref = refs[off], refs[off + 1]
            off += 2
            xbuf, vbuf, bbuf = refs[off], refs[off + 1], refs[off + 2]
            off += 3
            dbuf = refs[off] if has_dinv else None
            off += 1 if has_dinv else 0
            cbuf = refs[off]
            off += 1
            wbuf = refs[off] if has_w else None
            off += 1 if has_w else 0
            sems = refs[off]
        else:
            xp_ref, bp_ref, ctab_ref = refs[0], refs[1], refs[2]
            vals_ref = dinv_ref = cwt_ref = None
            coeffs_ref, cb_ref, taus_ref = refs[3], refs[4], refs[5]
            y_ref, bc_ref = refs[6], refs[7]
            xbuf, bbuf, cbuf = refs[8], refs[9], refs[10]
            vbuf = dbuf = wbuf = None
            sems = refs[11]

        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dmas(s, blk):
            base = jnp.int32(blk) * jnp.int32(br)
            qbase = base + jnp.int32(slab_shift)
            ops = [
                pltpu.make_async_copy(xp_ref.at[pl.ds(base, win_x)],
                                      xbuf.at[jnp.int32(s)],
                                      sems.at[jnp.int32(s), 0]),
            ]
            if mf is None:
                ops.append(pltpu.make_async_copy(
                    vals_ref.at[:, pl.ds(qbase, win_v)],
                    vbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 1]))
            ops.append(pltpu.make_async_copy(
                bp_ref.at[pl.ds(base, win_v)], bbuf.at[jnp.int32(s)],
                sems.at[jnp.int32(s), 1 if mf is not None else 2]))
            nsem = 2 if mf is not None else 3
            if has_dinv:
                ops.append(pltpu.make_async_copy(
                    dinv_ref.at[pl.ds(qbase, win_v)],
                    dbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), nsem]))
                nsem += 1
            cbv = cb_ref[blk]
            for j in range(m):
                ops.append(pltpu.make_async_copy(
                    ctab_ref.at[j, pl.ds(cbv, cw)],
                    cbuf.at[jnp.int32(s), j],
                    sems.at[jnp.int32(s), nsem + j]))
            if has_w:
                for j in range(m):
                    ops.append(pltpu.make_async_copy(
                        cwt_ref.at[j, pl.ds(cbv, cw)],
                        wbuf.at[jnp.int32(s), j],
                        sems.at[jnp.int32(s), nsem + m + j]))
            return ops

        @pl.when(i == 0)
        def _():
            for d in dmas(0, 0):
                d.start()

        @pl.when(i + 1 < n_blocks)
        def _():
            for d in dmas(jax.lax.rem(i + 1, jnp.int32(2)), i + 1):
                d.start()

        for d in dmas(slot, i):
            d.wait()

        col = jax.lax.broadcasted_iota(jnp.int32, (win_v, LANES), 1)
        bw = bbuf[slot].astype(cdt)
        if mf is None:
            vals = vbuf[slot]
            def val(t):
                return vals[t].astype(cdt)
            dw = dbuf[slot].astype(cdt) if has_dinv else None
        else:
            row0 = i * jnp.int32(br) - jnp.int32((n_app - 1) * mr0)
            val, dw = _mf_block_vals(mf, coeffs_ref, row0, win_v, col,
                                     cdt)

        def apply_A(s):
            acc = jnp.zeros((win_v, LANES), cdt)
            for t, _ in enumerate(offsets):
                a = jax.lax.slice_in_dim(s, ro[t], ro[t] + win_v, 1, 0)
                if rl[t] == 0:
                    w = a
                else:
                    b2 = jax.lax.slice_in_dim(s, ro[t] + 1,
                                              ro[t] + 1 + win_v, 1, 0)
                    shift = LANES - rl[t]
                    wa = pltpu.roll(a, jnp.int32(shift), 1)
                    wb = pltpu.roll(b2, jnp.int32(shift), 1)
                    w = jnp.where(col < shift, wa, wb)
                acc = acc + val(t) * w
            return acc

        s = xbuf[slot].astype(cdt)
        for t in range(n_steps):
            tau = taus_ref[t]
            mid = jax.lax.slice_in_dim(s, mr0, mr0 + win_v, 1, 0)
            corr = tau * (bw - apply_A(s))
            if dw is not None:
                corr = corr * dw
            pieces = [mid + corr, jnp.zeros((Mr0, LANES), cdt)]
            if mr0:
                pieces.insert(0, jnp.zeros((mr0, LANES), cdt))
            s = jnp.concatenate(pieces, axis=0)
        y_ref[...] = jax.lax.slice_in_dim(
            s, n_app * mr0, n_app * mr0 + br, 1, 0).astype(dtype)
        r = bw - apply_A(s)
        rblk = jax.lax.slice_in_dim(
            r, (n_app - 1) * mr0, (n_app - 1) * mr0 + br, 1, 0)
        rflat = rblk.reshape(br * LANES)
        base = i * jnp.int32(br * LANES)
        part = jnp.zeros((cw, LANES), cdt)
        for j in range(m):
            idxj = cbuf[slot, j]                       # (cw, 128) int32
            rel = idxj - base
            valid = (idxj >= 0) & (rel >= 0) & (rel < br * LANES)
            g = jnp.take(rflat, jnp.where(valid, rel, 0))
            if has_w:
                g = g * wbuf[slot, j].astype(cdt)
            part = part + jnp.where(valid, g, jnp.zeros((), cdt))
        bc_ref[...] = part.astype(dtype)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "offsets", "num_rows", "mf", "interpret"))
def _dia_smooth_restrict_call(vals_q, dinv_q, taus, b, x, xfer,
                              offsets, num_rows, mf=None, coeffs=None,
                              interpret=False):
    """Fused presmoother + restriction epilogue: (x', bc) after
    len(taus) damped sweeps, with bc the segment-summed coarse rhs of
    the trailing residual. Caller must have checked
    dia_restrict_supported. Matrix-free form (`mf` + `coeffs`): no
    vals/dinv slabs; the child-index windows (structure-only) stay."""
    n_steps = taus.shape[0]
    has_dinv = dinv_q is not None
    has_w = xfer.cwt is not None
    if mf is None:
        k = vals_q.shape[0]
        dtype = vals_q.dtype
    else:
        k = len(offsets)
        dtype = x.dtype
    ib = jnp.dtype(dtype).itemsize
    plan = dia_restrict_plan(offsets, k, num_rows, n_steps, xfer.m,
                             xfer.windows, weighted=has_w,
                             wavg=xfer.wavg, itemsize=ib,
                             coeffs=mf is not None)
    br, n_app, mr0, Mr0, win_x, win_v, nb, cw = plan
    if mf is None:
        qf, qc, qb = smooth_quota_rows(offsets, num_rows)
        assert vals_q.shape[1] == qf + qc + qb
        slab_shift = qf - (n_app - 1) * mr0
    else:
        slab_shift = 0
    n = num_rows
    cb = xfer.bases[br][0]
    xp_rows = n_app * mr0 + nb * br + n_app * Mr0
    xp = jnp.zeros((xp_rows * LANES,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype),
                                      (n_app * mr0 * LANES,))
    xp = xp.reshape(xp_rows, LANES)
    front_v = (n_app - 1) * mr0
    rows_v = front_v + nb * br + (n_app - 1) * Mr0
    bp = jnp.zeros((rows_v * LANES,), dtype)
    bp = jax.lax.dynamic_update_slice(bp, b.astype(dtype),
                                      (front_v * LANES,))
    bp = bp.reshape(rows_v, LANES)

    kernel = _dia_smooth_restrict_kernel(
        offsets, br, n_app, mr0, Mr0, win_x, win_v, n_steps, has_dinv,
        nb, slab_shift, xfer.m, cw, has_w, dtype, mf=mf)
    if mf is None:
        n_sem = (4 if has_dinv else 3) + xfer.m * (2 if has_w else 1)
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # vals_q
            pl.BlockSpec(memory_space=pl.ANY),          # bp
        ]
        operands = [xp, vals_q, bp]
        if has_dinv:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            operands.append(dinv_q)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))   # ctab
        operands.append(xfer.ctab)
        if has_w:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))   # cwt
            operands.append(xfer.cwt.astype(dtype))
    else:
        n_sem = 2 + xfer.m
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # bp
            pl.BlockSpec(memory_space=pl.ANY),          # ctab
            pl.BlockSpec((k,), lambda i: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),      # coeffs
        ]
        operands = [xp, bp, xfer.ctab,
                    coeffs.astype(compute_dtype(dtype))]
    in_specs.append(pl.BlockSpec((nb,), lambda i: (jnp.int32(0),),
                                 memory_space=pltpu.SMEM))
    operands.append(cb.astype(jnp.int32))
    in_specs.append(pl.BlockSpec((n_steps,), lambda i: (jnp.int32(0),),
                                 memory_space=pltpu.SMEM))
    operands.append(taus.astype(compute_dtype(dtype)))
    out_specs = (
        pl.BlockSpec((br, LANES), lambda i: (i, jnp.int32(0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((cw, LANES), lambda i: (i, jnp.int32(0)),
                     memory_space=pltpu.VMEM),
    )
    out_shape = (
        jax.ShapeDtypeStruct((nb * br, LANES), dtype),
        jax.ShapeDtypeStruct((nb * cw, LANES), dtype),
    )
    scratch = [pltpu.VMEM((2, win_x, LANES), dtype)]
    if mf is None:
        scratch.append(pltpu.VMEM((2, k, win_v, LANES), dtype))
    scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    if has_dinv:
        scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    scratch.append(pltpu.VMEM((2, xfer.m, cw, LANES), jnp.int32))
    if has_w:
        scratch.append(pltpu.VMEM((2, xfer.m, cw, LANES), dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2, n_sem)))
    nbytes = ((k + 2) * win_v + win_x
              + (xfer.m * (2 if has_w else 1) + 1) * cw + br) \
        if mf is None else (2 * win_v + win_x + (xfer.m + 1) * cw + br)
    y2, parts = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_app * k * nb * br * LANES,
            bytes_accessed=nbytes * nb * LANES * ib,
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    # combine: add each block's partial coarse window at its base row —
    # every fine slot lives in exactly one block, so aggregates that
    # straddle block windows complete here
    if nb == 1 and cw == xfer.ncr:
        bc = parts.reshape(-1)[:xfer.nc]
        return y, bc
    flat = parts.reshape(nb, cw * LANES)
    bcp = jnp.zeros((xfer.ncr * LANES,), dtype)
    for i in range(nb):
        start = cb[i].astype(jnp.int32) * LANES
        cur = jax.lax.dynamic_slice(bcp, (start,), (cw * LANES,))
        bcp = jax.lax.dynamic_update_slice(bcp, cur + flat[i], (start,))
    return y, bcp[:xfer.nc]


def _dia_stencil_smooth_restrict_call(coeffs, taus, b, x, xfer, spec,
                                      interpret=False):
    """Matrix-free fused presmoother + restriction epilogue. Caller
    must have checked stencil_restrict_supported."""
    return _dia_smooth_restrict_call(None, None, taus, b, x, xfer,
                                     spec.offsets, spec.n, mf=spec,
                                     coeffs=coeffs, interpret=interpret)


def _dia_prolong_smooth_kernel(offsets, br, n_app, mr0, Mr0, win_x,
                               win_v, n_steps, has_dinv, n_blocks,
                               slab_shift, ashift, pcw, mp, has_w,
                               dtype, mf=None, with_dot=False):
    """Kernel body factory: the dia_smooth body with a prologue that
    folds the coarse correction in — the state window becomes
    x + P xc (gather of the block's coarse window through the
    aggregate-id window) BEFORE the first sweep, so the correction
    add's full-vector HBM pass disappears. `ashift` is the static
    offset of the x-window base inside the quota-padded atab/ptab
    slab. The general-CSR (classical) form — `has_w` — gathers mp
    (coarse id, weight) window pairs per fine slot and accumulates
    x += sum_j w[j] * xc[id[j]]; the aggregation form (mp=1, no
    weights, 2-D atab) is unchanged. Sub-f32 operands upcast per
    block; state/accumulation in `cdt` (f32+) — see
    _dia_smooth_kernel."""
    ro = [mr0 + (o - (o % LANES)) // LANES for o in offsets]
    rl = [o % LANES for o in offsets]
    cdt = compute_dtype(dtype)

    def kernel(*refs):
        # refs: xp, vals_q, bp, [dinv_q], xcp, atab|ptab, [pwt], pcb,
        #       taus, out_x, xbuf, vbuf, bbuf, [dbuf], xcbuf, abuf,
        #       [wbuf], sems
        # mf:   xp, bp, xcp, atab, coeffs, pcb, taus, out_x,
        #       xbuf, bbuf, xcbuf, abuf, sems
        if mf is None:
            xp_ref, vals_ref, bp_ref = refs[0], refs[1], refs[2]
            coeffs_ref = None
            off = 3
            dinv_ref = refs[off] if has_dinv else None
            off += 1 if has_dinv else 0
            xcp_ref, atab_ref = refs[off], refs[off + 1]
            off += 2
            pwt_ref = refs[off] if has_w else None
            off += 1 if has_w else 0
            pcb_ref, taus_ref = refs[off], refs[off + 1]
            off += 2
            y_ref = refs[off]
            off += 1
            d_ref = refs[off] if with_dot else None
            off += 1 if with_dot else 0
            xbuf, vbuf, bbuf = refs[off], refs[off + 1], refs[off + 2]
            off += 3
            dbuf = refs[off] if has_dinv else None
            off += 1 if has_dinv else 0
            xcbuf, abuf = refs[off], refs[off + 1]
            off += 2
            wbuf = refs[off] if has_w else None
            off += 1 if has_w else 0
            sems = refs[off]
        else:
            xp_ref, bp_ref = refs[0], refs[1]
            vals_ref = dinv_ref = pwt_ref = None
            xcp_ref, atab_ref = refs[2], refs[3]
            coeffs_ref, pcb_ref, taus_ref = refs[4], refs[5], refs[6]
            y_ref = refs[7]
            off = 8
            d_ref = refs[off] if with_dot else None
            off += 1 if with_dot else 0
            xbuf, bbuf = refs[off], refs[off + 1]
            vbuf = dbuf = wbuf = None
            xcbuf, abuf = refs[off + 2], refs[off + 3]
            sems = refs[off + 4]

        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dmas(s, blk):
            base = jnp.int32(blk) * jnp.int32(br)
            qbase = base + jnp.int32(slab_shift)
            abase = base + jnp.int32(ashift)
            ops = [
                pltpu.make_async_copy(xp_ref.at[pl.ds(base, win_x)],
                                      xbuf.at[jnp.int32(s)],
                                      sems.at[jnp.int32(s), 0]),
            ]
            if mf is None:
                ops.append(pltpu.make_async_copy(
                    vals_ref.at[:, pl.ds(qbase, win_v)],
                    vbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 1]))
            ops.append(pltpu.make_async_copy(
                bp_ref.at[pl.ds(base, win_v)], bbuf.at[jnp.int32(s)],
                sems.at[jnp.int32(s), 1 if mf is not None else 2]))
            nsem = 2 if mf is not None else 3
            if has_dinv:
                ops.append(pltpu.make_async_copy(
                    dinv_ref.at[pl.ds(qbase, win_v)],
                    dbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), nsem]))
                nsem += 1
            ops.append(pltpu.make_async_copy(
                xcp_ref.at[pl.ds(pcb_ref[blk], pcw)],
                xcbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), nsem]))
            nsem += 1
            if has_w:
                for j in range(mp):
                    ops.append(pltpu.make_async_copy(
                        atab_ref.at[j, pl.ds(abase, win_x)],
                        abuf.at[jnp.int32(s), j],
                        sems.at[jnp.int32(s), nsem + j]))
                    ops.append(pltpu.make_async_copy(
                        pwt_ref.at[j, pl.ds(abase, win_x)],
                        wbuf.at[jnp.int32(s), j],
                        sems.at[jnp.int32(s), nsem + mp + j]))
            else:
                ops.append(pltpu.make_async_copy(
                    atab_ref.at[pl.ds(abase, win_x)],
                    abuf.at[jnp.int32(s)], sems.at[jnp.int32(s), nsem]))
            return ops

        @pl.when(i == 0)
        def _():
            for d in dmas(0, 0):
                d.start()

        @pl.when(i + 1 < n_blocks)
        def _():
            for d in dmas(jax.lax.rem(i + 1, jnp.int32(2)), i + 1):
                d.start()

        for d in dmas(slot, i):
            d.wait()

        col = jax.lax.broadcasted_iota(jnp.int32, (win_v, LANES), 1)
        bw = bbuf[slot].astype(cdt)
        if mf is None:
            vals = vbuf[slot]
            def val(t):
                return vals[t].astype(cdt)
            dw = dbuf[slot].astype(cdt) if has_dinv else None
        else:
            row0 = i * jnp.int32(br) - jnp.int32((n_app - 1) * mr0)
            val, dw = _mf_block_vals(mf, coeffs_ref, row0, win_v, col,
                                     cdt)

        def apply_A(s):
            acc = jnp.zeros((win_v, LANES), cdt)
            for t, _ in enumerate(offsets):
                a = jax.lax.slice_in_dim(s, ro[t], ro[t] + win_v, 1, 0)
                if rl[t] == 0:
                    w = a
                else:
                    b2 = jax.lax.slice_in_dim(s, ro[t] + 1,
                                              ro[t] + 1 + win_v, 1, 0)
                    shift = LANES - rl[t]
                    wa = pltpu.roll(a, jnp.int32(shift), 1)
                    wb = pltpu.roll(b2, jnp.int32(shift), 1)
                    w = jnp.where(col < shift, wa, wb)
                acc = acc + val(t) * w
            return acc

        # prologue: s = x + P xc over the WHOLE x window (the sweeps
        # consume halo rows, which need the corrected state too)
        s = xbuf[slot].astype(cdt)
        xcw = xcbuf[slot].reshape(pcw * LANES).astype(cdt)
        if has_w:
            for j in range(mp):
                aw = abuf[slot, j]                     # (win_x, 128)
                rel = aw - pcb_ref[i] * jnp.int32(LANES)
                valid = (aw >= 0) & (rel >= 0) & (rel < pcw * LANES)
                g = jnp.take(xcw, jnp.where(valid, rel, 0))
                g = g * wbuf[slot, j].astype(cdt)
                s = s + jnp.where(valid, g, jnp.zeros((), cdt))
        else:
            aw = abuf[slot]                            # (win_x, 128)
            rel = aw - pcb_ref[i] * jnp.int32(LANES)
            valid = (aw >= 0) & (rel >= 0) & (rel < pcw * LANES)
            corr0 = jnp.take(xcw, jnp.where(valid, rel, 0))
            s = s + jnp.where(valid, corr0, jnp.zeros((), cdt))
        for t in range(n_steps):
            tau = taus_ref[t]
            mid = jax.lax.slice_in_dim(s, mr0, mr0 + win_v, 1, 0)
            corr = tau * (bw - apply_A(s))
            if dw is not None:
                corr = corr * dw
            pieces = [mid + corr, jnp.zeros((Mr0, LANES), cdt)]
            if mr0:
                pieces.insert(0, jnp.zeros((mr0, LANES), cdt))
            s = jnp.concatenate(pieces, axis=0)
        y_ref[...] = jax.lax.slice_in_dim(
            s, n_app * mr0, n_app * mr0 + br, 1, 0).astype(dtype)
        if with_dot:
            # cycle-borne reduction: the postsmoothed x' against the
            # aligned b rows — per-block (1, 128) partials, lanes
            # combined by the caller's XLA sum
            xb = jax.lax.slice_in_dim(
                s, n_app * mr0, n_app * mr0 + br, 1, 0)
            bb = jax.lax.slice_in_dim(
                bw, (n_app - 1) * mr0, (n_app - 1) * mr0 + br, 1, 0)
            d_ref[...] = jnp.sum(xb * bb, axis=0,
                                 keepdims=True).astype(jnp.float32)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "offsets", "num_rows", "mf", "with_dot", "interpret"))
def _dia_prolong_smooth_call(vals_q, dinv_q, taus, b, x, xc, xfer,
                             offsets, num_rows, mf=None, coeffs=None,
                             with_dot=False, interpret=False):
    """Fused prolongation/correction prologue + postsmoother:
    x' = smooth(b, x + P xc) after len(taus) damped sweeps. Caller
    must have checked dia_prolong_supported. Matrix-free form (`mf` +
    `coeffs`): no vals/dinv slabs; the aggregate-id windows
    (structure-only) stay."""
    n_steps = taus.shape[0]
    has_dinv = dinv_q is not None
    has_w = xfer.ptab is not None
    if mf is None:
        k = vals_q.shape[0]
        dtype = vals_q.dtype
    else:
        k = len(offsets)
        dtype = x.dtype
    ib = jnp.dtype(dtype).itemsize
    plan = dia_prolong_plan(offsets, k, num_rows, n_steps, xfer.windows,
                            mp=xfer.mp, weighted=has_w, pavg=xfer.pavg,
                            itemsize=ib, coeffs=mf is not None)
    br, n_app, mr0, Mr0, win_x, win_v, nb, pcw = plan
    if mf is None:
        qf, qc, qb = smooth_quota_rows(offsets, num_rows)
        assert vals_q.shape[1] == qf + qc + qb
        slab_shift = qf - (n_app - 1) * mr0
    else:
        slab_shift = 0
    aqf, aqc, aqb = transfer_quota_rows(offsets, num_rows)
    id_slab = xfer.ptab if has_w else xfer.atab
    assert id_slab.shape[1 if has_w else 0] == aqf + aqc + aqb
    ashift = aqf - n_app * mr0
    n = num_rows
    pcb = xfer.bases[br][1]
    xp_rows = n_app * mr0 + nb * br + n_app * Mr0
    xp = jnp.zeros((xp_rows * LANES,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype),
                                      (n_app * mr0 * LANES,))
    xp = xp.reshape(xp_rows, LANES)
    front_v = (n_app - 1) * mr0
    rows_v = front_v + nb * br + (n_app - 1) * Mr0
    bp = jnp.zeros((rows_v * LANES,), dtype)
    bp = jax.lax.dynamic_update_slice(bp, b.astype(dtype),
                                      (front_v * LANES,))
    bp = bp.reshape(rows_v, LANES)
    xcp = jnp.zeros((xfer.ncr * LANES,), dtype)
    xcp = jax.lax.dynamic_update_slice(xcp, xc.astype(dtype), (0,))
    xcp = xcp.reshape(xfer.ncr, LANES)

    kernel = _dia_prolong_smooth_kernel(
        offsets, br, n_app, mr0, Mr0, win_x, win_v, n_steps, has_dinv,
        nb, slab_shift, ashift, pcw, xfer.mp, has_w, dtype, mf=mf,
        with_dot=with_dot)
    if mf is None:
        n_sem = (4 if has_dinv else 3) + 1 \
            + (2 * xfer.mp if has_w else 1)
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # vals_q
            pl.BlockSpec(memory_space=pl.ANY),          # bp
        ]
        operands = [xp, vals_q, bp]
        if has_dinv:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            operands.append(dinv_q)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))   # xcp
        operands.append(xcp)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))   # atab|ptab
        operands.append(id_slab)
        if has_w:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))   # pwt
            operands.append(xfer.pwt.astype(dtype))
    else:
        n_sem = 4
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),          # xp
            pl.BlockSpec(memory_space=pl.ANY),          # bp
            pl.BlockSpec(memory_space=pl.ANY),          # xcp
            pl.BlockSpec(memory_space=pl.ANY),          # atab
            pl.BlockSpec((k,), lambda i: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),      # coeffs
        ]
        operands = [xp, bp, xcp, id_slab,
                    coeffs.astype(compute_dtype(dtype))]
    in_specs.append(pl.BlockSpec((nb,), lambda i: (jnp.int32(0),),
                                 memory_space=pltpu.SMEM))
    operands.append(pcb.astype(jnp.int32))
    in_specs.append(pl.BlockSpec((n_steps,), lambda i: (jnp.int32(0),),
                                 memory_space=pltpu.SMEM))
    operands.append(taus.astype(compute_dtype(dtype)))
    out_specs = pl.BlockSpec((br, LANES), lambda i: (i, jnp.int32(0)),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((nb * br, LANES), dtype)
    if with_dot:
        out_specs = (out_specs, pl.BlockSpec(
            (1, LANES), lambda i: (i, jnp.int32(0)),
            memory_space=pltpu.VMEM))
        out_shape = (out_shape, jax.ShapeDtypeStruct((nb, LANES),
                                                     jnp.float32))
    scratch = [pltpu.VMEM((2, win_x, LANES), dtype)]
    if mf is None:
        scratch.append(pltpu.VMEM((2, k, win_v, LANES), dtype))
    scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    if has_dinv:
        scratch.append(pltpu.VMEM((2, win_v, LANES), dtype))
    scratch.append(pltpu.VMEM((2, pcw, LANES), dtype))
    if has_w:
        scratch.append(pltpu.VMEM((2, xfer.mp, win_x, LANES),
                                  jnp.int32))
        scratch.append(pltpu.VMEM((2, xfer.mp, win_x, LANES), dtype))
    else:
        scratch.append(pltpu.VMEM((2, win_x, LANES), jnp.int32))
    scratch.append(pltpu.SemaphoreType.DMA((2, n_sem)))
    nbytes = ((k + 2) * win_v + win_x + pcw + br
              + (2 * xfer.mp if has_w else 1) * win_x) if mf is None \
        else (2 * win_v + win_x + pcw + br + win_x)
    y2 = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_app * k * nb * br * LANES,
            bytes_accessed=nbytes * nb * LANES * ib,
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)
    if with_dot:
        y2, dparts = y2
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    if with_dot:
        return y, jnp.sum(dparts)
    return y


def _dia_stencil_prolong_smooth_call(coeffs, taus, b, x, xc, xfer,
                                     spec, with_dot=False,
                                     interpret=False):
    """Matrix-free fused prolongation prologue + postsmoother. Caller
    must have checked stencil_prolong_supported."""
    return _dia_prolong_smooth_call(None, None, taus, b, x, xc, xfer,
                                    spec.offsets, spec.n, mf=spec,
                                    coeffs=coeffs, with_dot=with_dot,
                                    interpret=interpret)


# ---------------------------------------------------------------------------
# VMEM-resident coarse-tail sub-cycle
# ---------------------------------------------------------------------------

import collections

# `mf` (default None) marks a matrix-free level: its arrs dict carries
# a (k,) "coeffs" leaf instead of the "vals"/"dinv" slab slices, and
# the per-offset value/dinv rows synthesize from the StencilSpec in
# _tail_compute — shared by the Pallas tail kernel and the XLA
# fallback exactly like the slab form.
TailLevelSpec = collections.namedtuple(
    "TailLevelSpec",
    "offsets n qc has_dinv n_pre n_post nc ncr m mf",
    defaults=(None,))
TailSpec = collections.namedtuple("TailSpec", "shape levels coarse")
# coarse: ("inv", nz, ncrz) — dense inverse matmul; ("none", nz, ncrz)
# — NOSOLVER (no coarse correction)


def _rows_to(v, rows: int):
    """Row-pad / row-trim a (r, 128) vector to `rows` 128-lane rows —
    the lane packing (linear index, x fastest) is shared by every
    level's vector layout, so converting between a level's coarse-rhs
    rows and the next level's content rows is pure row arithmetic."""
    r = v.shape[0]
    if rows == r:
        return v
    if rows > r:
        return jnp.pad(v, ((0, rows - r), (0, 0)))
    return jax.lax.slice_in_dim(v, 0, rows, 1, 0)


def _tail_compute(arrs, b, x, spec):
    """The whole coarse-tail sub-cycle on (rows, 128) VMEM-resident
    values: per level — presmooth sweeps, residual, child-gather
    restriction, recursion (V/W/F shape), aggregate-gather prolongation
    + correction, postsmooth sweeps; dense-inverse matmul (or nothing,
    NOSOLVER) at the coarsest. SINGLE SOURCE OF TRUTH: the Pallas
    kernel body runs this on loaded refs and the XLA fallback
    (ops/batched.py tail_cycle_multi, the f64 / vmapped route) runs it
    on plain arrays — they cannot drift apart. Sub-f32 vectors/slabs
    (bf16) upcast at entry/use and the WHOLE sub-cycle accumulates in
    f32 (the coarse inverse stays f32 by the precision policy); the
    caller rounds the returned state back to its vector dtype."""
    levels = spec.levels
    cdt = compute_dtype(b.dtype)
    b = b.astype(cdt)
    x = x.astype(cdt)

    def level_vals(ls, ar):
        """(val(t), dinv | None): slab levels slice their VMEM-loaded
        quota slabs; matrix-free levels synthesize both from the (k,)
        coefficient leaf and ls.mf's static masks (tail vectors start
        at element 0, so idx = row*128 + lane directly)."""
        if ls.mf is None:
            dw = ar["dinv"].astype(cdt) if ls.has_dinv else None
            return (lambda t: ar["vals"][t].astype(cdt)), dw
        col = jax.lax.broadcasted_iota(jnp.int32, (ls.qc, LANES), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (ls.qc, LANES), 0)
        idx = row * jnp.int32(LANES) + col
        coords = _mf_coords(ls.mf.shape, idx)
        valid = idx < jnp.int32(ls.mf.n)
        return _mf_vals_dinv(ls.mf,
                             lambda t: ar["coeffs"][t].astype(cdt),
                             coords, valid, cdt)

    def apply_dia(ls, val, s):
        mr0, Mr0 = smooth_halo_rows(ls.offsets)
        sp = jnp.pad(s, ((mr0, Mr0), (0, 0)))
        col = jax.lax.broadcasted_iota(jnp.int32, (ls.qc, LANES), 1)
        acc = jnp.zeros((ls.qc, LANES), cdt)
        for t, o in enumerate(ls.offsets):
            ro = mr0 + (o - (o % LANES)) // LANES
            a = jax.lax.slice_in_dim(sp, ro, ro + ls.qc, 1, 0)
            rl = o % LANES
            if rl == 0:
                w = a
            else:
                b2 = jax.lax.slice_in_dim(sp, ro + 1, ro + 1 + ls.qc,
                                          1, 0)
                shift = LANES - rl
                w = jnp.where(col < shift, jnp.roll(a, shift, 1),
                              jnp.roll(b2, shift, 1))
            acc = acc + val(t) * w
        return acc

    def sweeps(ls, val, dw, bc, s, taus, n_taus):
        for t in range(n_taus):
            corr = taus[t].astype(cdt) * (bc - apply_dia(ls, val, s))
            if dw is not None:
                corr = corr * dw
            s = s + corr
        return s

    def run(shape, i, bc, s):
        ls, ar = levels[i], arrs[i]
        val, dw = level_vals(ls, ar)
        s = sweeps(ls, val, dw, bc, s, ar["taus_pre"], ls.n_pre)
        r = bc - apply_dia(ls, val, s)
        rflat = r.reshape(-1)
        coarse_b = jnp.zeros((ls.ncr, LANES), cdt)
        for j in range(ls.m):
            idxj = ar["ctab"][j]
            valid = idxj >= 0
            g = jnp.take(rflat, jnp.where(valid, idxj, 0))
            coarse_b = coarse_b + jnp.where(valid, g,
                                            jnp.zeros((), cdt))
        if i + 1 < len(levels):
            bq = _rows_to(coarse_b, levels[i + 1].qc)
            xc = run(shape, i + 1, bq, jnp.zeros_like(bq))
            if shape == "W":
                xc = run("W", i + 1, bq, xc)
            elif shape == "F":
                xc = run("V", i + 1, bq, xc)
            xc = _rows_to(xc, ls.ncr)
        else:
            kind, nz, ncrz = spec.coarse
            bz = _rows_to(coarse_b, ncrz)
            if kind == "inv":
                F = ncrz * LANES
                xcf = jnp.dot(bz.reshape(1, F),
                              arrs[-1]["invT"].astype(cdt),
                              preferred_element_type=cdt)
                xc = _rows_to(xcf.reshape(ncrz, LANES), ls.ncr)
            else:               # NOSOLVER: no coarse correction
                xc = jnp.zeros((ls.ncr, LANES), cdt)
        xcflat = xc.reshape(-1)
        aw = ar["atab_c"]
        valid = aw >= 0
        corr = jnp.take(xcflat, jnp.where(valid, aw, 0))
        s = s + jnp.where(valid, corr, jnp.zeros((), cdt))
        s = sweeps(ls, val, dw, bc, s, ar["taus_post"], ls.n_post)
        return s

    return run(spec.shape, 0, b, x)


def _dia_tail_kernel(spec, treedef, n_leaves, dtype, with_dot=False):
    def kernel(*refs):
        arrs = jax.tree_util.tree_unflatten(
            treedef, [r[...] for r in refs[:n_leaves]])
        b, x = refs[n_leaves][...], refs[n_leaves + 1][...]
        out = _tail_compute(arrs, b, x, spec)
        refs[n_leaves + 2][...] = out.astype(dtype)
        if with_dot:
            # everything is VMEM-resident, so the x'.b reduction over
            # rows is free; lanes combine in the caller's XLA sum
            refs[n_leaves + 3][...] = jnp.sum(
                out * b.astype(out.dtype), axis=0,
                keepdims=True).astype(jnp.float32)
    return kernel


@functools.partial(jax.jit, static_argnames=("spec", "with_dot",
                                             "interpret"))
def _dia_coarse_tail_call(arrs, b, x, spec, with_dot=False,
                          interpret=False):
    """One grid=(1,) pallas_call running the whole coarse-tail
    sub-cycle with every intermediate vector VMEM-resident — ~10 tiny
    kernel dispatches per cycle become one. Caller (ops.smooth
    coarse_tail_plan) has checked eligibility and the VMEM budget."""
    l0 = spec.levels[0]
    dtype = b.dtype
    b2 = jnp.zeros((l0.qc * LANES,), dtype)
    b2 = jax.lax.dynamic_update_slice(b2, b, (0,)).reshape(l0.qc, LANES)
    x2 = jnp.zeros((l0.qc * LANES,), dtype)
    x2 = jax.lax.dynamic_update_slice(x2, x, (0,)).reshape(l0.qc, LANES)
    leaves, treedef = jax.tree_util.tree_flatten(arrs)
    kernel = _dia_tail_kernel(spec, treedef, len(leaves), dtype,
                              with_dot=with_dot)

    def _spec_of(v):
        nd = len(v.shape)
        return pl.BlockSpec(v.shape, lambda i, _nd=nd: (jnp.int32(0),)
                            * _nd, memory_space=pltpu.VMEM)

    flops = sum(2 * (ls.n_pre + ls.n_post + 1) * len(ls.offsets)
                * ls.qc * LANES for ls in spec.levels)
    byts = sum(int(v.size) * v.dtype.itemsize for v in leaves) \
        + 3 * l0.qc * LANES * 4
    out_specs = pl.BlockSpec((l0.qc, LANES),
                             lambda i: (jnp.int32(0), jnp.int32(0)),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((l0.qc, LANES), dtype)
    if with_dot:
        out_specs = (out_specs, pl.BlockSpec(
            (1, LANES), lambda i: (jnp.int32(0), jnp.int32(0)),
            memory_space=pltpu.VMEM))
        out_shape = (out_shape, jax.ShapeDtypeStruct((1, LANES),
                                                     jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[_spec_of(v) for v in leaves] + [_spec_of(b2),
                                                  _spec_of(x2)],
        out_specs=out_specs,
        out_shape=out_shape,
        cost_estimate=pl.CostEstimate(flops=flops, bytes_accessed=byts,
                                      transcendentals=0),
        interpret=interpret,
    )(*leaves, b2, x2)
    if with_dot:
        out, dparts = out
        return out.reshape(-1)[:l0.n], jnp.sum(dparts)
    return out.reshape(-1)[:l0.n]


# ---------------------------------------------------------------------------
# Krylov shell fusion: SpMV + dot epilogues and the single-pass CG
# update
#
# The fused-cycle suite stops at the preconditioner boundary: a
# CG/PCG iteration still runs a standalone SpMV, three separate dot
# reductions, and bare axpy updates — each a full n-vector HBM pass
# outside the cycle. Two kernels close the shell:
#
# - SPMV + DOT (`_dia_spmv_dot_call`): A.p with a per-block d.Ap
#   partial-sum epilogue ((nb, 128) partials, rows reduced in-kernel,
#   lanes combined by a cheap XLA sum — the restriction-epilogue
#   pattern), an optional PROLOGUE folding the direction update
#   p = z + beta*p_prev (beta a scalar in SMEM; the halo rows
#   recompute the update redundantly so the window stays exact), and
#   an optional second Ap.Ap self-dot (BiCGStab's t.t). The x-window
#   layout/DMA pipeline is the plain dia_spmv kernel's; operands
#   follow the fused-suite dtype rules (f32/bf16 streams, f32
#   accumulation, f32 partials).
#
# - CG UPDATE (`_cg_update_call`): x += alpha p and r -= alpha Ap in
#   one auto-pipelined elementwise pass with an r'.r' dot epilogue, so
#   the monitor's residual norm is a free by-product.
#
# Padding rows/lanes carry zero vectors (and zero matrix values), so
# the partial dots are exact without masking.
# ---------------------------------------------------------------------------


def dia_spmv_dot_supported(A, x_dtype) -> bool:
    """Trace-time gate for the SpMV+dot (Krylov shell) Pallas path.
    Wider than dia_spmv_supported: bf16 operands are admitted under
    the fused-suite rules (f32 accumulation)."""
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    if not smooth_dtype_ok(A, x_dtype):
        return False
    if A.num_rows != A.num_cols:
        return False
    k, rows_pad, _ = A.dia_vals.shape
    left, halo_rows, br = _layout(A.dia_offsets, k, A.num_rows)
    if rows_pad % br != 0:
        return False
    ib = jnp.dtype(x_dtype).itemsize
    win = br + halo_rows
    # worst-case variant: beta prologue (2 windows + p output) plus a
    # streamed dot operand and both partial outputs
    vmem = 2 * k * br * LANES * ib \
        + 2 * 2 * win * LANES * ib \
        + 2 * 3 * br * LANES * ib
    if ib < 4:
        vmem += (2 * win + 2 * br) * LANES * 4
    return vmem <= _VMEM_BUDGET + 4 * 1024 * 1024


def _dia_spmv_dot_kernel(offsets, left, br, halo_rows, n_blocks, dtype,
                         with_beta, with_d, self_dot, mf=None):
    """Kernel body factory. Window coordinates are the plain dia_spmv
    kernel's (x row r lives at window row left//128 + r); the dot
    epilogue reduces rows in-kernel and leaves the 128 lanes to the
    caller's XLA combine. `with_d` streams a separate dot operand
    (auto-pipelined block, no halo) in place of p itself."""
    ro = [(left + o) // LANES for o in offsets]
    rl = [(left + o) % LANES for o in offsets]
    win_rows = br + halo_rows
    prow = left // LANES
    cdt = compute_dtype(dtype)

    def kernel(*refs):
        # refs: pp, [zp], vals|coeffs, [d], [beta], [p_out], ap,
        #       dot, [sdot], pbuf, [zbuf], sems
        off = 0
        pp_ref = refs[off]
        off += 1
        zp_ref = refs[off] if with_beta else None
        off += 1 if with_beta else 0
        if mf is None:
            vals_ref, coeffs_ref = refs[off], None
        else:
            vals_ref, coeffs_ref = None, refs[off]
        off += 1
        d_ref = refs[off] if with_d else None
        off += 1 if with_d else 0
        beta_ref = refs[off] if with_beta else None
        off += 1 if with_beta else 0
        pout_ref = refs[off] if with_beta else None
        off += 1 if with_beta else 0
        ap_ref, dot_ref = refs[off], refs[off + 1]
        off += 2
        sdot_ref = refs[off] if self_dot else None
        off += 1 if self_dot else 0
        pbuf = refs[off]
        off += 1
        zbuf = refs[off] if with_beta else None
        off += 1 if with_beta else 0
        sems = refs[off]

        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dmas(s, blk):
            base = jnp.int32(blk) * jnp.int32(br)
            ops = [pltpu.make_async_copy(
                pp_ref.at[pl.ds(base, win_rows)],
                pbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 0])]
            if with_beta:
                ops.append(pltpu.make_async_copy(
                    zp_ref.at[pl.ds(base, win_rows)],
                    zbuf.at[jnp.int32(s)], sems.at[jnp.int32(s), 1]))
            return ops

        @pl.when(i == 0)
        def _():
            for d in dmas(0, 0):
                d.start()

        @pl.when(i + 1 < n_blocks)
        def _():
            for d in dmas(jax.lax.rem(i + 1, jnp.int32(2)), i + 1):
                d.start()

        for d in dmas(slot, i):
            d.wait()

        if with_beta:
            # direction-update prologue over the WHOLE window: the
            # halo rows feed the shifts, so they need the updated p
            # too (redundant recompute, zero extra HBM)
            s = zbuf[slot].astype(cdt) \
                + beta_ref[0] * pbuf[slot].astype(cdt)
        else:
            s = pbuf[slot].astype(cdt)

        col = jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 1)
        if mf is None:
            def val(t):
                return vals_ref[t].astype(cdt)
        else:
            row0 = i * jnp.int32(br)
            val, _dw = _mf_block_vals(mf, coeffs_ref, row0, br, col,
                                      cdt)

        acc = jnp.zeros((br, LANES), cdt)
        for t, _o in enumerate(offsets):
            a = jax.lax.slice_in_dim(s, ro[t], ro[t] + br, 1, 0)
            if rl[t] == 0:
                w = a
            else:
                b2 = jax.lax.slice_in_dim(s, ro[t] + 1, ro[t] + 1 + br,
                                          1, 0)
                shift = LANES - rl[t]
                wa = pltpu.roll(a, jnp.int32(shift), 1)
                wb = pltpu.roll(b2, jnp.int32(shift), 1)
                w = jnp.where(col < shift, wa, wb)
            acc = acc + val(t) * w

        p_blk = jax.lax.slice_in_dim(s, prow, prow + br, 1, 0)
        if with_beta:
            pout_ref[...] = p_blk.astype(dtype)
        ap_ref[...] = acc.astype(dtype)
        dvec = d_ref[...].astype(cdt) if with_d else p_blk
        dot_ref[...] = jnp.sum(dvec * acc, axis=0,
                               keepdims=True).astype(jnp.float32)
        if self_dot:
            sdot_ref[...] = jnp.sum(acc * acc, axis=0,
                                    keepdims=True).astype(jnp.float32)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "offsets", "num_rows", "self_dot", "mf", "interpret"))
def _dia_spmv_dot_call(dia_vals, p, z, beta, d, offsets, num_rows,
                       self_dot=False, mf=None, coeffs=None,
                       interpret=False):
    """Fused SpMV + dot epilogue. Returns (Ap, d.Ap[, Ap.Ap]) with
    d = p when no separate dot operand is streamed; with the beta
    prologue (z is not None), p' = z + beta*p is computed in-window
    and the returns become (p', Ap', p'.Ap'[, ...]). The dot scalars
    are LOCAL f32 sums — distributed callers psum them (packed).
    Caller must have checked dia_spmv_dot_supported (slab mode) or
    the stencil twin's gate (mf mode)."""
    with_beta = z is not None
    with_d = d is not None
    if mf is None:
        k, rows_pad, _ = dia_vals.shape
        dtype = dia_vals.dtype
    else:
        k = len(offsets)
        dtype = p.dtype
    left, halo_rows, br = _layout(offsets, k, num_rows)
    if mf is None:
        nb = rows_pad // br
    else:
        rows128 = max(1, -(-num_rows // LANES))
        nb = -(-rows128 // br)
        rows_pad = nb * br
    n = num_rows
    win_rows = br + halo_rows
    xp_rows = rows_pad + halo_rows
    cdt = compute_dtype(dtype)

    def _pad_win(v):
        vp = jnp.zeros((xp_rows * LANES,), dtype)
        vp = jax.lax.dynamic_update_slice(vp, v.astype(dtype), (left,))
        return vp.reshape(xp_rows, LANES)

    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]          # pp
    operands = [_pad_win(p)]
    if with_beta:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))  # zp
        operands.append(_pad_win(z))
    if mf is None:
        in_specs.append(pl.BlockSpec(
            (k, br, LANES), lambda i: (jnp.int32(0), i, jnp.int32(0)),
            memory_space=pltpu.VMEM))
        operands.append(dia_vals)
    else:
        in_specs.append(pl.BlockSpec((k,), lambda i: (jnp.int32(0),),
                                     memory_space=pltpu.SMEM))
        operands.append(coeffs.astype(cdt))
    if with_d:
        dp = jnp.zeros((rows_pad * LANES,), dtype)
        dp = jax.lax.dynamic_update_slice(dp, d.astype(dtype), (0,))
        in_specs.append(pl.BlockSpec((br, LANES),
                                     lambda i: (i, jnp.int32(0)),
                                     memory_space=pltpu.VMEM))
        operands.append(dp.reshape(rows_pad, LANES))
    if with_beta:
        in_specs.append(pl.BlockSpec((1,), lambda i: (jnp.int32(0),),
                                     memory_space=pltpu.SMEM))
        operands.append(jnp.reshape(beta, (1,)).astype(cdt))

    blk = pl.BlockSpec((br, LANES), lambda i: (i, jnp.int32(0)),
                       memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, LANES), lambda i: (i, jnp.int32(0)),
                        memory_space=pltpu.VMEM)
    vec_shape = jax.ShapeDtypeStruct((rows_pad, LANES), dtype)
    part_shape = jax.ShapeDtypeStruct((nb, LANES), jnp.float32)
    out_specs = ([blk] if with_beta else []) + [blk, part] \
        + ([part] if self_dot else [])
    out_shape = ([vec_shape] if with_beta else []) \
        + [vec_shape, part_shape] + ([part_shape] if self_dot else [])

    scratch = [pltpu.VMEM((2, win_rows, LANES), dtype)]
    if with_beta:
        scratch.append(pltpu.VMEM((2, win_rows, LANES), dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2, 2 if with_beta else 1)))

    kernel = _dia_spmv_dot_kernel(offsets, left, br, halo_rows, nb,
                                  dtype, with_beta, with_d, self_dot,
                                  mf=mf)
    ib = jnp.dtype(dtype).itemsize
    streams = (0 if mf is not None else k) + 2 * (2 if with_beta else 1) \
        + (1 if with_d else 0)
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * (k + 2) * nb * br * LANES,
            bytes_accessed=streams * nb * br * LANES * ib,
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)
    idx = 0
    res = []
    for _ in range(2 if with_beta else 1):
        v = outs[idx].reshape(-1)
        res.append(v[:n] if v.shape[0] != n else v)
        idx += 1
    res.append(jnp.sum(outs[idx]))
    idx += 1
    if self_dot:
        res.append(jnp.sum(outs[idx]))
    return tuple(res)


def dia_spmv_dot(A, p, z=None, beta=None, d=None, self_dot=False,
                 interpret=False):
    """Fused DIA SpMV + dot epilogue(s); caller must have checked
    dia_spmv_dot_supported. See _dia_spmv_dot_call for the return
    shapes."""
    return _dia_spmv_dot_call(A.dia_vals, p, z, beta, d,
                              A.dia_offsets, A.num_rows,
                              self_dot=self_dot,
                              interpret=interpret or _FORCE_INTERPRET)


def cg_update_supported(x_dtype) -> bool:
    """Trace-time gate for the single-pass CG update kernel."""
    if jax.default_backend() != "tpu" and not _FORCE_INTERPRET:
        return False
    return jnp.dtype(x_dtype).name in SMOOTH_DTYPES


def _cg_update_kernel(dtype):
    cdt = compute_dtype(dtype)

    def kernel(x_ref, p_ref, r_ref, ap_ref, alpha_ref, xo_ref, ro_ref,
               rr_ref):
        a = alpha_ref[0]
        xn = x_ref[...].astype(cdt) + a * p_ref[...].astype(cdt)
        rn = r_ref[...].astype(cdt) - a * ap_ref[...].astype(cdt)
        xo_ref[...] = xn.astype(dtype)
        ro_ref[...] = rn.astype(dtype)
        rr_ref[...] = jnp.sum(rn * rn, axis=0,
                              keepdims=True).astype(jnp.float32)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _cg_update_call(x, p, r, ap, alpha, interpret=False):
    """Single-pass CG state update: (x + alpha p, r - alpha Ap,
    r'.r') in one auto-pipelined elementwise kernel — the residual
    norm the monitor wants becomes a free epilogue instead of a
    standalone blas.norm stream. The rr scalar is the LOCAL f32 sum.
    Caller must have checked cg_update_supported."""
    dtype = x.dtype
    n = x.shape[0]
    cdt = compute_dtype(dtype)
    rows128 = max(1, -(-n // LANES))
    br = pick_block_rows(6, rows128)
    nb = -(-rows128 // br)
    rows_pad = nb * br

    def padv(v):
        vp = jnp.zeros((rows_pad * LANES,), dtype)
        vp = jax.lax.dynamic_update_slice(vp, v.astype(dtype), (0,))
        return vp.reshape(rows_pad, LANES)

    blk = pl.BlockSpec((br, LANES), lambda i: (i, jnp.int32(0)),
                       memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, LANES), lambda i: (i, jnp.int32(0)),
                        memory_space=pltpu.VMEM)
    xo, ro, rr = pl.pallas_call(
        _cg_update_kernel(dtype),
        grid=(nb,),
        in_specs=[blk, blk, blk, blk,
                  pl.BlockSpec((1,), lambda i: (jnp.int32(0),),
                               memory_space=pltpu.SMEM)],
        out_specs=(blk, blk, part),
        out_shape=(jax.ShapeDtypeStruct((rows_pad, LANES), dtype),
                   jax.ShapeDtypeStruct((rows_pad, LANES), dtype),
                   jax.ShapeDtypeStruct((nb, LANES), jnp.float32)),
        cost_estimate=pl.CostEstimate(
            flops=5 * nb * br * LANES,
            bytes_accessed=6 * nb * br * LANES
            * jnp.dtype(dtype).itemsize,
            transcendentals=0),
        interpret=interpret,
    )(padv(x), padv(p), padv(r), padv(ap),
      jnp.reshape(alpha, (1,)).astype(cdt))
    xv = xo.reshape(-1)
    rv = ro.reshape(-1)
    if xv.shape[0] != n:
        xv = xv[:n]
        rv = rv[:n]
    return xv, rv, jnp.sum(rr)
