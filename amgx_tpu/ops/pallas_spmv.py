"""Pallas TPU SpMV kernel for the DIA (banded stencil) layout.

The reference's SpMV fast path is a hand-tuned CUDA csrmv
(src/multiply.cu:74-121 and the CHANGELOG "fast path" entry). The TPU
equivalent is not a translation of that kernel: on TPU the roofline
layout for stencil matrices is DIA — y = sum_d vals_d * shift(x, d) —
because every stream is a dense sequential read (no gather hardware).
XLA alone materializes each partial sum in HBM, so a 7-diagonal SpMV
pays ~4x the minimum traffic. This kernel performs the whole reduction
in one fused pass:

- grid over row blocks of BLOCK_ROWS*128 elements, sequential on core;
- diagonal values arrive via an auto-pipelined (k, BR, 128) block;
- the x window (block + halo rows for every diagonal offset) is DMA'd
  from HBM into a manually double-buffered VMEM scratch, so the next
  block's halo loads while the current block computes;
- lane-crossing shifts (offset % 128 != 0) use the two-row roll+select
  trick: W[p, q] = a[p, q+r] for q < 128-r else b[p, q+r-128], where
  a/b are consecutive row views of the window — pure VPU work.

Traffic per output element for a k-diagonal matrix: k value floats +
~1 x float + 1 y float, i.e. the HBM minimum (plus a halo sliver).

The matrix stores dia_vals tile-aligned as (k, rows_pad, 128) — see
CsrMatrix._build_dia_vals — so the kernel reads values with zero
re-layout cost. float32 only (TPU has no native f64; the XLA spmv_dia
path covers f64/CPU).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
_VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom under ~16 MB/core


def pick_block_rows(k: int, rows128: int) -> int:
    """Rows (of 128 lanes) per grid block. Shared by matrix init (which
    pads dia_vals to a multiple of this) and the kernel wrapper, so the
    two always agree. Sized so the double-buffered values block fits
    VMEM comfortably."""
    budget_rows = _VMEM_BUDGET // (max(k, 1) * LANES * 4 * 2)
    br = 512
    while br > 8 and br > budget_rows:
        br //= 2
    if rows128 <= br:
        # single block: round the whole matrix up to a tile of 8 rows
        return max(8, -(-rows128 // 8) * 8)
    return br


def dia_padded_rows(k: int, n: int) -> int:
    """Padded row count (of 128 lanes) for the tiled dia_vals store."""
    rows128 = max(1, -(-n // LANES))
    br = pick_block_rows(k, rows128)
    return -(-rows128 // br) * br


def _dia_kernel(offsets, left, block_rows, halo_rows, n_blocks, dtype):
    """Build the kernel body. All layout numbers are static."""
    ro = [(left + o) // LANES for o in offsets]   # window row offset
    rl = [(left + o) % LANES for o in offsets]    # lane shift
    win_rows = block_rows + halo_rows

    def kernel(xp_ref, vals_ref, y_ref, xbuf, sems):
        i = pl.program_id(0)
        slot = jax.lax.rem(i, jnp.int32(2))

        def dma(s, blk):
            return pltpu.make_async_copy(
                xp_ref.at[pl.ds(jnp.int32(blk) * jnp.int32(block_rows),
                                win_rows)],
                xbuf.at[jnp.int32(s)], sems.at[jnp.int32(s)])

        @pl.when(i == 0)
        def _():
            dma(0, 0).start()

        @pl.when(i + 1 < n_blocks)
        def _():
            dma(jax.lax.rem(i + 1, jnp.int32(2)), i + 1).start()

        dma(slot, i).wait()

        col = jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANES), 1)
        acc = jnp.zeros((block_rows, LANES), dtype)
        xv = xbuf[slot]          # (win_rows, 128) view of this block's x
        for k, _ in enumerate(offsets):
            vk = vals_ref[k]
            if rl[k] == 0:
                w = jax.lax.slice_in_dim(xv, ro[k], ro[k] + block_rows, 1, 0)
            else:
                a = jax.lax.slice_in_dim(xv, ro[k], ro[k] + block_rows, 1, 0)
                b = jax.lax.slice_in_dim(xv, ro[k] + 1,
                                         ro[k] + 1 + block_rows, 1, 0)
                shift = LANES - rl[k]
                wa = pltpu.roll(a, jnp.int32(shift), 1)
                wb = pltpu.roll(b, jnp.int32(shift), 1)
                w = jnp.where(col < shift, wa, wb)
            acc = acc + vk * w
        y_ref[...] = acc

    return kernel


def _layout(offsets, k: int, num_rows: int):
    """Shared layout math: (left pad, halo rows, block rows). The gate
    and the kernel wrapper both call this so they can never diverge."""
    left = -(-max(0, -min(offsets)) // LANES) * LANES
    halo_rows = (left + max(max(offsets), 0)) // LANES + 1
    br = pick_block_rows(k, max(1, -(-num_rows // LANES)))
    return left, halo_rows, br


def dia_spmv_supported(A, x_dtype) -> bool:
    """Trace-time gate for the Pallas path."""
    if jax.default_backend() != "tpu":
        return False
    if A.dia_vals is None or A.dia_vals.dtype != jnp.float32 \
            or x_dtype != jnp.float32:
        return False
    if A.num_rows != A.num_cols:
        return False
    k, rows_pad, _ = A.dia_vals.shape
    left, halo_rows, br = _layout(A.dia_offsets, k, A.num_rows)
    if rows_pad % br != 0:
        return False
    # window scratch must fit alongside the values pipeline
    win_bytes = 2 * (br + halo_rows) * LANES * 4
    vals_bytes = 2 * k * br * LANES * 4
    return win_bytes + vals_bytes + 2 * br * LANES * 4 <= \
        _VMEM_BUDGET + 4 * 1024 * 1024


@functools.partial(jax.jit,
                   static_argnames=("offsets", "num_rows", "interpret"))
def _dia_spmv_call(dia_vals, x, offsets, num_rows, interpret=False):
    k, rows_pad, _ = dia_vals.shape
    dtype = dia_vals.dtype
    n = num_rows
    left, halo_rows, br = _layout(offsets, k, n)
    n_blocks = rows_pad // br
    xp_rows = rows_pad + halo_rows
    xp = jnp.zeros((xp_rows * LANES,), dtype)
    xp = jax.lax.dynamic_update_slice(xp, x.astype(dtype), (left,))
    xp = xp.reshape(xp_rows, LANES)

    kernel = _dia_kernel(offsets, left, br, halo_rows, n_blocks, dtype)
    y2 = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (k, br, LANES),
                lambda i: (jnp.int32(0), i, jnp.int32(0)),
                memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, LANES),
                               lambda i: (i, jnp.int32(0)),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_pad, LANES), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, br + halo_rows, LANES), dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * k * rows_pad * LANES,
            bytes_accessed=(k + 2) * rows_pad * LANES * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(xp, dia_vals)
    y = y2.reshape(-1)
    if y.shape[0] != n:
        y = y[:n]
    return y


def dia_spmv(A, x, interpret=False):
    """Fused DIA SpMV; caller must have checked dia_spmv_supported
    (`interpret=True` runs the Pallas interpreter — CPU test path)."""
    return _dia_spmv_call(A.dia_vals, x, A.dia_offsets, A.num_rows,
                          interpret=interpret)
